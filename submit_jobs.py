#!/usr/bin/env python
"""Repo-root shim matching the reference UX: ``python submit_jobs.py --inp_dir sweeps/``."""

from picotron_tpu.tools.submit_jobs import main

if __name__ == "__main__":
    raise SystemExit(main())
