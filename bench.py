"""Benchmark: SmolLM-1.7B training MFU on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline is the reference's headline SmolLM-1.7B number: ~50% MFU on 8xH100
(reference README.md:7); vs_baseline = our_mfu / 50.

Protocol mirrors the reference's extract_metrics.py:82-89: time real optimizer
steps, skip the first 3 as warmup, mean the rest. MFU uses the reference's
analytic formula (utils.py:42-48) with the per-chip peak-FLOPs table in
picotron_tpu.utils instead of the hardcoded H100 constant.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax

from picotron_tpu.bench_record import BENCH_METRICS, iter_metric_records
from picotron_tpu.obs.metrics import MetricsRegistry

# the last COMPLETED run's registry summary (picotron_tpu/obs): run()
# times each call into a FRESH registry and publishes the snapshot here
# only when the run finishes, so the final JSON's "obs" blob describes
# exactly the run whose number it reports — OOM'd/descended sizes and a
# losing flash-layout A/B leg never pollute it
LAST_RUN_OBS: dict = {}


def smollm_cfg(mbs: int, seq: int, on_tpu: bool, remat: str = "full"):
    from picotron_tpu.config import SMOLLM_1_7B, Config

    if on_tpu:
        model = dict(SMOLLM_1_7B)
    else:  # CPU smoke path so the bench always prints a line
        model = dict(
            name="tiny", num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=8, hidden_size=256, intermediate_size=1024,
            vocab_size=4096, max_position_embeddings=2048, dtype="float32",
            attention_impl="sdpa",
        )
    return Config.from_dict({
        "distributed": {"dp_size": 1, "pp_size": 1, "cp_size": 1, "tp_size": 1},
        "model": model,
        "training": {"seq_length": seq, "micro_batch_size": mbs,
                     "gradient_accumulation_steps": 1, "remat": remat,
                     "grad_accum_dtype": "param", "learning_rate": 3e-4},
        "dataset": {"name": "synthetic"},
    })


def run(cfg, calls=4, warmup=1, steps_per_call=16):
    """Time multi-step calls (K optimizer steps fused into one dispatch via
    lax.scan — an on-device training loop, so per-step host latency doesn't
    pollute the measurement); first `warmup` calls (compile + cache) skipped."""
    from picotron_tpu import train_step as ts
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.topology import topology_from_config

    topo = topology_from_config(cfg, devices=jax.devices()[:1])
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo, multi_step=steps_per_call)
    loader = MicroBatchDataLoader(cfg)
    tokens, targets = ts.shard_batch_stack(
        [next(loader) for _ in range(steps_per_call)], topo)

    times = []
    reg = MetricsRegistry()
    call_hist = reg.histogram(
        "bench_step_call_seconds",
        f"one timed call ({steps_per_call} fused optimizer steps)")
    for _ in range(calls):
        t0 = time.perf_counter()
        params, opt_state, losses = step(params, opt_state, tokens, targets)
        jax.block_until_ready(losses)
        times.append(time.perf_counter() - t0)
        call_hist.observe(times[-1])
    assert jax.numpy.isfinite(losses).all(), f"loss diverged: {losses}"
    mean_t = sum(times[warmup:]) / len(times[warmup:])
    # publish only on completion — an aborted run's partial timings die
    # with its local registry
    LAST_RUN_OBS.clear()
    LAST_RUN_OBS.update(reg.summary())
    return steps_per_call * cfg.tokens_per_step / mean_t


def _cpu_pinned() -> bool:
    """The caller pinned the CPU platform via JAX_PLATFORMS."""
    from picotron_tpu.utils import cpu_pinned

    return cpu_pinned()


def kernel_parity_preflight() -> str:
    """Run the real-TPU Pallas-vs-XLA parity tests (tests/test_tpu_kernels.py)
    in a child process before the parent touches JAX — the bench numbers are
    meaningless if the kernels are wrong, and this is how the driver's bench
    environment executes the on-hardware kernel validation (round-2 VERDICT
    item 4). The child decides TPU-ness itself (it must run before the
    parent can hold the chip); returns the pytest summary line so the caller
    can demand real passes once it knows the parent backend is TPU."""
    import subprocess

    if _cpu_pinned():
        # CPU smoke run: no chip to validate, and on this site the TPU is
        # behind a tunnel whose client blocks forever when dead — don't let
        # the preflight child touch it.
        return "skipped (JAX_PLATFORMS=cpu)"
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run(
            [sys.executable, "-m", "pytest", "-q",
             os.path.join(here, "tests", "test_tpu_kernels.py")],
            env={**os.environ, "PICOTRON_TEST_TPU": "1"},
            capture_output=True, text=True, timeout=1200)
    except subprocess.TimeoutExpired:
        # A dead TPU tunnel hangs backend init inside the child — and would
        # hang the parent identically at its first backend touch, so exit
        # with the diagnosis now rather than blocking forever.
        raise SystemExit(
            "TPU kernel parity preflight timed out: backend init hung "
            "(dead TPU tunnel?); not publishing unvalidated numbers")
    tail = (r.stdout + r.stderr)[-2000:]
    if r.returncode != 0:
        raise SystemExit(f"TPU kernel parity tests FAILED:\n{tail}")
    lines = [l for l in r.stdout.splitlines() if l.strip()]
    return lines[-1] if lines else ""


class EntryTimeout(Exception):
    """A single ladder entry (compile + timed runs) exceeded its watchdog."""


# Inner exit code for "the TPU infra is sick, not the bench code" (EX_TEMPFAIL
# from sysexits). The orchestrator must distinguish this from an rc=1 code
# failure: an infra bail-out keeps the stale-capture fallback eligible.
EX_INFRA = 75


class _entry_watchdog:
    """SIGALRM deadline around one ladder entry. The 20260731T0316 window
    showed why: the tunneled compile service wedged silently on ONE compile
    for 50+ minutes (the client sleeps in an interruptible poll loop, so
    the alarm lands) and a single entry consumed the orchestrator's whole
    budget. Bounding each entry converts a sick compile service from
    'window lost' into 'one entry's cap lost, ladder moves on'. Main
    thread only; seconds <= 0 disables."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def __enter__(self):
        import signal

        if self.seconds <= 0:
            return self
        def _fire(signum, frame):
            raise EntryTimeout(
                f"ladder entry exceeded its {self.seconds:.0f}s watchdog "
                f"(wedged remote compile?)")
        self._prev = signal.signal(signal.SIGALRM, _fire)
        signal.setitimer(signal.ITIMER_REAL, self.seconds)
        return self

    def __exit__(self, *exc):
        import signal

        if self.seconds <= 0:
            return False
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, self._prev)
        return False


def _entry_timeout_s() -> float:
    """Per-entry watchdog for run_descending. Sized so a healthy entry
    (compile ~2-6 min on this tunnel + ~30 s of timed runs) never trips
    it, while a wedge costs at most this instead of the whole budget.
    Override with PICOTRON_BENCH_ENTRY_TIMEOUT (seconds; 0 disables)."""
    try:
        return float(os.environ.get("PICOTRON_BENCH_ENTRY_TIMEOUT", "900"))
    except ValueError:
        return 900.0


# the tunneled compile service's error framing (it reports through an HTTP
# proxy with no gRPC status) — used both to classify ladder errors as
# opaque-service and as part of the infra signature below; one list so the
# two classifiers cannot drift
_SERVICE_SUBSTRINGS = ("remote_compile", "tpu_compile_helper")

# what a tunnel/compile-service failure's EXCEPTION TEXT looks like: gRPC
# transport errors, the preflight's backend-init-hang diagnosis, and the
# ladder watchdog's own wording (a 'failed at all sizes' exit whose last
# error was a watchdog trip is an infra wedge, not a code bug). Anchored
# to the gRPC status framing ("unavailable:" / ".unavailable") and the
# watchdog's exact phrase — a bare "watchdog" or "unavailable" in a
# genuine code failure's message must not buy it an infra verdict (the
# watcher would then retry it every live window for the whole budget).
_INFRA_SUBSTRINGS = _SERVICE_SUBSTRINGS + (
    "unavailable:", ".unavailable", "socket closed", "deadline_exceeded",
    "deadline exceeded", "connection failed", "failed to connect",
    "connection reset", "backend init hung",
    "watchdog (wedged remote compile")


def _infra_signature(msg: str) -> bool:
    """Whether a failure MESSAGE (one exception's text, not log soup)
    points at TPU-tunnel infra rather than the bench code. Matching only
    the exception that actually killed the run keeps an earlier retry
    note (which also carries these words) from vouching for a later
    genuine code bug."""
    t = msg.lower()
    return any(s in t for s in _INFRA_SUBSTRINGS)


def run_inner_guarded(fn) -> None:
    """Run an inner bench main and convert ITS OWN terminal failure into
    an exit-code verdict: EX_INFRA when the exception that killed the run
    carries an infra signature, normal propagation (rc=1) otherwise. The
    verdict is computed here, on the actual exception object, because the
    orchestrator only sees the combined output — where retry notes and
    tracebacks interleave beyond reliable classification."""
    import traceback

    try:
        fn()
    except SystemExit as e:
        # classify on the FIRST line only: bench SystemExits put their
        # structured diagnosis there and may embed a child-log tail below
        # it (kernel_parity_preflight), where stray transport noise from
        # an otherwise-deterministic failure must not vouch for infra
        first = (str(e.code).splitlines() or [""])[0] \
            if isinstance(e.code, str) else ""
        if first and _infra_signature(first):
            print(e.code, file=sys.stderr)
            raise SystemExit(EX_INFRA) from None
        raise
    except Exception as e:
        first = (f"{type(e).__name__}: {e}".splitlines() or [""])[0]
        if _infra_signature(first):
            traceback.print_exc()
            print("# infra signature in the terminal failure; "
                  "exiting EX_INFRA", file=sys.stderr)
            raise SystemExit(EX_INFRA) from None
        raise


def classify_bench_error(msg: str) -> str:
    """'oom' = definite out-of-HBM (descend to a smaller size); 'opaque' =
    the tunneled-TPU compile service surfaced an error with no status (it
    reports out-of-HBM as an opaque HTTP 500, but a transient service
    failure looks identical — retry the same size once before treating it
    as OOM); anything else re-raises."""
    if any(s in msg for s in ("resource_exhausted", "out of memory",
                              "exceeds the amount of memory available")):
        return "oom"
    if any(s in msg for s in _SERVICE_SUBSTRINGS):
        return "opaque"
    return "raise"


def run_descending(sizes, make_cfg, tag, **run_kw):
    """Try configs from `sizes` in order — callers order them descending by
    memory footprint, best-expected-MFU first among comparable footprints.
    Definite OOMs move to the next entry, opaque compile-service errors
    retry the same entry once, anything else raises. Returns
    (cfg, tokens_per_sec) of the first entry that runs."""
    import gc

    last_err = None
    trips = 0
    for size in sizes:
        cfg = make_cfg(size)
        for attempt in range(2):
            try:
                with _entry_watchdog(_entry_timeout_s()):
                    return cfg, run(cfg, **run_kw)
            except Exception as e:
                msg = str(e).lower()
                last_err = msg
                # a watchdog trip is indistinguishable from a transient
                # service wedge: same policy as an opaque service error
                # (retry this size once, then descend) — but a SECOND trip
                # means the service is sick for the day; paying the cap
                # again on every remaining size would consume the very
                # budget the watchdog protects, so bail out with the
                # infra exit code (orchestrator retries / falls back)
                if isinstance(e, EntryTimeout):
                    trips += 1
                    if trips >= 2:
                        print(f"# {tag}: {trips} watchdog trips — compile "
                              f"service wedged; giving up early ({msg})",
                              file=sys.stderr)
                        raise SystemExit(EX_INFRA) from None
                    kind = "opaque"
                else:
                    kind = classify_bench_error(msg)
                if kind == "raise":
                    raise
                # the exception's traceback pins the failed attempt's
                # device arrays via frame refs; break it explicitly so the
                # collect below can actually free HBM for the next attempt
                e.__traceback__ = None
                del e
                jax.clear_caches()
                gc.collect()
                if kind == "oom":
                    print(f"# {tag}: OOM at {size}, trying smaller "
                          f"({msg[:120]})", file=sys.stderr)
                    break
                if attempt == 0:
                    print(f"# {tag}: opaque compile-service error at {size}; "
                          f"retrying same size once ({msg[:120]})",
                          file=sys.stderr)
                else:
                    print(f"# {tag}: opaque compile-service error repeated at "
                          f"{size}; treating as out-of-HBM ({msg[:120]})",
                          file=sys.stderr)
    raise SystemExit(f"{tag} failed at all sizes: {last_err}")


def try_flash_layout_ab(cfg, tok_s_folded, **run_kw):
    """One extra timed run of the winning config with a transpose-free
    flash layout: 'merged' when the geometry allows it (head_dim % 128 ==
    0, e.g. the 7B proxy's D=128), else 'bshd' — which Mosaic is known to
    reject on hardware (docs/chip_runs/20260730T221221Z), kept so the
    refusal stays in the bench record. Any failure keeps the battle-tested
    folded layout — the A/B can only improve the published number, never
    lose it. Returns (cfg, tokens_per_sec)."""
    import copy
    import gc

    from picotron_tpu.ops.pallas.flash_attention import LANE

    alt = "merged" if cfg.model.head_dim % LANE == 0 else "bshd"
    cfg2 = copy.deepcopy(cfg)
    cfg2.model.flash_layout = alt
    folded_obs = dict(LAST_RUN_OBS)  # the winning folded run's snapshot
    jax.clear_caches()
    gc.collect()
    try:
        with _entry_watchdog(_entry_timeout_s()):
            tok_s = run(cfg2, **run_kw)
    except Exception as e:
        print(f"# flash_layout={alt} failed; keeping folded "
              f"({str(e)[:160]})", file=sys.stderr)
        return cfg, tok_s_folded
    if tok_s > tok_s_folded:
        print(f"# flash_layout={alt} wins: {tok_s:.0f} vs {tok_s_folded:.0f} "
              f"tok/s (+{100 * (tok_s / tok_s_folded - 1):.1f}%)",
              file=sys.stderr)
        return cfg2, tok_s
    print(f"# flash_layout={alt} slower: {tok_s:.0f} vs {tok_s_folded:.0f} "
          f"tok/s; keeping folded", file=sys.stderr)
    # the published number is the folded run's — restore its obs snapshot
    # over the losing alt leg's
    LAST_RUN_OBS.clear()
    LAST_RUN_OBS.update(folded_obs)
    return cfg, tok_s_folded


def _honor_cpu_env() -> None:
    """JAX_PLATFORMS=cpu must win over the axon site's platform pin BEFORE
    any backend initializes — a dead TPU tunnel blocks the axon client
    constructor forever, so a CPU smoke run must never touch it."""
    from picotron_tpu.utils import honor_cpu_env_pin

    honor_cpu_env_pin()


def probe_tunnel(timeout: float = 90.0) -> str:
    """'tpu' | 'cpu' | 'dead': what a child process finds when it
    initializes the default JAX backend within `timeout`. On this site the
    chip sits behind a tunnel whose client blocks FOREVER inside backend
    init when the tunnel is dead (round-3 postmortem: that hang erased the
    round's number), so liveness must be established by a killable child,
    never the calling process. 'cpu' means the backend works but there is no
    accelerator at all (plain CPU box) — retrying would never help."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; "
             "print(d.platform, d.device_kind)"],
            capture_output=True, text=True, timeout=timeout)
        if r.returncode != 0:
            return "dead"
        # stdout only: stderr may carry "Unable to initialize backend 'tpu'"
        # fallback warnings on a CPU-only box with the plugin installed
        return "tpu" if "tpu" in r.stdout.lower() else "cpu"
    except subprocess.TimeoutExpired:
        return "dead"


def _run_inner(script: str, timeout: float):
    """Run `script --inner` in its OWN session so a timeout kills the whole
    process GROUP — the inner bench spawns a pytest preflight grandchild
    (kernel_parity_preflight) that would otherwise survive as an orphan
    holding the TPU/tunnel for every later step. Output goes to temp FILES,
    not pipes: on this CPython, communicate()-after-timeout silently drops
    the partial output (measured: both TimeoutExpired.stderr and the second
    communicate() come back empty), and the timeout diagnosis is exactly
    the clue the round artifact must carry. Returns a CompletedProcess on
    exit, or the partial stderr/stdout string on timeout."""
    import signal
    import tempfile

    # binary files + errors='replace': a SIGKILL mid-write can truncate a
    # multibyte character, and a decode crash here would break the
    # never-empty-artifact contract
    with tempfile.TemporaryFile() as fo, tempfile.TemporaryFile() as fe:
        p = subprocess.Popen([sys.executable, script, "--inner"],
                             stdout=fo, stderr=fe,
                             start_new_session=True)
        timed_out = False
        try:
            p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            timed_out = True
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()
            p.wait()
        fo.seek(0)
        fe.seek(0)
        out = fo.read().decode("utf-8", errors="replace")
        err = fe.read().decode("utf-8", errors="replace")
    if timed_out:
        return err or out or ""
    return subprocess.CompletedProcess(p.args, p.returncode, out, err)


def _round_start_epoch(repo: str) -> float | None:
    """Commit time of the newest BENCH_r*.json — the driver writes one at
    every round boundary, so captures older than this belong to a previous
    round's code and must never be republished as this round's number."""
    try:
        r = subprocess.run(
            ["git", "log", "-1", "--format=%ct", "--", "BENCH_r*.json"],
            cwd=repo, capture_output=True, text=True, timeout=30)
        return float(r.stdout.strip()) if r.returncode == 0 else None
    except (OSError, ValueError, subprocess.TimeoutExpired):
        return None


def latest_captured_record(metric: str, max_age_hours: float = 18.0,
                           base: str | None = None,
                           after_epoch: float | None = None):
    """Freshest real (non-null) one-line JSON record for ``metric`` that an
    earlier IN-ROUND bench run captured under docs/chip_runs/<UTC-stamp>/
    (chip_agenda / tunnel_watch step logs). The flaky-tunnel failure mode
    this exists for: a live window mid-round produced a real number, the
    tunnel is dead again when the driver publishes — a validated number
    captured by this same pipeline hours ago beats a null artifact. The
    age cap keeps records from a previous round (or a stale checkout) from
    masquerading as this round's. Returns (record, run_dir) or None."""
    import datetime
    import glob

    here = base or os.path.dirname(os.path.abspath(__file__))
    if after_epoch is None:
        after_epoch = _round_start_epoch(here)
    best = None
    for log in glob.glob(os.path.join(here, "docs", "chip_runs", "*",
                                      "*.log")):
        stamp = os.path.basename(os.path.dirname(log))
        try:
            t = datetime.datetime.strptime(
                stamp, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=datetime.timezone.utc)
        except ValueError:
            continue
        age_h = (datetime.datetime.now(datetime.timezone.utc)
                 - t).total_seconds() / 3600
        if age_h > max_age_hours:
            continue
        if after_epoch is not None and t.timestamp() <= after_epoch:
            continue  # captured before this round started: previous code
        for rec in iter_metric_records(log):
            if (rec.get("metric") == metric
                    and rec.get("value") is not None
                    and "stale_from" not in rec):  # originals only
                if best is None or stamp > best[2]:
                    best = (rec, os.path.dirname(log), stamp)
    return (best[0], best[1]) if best else None


def orchestrate(script: str, metric: str, unit: str,
                max_total: float = 5400.0) -> None:
    """Outer harness that makes a bench survive TPU-tunnel flaps.

    Runs `script --inner` (the real bench) as a child with a hard timeout,
    after a cheap tunnel-liveness probe; retries both with backoff inside a
    wall-clock budget. On final failure it still prints the one-line JSON
    artifact with value=null plus the diagnosis — the round artifact is
    never empty and never blocks the driver (round-3 VERDICT item 1).

    Budget sizing: a healthy worst-case inner run is the 1200 s preflight
    cap + a multi-config compile sweep + the flash-layout A/B (~30-45 min
    total), so the 90 min default leaves attempt 1 room to FINISH — a
    budget that can kill a healthy run just converts a good number into a
    null artifact. A dead tunnel never gets near it: each probe fails in
    <= 90 s, the backoffs cap at 180 s, and six consecutive probe
    failures publish the null artifact at ~21 min."""
    start = time.time()
    diagnosis: list[str] = []
    attempt = 0
    probe_ok_ever = False
    inner_attempts = 0
    hangs = 0
    full_cap_hangs = 0   # hangs whose attempt had the full per-attempt cap
    last_probe = None    # tunnel status at the most recent probe
    # the most recent inner attempt's failure mode — "hang" (timed out),
    # "infra" (exited EX_INFRA: watchdog bail-out or infra-signature
    # crash), or "code" (exited without a valid artifact and without an
    # infra verdict: the inner code is broken). Latest evidence wins: a
    # deterministic code bug keeps reproducing, while an early
    # unlisted-text flap must not stick a code verdict onto a run whose
    # later attempts were diagnosed infra.
    last_verdict = None
    while True:
        attempt += 1
        remaining = max_total - (time.time() - start)
        if remaining < 240:
            diagnosis.append("wall-clock budget exhausted")
            break
        # 90 s probe: a live tunnel initializes the backend in 10-35 s
        # (round-3 measurements); a dead one hangs forever, so waiting
        # longer only delays the verdict
        backend = probe_tunnel(timeout=min(90.0, remaining))
        last_probe = backend
        if backend == "dead":
            diagnosis.append(f"attempt {attempt}: tunnel probe hung/failed")
            if not probe_ok_ever and attempt >= 6:
                # ~20 min of consecutive probe failures: the tunnel is down
                # for the count, not flapping — publish the diagnosis now
                # (inside the window round 3 proved the driver waits)
                # instead of sleeping out the rest of the budget
                diagnosis.append("tunnel dead across all probes; giving up")
                break
            remaining = max_total - (time.time() - start)
            if remaining < 240:
                diagnosis.append("wall-clock budget exhausted")
                break
            print(f"# {diagnosis[-1]}; backing off", file=sys.stderr)
            # clamped so the null artifact is printed BEFORE a driver
            # enforcing max_total as a hard deadline would kill us
            time.sleep(min(60.0 * attempt, 180.0, remaining - 200))
            continue
        probe_ok_ever = True
        # 'tpu': run the real bench. 'cpu' (a plain CPU box, no pin, no
        # accelerator): run the same inner child — it detects the CPU
        # backend and prints the fast smoke record; retrying can't help, so
        # a failure there is final.
        remaining = max_total - (time.time() - start)
        if remaining < 180:
            diagnosis.append("wall-clock budget exhausted after probe")
            break
        # per-attempt cap below the whole remaining budget: a healthy
        # worst-case inner run fits in ~45 min (preflight cap + compile
        # sweep + A/B), so 3000 s never kills a good run — while a wedged
        # one costs a single attempt, leaving room for a second attempt
        # whose outcome disambiguates "wedged service" from "code
        # deadlock" (two full-cap hangs with a live tunnel = ambiguous,
        # see below)
        inner_timeout = min(remaining - 30, 3000.0)
        r = _run_inner(script, timeout=inner_timeout)
        inner_attempts += 1
        if isinstance(r, str):  # timed out; r = partial stderr
            last_verdict = "hang"
            hangs += 1
            if inner_timeout >= 3000.0:
                # only a FULL-cap hang votes for "deterministic deadlock":
                # a budget-truncated attempt can kill a healthy-but-slow
                # run, and that must not suppress the stale fallback
                full_cap_hangs += 1
            diagnosis.append(
                f"attempt {attempt}: inner bench timed out after "
                f"{inner_timeout:.0f}s; "
                f"stderr tail: {(r or '')[-300:]!r}")
            print(f"# {diagnosis[-1]}", file=sys.stderr)
            continue
        sys.stderr.write(r.stderr)  # A/B + config notes: keep in the record
        line = next((l for l in r.stdout.splitlines()
                     if l.startswith("{")), None)
        if r.returncode == 0 and line is not None:
            print(line)
            return
        # EX_INFRA is the inner's own verdict (watchdog bail-out, or its
        # terminal exception carried an infra signature —
        # run_inner_guarded): a flap, not a code bug; retrying / falling
        # back to an in-round capture stays legitimate
        last_verdict = "infra" if r.returncode == EX_INFRA else "code"
        diagnosis.append(
            f"attempt {attempt}: inner bench rc={r.returncode}; "
            f"tail: {(r.stdout + r.stderr)[-300:]!r}")
        if backend == "cpu":
            break  # no accelerator to wait for; the failure is final
        print(f"# {diagnosis[-1]}; backing off", file=sys.stderr)
        time.sleep(max(0.0, min(60.0, max_total - (time.time() - start) - 200)))
    # last resort before a null artifact: a real number captured earlier
    # this round by a live-window agenda/watcher run of this same bench.
    # Gated on the LAST attempt not being a code failure — that's a
    # problem a stale number would mask. Hangs and infra verdicts (dead
    # probes, a half-alive tunnel whose remote compiles wedge —
    # 20260731T0103's failure mode — or the inner's own EX_INFRA): there
    # a validated in-round capture beats a null artifact. EXCEPT when
    # EVERY inner attempt hung at the full per-attempt cap and the tunnel
    # was still alive at the last look: a deterministic deadlock in the
    # bench code looks exactly like that, and a timeout carries no
    # signature to tell it from a wedged compile service — ambiguous, so
    # publish null rather than mask a possible regression behind a stale
    # number. Budget-truncated hangs don't vote (they can kill a healthy
    # run), and a tunnel that died after the hangs falls back to the
    # dead-tunnel reasoning where stale is legitimate.
    all_hung = (inner_attempts >= 2 and hangs == inner_attempts
                and full_cap_hangs >= 2 and last_probe == "tpu")
    if all_hung:
        diagnosis.append(
            "every inner attempt hung at the full per-attempt cap with a "
            "live tunnel — ambiguous (code deadlock vs wedged compile "
            "service); not serving a stale capture")
    stale = (None if last_verdict == "code" or all_hung
             else latest_captured_record(metric))
    if stale is not None:
        rec, run_dir = stale
        rec["stale_from"] = run_dir
        if not probe_ok_ever or last_probe == "dead":
            why = "tunnel dead at publish time"
        elif last_verdict == "hang":
            why = ("tunnel half-alive at publish time (probes ok, inner "
                   "bench hung)")
        elif last_verdict == "infra":
            why = ("TPU infra sick at publish time (inner bench bailed "
                   "out or died on an infra signature)")
        else:
            why = ("wall-clock budget exhausted before an inner run "
                   "completed")
        rec["note"] = (f"{why}; value captured "
                       "earlier this round by the in-session chip agenda "
                       f"(log dir {os.path.basename(run_dir)})")
        rec["error"] = " | ".join(diagnosis)[-800:]
        print(f"# publishing stale in-round capture from {run_dir}",
              file=sys.stderr)
        print(json.dumps(rec))
        return
    rec = {"metric": metric, "value": None, "unit": unit,
           "vs_baseline": None, "error": " | ".join(diagnosis)[-1500:]}
    if last_verdict == "code":
        # explicit verdict for the watcher (tunnel_watch strikes code
        # failures, retries infra ones) — the error string above is
        # truncated and unparseable by design
        rec["code_failure"] = True
    print(json.dumps(rec))


def main():
    _honor_cpu_env()
    if not _cpu_pinned() and "--inner" not in sys.argv:
        orchestrate(os.path.abspath(__file__),
                    metric=BENCH_METRICS["bench"], unit="%")
        return
    run_inner_guarded(inner_main)


def inner_main():
    parity = kernel_parity_preflight()  # before the parent holds the chip
    from picotron_tpu.utils import on_tpu as _on_tpu
    on_tpu = _on_tpu()
    if on_tpu:
        if "passed" not in parity or "skipped" in parity:
            raise SystemExit(
                f"parent backend is TPU but the kernel parity preflight did "
                f"not run on TPU: {parity!r}")
        print(f"# TPU kernel parity: {parity}", file=sys.stderr)
    from picotron_tpu.models import llama
    from picotron_tpu.utils import get_mfu, peak_flops_per_chip

    # (remat, mbs) candidates, descending by activation memory (save_attn
    # stores the flash out+LSE on top of layer boundaries, roughly
    # full@2*mbs): the reference trains WITHOUT activation checkpointing,
    # so lighter remat is parity behavior and the saved recompute FLOPs
    # turn into MFU — on the 16 GB v5e the search lands on save_attn@mbs2,
    # 54.8-55.3% across runs vs full@mbs4's 53.9%; larger-HBM chips get the
    # larger save_attn batches first. (remat="none" is an HBM wall at this
    # scale: ~14.5 GB static state + 6+ GB of unrematerialized residuals
    # on a 16 GB chip — docs/BENCH_7B.md has the arithmetic; it stays a
    # config option for smaller models / larger chips.)
    sizes = ((("save_attn", 8), ("save_attn", 4), ("save_attn", 2),
              ("full", 4), ("save_attn", 1), ("full", 2),
              ("full", 1)) if on_tpu else (("full", 2),))
    cfg, tok_s = run_descending(
        sizes,
        lambda rm: smollm_cfg(mbs=rm[1], seq=2048 if on_tpu else 128,
                              on_tpu=on_tpu, remat=rm[0]),
        tag="bench")
    if on_tpu:
        cfg, tok_s = try_flash_layout_ab(cfg, tok_s)

    m = cfg.model
    n_params = llama.num_params(m)
    peak = peak_flops_per_chip()
    if peak is None:  # CPU: report raw throughput, no MFU baseline claim
        print(json.dumps({"metric": "tokens_per_sec_cpu_smoke",
                          "value": round(tok_s, 1), "unit": "tokens/s",
                          "vs_baseline": 0.0,
                          "obs": dict(LAST_RUN_OBS)}))
        return
    mfu = get_mfu(tok_s, n_params, m.num_hidden_layers, m.hidden_size,
                  cfg.training.seq_length, peak)
    print(json.dumps({"metric": BENCH_METRICS["bench"],
                      "value": round(mfu, 2), "unit": "%",
                      "vs_baseline": round(mfu / 50.0, 3),
                      "obs": dict(LAST_RUN_OBS)}))
    print(f"# mbs={cfg.training.micro_batch_size} seq={cfg.training.seq_length} "
          f"remat={cfg.training.remat} flash={cfg.model.flash_layout} "
          f"tokens/s/chip={tok_s:.0f} "
          f"params={n_params/1e9:.2f}B peak={peak/1e12:.0f}TF", file=sys.stderr)


if __name__ == "__main__":
    main()
