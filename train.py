#!/usr/bin/env python
"""Convenience shim: ``python train.py --config exp.json`` from the repo root
(the reference's entry-point UX) — the real trainer is picotron_tpu.train."""

import sys

from picotron_tpu.train import main

if __name__ == "__main__":
    sys.exit(main())
