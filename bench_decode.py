"""Decode throughput benchmark: continuous-batched KV-cache generation.

The serving-side complement of bench.py's training MFU: with every engine
slot busy, how many tokens/sec does the decode hot path sustain?
Protocol: prefill fills all slots with fixed-length random prompts, a
warmup call absorbs compilation, then the timed window runs end-to-end
(including the host round-trip that feeds sampled tokens back — that
latency is part of serving).

Three modes, selected by ``--block-len`` / ``--spec-len``:

- ``--block-len 1`` (default): the classic per-token loop — one
  ``decode_step`` dispatch, one host sync, per generated token
  (dispatches/token = 1.0);
- ``--block-len N``: the blocked fast path — ``decode_block`` runs N
  autoregressive steps inside one jitted program with on-device stop
  state, so the host syncs once per N tokens (dispatches/token = 1/N).
  The tokens/s delta between the two modes IS the host-dispatch overhead
  the block amortizes.
- ``--spec-len G``: speculative decoding — prompts are REPETITIVE (the
  regime prompt-lookup drafting serves: boilerplate, code, loops), the
  n-gram drafter proposes G tokens per slot per round, and one
  ``engine.verify`` dispatch accepts the matching prefix. Same protocol
  and normalization as the other modes (prefill outside the timed
  window, dispatches per PER-SLOT decode token), so zero acceptance
  reads exactly 1.0 — the per-token baseline — and every accepted draft
  pushes dispatches/token strictly below it (~1/(1 + r*G) at
  accept-rate r).

Prints ONE JSON line starting ``{"metric"`` (the bench_record contract, so
the tunnel watcher / orchestrator can find and classify it in step logs):
tokens/s/chip on SmolLM-1.7B on TPU, a tiny-model smoke metric on CPU,
with ``dispatches_per_token`` (and ``accept_rate`` when speculating)
riding along so the host-sync win is visible in the bench trajectory.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# --dp N shards ONE engine's slot axis over N dp shards; on the CPU proxy
# that needs a forced multi-device host platform, and XLA fixes the device
# count at backend init — so the flag must land BEFORE any jax import
# (picotron_tpu's package import below touches jax via topology).
if "--dp" in sys.argv:
    try:
        _dp = int(sys.argv[sys.argv.index("--dp") + 1])
    except (IndexError, ValueError):
        _dp = 1
    if (_dp > 1 and "xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={max(8, _dp)}"
        ).strip()

from picotron_tpu.bench_record import BENCH_METRICS

# verify-dispatch rounds absorbed before the spec mode's timed window —
# shared by run_spec and main's cache-budget sizing
SPEC_WARMUP_ROUNDS = 4


def tpu_preflight(timeout_s: float = 120.0) -> tuple:
    """Probe the TPU backend in a CHILD process before the parent touches
    JAX. On this site the TPU sits behind a tunnel whose client blocks
    forever inside backend init when the tunnel is dead (BENCH_r03-r05 were
    lost exactly this way) — probing in a child with a timeout converts
    "bench hangs, window lost, empty artifact" into "CPU-proxy numbers
    published with validated=false". Returns (is_tpu, note):

    - (True,  "tpu")   — a live TPU backend; numbers are hardware-valid;
    - (False, reason)  — CPU pin, dead/absent tunnel, or a non-TPU
      backend; the caller pins CPU and publishes the proxy metric.

    Override the probe deadline with $PICOTRON_DECODE_PREFLIGHT_TIMEOUT
    (seconds)."""
    from picotron_tpu.utils import cpu_pinned

    if cpu_pinned():
        return False, "JAX_PLATFORMS=cpu"
    try:
        timeout_s = float(os.environ.get(
            "PICOTRON_DECODE_PREFLIGHT_TIMEOUT", timeout_s))
    except ValueError:
        pass
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, (f"backend init hung for {timeout_s:.0f}s "
                       f"(dead TPU tunnel?)")
    if r.returncode != 0:
        tail = (r.stderr or r.stdout).strip().splitlines()
        return False, ("backend init failed: "
                       + (tail[-1][:200] if tail else f"rc={r.returncode}"))
    backend = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    if backend != "tpu":
        return False, f"default backend is {backend or 'unknown'}, not tpu"
    return True, "tpu"


def logits_bytes_to_host_per_token(engine, vocab: int, block_len: int,
                                   spec_len: int = 0) -> int:
    """Bytes of sampling payload that cross the device->host boundary per
    generated token: the [B, V] fp32 logits the per-token loop round-trips
    just to pick one id each — or, everywhere sampling is fused into the
    dispatch (``--sample-on-device``, blocked decode's on-device stop
    state, the speculative verify), the int32 token ids alone. The
    acceptance shape: V*4 per token on the host-sampling per-token loop,
    O(B) per dispatch (= 4 bytes per token) with the epilogue on."""
    if block_len == 1 and spec_len == 0 and not engine.sample_on_device:
        return vocab * 4 + 4  # [V] fp32 logits + the sampled id fed back
    if spec_len > 0:
        # one verify dispatch emits ~(1 + r*G) ids per slot; conservatively
        # charge the whole emitted row (G+1 ids) per produced token
        return (spec_len + 1) * 4
    return 4  # token ids only — logits never leave the device


def dispatch_latency_summary(engine) -> dict:
    """Per-kind dispatch-latency percentiles out of the registry histogram
    PR 10 wired (``picotron_dispatch_seconds``): the per-rung before/after
    the bench JSON records, so an A/B across flag flips (serial vs
    pipelined DMA, host vs on-device sampling, uniform vs hot_bf16 pages)
    is a diff of two JSON lines, not a re-instrumentation."""
    out = {}
    for kind in ("decode", "verify"):
        h = engine.obs.registry.histogram(
            "picotron_dispatch_seconds",
            "dispatch wall time incl. host sync, by kind", kind=kind)
        p = h.percentiles()
        if p is not None:
            out[kind] = p
    return out


def kv_bytes_per_token(engine, lengths) -> int:
    """Estimated KV HBM bytes the attend moves per cache walk: layers x
    K+V x (attention window rows) x kv_heads x head_dim x storage bytes,
    plus the per-row fp32 scale vectors for int8 caches. The window is what
    distinguishes the kernels — the dense attend walks the full
    ``max_seq_len`` cache block, the flash kernel only the live rows
    (``lengths``, averaged over slots at the end of the timed window). The
    dense int8 path additionally materializes whole-window dequantized
    fp32 copies of K and V (kv_cache.attend) — that write+read traffic is
    counted, since hiding it would make dense-int8 look CHEAPER than
    dense-bf16, the opposite of what the flash path exists to fix. One
    walk serves one decode token (decode/blocked modes); speculative
    callers scale by dispatches-per-token (one walk per verify dispatch
    emits ~1/dpt tokens).

    Paged layout (``--kv-layout paged``): flash walks whole pages, so the
    live window rounds up to the page size; dense first GATHERS the
    slot's pages into a contiguous full-window copy (paged_kv.attend) —
    that copy's write+read is counted on top, the same honesty rule as
    the dense-int8 materialization."""
    import numpy as np

    m = engine.cfg.model
    live = float(np.mean(np.asarray(lengths)))
    paged = engine.paged is not None
    if engine.attend_impl == "flash":
        window = (-(-live // engine.page_len) * engine.page_len if paged
                  else live)
    else:
        window = float(engine.max_seq_len)
    fp_row = 2 * m.num_key_value_heads * m.head_dim * \
        engine.cache_dtype.itemsize
    q_row = (2 * m.num_key_value_heads * m.head_dim  # int8 bytes
             + 2 * m.num_key_value_heads * 4)  # + per-row fp32 scales
    if getattr(engine, "page_policy", False):
        # hot_bf16 mixed pages: the flash DMA fetches each page from ONE
        # representation — full precision for hot (shared) pages, int8 +
        # scales for cold (exclusive) tails — so per-row bytes are the
        # live-page mix. The dense reference gathers BOTH windows plus
        # the fp32 select copy (write + read), the same honesty rule as
        # the dense-int8 materialization below.
        flags = engine.paged.quant_flags()
        refs = engine.paged.pool.refs
        live = np.flatnonzero(refs[1:] > 0) + 1
        qfrac = float(np.mean(flags[live])) if live.size else 0.0
        if engine.attend_impl == "flash":
            per_row = qfrac * q_row + (1.0 - qfrac) * fp_row
        else:
            per_row = (fp_row + q_row
                       + 2 * m.num_key_value_heads * m.head_dim * 4 * 2)
    else:
        per_row = fp_row
        if engine.quantized:
            per_row += 2 * m.num_key_value_heads * 4  # k_scale/v_scale rows
            if engine.attend_impl == "dense":
                # whole-window fp32 K/V materialization: 4 bytes written
                # then read back per element, on top of the int8 cache read
                per_row += 2 * m.num_key_value_heads * m.head_dim * 4 * 2
    if paged and engine.attend_impl == "dense":
        # the gathered contiguous window copy: written then read back at
        # the storage width (the fp32 materialization above already
        # covers the int8 dequant copy)
        per_row += 2 * m.num_key_value_heads * m.head_dim * \
            engine.cache_dtype.itemsize * 2
    return int(round(m.num_hidden_layers * window * per_row))


def bench_params(engine, cfg):
    """Seed-derived weights in the engine's storage format (int8 engines
    get the per-channel quantized tree), plus their total byte footprint
    — the ``weight_bytes_total`` the int8 mode roughly halves."""
    import jax

    from picotron_tpu.models import llama

    params = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(0))
    if engine.quant_weights:
        params = llama.quantize_params(params)
    params = engine.shard_params(params)
    return params, llama.param_bytes(params)


def run(cfg, *, slots: int, max_seq_len: int, prompt_len: int,
        steps: int, warmup: int = 8, block_len: int = 1,
        attend_impl: str = "dense", kv_layout: str = "contiguous",
        kv_page_policy: str = "uniform", sample_on_device: bool = False,
        weight_dtype: str = "bf16"):
    """Time ``steps`` decode rounds (tokens per slot). Returns
    (tokens/s, dispatches_per_token, kv_bytes/token, weight_bytes_total,
    engine)."""
    import jax
    import numpy as np

    from picotron_tpu.inference import InferenceEngine

    engine = InferenceEngine(cfg, slots=slots, max_seq_len=max_seq_len,
                             decode_block_len=block_len,
                             attend_impl=attend_impl, kv_layout=kv_layout,
                             kv_page_policy=kv_page_policy,
                             sample_on_device=sample_on_device,
                             weight_dtype=weight_dtype)
    params, weight_bytes = bench_params(engine, cfg)
    cache = engine.init_cache()
    rng = np.random.default_rng(0)
    # greedy prefill epilogue (temp 0) == the host argmax it replaces
    pf_sample = ((jax.random.PRNGKey(1), 0.0, 0, 1.0)
                 if sample_on_device else None)
    for s in range(slots):
        prompt = rng.integers(1, cfg.model.vocab_size, prompt_len)
        kv, _ = engine.prefill(params, prompt, sample=pf_sample)
        cache = engine.insert(cache, kv, s, prompt_len)

    toks = np.ones(slots, np.int32)
    temp = np.zeros(slots, np.float32)  # greedy: no sampling noise in the timing
    top_k = np.zeros(slots, np.int32)
    top_p = np.ones(slots, np.float32)
    key = jax.random.PRNGKey(0)

    assert steps % block_len == 0, "steps must divide into whole blocks"
    assert prompt_len + warmup * block_len + steps <= max_seq_len, \
        "cache would overflow"

    if block_len == 1:
        for _ in range(warmup):
            key, sub = jax.random.split(key)
            cache, toks, _ = engine.decode_step(params, cache, toks, sub,
                                                temp, top_k, top_p)
        jax.block_until_ready(toks)
        t0 = time.perf_counter()
        for _ in range(steps):
            key, sub = jax.random.split(key)
            td = time.perf_counter()
            cache, toks, _ = engine.decode_step(params, cache, toks, sub,
                                                temp, top_k, top_p)
            toks = np.asarray(toks)  # the host feedback every real server pays
            # per-dispatch wall (incl. the sync above) into the registry
            # histogram the JSON record snapshots
            engine.observe_dispatch("decode", time.perf_counter() - td)
        dt = time.perf_counter() - t0
        dispatches = steps
        last = toks
    else:
        eos = np.full(slots, -1, np.int32)  # bench streams never stop early

        def block(cache, toks, key):
            subs = []
            for _ in range(block_len):
                key, sub = jax.random.split(key)
                subs.append(np.asarray(sub))
            budget = np.full(slots, block_len, np.int32)
            td = time.perf_counter()
            cache, out, counts = engine.decode_block(
                params, cache, toks, np.stack(subs), eos, budget,
                temp, top_k, top_p)
            out = np.asarray(out)  # one host sync per block, not per token
            engine.observe_dispatch("decode", time.perf_counter() - td)
            assert np.all(np.asarray(counts) == block_len)
            return cache, out[:, -1], key

        for _ in range(warmup):
            cache, toks, key = block(cache, toks, key)
        t0 = time.perf_counter()
        for _ in range(steps // block_len):
            cache, toks, key = block(cache, toks, key)
        dt = time.perf_counter() - t0
        dispatches = steps // block_len
        last = toks

    assert np.all((last >= 0) & (last < cfg.model.vocab_size))
    kv_bytes = kv_bytes_per_token(engine, cache["lengths"])
    return slots * steps / dt, dispatches / steps, kv_bytes, weight_bytes, \
        engine


def run_spec(cfg, *, slots: int, max_seq_len: int, prompt_len: int,
             steps: int, warmup_rounds: int = SPEC_WARMUP_ROUNDS,
             spec_len: int = 4, attend_impl: str = "dense",
             kv_layout: str = "contiguous",
             kv_page_policy: str = "uniform",
             sample_on_device: bool = False,
             weight_dtype: str = "bf16", drafter: str = "ngram"):
    """Time ``steps`` speculative decode tokens per slot: the same
    protocol as ``run`` — prefill fills every slot OUTSIDE the timed
    window, warmup rounds absorb compilation, then the timed window runs
    draft (host-side n-gram lookup) + one ``engine.verify`` dispatch per
    round until every slot has produced ``steps`` tokens. Prompts are
    REPETITIVE (one shared pattern — the regime prompt-lookup speculation
    serves: greedy decode falls into token loops the drafter rides).

    dispatches-per-token is dispatches / per-slot decode tokens, exactly
    ``run``'s normalization: with nothing accepted every round yields one
    token per slot and dpt == 1.0 (the spec-off per-token baseline);
    every accepted draft pushes it strictly below. Returns (tokens/s,
    dispatches_per_token, accept_rate, kv_bytes/token,
    weight_bytes_total, engine)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from picotron_tpu.inference import (
        InferenceEngine,
        LearnedDrafter,
        NgramDrafter,
    )

    engine = InferenceEngine(cfg, slots=slots, max_seq_len=max_seq_len,
                             spec_len=spec_len, attend_impl=attend_impl,
                             kv_layout=kv_layout,
                             kv_page_policy=kv_page_policy,
                             sample_on_device=sample_on_device,
                             weight_dtype=weight_dtype, drafter=drafter)
    params, weight_bytes = bench_params(engine, cfg)
    rng = np.random.default_rng(0)
    prompt = np.resize(rng.integers(1, cfg.model.vocab_size, 4), prompt_len)
    assert (prompt_len + 1 + warmup_rounds * (spec_len + 1) + steps
            <= max_seq_len), "cache would overflow"

    cache = engine.init_cache()
    toks = np.zeros(slots, np.int32)
    learned = engine.return_hidden  # drafter == "learned"
    hidden = (jnp.zeros((slots, cfg.model.hidden_size),
                        jnp.dtype(cfg.model.dtype)) if learned else None)
    # greedy prefill epilogue (temp 0) == the host argmax it replaces
    pf_sample = ((jax.random.PRNGKey(1), 0.0, 0, 1.0)
                 if sample_on_device else None)
    hist = []
    for s in range(slots):
        out = engine.prefill(params, prompt, sample=pf_sample)
        kv, logits = out[:2]
        if learned:
            hidden = hidden.at[s].set(jnp.asarray(out[2])[0])
        cache = engine.insert(cache, kv, s, prompt_len)
        # epilogue engines return the greedy token id directly
        toks[s] = (np.asarray(logits).reshape(-1)[0] if sample_on_device
                   else np.argmax(np.asarray(logits)[0]))
        hist.append(list(prompt) + [int(toks[s])])
    proposer = (LearnedDrafter(engine, params) if learned
                else NgramDrafter(engine.spec_ngram))

    eos = np.full(slots, -1, np.int32)  # bench streams never stop early
    temp = np.zeros(slots, np.float32)
    top_k = np.zeros(slots, np.int32)
    top_p = np.ones(slots, np.float32)
    key = jax.random.PRNGKey(0)
    produced = np.zeros(slots, np.int64)
    stats = np.zeros(2, np.int64)  # proposed, accepted

    def spec_round(cache, key, budget):
        nonlocal hidden
        import jax.numpy as jnp

        tokens = np.zeros((slots, spec_len + 1), np.int32)
        active = budget > 0
        if learned:
            td = time.perf_counter()
            batch = proposer.propose_batch(toks, hidden, spec_len)
            engine.observe_dispatch("draft", time.perf_counter() - td)
        for s in np.flatnonzero(active):
            tokens[s, 0] = toks[s]
            tokens[s, 1:] = (batch[s] if learned
                             else proposer.propose(hist[s], spec_len))
        key, sub = jax.random.split(key)
        td = time.perf_counter()
        out = engine.verify(
            params, cache, tokens, sub, eos, budget, temp, top_k, top_p)
        cache, emitted, counts, accepted = out[:4]
        emitted = np.asarray(emitted)  # ONE host sync per dispatch
        counts = np.asarray(counts)
        if learned:
            hidden = jnp.where(jnp.asarray(counts > 0)[:, None], out[4],
                               hidden)
        engine.observe_dispatch("verify", time.perf_counter() - td)
        for s in np.flatnonzero(counts):
            hist[s].extend(int(t) for t in emitted[s, : counts[s]])
            toks[s] = emitted[s, counts[s] - 1]
        stats[0] += spec_len * int(active.sum())
        stats[1] += int(np.asarray(accepted).sum())
        return cache, key, counts

    for _ in range(warmup_rounds):
        cache, key, _ = spec_round(
            cache, key, np.full(slots, spec_len + 1, np.int32))
    stats[:] = 0
    dispatches = 0
    t0 = time.perf_counter()
    while np.any(produced < steps):
        cache, key, counts = spec_round(
            cache, key, (steps - produced).astype(np.int32))
        produced += counts
        dispatches += 1
    dt = time.perf_counter() - t0
    accept = stats[1] / max(stats[0], 1)
    # one cache walk per verify dispatch emits ~1/dpt tokens, so per-TOKEN
    # bytes scale by dispatches-per-token (keeps spec rows comparable to
    # the decode modes' one-walk-per-token accounting)
    dpt = dispatches / steps
    kv_bytes = int(round(kv_bytes_per_token(engine, cache["lengths"]) * dpt))
    return slots * steps / dt, dpt, accept, kv_bytes, weight_bytes, engine


def run_spec_auto(cfg, *, slots: int, max_seq_len: int, prompt_len: int,
                  steps: int, spec_len: int = 4, drafter: str = "ngram",
                  attend_impl: str = "dense",
                  kv_layout: str = "contiguous",
                  kv_page_policy: str = "uniform",
                  sample_on_device: bool = False,
                  weight_dtype: str = "bf16"):
    """The CONTROLLER run: a mixed repetitive/random workload through the
    real ContinuousBatcher with ``inference.spec_controller`` enabled.
    Half the requests carry the repetitive prompt ``run_spec`` uses (the
    regime speculation serves — their slots should converge to
    spec_len > 0 and per-request dispatches/token < 1), half carry
    RANDOM prompts (hard traffic — their slots should converge to
    spec_len == 0, speculation out of the way). Greedy, so output is
    bit-identical to spec-off regardless of what the controller decides.

    Returns (tokens/s, dispatches_per_token, accept_rate, kv_bytes/token,
    weight_bytes_total, engine, auto) where ``auto`` carries the
    controller story: spec_len_effective (mean final per-slot draft
    length), accept_rate_by_drafter, controller-decision counts, and
    per-regime dispatches-per-token."""
    import numpy as np

    from picotron_tpu.config import Config
    from picotron_tpu.inference import ContinuousBatcher, InferenceEngine, \
        Request

    raw = cfg.to_dict()
    raw["inference"].update(dict(
        spec_len=spec_len, drafter=drafter,
        spec_controller=dict(raw["inference"].get("spec_controller", {}),
                             enabled=True, window=max(4, spec_len),
                             hysteresis=2)))
    cfg = Config.from_dict(raw)
    import jax

    engine = InferenceEngine(cfg, slots=slots, max_seq_len=max_seq_len,
                             attend_impl=attend_impl, kv_layout=kv_layout,
                             kv_page_policy=kv_page_policy,
                             sample_on_device=sample_on_device,
                             weight_dtype=weight_dtype)
    params, weight_bytes = bench_params(engine, cfg)
    rng = np.random.default_rng(0)
    rep_prompt = [int(t) for t in np.resize(
        rng.integers(1, cfg.model.vocab_size, 4), prompt_len)]
    # warmup: absorb compilation OUTSIDE the timed window, run/run_spec's
    # protocol — a throwaway batcher on the same engine compiles the
    # prefill bucket, the verify program, and (learned) the draft
    # dispatch; the decode_block fallback program (reached mid-run once
    # the controller turns slots off) is compiled explicitly against a
    # scratch cache with zero budgets
    warm = ContinuousBatcher(engine, params)
    warm.run([Request("w_rep", list(rep_prompt),
                      max_new_tokens=spec_len + 2),
              Request("w_rand",
                      [int(t) for t in rng.integers(
                          1, cfg.model.vocab_size, prompt_len)],
                      max_new_tokens=spec_len + 2)])
    keys = np.stack([np.asarray(jax.random.PRNGKey(i))
                     for i in range(engine.decode_block_len)])
    zero = np.zeros(slots, np.int32)
    engine.decode_block(params, engine.init_cache(), zero, keys,
                        np.full(slots, -1, np.int32), zero,
                        np.zeros(slots, np.float32), zero,
                        np.ones(slots, np.float32))
    batcher = ContinuousBatcher(engine, params)
    # registry counters are engine-lifetime: snapshot what the warmup
    # drafted so the per-drafter split below covers the timed run only
    reg = batcher.obs.registry
    base = {kind: (reg.counter("picotron_drafter_proposed_total",
                               drafter=kind).value,
                   reg.counter("picotron_drafter_accepted_total",
                               drafter=kind).value)
            for kind in batcher._drafters}
    reqs = []
    for s in range(slots):
        if s % 2 == 0:
            reqs.append(Request(f"rep{s}", list(rep_prompt),
                                max_new_tokens=steps))
        else:
            prompt = [int(t) for t in
                      rng.integers(1, cfg.model.vocab_size, prompt_len)]
            reqs.append(Request(f"rand{s}", prompt, max_new_tokens=steps))
    t0 = time.perf_counter()
    results = batcher.run(reqs)
    dt = time.perf_counter() - t0
    total_toks = sum(len(r.tokens) for r in results.values())
    dpt = batcher.decode_dispatches / max(total_toks, 1)

    def regime_dpt(prefix):
        rs = [r for u, r in results.items() if u.startswith(prefix)]
        toks = sum(len(r.tokens) for r in rs)
        return round(sum(r.dispatches for r in rs) / max(toks, 1), 4)

    by_drafter = {}
    for kind in batcher._drafters:
        bp, ba = base.get(kind, (0.0, 0.0))
        prop = reg.counter("picotron_drafter_proposed_total",
                           drafter=kind).value - bp
        if prop:
            acc = reg.counter("picotron_drafter_accepted_total",
                              drafter=kind).value - ba
            by_drafter[kind] = round(acc / prop, 4)
    auto = {
        "spec_len_effective": round(float(np.mean(
            [r.spec_len_final or 0 for r in results.values()])), 3),
        "spec_len_by_regime": {
            "repetitive": round(float(np.mean(
                [r.spec_len_final or 0 for u, r in results.items()
                 if u.startswith("rep")])), 3),
            "random": round(float(np.mean(
                [r.spec_len_final or 0 for u, r in results.items()
                 if u.startswith("rand")])), 3)},
        "dispatches_per_token_by_regime": {
            "repetitive": regime_dpt("rep"), "random": regime_dpt("rand")},
        "accept_rate_by_drafter": by_drafter,
        "controller_decisions": batcher.controller.decisions,
    }
    # end-of-stream live window per slot: retired slots have released
    # their cache lengths to 0, so reconstruct what each request held
    # when it finished (run/run_spec sample lengths while still parked)
    final_lengths = np.asarray(
        [len(r.prompt) + len(r.tokens) for r in results.values()],
        np.int64)
    kv_bytes = int(round(kv_bytes_per_token(engine, final_lengths) * dpt))
    return (total_toks / dt, dpt, batcher.accept_rate or 0.0, kv_bytes,
            weight_bytes, engine, auto)


def run_tenants(cfg, *, tenants: int, adapter_rank: int, slots: int,
                max_seq_len: int, prompt_len: int, steps: int,
                spec_len: int = 0, drafter: str = "ngram",
                attend_impl: str = "dense", kv_layout: str = "contiguous",
                kv_page_policy: str = "uniform",
                sample_on_device: bool = False,
                weight_dtype: str = "bf16"):
    """The MULTI-TENANT run (ISSUE 16): ``tenants`` rank-``adapter_rank``
    adapters over one shared base, plus base-only (null-adapter) rows, all
    mixed in the SAME continuous batch — every decode/verify dispatch
    serves several tenants at once through the segmented adapter matmul.
    Requests round-robin across tenants (repetitive prompts, so the
    speculative variant has an attractor to ride) with one anonymous
    base request per batch wave riding along as the isolation control.

    Returns (tokens/s, dispatches_per_token, accept_rate_or_None,
    kv_bytes/token, weight_bytes_total, engine, tenancy) where
    ``tenancy`` carries the per-tenant story: tokens, dispatches/token,
    TTFT, accept (spec runs), and the pack's adapter_bytes_per_token —
    the HBM cost every decode step pays to stream all live adapters."""
    import numpy as np

    from picotron_tpu.inference import ContinuousBatcher, InferenceEngine, \
        Request
    from picotron_tpu.inference import tenancy as _tenancy

    pack = _tenancy.AdapterPack(cfg.model, slots=tenants + 1,
                                rank=adapter_rank)
    for i in range(1, tenants + 1):
        # a visible per-tenant voice: large enough to steer greedy argmax
        # on the tiny smoke model, distinct seed per tenant
        pack.set_slot(i, pack.random_leaves(adapter_rank, seed=i,
                                            scale=0.5))
    engine = InferenceEngine(cfg, slots=slots, max_seq_len=max_seq_len,
                             spec_len=spec_len, attend_impl=attend_impl,
                             kv_layout=kv_layout,
                             kv_page_policy=kv_page_policy,
                             sample_on_device=sample_on_device,
                             weight_dtype=weight_dtype, drafter=drafter,
                             adapters=pack)
    params, weight_bytes = bench_params(engine, cfg)
    rng = np.random.default_rng(0)
    rep_prompt = [int(t) for t in np.resize(
        rng.integers(1, cfg.model.vocab_size, 4), prompt_len)]

    def reqs_for(tag):
        out = []
        for s in range(slots):
            tid = s % (tenants + 1)  # slot 0 of each wave = base-only
            out.append(Request(
                f"{tag}t{tid}_{s}", list(rep_prompt),
                max_new_tokens=steps,
                tenant=f"tenant{tid}" if tid else "",
                adapter_slot=tid,
                priority=2 if tid == 1 else 1,  # one premium class
                ttft_slo_ms=500.0 if tid == 1 else None))
        return out

    # warmup wave absorbs compilation (prefill bucket + decode/verify
    # programs) outside the timed window, run/run_spec's protocol
    warm = ContinuousBatcher(engine, params)
    warm.run([Request(f"w{i}", list(rep_prompt), max_new_tokens=2,
                      adapter_slot=i % (tenants + 1))
              for i in range(min(slots, tenants + 1))])
    batcher = ContinuousBatcher(engine, params)
    t0 = time.perf_counter()
    results = batcher.run(reqs_for("m_"))
    dt = time.perf_counter() - t0
    total_toks = sum(len(r.tokens) for r in results.values())
    dpt = batcher.decode_dispatches / max(total_toks, 1)

    per_tenant = {}
    for tid in range(tenants + 1):
        rs = [r for u, r in results.items()
              if u.startswith(f"m_t{tid}_")]
        toks = sum(len(r.tokens) for r in rs)
        disp = sum(r.dispatches for r in rs)
        ttfts = [r.ttft_s for r in rs if r.ttft_s is not None]
        row = {
            "tokens": toks,
            "dispatches_per_token": round(disp / max(toks, 1), 4),
            "ttft_s": round(float(np.mean(ttfts)), 5) if ttfts else None,
        }
        if spec_len > 0:
            # each verify dispatch emits 1 + accepted and proposes
            # spec_len, so the per-tenant accept rate falls out of the
            # per-request (dispatches, tokens) pair
            row["accept_rate"] = round(
                max(0, toks - disp) / max(disp * spec_len, 1), 4)
        per_tenant["base" if tid == 0 else f"tenant{tid}"] = row
    tenancy = {
        "tenants": tenants,
        "adapter_rank": adapter_rank,
        "adapter_bytes_per_token": pack.bytes_per_token(),
        "per_tenant": per_tenant,
    }
    final_lengths = np.asarray(
        [len(r.prompt) + len(r.tokens) for r in results.values()],
        np.int64)
    kv_bytes = kv_bytes_per_token(engine, final_lengths)
    if spec_len > 0:  # run_spec's per-token walk normalization
        kv_bytes = int(round(kv_bytes * dpt))
    accept = (batcher.accept_rate or 0.0) if spec_len > 0 else None
    return (total_toks / dt, dpt, accept, kv_bytes, weight_bytes, engine,
            tenancy)


# --------------------------------------------------------------------------- #
# --disagg: prefill/decode interference bench (ISSUE 15)
# --------------------------------------------------------------------------- #

# the interference workload: short-prompt decode streams whose inter-token
# gaps we time, plus long shared-prefix prompts arriving mid-stream whose
# chunked prefills are the interference source
_DISAGG_MODEL = dict(
    name="tiny-disagg", num_hidden_layers=4, num_attention_heads=8,
    num_key_value_heads=8, hidden_size=256, intermediate_size=1024,
    vocab_size=4096, max_position_embeddings=256, dtype="float32",
    attention_impl="sdpa")
_DISAGG_SIZES = dict(slots=3, stream_prompt=8, stream_tokens=48,
                     long_prompt=96, long_shared=64, n_streams=2,
                     n_long=3, prefill_chunk=16, page_len=16)


def _launch_replica(cfg_path: str, role: str, slots: int):
    """One serve.py replica as a SUBPROCESS (its own interpreter + GIL —
    the honest CPU proxy for a disaggregated host). Returns
    (Popen, port) once the CLI's "serving" event line reports the
    ephemeral port; a reader thread keeps draining stdout after that."""
    import subprocess
    import threading

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "picotron_tpu.tools.serve",
         "--config", cfg_path, "--random-init", "--port", "0",
         "--slots", str(slots), "--role", role,
         "--stall-timeout", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    # readline() blocks with no timeout of its own: a replica that wedges
    # before printing the serving event would hang the smoke forever. The
    # timer kill turns that into EOF -> a loud launch failure at 180s.
    watchdog = threading.Timer(180.0, proc.kill)
    watchdog.start()
    port = None
    try:
        while True:
            line = proc.stdout.readline()
            if not line:  # EOF: the child exited (or the watchdog fired)
                raise RuntimeError(
                    f"replica (role={role}) died (or hung past the launch "
                    f"deadline) before reporting a port")
            try:
                evt = json.loads(line)
            except ValueError:
                continue
            if evt.get("evt") == "serving":
                port = evt["port"]
                break
    except BaseException:
        proc.kill()
        raise
    finally:
        watchdog.cancel()
    threading.Thread(target=lambda: [None for _ in proc.stdout],
                     daemon=True).start()
    return proc, port


def _stream_tpot(port: int, prompt, max_new: int, times: list) -> list:
    """Stream one request, appending a perf_counter stamp per token row;
    returns the tokens (the bit-identity cross-check)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
    toks = []
    try:
        conn.request("POST", "/generate",
                     json.dumps({"prompt": list(prompt),
                                 "max_new_tokens": max_new,
                                 "stream": True}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        while True:
            line = resp.readline()
            if not line:
                return toks
            row = json.loads(line)
            if row.get("event") == "token":
                times.append(time.perf_counter())
                toks.append(int(row["token"]))
            elif row.get("event") == "done":
                return toks
    finally:
        conn.close()


def _interference_phase(port: int, sizes: dict, rng, long_prompts) -> tuple:
    """One timed phase against ONE endpoint (a replica or the router):
    ``n_streams`` token-timed decode streams, with ``long_prompts``
    injected once the streams are flowing. Returns (tpot samples,
    stream token lists)."""
    import threading

    stamps = [[] for _ in range(sizes["n_streams"])]
    streams = [[] for _ in range(sizes["n_streams"])]
    threads = []
    for i in range(sizes["n_streams"]):
        prompt = [int(t) for t in
                  rng.integers(1, _DISAGG_MODEL["vocab_size"],
                               sizes["stream_prompt"])]

        def go(i=i, prompt=prompt):
            streams[i].extend(_stream_tpot(
                port, prompt, sizes["stream_tokens"], stamps[i]))

        t = threading.Thread(target=go)
        t.start()
        threads.append(t)
    # inject the long prefills once every stream is past its own prefill
    deadline = time.monotonic() + 60
    while (any(len(s) < 3 for s in stamps)
           and time.monotonic() < deadline):
        time.sleep(0.005)
    longs = []
    for prompt in long_prompts:
        def go_long(prompt=prompt):
            import http.client

            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=600)
            try:
                conn.request("POST", "/generate",
                             json.dumps({"prompt": list(prompt),
                                         "max_new_tokens": 4}),
                             {"Content-Type": "application/json"})
                conn.getresponse().read()
            finally:
                conn.close()

        t = threading.Thread(target=go_long)
        t.start()
        longs.append(t)
    for t in threads + longs:
        t.join(timeout=600)
    samples = []
    for row in stamps:
        samples.extend(b - a for a, b in zip(row[1:], row[2:]))
    return samples, streams


def _p(samples, q):
    import numpy as np

    return float(np.percentile(np.asarray(samples), q)) if samples else None


def run_disagg() -> dict:
    """The mixed-interference A/B/C (CPU proxy; subprocess replicas so
    each role owns an interpreter, the one-host stand-in for separate
    machines):

    - ``baseline``:  decode streams on one colocated (role=both) replica,
      NO long prefills — the no-interference TPOT floor;
    - ``colocated``: same replica shape, long shared-prefix prompts
      arriving mid-stream — their chunked prefills run inside the same
      batcher loop, so every decode slot stalls behind them;
    - ``disagg``:    a prefill + decode two-role fleet behind the
      router — the long prompts' prefills land on the prefill worker and
      stream to the decode worker as KV pages, so the decode batcher
      never spends a dispatch on them.

    Greedy streams are asserted bit-identical across the three phases
    (same seed everywhere); the record carries the TPOT percentiles,
    handoff bytes/latency, and the cluster-wide prefix hit rate."""
    import tempfile

    import numpy as np

    from picotron_tpu.config import RouterConfig
    from picotron_tpu.tools import serve
    from picotron_tpu.tools.router import RouterServer

    sizes = dict(_DISAGG_SIZES)
    rng0 = np.random.default_rng(7)
    shared = [int(t) for t in rng0.integers(
        1, _DISAGG_MODEL["vocab_size"], sizes["long_shared"])]
    long_prompts = []
    for _ in range(sizes["n_long"]):
        tail = [int(t) for t in rng0.integers(
            1, _DISAGG_MODEL["vocab_size"],
            sizes["long_prompt"] - sizes["long_shared"])]
        long_prompts.append(shared + tail)

    raw = {
        "distributed": {"tp_size": 1, "use_cpu": True},
        "model": dict(_DISAGG_MODEL),
        "training": {"seq_length": 64},
        "dataset": {"name": "synthetic"},
        "inference": {"kv_layout": "paged",
                      "kv_page_len": sizes["page_len"],
                      "prefill_chunk": sizes["prefill_chunk"],
                      "decode_block_len": 1},
    }
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(raw, f)
        cfg_path = f.name

    procs = []
    rs = None
    out: dict = {}
    try:
        both_proc, both_port = _launch_replica(cfg_path, "both",
                                               sizes["slots"])
        procs.append(both_proc)

        def warm(port):
            # absorb compiles outside every timed window: the stream
            # shape, the chunked-prefill program, and a page import
            serve._post(port, {"prompt": [1] * sizes["stream_prompt"],
                               "max_new_tokens": 4})
            serve._post(port, {"prompt": list(range(
                1, sizes["long_prompt"] + 1)), "max_new_tokens": 2})

        warm(both_port)
        rng = np.random.default_rng(0)
        base_samples, base_streams = _interference_phase(
            both_port, sizes, rng, [])
        rng = np.random.default_rng(0)
        colo_samples, colo_streams = _interference_phase(
            both_port, sizes, rng, long_prompts)

        pre_proc, pre_port = _launch_replica(cfg_path, "prefill",
                                             sizes["slots"])
        dec_proc, dec_port = _launch_replica(cfg_path, "decode",
                                             sizes["slots"])
        procs += [pre_proc, dec_proc]
        rs = RouterServer(
            [f"127.0.0.1:{pre_port}", f"127.0.0.1:{dec_port}"],
            RouterConfig(probe_interval_s=0.1, scrape_stale_s=5.0),
            log=lambda *a, **k: None)
        rs.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (len(rs.router._candidates(kind="prefill")) == 1
                    and len(rs.router._eligible()) == 1):
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("disagg fleet never became eligible")
        warm(rs.port)
        rng = np.random.default_rng(0)
        dis_samples, dis_streams = _interference_phase(
            rs.port, sizes, rng, long_prompts)

        # greedy bit-identity across phases: interference must cost
        # latency, never tokens
        assert colo_streams == base_streams == dis_streams, \
            "streams diverged across phases (greedy must be identical)"

        router_stats = rs.router.stats()
        stz = {"prefill": serve._get(pre_port, "/statz")[1],
               "decode": serve._get(dec_port, "/statz")[1]}
        # cluster-wide prefix effectiveness: cached (local radix hits on
        # the prefill worker + remote imports seated on the decode
        # worker) over all prompt tokens the fleet admitted
        cached = sum(s.get("prefix_cached_tokens", 0) for s in stz.values())
        queried = sum(s.get("prefix_queries", 0) for s in stz.values())
        prompt_total = 0
        for s in stz.values():
            # prompt_tokens isn't exported; reconstruct from hit rate
            hr = s.get("prefix_hit_rate")
            ct = s.get("prefix_cached_tokens", 0)
            if hr:
                prompt_total += int(round(ct / hr))
        handoffs = max(1, router_stats["handoffs"].get("served", 0))
        out = {
            "tpot_p50_baseline": _p(base_samples, 50),
            "tpot_p95_baseline": _p(base_samples, 95),
            "tpot_p50_colocated": _p(colo_samples, 50),
            "tpot_p95_colocated": _p(colo_samples, 95),
            "tpot_p50_disagg": _p(dis_samples, 50),
            "tpot_p95_disagg": _p(dis_samples, 95),
            "handoffs_served": router_stats["handoffs"].get("served", 0),
            "handoffs_fallback": router_stats["handoffs"].get(
                "fallback", 0),
            "handoff_bytes_per_request":
                router_stats["handoff_bytes"] // handoffs,
            "handoff_latency_s": router_stats["handoff_s"],
            "cluster_prefix_hit_rate": (
                round(cached / prompt_total, 4) if prompt_total else None),
            "cluster_prefix_queries": queried,
            "decode_worker_handoff_seated":
                stz["decode"].get("handoff_seated", 0),
            "decode_worker_prefill_dispatches":
                stz["decode"].get("prefill_dispatches", 0),
            "sizes": sizes,
        }
        return out
    finally:
        if rs is not None:
            rs.stop()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except Exception:  # noqa: BLE001 - teardown best effort
                p.kill()
        os.unlink(cfg_path)


# --------------------------------------------------------------------------- #
# --fleet: elastic fleet controller bench (ISSUE 17)
# --------------------------------------------------------------------------- #

# Sized for a 1-core box: three subprocess jax workers plus the router
# and controller share whatever CPU there is, so the model is as small
# as the serving stack allows and the spike is just deep enough to put
# requests in a queue (3 workers x 1 slot, 5 concurrent streams).
_FLEET_MODEL = dict(
    name="tiny-fleet", num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=4, hidden_size=128, intermediate_size=512,
    vocab_size=2048, max_position_embeddings=256, dtype="float32",
    attention_impl="sdpa")
_FLEET_SIZES = dict(slots=1, stream_prompt=8, stream_tokens=16,
                    n_steady=3, n_spike=5, kv_num_pages=128)


def _stream_ttft(port: int, prompt, max_new: int):
    """Stream one request via the router; returns (ttft_s, tokens,
    done_row) — ttft is request-start to first token row on the wire."""
    from picotron_tpu.tools.router import _stream_post

    t0 = time.perf_counter()
    first = {}

    def on_tok(i, row):
        if i == 0:
            first["t"] = time.perf_counter() - t0

    st, rows = _stream_post(port, {"prompt": list(prompt),
                                   "max_new_tokens": max_new},
                            on_token=on_tok)
    toks = [r["token"] for r in rows if r.get("event") == "token"]
    done = [r for r in rows if r.get("event") == "done"]
    if st != 200 or len(done) != 1 or done[0].get("tokens") != toks:
        raise RuntimeError(f"stream failed: HTTP {st}, rows={rows[-2:]}")
    return first.get("t"), toks, done[0]


def run_fleet() -> dict:
    """The elastic-controller rung: a real 3-worker SUBPROCESS fleet
    (serve.py under supervise --serve; a SIGKILL is a real process-group
    death) behind the router, owned by the fleet controller.

    Measures the three latencies that define elasticity on this stack:

    - ``scale_up_latency_s``: controller start to 3 workers launched,
      registered, and router-eligible (cold jax startup included — this
      IS the price of a scale-up on CPU);
    - ``replace_latency_s``: SIGKILL of a worker holding a live routed
      stream to the fleet back at full strength (the stream itself must
      finish exactly-once, greedy bit-identical, via router replay);
    - ``ttft_p95_during_spike`` vs ``ttft_p95_steady``: first-token
      latency under an admission spike that forces a grow decision,
      against the unloaded floor."""
    import tempfile
    import threading

    from picotron_tpu.config import FleetConfig, RouterConfig
    from picotron_tpu.tools.fleet import (FleetController, RouterAdmin,
                                          SubprocessLauncher)
    from picotron_tpu.tools.router import RouterServer, _wait_for

    sizes = dict(_FLEET_SIZES)
    raw = {
        "distributed": {"tp_size": 1, "use_cpu": True},
        "model": dict(_FLEET_MODEL),
        "training": {"seq_length": 64},
        "dataset": {"name": "synthetic"},
        "inference": {"kv_layout": "paged", "kv_page_len": 16,
                      "kv_num_pages": sizes["kv_num_pages"],
                      "decode_block_len": 1},
    }
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(raw, f)
        cfg_path = f.name

    # generous stream budgets: a queued spike request legitimately waits
    # for a slot on a contended box, and waiting is what the TTFT delta
    # measures — a mid-queue idle timeout would misread it as a failure
    rcfg = RouterConfig(probe_interval_s=0.2, scrape_stale_s=10.0,
                        connect_timeout_s=30.0,
                        stream_idle_timeout_s=300.0)
    rs = RouterServer([], rcfg, allow_empty=True,
                      log=lambda *a, **k: None)
    rs.start()
    launcher = SubprocessLauncher(
        cfg_path, slots=sizes["slots"],
        serve_args=("--stall-timeout", "0"))
    fcfg = FleetConfig(
        scrape_interval_s=0.5, scrape_timeout_s=5.0, hysteresis=2,
        cooloff_s=2.0, queue_high=0.5, queue_low=0.25, pool_high=0.95,
        pool_low=0.4, min_workers=3, max_workers=4, max_replaces=3,
        replace_backoff_s=0.25, replace_backoff_max_s=2.0,
        drain_timeout_s=60.0)
    ctl = FleetController(fcfg, launcher, RouterAdmin("127.0.0.1", rs.port),
                          log=lambda *a, **k: None)

    def up():
        with ctl._mu:
            return [w for w in ctl.workers.values() if w.state == "up"]

    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    out: dict = {}
    try:
        t0 = time.perf_counter()
        ctl.start()
        if not (_wait_for(lambda: len(up()) >= 3, timeout=600)
                and rs.router.wait_eligible(3, timeout=60)):
            raise RuntimeError("fleet never bootstrapped to 3 workers")
        scale_up_latency_s = time.perf_counter() - t0

        # warm every worker's stream shape, then the steady TTFT floor
        for _ in range(3):
            _stream_ttft(rs.port, prompt, 4)
        steady = []
        oracle = None
        for _ in range(sizes["n_steady"]):
            ttft, toks, _done = _stream_ttft(rs.port, prompt,
                                             sizes["stream_tokens"])
            steady.append(ttft)
            if oracle is None:
                oracle = toks
            elif toks != oracle:
                raise RuntimeError("greedy streams diverged across "
                                   "workers (identical seeds required)")

        # SIGKILL a worker holding this live stream; the router must
        # replay it exactly-once and the controller must replace
        killed = {}

        def kill_at(i, row):
            if i == 4 and not killed:
                busy = None
                for nm, rep in rs.router.replicas.items():
                    with rep._mu:
                        if rep.inflight > 0:
                            busy = nm
                            break
                ws = up()
                for w in ws:
                    if w.router_name == busy:
                        killed["worker"] = w.name
                        w.handle.kill()
                        return
                killed["worker"] = ws[0].name
                ws[0].handle.kill()

        from picotron_tpu.tools.router import _stream_post

        t_kill = time.perf_counter()
        st, rows = _stream_post(rs.port,
                                {"prompt": list(prompt),
                                 "max_new_tokens": sizes["stream_tokens"]},
                                on_token=kill_at)
        toks = [r["token"] for r in rows if r.get("event") == "token"]
        done = [r for r in rows if r.get("event") == "done"]
        if not (st == 200 and killed and len(done) == 1
                and done[0]["replays"] >= 1 and toks == oracle):
            raise RuntimeError(
                f"kill drill stream not exactly-once bit-identical: "
                f"HTTP {st}, killed={killed}, tail={rows[-2:]}")
        if not _wait_for(
                lambda: (ctl.decisions().get("replace", 0) >= 1
                         and len(up()) >= 3), timeout=600):
            raise RuntimeError("dead worker never replaced")
        replace_latency_s = time.perf_counter() - t_kill

        # admission spike: concurrent streams over the fleet; the
        # controller must decide to grow, and nothing may be shed
        grow0 = ctl.decisions().get("grow", 0)
        spike_ttfts: list = []
        spike_errs: list = []

        def spike_one():
            try:
                ttft, toks, _d = _stream_ttft(rs.port, prompt,
                                              sizes["stream_tokens"])
                if toks != oracle:
                    raise RuntimeError("spike stream diverged")
                if ttft is not None:
                    spike_ttfts.append(ttft)
            except Exception as e:  # noqa: BLE001 - collected and gated
                spike_errs.append(repr(e))

        threads = [threading.Thread(target=spike_one)
                   for _ in range(sizes["n_spike"])]
        for t in threads:
            t.start()
        grew = _wait_for(
            lambda: ctl.decisions().get("grow", 0) > grow0, timeout=60)
        for t in threads:
            t.join(timeout=600)
        if spike_errs:
            raise RuntimeError(f"spike streams failed: {spike_errs[:3]}")
        shed = rs.router.stats()["requests"]["shed"]
        out = {
            "scale_up_latency_s": round(scale_up_latency_s, 3),
            "replace_latency_s": round(replace_latency_s, 3),
            "ttft_p95_steady": _p(steady, 95),
            "ttft_p50_steady": _p(steady, 50),
            "ttft_p95_during_spike": _p(spike_ttfts, 95),
            "ttft_p50_during_spike": _p(spike_ttfts, 50),
            "grow_decided": bool(grew),
            "spike_shed": int(shed),
            "decisions": ctl.decisions(),
            "sizes": sizes,
        }
        return out
    finally:
        ctl.stop(drain_workers=True)
        rs.stop()
        os.unlink(cfg_path)


def run_dp(dp: int) -> dict:
    """dp-sharded continuous batching (CPU proxy): the SAME tiny-model
    batcher workload at dp=1 and dp=N — one logical engine whose slot axis
    spans the dp mesh axis, paged KV pool sharded with it, rebalance
    planner armed. The workload is shaped to skew occupancy (long streams
    land on shard 0, short ones on shard 1 finish early), so the planner
    must migrate a slot's pages across shards mid-run through the
    page-transport device path while streams keep decoding.

    Gates (enforced by main's --dp branch / ``make dp-smoke``):
    - greedy token streams at dp=N are BIT-IDENTICAL to dp=1;
    - ``slots_total == dp * slots_per_shard`` (the global slot map);
    - zero dp-axis collectives traced during the whole run — prompts fit
      one prefill chunk, so even the chunked-prefill owner-reduce (the one
      dp collective the engine owns) never appears, and the decode hot
      path is verified shard-local via the comm_trace channel;
    - the rebalance planner fired at least once (the workload is
      deterministic, so this pins that migration happens OFF the jitted
      dispatch path yet streams stay exact).
    """
    import contextlib
    import io

    import jax

    from picotron_tpu.config import Config
    from picotron_tpu.inference import (
        ContinuousBatcher,
        InferenceEngine,
        Request,
    )
    from picotron_tpu.models import llama

    model = dict(
        name="tiny", num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, hidden_size=64, intermediate_size=128,
        vocab_size=256, max_position_embeddings=96, dtype="float32",
        attention_impl="sdpa")

    def one(d: int) -> dict:
        cfg = Config.from_dict({
            "distributed": {"tp_size": 1, "use_cpu": True},
            "model": dict(model),
            "training": {"seq_length": 96},
            "dataset": {"name": "synthetic"},
            "inference": {"dp_size": d, "kv_layout": "paged",
                          "kv_page_len": 8},
        })
        engine = InferenceEngine(cfg, slots=4, max_seq_len=96,
                                 decode_block_len=4)
        params = engine.shard_params(jax.jit(
            lambda k: llama.init_params(k, cfg.model))(
                jax.random.PRNGKey(0)))
        b = ContinuousBatcher(engine, params)
        skew = [0]

        def on_token(uid, tok):
            occ = b.shard_occupancy()
            skew[0] = max(skew[0], max(occ) - min(occ))

        b.on_token = on_token
        reqs = [Request("l0", [1, 2, 3, 4, 5], max_new_tokens=28),
                Request("l1", [9, 8, 7, 6], max_new_tokens=28),
                Request("s0", [11, 12], max_new_tokens=4),
                Request("s1", [13, 14, 15], max_new_tokens=4)]
        # comm_trace capture: PICOTRON_VERBOSE=1 prints one stderr line
        # per collective per trace — a dp-axis line during this window
        # would mean the sharded hot path grew cross-shard traffic
        old = os.environ.get("PICOTRON_VERBOSE")
        os.environ["PICOTRON_VERBOSE"] = "1"
        buf = io.StringIO()
        t0 = time.perf_counter()
        try:
            with contextlib.redirect_stderr(buf):
                res = b.run(reqs)
        finally:
            if old is None:
                os.environ.pop("PICOTRON_VERBOSE", None)
            else:
                os.environ["PICOTRON_VERBOSE"] = old
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in res.values())
        dp_comms = [ln for ln in buf.getvalue().splitlines()
                    if ln.startswith("[comm]") and "axis=dp" in ln]
        st = b.stats()
        return {
            "streams": {uid: r.tokens for uid, r in res.items()},
            "tokens_per_s": toks / dt if dt > 0 else 0.0,
            "stats": st,
            "dispatch_latency_s": dispatch_latency_summary(engine),
            "dp_comm_lines": dp_comms,
            "occupancy_skew_peak": skew[0],
            "slots_per_shard": engine.slots_per_shard,
        }

    base, sharded = one(1), one(dp)
    st = sharded["stats"]
    return {
        "dp_size": st["dp_size"],
        "slots_total": st["slots_total"],
        "slots_per_shard": sharded["slots_per_shard"],
        "shard_occupancy": st["shard_occupancy"],
        "occupancy_skew_peak": sharded["occupancy_skew_peak"],
        "rebalance_count": st["rebalance_count"],
        "rebalance_bytes": st["rebalance_bytes"],
        "tokens_per_s_dp1": round(base["tokens_per_s"], 1),
        "tokens_per_s_dpN": round(sharded["tokens_per_s"], 1),
        "dispatch_latency_s": {"dp1": base["dispatch_latency_s"],
                               f"dp{dp}": sharded["dispatch_latency_s"]},
        "dp_collectives_traced": len(sharded["dp_comm_lines"]),
        "dp_comm_lines": sharded["dp_comm_lines"][:8],
        "streams_match": base["streams"] == sharded["streams"],
    }


def run_overlap(synthetic_s: float) -> dict:
    """Zero-bubble overlapped scheduling A/B (CPU proxy): the SAME
    tiny-model batcher workload with ``inference.overlap`` off and on,
    same seed, same per-slot key schedule. The tiny CPU model produces
    no hideable device time of its own, so the batcher's synthetic-sync
    knob pads every round's device window to ``synthetic_s`` and an
    ``on_token`` sleeper injects per-token host delivery work sized so
    per-round host work matches it — the "host work and device time
    comparable" regime the pipeline exists for. Off mode pays
    device + host serially per round; on mode hides the host walk of
    round N inside round N+1's device window.

    Gates (enforced by main's --overlap branch / ``make overlap-smoke``):
    - token streams BIT-IDENTICAL on vs off (the tentpole invariant);
    - overlap-on ``dispatch_gap_s`` p50 <= 0.5x overlap-off (the
      pipeline is gapless by construction while a round is in flight);
    - overlap-on tokens/s >= 1.3x overlap-off.
    """
    import jax

    from picotron_tpu.config import Config
    from picotron_tpu.inference import (
        ContinuousBatcher,
        InferenceEngine,
        Request,
    )
    from picotron_tpu.models import llama

    model = dict(
        name="tiny", num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, hidden_size=64, intermediate_size=128,
        vocab_size=256, max_position_embeddings=160, dtype="float32",
        attention_impl="sdpa")
    slots, block, new_toks = 4, 4, 40
    # per-token host delivery work sized so a full round's walk (slots *
    # block tokens) plus the batcher's own per-round scheduling overhead
    # lands NEAR the synthetic device window without exceeding it — on
    # the hidden side of the pipeline, host work past the device window
    # becomes the bottleneck again and the A/B only measures noise
    host_tok_s = synthetic_s / (2 * slots * block)

    def one(overlap: bool) -> dict:
        cfg = Config.from_dict({
            "distributed": {"tp_size": 1, "use_cpu": True},
            "model": dict(model),
            "training": {"seq_length": 160},
            "dataset": {"name": "synthetic"},
            "inference": {"overlap": overlap, "key_schedule": "slot"},
        })
        engine = InferenceEngine(cfg, slots=slots, max_seq_len=160,
                                 decode_block_len=block)
        params = engine.shard_params(jax.jit(
            lambda k: llama.init_params(k, cfg.model))(
                jax.random.PRNGKey(0)))
        b = ContinuousBatcher(engine, params, seed=7)
        # warm the jitted prefill/decode programs OUTSIDE the timed
        # window — a FULL batch at the measured prompt length, so the
        # measured run recompiles nothing — then arm the delay knobs
        b.run([Request(f"warm{i}", [3, 1, 4, 1, 5],
                       max_new_tokens=block) for i in range(slots)])
        b._synthetic_sync_s = synthetic_s
        b.on_token = lambda uid, tok: time.sleep(host_tok_s)
        reqs = [Request(f"r{i}", [(7 * i + j) % 199 + 1 for j in range(5)],
                        max_new_tokens=new_toks) for i in range(slots)]
        t0 = time.perf_counter()
        res = b.run(reqs)
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in res.values())
        st = b.stats()
        return {
            "streams": {uid: r.tokens for uid, r in res.items()},
            "tokens_per_s": toks / dt if dt > 0 else 0.0,
            "overlap": st["overlap"],
            "last_host_sync_s": st.get("last_host_sync_s"),
        }

    off, on = one(False), one(True)

    def p50(leg):
        gap = leg["overlap"].get("dispatch_gap_s") or {}
        return gap.get("p50")

    return {
        "synthetic_device_s": synthetic_s,
        "host_token_s": host_tok_s,
        "tokens_per_s_off": round(off["tokens_per_s"], 1),
        "tokens_per_s_on": round(on["tokens_per_s"], 1),
        "speedup": round(on["tokens_per_s"]
                         / max(off["tokens_per_s"], 1e-9), 3),
        "dispatch_gap_s": {"off": off["overlap"].get("dispatch_gap_s"),
                           "on": on["overlap"].get("dispatch_gap_s")},
        "dispatch_gap_p50_off": p50(off),
        "dispatch_gap_p50_on": p50(on),
        "host_work_s": {"off": off["overlap"].get("host_work_s"),
                        "on": on["overlap"].get("host_work_s")},
        "overlap_efficiency": on["overlap"].get("overlap_efficiency"),
        "device_busy_s": on["overlap"].get("device_busy_s"),
        "wall_s": on["overlap"].get("wall_s"),
        "streams_match": off["streams"] == on["streams"],
    }


def run_mixed() -> dict:
    """Mixed prefill–decode dispatch A/B (CPU proxy): long prompts keep
    arriving while a batch of decoders is mid-stream, with
    ``inference.mixed_dispatch`` off (serial admission prefill: every
    chunk is a solo dispatch the seated decoders wait out) and on (the
    chunk rides the fused lane of the decode dispatch itself). Three
    legs, same seed, per-slot key schedule pinned on both sides:

    - ``floor``: decoders only, mixed on — the no-prefill TPOT floor;
    - ``on``:    decoders + arriving long prompts, mixed on;
    - ``off``:   the identical workload, mixed off (serial + gate).

    TPOT is the pooled inter-token gap of the DECODER streams (their
    own first token excluded); TTFT is submit-to-first-token of the
    long prompts. Gates (enforced by main's --mixed branch /
    ``make mixed-smoke``):

    - token streams BIT-IDENTICAL on vs off (the tentpole invariant);
    - decode TPOT p95 under concurrent prefill (on) <= 3x the
      no-prefill floor — prompts land without stalling decode;
    - TTFT p95 on <= 3x off — admission through the lane stays at its
      feed rate, ceil(prompt/chunk) rounds to first token. The bound
      is a CPU-proxy allowance, not a target: here a solo B=1 chunk
      dispatch costs ~1/3 of a full fused round (per-dispatch python
      overhead dominates), so the serial leg's TTFT is structurally
      understated relative to an accelerator, where a C-token chunk
      and a slots*block decode round do comparable work;
    - the on leg actually moved prompt tokens through the lane
      (``picotron_prefill_lane_tokens_total`` > 0).
    """
    import jax

    from picotron_tpu.config import Config
    from picotron_tpu.inference import (
        ContinuousBatcher,
        InferenceEngine,
        Request,
    )
    from picotron_tpu.models import llama

    model = dict(
        name="tiny", num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, hidden_size=64, intermediate_size=128,
        vocab_size=256, max_position_embeddings=160, dtype="float32",
        attention_impl="sdpa")
    slots, block, chunk = 4, 4, 8
    decoders = 3          # long-running decode streams (the TPOT probes)
    long_prompt = 24      # 3 lane chunks at chunk=8; > chunk so it lanes
    # arrivals land mid-decode, triggered by d0's token count — spaced
    # wider than the 3 rounds a 24-token prompt occupies the lane, so
    # TTFT measures the prefill path itself, not queue backlog behind a
    # saturated lane (arrival-rate <= lane-feed-rate is the regime the
    # fused lane serves; past saturation every scheme queues)
    arrive_at_tok = {8: 0, 24: 1, 40: 2}

    def one(mixed: bool, with_prefill: bool) -> dict:
        cfg = Config.from_dict({
            "distributed": {"tp_size": 1, "use_cpu": True},
            "model": dict(model),
            "training": {"seq_length": 160},
            "dataset": {"name": "synthetic"},
            "inference": {"mixed_dispatch": mixed, "prefill_chunk": chunk,
                          "key_schedule": "slot"},
        })
        engine = InferenceEngine(cfg, slots=slots, max_seq_len=160,
                                 decode_block_len=block)
        params = engine.shard_params(jax.jit(
            lambda k: llama.init_params(k, cfg.model))(
                jax.random.PRNGKey(0)))
        b = ContinuousBatcher(engine, params, seed=7)
        # warm every program the measured run needs OUTSIDE the timed
        # window: the (fused, when mixed) decode family at full batch,
        # the short-prompt prefill bucket, and the long-prompt path
        # (lane chunks when mixed, bucketed serial prefill when not)
        b.run([Request(f"warm{i}", [3, 1, 4, 1, 5], max_new_tokens=block)
               for i in range(slots - 1)]
              + [Request("warmL", [2 * j % 199 + 1
                                   for j in range(long_prompt)],
                         max_new_tokens=block)])
        # two measured repeats on the SAME warmed batcher (no
        # recompiles): the gates use the per-leg MIN p95, which
        # de-noises scheduler hiccups on both sides of every ratio —
        # with 3 TTFT samples per repeat a p95 is effectively a max,
        # and one preempted leg would otherwise fail a sound gate
        streams: dict = {}
        tpots, ttfts = [], []
        for rep in range(2):
            t_tok: dict = {}
            sub_t: dict = {}
            fired: set = set()
            d0 = f"d{rep}.0"

            def on_token(uid, tok, t_tok=t_tok, sub_t=sub_t,
                         fired=fired, d0=d0, rep=rep):
                t_tok.setdefault(uid, []).append(time.perf_counter())
                k = (arrive_at_tok.get(len(t_tok[uid]))
                     if uid == d0 else None)
                if with_prefill and k is not None and k not in fired:
                    fired.add(k)
                    r = Request(f"L{rep}.{k}",
                                [(5 * k + 3 * j) % 199 + 1
                                 for j in range(long_prompt)],
                                max_new_tokens=4)
                    sub_t[r.uid] = time.perf_counter()
                    b.submit(r)

            b.on_token = on_token
            # the decoders: short (sub-chunk) prompts, long streams,
            # and a TPOT SLO so the off leg's admissions run through
            # the ARMED prefill gate — serial+gate, not bare serial
            res = b.run([Request(f"d{rep}.{i}",
                                 [(7 * i + j) % 199 + 1
                                  for j in range(5)],
                                 max_new_tokens=60, tpot_slo_ms=50.0)
                         for i in range(decoders)])
            tpots.append(_p(
                [dt for uid, ts in t_tok.items() if uid.startswith("d")
                 for dt in (t1 - t0 for t0, t1 in zip(ts, ts[1:]))], 95))
            ttft = [t_tok[uid][0] - t for uid, t in sub_t.items()
                    if uid in t_tok]
            ttfts.append(_p(ttft, 95) if ttft else None)
            # the key chain advances one split per admission — the same
            # count in both modes — so repeat r's streams match across
            # legs (and only across the same r); uids carry the repeat
            streams.update({uid: r.tokens for uid, r in res.items()
                            if uid.startswith(("d", "L"))})
        snap = b.obs.registry.snapshot()

        def total(name, field=None):
            fam = snap.get(name)
            if not fam:
                return 0
            vals = fam["values"].values()
            return sum(v[field] for v in vals) if field else sum(vals)

        toks = sum(len(t) for t in streams.values())
        return {
            "streams": streams,
            "tpot_p95_s": min(tpots),
            "ttft_p95_s": (min(t for t in ttfts if t is not None)
                           if any(t is not None for t in ttfts)
                           else None),
            "lane_tokens": total("picotron_prefill_lane_tokens_total"),
            "decode_stalls": total("picotron_decode_stall_seconds",
                                   "count"),
            "dispatches_per_token": round(
                (b.decode_dispatches + b.prefill_dispatches)
                / max(toks, 1), 3),
        }

    floor = one(True, False)
    on = one(True, True)
    off = one(False, True)
    return {
        "tpot_floor_p95_s": floor["tpot_p95_s"],
        "tpot_on_p95_s": on["tpot_p95_s"],
        "tpot_off_p95_s": off["tpot_p95_s"],
        "tpot_vs_floor": round(on["tpot_p95_s"]
                               / max(floor["tpot_p95_s"], 1e-9), 3),
        "ttft_on_p95_s": on["ttft_p95_s"],
        "ttft_off_p95_s": off["ttft_p95_s"],
        "lane_tokens_on": on["lane_tokens"],
        "lane_tokens_off": off["lane_tokens"],
        "decode_stalls_on": on["decode_stalls"],
        "decode_stalls_off": off["decode_stalls"],
        "dispatches_per_token": {"on": on["dispatches_per_token"],
                                 "off": off["dispatches_per_token"]},
        "streams_match": on["streams"] == off["streams"],
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="decode throughput bench")
    ap.add_argument("--block-len", type=int, default=1,
                    help="decode steps fused per dispatch (1 = per-token "
                         "loop; N = blocked fast path, 1/N dispatches per "
                         "token)")
    ap.add_argument("--spec-len", type=int, default=0,
                    help="speculative decoding: draft tokens per verify "
                         "dispatch on repetitive prompts (0 = off; "
                         "mutually exclusive with --block-len > 1)")
    ap.add_argument("--drafter", choices=("ngram", "learned"),
                    default="ngram",
                    help="draft model for --spec-len runs: the model-free "
                         "prompt-lookup drafter (default) or the "
                         "EAGLE-style learned head over the target's own "
                         "last hidden state (shares the target's "
                         "embedding + lm_head; one small jitted draft "
                         "dispatch per round)")
    ap.add_argument("--disagg", action="store_true",
                    help="prefill/decode interference bench (CPU proxy): "
                         "decode-stream TPOT with long shared-prefix "
                         "prefills arriving mid-stream, measured "
                         "baseline (no interference) vs colocated vs a "
                         "disaggregated prefill+decode fleet behind the "
                         "router — the JSON gains tpot_p95_colocated / "
                         "tpot_p95_disagg, handoff_bytes_per_request, "
                         "handoff_latency_s, cluster_prefix_hit_rate")
    ap.add_argument("--fleet", action="store_true",
                    help="elastic fleet controller bench (CPU proxy): a "
                         "3-worker subprocess fleet behind the router "
                         "under tools/fleet.py — SIGKILL-under-load "
                         "replacement and an admission spike that forces "
                         "a grow decision; the JSON gains "
                         "scale_up_latency_s, replace_latency_s, and "
                         "ttft_p95_during_spike vs ttft_p95_steady")
    ap.add_argument("--spec-auto", action="store_true",
                    help="closed-loop controller run: a mixed "
                         "repetitive/random-prompt workload through the "
                         "real batcher with inference.spec_controller "
                         "enabled — the JSON gains spec_len_effective, "
                         "accept_rate_by_drafter, per-regime "
                         "dispatches/token, and controller-decision "
                         "counts (requires --spec-len)")
    ap.add_argument("--attend-impl", choices=("dense", "flash"),
                    default="dense",
                    help="KV-cache attention kernel: the dense "
                         "whole-window einsum (default) or the "
                         "length-aware Pallas flash decode (interpret "
                         "mode off TPU — a parity surface, not a CPU "
                         "perf one)")
    ap.add_argument("--kv-layout", choices=("contiguous", "paged"),
                    default="contiguous",
                    help="KV cache layout: per-slot contiguous strips "
                         "(default) or the paged pool with block-table "
                         "indirection (inference/paged_kv.py) — the JSON "
                         "then adds kv_pages_total/live, pool "
                         "utilization, and prefix_hit_rate")
    ap.add_argument("--kv-page-policy", choices=("uniform", "hot_bf16"),
                    default="uniform",
                    help="per-page storage policy (paged layout only): "
                         "hot_bf16 reads radix-shared prefix pages at "
                         "full precision and exclusively-held tails as "
                         "int8 + scales — kv_bytes_per_token then "
                         "reflects the live-page mix")
    ap.add_argument("--sample-on-device", action="store_true",
                    help="fused sampling epilogue: prefill/decode "
                         "dispatches sample inside the jitted program "
                         "and ship token ids, never [B, vocab] logits — "
                         "logits_bytes_to_host_per_token drops from "
                         "vocab*4 to O(B)")
    ap.add_argument("--weight-dtype", choices=("bf16", "int8"),
                    default="bf16",
                    help="weight storage: bf16 (the model dtype, "
                         "default) or per-channel int8 served through "
                         "the fused dequant matmul — weight_bytes_total "
                         "in the JSON drops to ~half the bf16 bytes")
    ap.add_argument("--tenants", type=int, default=0,
                    help="multi-tenant run: N rank-R LoRA adapters over "
                         "one shared base, mixed with base-only rows in "
                         "the SAME continuous batch (every dispatch "
                         "serves several tenants through the segmented "
                         "adapter matmul) — the JSON gains per-tenant "
                         "tokens/dpt/TTFT (+ accept with --spec-len) and "
                         "adapter_bytes_per_token (composes with "
                         "--weight-dtype int8 and --spec-len)")
    ap.add_argument("--adapter-rank", type=int, default=8,
                    help="LoRA rank for --tenants adapters (default 8)")
    ap.add_argument("--dp", type=int, default=1,
                    help="dp-sharded batching smoke (CPU proxy): run the "
                         "continuous batcher as ONE logical engine whose "
                         "slot axis spans N dp shards, vs the dp=1 "
                         "baseline — the JSON gains dp_size, slots_total, "
                         "per-shard occupancy skew, rebalance_count/"
                         "bytes, and dispatch-latency percentiles at "
                         "both widths; gates bit-identical streams and a "
                         "collective-free decode hot path")
    ap.add_argument("--overlap", choices=("ab",), default=None,
                    help="zero-bubble overlapped-scheduling A/B (CPU "
                         "proxy): the SAME batcher workload with "
                         "inference.overlap off then on, synthetic "
                         "device windows + injected per-token host work "
                         "— the JSON gains dispatch_gap_s percentiles, "
                         "host_work_s, overlap_efficiency, and the "
                         "off/on tokens/s; gates bit-identical streams, "
                         "gap p50 <= 0.5x off, tokens/s >= 1.3x off")
    ap.add_argument("--synthetic-device-s", type=float, default=0.02,
                    help="--overlap ab: pad every round's device window "
                         "to this many seconds via the batcher's "
                         "synthetic-sync knob (models hideable device "
                         "time the tiny CPU model lacks; default 20ms)")
    ap.add_argument("--mixed", choices=("ab",), default=None,
                    help="mixed prefill-decode dispatch A/B (CPU proxy): "
                         "long prompts arriving mid-decode with "
                         "inference.mixed_dispatch off then on, plus a "
                         "decoders-only TPOT floor leg — the JSON gains "
                         "decode TPOT p95 / TTFT p95 / lane-token / "
                         "stall-count comparisons; gates bit-identical "
                         "streams, TPOT p95 under concurrent prefill "
                         "<= 3x the floor, TTFT p95 <= 3x serial")
    args = ap.parse_args(argv)
    if args.mixed:
        # the mixed smoke is its own protocol (three batcher legs,
        # fused lane off vs on vs no-prefill floor; stream-exactness +
        # stall-closure gates, not absolute tokens/s) — CPU proxy
        if args.disagg or args.fleet or args.tenants or args.spec_len \
                or args.dp > 1 or args.overlap:
            ap.error("--mixed is its own protocol; drop the other "
                     "mode flags")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            res = run_mixed()
        except Exception as e:  # noqa: BLE001 - the record IS the channel
            print(json.dumps({
                "metric": "mixed_dispatch_cpu_smoke", "value": None,
                "unit": "s", "vs_baseline": None,
                "code_failure": True,
                "error": f"{type(e).__name__}: {e}"[:800]}))
            raise
        print(f"# mixed bench: tpot_p95 floor={res['tpot_floor_p95_s']} "
              f"on={res['tpot_on_p95_s']} off={res['tpot_off_p95_s']} "
              f"(on/floor {res['tpot_vs_floor']}x) "
              f"ttft_p95 on={res['ttft_on_p95_s']} "
              f"off={res['ttft_off_p95_s']} "
              f"lane_tokens={res['lane_tokens_on']} "
              f"stalls off={res['decode_stalls_off']} "
              f"on={res['decode_stalls_on']} "
              f"streams_match={res['streams_match']}",
              file=sys.stderr)
        record = {"metric": "mixed_dispatch_cpu_smoke",
                  "value": res["tpot_on_p95_s"], "unit": "s",
                  "vs_baseline": None, "validated": False, **res}
        print(json.dumps(record))
        # the gates: the fused lane must change NOTHING about the
        # emitted streams, keep decode within 3x its no-prefill floor
        # while prompts land, actually carry the prompts (lane tokens),
        # and not starve admission relative to the serial path
        if not res["streams_match"]:
            raise SystemExit("mixed gate failed: mixed-on streams "
                             "diverge from mixed-off")
        if not res["lane_tokens_on"]:
            raise SystemExit("mixed gate failed: no prompt tokens moved "
                             "through the lane in the on leg")
        if res["lane_tokens_off"]:
            raise SystemExit("mixed gate failed: the mixed-off leg "
                             "moved tokens through the lane")
        if res["tpot_vs_floor"] > 3.0:
            raise SystemExit(
                f"mixed gate failed: decode TPOT p95 under concurrent "
                f"prefill {res['tpot_on_p95_s']:.6f}s > 3x no-prefill "
                f"floor {res['tpot_floor_p95_s']:.6f}s")
        if res["ttft_on_p95_s"] is None or res["ttft_off_p95_s"] is None:
            raise SystemExit("mixed gate failed: missing TTFT "
                             "percentiles")
        if res["ttft_on_p95_s"] > 3.0 * res["ttft_off_p95_s"]:
            raise SystemExit(
                f"mixed gate failed: TTFT p95 on {res['ttft_on_p95_s']:.6f}s "
                f"> 3x serial {res['ttft_off_p95_s']:.6f}s")
        return
    if args.overlap:
        # the overlap smoke is its own protocol (one batcher workload,
        # pipeline off vs on; stream-exactness + bubble-closure gates,
        # not absolute tokens/s) — CPU proxy by design
        if args.disagg or args.fleet or args.tenants or args.spec_len \
                or args.dp > 1:
            ap.error("--overlap is its own protocol; drop the other "
                     "mode flags")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            res = run_overlap(args.synthetic_device_s)
        except Exception as e:  # noqa: BLE001 - the record IS the channel
            print(json.dumps({
                "metric": "overlap_scheduling_cpu_smoke", "value": None,
                "unit": "tokens/s", "vs_baseline": None,
                "code_failure": True,
                "error": f"{type(e).__name__}: {e}"[:800]}))
            raise
        print(f"# overlap bench: tokens/s off={res['tokens_per_s_off']} "
              f"on={res['tokens_per_s_on']} "
              f"(speedup {res['speedup']}x) "
              f"gap_p50 off={res['dispatch_gap_p50_off']} "
              f"on={res['dispatch_gap_p50_on']} "
              f"overlap_efficiency={res['overlap_efficiency']} "
              f"streams_match={res['streams_match']}",
              file=sys.stderr)
        record = {"metric": "overlap_scheduling_cpu_smoke",
                  "value": res["tokens_per_s_on"], "unit": "tokens/s",
                  "vs_baseline": None, "validated": False, **res}
        print(json.dumps(record))
        # the gates: the pipeline must change NOTHING about the emitted
        # streams, close the issue-to-issue bubble, and convert the
        # closed bubble into throughput in the comparable-host regime
        if not res["streams_match"]:
            raise SystemExit("overlap gate failed: overlap-on streams "
                             "diverge from overlap-off")
        g_off, g_on = (res["dispatch_gap_p50_off"],
                       res["dispatch_gap_p50_on"])
        if g_off is None or g_on is None:
            raise SystemExit("overlap gate failed: missing dispatch-gap "
                             "percentiles")
        if g_on > 0.5 * g_off:
            raise SystemExit(
                f"overlap gate failed: on gap p50 {g_on:.6f}s > 0.5x "
                f"off {g_off:.6f}s")
        if res["speedup"] < 1.3:
            raise SystemExit(
                f"overlap gate failed: speedup {res['speedup']}x < 1.3x "
                f"with host work ~= device time")
        return
    if args.dp > 1:
        # the dp smoke is its own protocol (an A/B of one batcher workload
        # at two mesh widths; stream-exactness gates, not tokens/s) — CPU
        # proxy by design, over the forced multi-device host platform the
        # module-top bootstrap set up before jax loaded
        if args.disagg or args.fleet or args.tenants or args.spec_len:
            ap.error("--dp is its own protocol; drop the other mode flags")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            res = run_dp(args.dp)
        except Exception as e:  # noqa: BLE001 - the record IS the channel
            print(json.dumps({
                "metric": "dp_sharded_batching_cpu_smoke", "value": None,
                "unit": "tokens/s", "vs_baseline": None,
                "code_failure": True,
                "error": f"{type(e).__name__}: {e}"[:800]}))
            raise
        print(f"# dp bench: dp={res['dp_size']} "
              f"slots_total={res['slots_total']} "
              f"occupancy_skew_peak={res['occupancy_skew_peak']} "
              f"rebalances={res['rebalance_count']} "
              f"({res['rebalance_bytes']}B) "
              f"tokens/s dp1={res['tokens_per_s_dp1']} "
              f"dp{args.dp}={res['tokens_per_s_dpN']} "
              f"streams_match={res['streams_match']} "
              f"dp_collectives={res['dp_collectives_traced']}",
              file=sys.stderr)
        record = {"metric": "dp_sharded_batching_cpu_smoke",
                  "value": res["tokens_per_s_dpN"], "unit": "tokens/s",
                  "vs_baseline": None, "validated": False, **res}
        print(json.dumps(record))
        # the gates: the sharded engine must be indistinguishable from
        # the dp=1 one token-for-token, expose the global slot map, keep
        # the hot path free of cross-shard collectives, and have actually
        # exercised the migration planner (the workload forces the skew)
        if not res["streams_match"]:
            raise SystemExit("dp gate failed: dp-sharded streams diverge "
                             "from the dp=1 baseline")
        if res["slots_total"] != args.dp * res["slots_per_shard"]:
            raise SystemExit(
                f"dp gate failed: slots_total {res['slots_total']} != "
                f"dp {args.dp} x slots_per_shard {res['slots_per_shard']}")
        if res["dp_collectives_traced"]:
            raise SystemExit(
                "dp gate failed: dp-axis collectives on the serving path: "
                + "; ".join(res["dp_comm_lines"]))
        if not res["rebalance_count"]:
            raise SystemExit("dp gate failed: the skewed workload never "
                             "triggered a cross-shard slot migration")
        return
    if args.disagg:
        # the disagg bench is its own protocol (subprocess fleet + the
        # router; TPOT percentiles, not tokens/s) — CPU proxy by design
        # until the TPU tunnel returns
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            res = run_disagg()
        except Exception as e:  # noqa: BLE001 - the record IS the channel
            print(json.dumps({
                "metric": "disagg_interference_cpu_smoke", "value": None,
                "unit": "tpot_p95_s", "vs_baseline": None,
                "code_failure": True,
                "error": f"{type(e).__name__}: {e}"[:800]}))
            raise
        base, colo, dis = (res["tpot_p95_baseline"],
                           res["tpot_p95_colocated"],
                           res["tpot_p95_disagg"])
        if None in (base, colo, dis):
            # a phase delivered too few tokens to sample TPOT at all:
            # that is a failed measurement, and the record must say so
            # in the structured channel, not via a raw format TypeError
            print(json.dumps({
                "metric": "disagg_interference_cpu_smoke", "value": None,
                "unit": "tpot_p95_s", "vs_baseline": None,
                "code_failure": True,
                "error": "a phase produced no TPOT samples "
                         f"(p95s: baseline={base} colocated={colo} "
                         f"disagg={dis})", **res}))
            raise SystemExit("disagg bench: empty TPOT sample set")
        print(f"# disagg bench: tpot_p95 baseline={base:.4f}s "
              f"colocated={colo:.4f}s disagg={dis:.4f}s "
              f"handoffs={res['handoffs_served']} "
              f"handoff_bytes/req={res['handoff_bytes_per_request']} "
              f"cluster_prefix_hit_rate={res['cluster_prefix_hit_rate']}",
              file=sys.stderr)
        record = {"metric": "disagg_interference_cpu_smoke",
                  "value": round(dis, 5), "unit": "tpot_p95_s",
                  "vs_baseline": None, "validated": False, **res}
        print(json.dumps(record))
        # the smoke gate (make disagg-smoke): interference must
        # measurably degrade the COLOCATED configuration while the
        # disaggregated decode worker stays near its no-prefill floor.
        # The ordering is the hard gate; the 10%-of-baseline acceptance
        # is recorded (p95s on a shared CPU box carry scheduler noise).
        if not (colo > dis):
            raise SystemExit(
                f"disagg gate failed: colocated p95 {colo:.4f}s is not "
                f"worse than disaggregated {dis:.4f}s")
        return
    if args.fleet:
        # the fleet bench is its own protocol (subprocess fleet + the
        # elastic controller; elasticity latencies, not tokens/s) — CPU
        # proxy by design until the TPU tunnel returns
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            res = run_fleet()
        except Exception as e:  # noqa: BLE001 - the record IS the channel
            print(json.dumps({
                "metric": "fleet_elasticity_cpu_smoke", "value": None,
                "unit": "replace_latency_s", "vs_baseline": None,
                "code_failure": True,
                "error": f"{type(e).__name__}: {e}"[:800]}))
            raise
        print(f"# fleet bench: scale_up={res['scale_up_latency_s']:.2f}s "
              f"replace={res['replace_latency_s']:.2f}s "
              f"ttft_p95 steady={res['ttft_p95_steady']:.4f}s "
              f"spike={res['ttft_p95_during_spike']:.4f}s "
              f"grow_decided={res['grow_decided']} "
              f"shed={res['spike_shed']}", file=sys.stderr)
        record = {"metric": "fleet_elasticity_cpu_smoke",
                  "value": res["replace_latency_s"],
                  "unit": "replace_latency_s", "vs_baseline": None,
                  "validated": False, **res}
        print(json.dumps(record))
        # the gate: capacity loss and load spikes must both be answered
        # (a replacement decision actually restored strength; the spike
        # produced a grow decision and shed nothing)
        if not res["grow_decided"]:
            raise SystemExit("fleet gate failed: spike produced no grow "
                             "decision")
        if res["spike_shed"]:
            raise SystemExit(f"fleet gate failed: spike shed "
                             f"{res['spike_shed']} request(s)")
        return
    if args.spec_len > 0 and args.block_len != 1:
        ap.error("--spec-len replaces blocked decode; drop --block-len")
    if args.spec_auto and args.spec_len < 1:
        ap.error("--spec-auto tunes speculation per slot; give it a "
                 "ceiling with --spec-len N")
    if args.kv_page_policy != "uniform" and args.kv_layout != "paged":
        ap.error("--kv-page-policy hot_bf16 requires --kv-layout paged "
                 "(per-page refcounts decide which pages read as int8)")
    if args.tenants:
        if args.tenants < 1 or args.adapter_rank < 1:
            ap.error("--tenants and --adapter-rank must be >= 1")
        if args.block_len != 1:
            ap.error("--tenants drives the continuous batcher; drop "
                     "--block-len")
        if args.spec_auto:
            ap.error("--tenants and --spec-auto are separate protocols")

    # Preflight BEFORE any backend touch: a dead TPU tunnel hangs backend
    # init forever, and the probe child is the only safe way to find out.
    # On failure the bench degrades to the CPU-proxy path and still
    # publishes its kv_bytes_per_token/attend_impl record — tagged
    # "validated": false so the orchestrator never mistakes proxy numbers
    # for hardware numbers (BENCH_r03-r05 published nothing at all).
    tpu, preflight_note = tpu_preflight()
    if not tpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        print(f"# preflight: {preflight_note}; running the CPU-proxy path",
              file=sys.stderr)

    from picotron_tpu.utils import honor_cpu_env_pin

    honor_cpu_env_pin()

    from picotron_tpu.config import SMOLLM_1_7B, Config
    if tpu:
        model = dict(SMOLLM_1_7B)
        sizes = dict(slots=8, max_seq_len=1024, prompt_len=128, steps=256)
    else:  # CPU smoke path so the bench always prints a line
        model = dict(
            name="tiny", num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=8, hidden_size=256, intermediate_size=1024,
            vocab_size=4096, max_position_embeddings=2048, dtype="float32",
            attention_impl="sdpa")
        sizes = dict(slots=4, max_seq_len=128, prompt_len=16, steps=32)
    if args.spec_len > 0:
        # longer streams give greedy generation room to fall into the
        # repetitive attractors prompt-lookup drafting feeds on — the
        # regime this mode exists to measure (capped so prefill + warmup
        # rounds + the timed window fit the cache)
        sizes["steps"] = min(
            3 * sizes["steps"],
            sizes["max_seq_len"] - sizes["prompt_len"] - 1
            - SPEC_WARMUP_ROUNDS * (args.spec_len + 1))
        if sizes["steps"] < 1:
            ap.error(
                f"--spec-len {args.spec_len} leaves no timed decode window "
                f"inside max_seq_len {sizes['max_seq_len']} (prompt + "
                f"warmup rounds consume it); use a smaller draft length")
    cfg = Config.from_dict({
        "distributed": {"tp_size": 1},
        "model": model,
        "training": {"seq_length": sizes["max_seq_len"]},
        "dataset": {"name": "synthetic"},
    })
    accept = None
    auto = None
    tenancy = None
    try:
        if args.tenants:
            (tok_s, dpt, accept, kv_bytes, weight_bytes, engine,
             tenancy) = run_tenants(
                cfg, tenants=args.tenants,
                adapter_rank=args.adapter_rank,
                spec_len=args.spec_len, drafter=args.drafter,
                attend_impl=args.attend_impl,
                kv_layout=args.kv_layout,
                kv_page_policy=args.kv_page_policy,
                sample_on_device=args.sample_on_device,
                weight_dtype=args.weight_dtype, **sizes)
        elif args.spec_auto:
            (tok_s, dpt, accept, kv_bytes, weight_bytes, engine,
             auto) = run_spec_auto(
                cfg, spec_len=args.spec_len, drafter=args.drafter,
                attend_impl=args.attend_impl,
                kv_layout=args.kv_layout,
                kv_page_policy=args.kv_page_policy,
                sample_on_device=args.sample_on_device,
                weight_dtype=args.weight_dtype, **sizes)
        elif args.spec_len > 0:
            tok_s, dpt, accept, kv_bytes, weight_bytes, engine = run_spec(
                cfg, spec_len=args.spec_len, drafter=args.drafter,
                attend_impl=args.attend_impl,
                kv_layout=args.kv_layout,
                kv_page_policy=args.kv_page_policy,
                sample_on_device=args.sample_on_device,
                weight_dtype=args.weight_dtype, **sizes)
        else:
            tok_s, dpt, kv_bytes, weight_bytes, engine = run(
                cfg, block_len=args.block_len,
                attend_impl=args.attend_impl,
                kv_layout=args.kv_layout,
                kv_page_policy=args.kv_page_policy,
                sample_on_device=args.sample_on_device,
                weight_dtype=args.weight_dtype, **sizes)
    except Exception as e:  # noqa: BLE001 - the record IS the error channel
        print(json.dumps({
            "metric": BENCH_METRICS["bench_decode"], "value": None,
            "unit": "tokens/s/chip", "vs_baseline": None,
            "code_failure": True, "error": f"{type(e).__name__}: {e}"[:800]}))
        raise
    chips = engine.topo.world_size
    metric = (BENCH_METRICS["bench_decode"] if tpu
              else "decode_tokens_per_sec_cpu_smoke")
    print(f"# slots={sizes['slots']} prompt={sizes['prompt_len']} "
          f"steps={sizes['steps']} chips={chips} block_len={args.block_len} "
          f"spec_len={args.spec_len} attend_impl={args.attend_impl} "
          f"kv_layout={args.kv_layout} "
          f"kv_page_policy={args.kv_page_policy} "
          f"sample_on_device={args.sample_on_device} "
          + (f"accept_rate={accept:.3f} " if accept is not None else "")
          + f"dispatches/token={dpt:.3f} kv_bytes/token={kv_bytes} "
          f"weight_dtype={args.weight_dtype} weight_bytes={weight_bytes} "
          f"tokens/s={tok_s:.1f}",
          file=sys.stderr)
    logit_bytes = logits_bytes_to_host_per_token(
        engine, cfg.model.vocab_size, args.block_len, args.spec_len)
    record = {"metric": metric, "value": round(tok_s / chips, 1),
              "unit": "tokens/s/chip", "vs_baseline": None,
              "block_len": args.block_len,
              "dispatches_per_token": round(dpt, 4),
              "attend_impl": args.attend_impl,
              "kv_layout": args.kv_layout,
              "kv_page_policy": args.kv_page_policy,
              "sample_on_device": args.sample_on_device,
              "kv_bytes_per_token": kv_bytes,
              # the weight-side bytes story: the whole tree (int8 values
              # + scales included) and what one generated token costs in
              # weight HBM reads — every decode step streams all weights
              # once and emits one token per active slot, so per-token =
              # total / slots; speculative rounds amortize by emitting
              # ~1/dpt tokens per weight walk
              "weight_dtype": args.weight_dtype,
              "weight_bytes_total": weight_bytes,
              "weight_bytes_per_token": int(round(
                  weight_bytes * (dpt if args.spec_len > 0 else 1.0)
                  / sizes["slots"])),
              "logits_bytes_to_host_per_token": logit_bytes,
              # the per-rung A/B referee: dispatch-latency percentiles
              # from the PR 10 histograms, so flipping ONE flag (pipeline,
              # epilogue, policy) and diffing two JSON lines is the whole
              # measurement protocol once the TPU tunnel returns. This is
              # the CANONICAL latency field — a projection of the same
              # registry instruments the "obs" snapshot below serializes,
              # so the two can never disagree at emit time.
              "dispatch_latency_s": dispatch_latency_summary(engine),
              # hardware-validated numbers vs CPU-proxy fallback: the
              # kv_bytes/attend_impl deltas are layout facts and hold
              # either way; tokens/s only means hardware when validated
              "validated": tpu}
    reg = engine.obs.registry
    if engine.paged is not None:
        # capacity story next to the bytes story: pool occupancy at the
        # end of the timed window + prefix-cache effectiveness (the bench
        # drives the engine directly, so hit rate is nonzero only for
        # workloads routed through the batcher's shared-prefix admission)
        p = engine.paged.stats()
        record.update(
            kv_page_len=p["kv_page_len"],
            kv_pages_total=p["kv_pages_total"],
            kv_pages_live=p["kv_pages_live"],
            kv_pages_quant=p["kv_pages_quant"],
            kv_pool_utilization=p["kv_pool_utilization"],
            prefix_hit_rate=p["prefix_hit_rate"])
        # ...and into the registry, so the obs snapshot below is complete
        reg.gauge("picotron_kv_pool_utilization").set(
            p["kv_pool_utilization"])
        reg.gauge("picotron_prefix_hit_rate").set(
            p["prefix_hit_rate"] or 0.0)
    if not tpu:
        record["preflight"] = preflight_note
    if args.spec_len > 0:
        record["spec_len"] = args.spec_len
        record["drafter"] = args.drafter
        record["accept_rate"] = round(accept, 4)
        reg.gauge("picotron_accept_rate").set(accept)
    if auto is not None:
        # the controller story: converged per-slot draft lengths,
        # per-drafter accept split, per-regime dispatches/token, and
        # what the policy loop actually decided
        record["spec_auto"] = True
        record.update(auto)
    if tenancy is not None:
        # the multi-tenant story: per-tenant tokens/dpt/TTFT (+ accept
        # when speculating) and what streaming all live adapters costs
        # per decoded token next to the base weight bytes
        record.update(tenancy)
    # the engine registry's compact snapshot (dispatch count/latency
    # histograms, pool/accept gauges) rides along — one structured blob
    # instead of growing the hand-picked field list forever
    record["obs"] = reg.summary()
    print(json.dumps(record))


if __name__ == "__main__":
    main()
