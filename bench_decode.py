"""Decode throughput benchmark: continuous-batched KV-cache generation.

The serving-side complement of bench.py's training MFU: with every engine
slot busy, how many tokens/sec does the decode hot path sustain?
Protocol: prefill fills all slots with fixed-length random prompts, a
warmup call absorbs compilation, then the timed window runs end-to-end
(including the host round-trip that feeds sampled tokens back — that
latency is part of serving).

Two modes, selected by ``--block-len``:

- ``--block-len 1`` (default): the classic per-token loop — one
  ``decode_step`` dispatch, one host sync, per generated token
  (dispatches/token = 1.0);
- ``--block-len N``: the blocked fast path — ``decode_block`` runs N
  autoregressive steps inside one jitted program with on-device stop
  state, so the host syncs once per N tokens (dispatches/token = 1/N).
  The tokens/s delta between the two modes IS the host-dispatch overhead
  the block amortizes.

Prints ONE JSON line starting ``{"metric"`` (the bench_record contract, so
the tunnel watcher / orchestrator can find and classify it in step logs):
tokens/s/chip on SmolLM-1.7B on TPU, a tiny-model smoke metric on CPU,
with ``dispatches_per_token`` riding along so the host-sync win is visible
in the bench trajectory.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from picotron_tpu.bench_record import BENCH_METRICS


def run(cfg, *, slots: int, max_seq_len: int, prompt_len: int,
        steps: int, warmup: int = 8, block_len: int = 1):
    """Time ``steps`` decode rounds (tokens per slot). Returns
    (tokens/s, dispatches_per_token, engine)."""
    import jax
    import numpy as np

    from picotron_tpu.inference import InferenceEngine
    from picotron_tpu.models import llama

    engine = InferenceEngine(cfg, slots=slots, max_seq_len=max_seq_len,
                             decode_block_len=block_len)
    params = engine.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    cache = engine.init_cache()
    rng = np.random.default_rng(0)
    for s in range(slots):
        prompt = rng.integers(1, cfg.model.vocab_size, prompt_len)
        kv, _ = engine.prefill(params, prompt)
        cache = engine.insert(cache, kv, s, prompt_len)

    toks = np.ones(slots, np.int32)
    temp = np.zeros(slots, np.float32)  # greedy: no sampling noise in the timing
    top_k = np.zeros(slots, np.int32)
    top_p = np.ones(slots, np.float32)
    key = jax.random.PRNGKey(0)

    assert steps % block_len == 0, "steps must divide into whole blocks"
    assert prompt_len + warmup * block_len + steps <= max_seq_len, \
        "cache would overflow"

    if block_len == 1:
        for _ in range(warmup):
            key, sub = jax.random.split(key)
            cache, toks, _ = engine.decode_step(params, cache, toks, sub,
                                                temp, top_k, top_p)
        jax.block_until_ready(toks)
        t0 = time.perf_counter()
        for _ in range(steps):
            key, sub = jax.random.split(key)
            cache, toks, _ = engine.decode_step(params, cache, toks, sub,
                                                temp, top_k, top_p)
            toks = np.asarray(toks)  # the host feedback every real server pays
        dt = time.perf_counter() - t0
        dispatches = steps
        last = toks
    else:
        eos = np.full(slots, -1, np.int32)  # bench streams never stop early

        def block(cache, toks, key):
            subs = []
            for _ in range(block_len):
                key, sub = jax.random.split(key)
                subs.append(np.asarray(sub))
            budget = np.full(slots, block_len, np.int32)
            cache, out, counts = engine.decode_block(
                params, cache, toks, np.stack(subs), eos, budget,
                temp, top_k, top_p)
            out = np.asarray(out)  # one host sync per block, not per token
            assert np.all(np.asarray(counts) == block_len)
            return cache, out[:, -1], key

        for _ in range(warmup):
            cache, toks, key = block(cache, toks, key)
        t0 = time.perf_counter()
        for _ in range(steps // block_len):
            cache, toks, key = block(cache, toks, key)
        dt = time.perf_counter() - t0
        dispatches = steps // block_len
        last = toks

    assert np.all((last >= 0) & (last < cfg.model.vocab_size))
    return slots * steps / dt, dispatches / steps, engine


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="decode throughput bench")
    ap.add_argument("--block-len", type=int, default=1,
                    help="decode steps fused per dispatch (1 = per-token "
                         "loop; N = blocked fast path, 1/N dispatches per "
                         "token)")
    args = ap.parse_args(argv)

    from picotron_tpu.utils import honor_cpu_env_pin

    honor_cpu_env_pin()

    from picotron_tpu.config import SMOLLM_1_7B, Config
    from picotron_tpu.utils import on_tpu

    tpu = on_tpu()
    if tpu:
        model = dict(SMOLLM_1_7B)
        sizes = dict(slots=8, max_seq_len=1024, prompt_len=128, steps=256)
    else:  # CPU smoke path so the bench always prints a line
        model = dict(
            name="tiny", num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=8, hidden_size=256, intermediate_size=1024,
            vocab_size=4096, max_position_embeddings=2048, dtype="float32",
            attention_impl="sdpa")
        sizes = dict(slots=4, max_seq_len=128, prompt_len=16, steps=32)
    cfg = Config.from_dict({
        "distributed": {"tp_size": 1},
        "model": model,
        "training": {"seq_length": sizes["max_seq_len"]},
        "dataset": {"name": "synthetic"},
    })
    try:
        tok_s, dpt, engine = run(cfg, block_len=args.block_len, **sizes)
    except Exception as e:  # noqa: BLE001 - the record IS the error channel
        print(json.dumps({
            "metric": BENCH_METRICS["bench_decode"], "value": None,
            "unit": "tokens/s/chip", "vs_baseline": None,
            "code_failure": True, "error": f"{type(e).__name__}: {e}"[:800]}))
        raise
    chips = engine.topo.world_size
    metric = (BENCH_METRICS["bench_decode"] if tpu
              else "decode_tokens_per_sec_cpu_smoke")
    print(f"# slots={sizes['slots']} prompt={sizes['prompt_len']} "
          f"steps={sizes['steps']} chips={chips} block_len={args.block_len} "
          f"dispatches/token={dpt:.3f} tokens/s={tok_s:.1f}", file=sys.stderr)
    print(json.dumps({"metric": metric, "value": round(tok_s / chips, 1),
                      "unit": "tokens/s/chip", "vs_baseline": None,
                      "block_len": args.block_len,
                      "dispatches_per_token": round(dpt, 4)}))


if __name__ == "__main__":
    main()
