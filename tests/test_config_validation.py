"""Config validation for the round-3 feature flags (the reference surfaces
topology constraints as asserts, train.py:85-86; here they are real errors)."""

import pytest

from conftest import make_config


def test_ulysses_rejects_zigzag(tiny_model_kwargs):
    with pytest.raises(ValueError, match="incompatible with cp_zigzag"):
        make_config(tiny_model_kwargs, cp=2, seq=64, cp_impl="ulysses",
                    zigzag=True)


def test_ulysses_rejects_indivisible_heads(tiny_model_kwargs):
    # 8 heads / tp 2 = 4 local heads, cp 8 does not divide them
    kw = dict(tiny_model_kwargs)
    with pytest.raises(ValueError, match="divisible"):
        make_config(kw, tp=2, cp=8, seq=64, cp_impl="ulysses")


def test_unknown_cp_impl_rejected(tiny_model_kwargs):
    with pytest.raises(ValueError, match="cp_impl"):
        make_config(tiny_model_kwargs, cp=2, seq=64, cp_impl="rong")


def test_sp_needs_divisible_local_seq(tiny_model_kwargs):
    # cp-local sequence = 12/2 = 6, not divisible by tp 4
    with pytest.raises(ValueError, match="tp_sequence_parallel"):
        make_config(tiny_model_kwargs, tp=4, cp=2, seq=12, sp=True)


def test_interleave_requires_pp(tiny_model_kwargs):
    """pp_interleave > 1 with pp_size == 1 must be a clean config error, not
    a bare assert deep in init_params' layout path (round-3 ADVICE)."""
    with pytest.raises(ValueError, match="pp_interleave > 1 requires pp_size"):
        make_config(tiny_model_kwargs, pp=1, interleave=2)


def test_decay_steps_must_exceed_warmup(tiny_model_kwargs):
    with pytest.raises(ValueError, match="lr_decay_steps"):
        make_config(tiny_model_kwargs, lr_schedule="cosine",
                    lr_warmup_steps=100, lr_decay_steps=100)


def test_decay_steps_ok_for_constant_schedule(tiny_model_kwargs):
    # constant schedule never decays; a small lr_decay_steps is inert
    make_config(tiny_model_kwargs, lr_schedule="constant",
                lr_warmup_steps=100, lr_decay_steps=50)


def test_cond_gating_on_cpu_requires_tp1(tiny_model_kwargs):
    # gated tp collectives can abort the XLA CPU rendezvous: reject at
    # load instead of failing intermittently mid-run
    with pytest.raises(ValueError, match="stage_gating"):
        make_config(tiny_model_kwargs, pp=2, acc=2, tp=2,
                    stage_gating="cond")
    make_config(tiny_model_kwargs, pp=2, acc=2, stage_gating="cond")
    with pytest.raises(ValueError, match="stage_gating"):
        make_config(tiny_model_kwargs, stage_gating="bogus")
