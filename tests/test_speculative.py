"""Speculative decoding (ISSUE-4): draft-verify pipeline on top of blocked
decode.

Acceptance surface:
- greedy speculative decode (prompt-lookup drafter, any draft quality) is
  BIT-IDENTICAL to the spec-off batcher streams on tp=1 and a tp=2 dryrun
  mesh, EOS mid-verify included;
- a drafter that guesses right turns dispatches-per-token into
  1/(spec_len+1): a scripted oracle drafter pins the dispatch count and a
  100% accept rate;
- the acceptance rule is distribution-preserving: greedy rows take the
  exact-match fast path (unit-pinned emitted prefixes), stochastic rows
  rejection-sample with residual resampling — a seeded statistical test
  pins the emitted-token frequencies against the non-speculative
  sampler's filtered softmax, at the pure-function level AND through the
  real verify dispatch;
- rollback is the length pointer: a rejected draft's optimistically
  written K/V rows leave ``attend`` output bit-identical to never having
  written them (bf16 and int8 caches);
- the n-gram drafter proposes cycle continuations from the slot's own
  history (longest suffix first) and always returns exactly n tokens.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.config import Config
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    NgramDrafter,
    Request,
    kv_cache,
    sampling,
)
from picotron_tpu.inference.speculative import Drafter
from picotron_tpu.models import llama

MAX_LEN = 96


def _engine(tiny_model_kwargs, tp=1, slots=2, **kw):
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    return cfg, InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN, **kw)


def _params(cfg, engine, seed=0):
    p = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(seed))
    return engine.shard_params(p)


class ScriptedDrafter(Drafter):
    """Oracle drafter for tests: proposes the known future of one scripted
    sequence (prompt + expected tokens) by matching the history length."""

    def __init__(self, script):
        self.script = list(script)

    def propose(self, history, n):
        start = len(np.asarray(history).reshape(-1))
        out = np.zeros(n, np.int32)
        tail = self.script[start: start + n]
        out[: len(tail)] = tail
        return out


# --------------------------------------------------------------------------- #
# greedy speculation == spec-off, bit for bit
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("tp,spec_len", [(1, 2), (1, 4), (2, 3)])
def test_greedy_spec_matches_spec_off(tiny_model_kwargs, tp, spec_len):
    """Mixed-length greedy requests through the speculative batcher (the
    real NgramDrafter — accepts and rejections both occur) must produce
    the spec-off engine's streams token for token."""
    cfg, eng_off = _engine(tiny_model_kwargs, tp=tp)
    _, eng_on = _engine(tiny_model_kwargs, tp=tp, spec_len=spec_len)
    params = _params(cfg, eng_off)
    reqs = [Request("a", [1, 2, 3, 1, 2, 3, 1, 2], max_new_tokens=17),
            Request("b", [9, 8, 7], max_new_tokens=6)]
    want = ContinuousBatcher(eng_off, params).run(reqs)
    got = ContinuousBatcher(eng_on, params).run(reqs)
    for r in reqs:
        assert got[r.uid].tokens == want[r.uid].tokens, (r.uid, tp, spec_len)
        assert got[r.uid].finish_reason == "length"


def test_greedy_spec_eos_mid_verify(tiny_model_kwargs):
    """A stream whose EOS lands mid-verify (inside an accepted draft run
    or at the fresh token) must end AT the EOS — identical to spec-off —
    and the queued request behind it still completes."""
    cfg, eng_off = _engine(tiny_model_kwargs, slots=1)
    _, eng_on = _engine(tiny_model_kwargs, slots=1, spec_len=4)
    params = _params(cfg, eng_off)
    prompt = [5, 6, 7, 8]
    free = ContinuousBatcher(eng_off, params).run(
        [Request("f", prompt, max_new_tokens=12)])["f"]
    eos = free.tokens[5]
    assert eos not in free.tokens[:5], "pick a different seed/prompt"
    res = ContinuousBatcher(eng_on, params).run([
        Request("x", prompt, max_new_tokens=12, eos_id=eos),
        Request("y", [3, 1, 4], max_new_tokens=5),
    ])
    assert res["x"].finish_reason == "eos"
    assert res["x"].tokens == free.tokens[:6]
    assert res["y"].finish_reason == "length"
    assert len(res["y"].tokens) == 5


def test_scripted_drafter_dispatch_savings(tiny_model_kwargs):
    """An oracle drafter (knows the greedy future) must drive acceptance
    to 100% and the decode dispatch count to ceil((n-1)/(spec_len+1)) —
    the one-pass-per-accepted-run win speculation exists for."""
    cfg, eng_off = _engine(tiny_model_kwargs)
    _, eng_on = _engine(tiny_model_kwargs, spec_len=3)
    params = _params(cfg, eng_off)
    prompt = [1, 2, 3, 4, 5]
    n_new = 13
    want = ContinuousBatcher(eng_off, params).run(
        [Request("r", prompt, max_new_tokens=n_new)])["r"].tokens
    drafter = ScriptedDrafter(prompt + want)
    b = ContinuousBatcher(eng_on, params, drafter=drafter)
    got = b.run([Request("r", prompt, max_new_tokens=n_new)])["r"].tokens
    assert got == want
    assert b.accept_rate == 1.0
    # token 1 comes from the prefill sample; each verify emits spec_len+1
    assert b.decode_dispatches == math.ceil((n_new - 1) / 4)
    assert b.decode_dispatches < n_new - 1  # strictly beats per-token


def test_spec_respects_budget_and_window(tiny_model_kwargs):
    """Budgets that are not multiples of spec_len+1 (and a prompt close to
    the window) stop at exactly max_new_tokens — the device budget clip on
    the variable-length emit."""
    cfg, eng = _engine(tiny_model_kwargs, slots=2, spec_len=4)
    params = _params(cfg, eng)
    reqs = [Request("a", [1, 2, 3], max_new_tokens=7),
            Request("b", list(range(1, 90)), max_new_tokens=64)]
    res = ContinuousBatcher(eng, params).run(reqs)
    assert len(res["a"].tokens) == 7 and res["a"].finish_reason == "length"
    # 89 prompt tokens under MAX_LEN 96 leave exactly 7
    assert len(res["b"].tokens) == 7 and res["b"].finish_reason == "length"


# --------------------------------------------------------------------------- #
# acceptance rule: greedy fast path + distribution preservation
# --------------------------------------------------------------------------- #


def _logits_for_chain(chain, V, boost=8.0):
    """[S, V] logits whose argmax at position i is chain[i], with enough
    margin that the argmax is unambiguous."""
    rng = np.random.default_rng(0)
    out = rng.normal(size=(len(chain), V)).astype(np.float32)
    out[np.arange(len(chain)), chain] += boost
    return out


def test_accept_greedy_prefix():
    """Greedy rows accept exactly the matching draft prefix and emit the
    argmax correction (or the bonus token when everything matched)."""
    V = 11
    chain = [3, 7, 1, 4, 9]  # argmax at the 5 verify positions
    logits = jnp.asarray(_logits_for_chain(chain, V)[None])  # [1, 5, V]
    zero, one = jnp.zeros(1), jnp.ones(1)
    for n_match in range(5):
        draft = list(chain[:4])
        if n_match < 4:
            draft[n_match] = (draft[n_match] + 1) % V  # first mismatch
        emitted, counts = sampling.speculative_accept(
            logits, jnp.asarray([draft], jnp.int32), jax.random.PRNGKey(0),
            zero, jnp.zeros(1, jnp.int32), one)
        want = chain[: n_match + 1]  # accepted prefix == greedy chain
        assert int(counts[0]) == n_match + 1
        assert list(np.asarray(emitted)[0, : n_match + 1]) == want
        assert np.all(np.asarray(emitted)[0, n_match + 1:] == 0)


def test_accept_distribution_matches_sampler():
    """Seeded statistical test of the rejection/residual rule: over many
    keys, the FIRST emitted token's frequencies must converge to the
    non-speculative sampler's distribution (filtered softmax) — whether
    the draft token is likely or unlikely — and the draft must accept at
    ~its target probability. Also exercised with top-k filtering."""
    rng = np.random.default_rng(2)
    V = 8
    logits = jnp.asarray(rng.normal(size=(1, 2, V)).astype(np.float32))
    n = 4000
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    one = jnp.ones(1)

    probs0 = np.asarray(jax.nn.softmax(logits[0, 0]))
    for draft_tok in (int(np.argmax(probs0)), int(np.argmin(probs0))):
        for top_k in (0, 3):
            draft = jnp.asarray([[draft_tok]], jnp.int32)
            ks = jnp.full(1, top_k, jnp.int32)

            def first_tok(key):
                emitted, _ = sampling.speculative_accept(
                    logits, draft, key, one, ks, one)
                return emitted[0, 0]

            toks = np.asarray(jax.vmap(first_tok)(keys))
            freq = np.bincount(toks, minlength=V) / n
            want = np.asarray(sampling.filtered_probs(
                logits[0, :1], one, ks, one))[0]
            np.testing.assert_allclose(freq, want, atol=0.04,
                                       err_msg=f"d={draft_tok} k={top_k}")
            # acceptance fires at the draft token's target probability
            def count(key):
                _, c = sampling.speculative_accept(
                    logits, draft, key, one, ks, one)
                return c[0]

            acc = np.mean(np.asarray(jax.vmap(count)(keys)) == 2)
            np.testing.assert_allclose(acc, want[draft_tok], atol=0.04)


def test_accept_second_position_distribution():
    """Given an accepted draft, the NEXT emitted token draws from the
    bonus position's own filtered softmax — the chain rule that makes the
    whole emitted run distributionally exact."""
    rng = np.random.default_rng(3)
    V = 8
    logits_np = rng.normal(size=(1, 2, V)).astype(np.float32)
    probs0 = np.asarray(jax.nn.softmax(jnp.asarray(logits_np[0, 0])))
    draft_tok = int(np.argmax(probs0))  # likely -> plenty of accepts
    logits = jnp.asarray(logits_np)
    draft = jnp.asarray([[draft_tok]], jnp.int32)
    one, zk = jnp.ones(1), jnp.zeros(1, jnp.int32)
    n = 4000
    keys = jax.random.split(jax.random.PRNGKey(1), n)

    def run(key):
        emitted, counts = sampling.speculative_accept(
            logits, draft, key, one, zk, one)
        return emitted[0, 1], counts[0]

    second, counts = jax.vmap(run)(keys)
    second, counts = np.asarray(second), np.asarray(counts)
    sel = counts == 2  # draft accepted: position 1 is the bonus draw
    assert sel.mean() > 0.25
    freq = np.bincount(second[sel], minlength=V) / sel.sum()
    want = np.asarray(jax.nn.softmax(logits[0, 1]))
    np.testing.assert_allclose(freq, want, atol=0.05)


def test_spec_sampled_e2e_distribution(tiny_model_kwargs):
    """The real verify dispatch preserves the sampler's distribution:
    park a prompt, feed a fixed last token + drafts, and over many keys
    the first emitted token's frequencies must match the filtered softmax
    of the full-forward oracle logits at that position (top-k 4
    concentrates the support so a few hundred draws resolve it)."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from picotron_tpu.utils import shard_map as shard_map_compat

    cfg, engine = _engine(tiny_model_kwargs, slots=1, spec_len=2)
    params = _params(cfg, engine)
    prompt = [7, 3, 5, 2, 7, 3]
    t0, top_k, temp = 9, 4, 1.0

    fwd = jax.jit(shard_map_compat(
        lambda p, t: llama.forward_logits(p, t, cfg), engine.topo.mesh,
        in_specs=(llama.param_pspecs(cfg.model), P()), out_specs=P()))
    oracle = np.asarray(fwd(params, jnp.asarray(
        np.asarray(prompt + [t0], np.int32)[None])))[0, -1]
    want = np.asarray(sampling.filtered_probs(
        jnp.asarray(oracle[None]), jnp.full(1, temp),
        jnp.full(1, top_k, jnp.int32), jnp.ones(1)))[0]
    draft_tok = int(np.argmax(want))  # exercises accept AND reject paths

    kv, _ = engine.prefill(params, prompt)
    cache0 = engine.insert(engine.init_cache(), kv, 0, len(prompt))
    cache0 = jax.tree.map(np.asarray, cache0)  # host copy: verify donates
    tokens = np.asarray([[t0, draft_tok, draft_tok]], np.int32)
    args = (np.full(1, -1, np.int32), np.full(1, 50, np.int32),
            np.full(1, temp, np.float32), np.full(1, top_k, np.int32),
            np.ones(1, np.float32))
    n = 400
    first = np.zeros(n, np.int32)
    for i in range(n):
        cache = jax.tree.map(jnp.asarray, cache0)
        _, emitted, counts, _ = engine.verify(
            params, cache, tokens, jax.random.PRNGKey(i), *args)
        assert int(np.asarray(counts)[0]) >= 1
        first[i] = np.asarray(emitted)[0, 0]
    freq = np.bincount(first, minlength=cfg.model.vocab_size) / n
    kept = np.flatnonzero(want)
    assert set(np.flatnonzero(freq)) <= set(kept)
    np.testing.assert_allclose(freq[kept], want[kept], atol=0.09)


# --------------------------------------------------------------------------- #
# rollback: the length pointer IS the rewind
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("quantized", [False, True])
def test_rejected_draft_rows_invisible_to_attend(quantized):
    """Optimistically written draft rows beyond the post-acceptance length
    must leave ``attend`` output BIT-IDENTICAL to never having written
    them — for bf16 and int8 (scales included) caches. This is the whole
    rollback mechanism: rewinding is one length-pointer write."""
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 16, 4, 8
    dt = jnp.bfloat16

    def block():
        base = {
            "k": jnp.asarray(rng.normal(size=(B, T, H, D)), dt),
            "v": jnp.asarray(rng.normal(size=(B, T, H, D)), dt),
        }
        if quantized:
            qk, ks = kv_cache.quantize_kv(base["k"])
            qv, vs = kv_cache.quantize_kv(base["v"])
            base = {"k": qk, "v": qv, "k_scale": ks, "v_scale": vs}
        return base

    base = block()
    pos = jnp.asarray([6, 3], jnp.int32)  # per-slot write offsets
    S = 4  # 1 fed token + 3 drafts
    k_new = jnp.asarray(rng.normal(size=(B, S, H, D)), dt)
    v_new = jnp.asarray(rng.normal(size=(B, S, H, D)), dt)
    # speculative write: all S rows land; suppose 0 drafts accepted, so the
    # post-acceptance lengths advance past the fed token only
    spec = kv_cache.cache_write(base, k_new, v_new, pos)
    clean = kv_cache.cache_write(base, k_new[:, :1], v_new[:, :1], pos)
    lengths = pos + 1

    q = jnp.asarray(rng.normal(size=(B, 1, H, D)), dt)
    out_spec = kv_cache.attend(q, spec, lengths, 0.3)
    out_clean = kv_cache.attend(q, clean, lengths, 0.3)
    np.testing.assert_array_equal(np.asarray(out_spec, np.float32),
                                  np.asarray(out_clean, np.float32))
    # and the next decode step's write simply overwrites a stale row
    k2 = jnp.asarray(rng.normal(size=(B, 1, H, D)), dt)
    v2 = jnp.asarray(rng.normal(size=(B, 1, H, D)), dt)
    again_spec = kv_cache.cache_write(spec, k2, v2, lengths)
    again_clean = kv_cache.cache_write(clean, k2, v2, lengths)
    out2s = kv_cache.attend(q, again_spec, lengths + 1, 0.3)
    out2c = kv_cache.attend(q, again_clean, lengths + 1, 0.3)
    np.testing.assert_array_equal(np.asarray(out2s, np.float32),
                                  np.asarray(out2c, np.float32))


def test_batched_write_drops_out_of_window_rows():
    """A speculative write window crossing the cache edge drops the
    out-of-range rows instead of clamping them onto earlier positions
    (the chunked-prefill bug class, pinned for the batched write)."""
    B, T, H, D = 2, 8, 2, 4
    base = {"k": jnp.zeros((B, T, H, D)), "v": jnp.zeros((B, T, H, D))}
    k_new = jnp.ones((B, 3, H, D))
    out = kv_cache.cache_write(base, k_new, k_new,
                               jnp.asarray([6, 2], jnp.int32))
    got = np.asarray(out["k"][:, :, 0, 0])
    want = np.zeros((B, T))
    want[0, 6:8] = 1  # row at pos 8 dropped
    want[1, 2:5] = 1
    np.testing.assert_array_equal(got, want)


# --------------------------------------------------------------------------- #
# the n-gram drafter
# --------------------------------------------------------------------------- #


def test_ngram_drafter_cycle_continuation():
    d = NgramDrafter(3)
    hist = [1, 2, 3, 1, 2, 3, 1, 2]
    # suffix [3, 1, 2] matched at position 2 -> continuation cycles 3,1,2
    np.testing.assert_array_equal(d.propose(np.asarray(hist), 4),
                                  [3, 1, 2, 3])
    # proposals always have exactly n tokens
    assert d.propose(np.asarray(hist), 7).shape == (7,)


def test_ngram_drafter_longest_suffix_wins():
    # 1-gram match for 9 exists at position 0 (-> 5), but the 2-gram
    # suffix [2, 9] matches at 2 (-> 7): the longer context must win
    d = NgramDrafter(3)
    hist = [9, 5, 2, 9, 7, 2, 9]
    assert d.propose(np.asarray(hist), 1)[0] == 7


def test_ngram_drafter_fallback_repeats_last():
    d = NgramDrafter(3)
    np.testing.assert_array_equal(
        d.propose(np.asarray([4, 5, 6]), 3), [6, 6, 6])
    np.testing.assert_array_equal(d.propose(np.asarray([2]), 2), [2, 2])
    np.testing.assert_array_equal(d.propose(np.asarray([], np.int32), 2),
                                  [0, 0])


# --------------------------------------------------------------------------- #
# config / engine validation
# --------------------------------------------------------------------------- #


def test_spec_config_validation(tiny_model_kwargs):
    with pytest.raises(ValueError, match="spec_len"):
        Config.from_dict({"inference": {"spec_len": -1}})
    with pytest.raises(ValueError, match="spec_ngram"):
        Config.from_dict({"inference": {"spec_ngram": 0}})
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    eng = InferenceEngine(cfg, max_seq_len=MAX_LEN)  # spec off by default
    assert eng.spec_len == 0
    with pytest.raises(ValueError, match="spec_len"):
        eng.verify(None, None, np.zeros((2, 3), np.int32), None,
                   None, None, None, None, None)
    # config knob flows through; keyword override wins
    cfg.inference.spec_len = 3
    assert InferenceEngine(cfg, max_seq_len=MAX_LEN).spec_len == 3
    assert InferenceEngine(cfg, max_seq_len=MAX_LEN,
                           spec_len=0).spec_len == 0
