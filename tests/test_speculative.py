"""Speculative decoding (ISSUE-4): draft-verify pipeline on top of blocked
decode.

Acceptance surface:
- greedy speculative decode (prompt-lookup drafter, any draft quality) is
  BIT-IDENTICAL to the spec-off batcher streams on tp=1 and a tp=2 dryrun
  mesh, EOS mid-verify included;
- a drafter that guesses right turns dispatches-per-token into
  1/(spec_len+1): a scripted oracle drafter pins the dispatch count and a
  100% accept rate;
- the acceptance rule is distribution-preserving: greedy rows take the
  exact-match fast path (unit-pinned emitted prefixes), stochastic rows
  rejection-sample with residual resampling — a seeded statistical test
  pins the emitted-token frequencies against the non-speculative
  sampler's filtered softmax, at the pure-function level AND through the
  real verify dispatch;
- rollback is the length pointer: a rejected draft's optimistically
  written K/V rows leave ``attend`` output bit-identical to never having
  written them (bf16 and int8 caches);
- the n-gram drafter proposes cycle continuations from the slot's own
  history (longest suffix first) and always returns exactly n tokens.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.config import Config, SpecControllerConfig
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    LearnedDrafter,
    NgramDrafter,
    Request,
    SpecController,
    init_draft_head,
    kv_cache,
    sampling,
)
from picotron_tpu.inference.speculative import Drafter
from picotron_tpu.models import llama
from picotron_tpu.obs.metrics import MetricsRegistry

MAX_LEN = 96


def _engine(tiny_model_kwargs, tp=1, slots=2, **kw):
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    return cfg, InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN, **kw)


def _params(cfg, engine, seed=0):
    p = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(seed))
    return engine.shard_params(p)


class ScriptedDrafter(Drafter):
    """Oracle drafter for tests: proposes the known future of one scripted
    sequence (prompt + expected tokens) by matching the history length."""

    def __init__(self, script):
        self.script = list(script)

    def propose(self, history, n):
        start = len(np.asarray(history).reshape(-1))
        out = np.zeros(n, np.int32)
        tail = self.script[start: start + n]
        out[: len(tail)] = tail
        return out


# --------------------------------------------------------------------------- #
# greedy speculation == spec-off, bit for bit
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("tp,spec_len", [(1, 2), (1, 4), (2, 3)])
def test_greedy_spec_matches_spec_off(tiny_model_kwargs, tp, spec_len):
    """Mixed-length greedy requests through the speculative batcher (the
    real NgramDrafter — accepts and rejections both occur) must produce
    the spec-off engine's streams token for token."""
    cfg, eng_off = _engine(tiny_model_kwargs, tp=tp)
    _, eng_on = _engine(tiny_model_kwargs, tp=tp, spec_len=spec_len)
    params = _params(cfg, eng_off)
    reqs = [Request("a", [1, 2, 3, 1, 2, 3, 1, 2], max_new_tokens=17),
            Request("b", [9, 8, 7], max_new_tokens=6)]
    want = ContinuousBatcher(eng_off, params).run(reqs)
    got = ContinuousBatcher(eng_on, params).run(reqs)
    for r in reqs:
        assert got[r.uid].tokens == want[r.uid].tokens, (r.uid, tp, spec_len)
        assert got[r.uid].finish_reason == "length"


def test_greedy_spec_eos_mid_verify(tiny_model_kwargs):
    """A stream whose EOS lands mid-verify (inside an accepted draft run
    or at the fresh token) must end AT the EOS — identical to spec-off —
    and the queued request behind it still completes."""
    cfg, eng_off = _engine(tiny_model_kwargs, slots=1)
    _, eng_on = _engine(tiny_model_kwargs, slots=1, spec_len=4)
    params = _params(cfg, eng_off)
    prompt = [5, 6, 7, 8]
    free = ContinuousBatcher(eng_off, params).run(
        [Request("f", prompt, max_new_tokens=12)])["f"]
    eos = free.tokens[5]
    assert eos not in free.tokens[:5], "pick a different seed/prompt"
    res = ContinuousBatcher(eng_on, params).run([
        Request("x", prompt, max_new_tokens=12, eos_id=eos),
        Request("y", [3, 1, 4], max_new_tokens=5),
    ])
    assert res["x"].finish_reason == "eos"
    assert res["x"].tokens == free.tokens[:6]
    assert res["y"].finish_reason == "length"
    assert len(res["y"].tokens) == 5


def test_scripted_drafter_dispatch_savings(tiny_model_kwargs):
    """An oracle drafter (knows the greedy future) must drive acceptance
    to 100% and the decode dispatch count to ceil((n-1)/(spec_len+1)) —
    the one-pass-per-accepted-run win speculation exists for."""
    cfg, eng_off = _engine(tiny_model_kwargs)
    _, eng_on = _engine(tiny_model_kwargs, spec_len=3)
    params = _params(cfg, eng_off)
    prompt = [1, 2, 3, 4, 5]
    n_new = 13
    want = ContinuousBatcher(eng_off, params).run(
        [Request("r", prompt, max_new_tokens=n_new)])["r"].tokens
    drafter = ScriptedDrafter(prompt + want)
    b = ContinuousBatcher(eng_on, params, drafter=drafter)
    got = b.run([Request("r", prompt, max_new_tokens=n_new)])["r"].tokens
    assert got == want
    assert b.accept_rate == 1.0
    # token 1 comes from the prefill sample; each verify emits spec_len+1
    assert b.decode_dispatches == math.ceil((n_new - 1) / 4)
    assert b.decode_dispatches < n_new - 1  # strictly beats per-token


def test_spec_respects_budget_and_window(tiny_model_kwargs):
    """Budgets that are not multiples of spec_len+1 (and a prompt close to
    the window) stop at exactly max_new_tokens — the device budget clip on
    the variable-length emit."""
    cfg, eng = _engine(tiny_model_kwargs, slots=2, spec_len=4)
    params = _params(cfg, eng)
    reqs = [Request("a", [1, 2, 3], max_new_tokens=7),
            Request("b", list(range(1, 90)), max_new_tokens=64)]
    res = ContinuousBatcher(eng, params).run(reqs)
    assert len(res["a"].tokens) == 7 and res["a"].finish_reason == "length"
    # 89 prompt tokens under MAX_LEN 96 leave exactly 7
    assert len(res["b"].tokens) == 7 and res["b"].finish_reason == "length"


# --------------------------------------------------------------------------- #
# acceptance rule: greedy fast path + distribution preservation
# --------------------------------------------------------------------------- #


def _logits_for_chain(chain, V, boost=8.0):
    """[S, V] logits whose argmax at position i is chain[i], with enough
    margin that the argmax is unambiguous."""
    rng = np.random.default_rng(0)
    out = rng.normal(size=(len(chain), V)).astype(np.float32)
    out[np.arange(len(chain)), chain] += boost
    return out


def test_accept_greedy_prefix():
    """Greedy rows accept exactly the matching draft prefix and emit the
    argmax correction (or the bonus token when everything matched)."""
    V = 11
    chain = [3, 7, 1, 4, 9]  # argmax at the 5 verify positions
    logits = jnp.asarray(_logits_for_chain(chain, V)[None])  # [1, 5, V]
    zero, one = jnp.zeros(1), jnp.ones(1)
    for n_match in range(5):
        draft = list(chain[:4])
        if n_match < 4:
            draft[n_match] = (draft[n_match] + 1) % V  # first mismatch
        emitted, counts = sampling.speculative_accept(
            logits, jnp.asarray([draft], jnp.int32), jax.random.PRNGKey(0),
            zero, jnp.zeros(1, jnp.int32), one)
        want = chain[: n_match + 1]  # accepted prefix == greedy chain
        assert int(counts[0]) == n_match + 1
        assert list(np.asarray(emitted)[0, : n_match + 1]) == want
        assert np.all(np.asarray(emitted)[0, n_match + 1:] == 0)


def test_accept_distribution_matches_sampler():
    """Seeded statistical test of the rejection/residual rule: over many
    keys, the FIRST emitted token's frequencies must converge to the
    non-speculative sampler's distribution (filtered softmax) — whether
    the draft token is likely or unlikely — and the draft must accept at
    ~its target probability. Also exercised with top-k filtering."""
    rng = np.random.default_rng(2)
    V = 8
    logits = jnp.asarray(rng.normal(size=(1, 2, V)).astype(np.float32))
    n = 4000
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    one = jnp.ones(1)

    probs0 = np.asarray(jax.nn.softmax(logits[0, 0]))
    for draft_tok in (int(np.argmax(probs0)), int(np.argmin(probs0))):
        for top_k in (0, 3):
            draft = jnp.asarray([[draft_tok]], jnp.int32)
            ks = jnp.full(1, top_k, jnp.int32)

            def first_tok(key):
                emitted, _ = sampling.speculative_accept(
                    logits, draft, key, one, ks, one)
                return emitted[0, 0]

            toks = np.asarray(jax.vmap(first_tok)(keys))
            freq = np.bincount(toks, minlength=V) / n
            want = np.asarray(sampling.filtered_probs(
                logits[0, :1], one, ks, one))[0]
            np.testing.assert_allclose(freq, want, atol=0.04,
                                       err_msg=f"d={draft_tok} k={top_k}")
            # acceptance fires at the draft token's target probability
            def count(key):
                _, c = sampling.speculative_accept(
                    logits, draft, key, one, ks, one)
                return c[0]

            acc = np.mean(np.asarray(jax.vmap(count)(keys)) == 2)
            np.testing.assert_allclose(acc, want[draft_tok], atol=0.04)


def test_accept_second_position_distribution():
    """Given an accepted draft, the NEXT emitted token draws from the
    bonus position's own filtered softmax — the chain rule that makes the
    whole emitted run distributionally exact."""
    rng = np.random.default_rng(3)
    V = 8
    logits_np = rng.normal(size=(1, 2, V)).astype(np.float32)
    probs0 = np.asarray(jax.nn.softmax(jnp.asarray(logits_np[0, 0])))
    draft_tok = int(np.argmax(probs0))  # likely -> plenty of accepts
    logits = jnp.asarray(logits_np)
    draft = jnp.asarray([[draft_tok]], jnp.int32)
    one, zk = jnp.ones(1), jnp.zeros(1, jnp.int32)
    n = 4000
    keys = jax.random.split(jax.random.PRNGKey(1), n)

    def run(key):
        emitted, counts = sampling.speculative_accept(
            logits, draft, key, one, zk, one)
        return emitted[0, 1], counts[0]

    second, counts = jax.vmap(run)(keys)
    second, counts = np.asarray(second), np.asarray(counts)
    sel = counts == 2  # draft accepted: position 1 is the bonus draw
    assert sel.mean() > 0.25
    freq = np.bincount(second[sel], minlength=V) / sel.sum()
    want = np.asarray(jax.nn.softmax(logits[0, 1]))
    np.testing.assert_allclose(freq, want, atol=0.05)


def test_spec_sampled_e2e_distribution(tiny_model_kwargs):
    """The real verify dispatch preserves the sampler's distribution:
    park a prompt, feed a fixed last token + drafts, and over many keys
    the first emitted token's frequencies must match the filtered softmax
    of the full-forward oracle logits at that position (top-k 4
    concentrates the support so a few hundred draws resolve it)."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from picotron_tpu.utils import shard_map as shard_map_compat

    cfg, engine = _engine(tiny_model_kwargs, slots=1, spec_len=2)
    params = _params(cfg, engine)
    prompt = [7, 3, 5, 2, 7, 3]
    t0, top_k, temp = 9, 4, 1.0

    fwd = jax.jit(shard_map_compat(
        lambda p, t: llama.forward_logits(p, t, cfg), engine.topo.mesh,
        in_specs=(llama.param_pspecs(cfg.model), P()), out_specs=P()))
    oracle = np.asarray(fwd(params, jnp.asarray(
        np.asarray(prompt + [t0], np.int32)[None])))[0, -1]
    want = np.asarray(sampling.filtered_probs(
        jnp.asarray(oracle[None]), jnp.full(1, temp),
        jnp.full(1, top_k, jnp.int32), jnp.ones(1)))[0]
    draft_tok = int(np.argmax(want))  # exercises accept AND reject paths

    kv, _ = engine.prefill(params, prompt)
    cache0 = engine.insert(engine.init_cache(), kv, 0, len(prompt))
    cache0 = jax.tree.map(np.asarray, cache0)  # host copy: verify donates
    tokens = np.asarray([[t0, draft_tok, draft_tok]], np.int32)
    args = (np.full(1, -1, np.int32), np.full(1, 50, np.int32),
            np.full(1, temp, np.float32), np.full(1, top_k, np.int32),
            np.ones(1, np.float32))
    n = 400
    first = np.zeros(n, np.int32)
    for i in range(n):
        cache = jax.tree.map(jnp.asarray, cache0)
        _, emitted, counts, _ = engine.verify(
            params, cache, tokens, jax.random.PRNGKey(i), *args)
        assert int(np.asarray(counts)[0]) >= 1
        first[i] = np.asarray(emitted)[0, 0]
    freq = np.bincount(first, minlength=cfg.model.vocab_size) / n
    kept = np.flatnonzero(want)
    assert set(np.flatnonzero(freq)) <= set(kept)
    np.testing.assert_allclose(freq[kept], want[kept], atol=0.09)


# --------------------------------------------------------------------------- #
# rollback: the length pointer IS the rewind
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("quantized", [False, True])
def test_rejected_draft_rows_invisible_to_attend(quantized):
    """Optimistically written draft rows beyond the post-acceptance length
    must leave ``attend`` output BIT-IDENTICAL to never having written
    them — for bf16 and int8 (scales included) caches. This is the whole
    rollback mechanism: rewinding is one length-pointer write."""
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 16, 4, 8
    dt = jnp.bfloat16

    def block():
        base = {
            "k": jnp.asarray(rng.normal(size=(B, T, H, D)), dt),
            "v": jnp.asarray(rng.normal(size=(B, T, H, D)), dt),
        }
        if quantized:
            qk, ks = kv_cache.quantize_kv(base["k"])
            qv, vs = kv_cache.quantize_kv(base["v"])
            base = {"k": qk, "v": qv, "k_scale": ks, "v_scale": vs}
        return base

    base = block()
    pos = jnp.asarray([6, 3], jnp.int32)  # per-slot write offsets
    S = 4  # 1 fed token + 3 drafts
    k_new = jnp.asarray(rng.normal(size=(B, S, H, D)), dt)
    v_new = jnp.asarray(rng.normal(size=(B, S, H, D)), dt)
    # speculative write: all S rows land; suppose 0 drafts accepted, so the
    # post-acceptance lengths advance past the fed token only
    spec = kv_cache.cache_write(base, k_new, v_new, pos)
    clean = kv_cache.cache_write(base, k_new[:, :1], v_new[:, :1], pos)
    lengths = pos + 1

    q = jnp.asarray(rng.normal(size=(B, 1, H, D)), dt)
    out_spec = kv_cache.attend(q, spec, lengths, 0.3)
    out_clean = kv_cache.attend(q, clean, lengths, 0.3)
    np.testing.assert_array_equal(np.asarray(out_spec, np.float32),
                                  np.asarray(out_clean, np.float32))
    # and the next decode step's write simply overwrites a stale row
    k2 = jnp.asarray(rng.normal(size=(B, 1, H, D)), dt)
    v2 = jnp.asarray(rng.normal(size=(B, 1, H, D)), dt)
    again_spec = kv_cache.cache_write(spec, k2, v2, lengths)
    again_clean = kv_cache.cache_write(clean, k2, v2, lengths)
    out2s = kv_cache.attend(q, again_spec, lengths + 1, 0.3)
    out2c = kv_cache.attend(q, again_clean, lengths + 1, 0.3)
    np.testing.assert_array_equal(np.asarray(out2s, np.float32),
                                  np.asarray(out2c, np.float32))


def test_batched_write_drops_out_of_window_rows():
    """A speculative write window crossing the cache edge drops the
    out-of-range rows instead of clamping them onto earlier positions
    (the chunked-prefill bug class, pinned for the batched write)."""
    B, T, H, D = 2, 8, 2, 4
    base = {"k": jnp.zeros((B, T, H, D)), "v": jnp.zeros((B, T, H, D))}
    k_new = jnp.ones((B, 3, H, D))
    out = kv_cache.cache_write(base, k_new, k_new,
                               jnp.asarray([6, 2], jnp.int32))
    got = np.asarray(out["k"][:, :, 0, 0])
    want = np.zeros((B, T))
    want[0, 6:8] = 1  # row at pos 8 dropped
    want[1, 2:5] = 1
    np.testing.assert_array_equal(got, want)


# --------------------------------------------------------------------------- #
# the n-gram drafter
# --------------------------------------------------------------------------- #


def test_ngram_drafter_cycle_continuation():
    d = NgramDrafter(3)
    hist = [1, 2, 3, 1, 2, 3, 1, 2]
    # suffix [3, 1, 2] matched at position 2 -> continuation cycles 3,1,2
    np.testing.assert_array_equal(d.propose(np.asarray(hist), 4),
                                  [3, 1, 2, 3])
    # proposals always have exactly n tokens
    assert d.propose(np.asarray(hist), 7).shape == (7,)


def test_ngram_drafter_longest_suffix_wins():
    # 1-gram match for 9 exists at position 0 (-> 5), but the 2-gram
    # suffix [2, 9] matches at 2 (-> 7): the longer context must win
    d = NgramDrafter(3)
    hist = [9, 5, 2, 9, 7, 2, 9]
    assert d.propose(np.asarray(hist), 1)[0] == 7


def test_ngram_drafter_fallback_repeats_last():
    d = NgramDrafter(3)
    np.testing.assert_array_equal(
        d.propose(np.asarray([4, 5, 6]), 3), [6, 6, 6])
    np.testing.assert_array_equal(d.propose(np.asarray([2]), 2), [2, 2])
    np.testing.assert_array_equal(d.propose(np.asarray([], np.int32), 2),
                                  [0, 0])


# --------------------------------------------------------------------------- #
# config / engine validation
# --------------------------------------------------------------------------- #


def test_spec_config_validation(tiny_model_kwargs):
    with pytest.raises(ValueError, match="spec_len"):
        Config.from_dict({"inference": {"spec_len": -1}})
    with pytest.raises(ValueError, match="spec_ngram"):
        Config.from_dict({"inference": {"spec_ngram": 0}})
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    eng = InferenceEngine(cfg, max_seq_len=MAX_LEN)  # spec off by default
    assert eng.spec_len == 0
    with pytest.raises(ValueError, match="spec_len"):
        eng.verify(None, None, np.zeros((2, 3), np.int32), None,
                   None, None, None, None, None)
    # config knob flows through; keyword override wins
    cfg.inference.spec_len = 3
    assert InferenceEngine(cfg, max_seq_len=MAX_LEN).spec_len == 3
    assert InferenceEngine(cfg, max_seq_len=MAX_LEN,
                           spec_len=0).spec_len == 0


def test_controller_and_drafter_config_validation():
    with pytest.raises(ValueError, match="drafter"):
        Config.from_dict({"inference": {"drafter": "oracle"}})
    with pytest.raises(ValueError, match="spec_history_window"):
        Config.from_dict({"inference": {"spec_history_window": -1}})
    with pytest.raises(ValueError, match="spec_len > 0"):
        Config.from_dict(
            {"inference": {"spec_controller": {"enabled": True}}})
    with pytest.raises(ValueError, match="low"):
        Config.from_dict({"inference": {
            "spec_len": 4,
            "spec_controller": {"low": 0.9, "target": 0.5}}})
    with pytest.raises(ValueError, match="hysteresis"):
        Config.from_dict({"inference": {
            "spec_len": 4, "spec_controller": {"hysteresis": 0}}})
    # the nested block round-trips through to_dict/from_dict (the engine's
    # inference_config() path)
    cfg = Config.from_dict({
        "dataset": {"name": "synthetic"},
        "inference": {"spec_len": 4, "drafter": "learned",
                      "spec_controller": {"enabled": True, "window": 8}}})
    cfg2 = Config.from_dict(cfg.to_dict())
    assert cfg2.inference.spec_controller.window == 8
    assert cfg2.inference.drafter == "learned"


# --------------------------------------------------------------------------- #
# incremental n-gram index == full rebuild
# --------------------------------------------------------------------------- #


def test_ngram_incremental_matches_full_rebuild():
    """The append-only per-request index (ctx path) must answer every
    lookup exactly like the stateless full suffix scan, across growing
    histories — windowed and unbounded."""
    rng = np.random.default_rng(7)
    for window in (0, 12):
        inc = NgramDrafter(3, window=window)
        ref = NgramDrafter(3, window=window)
        inc.begin("r")
        hist = list(rng.integers(0, 6, 5))
        for round_ in range(40):
            h = np.asarray(hist, np.int32)
            got = inc.propose(h, 4, ctx="r")
            want = ref.propose(h, 4)  # stateless: full rebuild each call
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"w={window} r={round_}")
            # append-only growth, mixing repeats (matches) and fresh noise
            if round_ % 3 == 0:
                hist.extend(hist[-3:])
            hist.append(int(rng.integers(0, 6)))
        inc.forget("r")
        assert "r" not in inc._idx


def test_ngram_window_caps_match_scan():
    """A match whose continuation lives beyond the window must be ignored
    (falls back to shorter grams / last-token repeat)."""
    hist = np.asarray([7, 8, 9, 1, 1, 1, 1, 1, 1, 1, 7, 8], np.int32)
    # unbounded: suffix [7, 8] matches at position 0 -> proposes 9
    assert NgramDrafter(2).propose(hist, 1)[0] == 9
    # window 4: that match is out of reach; 1-gram 8 has no earlier
    # occurrence in the window either -> last-token fallback (8)
    assert NgramDrafter(2, window=4).propose(hist, 1)[0] == 8
    # the incremental path applies the same cap
    d = NgramDrafter(2, window=4)
    assert d.propose(hist, 1, ctx="x")[0] == 8


def test_ngram_stale_ctx_rebuilds_on_shrunk_history():
    """A slot recycled without begin() (history shrinks) must not answer
    from the dead request's index."""
    d = NgramDrafter(3)
    long_h = np.asarray([1, 2, 3, 4, 5, 1, 2, 3, 4], np.int32)
    d.propose(long_h, 2, ctx="s")
    short_h = np.asarray([9, 8], np.int32)
    np.testing.assert_array_equal(
        d.propose(short_h, 2, ctx="s"),
        NgramDrafter(3).propose(short_h, 2))


# --------------------------------------------------------------------------- #
# ragged verify: per-slot draft lengths in ONE dispatch
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("tp,impl,layout,quant,temp", [
    (1, "dense", "contiguous", False, 0.0),
    (1, "dense", "contiguous", True, 0.0),
    (1, "dense", "contiguous", False, 1.0),
    (1, "flash", "contiguous", False, 0.0),
    (1, "dense", "paged", False, 0.0),
    (1, "flash", "paged", True, 0.0),
    (2, "dense", "contiguous", False, 0.0),
    (2, "dense", "paged", True, 0.0),
])
def test_ragged_verify_matches_per_slot_sequential(tiny_model_kwargs, tp,
                                                   impl, layout, quant,
                                                   temp):
    """One RAGGED verify dispatch (per-slot draft_len) must emit, count,
    accept, and advance lengths exactly as per-slot SEQUENTIAL solo
    verifies (each slot alone with its own draft length) — across tp,
    attend kernels, KV layouts, and int8 storage. Row b's acceptance
    depends only on row b's logits and the shared key, so the group
    dispatch is the sum of its solo parts."""
    slots = 3
    cfg, engine = _engine(
        tiny_model_kwargs, tp=tp, slots=slots, spec_len=4,
        attend_impl=impl, kv_layout=layout,
        cache_dtype="int8" if quant else None)
    params = _params(cfg, engine)
    prompts = [[1, 2, 3, 1, 2, 3], [9, 8, 7, 6], [4, 4, 5]]
    draft_len = np.asarray([3, 1, 0], np.int32)
    rng = np.random.default_rng(0)
    drafts = rng.integers(1, cfg.model.vocab_size,
                          (slots, engine.spec_len)).astype(np.int32)
    key = jax.random.PRNGKey(5)
    eos = np.full(slots, -1, np.int32)
    temps = np.full(slots, temp, np.float32)
    tk = np.full(slots, 4 if temp > 0 else 0, np.int32)
    tp_ = np.ones(slots, np.float32)

    def one_run(budget):
        """Fresh cache + parked prompts, one verify dispatch."""
        cache = engine.init_cache()
        for s, p in enumerate(prompts):
            if layout == "paged":
                out = engine.prefill_paged(params, cache, p, s)
                cache = out[0]
            else:
                kv, _ = engine.prefill(params, p)
                cache = engine.insert(cache, kv, s, len(p))
        tokens = np.concatenate(
            [np.asarray([[p[-1]] for p in prompts], np.int32), drafts],
            axis=1)
        cache, emitted, counts, accepted = engine.verify(
            params, cache, tokens, key, eos, budget, temps, tk, tp_,
            draft_len=draft_len)
        return (np.asarray(emitted), np.asarray(counts),
                np.asarray(accepted), np.asarray(cache["lengths"]))

    full_budget = np.asarray([8, 2, 8], np.int32)  # slot 1: budget clip
    g_em, g_ct, g_ac, g_len = one_run(full_budget)
    for s in range(slots):
        solo = np.zeros(slots, np.int32)
        solo[s] = full_budget[s]
        em, ct, ac, ln = one_run(solo)
        assert ct[s] == g_ct[s], (s, ct, g_ct)
        assert ac[s] == g_ac[s]
        np.testing.assert_array_equal(em[s], g_em[s])
        assert ln[s] == g_len[s]
    # the ragged contract itself: counts bounded by the slot's own draft
    assert np.all(g_ct <= draft_len + 1)
    assert g_ct[2] == 1  # a 0-draft slot is exactly one decode step
    assert np.all(g_ac <= draft_len)


def test_ragged_zero_draft_row_matches_decode_step(tiny_model_kwargs):
    """A draft_len == 0 row through the RAGGED verify must emit exactly
    the greedy decode_step token — pad drafts can never leak in."""
    cfg, engine = _engine(tiny_model_kwargs, slots=2, spec_len=3)
    params = _params(cfg, engine)
    prompts = [[1, 2, 3, 4], [5, 6, 7]]

    def park():
        cache = engine.init_cache()
        for s, p in enumerate(prompts):
            kv, _ = engine.prefill(params, p)
            cache = engine.insert(cache, kv, s, len(p))
        return cache

    args = (np.full(2, -1, np.int32), np.full(2, 8, np.int32),
            np.zeros(2, np.float32), np.zeros(2, np.int32),
            np.ones(2, np.float32))
    key = jax.random.PRNGKey(0)
    _, want, _ = engine.decode_step(
        params, park(), np.asarray([4, 7], np.int32), key, *args[2:])
    want = np.asarray(want)  # greedy: the sampled token IS the argmax
    tokens = np.asarray([[4, 111, 112, 113], [7, 114, 115, 116]], np.int32)
    _, emitted, counts, _ = engine.verify(
        params, park(), tokens, key, *args,
        draft_len=np.zeros(2, np.int32))
    counts = np.asarray(counts)
    np.testing.assert_array_equal(counts, [1, 1])
    np.testing.assert_array_equal(np.asarray(emitted)[:, 0], want)


# --------------------------------------------------------------------------- #
# the learned drafter (EAGLE-style head over the target's hidden state)
# --------------------------------------------------------------------------- #


def _np_head(params_np, h, eps):
    """The target's logits path over a hidden state, in numpy: final
    RMSNorm then the shared lm_head — the oracle for the return_hidden
    hook's contract."""
    w = params_np["final_norm"].astype(np.float64)
    x = h.astype(np.float64)
    x = x / np.sqrt(np.mean(x * x, axis=-1, keepdims=True) + eps) * w
    return x @ params_np["lm_head"].astype(np.float64)


def test_return_hidden_is_the_logits_producing_state(tiny_model_kwargs):
    """The hook's contract, pinned against the model's own head: the
    hidden state every dispatch returns is the one whose (final-norm +
    lm_head) logits produced that slot's last emitted token — prefill,
    decode_block, and verify (ragged rows included)."""
    cfg, engine = _engine(tiny_model_kwargs, slots=2, spec_len=3,
                          drafter="learned", decode_block_len=4)
    assert engine.return_hidden
    params = _params(cfg, engine)
    params_np = jax.tree.map(np.asarray, jax.device_get(params))
    eps = cfg.model.rms_norm_eps

    # prefill: returned logits == head(returned hidden)
    prompt = [1, 2, 3, 4, 5]
    kv, logits, hid = engine.prefill(params, prompt)
    np.testing.assert_allclose(
        _np_head(params_np, np.asarray(hid), eps)[0],
        np.asarray(logits)[0], rtol=1e-4, atol=1e-4)

    cache = engine.insert(engine.init_cache(), kv, 0, len(prompt))
    kv2, logits2, _ = engine.prefill(params, [9, 8])
    cache = engine.insert(cache, kv2, 1, 2)
    first = np.asarray([int(np.argmax(np.asarray(logits)[0])),
                        int(np.argmax(np.asarray(logits2)[0]))], np.int32)

    # decode_block: argmax(head(hidden)) == the slot's last emitted token
    keys = np.stack([np.asarray(jax.random.PRNGKey(i)) for i in range(4)])
    args = (np.full(2, -1, np.int32), np.asarray([4, 2], np.int32),
            np.zeros(2, np.float32), np.zeros(2, np.int32),
            np.ones(2, np.float32))
    cache, toks, counts, hid = engine.decode_block(
        params, cache, first, keys, *args)
    toks, counts = np.asarray(toks), np.asarray(counts)
    for s in range(2):
        last = toks[s, counts[s] - 1]
        assert np.argmax(_np_head(params_np,
                                  np.asarray(hid)[s][None], eps)[0]) == last

    # verify (ragged): same invariant, draft lengths [2, 0]
    last_toks = np.asarray([toks[s, counts[s] - 1] for s in range(2)],
                           np.int32)
    tokens = np.zeros((2, 4), np.int32)
    tokens[:, 0] = last_toks
    tokens[0, 1:3] = [7, 7]
    cache, emitted, vcounts, _, vhid = engine.verify(
        params, cache, tokens, jax.random.PRNGKey(9),
        np.full(2, -1, np.int32), np.full(2, 8, np.int32),
        np.zeros(2, np.float32), np.zeros(2, np.int32),
        np.ones(2, np.float32), draft_len=np.asarray([2, 0], np.int32))
    emitted, vcounts = np.asarray(emitted), np.asarray(vcounts)
    for s in range(2):
        last = emitted[s, vcounts[s] - 1]
        assert np.argmax(_np_head(params_np,
                                  np.asarray(vhid)[s][None], eps)[0]) == last


@pytest.mark.parametrize("tp", [1, 2])
def test_learned_drafter_greedy_bit_identical(tiny_model_kwargs, tp):
    """Greedy batcher streams with the learned drafter (whatever it
    proposes) must equal the spec-off streams token for token — the
    acceptance rule's guarantee holds for the new drafter + hidden
    plumbing, on tp=1 and a tp=2 mesh."""
    cfg, eng_off = _engine(tiny_model_kwargs, tp=tp)
    _, eng_on = _engine(tiny_model_kwargs, tp=tp, spec_len=3,
                        drafter="learned")
    params = _params(cfg, eng_off)
    reqs = [Request("a", [1, 2, 3, 1, 2, 3, 1, 2], max_new_tokens=17),
            Request("b", [9, 8, 7], max_new_tokens=6)]
    want = ContinuousBatcher(eng_off, params).run(reqs)
    b = ContinuousBatcher(eng_on, params)
    assert b.drafter.kind == "learned"
    got = b.run(reqs)
    for r in reqs:
        assert got[r.uid].tokens == want[r.uid].tokens, (r.uid, tp)
        assert got[r.uid].drafter == "learned"
    assert b.draft_proposed > 0  # it really drafted


def test_learned_drafter_deterministic_and_head_variant(tiny_model_kwargs):
    """propose_batch is a deterministic function of (hidden, token) —
    the point-mass contract the accept rule assumes — and the optional
    tiny-head params change the proposal function without breaking it."""
    cfg, engine = _engine(tiny_model_kwargs, slots=2, spec_len=4,
                          drafter="learned")
    params = _params(cfg, engine)
    d = LearnedDrafter(engine, params)
    hidden = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 64)).astype(np.float32))
    toks = np.asarray([5, 9], np.int32)
    a = d.propose_batch(toks, hidden, 4)
    b = d.propose_batch(toks, hidden, 4)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 4) and a.dtype == np.int32
    assert np.all((a >= 0) & (a < cfg.model.vocab_size))
    with pytest.raises(ValueError, match="spec_len"):
        d.propose_batch(toks, hidden, 2)
    with pytest.raises(TypeError, match="propose_batch"):
        d.propose(np.asarray([1, 2]), 4)
    # tiny-head variant (the shape checkpoint.load_params would restore)
    head = init_draft_head(jax.random.PRNGKey(1), cfg.model.hidden_size)
    dh = LearnedDrafter(engine, params, head=head)
    c = dh.propose_batch(toks, hidden, 4)
    assert c.shape == (2, 4)
    np.testing.assert_array_equal(c, dh.propose_batch(toks, hidden, 4))
    # a spec-off / hidden-less engine is rejected with the fix named
    _, plain = _engine(tiny_model_kwargs)
    with pytest.raises(ValueError, match="spec"):
        LearnedDrafter(plain, params)
    _, no_hidden = _engine(tiny_model_kwargs, spec_len=3)
    with pytest.raises(ValueError, match="return_hidden"):
        LearnedDrafter(no_hidden, params)


# --------------------------------------------------------------------------- #
# the spec controller: hysteresis, convergence, switching, cost model
# --------------------------------------------------------------------------- #


def _controller(reg=None, *, kinds=("ngram",), gmax=4, block_len=8, **kw):
    cfg = SpecControllerConfig(enabled=True, **kw)
    reg = reg if reg is not None else MetricsRegistry()
    c = SpecController(cfg, reg, slots=1, max_spec_len=gmax,
                       block_len=block_len, kinds=kinds)
    c.reset(0)
    return c, reg


def _feed(c, reg, proposed, accepted):
    """One round's worth of counters into the registry (what the batcher
    writes), then the controller's policy tick."""
    reg.counter("picotron_slot_draft_proposed_total",
                slot="0").inc(proposed)
    reg.counter("picotron_slot_draft_accepted_total",
                slot="0").inc(accepted)
    c.record(0, proposed, accepted)
    c.after_round(0)


def test_controller_hysteresis_no_oscillation():
    """Adversarial accept-rate flip-flop traffic: full-accept windows
    alternating with zero-accept windows. The direction alternates every
    evaluation, the hysteresis streak never completes, and spec_len must
    NOT move — not once."""
    c, reg = _controller(window=4, hysteresis=2, cooloff=1000)
    g0 = int(c.lens()[0])
    for i in range(40):
        _feed(c, reg, 4, 4 if i % 2 == 0 else 0)
        assert int(c.lens()[0]) == g0, f"oscillated at round {i}"
    assert not c.decisions  # no ramp was ever applied


def test_controller_ramps_down_to_off_and_probes():
    """Persistently hard traffic: halve per hysteresis streak down to 1,
    then (single drafter) OFF; after cooloff idle rounds the controller
    re-probes with a 1-token draft."""
    c, reg = _controller(window=4, hysteresis=2, low=0.25, cooloff=3)
    seen = [int(c.lens()[0])]
    for _ in range(30):
        if int(c.lens()[0]) == 0:
            break
        _feed(c, reg, max(int(c.lens()[0]), 1), 0)
        seen.append(int(c.lens()[0]))
    assert seen[0] == 4 and 2 in seen and 1 in seen
    assert int(c.lens()[0]) == 0
    assert c.decisions.get("spec_off") == 1
    # monotone on persistent signal: never back up mid-descent
    assert all(a >= b for a, b in zip(seen, seen[1:]))
    for _ in range(3):  # cooloff rounds at 0
        c.after_round(0)
    assert int(c.lens()[0]) == 1  # the probe
    assert c.decisions.get("probe") == 1


def test_controller_ramps_up_on_easy_traffic():
    c, reg = _controller(window=2, hysteresis=2, target=0.5, cooloff=1000)
    # drive down to 1 first
    while int(c.lens()[0]) > 1:
        _feed(c, reg, max(int(c.lens()[0]), 1), 0)
    # then full acceptance doubles back to the ceiling
    for _ in range(20):
        g = int(c.lens()[0])
        _feed(c, reg, max(g, 1), max(g, 1))
    assert int(c.lens()[0]) == 4
    assert c.decisions.get("ramp_up", 0) >= 2


def test_controller_switches_drafter_before_giving_up():
    """With a learned primary and the n-gram fallback registered, a slot
    losing at spec_len 1 tries the OTHER drafter before turning
    speculation off."""
    c, reg = _controller(window=2, hysteresis=1, kinds=("learned", "ngram"),
                         cooloff=1000)
    assert c.drafter_kinds()[0] == "learned"
    switched = False
    for _ in range(30):
        if int(c.lens()[0]) == 0:
            break
        _feed(c, reg, max(int(c.lens()[0]), 1), 0)
        if c.drafter_kinds()[0] == "ngram":
            switched = True
    assert switched and c.decisions.get("switch_drafter") == 1
    assert int(c.lens()[0]) == 0  # both tried and bad -> off


def test_controller_latency_term_vetoes_losing_speculation():
    """Once the dispatch-latency histograms hold enough samples, a
    measured verify cost that can't beat blocked decode forces the ramp
    DOWN even at full acceptance — speculation must PAY, not just
    accept."""
    c, reg = _controller(window=2, hysteresis=1, latency_min_samples=4,
                         block_len=8)
    hv = reg.histogram("picotron_dispatch_seconds",
                       "dispatch wall time incl. host sync, by kind",
                       kind="verify")
    hd = reg.histogram("picotron_dispatch_seconds",
                       "dispatch wall time incl. host sync, by kind",
                       kind="decode")
    for _ in range(8):
        hv.observe(0.2)   # a verify costs 0.2s for <= 5 tokens
        hd.observe(0.08)  # a block of 8 tokens costs 0.08s
    for _ in range(10):
        if int(c.lens()[0]) == 0:
            break
        _feed(c, reg, max(int(c.lens()[0]), 1), max(int(c.lens()[0]), 1))
    assert int(c.lens()[0]) == 0  # full acceptance, measured loss -> off


class RegimeDrafter(Drafter):
    """Per-request regimes for the acceptance test: requests with a
    script (the 'repetitive' regime) get ORACLE proposals — the known
    greedy future — while scriptless ('random') requests get junk, so
    the two regimes' accept rates are deterministic extremes."""

    kind = "ngram"
    stateful = True

    def __init__(self, scripts):
        self.scripts = scripts  # uid -> prompt + expected tokens

    def propose(self, history, n, ctx=None):
        h = np.asarray(history, np.int32).reshape(-1)
        script = self.scripts.get(ctx)
        out = np.zeros(n, np.int32)
        if script is None:  # junk: varies so it can't accidentally loop
            return (h[-1] + 1 + np.arange(n, dtype=np.int32)) % 251
        tail = script[h.size: h.size + n]
        out[: len(tail)] = tail
        return out


def test_controller_mixed_workload_convergence(tiny_model_kwargs):
    """THE acceptance run (through the real batcher): on a mixed
    workload, repetitive-regime slots converge to spec_len > 0 with
    per-request dispatches/token strictly below the spec-off per-token
    baseline of 1, random-regime slots converge to spec_len == 0 within
    the run, and every greedy stream stays BIT-IDENTICAL to spec-off."""
    raw = make_config(tiny_model_kwargs, seq=MAX_LEN).to_dict()
    raw["inference"].update(dict(
        spec_len=4,
        spec_controller=dict(enabled=True, window=4, hysteresis=2,
                             target=0.6, low=0.3, cooloff=10_000)))
    cfg = Config.from_dict(raw)
    eng_off = InferenceEngine(cfg, slots=4, max_seq_len=MAX_LEN,
                              spec_len=0)
    params = _params(cfg, eng_off)

    def reqs():
        return [Request("rep0", [1, 2, 3, 1, 2, 3], max_new_tokens=48),
                Request("rep1", [5, 6, 5, 6, 5], max_new_tokens=48),
                Request("rand0", [11, 23, 7], max_new_tokens=30),
                Request("rand1", [42, 9, 31, 8], max_new_tokens=30)]

    want = ContinuousBatcher(eng_off, params).run(reqs())
    scripts = {u: list(r.prompt) + want[u].tokens
               for u, r in ((q.uid, q) for q in reqs())
               if u.startswith("rep")}
    eng_on = InferenceEngine(cfg, slots=4, max_seq_len=MAX_LEN)
    b = ContinuousBatcher(eng_on, params, drafter=RegimeDrafter(scripts))
    assert b.controller is not None
    got = b.run(reqs())
    for u, r in want.items():
        assert got[u].tokens == r.tokens, u  # greedy unchanged, always
    for u in ("rep0", "rep1"):
        assert got[u].spec_len_final > 0, (u, got[u])
        dpt = got[u].dispatches / len(got[u].tokens)
        assert dpt < 1.0, (u, dpt)  # strictly beats spec-off per-token
    for u in ("rand0", "rand1"):
        assert got[u].spec_len_final == 0, (u, got[u])
    # decisions + effective length landed in stats and on the scrape
    st = b.stats()
    assert st["spec_controller"].get("spec_off", 0) >= 2
    assert "spec_len_effective" in st
    b.refresh_gauges()
    prom = b.obs.registry.prometheus()
    assert "picotron_spec_accept_rate" in prom
    assert "picotron_spec_len" in prom


def test_controller_loop_closes_with_obs_disabled(tiny_model_kwargs):
    """``obs.enabled: false`` swaps the registry for null instruments —
    the controller must still close its loop off the internal shadow
    tallies (and greedy output stays identical, as everywhere)."""
    raw = make_config(tiny_model_kwargs, seq=MAX_LEN).to_dict()
    raw["inference"].update(dict(
        spec_len=4,
        spec_controller=dict(enabled=True, window=4, hysteresis=2)))
    raw["obs"] = {"enabled": False}
    cfg = Config.from_dict(raw)
    eng_off = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                              spec_len=0)
    eng_on = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
    params = _params(cfg, eng_off)
    reqs = [Request("a", [1, 2, 3, 1, 2, 3, 1, 2], max_new_tokens=20),
            Request("b", [11, 23, 7], max_new_tokens=16)]
    want = ContinuousBatcher(eng_off, params).run(reqs)
    b = ContinuousBatcher(eng_on, params)
    got = b.run(reqs)
    for r in reqs:
        assert got[r.uid].tokens == want[r.uid].tokens, r.uid
    assert b.controller.decisions  # it DECIDED, blind registry and all


def test_controller_on_greedy_identical_with_real_ngram(tiny_model_kwargs):
    """Controller enabled with the REAL n-gram drafter (accepts and
    rejections both occur, lengths ramp): greedy streams still equal
    spec-off bit for bit — the ragged verify preserves the greedy
    chain no matter what the policy loop decides."""
    raw = make_config(tiny_model_kwargs, seq=MAX_LEN).to_dict()
    raw["inference"].update(dict(
        spec_len=4,
        spec_controller=dict(enabled=True, window=4, hysteresis=2)))
    cfg = Config.from_dict(raw)
    eng_off = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                              spec_len=0)
    eng_on = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
    params = _params(cfg, eng_off)
    reqs = [Request("a", [1, 2, 3, 1, 2, 3, 1, 2], max_new_tokens=20),
            Request("b", [9, 8, 7], max_new_tokens=9)]
    want = ContinuousBatcher(eng_off, params).run(reqs)
    got = ContinuousBatcher(eng_on, params).run(reqs)
    for r in reqs:
        assert got[r.uid].tokens == want[r.uid].tokens, r.uid
