"""Paged KV cache suite (ISSUE 7; inference/paged_kv.py).

Three layers of pinning:

- **allocator invariants** (pure host): alloc/free round-trips never
  double-free, refcounts never go negative (both raise instead), radix
  eviction frees exactly the refcount-1 leaves LRU-first, COW planning
  swaps references without leaking;
- **byte equivalence** (device): the paged scatter/gather write and
  attend paths produce byte-identical K/V rows and identical attention
  outputs to the contiguous layout, fp32 and int8, dense and flash;
- **generation equivalence** (engine + batcher): with
  ``inference.kv_layout: "paged"``, greedy generations through blocked
  decode, speculative verify (incl. rollback), and chunked prefill are
  IDENTICAL to the contiguous layout — bf16 and int8 caches, dense and
  flash attends, tp=1 and tp=2 — and prefix sharing/COW are invisible in
  the output: forked requests generate exactly what independent requests
  would, while the shared pages' bytes never change.

Plus the capacity story the subsystem exists for: a shared-prefix
workload's prefill work and live pages scale with UNIQUE tokens, not
requests x prompt length, and out-of-pages admission sheds at the door
instead of corrupting a live slot (the serve front end's 429 carries a
pool-pressure Retry-After).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    Request,
    paged_kv,
)
from picotron_tpu.inference.paged_kv import (
    NULL_PAGE,
    PagedKV,
    PagePool,
    PagePoolExhausted,
    RadixCache,
)
from picotron_tpu.models import llama

MAX_LEN = 64
PAGE = 8

_TINY = dict(
    num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
    hidden_size=64, intermediate_size=128, vocab_size=256,
    max_position_embeddings=MAX_LEN, rope_theta=10000.0, dtype="float32",
    attention_impl="sdpa")


# --------------------------------------------------------------------------- #
# allocator invariants (pure host)
# --------------------------------------------------------------------------- #


def test_pool_alloc_free_roundtrip_and_double_free():
    pool = PagePool(5)  # 4 usable + NULL
    got = [pool.alloc() for _ in range(4)]
    assert sorted(got) == [1, 2, 3, 4] and NULL_PAGE not in got
    assert pool.alloc() is None  # dry pool is a None, not corruption
    assert pool.free_count == 0 and pool.live_count == 4
    for pid in got:
        assert pool.unref(pid)  # refcount 1 -> 0 frees
    assert pool.free_count == 4
    with pytest.raises(ValueError, match="double free"):
        pool.unref(got[0])  # refcount already 0
    with pytest.raises(ValueError, match="resurrect"):
        pool.ref(got[0])  # a freed page cannot be re-shared
    # refcounted sharing: two holders, page survives the first drop
    pid = pool.alloc()
    pool.ref(pid)
    assert not pool.unref(pid)
    assert pool.unref(pid)
    with pytest.raises(ValueError):
        pool.ref(NULL_PAGE)


def test_radix_match_insert_evict():
    pool = PagePool(16)
    radix = RadixCache(PAGE, pool)
    # "prefill" a 19-token prompt: two full pages + a 3-row partial tail
    prompt = list(range(100, 119))
    pages = [pool.alloc() for _ in range(3)]
    assert radix.insert(prompt, lambda i: pages[i]) == 3
    assert [pool.refs[p] for p in pages] == [2, 2, 2]  # slot + cache
    # exact full-prefix + partial-tail match
    got, matched = radix.match(prompt + [7, 8])
    assert matched == 19 and got == pages
    # mid-page fork: 11 tokens shared means page0 full + 3 rows of page1
    got, matched = radix.match(prompt[:11] + [9, 9, 9])
    assert matched == 11 and got == pages[:2]
    # no overlap at all
    assert radix.match([1, 2, 3]) == ([], 0)
    # the slot releases its references; pages are now cache-only (refs 1)
    for p in pages:
        pool.unref(p)
    assert radix.evictable_count() == 3  # the refcount-1 chain cascades
    # a second prompt sharing page0 keeps it alive through eviction
    pool.ref(pages[0])
    assert radix.evictable_count() == 2
    assert radix.evict_one() and radix.evict_one()  # tail first (LRU leaf)
    assert pool.refs[pages[1]] == 0 and pool.refs[pages[2]] == 0
    assert not radix.evict_one()  # page0 is shared: nothing evictable
    assert pool.refs[pages[0]] == 2
    assert radix.evictions == 2


def test_manager_cow_planning_and_free_slot():
    mgr = PagedKV(slots=2, page_len=PAGE, max_pages=4, num_pages=16)
    # slot 0 grows into two fresh pages — no COW on exclusive pages
    assert mgr.ensure_writable(0, 0, 12) == []
    held = [int(p) for p in mgr.tables[0, :2]]
    assert all(p != NULL_PAGE for p in held)
    assert mgr.ensure_writable(0, 8, 12) == []  # idempotent
    # share slot 0's first page into slot 1 (what a prefix hit does)
    mgr.pool.ref(held[0])
    mgr.tables[1, 0] = held[0]
    # slot 1's first write into the shared page must plan exactly one COW
    cows = mgr.ensure_writable(1, 4, 9)
    assert len(cows) == 1 and cows[0][0] == held[0]
    assert mgr.tables[1, 0] == cows[0][1] != held[0]
    assert mgr.pool.refs[held[0]] == 1  # slot 1 dropped its reference
    mgr.set_len(0, 12)
    mgr.free_slot(0)
    assert mgr.pool.refs[held[0]] == 0 and mgr.pool.refs[held[1]] == 0
    assert np.all(mgr.tables[0] == NULL_PAGE) and mgr.host_len[0] == 0
    mgr.free_slot(1)
    assert mgr.pool.free_count == mgr.pool.usable_pages


def test_match_prefix_idempotent_under_retry():
    """The batcher retries a faulted prefill dispatch, which re-runs the
    whole admission (match_prefix included) on the same slot. The re-match
    must release the failed attempt's holdings first — or shared pages
    double-ref (never evictable, never freed) and stranded COW copies
    leak outright."""
    mgr = PagedKV(slots=1, page_len=PAGE, max_pages=4, num_pages=16)
    prompt = list(range(100, 118))  # 2 full pages + 2-row tail
    # seed the radix cache as a completed request would
    mgr.ensure_writable(0, 0, len(prompt))
    cached_pages = [int(p) for p in mgr.tables[0] if p != NULL_PAGE]
    mgr.set_len(0, len(prompt))
    mgr.register_prompt(0, prompt)
    mgr.free_slot(0)
    live0 = mgr.pool.live_count
    # attempt 1 matches, COWs the fork page, then "fails"; attempt 2
    # re-matches the same slot
    assert mgr.match_prefix(0, prompt + [7]) == 18
    mgr.ensure_writable(0, 18, 19)  # the suffix COW a real attempt plans
    assert mgr.match_prefix(0, prompt + [7]) == 18  # the retry
    mgr.free_slot(0)  # the admission ultimately fails -> slot released
    # nothing leaked: pool back to the radix-only footprint, every cached
    # page at exactly the cache's one reference (still evictable)
    assert mgr.pool.live_count == live0
    assert all(mgr.pool.refs[p] == 1 for p in cached_pages)
    assert mgr.radix.evictable_count() == live0


def test_manager_exhaustion_raises_not_corrupts():
    mgr = PagedKV(slots=1, page_len=PAGE, max_pages=4, num_pages=3)
    mgr.ensure_writable(0, 0, 16)  # both usable pages
    before = mgr.tables[0].copy()
    with pytest.raises(PagePoolExhausted):
        mgr.ensure_writable(0, 16, 24)
    np.testing.assert_array_equal(mgr.tables[0], before)  # untouched


# --------------------------------------------------------------------------- #
# byte equivalence (device ops)
# --------------------------------------------------------------------------- #


def _cfg(tp=1, **inf):
    cfg = make_config(dict(_TINY), tp=tp, seq=32)
    for k, v in inf.items():
        setattr(cfg.inference, k, v)
    return cfg


def _engines(tp=1, slots=3, **kw):
    """(contiguous engine, paged engine) over one tiny config."""
    cfg = _cfg(tp=tp)
    ec = InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN,
                         kv_layout="contiguous", **kw)
    ep = InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN,
                         kv_layout="paged", kv_page_len=PAGE, **kw)
    params = ec.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    return cfg, ec, ep, params


@pytest.mark.parametrize("cache_dtype", [None, "int8"])
def test_insert_bytes_match_contiguous(cache_dtype):
    """A one-shot prefill parked through page indirection holds byte-
    identical K/V (and scale) rows to the contiguous insert."""
    cfg, ec, ep, params = _engines(cache_dtype=cache_dtype)
    prompt = list(range(1, 20))  # 2 full pages + a 3-row tail
    kv, _ = ec.prefill(params, prompt)
    cc = ec.insert(ec.init_cache(), kv, 1, len(prompt))
    pc = ep.insert(ep.init_cache(), kv, 1, len(prompt))
    names = ["k", "v"] + (["k_scale", "v_scale"] if cache_dtype else [])
    for name in names:
        want = np.asarray(cc[name])[:, 1, :len(prompt)]
        got = paged_kv.slot_rows(pc, ep.paged.tables, 1, len(prompt), name)
        np.testing.assert_array_equal(got, want)
    assert int(np.asarray(pc["lengths"])[1]) == len(prompt)


def test_cow_copy_page_is_byte_exact():
    cfg, ec, ep, params = _engines(cache_dtype="int8")
    kv, _ = ep.prefill(params, list(range(1, 17)))
    cache = ep.insert(ep.init_cache(), kv, 0, 16)
    src = int(ep.paged.tables[0, 1])
    dst = ep.paged.pool.alloc()
    before = {n: np.asarray(cache[n])[:, src].copy()
              for n in ("k", "v", "k_scale", "v_scale")}
    cache = ep._copy_page_jit(cache, src, dst)
    for n, want in before.items():
        got = np.asarray(cache[n])
        np.testing.assert_array_equal(got[:, dst], want)  # copy exact
        np.testing.assert_array_equal(got[:, src], want)  # parent intact


# --------------------------------------------------------------------------- #
# generation equivalence (engine + batcher)
# --------------------------------------------------------------------------- #


_PROMPTS = [list(range(1, 11)), [11, 12, 13],
            [1, 2, 3, 4, 5, 6, 7, 8, 21, 22]]  # 8-token shared prefix


def _generate(engine, params, seed=0, prompts=_PROMPTS, max_new=10,
              **req_kw):
    b = ContinuousBatcher(engine, params, seed=seed)
    res = b.run([Request(uid=f"r{i}", prompt=list(p),
                         max_new_tokens=max_new, **req_kw)
                 for i, p in enumerate(prompts)])
    return {u: r.tokens for u, r in res.items()}, b


@pytest.mark.parametrize("cache_dtype,attend_impl", [
    (None, "dense"), (None, "flash"),
    ("int8", "dense"), ("int8", "flash")])
def test_blocked_decode_generations_match_contiguous(cache_dtype,
                                                     attend_impl):
    """The core pin: paged == contiguous token streams through prefill +
    blocked decode, across cache dtypes and attend kernels, on a batch
    with a shared prefix (so sharing + COW are exercised AND invisible)."""
    cfg, ec, ep, params = _engines(cache_dtype=cache_dtype,
                                   attend_impl=attend_impl,
                                   decode_block_len=4)
    want, _ = _generate(ec, params)
    got, bp = _generate(ep, params)
    assert got == want
    s = bp.stats()
    assert s["prefix_hits"] >= 1 and s["cow_copies"] >= 1


def test_bf16_generations_match_contiguous():
    cfg = make_config(dict(_TINY), tp=1, seq=32, dtype="bfloat16")
    ec = InferenceEngine(cfg, slots=3, max_seq_len=MAX_LEN,
                         kv_layout="contiguous", decode_block_len=4)
    ep = InferenceEngine(cfg, slots=3, max_seq_len=MAX_LEN,
                         kv_layout="paged", kv_page_len=PAGE,
                         decode_block_len=4)
    params = ec.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    want, _ = _generate(ec, params)
    got, _ = _generate(ep, params)
    assert got == want


def test_speculative_verify_generations_match_contiguous():
    """Draft-verify with rollback: the optimistic writes land in pages,
    rejected rows strand beyond the length pointer — and the emitted
    streams still equal the contiguous layout's exactly."""
    cfg, ec, ep, params = _engines(spec_len=3)
    want, bc = _generate(ec, params, max_new=12)
    got, bp = _generate(ep, params, max_new=12)
    assert got == want
    assert bp.draft_proposed > 0  # speculation actually ran


def test_chunked_prefill_generations_match_contiguous():
    """Long prompts (over prefill_chunk) take the chunked path on both
    layouts; the ragged final chunk and the page-scatter writes agree."""
    prompts = [list(range(1, 30)), list(range(1, 30)) + [40, 41]]
    cfg, ec, ep, params = _engines(prefill_chunk=8)
    want, _ = _generate(ec, params, prompts=prompts, max_new=8)
    got, _ = _generate(ep, params, prompts=prompts, max_new=8)
    assert got == want


def test_tp2_generations_match_contiguous(tiny_model_kwargs):
    """tp=2: the pool's kv-head axis is sharded; block tables and the
    allocator are replicated host state — generations must not notice."""
    cfg = make_config(dict(_TINY), tp=2, seq=32)
    ec = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                         kv_layout="contiguous")
    ep = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                         kv_layout="paged", kv_page_len=PAGE)
    params = ec.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    want, _ = _generate(ec, params, prompts=_PROMPTS[:2], max_new=6)
    got, _ = _generate(ep, params, prompts=_PROMPTS[:2], max_new=6)
    assert got == want


def test_eos_and_timeout_slot_recycling_paged():
    """Retired slots (EOS mid-stream) release refcounted pages and the
    recycled slot serves the queue — more requests than slots."""
    cfg, ec, ep, params = _engines(slots=2)
    prompts = [[i + 1, i + 2, i + 3] for i in range(5)]
    want, _ = _generate(ec, params, prompts=prompts, max_new=6, eos_id=5)
    got, bp = _generate(ep, params, prompts=prompts, max_new=6, eos_id=5)
    assert got == want
    assert bp.counters["completed"] == 5
    # every slot's pages released; only radix-cached prefix pages remain
    p = ep.paged
    assert np.all(p.tables == NULL_PAGE)
    assert p.pool.live_count == p.radix.evictable_count()


# --------------------------------------------------------------------------- #
# prefix sharing: capacity scales with unique tokens; COW is invisible
# --------------------------------------------------------------------------- #


def test_shared_prefix_scales_with_unique_tokens():
    """N requests behind one long system prompt: prefill dispatches and
    live pages track the UNIQUE tokens, not N x prompt length."""
    system = list(range(1, 41))  # 5 full pages
    prompts = [system + [50 + i] for i in range(4)]
    cfg, ec, ep, params = _engines(slots=4, prefill_chunk=8)
    want, bc = _generate(ec, params, prompts=prompts, max_new=4)
    got, bp = _generate(ep, params, prompts=prompts, max_new=4)
    assert got == want
    # contiguous prefills the full prompt 4 times (5+1 chunks each);
    # paged prefills it once and then only suffixes
    assert bc.prefill_dispatches == 4 * 6
    assert bp.prefill_dispatches < bc.prefill_dispatches / 2
    s = bp.stats()
    assert s["prefix_hits"] == 3
    # 3 followers x 40 cached tokens = 120 of 164 prompt tokens served
    # from the cache
    assert s["prefix_cached_tokens"] == 3 * len(system)
    assert s["prefix_hit_rate"] > 0.7
    # capacity: unique tokens ~ 41 + 3 extra tails, nowhere near 4x44
    unique_pages_bound = ep.paged.pages_for(len(system) + 8) + 2 * 4
    assert s["kv_pages_live"] <= unique_pages_bound
    assert s["kv_pages_live"] < 4 * ep.paged.pages_for(len(prompts[0]))


def test_cow_forked_generations_equal_independent_and_preserve_bytes():
    """The COW acceptance pin: requests forking from a shared prefix
    generate exactly what fully-independent requests would, and the
    radix-cached pages' bytes are unchanged after all of them finish."""
    base = list(range(1, 20))  # forks mid-page (19 = 2 pages + 3 rows)
    forks = [base + [30], base + [31], base[:11] + [32]]
    cfg, ec, ep, params = _engines(slots=1)  # serialize: maximal reuse
    want, _ = _generate(ec, params, prompts=forks, max_new=6)

    b = ContinuousBatcher(ep, params, seed=0)
    res = b.run([Request(uid="r0", prompt=forks[0], max_new_tokens=6)])
    # snapshot every radix-held page AFTER the seeding request finished
    frozen = {}
    for node in ep.paged.radix.root.children.values():
        stack = [node]
        while stack:
            n = stack.pop()
            frozen[n.page_id] = {
                leaf: np.asarray(b._cache[leaf])[:, n.page_id].copy()
                for leaf in ("k", "v")}
            stack.extend(n.children.values())
    assert frozen  # the prompt registered
    res.update(b.run([Request(uid="r1", prompt=forks[1], max_new_tokens=6),
                      Request(uid="r2", prompt=forks[2],
                              max_new_tokens=6)]))
    got = {u: r.tokens for u, r in res.items()}
    assert got == want  # sharing + COW invisible in the output
    assert ep.paged.cow_copies >= 1  # and COW actually fired
    for pid, leaves in frozen.items():
        for leaf, before in leaves.items():
            np.testing.assert_array_equal(
                np.asarray(b._cache[leaf])[:, pid], before,
                err_msg=f"shared page {pid} leaf {leaf} mutated")


def test_prefix_cache_off_still_pages():
    """prefix_cache=False: pure paging — no sharing, no trie retention,
    generations still identical."""
    cfg = _cfg(prefix_cache=False)
    ec = InferenceEngine(cfg, slots=3, max_seq_len=MAX_LEN,
                         kv_layout="contiguous")
    ep = InferenceEngine(cfg, slots=3, max_seq_len=MAX_LEN,
                         kv_layout="paged", kv_page_len=PAGE)
    params = ec.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    want, _ = _generate(ec, params)
    got, bp = _generate(ep, params)
    assert got == want
    s = bp.stats()
    assert s["prefix_hits"] == 0 and s["kv_pages_live"] == 0  # all freed


# --------------------------------------------------------------------------- #
# admission: page pricing, shed-not-corrupt, serve 429
# --------------------------------------------------------------------------- #


def test_out_of_pages_sheds_and_spares_live_slots():
    """A pool sized for ~one request: the oversized request sheds at the
    door, the waiting request is admitted only after the live one frees
    its pages — and the live slot's stream is untouched either way."""
    cfg = _cfg()
    ec = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                         kv_layout="contiguous")
    # 5 usable pages = 40 rows: request a (commitment 16 tokens = 2
    # pages) and request b (commitment 2 pages) fit only serially once
    # a's radix-retained pages are accounted
    ep = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                         kv_layout="paged", kv_page_len=PAGE,
                         kv_num_pages=6)
    params = ec.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    reqs = [Request(uid="a", prompt=list(range(1, 9)), max_new_tokens=8),
            # needs ceil(64/8) = 8 pages > 5 usable: can NEVER fit
            Request(uid="big", prompt=list(range(1, 33)),
                    max_new_tokens=64),
            Request(uid="b", prompt=[41, 42, 43], max_new_tokens=8)]
    want, _ = _generate(ec, params, prompts=[reqs[0].prompt],
                        max_new=8)
    b = ContinuousBatcher(ep, params, seed=0)
    res = b.run(reqs)
    assert res["big"].finish_reason == "shed"
    assert res["a"].finish_reason == "length"
    assert res["a"].tokens == want["r0"]  # live slot never corrupted
    assert res["b"].finish_reason == "length" and len(res["b"].tokens) == 8
    assert b.counters["shed"] == 1 and b.counters["completed"] == 2


def test_serve_429_reflects_pool_pressure():
    """The HTTP admission path prices in pages: a request beyond the
    pool's capacity is a 429 whose Retry-After scales with the page
    deficit, and /statz surfaces the pool + prefix stats."""
    from picotron_tpu.tools import serve

    cfg = _cfg()
    engine = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                             kv_layout="paged", kv_page_len=PAGE,
                             kv_num_pages=5)  # 4 usable pages
    params = engine.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    srv = serve.Server(engine, params, port=0,
                       log=lambda *a, **k: None)
    srv.start()
    try:
        port = srv.port
        # commitment 8 + 56-cap -> 64 tokens = 8 pages > 4 usable: 429
        st, body = serve._post(port, {"prompt": list(range(1, 9)),
                                      "max_new_tokens": 100})
        assert st == 429 and body["shed"]
        # a mildly-over request backs off less than a hugely-over one
        import http.client

        def retry_after(spec):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            conn.request("POST", "/generate", serve.json.dumps(spec),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 429
            ra = int(resp.getheader("Retry-After"))
            resp.read()
            conn.close()
            return ra
        mild = retry_after({"prompt": list(range(1, 9)),
                            "max_new_tokens": 33})  # 6 pages, deficit 2
        huge = retry_after({"prompt": list(range(1, 9)),
                            "max_new_tokens": 100})  # 8 pages, deficit 4
        assert 1 <= mild <= huge
        # a fitting request serves; /statz carries the pool fields
        st, body = serve._post(port, {"prompt": [1, 2, 3],
                                      "max_new_tokens": 4})
        assert st == 200 and body["finish_reason"] == "length"
        st, stats = serve._get(port, "/statz")
        assert stats["rejected"]["page_budget"] == 3
        assert stats["kv_layout"] == "paged"
        assert stats["kv_pages_total"] == 4
        assert 0.0 <= stats["kv_pool_utilization"] <= 1.0
        assert "prefix_hit_rate" in stats and "cow_copies" in stats
    finally:
        srv.drain_and_join(timeout=60)


def test_kv_layout_validated():
    cfg = _cfg()
    with pytest.raises(ValueError, match="kv_layout"):
        InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                        kv_layout="vmem")
    with pytest.raises(ValueError, match="kv_page_len"):
        InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                        kv_layout="paged", kv_page_len=12)
    from picotron_tpu.config import Config

    raw = cfg.to_dict()
    raw["inference"]["kv_layout"] = "vmem"
    with pytest.raises(ValueError, match="kv_layout"):
        Config.from_dict(raw)
    raw["inference"]["kv_layout"] = "paged"
    raw["inference"]["kv_page_len"] = 12
    with pytest.raises(ValueError, match="kv_page_len"):
        Config.from_dict(raw)


def test_cache_lost_rebuild_resets_pool():
    """The batcher's cache-lost path rebuilds via engine.init_cache —
    which must reset the allocator too, or the fresh zeroed pool would
    disagree with stale refcounts/tables."""
    cfg, ec, ep, params = _engines()
    _generate(ep, params, prompts=[_PROMPTS[0]], max_new=4)
    assert ep.paged.pool.live_count > 0  # radix retained the prompt
    cache = ep.init_cache()
    p = ep.paged
    assert p.pool.free_count == p.pool.usable_pages
    assert np.all(p.tables == NULL_PAGE) and np.all(p.host_len == 0)
    assert p.radix.evictable_count() == 0
    del cache
