"""Elastic fleet controller suite (ISSUE 17; docs/SERVING.md "Elastic
fleet").

Unit layers first — the decision ladder (replace before grow before
drain), watermark hysteresis and cooloff, the budget-gated replacement
ladder, and the stale-is-not-dead scrape discipline — driven with fake
launchers/admins and a fake clock so every transition is exact; then the
admin-plane integration: the real HTTP ``RouterAdmin`` against a live
``RouterServer`` (register / 409-idempotent / deregister / 404), and the
``DirectRouterAdmin`` in-process seam. The full chaos acceptance
(SIGKILL-under-load replacement with bit-identical replay, spike-driven
grow, zero-loss drain) is ``make fleet-chaos-smoke``.
"""

import threading
import time

import pytest

from picotron_tpu.config import FleetConfig, RouterConfig
from picotron_tpu.resilience.chaos import FleetChaos
from picotron_tpu.tools.fleet import (
    DirectRouterAdmin,
    FleetController,
    RouterAdmin,
    _req_json,
)
from picotron_tpu.tools.router import Router, RouterServer


# --------------------------------------------------------------------------- #
# fakes
# --------------------------------------------------------------------------- #


class FakeHandle:
    """A worker handle whose liveness the test scripts directly. The
    port is unroutable-fast (connection refused), so controller code
    paths that tolerate a dead listener get exercised for real."""

    def __init__(self):
        self.host = "127.0.0.1"
        self.port = 1
        self.live = True
        self.calls = []

    def alive(self):
        return self.live

    def kill(self):
        self.calls.append("kill")
        self.live = False

    def terminate(self):
        self.calls.append("terminate")

    def wait(self, timeout=None):
        self.calls.append("wait")
        self.live = False
        return True


class FakeLauncher:
    def __init__(self, fail_first=0):
        self.fail_first = fail_first
        self.launched = []
        self.handles = {}

    def launch(self, name, role):
        if self.fail_first > 0:
            self.fail_first -= 1
            raise RuntimeError("launch quota")
        h = FakeHandle()
        self.launched.append((name, role))
        self.handles[name] = h
        return h


class FakeAdmin:
    def __init__(self):
        self.registered = []
        self.deregistered = []

    def register(self, host, port):
        name = f"{host}:{port}"
        self.registered.append(name)
        return name

    def deregister(self, name):
        self.deregistered.append(name)


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _fcfg(**kw):
    base = dict(scrape_interval_s=0.01, scrape_timeout_s=0.2,
                hysteresis=2, cooloff_s=10.0, queue_high=1.0,
                queue_low=0.5, pool_high=0.9, pool_low=0.3,
                min_workers=1, max_workers=4, max_replaces=2,
                replace_backoff_s=0.5, replace_backoff_max_s=4.0,
                healthy_reset_s=1e9, launch_attempts=1,
                drain_timeout_s=5.0, export_prefixes=False)
    base.update(kw)
    return FleetConfig(**base)


def _ctl(n=2, clock=None, chaos=None, **cfg_kw):
    """A controller with ``n`` workers already up, tick-driven by the
    test (no control thread started)."""
    clock = clock or Clock()
    launcher = FakeLauncher()
    admin = FakeAdmin()
    ctl = FleetController(_fcfg(**cfg_kw), launcher, admin, chaos=chaos,
                          log=lambda *a, **k: None, clock=clock)
    for _ in range(n):
        ctl._spawn_launch("both", "bootstrap", clock())
    _join_actuation(ctl)
    assert len(_up(ctl)) == n
    return ctl, launcher, admin, clock


def _join_actuation(ctl, timeout=10.0):
    deadline = time.monotonic() + timeout
    for t in list(ctl._threads):
        t.join(timeout=max(0.01, deadline - time.monotonic()))
        assert not t.is_alive(), f"actuation thread {t.name} wedged"


def _up(ctl):
    with ctl._mu:
        return [w for w in ctl.workers.values() if w.state == "up"]


def _feed(ctl, **scrape):
    """Script every up worker's next scrape reading."""
    vals = {"queue": 0.0, "pool": 0.0, "active": 0.0, "ttft_p95": 0.0,
            "draining": False, **scrape}
    ctl._scrape = lambda w: ("ok", dict(vals))


# --------------------------------------------------------------------------- #
# config
# --------------------------------------------------------------------------- #


def test_fleet_config_validation():
    FleetConfig().validate()  # defaults are a valid config
    for field, bad in [("hysteresis", 0), ("min_workers", 0),
                      ("max_workers", 0), ("scrape_interval_s", 0.0),
                      ("queue_high", -1.0), ("max_replaces", -1),
                      ("launch_attempts", 0)]:
        cfg = FleetConfig(**{field: bad})
        with pytest.raises(ValueError, match=f"fleet.{field}"):
            cfg.validate()
    with pytest.raises(ValueError, match="fleet.queue_low"):
        FleetConfig(queue_high=1.0, queue_low=2.0).validate()
    with pytest.raises(ValueError, match="fleet.max_workers"):
        FleetConfig(min_workers=4, max_workers=2).validate()


def test_fleet_config_from_dict_filters_and_validates():
    cfg = FleetConfig.from_dict({"queue_high": 12.0, "not_a_field": 1})
    assert cfg.queue_high == 12.0
    with pytest.raises(ValueError, match="fleet.hysteresis"):
        FleetConfig.from_dict({"hysteresis": 0})


# --------------------------------------------------------------------------- #
# the decision ladder
# --------------------------------------------------------------------------- #


def test_grow_needs_sustained_breach_not_one_tick():
    ctl, launcher, admin, clk = _ctl(2)
    _feed(ctl, queue=5.0)
    ctl.tick()  # one high tick: streak 1 < hysteresis 2
    assert ctl.decisions().get("grow", 0) == 0
    _feed(ctl, queue=0.0)
    ctl.tick()  # breach not sustained: streak resets
    _feed(ctl, queue=5.0)
    ctl.tick()
    assert ctl.decisions().get("grow", 0) == 0
    ctl.tick()  # second consecutive high tick: grow
    _join_actuation(ctl)
    assert ctl.decisions().get("grow", 0) == 1
    assert len(_up(ctl)) == 3
    assert len(admin.registered) == 3


def test_grow_respects_cooloff_and_max_workers():
    ctl, launcher, admin, clk = _ctl(2, max_workers=4)
    _feed(ctl, queue=5.0)
    ctl.tick()
    ctl.tick()
    _join_actuation(ctl)
    assert len(_up(ctl)) == 3
    # still breaching, but inside the cooloff window: no second grow
    ctl.tick()
    ctl.tick()
    ctl.tick()
    assert ctl.decisions().get("grow", 0) == 1
    clk.t += ctl.cfg.cooloff_s  # cooloff elapses -> the ladder re-arms
    ctl.tick()
    ctl.tick()
    _join_actuation(ctl)
    assert ctl.decisions().get("grow", 0) == 2
    assert len(_up(ctl)) == 4
    # at max_workers: sustained breach no longer grows
    clk.t += ctl.cfg.cooloff_s
    for _ in range(4):
        ctl.tick()
    assert ctl.decisions().get("grow", 0) == 2


def test_dead_worker_replaced_without_waiting_for_cooloff():
    """Rung 1 is budget-gated, never cooloff-gated: capacity loss right
    after a scale decision must not wait out the cooloff window."""
    ctl, launcher, admin, clk = _ctl(2)
    _feed(ctl, queue=5.0)
    ctl.tick()
    ctl.tick()  # grow fires -> cooloff stamp is NOW
    _join_actuation(ctl)
    victim = _up(ctl)[0]
    victim.handle.live = False  # SIGKILL flavor: process gone
    ctl.tick()  # same instant as the grow: replace still decided
    assert ctl.decisions().get("replace", 0) == 1
    assert admin.deregistered == [victim.router_name]
    clk.t += ctl.cfg.replace_backoff_s  # the ladder's first delay
    ctl.tick()
    _join_actuation(ctl)
    assert len(_up(ctl)) == 3  # replacement landed (2 + the grow)


def test_replace_budget_exhaustion_stops_the_crash_loop():
    ctl, launcher, admin, clk = _ctl(1, max_replaces=1, min_workers=1)
    _up(ctl)[0].handle.live = False
    ctl.tick()
    assert ctl.decisions().get("replace", 0) == 1
    clk.t += ctl.cfg.replace_backoff_s
    ctl.tick()
    _join_actuation(ctl)
    assert len(_up(ctl)) == 1
    # the replacement dies instantly: the budget (1) is spent
    _up(ctl)[0].handle.live = False
    ctl.tick()
    assert ctl.decisions().get("replace_exhausted", 0) == 1
    clk.t += 60.0
    for _ in range(3):
        ctl.tick()
    _join_actuation(ctl)
    assert len(_up(ctl)) == 0  # no relaunch storm past the budget


def test_failed_launch_walks_the_same_budget_ladder():
    clock = Clock()
    launcher = FakeLauncher(fail_first=1)
    admin = FakeAdmin()
    ctl = FleetController(_fcfg(), launcher, admin,
                          log=lambda *a, **k: None, clock=clock)
    ctl._spawn_launch("both", "bootstrap", clock())
    _join_actuation(ctl)
    assert not _up(ctl)  # launch failed -> worker parked as "failed"
    ctl.tick()  # rung 1 reaps it and schedules a budgeted retry
    assert ctl.decisions().get("replace", 0) == 1
    clock.t += ctl.cfg.replace_backoff_s
    ctl.tick()
    _join_actuation(ctl)
    assert len(_up(ctl)) == 1  # the retry (launcher now succeeds) landed


def test_scrape_stall_is_stale_never_dead():
    """A wedged scrape plane (FleetChaos.stall_scrape) must not read as
    worker death — no replacement storm off a monitoring failure."""
    chaos = FleetChaos()
    ctl, launcher, admin, clk = _ctl(2, chaos=chaos, hysteresis=2)
    w = _up(ctl)[0]
    chaos.stall_scrape(w.name)
    # the OTHER worker scrapes "down" (port 1 refuses) and dies after
    # hysteresis ticks; the STALLED one must survive indefinitely
    other = _up(ctl)[1]
    for _ in range(6):
        ctl.tick()
    with ctl._mu:
        assert ctl.workers[w.name].state == "up"
        assert w.down_fails == 0
        assert other.name not in ctl.workers  # down IS death...
    assert ctl.decisions().get("replace", 0) == 1  # ...for the other


def test_drain_picks_least_loaded_and_respects_min_workers():
    ctl, launcher, admin, clk = _ctl(3, min_workers=2)
    # script per-worker scrapes: w3 is the idle one
    loads = {w.name: 2.0 for w in _up(ctl)}
    idle = _up(ctl)[2]
    loads[idle.name] = 0.0
    ctl._scrape = lambda w: ("ok", {
        "queue": loads[w.name] * 0.1, "pool": 0.0,
        "active": loads[w.name], "ttft_p95": 0.0, "draining": False})
    ctl.tick()
    ctl.tick()
    _join_actuation(ctl)
    assert ctl.decisions().get("drain", 0) == 1
    with ctl._mu:
        assert idle.name not in ctl.workers  # the idle one went
    assert idle.handle.calls[0] == "terminate"  # stop armed before wait
    assert "wait" in idle.handle.calls
    assert admin.deregistered == [idle.router_name]
    # at min_workers now: sustained idle never drains below the floor
    clk.t += ctl.cfg.cooloff_s
    for _ in range(4):
        ctl.tick()
    assert ctl.decisions().get("drain", 0) == 1
    assert len(_up(ctl)) == 2


def test_stop_with_drain_workers_tears_down_and_deregisters():
    ctl, launcher, admin, clk = _ctl(2)
    ctl.stop(drain_workers=True)
    with ctl._mu:
        assert not ctl.workers
    assert len(admin.deregistered) == 2


# --------------------------------------------------------------------------- #
# admin plane
# --------------------------------------------------------------------------- #


def test_direct_router_admin_is_idempotent():
    r = Router([], RouterConfig(), allow_empty=True,
               log=lambda *a, **k: None)
    admin = DirectRouterAdmin(r)
    name = admin.register("10.0.0.9", 809)
    assert name in r.replicas
    assert admin.register("10.0.0.9", 809) == name  # duplicate: no-op
    assert len(r.replicas) == 1
    admin.deregister(name)
    assert name not in r.replicas
    admin.deregister(name)  # already gone: no-op


def test_router_admin_http_register_409_deregister_404():
    rs = RouterServer([], RouterConfig(probe_interval_s=0.05,
                                       probe_timeout_s=0.2),
                      allow_empty=True, log=lambda *a, **k: None)
    rs.start()
    try:
        admin = RouterAdmin("127.0.0.1", rs.port)
        name = admin.register("10.0.0.7", 807)
        assert name == "10.0.0.7:807" and name in rs.router.replicas
        assert admin.register("10.0.0.7", 807) == name  # 409 tolerated
        assert set(admin.replicas()) == {name}
        # raw-status checks under the tolerant client
        st, body = _req_json("POST", "127.0.0.1", rs.port, "/replicas",
                             {"replica": "10.0.0.7:807"})
        assert st == 409
        st, body = _req_json("POST", "127.0.0.1", rs.port, "/replicas",
                             {"replica": "no-port"})
        assert st == 400
        st, body = _req_json("DELETE", "127.0.0.1", rs.port,
                             "/replicas/never-was")
        assert st == 404
        admin.deregister(name)
        assert name not in rs.router.replicas
        admin.deregister(name)  # 404 tolerated: already the goal state
    finally:
        rs.stop()
