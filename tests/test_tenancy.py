"""Multi-tenant serving (ISSUE 16): the segmented multi-LoRA matmul, the
tenant registry/adapter pack, and the tenancy plumbing through the
batcher, the serve front end, and the KV reuse planes.

The acceptance surface:

- kernel-level parity: the Pallas segmented-gather kernel (interpret
  mode — the CPU tier-1 gate) and the XLA gather-einsum fallback are
  both exact against the per-row reference ``(x[b] @ a[ids[b]]) @
  b[ids[b]]``, and null-adapter rows (slot 0) produce an EXACTLY zero
  residual;
- engine-level equivalence: a MIXED batch (3 adapters + base-only rows
  in one dispatch) produces, per tenant, greedy tokens bit-identical to
  a solo adapter-less engine fed the merged-weight ``W + BA`` reference
  — across decode_block / speculative verify / chunked prefill,
  dense AND flash attends, contiguous AND paged KV layouts, bf16-dense
  AND int8 bases, tp=1 and tp=2 (the int8 oracle merges into the
  fake-quant dense twin, mirroring the weight-parity gate);
- the null-adapter identity: an engine CARRYING a live adapter pack
  serves base-only rows bit-identical to an engine built without one;
- isolation: tenant names salt the radix prefix domains — identical
  prompts under different tenants never share pages;
- scheduling: priority classes admit highest-first and shed
  lowest-first under budget pressure, TTFT-SLO requests jump their
  class's queue, and a TPOT-SLO slot over budget halves its draft width
  (``slo_cap``);
- the HTTP surface: unknown tenants 400 (never a silent base fallback),
  ``/tenants`` hot add/remove, per-tenant quota 429s naming the tripped
  budget.

``make tenant-smoke`` runs the CLI gate (generate.py
--check-adapter-parity) + the mixed-tenant bench on top of this file.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.inference import ContinuousBatcher, InferenceEngine, Request
from picotron_tpu.inference import tenancy
from picotron_tpu.inference.paged_kv import RadixCache
from picotron_tpu.config import SpecControllerConfig
from picotron_tpu.inference.speculative import SpecController
from picotron_tpu.models import llama
from picotron_tpu.obs.metrics import MetricsRegistry
from picotron_tpu.ops.pallas import lora_matmul as lm

MAX_LEN = 96


# --------------------------------------------------------------------------- #
# kernel parity (direct calls)
# --------------------------------------------------------------------------- #


def _reference(x, a, b, ids):
    out = np.zeros(x.shape[:2] + (b.shape[2],), np.float32)
    xf = np.asarray(x, np.float32)
    for i, t in enumerate(ids):
        out[i] = (xf[i] @ np.asarray(a)[t]) @ np.asarray(b)[t]
    return out


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("B,S,K,N,r", [(4, 1, 32, 48, 8), (3, 5, 64, 40, 4),
                                       (2, 16, 48, 64, 16)])
def test_lora_matmul_impls_match_reference(B, S, K, N, r, dtype):
    """Pallas (interpret) and the XLA fallback against the per-row
    gather reference: decode (S=1), verify (small S), prefill-chunk
    (larger S) shapes, repeated and out-of-order ids, a null row in
    every batch."""
    rng = np.random.default_rng(0)
    T = 4
    x = jnp.asarray(rng.normal(size=(B, S, K)).astype(np.float32)).astype(
        jnp.dtype(dtype))
    a = jnp.asarray(rng.normal(size=(T, K, r)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(T, r, N)).astype(np.float32))
    a = a.at[0].set(0.0)  # slot 0 = the null adapter
    b = b.at[0].set(0.0)
    ids = np.array([0, 2, 1, 2][:B], np.int32)
    ref = _reference(x, a, b, ids)
    got_p = np.asarray(lm.lora_matmul(x, a, b, ids, interpret=True))
    got_x = np.asarray(lm.lora_matmul(x, a, b, ids, impl="xla"))
    tol = 2e-5 if dtype == "float32" else 2e-2
    np.testing.assert_allclose(got_p, ref, rtol=tol, atol=tol)
    np.testing.assert_allclose(got_x, ref, rtol=tol, atol=tol)
    assert got_p.dtype == got_x.dtype == np.float32
    # the null row's residual is EXACTLY zero on both impls — base-only
    # rows riding a mixed dispatch bypass bit-exactly
    np.testing.assert_array_equal(got_p[0], 0.0)
    np.testing.assert_array_equal(got_x[0], 0.0)


def test_lora_matmul_validates():
    x = jnp.zeros((2, 1, 8))
    a = jnp.zeros((3, 8, 4))
    b = jnp.zeros((3, 4, 8))
    with pytest.raises(ValueError, match=r"\[B, S, in\]"):
        lm.lora_matmul(jnp.zeros((2, 8)), a, b, [0, 0])
    with pytest.raises(ValueError, match="disagree"):
        lm.lora_matmul(x, a, jnp.zeros((3, 5, 8)), [0, 0])
    with pytest.raises(ValueError, match="impl"):
        lm.lora_matmul(x, a, b, [0, 0], impl="dense")


# --------------------------------------------------------------------------- #
# AdapterPack + TenantRegistry (host side, no engine)
# --------------------------------------------------------------------------- #


def test_adapter_pack_capacity_version_and_null_slot(tiny_model_kwargs):
    cfg = make_config(tiny_model_kwargs)
    pack = tenancy.AdapterPack(cfg.model, slots=4, rank=8)
    v0 = pack.version
    d0 = pack.device_leaves()
    assert pack.device_leaves() is d0  # cached until a mutation
    leaves = pack.random_leaves(4, seed=1)  # rank 4 < capacity 8
    pack.set_slot(1, leaves)
    assert pack.version == v0 + 1
    d1 = pack.device_leaves()
    assert d1 is not d0
    # shapes are capacity-static: rank-4 weights land in the first 4
    # columns, the rest stay zero
    a = np.asarray(d1["wq"]["a"])
    assert a.shape[-1] == 8
    assert np.any(a[:, 1, :, :4])
    np.testing.assert_array_equal(a[:, 1, :, 4:], 0.0)
    np.testing.assert_array_equal(a[:, 0], 0.0)  # null slot stays null
    pack.clear_slot(1)
    np.testing.assert_array_equal(
        np.asarray(pack.device_leaves()["wq"]["a"][:, 1]), 0.0)
    with pytest.raises(ValueError, match="slot 0"):
        pack.set_slot(0, leaves)
    with pytest.raises(ValueError, match="outside"):
        pack.random_leaves(9, seed=0)  # rank above capacity
    with pytest.raises(ValueError, match="adapter_slots"):
        tenancy.AdapterPack(cfg.model, slots=1)
    # bytes_per_token: every layer streams its [in, R] + [R, out] fp32
    # pair for each projection leaf
    L = cfg.model.num_hidden_layers
    want = 4 * L * sum((din + dout) * 8
                       for din, dout in tenancy.adapter_dims(
                           cfg.model).values())
    assert pack.bytes_per_token() == want


def test_tenant_validation_registry_and_manifest(tiny_model_kwargs, tmp_path):
    cfg = make_config(tiny_model_kwargs)
    with pytest.raises(ValueError, match="name"):
        tenancy.Tenant(name="a/b")
    with pytest.raises(ValueError, match="priority"):
        tenancy.Tenant(name="x", priority=-1)
    with pytest.raises(ValueError, match="unknown tenant field"):
        tenancy.Tenant.from_dict({"name": "x", "color": "red"})

    pack = tenancy.AdapterPack(cfg.model, slots=3, rank=4)
    reg = tenancy.TenantRegistry(pack)
    assert reg.resolve(None)[1] == 0  # implicit base -> null slot
    assert reg.resolve("")[0].name == tenancy.BASE_TENANT
    with pytest.raises(KeyError, match="unknown tenant"):
        reg.resolve("nope")
    s1 = reg.add(tenancy.Tenant(name="acme", adapter_rank=2, priority=2))
    s2 = reg.add(tenancy.Tenant(name="bulk", priority=0))  # rank 0
    assert s1 == 1 and s2 == 0  # rank-0 tenants share the null slot
    with pytest.raises(ValueError, match="already exists"):
        reg.add(tenancy.Tenant(name="acme"))
    reg.add(tenancy.Tenant(name="beta", adapter_rank=4))
    with pytest.raises(ValueError, match="full"):
        reg.add(tenancy.Tenant(name="gamma", adapter_rank=1))
    reg.remove("beta")  # frees slot 2 and zeroes it
    np.testing.assert_array_equal(
        np.asarray(pack.device_leaves()["wq"]["a"][:, 2]), 0.0)
    assert reg.add(tenancy.Tenant(name="gamma", adapter_rank=1)) == 2
    with pytest.raises(KeyError):
        reg.remove("never-was")

    # manifest load; a defined "base" entry governs anonymous traffic
    mf = tmp_path / "tenants.json"
    mf.write_text(json.dumps({"tenants": [
        {"name": "base", "priority": 0, "max_tokens": 7},
        {"name": "acme", "priority": 2, "adapter_rank": 2,
         "adapter_seed": 7, "ttft_slo_ms": 300.0},
    ]}))
    reg2 = tenancy.TenantRegistry.from_manifest(
        str(mf), tenancy.AdapterPack(cfg.model, slots=3, rank=4))
    t, slot = reg2.resolve(None)
    assert t.max_tokens == 7 and slot == 0
    assert reg2.resolve("acme")[0].ttft_slo_ms == 300.0
    with pytest.raises(ValueError, match="tenants"):
        bad = tmp_path / "bad.json"
        bad.write_text("[]")
        tenancy.TenantRegistry.from_manifest(str(bad))


# --------------------------------------------------------------------------- #
# isolation: per-tenant radix domains
# --------------------------------------------------------------------------- #


def test_radix_cache_salt_isolation():
    """Identical token chunks under different salts occupy separate trie
    domains; the default '' salt is the pre-tenancy behavior."""
    from picotron_tpu.inference.paged_kv import PagePool

    pool = PagePool(num_pages=16)
    r = RadixCache(page_len=4, pool=pool)
    ids = list(range(1, 13))  # three full pages
    pa = [pool.alloc() for _ in range(3)]
    assert r.insert(ids, lambda i: pa[i], salt="acme") == 3
    pages, matched = r.match(ids, salt="acme")
    assert matched == 12 and pages == pa
    for other in ("", "bulk"):
        pages, matched = r.match(ids, salt=other)
        assert matched == 0 and pages == []
    # same chunks under another salt take their OWN nodes AND pages
    pb = [pool.alloc() for _ in range(3)]
    assert r.insert(ids, lambda i: pb[i], salt="bulk") == 3
    assert r.match(ids, salt="bulk")[0] == pb
    assert r.match(ids, salt="acme")[0] == pa
    assert not set(pa) & set(pb)  # no cross-tenant page sharing


# --------------------------------------------------------------------------- #
# scheduling: priority classes, SLO-aware admission, spec slo_cap
# --------------------------------------------------------------------------- #


def _bare_batcher(tiny_model_kwargs):
    """A batcher whose queue/shed logic is exercised WITHOUT dispatching
    (engine construction is cheap; compilation happens at dispatch)."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    eng = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
    return ContinuousBatcher(eng, params=None)


def test_pick_priority_fifo_and_ttft_jump(tiny_model_kwargs):
    b = _bare_batcher(tiny_model_kwargs)
    for r in (Request("lo", [1], priority=0),
              Request("a", [1], priority=1),
              Request("b", [1], priority=1),
              Request("hi", [1], priority=2),
              Request("slo", [1], priority=1, ttft_slo_ms=100.0)):
        b._pending.append(r)
    assert b._pending[b._pick()].uid == "hi"  # highest class first
    b._pending = type(b._pending)(
        r for r in b._pending if r.uid != "hi")
    # within class 1: the TTFT-SLO request jumps its best-effort peers
    assert b._pending[b._pick()].uid == "slo"


def test_shed_lower_priority_frees_lowest_class_first(tiny_model_kwargs):
    b = _bare_batcher(tiny_model_kwargs)
    reqs = [Request("lo1", [1, 2], max_new_tokens=10, priority=0),
            Request("lo2", [1, 2], max_new_tokens=10, priority=0),
            Request("mid", [1, 2], max_new_tokens=10, priority=1)]
    for r in reqs:
        b._pending.append(r)
    per = b.commitment(reqs[0])
    # demand one request's worth: only the NEWEST class-0 request sheds
    freed_t, _ = b.shed_lower_priority(2, tokens=per)
    assert freed_t == per
    assert [r.uid for r in b._pending] == ["lo1", "mid"]
    shed = b.take_results()
    assert list(shed) == ["lo2"] and shed["lo2"].finish_reason == "shed"
    # a class-1 arrival must NOT shed its own class
    assert b.shed_lower_priority(1, tokens=10 * per)[0] == per
    assert [r.uid for r in b._pending] == ["mid"]
    # tenant load prices queued + in-flight work per tenant
    b._pending.append(Request("t1", [1, 2], max_new_tokens=10,
                              tenant="acme"))
    assert b.tenant_token_load("acme") == per
    assert b.tenant_token_load("other") == 0


def test_spec_controller_slo_cap():
    """A slot whose measured dispatch cadence misses its TPOT budget
    halves its draft width immediately (decision 'slo_cap'); without an
    SLO the same latencies change nothing."""
    reg = MetricsRegistry()
    h = reg.histogram("picotron_dispatch_seconds",
                      "dispatch wall time incl. host sync, by kind",
                      kind="verify")
    for _ in range(8):
        h.observe(0.05)  # 50ms verify cadence on the record
    cfg = SpecControllerConfig(enabled=True, window=64, hysteresis=2,
                               latency_min_samples=4)
    c = SpecController(cfg, reg, slots=1, max_spec_len=8, block_len=8)
    c.reset(0)  # no SLO: full optimistic draft
    assert int(c.lens()[0]) == 8
    c.after_round(0)
    assert int(c.lens()[0]) == 8  # no SLO -> no cap
    c.reset(0, tpot_slo_s=0.010)  # 10ms budget vs 50ms measured
    assert int(c.lens()[0]) == 1  # starts narrow: cadence already misses
    c.reset(0, tpot_slo_s=0.500)  # roomy budget: optimistic start holds
    assert int(c.lens()[0]) == 8
    c._slo[0] = 0.010  # budget tightens mid-flight
    c.after_round(0)
    assert int(c.lens()[0]) == 4  # halved, not re-evaluated by accept
    assert c.decisions.get("slo_cap") == 1


# --------------------------------------------------------------------------- #
# engine-level equivalence: the mixed batch vs solo merged references
# --------------------------------------------------------------------------- #

N_TENANTS = 3
RANK = 4
SCALE = 0.5  # large enough to steer greedy argmax on the tiny model


def _pack_and_leaves(cfg):
    pack = tenancy.AdapterPack(cfg.model, slots=N_TENANTS + 1, rank=RANK)
    leaves = {}
    for t in range(1, N_TENANTS + 1):
        leaves[t] = pack.random_leaves(RANK, seed=t, scale=SCALE)
        pack.set_slot(t, leaves[t])
    return pack, leaves


def _params(cfg, seed=0):
    return jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(seed))


def _prompts():
    return {slot: [(7 * slot + 3 * i) % 199 + 1 for i in range(8)]
            for slot in range(N_TENANTS + 1)}


def _run_mixed(eng, params, prompts, max_new=10, **req_kw):
    reqs = [Request(uid=f"t{slot}", prompt=list(p), max_new_tokens=max_new,
                    adapter_slot=slot,
                    tenant=f"tenant{slot}" if slot else "", **req_kw)
            for slot, p in prompts.items()]
    return ContinuousBatcher(eng, params, seed=0).run(reqs)


@pytest.mark.parametrize("attend_impl,kv_layout,tp", [
    ("dense", "contiguous", 1),
    ("dense", "paged", 1),
    ("flash", "contiguous", 1),
    ("flash", "paged", 2),
])
def test_mixed_batch_matches_merged_refs(tiny_model_kwargs, attend_impl,
                                         kv_layout, tp):
    """3 adapters + a base-only row in ONE continuous batch: each row's
    greedy tokens are bit-identical to a solo adapter-less engine fed
    that tenant's merged-weight (W + BA) tree — across attend kernels,
    KV layouts, and a tp=2 mesh. The base row doubles as the null
    identity through the same dispatch."""
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    pack, leaves = _pack_and_leaves(cfg)
    kw = dict(slots=N_TENANTS + 1, max_seq_len=MAX_LEN,
              attend_impl=attend_impl, kv_layout=kv_layout)
    eng = InferenceEngine(cfg, adapters=pack, **kw)
    base = _params(cfg)
    prompts = _prompts()
    mixed = _run_mixed(eng, eng.shard_params(base), prompts)

    ref_eng = InferenceEngine(cfg, **kw)
    for slot, p in prompts.items():
        tree = (base if slot == 0
                else llama.merge_adapter(base, leaves[slot]))
        ref = ContinuousBatcher(ref_eng, ref_eng.shard_params(tree),
                                seed=0).run(
            [Request(uid="solo", prompt=list(p), max_new_tokens=10)])
        assert mixed[f"t{slot}"].tokens == ref["solo"].tokens, slot
        assert mixed[f"t{slot}"].finish_reason == ref["solo"].finish_reason
    # adapters actually bite: tenants diverge from the base row even on
    # a shared-prefix-free prompt set
    assert any(mixed[f"t{t}"].tokens != mixed["t0"].tokens
               for t in range(1, N_TENANTS + 1))
    if kv_layout == "paged":
        # per-tenant radix domains: each tenant's prompt registered under
        # its own salt, never the anonymous ("") domain
        radix = eng.paged.radix
        for slot in range(1, N_TENANTS + 1):
            salt = f"tenant{slot}"
            assert radix.match(prompts[slot], salt=salt)[1] > 0
            assert radix.match(prompts[slot], salt="")[1] == 0


def test_mixed_verify_matches_merged_refs(tiny_model_kwargs):
    """The speculative-verify dispatch (spec_len=3, repetitive prompts so
    drafts accept): mixed-tenant greedy tokens == solo merged
    references, and the spec run == its own spec-off twin."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    pack, leaves = _pack_and_leaves(cfg)
    kw = dict(slots=N_TENANTS + 1, max_seq_len=MAX_LEN)
    eng = InferenceEngine(cfg, adapters=pack, spec_len=3, **kw)
    base = _params(cfg)
    prompts = {slot: ([5, 9, 5, 9] * 2) for slot in range(N_TENANTS + 1)}
    mixed = _run_mixed(eng, eng.shard_params(base), prompts, max_new=12)
    ref_eng = InferenceEngine(cfg, **kw)  # spec-off: greedy oracle
    for slot, p in prompts.items():
        tree = (base if slot == 0
                else llama.merge_adapter(base, leaves[slot]))
        ref = ContinuousBatcher(ref_eng, ref_eng.shard_params(tree),
                                seed=0).run(
            [Request(uid="solo", prompt=list(p), max_new_tokens=12)])
        assert mixed[f"t{slot}"].tokens == ref["solo"].tokens, slot


def test_chunked_prefill_adapter_matches_merged(tiny_model_kwargs):
    """The chunked-prefill dispatch under an adapter id: final logits
    agree with the merged-weight engine's chunked prefill AND with the
    adapter engine's own one-shot prefill."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    pack, leaves = _pack_and_leaves(cfg)
    kw = dict(slots=2, max_seq_len=MAX_LEN, prefill_chunk=8)
    eng = InferenceEngine(cfg, adapters=pack, **kw)
    base = _params(cfg)
    params = eng.shard_params(base)
    prompt = [(5 * i + 2) % 199 + 1 for i in range(20)]
    cache, last = eng.prefill_chunked(params, eng.init_cache(), prompt,
                                      slot=1, adapter_id=2)
    oneshot = eng.prefill(params, prompt, adapter_id=2)[1]
    np.testing.assert_allclose(np.asarray(last)[0], np.asarray(oneshot)[0],
                               rtol=1e-4, atol=1e-4)
    ref_eng = InferenceEngine(cfg, **kw)
    merged = ref_eng.shard_params(llama.merge_adapter(base, leaves[2]))
    _, ref_last = ref_eng.prefill_chunked(merged, ref_eng.init_cache(),
                                          prompt, slot=1)
    ref = np.asarray(ref_last)[0]
    got = np.asarray(last)[0]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    assert int(np.argmax(got)) == int(np.argmax(ref))


@pytest.mark.parametrize("tp", [1, 2])
def test_int8_mixed_matches_fakequant_merged(tiny_model_kwargs, tp):
    """Multi-LoRA over the int8 base on tp=1 AND tp=2: the oracle is an
    adapter-less dense engine fed fake-quant(W) + BA — the quantization
    error is in both trees, so any difference is the segmented adapter
    path composed with the fused dequant matmul."""
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    pack, leaves = _pack_and_leaves(cfg)
    kw = dict(slots=N_TENANTS + 1, max_seq_len=MAX_LEN)
    eng = InferenceEngine(cfg, adapters=pack, weight_dtype="int8", **kw)
    base = _params(cfg)
    qp = llama.quantize_params(base)
    prompts = _prompts()
    mixed = _run_mixed(eng, eng.shard_params(qp), prompts)
    fq = llama.dequantize_params(qp, jnp.dtype(cfg.model.dtype))
    ref_eng = InferenceEngine(cfg, **kw)
    for slot, p in prompts.items():
        tree = fq if slot == 0 else llama.merge_adapter(fq, leaves[slot])
        ref = ContinuousBatcher(ref_eng, ref_eng.shard_params(tree),
                                seed=0).run(
            [Request(uid="solo", prompt=list(p), max_new_tokens=10)])
        assert mixed[f"t{slot}"].tokens == ref["solo"].tokens, slot


def test_null_pack_engine_identical_to_packless(tiny_model_kwargs):
    """An engine CARRYING a live pack but serving only slot-0 rows is
    bit-identical to an engine built without one — logits included, not
    just argmax (the null residual is exactly zero)."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    pack, _ = _pack_and_leaves(cfg)  # live adapters in slots 1..3
    base = _params(cfg)
    prompt = list(range(1, 9))
    outs = []
    for adapters in (pack, None):
        eng = InferenceEngine(cfg, adapters=adapters, slots=2,
                              max_seq_len=MAX_LEN)
        params = eng.shard_params(base)
        kv, logits = eng.prefill(params, prompt)
        res = ContinuousBatcher(eng, params, seed=0).run(
            [Request(uid="r", prompt=list(prompt), max_new_tokens=10)])
        outs.append((np.asarray(logits), res["r"].tokens))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    assert outs[0][1] == outs[1][1]
    # binding ids on a packless engine is a loud error, not a silent drop
    eng = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
    with pytest.raises(ValueError, match="no adapter pack"):
        eng.bind_adapter_ids(base, [1, 0], 2)


# --------------------------------------------------------------------------- #
# the HTTP surface: tenant resolution, /tenants admin, quota 429 bodies
# --------------------------------------------------------------------------- #


def _req(port, method, path, body=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path, None if body is None else json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read() or b"{}"))
    conn.close()
    return out


def test_http_tenant_resolution_admin_and_quota(tiny_model_kwargs):
    from picotron_tpu.tools import serve

    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    pack, _ = _pack_and_leaves(cfg)
    reg = tenancy.TenantRegistry(pack)
    reg.add(tenancy.Tenant(name="acme", priority=2, adapter_rank=RANK,
                           adapter_seed=1, adapter_scale=SCALE))
    reg.add(tenancy.Tenant(name="capped", priority=1, max_tokens=8))
    eng = InferenceEngine(cfg, adapters=pack, slots=2, max_seq_len=MAX_LEN)
    params = eng.shard_params(_params(cfg))
    srv = serve.Server(eng, params, port=0, tenants=reg,
                       log=lambda *a, **k: None)
    srv.start()
    try:
        port = srv.port
        spec = {"prompt": [1, 2, 3], "max_new_tokens": 6}
        st, base_body = serve._post(port, spec)
        assert st == 200
        st, body = serve._post(port, {**spec, "tenant": "nope"})
        assert st == 400 and "unknown tenant" in body["error"]
        st, acme = serve._post(port, {**spec, "tenant": "acme"})
        assert st == 200
        assert acme["tokens"] != base_body["tokens"]  # the adapter bites
        # per-tenant quota: commitment (3 + 20) blows max_tokens=8 and
        # the 429 body names WHICH budget tripped, for WHOM
        st, body = serve._post(port, {"prompt": [1, 2, 3],
                                      "max_new_tokens": 20,
                                      "tenant": "capped"})
        assert st == 429
        assert body["budget"] == "tenant_tokens"
        assert body["tenant"] == "capped"
        # admin surface: snapshot, hot add, duplicate 409, hot remove
        st, snap = serve._get(port, "/tenants")
        assert st == 200
        assert {t["name"] for t in snap["tenants"]} == {"acme", "capped"}
        assert snap["pack"]["adapter_bytes_per_token"] == \
            pack.bytes_per_token()
        st, added = _req(port, "POST", "/tenants",
                         {"name": "hot", "priority": 0})
        assert st == 200 and added["adapter_slot"] == 0
        st, _ = _req(port, "POST", "/tenants", {"name": "hot"})
        assert st == 409
        st, body = serve._post(port, {**spec, "tenant": "hot"})
        assert st == 200 and body["tokens"] == base_body["tokens"]
        st, stats = serve._get(port, "/statz")
        assert st == 200
        assert stats["rejected"]["tenant_quota"] == 1
        assert "hot" in stats["tenant_names"]
        # hot remove: the name 400s afterwards (no silent base fallback)
        st, body = _req(port, "DELETE", "/tenants/hot")
        assert st == 200
        st, _ = _req(port, "DELETE", "/tenants/hot")
        assert st == 404
        st, body = serve._post(port, {**spec, "tenant": "hot"})
        assert st == 400
    finally:
        srv.drain_and_join(timeout=60)
