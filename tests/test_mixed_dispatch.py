"""Mixed prefill–decode dispatch (inference.mixed_dispatch,
docs/INFERENCE.md "Mixed prefill–decode dispatch").

The tentpole gate is BIT-IDENTITY: the fused program family — every
decode slot advances one step AND one fixed-width prefill lane per dp
shard in the SAME jitted call — must emit exactly the streams the
serial scheduler (separate prefill dispatches) emits, greedy AND seeded
stochastic, across the engine matrix (decode_block/verify x dense/flash
x contiguous/paged x int8 x tp x dp), with overlap composed on top.
Both sides run the slot key schedule (the lane's prerequisite, same as
overlap's): a slot-keyed stream depends only on (base key, position),
and the lane body is byte-for-byte the serial chunk program, so fusing
it into the decode dispatch cannot move a single bit. Around it:

- the scheduling contract: the lane is fed through ``_prefill_gate``'s
  token budget (the gate's round cap becomes the lane feed rate), its
  chunks count ``prefill_dispatches`` exactly like serial chunks, and
  ``picotron_prefill_lane_tokens_total`` /
  ``picotron_decode_stall_seconds`` make the interference story
  measurable;
- the gate itself (satellite): direct unit pins on the defer / preempt
  branches and their ``prefill_deferred`` / ``prefill_preempts``
  counter semantics, which the lane reuses verbatim;
- mixed_dispatch=False (default) leaves the serial path byte-identical
  — no lane state, no fused programs, lanes= rejected at the engine.

`make mixed-smoke` (bench_decode --mixed ab) is the throughput half:
decode TPOT p95 under concurrent long prefills <= 3x the no-prefill
floor with TTFT p95 not regressing vs the serial+gate baseline.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from conftest import make_config
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    Request,
)
from picotron_tpu.models import llama
from picotron_tpu.resilience.chaos import ServingChaos

MAX_LEN = 96


def _engine(tiny_model_kwargs, mixed, tp=1, dp=1, slots=4,
            key_schedule="slot", hooks=None, **kw):
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    cfg.inference.dp_size = dp
    kw.setdefault("decode_block_len", 4)
    kw.setdefault("prefill_chunk", 8)
    eng = InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN,
                          mixed_dispatch=mixed, key_schedule=key_schedule,
                          hooks=hooks, **kw)
    return cfg, eng


def _params(cfg, engine, seed=0):
    p = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(seed))
    if engine.quant_weights:
        p = llama.quantize_params(p)
    return engine.shard_params(p)


def _reqs(temp=0.0, **extra):
    """Every prompt spans several prefill chunks (chunk=8), so every
    admission is lane-worthy on a mixed engine — the identity legs
    exercise the fused path, not a serial fallback. Lengths retire at
    different rounds so the lane crosses admissions, finishes, and
    partial occupancy."""
    k = dict(temperature=temp, top_k=0 if temp == 0 else 40, top_p=0.95,
             **extra)
    long_a = [(5 * i + 2) % 199 + 1 for i in range(20)]
    long_b = [(3 * i + 7) % 199 + 1 for i in range(17)]
    return [Request("a", long_a, max_new_tokens=14, **k),
            Request("b", long_b, max_new_tokens=10, **k),
            Request("c", [11, 12] * 5, max_new_tokens=4, **k)]


def _run(tiny_model_kwargs, mixed, program="block", temp=0.0, seed=7,
         reqs=None, obs=None, **kw):
    if program == "verify":
        kw.setdefault("spec_len", 3)
    cfg, eng = _engine(tiny_model_kwargs, mixed, **kw)
    b = ContinuousBatcher(eng, _params(cfg, eng), seed=seed, obs=obs)
    res = b.run(reqs if reqs is not None else _reqs(temp))
    return {u: (r.tokens, r.finish_reason) for u, r in res.items()}, b


def _lane_tokens(b):
    snap = b.obs.registry.snapshot().get(
        "picotron_prefill_lane_tokens_total")
    return sum(snap["values"].values()) if snap else 0


# --------------------------------------------------------------------------- #
# the tentpole: mixed-on == mixed-off across the engine matrix
# --------------------------------------------------------------------------- #


# The full matrix is the gate; ONE canonical leg stays un-marked as the
# tier-1 core (the single-core tier-1 budget is tight — ~25s per leg)
# and the rest ride the `slow` lane (same budget discipline as the
# overlap and speculative matrices; `make test-all` and `make
# mixed-smoke` run the full set).
_slow = pytest.mark.slow
@pytest.mark.parametrize(
    "program,layout,attend,quant,tp,dp,temp,overlap", [
        ("block",  "contiguous", "dense", None,     1, 1, 0.0, False),
        pytest.param("block", "contiguous", "dense", None, 1, 1, 0.9,
                     False, marks=_slow),
        pytest.param("block", "paged", "dense", None, 1, 1, 0.9, False,
                     marks=_slow),
        pytest.param("block", "paged", "flash", None, 1, 1, 0.0, False,
                     marks=_slow),
        pytest.param("block", "contiguous", "dense", "int8kv", 1, 1, 0.9,
                     False, marks=_slow),
        pytest.param("block", "paged", "dense", "int8w", 1, 1, 0.0, False,
                     marks=_slow),
        pytest.param("block", "contiguous", "dense", None, 2, 1, 0.9,
                     False, marks=_slow),
        pytest.param("block", "paged", "dense", None, 1, 2, 0.9, False,
                     marks=_slow),
        pytest.param("verify", "contiguous", "dense", None, 1, 1, 0.9,
                     False, marks=_slow),
        pytest.param("verify", "paged", "dense", None, 1, 2, 0.0, False,
                     marks=_slow),
        pytest.param("block", "contiguous", "dense", None, 1, 1, 0.0,
                     True, marks=_slow),
        pytest.param("block", "paged", "dense", None, 1, 1, 0.9, True,
                     marks=_slow),
        pytest.param("verify", "contiguous", "dense", None, 1, 1, 0.9,
                     True, marks=_slow),
    ])
def test_mixed_identity_matrix(tiny_model_kwargs, program, layout, attend,
                               quant, tp, dp, temp, overlap):
    """Mixed-on emits streams BIT-IDENTICAL to mixed-off — same seed,
    same slot key schedule — for every program family crossed with
    representative kernel/layout/quantization corners, greedy and seeded
    stochastic, on tp=2 and dp=2, with the overlap pipeline composed on
    top. The lane must actually have run (lane token counter moved):
    a leg that silently fell back to serial prefill proves nothing."""
    kw = dict(kv_layout=layout, attend_impl=attend, tp=tp, dp=dp)
    if quant == "int8kv":
        kw["cache_dtype"] = "int8"
    elif quant == "int8w":
        kw["weight_dtype"] = "int8"
    off, b_off = _run(tiny_model_kwargs, False, program, temp, **kw)
    on, b_on = _run(tiny_model_kwargs, True, program, temp,
                    overlap=overlap, **kw)
    assert on == off, (program, layout, attend, quant, tp, dp, temp,
                       overlap)
    assert _lane_tokens(b_on) > 0
    assert _lane_tokens(b_off) == 0
    st = b_on.stats()
    assert st["mixed"] == {"enabled": True, "lanes_active": 0}
    assert all(s is None for s in b_on._slots)  # drained, nothing stuck


@pytest.mark.slow
def test_mixed_lane_chunk_accounting_matches_serial(tiny_model_kwargs):
    """Lane chunks are the SAME chunk schedule the serial path runs:
    ``prefill_dispatches`` (3 + 3 + 2 chunks for the 20/17/10-token
    prompts at chunk=8) agrees across modes, and the lane token counter
    equals the total prompt tokens fed."""
    _, b_off = _run(tiny_model_kwargs, False)
    _, b_on = _run(tiny_model_kwargs, True)
    assert b_on.prefill_dispatches == b_off.prefill_dispatches == 8
    assert _lane_tokens(b_on) == 20 + 17 + 10


@pytest.mark.slow
def test_mixed_removes_solo_prefill_stalls(tiny_model_kwargs):
    """The interference story in one metric: serial admissions that run
    while a decoder is already seated record
    ``picotron_decode_stall_seconds`` (the decode batch sits idle for
    that dispatch); with every prompt lane-worthy, mixed mode records
    NONE — no dispatch ran that did not also advance the decoders."""

    def stall_count(b):
        snap = b.obs.registry.snapshot().get(
            "picotron_decode_stall_seconds")
        if not snap:
            return 0
        return sum(v["count"] for v in snap["values"].values())

    _, b_off = _run(tiny_model_kwargs, False)
    _, b_on = _run(tiny_model_kwargs, True)
    assert stall_count(b_off) >= 1  # 2nd/3rd admission stalls a decoder
    assert stall_count(b_on) == 0


def test_mixed_rejects_round_key_schedule(tiny_model_kwargs):
    """mixed_dispatch + key_schedule='round' is an invalid combination
    (the lane's first token must be keyed by position, not round
    membership): config.validate and the engine both refuse it."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    cfg.inference.mixed_dispatch = True
    cfg.inference.key_schedule = "round"
    with pytest.raises(ValueError, match="key schedule"):
        cfg.validate()
    cfg2 = make_config(tiny_model_kwargs, seq=MAX_LEN)
    with pytest.raises(ValueError, match="key schedule"):
        InferenceEngine(cfg2, slots=2, max_seq_len=MAX_LEN,
                        mixed_dispatch=True, key_schedule="round")


def test_mixed_off_default_leaves_serial_path(tiny_model_kwargs):
    """mixed_dispatch defaults to False: no fused programs are built, no
    lane state exists, and handing ``lanes=`` to the engine is a
    programming error — the serial scheduler is byte-identical to
    before the lane existed."""
    cfg, eng = _engine(tiny_model_kwargs, False)
    assert eng.mixed is False
    assert getattr(eng, "_decode_block_mixed_jit", None) is None
    b = ContinuousBatcher(eng, _params(cfg, eng), seed=7)
    assert b._mixed is False and all(ln is None for ln in b._lanes)
    cache = eng.init_cache()
    n = eng.slots
    with pytest.raises(ValueError, match="mixed"):
        eng.decode_block(_params(cfg, eng), cache,
                         np.zeros(n, np.int32),
                         np.zeros((n, 2), np.uint32),
                         np.full(n, -1, np.int32),
                         np.zeros(n, np.int32),
                         np.ones(n, np.float32),
                         np.zeros(n, np.int32),
                         np.ones(n, np.float32),
                         lanes=[None])


@pytest.mark.slow
def test_mixed_cold_short_prompt_admits_serially(tiny_model_kwargs):
    """A cold prompt at or under one chunk keeps the one-shot bucketed
    prefill (a different program family than the chunk the lane runs) —
    so short-prompt streams stay bit-identical to mixed-off by running
    the IDENTICAL serial dispatch, and the lane counter only moves for
    the long prompt."""
    reqs = [Request("s", [3, 1, 4], max_new_tokens=6),
            Request("l", [(5 * i + 2) % 199 + 1 for i in range(20)],
                    max_new_tokens=6)]
    off, _ = _run(tiny_model_kwargs, False,
                  reqs=[Request(**vars(r)) for r in reqs])
    on, b = _run(tiny_model_kwargs, True,
                 reqs=[Request(**vars(r)) for r in reqs])
    assert on == off
    assert _lane_tokens(b) == 20


@pytest.mark.slow
def test_mixed_lane_spans_pass_trace_audit(tiny_model_kwargs, tmp_path):
    """A real mixed run's trace passes the lane-chain audit: every lane
    chunk span parents to its request root and the chunks tile each
    prompt exactly (``--require-lane-chain``, the obs gate for the
    fused path)."""
    from picotron_tpu.obs import Obs, SpanTracer
    from picotron_tpu.tools import trace_dump

    # a PRIVATE span ring: the process-wide GLOBAL_TRACER interleaves
    # every batcher this pytest process has run, so the tiling counts
    # below would otherwise depend on which tests ran first
    _, b = _run(tiny_model_kwargs, True,
                obs=Obs(tracer=SpanTracer()))
    path = tmp_path / "mixed_trace.json"
    b.obs.tracer.dump_chrome(str(path))
    la = trace_dump.lane_chain(trace_dump.load(str(path)))
    assert la["errors"] == []
    assert la["lanes"] == la["linked"] == 8  # 3+3+2 chunks
    assert trace_dump.main([str(path), "--require-lane-chain"]) == 0


# --------------------------------------------------------------------------- #
# composition: isolation re-dispatch under the fused program
# --------------------------------------------------------------------------- #


@pytest.mark.slow
def test_mixed_slot_isolation_redispatch(tiny_model_kwargs):
    """A persistently failing slot under the fused program: the solo
    isolation re-dispatches re-run the lane chunk idempotently (same
    chunk, same rows, same bytes), the faulted slot finishes "error",
    and SURVIVORS' streams equal the fault-free mixed run."""
    clean, _ = _run(tiny_model_kwargs, True, temp=0.9)
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    cfg.resilience.chaos_dispatch_fail_slot = 1
    cfg.validate()
    on, b = _run(tiny_model_kwargs, True, temp=0.9,
                 hooks=ServingChaos(cfg.resilience))
    assert on["b"][1] == "error"
    for uid in ("a", "c"):
        assert on[uid] == clean[uid]
    assert all(s is None for s in b._slots)
    assert all(ln is None for ln in b._lanes)
    assert b.queue_depth == 0
    assert b.counters["errored"] == 1
    assert b.counters["completed"] == 2


# --------------------------------------------------------------------------- #
# satellite: _prefill_gate defer / preempt branch pins
# --------------------------------------------------------------------------- #


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _gated_batcher(tiny_model_kwargs):
    """A real batcher (serial engine) with a deterministic clock, one
    seated decoder carrying a TPOT SLO, and round budget already spent —
    the configuration in which the gate's defer/preempt branches are
    live."""
    cfg, eng = _engine(tiny_model_kwargs, False)
    clock = _FakeClock()
    b = ContinuousBatcher(eng, _params(cfg, eng), seed=7, clock=clock)
    from picotron_tpu.inference.batcher import _Slot

    holder = Request("held", [1, 2], tpot_slo_ms=50.0)
    b._slots[0] = _Slot(holder, deadline=None, submit_t=None)
    return b, clock


def test_gate_first_admission_of_round_always_passes(tiny_model_kwargs):
    """Branch pin: the progress guarantee. With zero prefill tokens spent
    this round the gate admits ANY prompt — SLO pressure or not — and
    neither counter moves."""
    b, _ = _gated_batcher(tiny_model_kwargs)
    req = Request("r", list(range(1, 30)), tenant="t0")
    assert b._round_prefill_tokens == 0
    assert b._prefill_gate(req) is True
    assert b._tstat(req)["prefill_deferred"] == 0
    assert b._tstat(req)["prefill_preempts"] == 0


def test_gate_without_tpot_slo_never_defers(tiny_model_kwargs):
    """Branch pin: the cap only exists to protect decoders with a TPOT
    SLO. Same spent budget, no SLO on the seated slot -> admit."""
    b, _ = _gated_batcher(tiny_model_kwargs)
    b._slots[0].req.tpot_slo_ms = None
    b._round_prefill_tokens = 8
    assert b._prefill_gate(Request("r", list(range(1, 30)))) is True


def test_gate_defers_and_counts_once_per_decision(tiny_model_kwargs):
    """Branch pin: budget spent + active TPOT SLO + prompt over the
    remaining chunk budget -> defer, ``prefill_deferred`` and the tenant
    counter up by exactly one per decision."""
    b, _ = _gated_batcher(tiny_model_kwargs)
    b._round_prefill_tokens = 8  # one chunk already admitted this round
    req = Request("r", list(range(1, 30)), tenant="t0")
    assert b._prefill_gate(req) is False
    assert b._prefill_gate(req) is False
    assert b._tstat(req)["prefill_deferred"] == 2
    snap = b.obs.registry.snapshot()
    [(lbl, v)] = list(
        snap["picotron_tenant_prefill_deferred_total"]["values"].items())
    assert lbl == 'tenant="t0"' and v == 2
    assert "picotron_tenant_prefill_preempts_total" not in snap


def test_gate_small_request_fits_remaining_budget(tiny_model_kwargs):
    """Branch pin: the cap is a token budget, not a one-admission latch —
    a prompt that still fits under prefill_chunk admits; the ``tokens``
    override prices a lane CHUNK the same way (the lane feed rate)."""
    b, _ = _gated_batcher(tiny_model_kwargs)
    b._round_prefill_tokens = 3
    assert b._prefill_gate(Request("r", [1, 2, 3, 4])) is True  # 3+4 <= 8
    assert b._prefill_gate(Request("r", list(range(1, 30)))) is False
    assert b._prefill_gate(Request("r", list(range(1, 30))),
                           tokens=5) is True


def test_gate_ttft_preempt_overrides_cap(tiny_model_kwargs):
    """Branch pin: a waiting request whose TTFT budget is half spent
    preempts the cap — admit despite the spent budget, with
    ``prefill_preempts`` (not deferred) counting the decision. The
    ``submit_t`` override stands in for the pending-queue clock (the
    lane's slot record carries the time after admission)."""
    b, clock = _gated_batcher(tiny_model_kwargs)
    b._round_prefill_tokens = 8
    req = Request("r", list(range(1, 30)), tenant="t1", ttft_slo_ms=200.0)
    b._submit_t[req.uid] = clock.t
    assert b._prefill_gate(req) is False  # 0ms elapsed: no preempt yet
    clock.t += 0.25  # 250ms >= 200/2
    assert b._prefill_gate(req) is True
    assert b._tstat(req)["prefill_preempts"] == 1
    assert b._tstat(req)["prefill_deferred"] == 1
    del b._submit_t[req.uid]
    assert b._prefill_gate(req, submit_t=clock.t - 0.25) is True
    assert b._prefill_gate(req) is False  # no clock source: cap holds
    assert b._tstat(req)["prefill_preempts"] == 2
