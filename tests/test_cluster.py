"""Cluster control plane units (picotron_tpu/resilience/cluster.py).

Fast tier-1 coverage for the pieces the slow 2-process pod drills
(tests/test_cluster_pod.py, ``make chaos-pod-smoke``) exercise end to end:
the preemption-consensus coordinator's scheduling/verdict logic, the
peer-liveness monitor's lease/done/birth accounting, the ``"RANK:STEP"``
pod-chaos parsing + one-shot-with-marker firing discipline, and the
``was_preempted()`` staleness regression from the satellite list.
"""

import os
import signal
import threading
import time

import pytest

from picotron_tpu import resilience
from picotron_tpu.config import parse_rank_at_step
from picotron_tpu.resilience.chaos import ChaosInjector
from picotron_tpu.resilience.cluster import (
    EXIT_CLUSTER_FAILED,
    ClusterCoordinator,
    ClusterMonitor,
)
from picotron_tpu.resilience.preemption import PreemptionGuard, was_preempted

from conftest import make_config

_TINY = dict(
    num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
    hidden_size=16, intermediate_size=32, vocab_size=64,
    max_position_embeddings=64, rope_theta=10000.0, dtype="float32",
    attention_impl="sdpa")


def _res_cfg(save_dir="", **kw):
    cfg = make_config(_TINY)
    cfg.checkpoint.save_dir = save_dir
    for k, v in kw.items():
        setattr(cfg.resilience, k, v)
    return cfg


# --------------------------------------------------------------------------- #
# ClusterCoordinator: consensus scheduling + verdict
# --------------------------------------------------------------------------- #


def test_coordinator_inert_on_single_process():
    """With one JAX process the local flag IS the global truth: no rounds,
    no collectives, every step checked — byte-identical to pre-cluster
    behavior."""
    c = ClusterCoordinator(interval=3, process_count=1)
    assert not c.active
    assert c.due(0) and c.due(7)  # every boundary, regardless of interval
    assert c.preempt_now(0, False) is False
    assert c.preempt_now(1, True) is True
    assert c.rounds == 0  # never evaluated a collective round


def test_coordinator_interval_gates_rounds():
    """An active coordinator holds its first round at the first boundary,
    then every ``interval`` steps; between rounds even a RAISED local flag
    waits (breaking alone would tear the collective save)."""
    c = ClusterCoordinator(interval=3, process_count=2)
    assert c.active
    assert c.preempt_now(0, False) is False and c.rounds == 1
    # flag raised between rounds: deferred, not evaluated
    assert c.preempt_now(1, True) is False and c.rounds == 1
    assert c.preempt_now(2, True) is False and c.rounds == 1
    # next due boundary: the round runs and the flag comes back
    assert c.preempt_now(3, True) is True and c.rounds == 2


def test_coordinator_all_reduce_propagates_flag():
    """The jitted jnp.max round returns exactly the OR of the contributed
    flags (here one process contributes for the whole 'pod', proving the
    device-mesh plumbing; the 2-process truth table is the slow suite)."""
    c = ClusterCoordinator(interval=1, process_count=2)
    assert c.preempt_now(0, False) is False
    assert c.preempt_now(1, True) is True
    assert c.preempt_now(2, False) is False  # verdict is per-round, not latched
    assert c.rounds == 3


def test_coordinator_interval_floor():
    assert ClusterCoordinator(interval=0, process_count=1).interval == 1


def test_coordinator_schedule_restarts_after_rollback():
    """An anomaly rollback rewinds the step counter on EVERY process at
    once; the consensus schedule must restart there — gating on the old
    high-water mark would leave the whole replay deaf to preemptions."""
    c = ClusterCoordinator(interval=4, process_count=2)
    assert c.preempt_now(10, False) is False and c.rounds == 1
    # rollback restored step 3; a preemption during the replay must be
    # seen at the next boundary, not at step >= 14
    assert c.due(3)
    assert c.preempt_now(3, True) is True and c.rounds == 2


# --------------------------------------------------------------------------- #
# ClusterMonitor: lease/done/birth accounting
# --------------------------------------------------------------------------- #


def _monitor(tmp_path, pid=0, nproc=2, timeout=5.0, **kw):
    m = ClusterMonitor(str(tmp_path), pid, nproc, peer_timeout_s=timeout,
                       **kw)
    os.makedirs(m.dir, exist_ok=True)
    now = time.time()
    m._births = {p: now for p in range(nproc) if p != pid}
    return m


def _backdate(path, by_s):
    old = time.time() - by_s
    os.utime(path, (old, old))


def test_monitor_fresh_lease_is_alive(tmp_path):
    m = _monitor(tmp_path)
    with open(m.lease_path(1), "w") as f:
        f.write("3")
    assert m.check_peers() is None


def test_monitor_stale_lease_is_dead(tmp_path):
    m = _monitor(tmp_path, timeout=5.0)
    m._births = {1: time.time() - 60.0}  # the pod has been up a while
    with open(m.lease_path(1), "w") as f:
        f.write("3")
    _backdate(m.lease_path(1), 30.0)
    peer, age = m.check_peers()
    assert peer == 1 and age > 5.0
    assert m._peer_step(1) == "3"  # the post-mortem names the last step


def test_monitor_ignores_previous_incarnations_files(tmp_path):
    """The pod supervisor relaunches every rank over the SAME cluster_dir.
    A dead incarnation's lease must not read as an instant timeout before
    its owner's reset() runs (startup skew), and its done marker must not
    blind this incarnation to that rank's next death."""
    m = _monitor(tmp_path, timeout=5.0)  # births = now: just (re)started
    # leftover lease from the previous incarnation, 30s old
    with open(m.lease_path(1), "w") as f:
        f.write("3")
    _backdate(m.lease_path(1), 30.0)
    assert m.check_peers() is None  # silence counts from OUR start, not 30s
    # leftover done marker: ignored — the peer is still being watched...
    with open(m.done_path(1), "w") as f:
        f.write("6")
    _backdate(m.done_path(1), 30.0)
    assert m.check_peers() is None
    assert 1 not in m._done
    # ...so its death THIS incarnation is still detected
    m._births[1] = time.time() - 60.0
    os.remove(m.done_path(1))
    peer, _ = m.check_peers()
    assert peer == 1


def test_monitor_never_leased_peer_counts_from_birth(tmp_path):
    """A host that fails to come up at all never writes a lease; its
    silence is aged from OUR start, so the pod still unwedges."""
    m = _monitor(tmp_path, timeout=5.0)
    m._births[1] = time.time() - 30.0
    peer, age = m.check_peers()
    assert peer == 1 and age > 5.0


def test_monitor_done_marker_suppresses_death_verdict(tmp_path):
    """A rank that finished cleanly (or took the coordinated preemption
    exit) marks done; its silence afterwards is natural, not a dead host."""
    m = _monitor(tmp_path, timeout=5.0)
    m._births = {1: time.time() - 60.0}
    with open(m.lease_path(1), "w") as f:
        f.write("6")
    _backdate(m.lease_path(1), 30.0)  # silent past timeout — but done
    with open(m.done_path(1), "w") as f:
        f.write("6")
    assert m.check_peers() is None
    # and the verdict is cached: a later unlink of the done file (pod
    # restart cleanup) must not resurrect the death sentence mid-check
    os.remove(m.done_path(1))
    assert m.check_peers() is None


def test_monitor_stop_marks_done_only_when_asked(tmp_path):
    m = _monitor(tmp_path)
    m.notify_step(4)
    m.stop(mark_done=True)
    with open(m.done_path(0)) as f:
        assert f.read() == "4"
    os.remove(m.done_path(0))
    m2 = _monitor(tmp_path)
    m2.stop(mark_done=False)  # a crash path: the stale lease must speak
    assert not os.path.exists(m2.done_path(0))


def test_monitor_reset_clears_own_stale_markers(tmp_path):
    """A pod restart reuses cluster_dir: leftover done/lease files from the
    previous incarnation would blind peers (done) or read as an instant
    timeout (stale lease)."""
    m = _monitor(tmp_path)
    for p in (m.lease_path(0), m.done_path(0)):
        with open(p, "w") as f:
            f.write("9")
    m.reset()
    assert not os.path.exists(m.lease_path(0))
    assert not os.path.exists(m.done_path(0))


def test_monitor_renew_writes_step_content(tmp_path):
    m = _monitor(tmp_path)
    m.notify_step(7)
    m._renew()
    with open(m.lease_path(0)) as f:
        assert f.read() == "7"


def test_monitor_thread_exits_on_dead_peer(tmp_path):
    """End to end through the real thread: a peer that never leases trips
    the (injected) exit_fn within a couple of timeout windows."""
    hit = threading.Event()
    verdicts = []

    def fake_exit(peer, age):
        verdicts.append((peer, age))
        hit.set()

    m = ClusterMonitor(str(tmp_path), 0, 2, peer_timeout_s=0.3,
                       lease_interval_s=0.05, exit_fn=fake_exit)
    m.start()
    try:
        assert hit.wait(timeout=5.0), "monitor never flagged the dead peer"
    finally:
        m.stop(mark_done=False)
    assert verdicts and verdicts[0][0] == 1 and verdicts[0][1] > 0.3
    # our own lease was being renewed the whole time
    assert os.path.exists(m.lease_path(0))


def test_monitor_thread_quiet_with_live_peer(tmp_path):
    """Two monitors in one process watching each other: both renew, neither
    dies, and a clean stop leaves both done markers."""
    boom = lambda peer, age: pytest.fail(f"false death verdict: {peer}")
    ms = [ClusterMonitor(str(tmp_path), p, 2, peer_timeout_s=1.0,
                         lease_interval_s=0.05, exit_fn=boom).start()
          for p in range(2)]
    time.sleep(1.5)  # several full timeout windows
    for m in ms:
        m.stop(mark_done=True)
    assert all(os.path.exists(m.done_path(m.pid)) for m in ms)


def test_exit_code_ladder_distinct():
    assert EXIT_CLUSTER_FAILED == 77
    assert len({0, resilience.EXIT_PREEMPTED, resilience.EXIT_ANOMALY,
                resilience.EXIT_CLUSTER_FAILED}) == 4


# --------------------------------------------------------------------------- #
# "RANK:STEP" parsing + config validation
# --------------------------------------------------------------------------- #


def test_parse_rank_at_step():
    assert parse_rank_at_step("f", "") == (-1, 0)
    assert parse_rank_at_step("f", "1:3") == (1, 3)
    assert parse_rank_at_step("f", "0:1") == (0, 1)
    for bad in ("3", "a:b", "-1:2", "1:0", "1:", ":3", "1:2:3"):
        with pytest.raises(ValueError, match="RANK:STEP"):
            parse_rank_at_step("chaos_kill_rank_at_step", bad)


def test_config_validates_pod_chaos_and_cluster_fields():
    _res_cfg("/tmp/ck", chaos_preempt_rank_at_step="1:3").validate()
    _res_cfg(peer_timeout_s=10.0, lease_interval_s=2.0).validate()
    with pytest.raises(ValueError, match="chaos_kill_rank_at_step"):
        _res_cfg("/tmp/ck", chaos_kill_rank_at_step="oops").validate()
    # rank chaos without a save_dir would re-trip on every pod relaunch
    # (no fired marker, no checkpoint past the step) — refuse loudly
    with pytest.raises(ValueError, match="save_dir"):
        _res_cfg(chaos_kill_rank_at_step="1:3").validate()
    with pytest.raises(ValueError, match="consensus_interval"):
        _res_cfg(consensus_interval=-1).validate()
    with pytest.raises(ValueError, match="lease_interval_s"):
        _res_cfg(lease_interval_s=0.0).validate()
    # a timeout inside the renewal cadence would kill healthy pods
    with pytest.raises(ValueError, match="peer_timeout_s"):
        _res_cfg(peer_timeout_s=3.0, lease_interval_s=2.0).validate()
    # round trip
    from picotron_tpu.config import Config

    cfg = _res_cfg("/tmp/ck", chaos_kill_rank_at_step="0:2",
                   peer_timeout_s=9.0)
    cfg2 = Config.from_dict(cfg.to_dict())
    assert cfg2.resilience.chaos_kill_rank_at_step == "0:2"
    assert cfg2.resilience.peer_timeout_s == 9.0


# --------------------------------------------------------------------------- #
# rank-targeted chaos: fires once, on the right rank, marker survives restart
# --------------------------------------------------------------------------- #


def _injector(tmp_path, rank, **res):
    cfg = _res_cfg(**res)
    return ChaosInjector(cfg.resilience, save_dir=str(tmp_path), rank=rank)


def test_rank_chaos_fires_only_on_target_rank(tmp_path):
    spec = dict(chaos_stall_rank_at_step="1:3", chaos_stall_rank_s=0.0)
    hit = _injector(tmp_path / "a", rank=1, **spec)
    miss = _injector(tmp_path / "b", rank=0, **spec)
    assert hit.active and miss.active
    assert not hit._fire_rank_once("stall", 1, 3, 2)  # wrong step
    assert hit._fire_rank_once("stall", 1, 3, 3)
    assert not hit._fire_rank_once("stall", 1, 3, 3)  # once per process
    assert not miss._fire_rank_once("stall", 1, 3, 3)  # wrong rank
    # only the targeted rank leaves a marker
    assert os.path.exists(hit._marker_path("stall", 1, 3))
    assert not os.path.exists(miss._marker_path("stall", 1, 3))


def test_rank_chaos_marker_survives_pod_restart(tmp_path):
    """A SIGKILL drill leaves no checkpoint past the chaos step, so the
    restarted pod REPLAYS it: the fired marker under save_dir is what keeps
    the fault from re-tripping every incarnation."""
    spec = dict(chaos_kill_rank_at_step="0:2")
    first = _injector(tmp_path, rank=0, **spec)
    assert first._fire_rank_once("kill", 0, 2, 2)
    relaunched = _injector(tmp_path, rank=0, **spec)  # same save_dir
    assert not relaunched._fire_rank_once("kill", 0, 2, 2)


def test_rank_chaos_preempt_delivers_sigterm_to_guard(tmp_path):
    """after_step drives the real signal path: the targeted rank SIGTERMs
    itself and its PreemptionGuard records the preemption."""
    inj = _injector(tmp_path, rank=0, chaos_preempt_rank_at_step="0:2")
    guard = PreemptionGuard().install()
    try:
        inj.after_step(1)
        assert not guard.triggered
        inj.after_step(2)
        assert guard.triggered and guard.signame == "SIGTERM"
    finally:
        guard.uninstall()


def test_rank_chaos_inactive_by_default(tmp_path):
    inj = _injector(tmp_path, rank=0)
    assert not inj.active


# --------------------------------------------------------------------------- #
# satellite regression: was_preempted() must not go stale across runs
# --------------------------------------------------------------------------- #


def test_was_preempted_not_stale_after_uninstall():
    g = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        assert g.triggered and was_preempted()
    finally:
        g.uninstall()
    # train's finally uninstalls before main reads the exit code: the
    # JUST-finished run's verdict must survive its guard...
    assert was_preempted()
    # ...but the next run in the same process (pytest, notebooks) must
    # start from a clean verdict, not the dead guard's
    g2 = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        assert not was_preempted()
    finally:
        g2.uninstall()
    assert not was_preempted()


def test_adopted_verdict_keeps_own_first_signal_benign():
    """Pod-wide preemption: a host that adopted a PEER's verdict via
    consensus still has its OWN copy of the provider's SIGTERM in flight.
    That first real signal must not read as the 'second signal' escalation
    (KeyboardInterrupt would tear the collective emergency save mid-flush);
    only a genuine second delivery escalates."""
    g = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        g.adopt()
        assert g.triggered and g.signame == "PEER-PREEMPT"
        os.kill(os.getpid(), signal.SIGUSR1)  # own copy of the pod SIGTERM
        assert g.triggered and g.signame == "SIGUSR1"  # no interrupt raised
        with pytest.raises(KeyboardInterrupt):  # a REAL second signal still
            os.kill(os.getpid(), signal.SIGUSR1)  # means "die now"
    finally:
        g.uninstall()


def test_was_preempted_false_for_never_installed_guard():
    g = PreemptionGuard(signals=(signal.SIGUSR1,))  # handle_signals=False path
    g.uninstall()
    assert not was_preempted()
