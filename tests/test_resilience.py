"""Fault-injection suite (picotron_tpu/resilience/, docs/RESILIENCE.md).

Every recovery path gets a deterministic chaos trigger and a bit-for-bit
oracle: the uninterrupted run's per-step loss trajectory. Kill→resume,
crash→finally-save→resume, NaN-step no-update, corrupt-latest fallback,
anomaly rollback, and the bounded-restart supervisor are all proven on the
dp=2,tp=2 CPU mesh — robustness regressions fail here instead of surfacing
as lost production runs. ``make chaos-smoke`` runs exactly this file.
"""

import os
import signal
import sys
import textwrap

import numpy as np
import pytest

import jax

from picotron_tpu import resilience
from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.resilience.anomaly import AnomalyAbort, LossAnomalyDetector
from picotron_tpu.resilience.chaos import ChaosError
from picotron_tpu.resilience.preemption import PreemptionGuard
from picotron_tpu.resilience.retry import retry
from picotron_tpu.tools.supervise import run_supervised
from picotron_tpu.topology import topology_from_config
from picotron_tpu.train import train

from conftest import make_config

# the shared training shape: the acceptance mesh (dp=2, tp=2), 6 steps
_TINY = dict(
    num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
    hidden_size=64, intermediate_size=128, vocab_size=256,
    max_position_embeddings=128, rope_theta=10000.0, dtype="float32",
    attention_impl="sdpa")
_COMMON = dict(dp=2, tp=2, mbs=2, seq=32, total_train_steps=6)


def _cfg(save_dir, **res):
    cfg = make_config(_TINY, **_COMMON)
    cfg.checkpoint.save_dir = str(save_dir)
    cfg.checkpoint.save_frequency = 2
    for k, v in res.items():
        setattr(cfg.resilience, k, v)
    return cfg


@pytest.fixture(scope="module")
def baseline(tmp_path_factory):
    """Per-step (step, loss) trajectory of the uninterrupted 6-step run —
    the oracle every recovery path must reproduce exactly."""
    hist = []
    steps, _, _ = train(_cfg(tmp_path_factory.mktemp("base") / "ckpt"),
                        loss_history=hist)
    assert steps == 6 and all(np.isfinite(l) for _, l in hist)
    return hist


# --------------------------------------------------------------------------- #
# host-side units: retry, anomaly detector, preemption guard
# --------------------------------------------------------------------------- #


def test_retry_succeeds_after_transient_failures():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert retry(flaky, attempts=3, backoff=0.5, jitter=0.0,
                 sleep=sleeps.append) == "ok"
    assert sleeps == [0.5, 1.0]  # exponential backoff, no jitter


def test_retry_exhausts_and_reraises_original():
    sleeps = []
    with pytest.raises(OSError, match="permanent"):
        retry(lambda: (_ for _ in ()).throw(OSError("permanent")),
              attempts=3, backoff=0.1, jitter=0.0, sleep=sleeps.append)
    assert len(sleeps) == 2  # no sleep after the final attempt


def test_anomaly_detector_flags_nonfinite_and_spikes():
    det = LossAnomalyDetector(ema_beta=0.9, zscore=3.0, warmup_steps=5,
                              min_deviation=0.05)
    # warmup: a flat-ish loss stream arms the detector without tripping
    for s in range(1, 8):
        assert det.observe(s, 5.0 + 0.01 * (s % 2)) is None
    a = det.observe(8, float("nan"))
    assert a is not None and a.kind == "nonfinite" and a.consecutive == 1
    a = det.observe(9, 50.0)  # a huge finite spike, consecutive with the NaN
    assert a is not None and a.kind == "spike" and a.consecutive == 2
    # healthy step resets the streak; the spike was NOT absorbed into the EMA
    assert det.observe(10, 5.0) is None
    assert det.consecutive == 0
    det.reset()
    assert det.observe(11, 500.0) is None  # post-reset: re-warming, not judged


def test_emergency_save_runs_off_signal_path_with_deadline():
    """The preemption flush runs on a background thread joined with a
    deadline: a completing save reports True (and re-raises its error on
    the caller's thread), a wedged save reports False after the deadline
    instead of eating the grace window."""
    import threading

    guard = PreemptionGuard()  # not installed: pure helper surface
    ran = {}

    def save():
        ran["thread"] = threading.current_thread().name
        ran["done"] = True

    assert guard.emergency_save(save, timeout_s=30.0) is True
    assert ran["done"] and ran["thread"] == "emergency-save"

    # the save's own failure surfaces on the CALLER's thread, unchanged
    def boom():
        raise OSError("mount died")

    with pytest.raises(OSError, match="mount died"):
        guard.emergency_save(boom, timeout_s=30.0)

    # a wedged save: the join deadline expires and the exit proceeds
    release = threading.Event()
    t0 = __import__("time").monotonic()
    assert guard.emergency_save(release.wait, timeout_s=0.2) is False
    assert __import__("time").monotonic() - t0 < 5.0
    release.set()  # unwedge the daemon thread before the test exits


def test_preemption_guard_flags_sigterm_and_restores_handlers():
    prev = signal.getsignal(signal.SIGTERM)
    guard = PreemptionGuard().install()
    try:
        assert not guard.triggered
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.triggered and guard.signame == "SIGTERM"
        assert resilience.was_preempted()
    finally:
        guard.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


# --------------------------------------------------------------------------- #
# the jit-side non-finite gate
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("zero1", [False, True])
def test_nonfinite_step_applies_no_update(zero1):
    """A NaN-poisoned dispatch must leave params AND optimizer state bitwise
    unchanged (zeroed grads would not do it: AdamW still decays weights and
    moments) — on the plain path and the ZeRO-1 chunked-update path."""
    cfg = make_config(_TINY, dp=2, tp=2 if not zero1 else 1, mbs=2, seq=32,
                      zero1=zero1)
    topo = topology_from_config(cfg)
    params, opt = ts.init_state(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    tok, tgt = ts.shard_batch(next(loader), topo)

    before = [np.asarray(jax.device_get(x)).copy()
              for x in jax.tree.leaves((params, opt))]
    poisoned = ts.build_train_step(cfg, topo, poison_nonfinite=True)
    params, opt, loss = poisoned(params, opt, tok, tgt)
    assert not np.isfinite(float(loss))
    after = [np.asarray(jax.device_get(x))
             for x in jax.tree.leaves((params, opt))]
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)

    # training continues: the next (clean) step updates params and is finite
    step = ts.build_train_step(cfg, topo)
    tok, tgt = ts.shard_batch(next(loader), topo)
    params2, _, loss2 = step(params, opt, tok, tgt)
    assert np.isfinite(float(loss2))
    assert any(
        not np.array_equal(a, np.asarray(jax.device_get(b)))
        for a, b in zip(before[:len(jax.tree.leaves(params2))],
                        jax.tree.leaves(params2)))


# --------------------------------------------------------------------------- #
# kill -> resume equivalence (the tentpole acceptance)
# --------------------------------------------------------------------------- #


def test_sigterm_kill_then_resume_matches_baseline(baseline, tmp_path):
    """Chaos SIGTERM at step 3: the run flushes an emergency checkpoint and
    stops; re-running the SAME command auto-resumes and the combined
    per-step loss trajectory equals the uninterrupted run bit-for-bit."""
    d = tmp_path / "ckpt"
    hist_a = []
    steps_a, _, _ = train(_cfg(d, chaos_sigterm_step=3), loss_history=hist_a)
    assert steps_a == 3
    assert resilience.was_preempted()

    hist_b = []
    steps_b, tokens_b, _ = train(_cfg(d), loss_history=hist_b)
    assert steps_b == 6
    assert not resilience.was_preempted()
    assert hist_a + hist_b == baseline  # bit-for-bit, floats compared exactly


def test_crash_still_flushes_checkpoint_and_resumes(baseline, tmp_path):
    """Chaos raise at step 3 (an unhandled crash between checkpoints): the
    try/finally must still flush a step-3 save, and auto-resume completes
    the run on the baseline trajectory."""
    d = tmp_path / "ckpt"
    hist_a = []
    with pytest.raises(ChaosError, match="injected crash after step 3"):
        train(_cfg(d, chaos_raise_step=3), loss_history=hist_a)
    assert hist_a == baseline[:3]

    hist_b = []
    steps_b, _, _ = train(_cfg(d), loss_history=hist_b)
    assert steps_b == 6
    assert hist_a + hist_b == baseline


def test_auto_resume_off_restarts_from_scratch(baseline, tmp_path):
    """resilience.auto_resume=False restores start-from-scratch semantics
    even with checkpoints present."""
    d = tmp_path / "ckpt"
    train(_cfg(d, chaos_sigterm_step=3))
    hist = []
    train(_cfg(d, auto_resume=False), loss_history=hist)
    assert hist[0] == baseline[0]  # step 1 again, not step 4


# --------------------------------------------------------------------------- #
# anomaly policies
# --------------------------------------------------------------------------- #


def test_nan_skip_policy_logs_and_continues(tmp_path, capsys):
    """Policy 'skip' (default): the NaN step applies no update, is logged
    with step + policy, and training runs to completion."""
    hist = []
    steps, _, loss = train(_cfg(tmp_path / "ckpt", chaos_nan_step=2),
                           loss_history=hist)
    assert steps == 6
    assert not np.isfinite(hist[1][1])  # step 2 observed the injected NaN
    assert all(np.isfinite(l) for s, l in hist if s != 2)
    assert np.isfinite(loss)
    out = capsys.readouterr().out
    assert "loss anomaly at step 2" in out and "policy=skip" in out


def test_rollback_policy_restores_and_replays(baseline, tmp_path):
    """Policy 'rollback': after the NaN at step 5, restore the step-4
    checkpoint, reposition the loader, and replay — the replayed steps 5-6
    match the uninterrupted trajectory bit-for-bit."""
    hist = []
    steps, _, _ = train(
        _cfg(tmp_path / "ckpt", chaos_nan_step=5, anomaly_policy="rollback",
             rollback_after=1), loss_history=hist)
    assert steps == 6
    finite = [h for h in hist if np.isfinite(h[1])]
    assert finite == baseline  # 1-4, then replayed 5-6


def test_rollback_at_save_boundary_skips_the_anomalous_save(
        baseline, tmp_path):
    """An anomaly that fires ON a save boundary must not checkpoint the
    anomalous state before rolling back — the restore must come from the
    last GOOD checkpoint (step 2), and the replay must match the
    uninterrupted trajectory bit-for-bit. (With the save running first,
    the rollback would restore the just-saved bad step and replay the
    anomaly until max_rollbacks aborted.)"""
    hist = []
    steps, _, _ = train(
        _cfg(tmp_path / "ckpt", chaos_nan_step=4, anomaly_policy="rollback",
             rollback_after=1), loss_history=hist)
    assert steps == 6
    finite = [h for h in hist if np.isfinite(h[1])]
    # steps 1-3, then the replay from the restored step-2 checkpoint: 3-6
    assert finite == baseline[:3] + baseline[2:]


def test_abort_policy_raises_and_flushes(tmp_path):
    import picotron_tpu.checkpoint as ckpt

    d = tmp_path / "ckpt"
    with pytest.raises(AnomalyAbort, match="anomaly_policy='abort'"):
        train(_cfg(d, chaos_nan_step=3, anomaly_policy="abort"))
    # the finally flushed the pre-abort state (step 3: gate kept step-2 params)
    mgr = ckpt.CheckpointManager(str(d))
    assert mgr.latest_step() == 3
    mgr.close()


# --------------------------------------------------------------------------- #
# corrupt-latest fallback + data-geometry guard
# --------------------------------------------------------------------------- #


def test_truncated_latest_checkpoint_falls_back(baseline, tmp_path):
    """Chaos-truncate the newest step's largest file after its save: resume
    warns, falls back to the previous step, and completes on the baseline
    trajectory."""
    d = tmp_path / "ckpt"
    cfg = _cfg(d, chaos_truncate_step=4)
    cfg.training.total_train_steps = 4
    train(cfg)

    hist = []
    cfg2 = _cfg(d, io_attempts=1)  # deterministic corruption: don't re-poll
    with pytest.warns(RuntimeWarning, match="corrupt or partially written"):
        steps, _, _ = train(cfg2, loss_history=hist)
    assert steps == 6
    assert hist == baseline[2:]  # resumed from step 2, replayed 3-6


def test_changed_batch_geometry_fails_loudly(tmp_path):
    """Resume under a different micro-batch size: the recorded loader
    position no longer matches, and the run must refuse instead of silently
    training on different data."""
    d = tmp_path / "ckpt"
    train(_cfg(d, chaos_sigterm_step=3))
    cfg2 = make_config(_TINY, **{**_COMMON, "mbs": 1})
    cfg2.checkpoint.save_dir = str(d)
    cfg2.checkpoint.save_frequency = 2
    with pytest.raises(ValueError, match="batch geometry changed"):
        train(cfg2)


# --------------------------------------------------------------------------- #
# supervisor
# --------------------------------------------------------------------------- #

_CRASHY = textwrap.dedent("""
    import os, sys
    p = sys.argv[1]
    n = int(open(p).read()) if os.path.exists(p) else 0
    open(p, "w").write(str(n + 1))
    sys.exit(7 if n < 2 else 0)
""")


def test_supervisor_restarts_until_success(tmp_path):
    script = tmp_path / "crashy.py"
    script.write_text(_CRASHY)
    counter = tmp_path / "count"
    rc = run_supervised([sys.executable, str(script), str(counter)],
                        max_restarts=3, backoff=0.01)
    assert rc == 0
    assert counter.read_text() == "3"  # two crashes + the clean third run


def test_supervisor_bounds_restarts_and_propagates_exit_code(tmp_path):
    script = tmp_path / "crashy.py"
    script.write_text(_CRASHY)
    counter = tmp_path / "count"
    rc = run_supervised([sys.executable, str(script), str(counter)],
                        max_restarts=1, backoff=0.01)
    assert rc == 7  # the child's final exit code, not a lying zero
    assert counter.read_text() == "2"  # initial launch + exactly one restart


def test_supervisor_kills_stalled_trainer(tmp_path):
    script = tmp_path / "hang.py"
    script.write_text("import time\ntime.sleep(60)\n")
    hb = tmp_path / "hb"
    rc = run_supervised([sys.executable, str(script)], max_restarts=0,
                        heartbeat=str(hb), stall_timeout=0.5, term_grace=2.0,
                        poll_interval=0.05)
    assert rc == 143  # 128 + SIGTERM: the stall kill is visible to schedulers


# --------------------------------------------------------------------------- #
# config surface
# --------------------------------------------------------------------------- #


def make_config_resilience(**res):
    cfg = make_config(_TINY)
    for k, v in res.items():
        setattr(cfg.resilience, k, v)
    cfg.validate()
    return cfg


def test_resilience_config_validation_fields():
    with pytest.raises(ValueError, match="anomaly_policy"):
        make_config_resilience(anomaly_policy="explode")
    with pytest.raises(ValueError, match="save_frequency"):
        make_config_resilience(anomaly_policy="rollback")
    with pytest.raises(ValueError, match="io_attempts"):
        make_config_resilience(io_attempts=0)
    with pytest.raises(ValueError, match="steps_per_call"):
        cfg = make_config(_TINY, steps_per_call=2)
        cfg.resilience.chaos_nan_step = 3
        cfg.validate()
    # round trip: the resilience section survives to_dict/from_dict
    from picotron_tpu.config import Config

    cfg = make_config(_TINY)
    cfg.resilience.chaos_sigterm_step = 9
    cfg2 = Config.from_dict(cfg.to_dict())
    assert cfg2.resilience.chaos_sigterm_step == 9
    assert cfg2.resilience.anomaly_policy == "skip"
