"""picolint suite (ISSUE 9; docs/ANALYSIS.md).

Three layers, mirroring the suite's contract:

1. **Fixture snippets per rule** — for each rule ID a positive snippet
   (the seeded hazard MUST be caught by exactly that rule), a negative
   snippet (the idiomatic near-miss MUST stay silent: precision is what
   keeps the shipped baseline empty), and the suppression comment.
2. **Baseline workflow** — fingerprint matching survives line drift but
   re-opens when the flagged line changes; stale entries are reported;
   undocumented reasons are rejected.
3. **The tier-1 gate** — the repo's own package scans clean against the
   checked-in baseline (every true positive fixed, the baseline reserved
   for documented false positives), in well under the 30s budget, and the
   CLI exit codes enforce it.

The scan is pure ``ast`` — fixtures are never imported or executed, so
they can reference jax/pallas APIs freely without a TPU or even jax.
"""

import json
import textwrap

import pytest

from picotron_tpu.analysis import engine
from picotron_tpu.analysis.findings import (
    RULES, Suppressions, validate_rule_ids)
from picotron_tpu.tools import lint


def _scan(tmp_path, source, name="fix_mod.py"):
    """Write one fixture module and run the full suite over it."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return engine.run_suite(str(tmp_path), [str(p)])


def _rules(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------------------------- #
# PICO-J001: host sync on a traced value
# --------------------------------------------------------------------------- #


def test_j001_float_of_tracer_in_jitted_function(tmp_path):
    found = _scan(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            return float(x) + 1.0
        """)
    assert _rules(found) == ["PICO-J001"]
    assert found[0].context == "f"
    assert "float()" in found[0].message


def test_j001_item_and_device_get_and_np_asarray(tmp_path):
    found = _scan(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            a = x.item()
            b = np.asarray(x)
            c = jax.device_get(x)
            return a, b, c
        """)
    assert _rules(found) == ["PICO-J001"]
    assert len(found) == 3


def test_j001_bool_coercion_of_array_in_if(tmp_path):
    found = _scan(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            bad = jnp.any(x > 3)
            if bad:
                return x * 0
            return x
        """)
    assert _rules(found) == ["PICO-J001"]
    assert "bool coercion" in found[0].message


def test_j001_negatives_static_idioms_stay_silent(tmp_path):
    # the idioms jitted code legitimately uses: shape/dtype reads,
    # identity tests on optionals, static config flags, host-scalar
    # annotated params, and a float() on a TRANSITIVE helper's static arg
    found = _scan(tmp_path, """
        import jax
        import jax.numpy as jnp

        def helper(x, scale):
            return x * float(scale)  # scale is a static Python float here

        @jax.jit
        def f(x, cache=None, eps: float = 1e-6, use_flash: bool = False):
            n = x.shape[0]
            d = float(x.ndim + len(x.shape))
            if cache is not None:
                x = x + cache
            if use_flash:
                x = x * 2
            return helper(x, 0.5) + n + d + float(eps)
        """)
    assert found == []


def test_j001_negative_jax_numpy_aliased_as_np(tmp_path):
    # regression: `import jax.numpy as np` rebinds the name — np.asarray
    # is then a traced no-sync op, not host numpy
    found = _scan(tmp_path, """
        import jax
        import jax.numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x) + 1
        """)
    assert found == []


def test_j001_negative_subscript_index_stays_untainted(tmp_path):
    # regression: `out[i] = jnp.sum(a)` taints the container `out`, not
    # the host loop index `i` — `if last:` below is static control flow
    found = _scan(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, n: int = 4):
            out = {}
            last = 0
            for i in range(n):
                out[i] = jnp.sum(x)
                last = i
            if last:
                return out[0]
            return out[0] * 2
        """)
    assert found == []


def test_j001_hidden_state_hook_shape(tmp_path):
    """The ISSUE-14 return_hidden hook shape: a jitted verify-like body
    that scans a hidden-state carry and selects the row the traced
    counts point at (take_along_axis over clip(counts - 1)) must stay
    SILENT — all on-device ops; the hazard variant (host-syncing the
    traced hidden/counts with float()/np.asarray inside the program)
    must be caught by exactly J001."""
    found = _scan(tmp_path, """
        import jax
        import jax.numpy as jnp
        from jax import lax

        @jax.jit
        def verify(h, counts):
            def step(carry, x):
                hid = carry
                active = counts > 0
                hid = jnp.where(active[:, None], x, hid)
                return hid, None
            hid, _ = lax.scan(step, h[:, 0], jnp.swapaxes(h, 0, 1))
            idx = jnp.clip(counts - 1, 0, h.shape[1] - 1)[:, None, None]
            return jnp.take_along_axis(h, idx, axis=1)[:, 0], hid
        """)
    assert found == []

    bad = _scan(tmp_path, """
        import jax
        import numpy as np
        import jax.numpy as jnp

        @jax.jit
        def verify(h, counts):
            sel = np.asarray(h)          # host sync on the traced hidden
            return sel[float(counts[0])]  # and on the traced count
        """, name="fix_bad.py")
    assert _rules(bad) == ["PICO-J001"]
    assert len(bad) == 2


def test_j001_dp_shard_occupancy_read_placement(tmp_path):
    """The ISSUE-18 rebalance-planner shape: per-shard occupancy must be
    computed HOST-SIDE from the batcher's slot list, OUTSIDE the jitted
    dispatch (batcher.shard_occupancy) — a plain Python walk, silent.
    The hazard variant reads a TRACED occupancy count inside the
    dp-sharded dispatch (int()/bool-coercion host syncs on the decode
    hot path): exactly J001."""
    found = _scan(tmp_path, """
        import jax
        import jax.numpy as jnp

        def shard_occupancy(slots, slots_per_shard, dp_size):
            # host-side planner input: a walk over the Python slot list,
            # never a device value
            occ = [0] * dp_size
            for i, s in enumerate(slots):
                if s is not None:
                    occ[i // slots_per_shard] += 1
            return occ

        @jax.jit
        def dispatch(params, tokens, budget):
            # the dispatch only consumes traced arrays; occupancy never
            # enters the program
            active = (budget > 0).astype(jnp.int32)
            return tokens * active
        """)
    assert found == []

    bad = _scan(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def dispatch(tokens, budget, slots_per_shard: int = 2):
            occ = jnp.sum((budget > 0).astype(jnp.int32))
            if int(occ) > slots_per_shard:   # host sync mid-dispatch
                return tokens * 0
            return tokens
        """, name="fix_bad.py")
    assert _rules(bad) == ["PICO-J001"]


# --------------------------------------------------------------------------- #
# PICO-J002: host nondeterminism under trace
# --------------------------------------------------------------------------- #


def test_j002_time_and_np_random_under_trace(tmp_path):
    found = _scan(tmp_path, """
        import time
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            t = time.time()
            r = np.random.rand()
            return x + t + r
        """)
    assert _rules(found) == ["PICO-J002"]
    assert len(found) == 2


def test_j002_negative_host_code_and_jax_random(tmp_path):
    found = _scan(tmp_path, """
        import time
        import jax
        from jax import random

        def host_loop():
            return time.time()  # not traced: fine

        @jax.jit
        def f(x, key):
            return x + random.normal(key, x.shape)  # jax.random: fine
        """)
    assert found == []


def test_j002_through_dotted_import_with_package_init(tmp_path):
    # regression: with pkg/__init__.py in the scan, `pkg` and
    # `pkg.sub.mod` are BOTH scanned modules — `pkg.sub.mod.helper(x)`
    # must resolve helper in the deepest one, not stall at `pkg` and
    # drop the call-graph edge (hiding helper's trace-time hazard)
    pkg = tmp_path / "pkg"
    sub = pkg / "sub"
    sub.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (sub / "__init__.py").write_text("")
    (sub / "mod.py").write_text(textwrap.dedent("""
        import time

        def helper(x):
            return x + time.time()
        """))
    main = tmp_path / "main.py"
    main.write_text(textwrap.dedent("""
        import jax
        import pkg.sub.mod

        @jax.jit
        def f(x):
            return pkg.sub.mod.helper(x)
        """))
    found = engine.run_suite(str(tmp_path), [
        str(pkg / "__init__.py"), str(sub / "__init__.py"),
        str(sub / "mod.py"), str(main)])
    assert _rules(found) == ["PICO-J002"]
    assert "time.time" in found[0].message


# --------------------------------------------------------------------------- #
# PICO-J003: pl.program_id inside a loop body
# --------------------------------------------------------------------------- #


def test_j003_program_id_inside_fori_loop_body(tmp_path):
    found = _scan(tmp_path, """
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(o_ref):
            def body(j, acc):
                b = pl.program_id(0)  # the decode_attention.py trap
                return acc + b
            o_ref[0] = lax.fori_loop(0, 4, body, 0)
        """)
    assert _rules(found) == ["PICO-J003"]
    assert "program_id" in found[0].message


def test_j003_negative_read_before_the_loop(tmp_path):
    # the fix PR 5 shipped: grid ids read once, the body closes over them
    found = _scan(tmp_path, """
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(o_ref):
            b = pl.program_id(0)

            def body(j, acc):
                return acc + b
            o_ref[0] = lax.fori_loop(0, 4, body, 0)
        """)
    assert found == []


def test_j003_quant_matmul_shaped_contraction_walk(tmp_path):
    """The quant_matmul kernel pattern (ISSUE 13): a fori_loop contraction
    walk slicing refs with pl.ds. Using program_id to compute the slice
    start INSIDE the body is the hazard variant — J003 must catch it —
    while the shipped shape (ids unused, ds offsets from the loop index
    alone) stays silent."""
    found = _scan(tmp_path, """
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(x_ref, q_ref, o_ref):
            def body(j, acc):
                n = pl.program_id(1)  # the trap: resolve OUTSIDE the loop
                wb = q_ref[pl.ds(j * 8, 8), pl.ds(n * 8, 8)]
                return acc + x_ref[:, pl.ds(j * 8, 8)] @ wb
            o_ref[:] = lax.fori_loop(0, 4, body, 0.0)
        """)
    assert _rules(found) == ["PICO-J003"]

    clean = _scan(tmp_path, """
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(x_ref, q_ref, s_ref, o_ref):
            def body(j, acc):
                wb = q_ref[pl.ds(j * 8, 8), :].astype(x_ref.dtype)
                return acc + x_ref[:, pl.ds(j * 8, 8)] @ wb
            acc = lax.fori_loop(0, 4, body, 0.0)
            o_ref[:] = acc * s_ref[0, :]
        """, name="fix_clean.py")
    assert clean == []


def test_j003_lambda_body(tmp_path):
    found = _scan(tmp_path, """
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(o_ref):
            o_ref[0] = lax.fori_loop(
                0, 4, lambda j, acc: acc + pl.program_id(0), 0)
        """)
    assert _rules(found) == ["PICO-J003"]


def test_j003_ragged_mask_loop_shape(tmp_path):
    """The ISSUE-14 ragged-verify kernel shape: a per-slot fori_loop whose
    body builds a where-mask from the loop index and a valid-count row.
    The shipped form (slot id resolved OUTSIDE the loop, mask from jnp
    ops inside) must stay silent; reading program_id inside the masked
    body is the J003 hazard and must be caught — precision both ways, so
    the baseline stays empty."""
    found = _scan(tmp_path, """
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(v_ref, k_ref, o_ref):
            def body(j, acc):
                b = pl.program_id(0)  # the trap: resolve before the loop
                cols = jnp.arange(8)
                rows = jnp.where(cols < v_ref[b], cols, 8)
                return acc + k_ref[pl.ds(j * 8, 8), :] * rows[:, None]
            o_ref[:] = lax.fori_loop(0, 4, body, 0.0)
        """)
    assert _rules(found) == ["PICO-J003"]

    clean = _scan(tmp_path, """
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(v_ref, k_ref, o_ref):
            b = pl.program_id(0)
            valid = v_ref[b]

            def body(j, acc):
                cols = jnp.arange(8)
                rows = jnp.where(cols < valid, cols, 8)
                return acc + k_ref[pl.ds(j * 8, 8), :] * rows[:, None]
            o_ref[:] = lax.fori_loop(0, 4, body, 0.0)
        """, name="fix_clean.py")
    assert clean == []


# --------------------------------------------------------------------------- #
# PICO-J005: make_async_copy started without a reachable wait
# --------------------------------------------------------------------------- #


def test_j005_start_without_wait(tmp_path):
    found = _scan(tmp_path, """
        from jax.experimental.pallas import tpu as pltpu

        def kernel(src_ref, buf, sem, o_ref):
            dma = pltpu.make_async_copy(src_ref, buf, sem)
            dma.start()  # nothing ever waits: buf read mid-flight
            o_ref[0] = buf[0]
        """)
    assert _rules(found) == ["PICO-J005"]
    assert "wait" in found[0].message


def test_j005_start_in_loop_body_wait_outside(tmp_path):
    # the exact double-buffering hazard: a per-iteration start whose only
    # wait sits after the loop — N starts against 1 wait
    found = _scan(tmp_path, """
        from jax import lax
        from jax.experimental.pallas import tpu as pltpu

        def kernel(src_ref, buf, sem, o_ref):
            def body(j, acc):
                pltpu.make_async_copy(src_ref.at[j], buf, sem).start()
                return acc + buf[0]
            acc = lax.fori_loop(0, 4, body, 0.0)
            pltpu.make_async_copy(src_ref.at[0], buf, sem).wait()
            o_ref[0] = acc
        """)
    assert _rules(found) == ["PICO-J005"]
    assert "loop" in found[0].message


def test_j005_negative_paired_double_buffer_idiom(tmp_path):
    # the shipped decode-kernel shape: start/wait pairs built from the
    # same triples by sibling helper closures, warm-up start outside the
    # loop, per-iteration prefetch + wait inside — silent
    found = _scan(tmp_path, """
        from jax import lax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(src_ref, buf, sems, o_ref):
            def start(j, slot):
                pltpu.make_async_copy(src_ref.at[j], buf.at[slot],
                                      sems.at[slot]).start()

            def wait(j, slot):
                pltpu.make_async_copy(src_ref.at[j], buf.at[slot],
                                      sems.at[slot]).wait()
                return buf[slot]

            def body(j, acc):
                slot = lax.rem(j, 2)

                @pl.when(j + 1 < 4)
                def _():
                    start(j + 1, 1 - slot)
                return acc + wait(j, slot)[0]

            start(0, 0)
            o_ref[0] = lax.fori_loop(0, 4, body, 0.0)
        """)
    assert found == []


def test_j003_segmented_gather_adapter_walk(tmp_path):
    """The ISSUE-16 segmented multi-LoRA matmul shape
    (ops/pallas/lora_matmul.py): a per-row grid whose A/B blocks are
    steered by a scalar-prefetch adapter-id vector. The shipped form
    resolves the row id via the BlockSpec index maps — the kernel body
    never reads program_id at all — and an in-body rank-chunk walk that
    re-reads program_id per iteration to re-derive the adapter row is
    the J003 hazard. Precision both ways keeps the baseline empty."""
    found = _scan(tmp_path, """
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
            def body(j, acc):
                bi = pl.program_id(0)  # the trap: the index maps own this
                t = ids_ref[bi]
                ab = a_ref[t, pl.ds(j * 8, 8), :]
                return acc + x_ref[0, :, pl.ds(j * 8, 8)] @ ab
            o_ref[0] = lax.fori_loop(0, 4, body, 0.0) @ b_ref[0]
        """)
    assert _rules(found) == ["PICO-J003"]

    clean = _scan(tmp_path, """
        from jax import lax
        from jax.experimental import pallas as pl

        def kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
            def body(j, acc):
                ab = a_ref[0, pl.ds(j * 8, 8), :]
                return acc + x_ref[0, :, pl.ds(j * 8, 8)] @ ab
            t = lax.fori_loop(0, 4, body, 0.0)
            o_ref[0] = t @ b_ref[0]
        """, name="fix_clean.py")
    assert clean == []


def test_j005_segmented_gather_hand_rolled_dma(tmp_path):
    """The hand-rolled variant lora_matmul.py avoids: DMA-ing each row's
    chosen adapter pair into VMEM scratch inside a per-row loop. A
    per-iteration start whose only wait sits after the loop is the J005
    hazard; the paired in-body start+wait (serial gather) stays silent —
    the shipped kernel needs neither because scalar-prefetch index maps
    do the steering."""
    found = _scan(tmp_path, """
        from jax import lax
        from jax.experimental.pallas import tpu as pltpu

        def kernel(ids_ref, pack_ref, buf, sem, o_ref):
            def body(j, acc):
                pltpu.make_async_copy(pack_ref.at[ids_ref[j]], buf,
                                      sem).start()
                return acc + buf[0]
            acc = lax.fori_loop(0, 4, body, 0.0)
            pltpu.make_async_copy(pack_ref.at[0], buf, sem).wait()
            o_ref[0] = acc
        """)
    assert _rules(found) == ["PICO-J005"]

    clean = _scan(tmp_path, """
        from jax import lax
        from jax.experimental.pallas import tpu as pltpu

        def kernel(ids_ref, pack_ref, buf, sem, o_ref):
            def body(j, acc):
                dma = pltpu.make_async_copy(pack_ref.at[ids_ref[j]], buf,
                                            sem)
                dma.start()
                dma.wait()
                return acc + buf[0]
            o_ref[0] = lax.fori_loop(0, 4, body, 0.0)
        """, name="fix_clean.py")
    assert clean == []


def test_j005_negative_thread_start_and_serial_pair(tmp_path):
    # receiver typing: thread.start()/event.wait() are not DMAs; a serial
    # in-body start+wait pair is the pre-pipelining idiom and stays silent
    found = _scan(tmp_path, """
        import threading
        from jax import lax
        from jax.experimental.pallas import tpu as pltpu

        def host():
            t = threading.Thread(target=print)
            t.start()

        def kernel(src_ref, buf, sem, o_ref):
            def body(j, acc):
                dma = pltpu.make_async_copy(src_ref.at[j], buf, sem)
                dma.start()
                dma.wait()
                return acc + buf[0]
            o_ref[0] = lax.fori_loop(0, 4, body, 0.0)
        """)
    assert found == []


# --------------------------------------------------------------------------- #
# PICO-J004: jit/pallas_call constructed inside a loop
# --------------------------------------------------------------------------- #


def test_j004_jit_built_per_iteration(tmp_path):
    found = _scan(tmp_path, """
        import jax

        def build(fns, x):
            out = []
            for fn in fns:
                out.append(jax.jit(fn)(x))  # fresh callable every pass
            return out
        """)
    assert _rules(found) == ["PICO-J004"]
    assert "recompile" in found[0].message


def test_j004_page_transport_shaped_export_loop(tmp_path):
    """The ISSUE-15 page-transport shape: the export walks pinned pages
    through a jitted dynamic-slice gather. Building the jit INSIDE the
    per-page loop is the J004 hazard (a recompile per exported page);
    the shipped form — slice/write jits built once at engine
    construction, the loop calling the hoisted executables — must stay
    silent. Precision both ways, so the baseline stays empty."""
    found = _scan(tmp_path, """
        import jax
        from jax import lax

        def slice_page(cache, pid):
            return {n: lax.dynamic_slice_in_dim(a, pid, 1, axis=1)
                    for n, a in cache.items()}

        def export(cache, pids):
            out = []
            for pid in pids:
                out.append(jax.jit(slice_page)(cache, pid))  # per page!
            return out
        """)
    assert _rules(found) == ["PICO-J004"]

    clean = _scan(tmp_path, """
        import jax
        from jax import lax

        def slice_page(cache, pid):
            return {n: lax.dynamic_slice_in_dim(a, pid, 1, axis=1)
                    for n, a in cache.items()}

        SLICE = jax.jit(slice_page)

        def export(cache, pids):
            return [SLICE(cache, pid) for pid in pids]
        """, name="fix_clean.py")
    assert clean == []


def test_j004_negative_jit_in_for_iterator_expression(tmp_path):
    # regression: the iterator expression runs ONCE at loop setup —
    # `for batch in loader_of(jax.jit(step)):` must not fire; a jit in
    # a while TEST re-evaluates per pass and must
    found = _scan(tmp_path, """
        import jax

        def loader_of(step):
            return [step]

        def run(step):
            for batch in loader_of(jax.jit(step)):
                batch()
        """)
    assert found == []
    found = _scan(tmp_path, """
        import jax

        def run(step, x):
            while jax.jit(step)(x):
                x = x - 1
        """)
    assert _rules(found) == ["PICO-J004"]


def test_j004_negative_hoisted_jit_and_def_in_loop(tmp_path):
    found = _scan(tmp_path, """
        import jax

        def build(fns, xs):
            jitted = [jax.jit(f) for f in fns]  # comprehension, not a loop stmt

            def apply(x):
                return jax.jit(step)(x)  # built per CALL, not per iteration

            out = []
            for x in xs:
                out.append(jitted[0](x))
            return out

        def step(x):
            return x
        """)
    assert found == []


# --------------------------------------------------------------------------- #
# PICO-J006: model program dispatched outside _dispatch
# --------------------------------------------------------------------------- #


def test_j006_program_called_outside_dispatch(tmp_path):
    found = _scan(tmp_path, """
        class Engine:
            def _dispatch(self, call):
                return call()

            def decode(self, params, cache):
                return self._decode_jit(params, cache)
        """)
    assert _rules(found) == ["PICO-J006"]
    assert found[0].context == "Engine.decode"
    assert "_decode_jit" in found[0].message
    assert "_dispatch" in found[0].message


def test_j006_negative_routed_through_dispatch(tmp_path):
    found = _scan(tmp_path, """
        class Engine:
            def _dispatch(self, call):
                return call()

            def decode(self, params, cache):
                return self._dispatch(lambda: self._decode_jit(params, cache))

            def verify(self, params, cache):
                return self._dispatch(
                    call=lambda: self._verify_prog(params, cache))
        """)
    assert found == []


def test_j006_negative_housekeeping_and_builders(tmp_path):
    # Housekeeping jits take the cache (or nothing) first — not model
    # dispatches.  `_make_*` builders construct rather than run programs.
    found = _scan(tmp_path, """
        class Engine:
            def _dispatch(self, call):
                return call()

            def setup(self, params, cache, slot):
                self._decode_jit = self._make_decode_jit(params)
                cache = self._init_cache_jit(cache)
                cache = self._set_length_jit(cache, slot)
                return cache
        """)
    assert found == []


def test_j006_negative_class_without_dispatch(tmp_path):
    # The rule only binds classes that define the fault wrapper.
    found = _scan(tmp_path, """
        class Helper:
            def decode(self, params, cache):
                return self._decode_jit(params, cache)
        """)
    assert found == []


def test_j006_mixed_routed_and_direct_in_one_class(tmp_path):
    found = _scan(tmp_path, """
        class Engine:
            def _dispatch(self, call):
                try:
                    return call()
                except RuntimeError:
                    return call()

            def good(self, params, cache):
                return self._dispatch(lambda: self._block_jit(params, cache))

            def bad(self, params, cache):
                out = self._verify_jit(params, cache)
                return out
        """)
    assert _rules(found) == ["PICO-J006"]
    assert len(found) == 1
    assert found[0].context == "Engine.bad"
    assert "self._verify_jit" in found[0].snippet


# --------------------------------------------------------------------------- #
# PICO-C001: lock-order inversion
# --------------------------------------------------------------------------- #

_C001_FIXTURE = """
    import threading

    class Inverted:
        def __init__(self):
            self.a_mu = threading.Lock()
            self.b_mu = threading.Lock()
            self.x = 0

        def one(self):
            with self.a_mu:
                with self.b_mu:
                    self.x = 1

        def two(self):
            with self.b_mu:
                with self.a_mu:
                    self.x = 2
    """


def test_c001_lock_order_inversion(tmp_path):
    found = _scan(tmp_path, _C001_FIXTURE)
    assert _rules(found) == ["PICO-C001"]
    assert len(found) == 1  # one inversion, reported once
    assert "opposite" in found[0].message


def test_c001_negative_consistent_order_and_transitive(tmp_path):
    # same nesting everywhere — including through a same-class call — is
    # a hierarchy, not an inversion
    found = _scan(tmp_path, """
        import threading

        class Ordered:
            def __init__(self):
                self.a_mu = threading.Lock()
                self.b_mu = threading.Lock()
                self.x = 0

            def one(self):
                with self.a_mu:
                    with self.b_mu:
                        self.x = 1

            def two(self):
                with self.a_mu:
                    self._locked_tail()

            def _locked_tail(self):
                with self.b_mu:
                    self.x = 2
        """)
    assert found == []


def test_c001_transitive_inversion_through_method_call(tmp_path):
    # one path nests a->b lexically; the other holds b and CALLS a method
    # that takes a — the deadlock picolint exists to catch (the PR 6
    # _next_uid-under-_mu incident shape)
    found = _scan(tmp_path, """
        import threading

        class Transitive:
            def __init__(self):
                self.a_mu = threading.Lock()
                self.b_mu = threading.Lock()
                self.x = 0

            def one(self):
                with self.a_mu:
                    with self.b_mu:
                        self.x = 1

            def two(self):
                with self.b_mu:
                    self._take_a()

            def _take_a(self):
                with self.a_mu:
                    self.x = 2
        """)
    assert "PICO-C001" in _rules(found)


# --------------------------------------------------------------------------- #
# PICO-C002: blocking call while holding a lock
# --------------------------------------------------------------------------- #


def test_c002_sleep_under_lock(tmp_path):
    found = _scan(tmp_path, """
        import threading
        import time

        class Sleeper:
            def __init__(self):
                self._mu = threading.Lock()

            def hold(self):
                with self._mu:
                    time.sleep(0.5)
        """)
    assert _rules(found) == ["PICO-C002"]
    assert "time.sleep" in found[0].message


def test_c002_blocking_io_and_join_under_lock(tmp_path):
    found = _scan(tmp_path, """
        import shutil
        import threading

        class Copier:
            def __init__(self):
                self._mu = threading.Lock()
                self._worker = None

            def hold(self, src, dst):
                with self._mu:
                    shutil.copytree(src, dst)
                    self._worker.join()
        """)
    assert _rules(found) == ["PICO-C002"]
    assert len(found) == 2


def test_c002_negative_str_join_under_lock(tmp_path):
    # regression: `sep.join(parts)` is string building (one iterable
    # arg), not a thread join — `t.join(5)` (numeric timeout) still is
    found = _scan(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._mu = threading.Lock()
                self.sep = ","
                self.parts = []
                self.worker = threading.Thread(target=self.render)

            def render(self):
                with self._mu:
                    return self.sep.join(self.parts)

            def stop(self):
                with self._mu:
                    self.worker.join(5)
        """)
    assert _rules(found) == ["PICO-C002"]
    assert all("worker.join" in f.message for f in found)


def test_c002_one_hop_propagation_and_negatives(tmp_path):
    # sleep in a LOCK-FREE callee is fine alone, a hazard when the caller
    # holds the lock across the call; str.join and os.path.join are not
    # blocking calls
    found = _scan(tmp_path, """
        import os
        import threading
        import time

        class Indirect:
            def __init__(self):
                self._mu = threading.Lock()

            def _backoff(self):
                time.sleep(0.1)  # lock-free here: fine

            def hold(self):
                with self._mu:
                    self._backoff()

            def harmless(self, parts):
                with self._mu:
                    a = ",".join(str(p) for p in parts)
                    return os.path.join(a, "x")
        """)
    assert _rules(found) == ["PICO-C002"]
    assert len(found) == 1
    assert "_backoff" in found[0].message


# --------------------------------------------------------------------------- #
# PICO-C003: guarded attribute mutated outside its lock
# --------------------------------------------------------------------------- #

_C003_FIXTURE = """
    import threading

    class Counter:
        def __init__(self):
            self._mu = threading.Lock()
            self.count = 0

        def locked_inc(self):
            with self._mu:
                self.count += 1

        def unlocked_inc(self):
            self.count += 1  # the serve.py rejections incident shape
    """


def test_c003_mutation_outside_the_guarding_lock(tmp_path):
    found = _scan(tmp_path, _C003_FIXTURE)
    assert _rules(found) == ["PICO-C003"]
    assert found[0].context == "Counter.unlocked_inc"


def test_c003_negatives_init_and_consistent_guarding(tmp_path):
    # __init__ runs before any thread exists; queues/events are the
    # sanctioned channels; consistently-guarded attrs are clean
    found = _scan(tmp_path, """
        import queue
        import threading

        class Clean:
            def __init__(self):
                self._mu = threading.Lock()
                self.count = 0
                self.inbox = queue.Queue()

            def inc(self):
                with self._mu:
                    self.count += 1

            def push(self, item):
                self.inbox.put(item)
        """)
    assert found == []


def test_c003_stats_scrape_scratch_fields_regression(tmp_path):
    """The batcher stats() race this repo shipped (and fixed alongside
    the overlap pipeline): the dispatch loop wrote ``_host_sync_s`` /
    ``_last_prefill`` bare while a server thread's stats() scrape read
    them — once the scrape takes a leaf lock, the loop's bare writes are
    exactly C003's mutated-outside-the-guarding-lock shape. The fixture
    mirrors inference/batcher.py's fields so a relapse trips here."""
    found = _scan(tmp_path, """
        import threading

        class Batcher:
            def __init__(self):
                self._scratch_mu = threading.Lock()
                self._host_sync_s = 0.0
                self._last_prefill = {}

            def _sync_round(self, dt):
                with self._scratch_mu:
                    self._host_sync_s = dt

            def _fallback_round(self, dt):
                self._host_sync_s = dt  # one path missed: the relapse

            def stats(self):
                with self._scratch_mu:
                    return {"last_host_sync_s": self._host_sync_s,
                            "last_prefill": dict(self._last_prefill)}
        """)
    assert _rules(found) == ["PICO-C003"]
    assert found[0].context == "Batcher._fallback_round"


def test_c003_negative_scratch_snapshots_under_leaf_lock(tmp_path):
    """The FIXED batcher shape stays clean: every write of the scratch
    fields and the scrape's snapshot sit under the same leaf lock, and
    the blocking device sync (C002's concern) happens OUTSIDE it — the
    lock wraps only the dict copy and float store."""
    found = _scan(tmp_path, """
        import threading
        import time

        class Batcher:
            def __init__(self):
                self._scratch_mu = threading.Lock()
                self._host_sync_s = 0.0
                self._last_prefill = {}

            def _sync_round(self, materialize, t0):
                materialize()       # device sync: blocks, lock-free
                time.sleep(0.001)   # synthetic device window: lock-free
                with self._scratch_mu:
                    self._host_sync_s = time.monotonic() - t0

            def _prefill(self, info):
                with self._scratch_mu:
                    self._last_prefill = dict(info)

            def stats(self):
                with self._scratch_mu:
                    return {"last_host_sync_s": self._host_sync_s,
                            "last_prefill": dict(self._last_prefill)}
        """)
    assert found == []


def test_c002_positive_device_sync_under_scratch_lock(tmp_path):
    """The tempting wrong fix for the stats() race — wrap the whole sync
    stage, blocking wait included, in the scratch lock — trades a race
    for a stalled scrape plane: C002 flags the sleep held under the
    lock, which is why the leaf lock wraps only the snapshot."""
    found = _scan(tmp_path, """
        import threading
        import time

        class Batcher:
            def __init__(self):
                self._scratch_mu = threading.Lock()
                self._host_sync_s = 0.0

            def _sync_round(self, t0):
                with self._scratch_mu:
                    time.sleep(0.001)  # blocking under the leaf lock
                    self._host_sync_s = time.monotonic() - t0

            def stats(self):
                with self._scratch_mu:
                    return {"last_host_sync_s": self._host_sync_s}
        """)
    assert "PICO-C002" in _rules(found)


def test_c003_negative_thread_starting_method_is_exempt(tmp_path):
    # regression: writes in the method that STARTS the worker thread
    # happen-before Thread.start, same as __init__ (module docstring
    # contract) — resetting state there needs no lock
    found = _scan(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._mu = threading.Lock()
                self.count = 0

            def start(self):
                self.count = 0
                threading.Thread(target=self._worker, daemon=True).start()

            def _worker(self):
                with self._mu:
                    self.count += 1
        """)
    assert found == []


# --------------------------------------------------------------------------- #
# PICO-C004: cross-thread mutation with no lock anywhere
# --------------------------------------------------------------------------- #


def test_c004_worker_and_foreground_mutate_unlocked(tmp_path):
    found = _scan(tmp_path, """
        import threading

        class Mirror:
            def __init__(self):
                self.errs = []

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()

            def _worker(self):
                self.errs.append("boom")  # the checkpoint.py incident shape

            def drain(self):
                out, self.errs = self.errs, []
                return out
        """)
    assert _rules(found) == ["PICO-C004"]
    assert "_worker" in found[0].context


def test_c004_negative_lock_on_both_sides(tmp_path):
    found = _scan(tmp_path, """
        import threading

        class Guarded:
            def __init__(self):
                self._mu = threading.Lock()
                self.errs = []

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()

            def _worker(self):
                with self._mu:
                    self.errs.append("boom")

            def drain(self):
                with self._mu:
                    out, self.errs = self.errs, []
                return out
        """)
    assert found == []


# --------------------------------------------------------------------------- #
# suppression comments
# --------------------------------------------------------------------------- #


def test_suppression_on_the_flagged_line(tmp_path):
    found = _scan(tmp_path, """
        import jax

        @jax.jit
        def f(x):
            return float(x)  # picolint: disable=PICO-J001
        """)
    assert found == []


def test_suppression_bare_suffix_and_file_scope(tmp_path):
    found = _scan(tmp_path, """
        # picolint: disable-file=C002
        import threading
        import time

        class Sleeper:
            def __init__(self):
                self._mu = threading.Lock()

            def hold(self):
                with self._mu:
                    time.sleep(0.5)
        """)
    assert found == []


def test_suppression_is_rule_specific(tmp_path):
    # disabling one rule must not swallow another rule's finding there
    found = _scan(tmp_path, """
        import jax
        import time

        @jax.jit
        def f(x):
            t = time.time() + float(x)  # picolint: disable=PICO-J002
            return t
        """)
    assert _rules(found) == ["PICO-J001"]


def test_suppression_parsing_and_rule_validation():
    sup = Suppressions.parse(
        "x = 1  # picolint: disable=J001, PICO-C002\n"
        "# picolint: disable-file=all\n")
    assert sup.by_line[1] == {"PICO-J001", "PICO-C002"}
    assert sup.whole_file == {"*"}
    assert validate_rule_ids(["PICO-J001", "*"]) is None
    assert validate_rule_ids(["PICO-J001", "PICO-Z999"]) == "PICO-Z999"


# --------------------------------------------------------------------------- #
# baseline workflow
# --------------------------------------------------------------------------- #


def _write_baseline(path, entries):
    path.write_text(json.dumps({"findings": entries}, indent=2))


def test_baseline_matches_by_fingerprint_not_line(tmp_path):
    src = """
        import jax

        @jax.jit
        def f(x):
            return float(x)
        """
    found = _scan(tmp_path, src)
    assert len(found) == 1
    bl = tmp_path / "baseline.json"
    _write_baseline(bl, [engine.baseline_entry(
        found[0], reason="fixture: demonstrating the baseline contract")])

    # line drift above the finding does not re-open it
    drifted = "# a new leading comment\n# another\n" + textwrap.dedent(src)
    (tmp_path / "fix_mod.py").write_text(drifted)
    out = engine.run(str(tmp_path), [str(tmp_path / "fix_mod.py")],
                     baseline_path=str(bl))
    assert out["counts"] == {"total": 1, "new": 0, "baselined": 1,
                             "stale_baseline": 0}

    # editing the FLAGGED line re-opens the finding and stales the entry
    edited = drifted.replace("float(x)", "float(x * 2)")
    (tmp_path / "fix_mod.py").write_text(edited)
    out = engine.run(str(tmp_path), [str(tmp_path / "fix_mod.py")],
                     baseline_path=str(bl))
    assert out["counts"]["new"] == 1
    assert out["counts"]["stale_baseline"] == 1


def test_baseline_undocumented_reasons_are_rejected():
    entries = [
        {"rule": "PICO-J001", "path": "a.py", "context": "f",
         "snippet": "x", "reason": "identity test on a static optional"},
        {"rule": "PICO-J001", "path": "b.py", "context": "g",
         "snippet": "y", "reason": ""},
        {"rule": "PICO-J001", "path": "c.py", "context": "h",
         "snippet": "z", "reason": "TODO: document why"},
    ]
    bad = engine.undocumented_entries(entries)
    assert [e["path"] for e in bad] == ["b.py", "c.py"]


def test_baseline_duplicate_fingerprints_are_counted(tmp_path):
    # two identical findings against ONE baseline entry: one stays new
    src = """
        import jax

        @jax.jit
        def f(x, flip=None):
            if flip is None:
                return float(x)
            return float(x)
        """
    found = _scan(tmp_path, src)
    assert len(found) == 2
    assert found[0].fingerprint() == found[1].fingerprint()
    new, matched, stale = engine.diff_baseline(
        found, [engine.baseline_entry(found[0], reason="fixture")])
    assert len(new) == 1 and len(matched) == 1 and stale == []


# --------------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------------- #


def test_cli_exit_codes_and_json(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            return float(x)
        """))
    clean = tmp_path / "clean.py"
    clean.write_text("def f(x):\n    return x\n")
    bl = str(tmp_path / "baseline.json")

    assert lint.main([str(bad), "--baseline", bl]) == 1
    capsys.readouterr()
    assert lint.main([str(clean), "--baseline", bl]) == 0
    capsys.readouterr()
    assert lint.main([str(bad), "--baseline", bl,
                      "--no-fail-on-new"]) == 0
    capsys.readouterr()

    assert lint.main([str(bad), "--baseline", bl, "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["tool"] == "picolint"
    assert report["counts"]["new"] == 1
    assert report["new"][0]["rule"] == "PICO-J001"
    assert set(report["rules"]) == set(RULES)

    assert lint.main(["--rules", "PICO-NOPE"]) == 2
    assert lint.main([str(tmp_path / "missing.py")]) == 2


def test_cli_rules_narrows_report_not_the_gate(tmp_path, capsys):
    # regression: --rules filters what is PRINTED; the exit-code gate
    # still fails on new findings from every other rule
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            return float(x)
        """))
    bl = str(tmp_path / "baseline.json")
    assert lint.main([str(bad), "--baseline", bl,
                      "--rules", "PICO-C002", "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["counts"]["new"] == 0  # J001 hidden from the report...
    # ...but the run still failed, so --rules cannot launder a finding


def test_cli_baselined_count_uses_the_budget_split(tmp_path, capsys):
    # two findings with the SAME fingerprint (same snippet text +
    # context, different lines) against one baseline entry: the CLI
    # report must carry diff_baseline's budget split through — exactly
    # one baselined, one new — not re-derive matched on its own
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            y = float(x)
            y = float(x)
            return y
        """))
    bl = tmp_path / "baseline.json"
    findings = engine.run_suite(str(tmp_path), [str(bad)])
    assert len(findings) == 2
    assert findings[0].fingerprint() == findings[1].fingerprint()
    bl.write_text(json.dumps(
        {"findings": [engine.baseline_entry(
            findings[0], reason="fixture: one of the two is baselined")]}))
    assert lint.main([str(bad), "--baseline", str(bl), "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["counts"]["baselined"] == 1
    assert report["counts"]["new"] == 1


def test_cli_malformed_baseline_is_a_usage_error(tmp_path, capsys):
    # regression: a baseline object without "findings" must exit 2 with
    # a descriptive message, not crash with a raw KeyError
    bl = tmp_path / "baseline.json"
    bl.write_text('{"entries": []}')
    clean = tmp_path / "clean.py"
    clean.write_text("def f(x):\n    return x\n")
    assert lint.main([str(clean), "--baseline", str(bl)]) == 2
    assert "findings" in capsys.readouterr().err


def test_cli_root_is_stable_across_invocation_shapes(tmp_path, capsys):
    # regression: out-of-repo, `lint proj` and `lint proj/bad.py` must
    # report the same file under the same relative path — fingerprints
    # (and so baselines) would otherwise churn with the invocation shape
    proj = tmp_path / "proj"
    (proj / "pkg").mkdir(parents=True)
    (proj / "pkg" / "other.py").write_text("def g(x):\n    return x\n")
    (proj / "bad.py").write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            return float(x)
        """))
    bl = str(tmp_path / "baseline.json")
    paths = []
    for spec in ([str(proj)], [str(proj / "bad.py")]):
        assert lint.main(spec + ["--baseline", bl, "--json"]) == 1
        report = json.loads(capsys.readouterr().out)
        paths.append(report["new"][0]["path"])
    assert paths[0] == paths[1] == "bad.py"


def test_cli_partial_scan_does_not_stale_out_of_scope_entries(tmp_path,
                                                              capsys):
    # regression: a baseline entry for a file the scan did not cover is
    # not evidence the entry is dead — only a scan that includes the
    # file may call it stale
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            return float(x)
        """))
    other = tmp_path / "other.py"
    other.write_text("def g(x):\n    return x\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": [{
        "rule": "PICO-C002", "path": "other.py", "context": "X.m",
        "snippet": "time.sleep(1)",
        "reason": "fixture: documented entry for an unscanned file"}]}))
    assert lint.main([str(bad), "--baseline", str(bl), "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["counts"]["stale_baseline"] == 0  # other.py not scanned
    assert lint.main([str(bad), str(other), "--baseline", str(bl),
                      "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["counts"]["stale_baseline"] == 1  # scanned and clean


def test_cli_rules_canonicalize_like_suppressions(tmp_path, capsys):
    # regression: `--rules j001` spells the same as a suppression
    # comment; `--rules '*'` means every rule, not an empty report
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            return float(x)
        """))
    bl = str(tmp_path / "baseline.json")
    assert lint.main([str(bad), "--baseline", bl,
                      "--rules", "j001", "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["counts"]["new"] == 1
    assert lint.main([str(bad), "--baseline", bl,
                      "--rules", "*", "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["counts"]["new"] == 1


def test_cli_empty_scope_scans_nothing(tmp_path, capsys):
    # regression: a directory with no .py files must scan ZERO files,
    # not silently fall back to the whole repo
    empty = tmp_path / "empty"
    empty.mkdir()
    (empty / "README.txt").write_text("no python here")
    assert lint.main([str(empty), "--baseline",
                      str(tmp_path / "baseline.json"), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["counts"]["total"] == 0


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            return float(x)
        """))
    bl = tmp_path / "baseline.json"
    # --write-baseline records the finding (exit 0) with a placeholder
    # reason that the documentation gate then rejects until filled in
    assert lint.main([str(bad), "--baseline", str(bl),
                      "--write-baseline"]) == 0
    capsys.readouterr()
    entries = engine.load_baseline(str(bl))
    assert len(entries) == 1
    assert engine.undocumented_entries(entries) == entries
    # once baselined, the same scan is clean
    assert lint.main([str(bad), "--baseline", str(bl)]) == 0


# --------------------------------------------------------------------------- #
# the tier-1 gate: the repo's own tree is clean
# --------------------------------------------------------------------------- #


def test_seeded_hazards_each_caught_by_exactly_their_rule(tmp_path):
    """The acceptance fixtures from ISSUE 9, one rule each."""
    cases = {
        "PICO-J003": """
            from jax import lax
            from jax.experimental import pallas as pl

            def kernel(o_ref):
                def body(j, acc):
                    return acc + pl.program_id(0)
                o_ref[0] = lax.fori_loop(0, 4, body, 0)
            """,
        "PICO-J001": """
            import jax

            @jax.jit
            def f(x):
                return float(x)
            """,
        "PICO-C001": _C001_FIXTURE,
        "PICO-C002": """
            import threading
            import time

            class S:
                def __init__(self):
                    self._mu = threading.Lock()

                def hold(self):
                    with self._mu:
                        time.sleep(1.0)
            """,
    }
    for rule, src in cases.items():
        found = _scan(tmp_path, src, name=f"{rule.lower().replace('-', '_')}.py")
        assert _rules(found) == [rule], (
            f"seeded {rule} fixture found {_rules(found)}")


def test_repo_self_scan_is_clean_against_baseline():
    """Every future PR is gated on this: the package has no new findings,
    no stale baseline entries, every baseline entry documents WHY it is a
    false positive, and the scan fits the <30s budget."""
    root, files = lint._scan_spec([])
    out = engine.run(root, files)
    assert not out["_new"], "new picolint findings:\n" + "\n".join(
        f.render() for f in out["_new"])
    assert not out["_stale"], (
        "stale baseline entries (the finding no longer fires — remove "
        f"them): {out['_stale']}")
    bad = engine.undocumented_entries(out["_baseline"])
    assert not bad, f"baseline entries without a documented reason: {bad}"
    assert out["elapsed_s"] < 30


def test_cli_default_scan_exits_zero():
    """`python -m picotron_tpu.tools.lint` — the `make lint` contract."""
    assert lint.main(["--json"]) == 0


def test_rule_catalog_is_stable():
    """Rule IDs are API (baselines, suppressions, docs cross-links):
    removing or renaming one breaks every consumer."""
    assert set(RULES) == {
        "PICO-J001", "PICO-J002", "PICO-J003", "PICO-J004", "PICO-J005",
        "PICO-J006",
        "PICO-C001", "PICO-C002", "PICO-C003", "PICO-C004"}
    for rule in RULES.values():
        assert rule.title and rule.rationale


# --------------------------------------------------------------------------- #
# fleet-controller thread fixture (ISSUE 17): the tools/fleet.py locking
# discipline — leaf ``_mu`` for worker STATE only, every scrape/launch
# I/O outside it — modeled as a lint fixture so the discipline that keeps
# ``make lint`` clean with an empty baseline is itself pinned by a test.
# --------------------------------------------------------------------------- #

_FLEET_CLEAN = """
    import threading
    import time

    class Controller:
        def __init__(self):
            self._mu = threading.Lock()
            self._workers = {}
            self._stop = threading.Event()
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

        def _run(self):
            while not self._stop.wait(0.05):
                self.tick()

        def _scrape(self, name):
            time.sleep(0.01)  # stands in for the HTTP metrics scrape
            return {"queue_depth": 0.0}

        def tick(self):
            with self._mu:
                names = list(self._workers)
            scrapes = {n: self._scrape(n) for n in names}
            with self._mu:
                for n, s in scrapes.items():
                    if n in self._workers:
                        self._workers[n] = s

        def stop(self):
            self._stop.set()
            t = self._thread
            if t is not None:
                t.join(timeout=5)
    """


def test_fleet_controller_thread_pattern_scans_clean(tmp_path):
    """The controller idiom — snapshot names under ``_mu``, scrape with
    the lock RELEASED, re-take it to apply — produces zero findings: the
    pattern tools/fleet.py ships with an empty baseline."""
    assert _scan(tmp_path, _FLEET_CLEAN) == []


def test_fleet_controller_scrape_under_lock_is_caught(tmp_path):
    """The tempting shortcut — scraping each worker while still holding
    ``_mu`` — is exactly the hazard C002's one-hop propagation exists
    for: the tick thread would serialize every HTTP round-trip against
    the admin/stop paths."""
    found = _scan(tmp_path, """
        import threading
        import time

        class Controller:
            def __init__(self):
                self._mu = threading.Lock()
                self._workers = {}

            def _scrape(self, name):
                time.sleep(0.01)  # the HTTP round-trip
                return {"queue_depth": 0.0}

            def tick(self):
                with self._mu:
                    for name in list(self._workers):
                        self._workers[name] = self._scrape(name)
        """)
    assert _rules(found) == ["PICO-C002"]
    assert "_scrape" in found[0].message
