"""Fused linear+CE vs the gathered-logits oracle: same value, same grads,
single-shard and vocab-sharded over 'tp' (ops/cross_entropy.py)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from picotron_tpu.ops.cross_entropy import (
    cross_entropy_fused,
    cross_entropy_gathered,
    cross_entropy_vocab_parallel,
)
from picotron_tpu.utils import shard_map as shard_map_compat


def _data(B=2, S=64, H=32, V=256, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(ks[0], (B, S, H), jnp.float32)
    w = jax.random.normal(ks[1], (H, V), jnp.float32) * 0.05
    t = jax.random.randint(ks[2], (B, S), 0, V)
    return x, w, t


def _run_tp1(fn, x, w, t):
    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    return shard_map_compat(fn, mesh=mesh, in_specs=(P(), P(), P()),
                         out_specs=P(), check_vma=False)(x, w, t)


def test_fused_value_matches_gathered():
    x, w, t = _data()
    ref = _run_tp1(lambda x, w, t: cross_entropy_gathered(x @ w, t), x, w, t)
    got = _run_tp1(lambda x, w, t: cross_entropy_fused(x, w, t), x, w, t)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5)


def test_fused_chunked_value_matches_unchunked():
    x, w, t = _data(B=2, S=64)  # 128 rows, chunk 32 -> 4 chunks
    one = _run_tp1(lambda x, w, t: cross_entropy_fused(x, w, t, "tp", 128), x, w, t)
    four = _run_tp1(lambda x, w, t: cross_entropy_fused(x, w, t, "tp", 32), x, w, t)
    np.testing.assert_allclose(np.asarray(four), np.asarray(one), rtol=1e-6)


def test_fused_nondivisible_rows_pads():
    """T=96 rows with chunk 40 -> 3 padded chunks; value and grads must
    still match the unchunked oracle (padding contributes nothing)."""
    x, w, t = _data(B=2, S=48)

    def g(fn):
        def inner(x, w, t):
            loss, grads = jax.value_and_grad(
                lambda x, w: fn(x, w, t), argnums=(0, 1))(x, w)
            return loss, grads
        return _run_tp1(inner, x, w, t)

    ref_l, (ref_dx, ref_dw) = g(lambda x, w, t: cross_entropy_gathered(x @ w, t))
    got_l, (got_dx, got_dw) = g(lambda x, w, t: cross_entropy_fused(x, w, t, "tp", 40))
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got_dx), np.asarray(ref_dx),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(ref_dw),
                               rtol=2e-4, atol=1e-6)


def test_fused_grads_match_gathered():
    x, w, t = _data()

    def g(fn):
        def inner(x, w, t):
            return jax.grad(lambda x, w: fn(x, w, t), argnums=(0, 1))(x, w)
        return _run_tp1(inner, x, w, t)

    ref_dx, ref_dw = g(lambda x, w, t: cross_entropy_gathered(x @ w, t))
    got_dx, got_dw = g(lambda x, w, t: cross_entropy_fused(x, w, t, "tp", 32))
    np.testing.assert_allclose(np.asarray(got_dx), np.asarray(ref_dx),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(ref_dw),
                               rtol=2e-4, atol=1e-6)


def test_fused_tp_sharded_matches_single():
    """Vocab-sharded over tp=4: fused loss and (psum-completed) dx match the
    unsharded oracle; dw shards match the oracle's slices."""
    x, w, t = _data(V=256)
    tp = 4
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def sharded(x, w, t):
        # dx partial + tp_copy-style completion psum, as in the model
        def loss_fn(x, w):
            return cross_entropy_fused(x, w, t, "tp", 32)

        loss, (dx, dw) = jax.value_and_grad(loss_fn, argnums=(0, 1))(x, w)
        return loss, jax.lax.psum(dx, "tp"), dw

    loss, dx, dw = shard_map_compat(
        sharded, mesh=mesh, in_specs=(P(), P(None, "tp"), P()),
        out_specs=(P(), P(), P(None, "tp")), check_vma=False)(x, w, t)

    def ref_fn(x, w):
        logits = (x @ w).astype(jnp.float32)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
        return jnp.mean(logz - tl)

    ref_loss, (ref_dx, ref_dw) = jax.value_and_grad(ref_fn, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(ref_dw),
                               rtol=2e-4, atol=1e-6)


def test_vocab_parallel_matches_gathered_tp_sharded():
    x, w, t = _data(V=256)
    tp = 4
    mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))

    def run(fn):
        return shard_map_compat(fn, mesh=mesh, in_specs=(P(), P(None, "tp"), P()),
                             out_specs=P(), check_vma=False)(x, w, t)

    ref = run(lambda x, w, t: cross_entropy_gathered(x @ w, t))
    got = run(lambda x, w, t: cross_entropy_vocab_parallel(x @ w, t))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5)
