"""Serving resilience suite (ISSUE 6; docs/SERVING.md).

The serving stack's fault surfaces, each with a deterministic chaos
trigger and a bit-for-bit oracle where one exists:

- the sampler's non-finite gate (greedy over a sanitized distribution);
- dispatch retry (a transient exception costs nothing — outputs equal a
  fault-free run exactly);
- slot-failure isolation (a persistently failing slot finishes "error";
  SURVIVING slots' outputs are bit-identical to a fault-free run; no slot
  or queue entry leaks);
- flash->dense graceful degradation (process-wide, logged once,
  generation equals a dense engine's bit-for-bit);
- the HTTP front end (tools/serve.py): admission control (bounded queue
  503, token budget 429, Retry-After), streaming, SIGTERM-style drain
  with shed accounting, the stall watchdog, /healthz //readyz //statz;
- the serve-chaos acceptance: dispatch-exception + latency-spike +
  poisoned-logits faults in one run — no hangs, every submitted request
  terminates with an accounted finish_reason, unaffected requests
  bit-identical to a chaos-off run.

``make serve-chaos-smoke`` runs exactly this file.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    Request,
    sampling,
)
from picotron_tpu.models import llama
from picotron_tpu.resilience.chaos import ChaosError, ServingChaos
from picotron_tpu.tools import serve

MAX_LEN = 64


def _res(**kw):
    """A ResilienceConfig with serving-chaos overrides."""
    cfg = make_config(dict(_TINY))
    for k, v in kw.items():
        setattr(cfg.resilience, k, v)
    cfg.validate()
    return cfg.resilience


_TINY = dict(
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    hidden_size=32, intermediate_size=64, vocab_size=128,
    max_position_embeddings=MAX_LEN, rope_theta=10000.0, dtype="float32",
    attention_impl="sdpa")


def _engine(slots=3, hooks=None, **inf):
    cfg = make_config(dict(_TINY), seq=32)
    for k, v in inf.items():
        setattr(cfg.inference, k, v)
    engine = InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN,
                             hooks=hooks)
    params = engine.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    return cfg, engine, params


def _requests(n=3, temperature=0.0, max_new=8):
    # even-indexed requests carry the stochastic sampling params, so in the
    # isolation test (slot 1 faulted) the SURVIVORS include a sampled row
    return [Request(f"q{i}", [3 + i, 7 + i, 11 + i], max_new_tokens=max_new,
                    temperature=0.0 if i % 2 else temperature)
            for i in range(n)]


# --------------------------------------------------------------------------- #
# sampler non-finite gate
# --------------------------------------------------------------------------- #


def test_sampler_nonfinite_gate_greedy_over_sanitized():
    """Rows with non-finite logits emit the argmax of the FINITE entries
    (token 0 when nothing survives) on both the greedy and the stochastic
    path; finite rows are bit-identical to the ungated sampler."""
    V = 16
    logits = np.zeros((4, V), np.float32)
    logits[0, 5] = 3.0                      # finite row
    logits[1, 7] = 2.0
    logits[1, 9] = np.nan                   # partially poisoned
    logits[2, :] = np.nan                   # fully poisoned
    logits[3, 11] = np.inf                  # +inf: also non-finite
    logits[3, 4] = 2.0                      # ...the finite max beneath it
    key = jax.random.PRNGKey(0)

    for temp in (0.0, 0.7):
        t = np.full(4, temp, np.float32)
        toks = np.asarray(sampling.sample(
            jnp.asarray(logits), key, t, np.zeros(4, np.int32),
            np.ones(4, np.float32)))
        assert toks[1] == 7      # NaN masked; argmax of the finite rest
        assert toks[2] == 0      # whole row bad -> the defined fallback
        assert toks[3] == 4      # inf masked; 4 is the finite max
        assert 0 <= toks[0] < V
    # finite-only input: the gate is the identity (greedy chain unchanged)
    clean = logits[:1]
    a = sampling.sample(jnp.asarray(clean), key, np.zeros(1, np.float32),
                        np.zeros(1, np.int32), np.ones(1, np.float32))
    assert int(a[0]) == 5


def test_poisoned_logits_round_emits_defined_tokens():
    """chaos_poison_logits_round: the poisoned dispatch's tokens are
    defined (the gate's greedy fallback), generation continues, and the
    request terminates normally — NaN never reaches the emitted stream."""
    chaos = ServingChaos(_res(chaos_poison_logits_round=2))
    cfg, engine, params = _engine(slots=2, hooks=chaos, decode_block_len=2)
    res = ContinuousBatcher(engine, params).run(_requests(2, max_new=10))
    for r in res.values():
        assert r.finish_reason == "length"
        assert len(r.tokens) == 10
        assert all(0 <= t < cfg.model.vocab_size for t in r.tokens)
    assert chaos.round >= 2  # the poison round actually ran
    assert "poison" in chaos._fired


def test_poisoned_verify_round_emits_defined_tokens():
    """On a speculative engine the poison round lands on a VERIFY
    dispatch: speculative_accept's sanitized argmax keeps the emitted
    stream defined and generation terminates normally."""
    chaos = ServingChaos(_res(chaos_poison_logits_round=2))
    cfg, engine, params = _engine(slots=2, hooks=chaos, spec_len=4)
    res = ContinuousBatcher(engine, params).run(_requests(2, max_new=10))
    for r in res.values():
        assert r.finish_reason == "length"
        assert len(r.tokens) == 10
        assert all(0 <= t < cfg.model.vocab_size for t in r.tokens)
    assert chaos.round >= 2
    # the knob actually fired on the verify path (it was silently a no-op
    # for spec engines before verify() consulted the poison hook)
    assert "poison" in chaos._fired


# --------------------------------------------------------------------------- #
# dispatch retry + slot isolation
# --------------------------------------------------------------------------- #


def test_transient_dispatch_exception_is_retried_bit_identical():
    """One injected dispatch exception (chaos_dispatch_raise_round) is
    absorbed by the retry: every output equals the fault-free run exactly
    — including the sampled (temperature > 0) streams, because the round's
    keys are drawn before the dispatch and reused by the retry."""
    reqs = _requests(3, temperature=0.8, max_new=20)  # >= 3 decode rounds
    _, e0, p0 = _engine()
    clean = ContinuousBatcher(e0, p0, seed=5).run(
        [Request(**vars(r)) for r in reqs])

    chaos = ServingChaos(_res(chaos_dispatch_raise_round=2))
    _, e1, p1 = _engine(hooks=chaos)
    b = ContinuousBatcher(e1, p1, seed=5)
    res = b.run([Request(**vars(r)) for r in reqs])

    assert chaos.round >= 2
    for uid in clean:
        assert res[uid].tokens == clean[uid].tokens
        assert res[uid].finish_reason == clean[uid].finish_reason
    assert b.counters["errored"] == 0
    assert b.counters["completed"] == 3


def test_slot_failure_isolation_mid_decode_block():
    """A slot whose dispatches persistently fail
    (chaos_dispatch_fail_slot) finishes "error"; SURVIVING slots' outputs
    are bit-identical to a fault-free run (greedy AND sampled rows); no
    slot or queue entry leaks."""
    reqs = _requests(3, temperature=0.8, max_new=10)
    _, e0, p0 = _engine()
    clean = ContinuousBatcher(e0, p0, seed=7).run(
        [Request(**vars(r)) for r in reqs])

    chaos = ServingChaos(_res(chaos_dispatch_fail_slot=1))
    _, e1, p1 = _engine(hooks=chaos)
    b = ContinuousBatcher(e1, p1, seed=7)
    res = b.run([Request(**vars(r)) for r in reqs])

    # q1 was admitted into slot 1: it errors with only its prefill-time
    # first token (identical to the clean run's first token)
    assert res["q1"].finish_reason == "error"
    assert res["q1"].tokens == clean["q1"].tokens[:1]
    # survivors: bit-identical streams
    for uid in ("q0", "q2"):
        assert res[uid].finish_reason == clean[uid].finish_reason
        assert res[uid].tokens == clean[uid].tokens
    # no leaks: every slot free, nothing queued, cache lengths zeroed,
    # and the accounting adds up
    assert all(s is None for s in b._slots)
    assert b.queue_depth == 0
    np.testing.assert_array_equal(np.asarray(b._cache["lengths"]), 0)
    assert b.counters["errored"] == 1
    assert b.counters["completed"] == 2
    assert b.counters["admitted"] == 3


def test_prefill_failure_costs_only_the_incoming_request():
    """A persistently failing prefill finishes ONLY the request being
    admitted ("error"); everyone already decoding — and everyone admitted
    after — is untouched (greedy oracle: identical tokens)."""

    class PrefillBomb:
        """Fails the 2nd prefill dispatch persistently (both attempts)."""

        def __init__(self):
            self.calls = 0

        def before_dispatch(self, kind, slots):
            if kind != "prefill":
                return
            self.calls += 1
            if self.calls in (2, 3):  # attempt + its retry
                raise ChaosError("prefill bomb")

        def poison_logits(self, kind):
            return False

    reqs = _requests(3, max_new=6)
    _, e0, p0 = _engine(slots=2)
    clean = ContinuousBatcher(e0, p0).run(
        [Request(**vars(r)) for r in reqs])

    _, e1, p1 = _engine(slots=2, hooks=PrefillBomb())
    b = ContinuousBatcher(e1, p1)
    res = b.run([Request(**vars(r)) for r in reqs])

    assert res["q1"].finish_reason == "error" and res["q1"].tokens == []
    for uid in ("q0", "q2"):
        assert res[uid].tokens == clean[uid].tokens
        assert res[uid].finish_reason == clean[uid].finish_reason
    assert all(s is None for s in b._slots) and b.queue_depth == 0
    assert b.counters == {"admitted": 3, "completed": 2, "expired": 0,
                          "errored": 1, "shed": 0}


def test_batcher_stats_counters_and_percentiles():
    _, engine, params = _engine(slots=2)
    b = ContinuousBatcher(engine, params)
    b.run(_requests(3, max_new=4))
    s = b.stats()
    assert s["admitted"] == s["completed"] == 3
    assert s["queued"] == 0 and s["active_slots"] == 0
    assert s["queue_wait_s"]["n"] == 3 and s["ttft_s"]["n"] == 3
    assert s["ttft_s"]["p50"] >= s["queue_wait_s"]["p50"] >= 0.0
    assert s["generated_tokens"] == 12


def test_batcher_rejects_duplicate_uid():
    """A duplicate uid would silently overwrite the first request's
    result and its queue-wait clock: fail at submission like the other
    contract violations. Once the result is taken, the uid is reusable."""
    _, engine, params = _engine(slots=2)
    b = ContinuousBatcher(engine, params)
    b.submit(Request("dup", [1, 2], max_new_tokens=2))
    with pytest.raises(ValueError, match="duplicate uid"):
        b.submit(Request("dup", [3, 4], max_new_tokens=2))
    res = b.run()
    assert res["dup"].finish_reason == "length"
    res2 = b.run([Request("dup", [5, 6], max_new_tokens=2)])
    assert res2["dup"].finish_reason == "length"


# --------------------------------------------------------------------------- #
# flash -> dense graceful degradation
# --------------------------------------------------------------------------- #


def test_flash_failure_falls_back_to_dense_for_the_process(
        monkeypatch, capsys):
    import picotron_tpu.inference.engine as eng_mod
    import picotron_tpu.ops.pallas.decode_attention as da

    monkeypatch.setattr(eng_mod, "_FLASH_BROKEN", False)

    def kaput(*a, **kw):
        raise RuntimeError("flash kernel kaput")

    monkeypatch.setattr(da, "flash_decode_attention", kaput)

    reqs = _requests(2, max_new=6)
    _, e0, p0 = _engine(slots=2)  # dense oracle
    clean = ContinuousBatcher(e0, p0).run(
        [Request(**vars(r)) for r in reqs])

    _, e1, p1 = _engine(slots=2, attend_impl="flash")
    assert e1.attend_impl == "flash"
    res = ContinuousBatcher(e1, p1).run(
        [Request(**vars(r)) for r in reqs])
    # degraded transparently: same results as a dense engine, flipped impl
    assert e1.attend_impl == "dense"
    for uid in clean:
        assert res[uid].tokens == clean[uid].tokens
    out = capsys.readouterr().out
    assert out.count("falling back to 'dense'") == 1
    # the latch is process-wide: a NEW flash engine starts on dense
    assert eng_mod._FLASH_BROKEN
    _, e2, _ = _engine(slots=2, attend_impl="flash")
    assert e2.attend_impl == "dense"
    # with the fallback disabled there is no silent degradation: the
    # failure lands in the batcher's slot recovery instead (requests
    # error, the engine stays on flash, the process survives)
    monkeypatch.setattr(eng_mod, "_FLASH_BROKEN", False)
    _, e3, p3 = _engine(slots=2, attend_impl="flash",
                        attend_fallback=False)
    res3 = ContinuousBatcher(e3, p3).run(
        [Request("x", [1, 2], max_new_tokens=2)])
    assert res3["x"].finish_reason == "error"
    assert e3.attend_impl == "flash"


# --------------------------------------------------------------------------- #
# HTTP front end
# --------------------------------------------------------------------------- #


def _server(slots=2, hooks=None, inf=(), **front_kw):
    cfg, engine, params = _engine(slots=slots, hooks=hooks, **dict(inf))
    front_kw.setdefault("log", lambda *a, **k: None)
    srv = serve.Server(engine, params, port=0, **front_kw)
    srv.start()
    return cfg, srv


def test_http_generate_stream_health_and_stats():
    cfg, srv = _server()
    try:
        port = srv.port
        assert serve._get(port, "/healthz")[0] == 200
        assert serve._get(port, "/readyz")[0] == 200

        spec = {"prompt": [1, 2, 3], "max_new_tokens": 6}
        st, body = serve._post(port, spec)
        assert st == 200 and body["finish_reason"] == "length"
        assert len(body["tokens"]) == 6
        assert body["queue_wait_s"] is not None

        st, events = serve._post(port, {**spec, "stream": True},
                                 stream=True)
        assert st == 200
        toks = [e["token"] for e in events if e["event"] == "token"]
        done = [e for e in events if e["event"] == "done"]
        assert len(done) == 1 and done[0]["tokens"] == toks
        assert toks == body["tokens"]  # greedy: deterministic across posts

        st, stats = serve._get(port, "/statz")
        assert st == 200
        assert stats["completed"] == stats["admitted"] == 2
        assert stats["rejected"] == {"queue_full": 0, "token_budget": 0,
                                     "page_budget": 0, "draining": 0,
                                     "stalled": 0, "dead": 0, "role": 0,
                                     "tenant_quota": 0}
        assert not stats["draining"] and not stats["stalled"]
    finally:
        srv.drain_and_join(timeout=60)


def _poll_statz(port, cond, deadline_s=10.0):
    """Poll /statz until ``cond(stats)`` holds (returns the stats) or the
    deadline passes (raises)."""
    deadline = time.monotonic() + deadline_s
    while True:
        stats = serve._get(port, "/statz")[1]
        if cond(stats):
            return stats
        if time.monotonic() > deadline:
            raise AssertionError(f"statz condition never held: {stats}")
        time.sleep(0.01)


def test_http_admission_bounds_shed_with_retry_after():
    # token budget first: one live request exhausts it. The slow request
    # runs per-token (block 1) with a big budget, so it is live for many
    # lock-release windows; its COMMITMENT counts from submission (queued
    # or slotted), so the second POST is over budget the moment /statz
    # shows the first one live.
    cfg, srv = _server(token_budget=70, max_queue=8,
                       inf={"decode_block_len": 1})
    try:
        port = srv.port
        results = {}

        def bg(name, spec):
            results[name] = serve._post(port, spec)

        t = threading.Thread(target=bg, args=(
            "a", {"prompt": [1, 2, 3], "max_new_tokens": 58,
                  "uid": "slow"}))
        t.start()  # cost 61 of 70
        # .get: while the first dispatch compiles, /statz may answer with
        # the degraded (lock-free) snapshot, which has no counters
        _poll_statz(port,
                    lambda s: s.get("admitted", 0) + s.get("queued", 0) >= 1)
        st, body = serve._post(port, {"prompt": [5, 6, 7],
                                      "max_new_tokens": 8})  # cost 11
        assert st == 429 and body["shed"]
        t.join(60)
        assert results["a"][0] == 200
        st, stats = serve._get(port, "/statz")
        assert stats["rejected"]["token_budget"] == 1
    finally:
        srv.drain_and_join(timeout=60)

    # bounded wait queue: depth 0 sheds every submission outright
    cfg, srv = _server(max_queue=0)
    try:
        st, body = serve._post(srv.port, {"prompt": [1], "max_new_tokens": 2})
        assert st == 503 and body["shed"]
        assert serve._get(srv.port, "/statz")[1]["rejected"]["queue_full"] == 1
    finally:
        srv.drain_and_join(timeout=60)


def test_oversized_budget_is_window_capped_not_rejected():
    """A max_new_tokens beyond the sequence window admits at its real
    (window-capped) commitment instead of 429ing forever — the batcher
    can only ever generate max_seq_len - len(prompt) tokens, so that is
    what admission charges against the token budget."""
    cfg, srv = _server()
    try:
        st, body = serve._post(srv.port, {"prompt": [1, 2, 3],
                                          "max_new_tokens": 100000})
        assert st == 200 and body["finish_reason"] == "length"
        assert len(body["tokens"]) == MAX_LEN - 3
    finally:
        srv.drain_and_join(timeout=60)


def test_stalled_rejections_count_under_their_own_lock():
    """The "stalled" rejection fires exactly when ``_mu`` could NOT be
    acquired, so the counter cannot be guarded by ``_mu`` — a dedicated
    leaf lock (``_rej_mu``) guards every increment (picolint PICO-C003:
    concurrent timed-out handlers were doing an unlocked read-modify-
    write and losing updates). N handlers shedding concurrently against
    a wedged dispatch must count exactly N."""
    cfg, engine, params = _engine(slots=1)
    front = serve.FrontEnd(engine, params, log=lambda *a, **k: None)

    class _Wedged:  # a dispatch holding _mu forever: timed acquires fail
        def acquire(self, timeout=None):
            return False

        def release(self):
            raise AssertionError("never acquired")

    front._mu = _Wedged()
    n, statuses = 16, []

    def handler():
        try:
            front.submit({"prompt": [1, 2], "max_new_tokens": 2})
        except serve.AdmissionError as e:
            statuses.append(e.status)

    threads = [threading.Thread(target=handler) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert statuses == [503] * n
    assert front.rejections["stalled"] == n
    # stats() snapshots the counters under the same leaf lock (and takes
    # the degraded no-_mu path here, like an operator mid-stall)
    assert front.stats()["rejected"]["stalled"] == n


def test_waiter_maps_only_mutated_under_mu():
    """``_deliver`` pops ``_req_t``/``_waiters`` under ``_mu`` (picolint
    PICO-C003): the dispatch thread used to pop them unlocked while
    handler threads insert them — and check duplicate uids against them
    — under the lock. Guarded dicts assert the lock is held at every
    mutation; an unlocked pop kills the dispatch loop, which the
    result/dead checks surface."""
    cfg, engine, params = _engine(slots=2)
    front = serve.FrontEnd(engine, params, log=lambda *a, **k: None)

    class _Guarded(dict):
        def __init__(self, lock):
            super().__init__()
            self._lock = lock

        def __setitem__(self, k, v):
            assert self._lock.locked(), "waiter-map mutation outside _mu"
            dict.__setitem__(self, k, v)

        def pop(self, *a):
            assert self._lock.locked(), "waiter-map mutation outside _mu"
            return dict.pop(self, *a)

    front._waiters = _Guarded(front._mu)
    front._req_t = _Guarded(front._mu)
    front.start()
    try:
        _, waiter = front.submit({"prompt": [1, 2, 3],
                                  "max_new_tokens": 4})
        toks, res = [], None
        while res is None:
            kind, payload = waiter.events.get(timeout=30)
            if kind == "done":
                res = payload
            else:
                toks.append(payload)
        assert res.finish_reason == "length" and res.tokens == toks
        assert len(res.tokens) == 4
        assert not front.dead
    finally:
        front.begin_drain()
        front.join(timeout=30)
    assert not front._waiters and not front._req_t


def test_http_rejects_zero_budget_and_oversized_bodies():
    """max_new_tokens < 1 is a 400 at the door (a zero-budget request
    would hold a slot forever — no token ever completes it — and a
    negative one corrupts the token-budget arithmetic); a body whose
    declared Content-Length exceeds the cap is a 413 before any read."""
    import http.client

    cfg, srv = _server()
    try:
        port = srv.port
        for bad in (0, -3):
            st, body = serve._post(port, {"prompt": [1, 2],
                                          "max_new_tokens": bad})
            assert st == 400 and "max_new_tokens" in body["error"]
        # the batcher guards too: direct embedders get the same contract
        with pytest.raises(ValueError, match="max_new_tokens"):
            srv.front._batcher.submit(Request("z", [1], max_new_tokens=0))

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/generate", b"{}",
                     {"Content-Length": str(serve.MAX_BODY_BYTES + 1)})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 413 and "too large" in body["error"]

        # a negative declared length is a malformed header: 400, not 413
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/generate", b"", {"Content-Length": "-5"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 400 and "Content-Length" in body["error"]

        # nothing above was admitted; the server still serves
        st, body = serve._post(port, {"prompt": [1, 2],
                                      "max_new_tokens": 2})
        assert st == 200 and body["finish_reason"] == "length"
        stats = serve._get(port, "/statz")[1]
        assert stats["admitted"] == stats["completed"] == 1
    finally:
        srv.drain_and_join(timeout=60)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_http_submissions_after_loop_death_are_shed():
    """Once the dispatch loop dies on an unexpected exception, in-flight
    waiters get terminal "error" results (nobody hangs) and LATER
    submissions are shed with 503 instead of registering waiters no loop
    will ever complete."""
    cfg, srv = _server()
    try:
        port = srv.port

        def boom(*a, **k):
            raise RuntimeError("dispatch wedged beyond repair")

        srv.front._batcher.step = boom
        st, body = serve._post(port, {"prompt": [1, 2],
                                      "max_new_tokens": 4})
        assert st == 500 and body["finish_reason"] == "error"
        srv.front.join(timeout=60)
        assert srv.front.stopped.is_set()
        # death is a dedicated latch: the watchdog's recovery tick clears
        # `stalled` (progress looked recent), which must NOT flip a dead
        # server's healthz back to 200
        assert srv.front.dead
        time.sleep(3 * srv.front.watchdog_poll_s)
        assert not srv.front.healthy()  # supervisors see the 503
        assert not srv.front.ready()
        with pytest.raises(serve.AdmissionError) as ei:
            srv.front.submit({"prompt": [1, 2], "max_new_tokens": 4})
        assert ei.value.status == 503
        assert srv.front.rejections["dead"] == 1
        assert not srv.front._waiters  # nothing stranded
    finally:
        srv.drain_and_join(timeout=60)


def test_http_drain_finishes_inflight_and_sheds_queued():
    # token_budget above the default slots*max_seq_len: "b" must reach the
    # QUEUE (and be shed by the drain), not bounce off the budget gate
    cfg, srv = _server(slots=1, token_budget=256,
                       inf={"decode_block_len": 1})
    try:
        port = srv.port
        results = {}

        def bg(name, spec):
            results[name] = serve._post(port, spec)

        ta = threading.Thread(target=bg, args=(
            "a", {"prompt": [1, 2, 3], "max_new_tokens": 59}))
        ta.start()
        _poll_statz(port, lambda s: s.get("admitted", 0) >= 1)  # "a" slotted
        tb = threading.Thread(target=bg, args=(
            "b", {"prompt": [4, 5], "max_new_tokens": 4}))
        tb.start()
        # "b" can only wait in the queue (one slot, "a" decoding per-token)
        _poll_statz(port, lambda s: s.get("queued", 0) >= 1)
        srv.front.begin_drain()
        assert serve._get(port, "/readyz")[0] == 503
        ta.join(60)
        tb.join(60)
        # in-flight finished intact; queued-but-unstarted was shed
        assert results["a"][0] == 200
        assert results["a"][1]["finish_reason"] == "length"
        assert len(results["a"][1]["tokens"]) == 59
        assert results["b"][0] == 503
        assert results["b"][1]["finish_reason"] == "shed"
        # post-drain: submissions are rejected, the loop has exited
        srv.front.join(timeout=60)
        assert srv.front.stopped.is_set()
        stats = srv.front.stats()
        assert stats["shed"] == 1 and stats["completed"] >= 1
        assert stats["queued"] == 0 and stats["active_slots"] == 0
    finally:
        srv.drain_and_join(timeout=60)


def test_watchdog_flags_latency_stall_and_recovers():
    chaos = ServingChaos(_res(chaos_latency_round=2, chaos_latency_s=0.8))
    cfg, srv = _server(hooks=chaos, stall_timeout_s=0.15,
                       watchdog_poll_s=0.03)
    try:
        st, body = serve._post(srv.port, {"prompt": [1, 2, 3],
                                          "max_new_tokens": 16})
        assert st == 200 and len(body["tokens"]) == 16  # spike, no hang
        # the flag and its recovery are the watchdog thread's writes —
        # poll for both (its next tick clears `stalled` once steps resume)
        deadline = time.monotonic() + 5
        while (time.monotonic() < deadline
               and not (srv.front.stalls >= 1 and not srv.front.stalled)):
            time.sleep(0.02)
        assert srv.front.stalls >= 1     # the spike was flagged...
        assert not srv.front.stalled     # ...and recovery cleared it
        assert serve._get(srv.port, "/healthz")[0] == 200
    finally:
        srv.drain_and_join(timeout=60)


def test_readyz_distinguishes_draining_from_dead():
    """The readyz 503 body carries the POLLER'S contract (ISSUE 12): a
    router must stop placing on a draining replica without tripping its
    circuit breaker, and must treat a dead one as a failure — before the
    "state" field, both were indistinguishable 503s."""
    cfg, srv = _server(slots=1, inf={"decode_block_len": 1})
    try:
        port = srv.port
        st, body = serve._get(port, "/readyz")
        assert st == 200 and body["state"] == "ready"
        # hold the drain window open with an in-flight request, exactly
        # like a rolling restart catches a replica mid-generation
        results = {}

        def bg():
            results["slow"] = serve._post(port, {"prompt": [1, 2, 3],
                                                 "max_new_tokens": 40})

        t = threading.Thread(target=bg)
        t.start()
        _poll_statz(port, lambda s: s.get("active_slots", 0) > 0)
        srv.front.begin_drain()
        st, body = serve._get(port, "/readyz")
        assert st == 503
        assert body["state"] == "draining" and body["draining"]
        assert not body["dead"]
        t.join(60)
        assert results["slow"][0] == 200  # drain finished the in-flight
    finally:
        srv.drain_and_join(timeout=60)

    # dead flavor: the dispatch loop died -> "dead", not "draining".
    # Keep the listener up past the death (the serve CLI's window between
    # loop death and process exit) so the surface is observable.
    cfg, srv = _server()
    try:
        srv.front._on_drained = None

        def boom(*a, **k):
            raise RuntimeError("dispatch died")

        srv.front._batcher.step = boom
        st, body = serve._post(srv.port, {"prompt": [1], "max_new_tokens": 2})
        assert st == 500
        srv.front.join(timeout=60)
        st, body = serve._get(srv.port, "/readyz")
        assert st == 503 and body["state"] == "dead"
    finally:
        srv.drain_and_join(timeout=60)


def test_request_id_echoed_on_every_stream_row():
    """A client-supplied request_id rides every NDJSON token row, the
    done row, and the non-streaming document (falling back to the server
    uid) — the correlation key router-side replay dedup is audited by."""
    cfg, srv = _server()
    try:
        spec = {"prompt": [5, 6, 7], "max_new_tokens": 4,
                "request_id": "corr-77", "stream": True}
        st, events = serve._post(srv.port, spec, stream=True)
        assert st == 200 and len(events) == 5
        assert all(e["request_id"] == "corr-77" for e in events)
        st, body = serve._post(srv.port, {"prompt": [5, 6, 7],
                                          "max_new_tokens": 2,
                                          "request_id": "corr-78"})
        assert st == 200 and body["request_id"] == "corr-78"
        # no request_id -> the uid stands in, so the field is always there
        st, events = serve._post(srv.port, {"prompt": [5, 6], "uid": "u9",
                                            "max_new_tokens": 2,
                                            "stream": True}, stream=True)
        assert st == 200
        assert all(e["request_id"] == "u9" for e in events)
    finally:
        srv.drain_and_join(timeout=60)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_killed_server_releases_streaming_waiters_with_error():
    """A replica killed mid-generation (dispatch loop dies, the
    in-process SIGKILL the router chaos drill uses) must release every
    in-flight STREAM with a terminal ``finish_reason: "error"`` done row
    — not strand the client — because that row is what triggers the
    router's failover replay."""
    from picotron_tpu.resilience.chaos import RouterChaos

    cfg, srv = _server(slots=2, inf={"decode_block_len": 1})
    try:
        port = srv.port
        rows = []
        got_some = threading.Event()

        def on_token(i, row):
            got_some.set()

        from picotron_tpu.tools.router import _stream_post

        def bg():
            rows.append(_stream_post(
                port, {"prompt": [3, 1, 4], "max_new_tokens": 48,
                       "request_id": "kill-1"}, on_token=on_token))

        t = threading.Thread(target=bg)
        t.start()
        assert got_some.wait(60)  # mid-generation, tokens flowing
        RouterChaos().kill(srv)
        t.join(60)
        assert not t.is_alive()  # the waiter was released, nobody hangs
        st, events = rows[0]
        done = [e for e in events if e.get("event") == "done"]
        assert len(done) == 1
        assert done[0]["finish_reason"] == "error"
        assert done[0]["request_id"] == "kill-1"
        assert srv.front.dead  # healthz tells the supervisor to restart
        assert not srv.front._waiters  # nothing stranded
    finally:
        srv.drain_and_join(timeout=60)


# --------------------------------------------------------------------------- #
# the serve-chaos acceptance: all three faults in one run
# --------------------------------------------------------------------------- #


def test_chaos_run_accounts_everything_and_spares_the_unaffected():
    """Dispatch-exception + latency-spike + poisoned-logits in one server:
    no hangs, every submitted request terminates with an accounted
    finish_reason, and requests that ran AFTER the fault window are
    bit-identical to a chaos-off run."""
    batch_a = [{"prompt": [2 + i, 9 + i], "max_new_tokens": 6,
                "uid": f"a{i}"} for i in range(3)]
    batch_b = [{"prompt": [30 + i, 40 + i, 50 + i], "max_new_tokens": 5,
                "uid": f"b{i}"} for i in range(3)]

    # chaos-off oracle for the unaffected batch (greedy: prompt-determined)
    cfg, srv = _server(slots=2, inf={"decode_block_len": 2})
    try:
        want_b = {s["uid"]: serve._post(srv.port, s)[1]["tokens"]
                  for s in batch_b}
    finally:
        srv.drain_and_join(timeout=60)

    chaos = ServingChaos(_res(
        chaos_dispatch_raise_round=2, chaos_latency_round=3,
        chaos_latency_s=0.1, chaos_poison_logits_round=4))
    cfg, srv = _server(slots=2, hooks=chaos, inf={"decode_block_len": 2})
    try:
        port = srv.port
        results = {}

        def bg(spec):
            results[spec["uid"]] = serve._post(port, spec)

        threads = [threading.Thread(target=bg, args=(s,)) for s in batch_a]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        # all three faults fired during batch A
        assert chaos._fired >= {"raise", "latency", "poison"}
        for s in batch_a:  # no hangs: every request terminated, accounted
            st, body = results[s["uid"]]
            assert st in (200, 500)
            assert body["finish_reason"] in ("eos", "length", "timeout",
                                             "shed", "error")
        # batch B runs after the fault window: bit-identical to chaos-off
        for s in batch_b:
            st, body = serve._post(port, s)
            assert st == 200
            assert body["tokens"] == want_b[s["uid"]]
        stats = srv.front.stats()
        terminal = (stats["completed"] + stats["expired"]
                    + stats["errored"])
        assert terminal == stats["admitted"] == 6
        assert stats["shed"] == 0 and stats["queued"] == 0
        assert stats["active_slots"] == 0
        assert serve._get(port, "/healthz")[0] == 200
    finally:
        srv.drain_and_join(timeout=60)


# --------------------------------------------------------------------------- #
# the fleet controller's drain protocol (ISSUE 17, tools/fleet.py)
# --------------------------------------------------------------------------- #


def _post_path(port, path, body=None):
    """POST an arbitrary path (serve._post is /generate-only)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", path, json.dumps(body or {}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _get_text(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def test_http_drain_202_then_409_and_sigterm_races_one_drain():
    """The fleet drain protocol's worker half: POST /drain starts exactly
    one drain (202); a repeat is 409 "already draining"; a SIGTERM
    arriving DURING the HTTP drain (the PreemptionGuard loop calling
    begin_drain again — the controller sends both on purpose,
    belt-and-braces) must not double-run the drain — ``drain_begins``
    stays 1 and the loop exits clean, the serve CLI's exit-0 path."""
    cfg, srv = _server(slots=1, inf={"decode_block_len": 1})
    try:
        port = srv.port
        srv.front._on_drained = None  # keep the listener observable
        results = {}

        def bg():
            results["a"] = serve._post(port, {"prompt": [1, 2, 3],
                                              "max_new_tokens": 24})

        t = threading.Thread(target=bg)
        t.start()
        _poll_statz(port, lambda s: s.get("active_slots", 0) > 0)
        st, body = _post_path(port, "/drain")
        assert st == 202 and body["ok"] and body["state"] == "draining"
        st, body = _post_path(port, "/drain")
        assert st == 409 and "already draining" in body["error"]
        # the SIGTERM flavor of the same race, in-process: a second
        # begin_drain is a no-op, never a second drain
        assert srv.front.begin_drain() is False
        assert srv.front.drain_begins == 1
        t.join(60)
        assert results["a"][0] == 200  # in-flight finished intact
        srv.front.join(timeout=60)
        assert not srv.front.dead  # exit-0, not the crash path
        # the loop has exited: drain now reports the terminal state
        st, body = _post_path(port, "/drain")
        assert st == 409 and body["state"] in ("stopped", "dead")
        assert srv.front.drain_begins == 1
    finally:
        srv.drain_and_join(timeout=60)


def test_http_drain_on_dead_loop_is_409_dead():
    cfg, srv = _server()
    try:
        srv.front._on_drained = None

        def boom(*a, **k):
            raise RuntimeError("dispatch died")

        srv.front._batcher.step = boom
        st, _ = serve._post(srv.port, {"prompt": [1], "max_new_tokens": 2})
        assert st == 500
        srv.front.join(timeout=60)
        st, body = _post_path(srv.port, "/drain")
        assert st == 409 and body["state"] == "dead"
    finally:
        srv.drain_and_join(timeout=60)


def test_metrics_renders_during_drain_and_after_shutdown():
    """The controller scrapes /metrics every tick, including while its
    drain is in flight and after the batcher has exited — the render
    must answer 200 (bounded work, no dead-batcher 500, no deadlock)."""
    cfg, srv = _server(slots=1, inf={"decode_block_len": 1})
    try:
        port = srv.port
        srv.front._on_drained = None
        results = {}

        def bg():
            results["a"] = serve._post(port, {"prompt": [1, 2, 3],
                                              "max_new_tokens": 30})

        t = threading.Thread(target=bg)
        t.start()
        _poll_statz(port, lambda s: s.get("active_slots", 0) > 0)
        srv.front.begin_drain()
        st, text = _get_text(port, "/metrics")  # mid-drain
        assert st == 200 and "picotron_queue_depth" in text
        t.join(60)
        srv.front.join(timeout=60)
        st, text = _get_text(port, "/metrics")  # batcher loop exited
        assert st == 200 and "picotron_active_slots" in text
        assert results["a"][0] == 200
    finally:
        srv.drain_and_join(timeout=60)


def test_kv_prefixes_enumerates_hot_paths_paged_only():
    """GET /kv/prefixes: the drain-time cache handoff's enumeration
    surface — hottest radix prefixes as root-path token runs (full-page
    chunks plus a possibly-partial tail leaf), 400 on a bad limit, and
    AdmissionError (not a crash) off the contiguous layout."""
    cfg, srv = _server(slots=2, inf={"kv_layout": "paged",
                                     "kv_page_len": 8,
                                     "decode_block_len": 1})
    try:
        port = srv.port
        shared = list(range(1, 17))  # two whole pages
        for tail in ([21, 22], [31, 32]):
            st, _ = serve._post(port, {"prompt": shared + tail,
                                       "max_new_tokens": 4})
            assert st == 200
        st, body = serve._get(port, "/kv/prefixes?limit=4")
        assert st == 200 and body["prefixes"]
        ids = body["prefixes"][0]["ids"]
        assert len(ids) >= len(shared) and ids[: len(shared)] == shared
        assert body["prefixes"][0]["tenant"] is None
        st, body = serve._get(port, "/kv/prefixes?limit=0")
        assert st == 400
    finally:
        srv.drain_and_join(timeout=60)

    cfg, srv = _server()  # contiguous layout: the kv-transport 503,
    try:                  # same contract as /kv/export — never a crash
        st, body = serve._get(srv.port, "/kv/prefixes")
        assert st == 503 and "paged" in body["error"]
    finally:
        srv.drain_and_join(timeout=60)
