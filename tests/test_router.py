"""Multi-replica router suite (ISSUE 12; docs/SERVING.md "Multi-replica
fabric").

Unit layers first — placement scoring (prefix affinity vs least-loaded),
the circuit-breaker state machine, and the replay splice math — each
driven without HTTP so the properties are exact; then the integration
layers: a real 2-replica fleet with a router-side stream sever (the
connection-drop flavor of a mid-stream death), and the full
``make router-chaos-smoke`` drill (the ISSUE 12 acceptance: 3 in-process
replicas, one killed while holding an in-flight greedy stream, the
spliced client stream bit-identical to an unfaulted run with
``replays == 1`` and every request accounted in the router's registry).
"""

import threading
import time

import pytest

from picotron_tpu.config import RouterConfig
from picotron_tpu.tools import router as router_mod
from picotron_tpu.tools.router import (
    Replica,
    ReplicaFailure,
    RouteRefused,
    Router,
    hist_quantile,
    prefix_key,
)


def _cfg(**kw):
    base = dict(probe_interval_s=0.01, probe_timeout_s=0.2,
                breaker_failures=3, breaker_backoff_s=0.01,
                breaker_backoff_max_s=0.05, breaker_probe_attempts=3,
                scrape_stale_s=10.0, affinity_page_len=16,
                affinity_load_slack=4.0, place_attempts=3,
                replay_budget=2)
    base.update(kw)
    return RouterConfig(**base)


def _router(n=3, **cfg_kw) -> Router:
    """A router over fake replica addresses, probers NOT started; tests
    poke replica state directly."""
    r = Router([f"10.0.0.{i}:80{i}" for i in range(n)], _cfg(**cfg_kw),
               log=lambda *a, **k: None)
    for rep in r.replicas.values():
        _mark_up(r, rep)
    return r


def _mark_up(r: Router, rep: Replica, **scrape):
    with rep._mu:
        rep.ready = True
        rep.draining = False
        rep.scrape = {"queue_depth": 0.0, "active_slots": 0.0,
                      "pool_utilization": 0.0, "ttft_p95": 0.0, **scrape}
        rep.scrape_t = r._clock()


# --------------------------------------------------------------------------- #
# pure helpers
# --------------------------------------------------------------------------- #


def test_prefix_key_is_page_aligned():
    p = list(range(40))
    # < one page: no affinity key (nothing the radix cache could share)
    assert prefix_key(p[:15], 16) is None
    # the key covers whole pages only: 16..31 tokens -> the same one-page key
    assert prefix_key(p[:16], 16) == prefix_key(p[:31], 16)
    # a second full page changes the key
    assert prefix_key(p[:32], 16) != prefix_key(p[:16], 16)
    # the key depends on prefix CONTENT
    q = list(p)
    q[3] = 999
    assert prefix_key(q[:16], 16) != prefix_key(p[:16], 16)


def test_hist_quantile_reads_cumulative_buckets():
    prom = {
        'picotron_ttft_seconds_bucket{le="0.1"}': 50.0,
        'picotron_ttft_seconds_bucket{le="0.2"}': 90.0,
        'picotron_ttft_seconds_bucket{le="0.4"}': 100.0,
        'picotron_ttft_seconds_bucket{le="+Inf"}': 100.0,
        'picotron_ttft_seconds_count': 100.0,
    }
    assert hist_quantile(prom, "picotron_ttft_seconds", 0.50) == 0.1
    assert hist_quantile(prom, "picotron_ttft_seconds", 0.95) == 0.4
    # absent or empty histogram -> 0.0, not a crash
    assert hist_quantile({}, "picotron_ttft_seconds", 0.95) == 0.0
    assert hist_quantile(
        {'x_bucket{le="+Inf"}': 0.0}, "x", 0.95) == 0.0


def test_scrape_tolerates_dp_sharded_worker_fields():
    """ISSUE-18 regression: a dp-sharded worker's /metrics page carries
    picotron_dp_size, per-shard picotron_shard_occupancy{shard} gauges,
    and picotron_slot_migrations_total{outcome} counters next to the
    classic scrape fields. The router's probe extraction (the exact dict
    _probe builds from parse_prometheus) must keep reading the fields it
    knows and stay undisturbed by the new families."""
    from picotron_tpu.obs.metrics import MetricsRegistry, parse_prometheus
    from picotron_tpu.tools.router import tenant_scrape

    reg = MetricsRegistry()
    reg.gauge("picotron_queue_depth").set(3)
    reg.gauge("picotron_active_slots").set(5)
    reg.gauge("picotron_kv_pool_utilization").set(0.25)
    # the new dp-sharded worker surface
    reg.gauge("picotron_dp_size").set(2)
    reg.gauge("picotron_shard_occupancy", shard="0").set(3)
    reg.gauge("picotron_shard_occupancy", shard="1").set(2)
    reg.counter("picotron_slot_migrations_total", outcome="ok").inc(4)
    reg.counter("picotron_slot_migrations_total", outcome="aborted").inc()
    prom = parse_prometheus(reg.prometheus())
    # the new families parsed as labeled samples...
    assert prom["picotron_dp_size"] == 2.0
    assert prom['picotron_shard_occupancy{shard="0"}'] == 3.0
    assert prom['picotron_shard_occupancy{shard="1"}'] == 2.0
    assert prom['picotron_slot_migrations_total{outcome="ok"}'] == 4.0
    # ...and the probe's scrape dict (router.py _probe) is unaffected
    scrape = {
        "queue_depth": prom.get("picotron_queue_depth", 0.0),
        "active_slots": prom.get("picotron_active_slots", 0.0),
        "pool_utilization": prom.get("picotron_kv_pool_utilization", 0.0),
        "ttft_p95": hist_quantile(prom, "picotron_ttft_seconds", 0.95),
        "tenants": tenant_scrape(prom),
    }
    assert scrape == {"queue_depth": 3.0, "active_slots": 5.0,
                      "pool_utilization": 0.25, "ttft_p95": 0.0,
                      "tenants": {}}


def test_router_config_validation():
    RouterConfig().validate()  # defaults are valid
    with pytest.raises(ValueError, match="affinity_page_len"):
        RouterConfig(affinity_page_len=12).validate()
    with pytest.raises(ValueError, match="breaker_backoff_max_s"):
        RouterConfig(breaker_backoff_s=5.0,
                     breaker_backoff_max_s=1.0).validate()
    with pytest.raises(ValueError, match="replay_budget"):
        RouterConfig(replay_budget=-1).validate()
    with pytest.raises(ValueError, match="probe_interval_s"):
        RouterConfig(probe_interval_s=0.0).validate()
    # from_dict ignores unknown keys (the Config policy) and validates
    cfg = RouterConfig.from_dict({"replay_budget": 5, "not_a_knob": 1})
    assert cfg.replay_budget == 5
    with pytest.raises(ValueError, match="place_attempts"):
        RouterConfig.from_dict({"place_attempts": 0})


# --------------------------------------------------------------------------- #
# placement
# --------------------------------------------------------------------------- #


def test_placement_affinity_is_stable_and_shared_prefixes_converge():
    r = _router(3)
    prompt = list(range(32))
    picks = set()
    for _ in range(4):
        rep = r.place(prompt)
        picks.add(rep.name)
        r._request_refused(rep)  # release the inflight slot
    assert len(picks) == 1  # rendezvous: one replica owns this prefix
    # a prompt sharing the page-aligned prefix (different tail) converges
    rep = r.place(prompt + [777])
    r._request_refused(rep)
    assert rep.name in picks
    # a different prefix may land elsewhere, but stays stable too
    other = [7] * 32
    a, b = r.place(other), None
    r._request_refused(a)
    b = r.place(other)
    r._request_refused(b)
    assert a.name == b.name


def test_placement_escapes_affinity_when_overloaded():
    r = _router(3, affinity_load_slack=4.0)
    prompt = list(range(32))
    home = r.place(prompt)
    r._request_refused(home)
    # pile load onto the affinity home beyond the slack: the pick must
    # escape to the least-loaded candidate
    _mark_up(r, home, queue_depth=50.0)
    rep = r.place(prompt)
    r._request_refused(rep)
    assert rep.name != home.name
    # inside the slack the affinity pick still wins
    _mark_up(r, home, queue_depth=2.0)
    rep = r.place(prompt)
    r._request_refused(rep)
    assert rep.name == home.name


def test_placement_drops_stale_open_draining_and_trial_replicas():
    r = _router(3)
    reps = list(r.replicas.values())
    # stale scrape: unknown load is unplaceable load
    with reps[0]._mu:
        reps[0].scrape_t = r._clock() - 1000.0
    # open breaker
    with reps[1]._mu:
        reps[1].breaker = "open"
    # draining: graceful, no placements
    with reps[2]._mu:
        reps[2].draining = True
    assert r.place([1] * 32) is None
    # half-open admits exactly ONE trial at a time
    with reps[2]._mu:
        reps[2].draining = False
        reps[2].breaker = "half_open"
    trial = r.place([1] * 32)
    assert trial is reps[2] and trial.trial
    assert r.place([1] * 32) is None  # the door admits one
    r._request_success(trial)  # trial served -> breaker closes
    with reps[2]._mu:
        assert reps[2].breaker == "closed"


def test_short_prompt_places_least_loaded():
    r = _router(3)
    reps = list(r.replicas.values())
    _mark_up(r, reps[0], queue_depth=9.0)
    _mark_up(r, reps[1], queue_depth=1.0)
    _mark_up(r, reps[2], queue_depth=5.0)
    rep = r.place([1, 2, 3])  # under one page: no affinity key
    r._request_refused(rep)
    assert rep is reps[1]


def test_load_score_weights_metrics_terms():
    r = _router(1, load_queue_weight=1.0, load_slot_weight=0.5,
                load_pool_weight=4.0, load_ttft_weight=2.0)
    rep = next(iter(r.replicas.values()))
    _mark_up(r, rep, queue_depth=3.0, active_slots=2.0,
             pool_utilization=0.5, ttft_p95=0.25)
    with rep._mu:
        rep.inflight = 2
        load = r._load(rep)
    # (3 + 2 inflight) * 1.0 + 2 * 0.5 + 0.5 * 4.0 + 0.25 * 2.0
    assert load == pytest.approx(5.0 + 1.0 + 2.0 + 0.5)


# --------------------------------------------------------------------------- #
# circuit breaker state machine
# --------------------------------------------------------------------------- #


def test_breaker_opens_after_consecutive_failures_and_probe_recovers():
    r = _router(1)
    rep = next(iter(r.replicas.values()))
    assert not r._probe_fail(rep, "x")  # 1
    assert not r._probe_fail(rep, "x")  # 2
    assert r._probe_fail(rep, "x")  # 3 -> open
    with rep._mu:
        assert rep.breaker == "open"
    # one clean probe: open -> half_open
    r._probe_ok(rep, ready=True, draining=False, scrape={})
    with rep._mu:
        assert rep.breaker == "half_open"
    # enough consecutive clean probes close without risking traffic
    r._probe_ok(rep, ready=True, draining=False, scrape={})
    r._probe_ok(rep, ready=True, draining=False, scrape={})
    with rep._mu:
        assert rep.breaker == "closed" and rep.fails == 0


def test_breaker_half_open_trial_failure_reopens():
    r = _router(1)
    rep = next(iter(r.replicas.values()))
    for _ in range(3):
        r._probe_fail(rep, "x")
    r._probe_ok(rep, ready=True, draining=False, scrape={})
    _mark_up(r, rep)
    with rep._mu:
        rep.breaker = "half_open"
    trial = r.place([1] * 32)
    assert trial is rep
    r._request_failure(rep, "trial died")
    with rep._mu:
        assert rep.breaker == "open" and not rep.trial
        assert rep.inflight == 0


def test_intermittent_failures_below_threshold_stay_closed():
    r = _router(1)
    rep = next(iter(r.replicas.values()))
    for _ in range(5):
        r._probe_fail(rep, "flap")
        r._probe_ok(rep, ready=True, draining=False, scrape={})
    with rep._mu:
        assert rep.breaker == "closed"


# --------------------------------------------------------------------------- #
# replay splice (scripted attempts, no HTTP)
# --------------------------------------------------------------------------- #


def _scripted(r: Router, script):
    """Replace ``r._attempt`` with a scripted sequence; records every
    submitted (replica, prompt, max_new) triple. Each script entry is
    ``(outcome, detail, tokens_to_deliver)``."""
    calls = []
    it = iter(script)

    def fake(rep, spec, rid, n, prompt, delivered, max_new, on_token,
             root, tracer, kv_payload=None):
        outcome, detail, toks = next(it)
        calls.append((rep.name, prompt + delivered,
                      max_new - len(delivered)))
        for t in toks:
            delivered.append(t)
            if on_token is not None:
                on_token(t)
        return outcome, detail

    r._attempt = fake
    return calls


def test_replay_resubmits_prompt_plus_delivered_exactly_once():
    r = _router(3)
    prompt = list(range(32))
    calls = _scripted(r, [
        ("failed", "mid-stream death", [100, 101, 102]),
        ("served", "length", [103, 104]),
    ])
    seen = []
    out = r.route({"prompt": prompt, "max_new_tokens": 5}, "rid-1",
                  on_token=seen.append)
    # exactly-once: every token delivered once, spliced in order
    assert seen == [100, 101, 102, 103, 104]
    assert out["tokens"] == seen and out["finish_reason"] == "length"
    assert out["replays"] == 1 and out["attempts"] == 2
    # the replay re-submitted the ORIGINAL prompt + delivered tokens,
    # with the budget reduced by what the client already holds
    assert calls[0] == (calls[0][0], prompt, 5)
    assert calls[1][1] == prompt + [100, 101, 102]
    assert calls[1][2] == 2
    # the failed replica was excluded from the replay placement
    assert calls[1][0] != calls[0][0]
    with r._ctr_mu:
        assert dict(r.requests)["completed"] == 1
    assert int(r._replays.value) == 1


def test_replay_synthesizes_terminal_when_failover_lands_at_the_end():
    # the dead replica delivered every budgeted token but not the done
    # row: the router owes the client a terminal, not a replay of a
    # request with max_new_tokens == 0 (which serve would 400)
    r = _router(3)
    calls = _scripted(r, [("failed", "death after last token", [5, 6, 7])])
    out = r.route({"prompt": [1] * 16, "max_new_tokens": 3}, "rid-2")
    assert out["finish_reason"] == "length" and out["tokens"] == [5, 6, 7]
    assert len(calls) == 1  # no second attempt was needed
    # ... and the eos flavor
    r2 = _router(3)
    _scripted(r2, [("failed", "death on the eos token", [5, 6, 99])])
    out = r2.route({"prompt": [1] * 16, "max_new_tokens": 8,
                    "eos_id": 99}, "rid-3")
    assert out["finish_reason"] == "eos" and out["tokens"] == [5, 6, 99]


def test_replay_refused_by_replica_validation_keeps_partials():
    """A replay the fleet can no longer express — e.g. the replayed
    prompt+delivered fills the replica window, so submit() 400s — must
    terminate ``"error"`` WITH the delivered tokens, not raise a 400
    that eats them (or tear the stream without a done row)."""
    r = _router(3)
    _scripted(r, [
        ("failed", "mid-stream death", [20, 21]),
        ("client_error", "prompt leaves no room to generate", []),
    ])
    out = r.route({"prompt": [1] * 16, "max_new_tokens": 8}, "rid-9")
    assert out["finish_reason"] == "error" and out["tokens"] == [20, 21]
    with r._ctr_mu:
        assert dict(r.requests)["failed"] == 1


def test_replay_budget_exhaustion_fails_with_partial_tokens():
    r = _router(3, replay_budget=1)
    _scripted(r, [
        ("failed", "death 1", [10]),
        ("failed", "death 2", [11]),
    ])
    out = r.route({"prompt": [1] * 16, "max_new_tokens": 8}, "rid-4")
    assert out["finish_reason"] == "error"
    assert out["tokens"] == [10, 11]  # nothing delivered is ever lost
    with r._ctr_mu:
        assert dict(r.requests)["failed"] == 1


def test_refused_placements_are_bounded_and_shed():
    r = _router(3, place_attempts=2)
    _scripted(r, [
        ("refused", "503: queue full", []),
        ("refused", "503: queue full", []),
    ])
    with pytest.raises(RouteRefused) as ei:
        r.route({"prompt": [1] * 16, "max_new_tokens": 4}, "rid-5")
    assert ei.value.status == 503 and ei.value.retry_after >= 1
    with r._ctr_mu:
        assert dict(r.requests)["shed"] == 1
    # refusals never touch the breaker: backpressure is an answer
    for rep in r.replicas.values():
        with rep._mu:
            assert rep.breaker == "closed"


def test_route_refuses_when_no_replica_eligible():
    r = _router(2)
    for rep in r.replicas.values():
        with rep._mu:
            rep.breaker = "open"
    with pytest.raises(RouteRefused) as ei:
        r.route({"prompt": [1, 2, 3], "max_new_tokens": 4}, "rid-6")
    assert ei.value.status == 503
    assert ei.value.retry_after == r.cfg.retry_after_s
    with pytest.raises(RouteRefused) as ei:
        r.route({"prompt": "nope", "max_new_tokens": 4}, "rid-7")
    assert ei.value.status == 400


def test_mid_stream_failure_with_no_survivor_errors_with_partials():
    r = _router(1)
    _scripted(r, [("failed", "only replica died", [42, 43])])
    out = r.route({"prompt": [1] * 16, "max_new_tokens": 8}, "rid-8")
    assert out["finish_reason"] == "error" and out["tokens"] == [42, 43]


# --------------------------------------------------------------------------- #
# integration: real replicas
# --------------------------------------------------------------------------- #


def _fleet(n):
    import jax

    from conftest import make_config
    from picotron_tpu.inference import InferenceEngine
    from picotron_tpu.models import llama
    from picotron_tpu.tools import serve

    servers = []
    for _ in range(n):
        cfg = make_config(dict(
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, hidden_size=32, intermediate_size=64,
            vocab_size=128, max_position_embeddings=64,
            rope_theta=10000.0, dtype="float32", attention_impl="sdpa"),
            seq=32)
        cfg.inference.decode_block_len = 1
        engine = InferenceEngine(cfg, slots=2, max_seq_len=64)
        params = engine.shard_params(jax.jit(
            lambda k, m=cfg.model: llama.init_params(k, m))(
                jax.random.PRNGKey(0)))
        srv = serve.Server(engine, params, port=0,
                           log=lambda *a, **k: None)
        srv.start()
        servers.append(srv)
    return servers


def test_stream_sever_replays_onto_survivor_exactly_once():
    """The connection-drop flavor of a mid-stream death (RouterChaos
    severs the router->replica stream after 3 tokens): the spliced
    client stream is bit-identical to an unfaulted greedy run, no token
    duplicated or dropped, replays accounted."""
    from picotron_tpu.resilience.chaos import RouterChaos
    from picotron_tpu.tools import serve
    from picotron_tpu.tools.router import RouterServer, _stream_post

    servers = _fleet(2)
    names = [f"127.0.0.1:{s.port}" for s in servers]
    chaos = RouterChaos()
    rs = RouterServer(names, _cfg(probe_interval_s=0.05), chaos=chaos,
                      log=lambda *a, **k: None)
    rs.start()
    try:
        assert rs.router.wait_eligible(2, timeout=30)
        spec = {"prompt": [2, 7, 1, 8, 2, 8], "max_new_tokens": 10}
        st, body = serve._post(servers[0].port, spec)  # greedy oracle
        assert st == 200
        oracle = body["tokens"]

        # the request's affinity home is deterministic: sever ITS stream
        home = rs.router.place(spec["prompt"])
        rs.router._request_refused(home)
        chaos.sever_stream(home.name, after_tokens=3)
        st, rows = _stream_post(rs.port, {**spec, "request_id": "sever-1"})
        toks = [r["token"] for r in rows if r.get("event") == "token"]
        done = [r for r in rows if r.get("event") == "done"][0]
        assert st == 200 and toks == oracle == done["tokens"]
        assert done["replays"] == 1 and done["finish_reason"] == "length"
        assert all(r.get("request_id") == "sever-1" for r in rows)
        # the failover excluded the severed home and the survivor served
        # (the home's fail count itself is reset by its next clean probe,
        # so the durable evidence is the replica that finished the job)
        assert done["replica"] != home.name
        stats = rs.router.stats()
        assert stats["replays"] == 1
        assert stats["requests"]["completed"] == 1
    finally:
        rs.stop()
        for s in servers:
            s.drain_and_join(timeout=60)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_router_chaos_smoke_acceptance():
    """The ISSUE 12 acceptance drill end to end (`make
    router-chaos-smoke`): 3 live replicas, one killed while holding an
    in-flight greedy stream -> the client receives the complete
    generation bit-identical to an unfaulted run (replays=1, nothing
    lost); a flapping replica trips the breaker open and recovers
    through half-open with no request erroring; stall, scrape-failure,
    and drain drills; full registry + span-chain accounting."""
    from picotron_tpu.tools import router as rt

    assert rt.main(["--smoke"]) == 0


# --------------------------------------------------------------------------- #
# dynamic replica set (ISSUE 17: the fleet controller's admin surface)
# --------------------------------------------------------------------------- #


def test_remove_replica_joins_prober_and_readd_starts_breaker_fresh():
    """Deregistering mid-stream must not strand the prober thread or leak
    breaker state: the prober is woken through ``gone`` (even out of a
    breaker-open reprobe ladder) and joined; the in-flight route's
    Replica OBJECT stays usable; and re-adding the same address builds a
    fresh closed-breaker replica — the old fails/inflight died with the
    old object."""
    r = _router(3)
    r.start()  # probers run against fake addrs and fail; that's the point
    try:
        name = sorted(r.replicas)[0]
        rep = r.replicas[name]
        # dirty the state exactly as a mid-stream death would
        with rep._mu:
            rep.breaker = "open"
            rep.fails = 7
            rep.inflight = 2
        snap = r.remove_replica(name)
        assert snap["breaker"] == "open" and snap["inflight"] == 2
        assert name not in r.replicas
        assert rep.gone.is_set()
        assert rep._prober is not None and not rep._prober.is_alive()
        assert r.stats()["replicas"].get(name) is None
        # an in-flight route still holds a valid object: bookkeeping on
        # it keeps working after deregistration (it just isn't placeable)
        with rep._mu:
            rep.inflight -= 1
        assert rep.snapshot(r._clock())["inflight"] == 1
        # same address re-registered: nothing carried over
        rep2 = r.add_replica(f"{rep.host}:{rep.port}")
        assert rep2 is not rep
        with rep2._mu:
            assert rep2.breaker == "closed"
            assert rep2.fails == 0 and rep2.inflight == 0
        assert rep2._prober is not None and rep2._prober.is_alive()
        with pytest.raises(router_mod.DuplicateReplica):
            r.add_replica(f"{rep.host}:{rep.port}")
    finally:
        r.stop()


def test_affinity_rehash_on_owner_removal_promotes_hrw_runner_up():
    """Rendezvous pin: removing a prefix's affinity owner re-homes ONLY
    that prefix (to the HRW runner-up over the survivors); prefixes owned
    elsewhere keep their owner — the minimal-disruption property the
    fleet controller's scale-down leans on."""
    r = _router(3)
    page = r.cfg.affinity_page_len
    prompts, before = {}, {}
    for seed in range(12):
        p = [seed * 1000 + j for j in range(page)]
        key = prefix_key(p, page)
        ranked = sorted(r.replicas.values(),
                        key=lambda rep: router_mod._rendezvous(key, rep.name),
                        reverse=True)
        owner = r._affinity_owner(p)
        assert owner is ranked[0]  # owner IS the HRW top, not load-dependent
        prompts[seed], before[seed] = p, owner.name
    victim = sorted(r.replicas)[0]
    assert any(n == victim for n in before.values()), \
        "fixture must exercise the rehash branch"
    r.remove_replica(victim)
    for seed, p in prompts.items():
        key = prefix_key(p, page)
        expect = max(r.replicas.values(),
                     key=lambda rep: router_mod._rendezvous(key, rep.name))
        after = r._affinity_owner(p)
        assert after is expect
        if before[seed] != victim:
            assert after.name == before[seed]  # pinned: unaffected keys stay


def test_replica_set_churn_is_safe_under_concurrent_candidate_scans():
    """The COW contract: candidate scans, snapshots, and stats() racing
    add/remove churn never see a mutating dict or a half-built replica."""
    r = _router(2)
    keep = set(r.replicas)
    stop = threading.Event()
    errs = []

    def reader():
        while not stop.is_set():
            try:
                for rep, _load in r._candidates():
                    rep.snapshot(r._clock())
                r.stats()
            except Exception as e:  # pragma: no cover - the failure mode
                errs.append(repr(e))
                return

    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers:
        t.start()
    try:
        for i in range(60):
            rep = r.add_replica(f"10.9.9.9:{8100 + i}")
            _mark_up(r, rep)
            r.remove_replica(rep.name)
    finally:
        stop.set()
        for t in readers:
            t.join(timeout=10)
    assert errs == []
    assert set(r.replicas) == keep
