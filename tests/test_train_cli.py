"""End-to-end trainer tests: the config→train→checkpoint→resume surface
(reference train.py:57-281), run in-process on the 8-virtual-device mesh.

The key property: a run interrupted at step k and resumed equals the
uninterrupted run — stronger than the reference (which replays data from the
top after resume, train.py:214-215): with ``skip_steps`` the resumed run sees
the same batches the uninterrupted one would.
"""

import pytest

import numpy as np

from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.train import train

from conftest import make_config


@pytest.mark.slow
def test_train_loop_and_interrupted_resume(tiny_model_kwargs, tmp_path):
    common = dict(dp=2, tp=2, mbs=2, seq=32,
                  total_train_steps=6)

    # uninterrupted 6-step run
    cfg_full = make_config(tiny_model_kwargs, **common)
    cfg_full.checkpoint.save_dir = str(tmp_path / "full")
    cfg_full.checkpoint.save_frequency = 6
    steps, tokens, loss_full = train(cfg_full)
    assert steps == 6
    assert tokens == 6 * cfg_full.tokens_per_step

    # same run stopped at 3...
    cfg_a = make_config(tiny_model_kwargs, **common)
    cfg_a.training.total_train_steps = 3
    cfg_a.checkpoint.save_dir = str(tmp_path / "ab")
    cfg_a.checkpoint.save_frequency = 3
    train(cfg_a)

    # ...then resumed to 6: identical final loss
    cfg_b = make_config(tiny_model_kwargs, **common)
    cfg_b.checkpoint.save_dir = str(tmp_path / "ab")
    cfg_b.checkpoint.save_frequency = 3
    cfg_b.checkpoint.load_path = str(tmp_path / "ab")
    steps_b, tokens_b, loss_b = train(cfg_b)
    assert steps_b == 6
    assert tokens_b == 6 * cfg_b.tokens_per_step
    assert float(loss_b) == float(loss_full)


def test_max_tokens_stop(tiny_model_kwargs, tmp_path):
    """max_tokens halts mid-schedule (reference stop condition, train.py:219)."""
    cfg = make_config(tiny_model_kwargs, dp=2, tp=2, mbs=2, seq=32,
                      total_train_steps=50)
    cfg.training.max_tokens = 3 * cfg.tokens_per_step
    steps, tokens, _ = train(cfg)
    assert steps == 3
    assert tokens == 3 * cfg.tokens_per_step


def test_loader_skip_steps_matches_replay(tiny_model_kwargs):
    cfg = make_config(tiny_model_kwargs, dp=2, mbs=2, acc=2, seq=32)
    a = MicroBatchDataLoader(cfg)
    b = MicroBatchDataLoader(cfg)
    for _ in range(5):
        next(a)
    b.skip_steps(5)
    xa, xb = next(a), next(b)
    np.testing.assert_array_equal(xa["input_ids"], xb["input_ids"])
    np.testing.assert_array_equal(xa["target_ids"], xb["target_ids"])


def test_wandb_logging_path(tiny_model_kwargs, monkeypatch):
    """use_wandb drives the full wandb call surface (init with the
    reference's run-name convention, per-step log, finish) via a stub
    module — no network, no wandb dependency."""
    import sys
    import types

    events = []
    stub = types.ModuleType("wandb")
    stub.init = lambda **kw: events.append(("init", kw)) or stub
    stub.log = lambda data, step=None: events.append(("log", step, data))
    stub.finish = lambda: events.append(("finish",))
    monkeypatch.setitem(sys.modules, "wandb", stub)

    from picotron_tpu.train import train

    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.training.total_train_steps = 2
    cfg.logging.use_wandb = True
    cfg.logging.run_name = "stubrun"
    steps, tokens, loss = train(cfg)
    assert steps == 2
    init_kw = events[0][1]
    assert init_kw["name"].startswith("stubrun_")
    assert "_dp1_tp1_pp1_cp1" in init_kw["name"]
    logs = [e for e in events if e[0] == "log"]
    assert len(logs) == 2 and logs[0][1] == 1 and "loss" in logs[0][2]
    assert events[-1] == ("finish",)
