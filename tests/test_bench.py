"""Unit tests for the bench harness logic (bench.py is a driver artifact:
its size-descent and error classification decide what number gets published,
so they get the same test treatment as the framework)."""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

from bench import classify_bench_error, run_descending


def test_classify_bench_error():
    assert classify_bench_error("resource_exhausted: out of hbm") == "oom"
    assert classify_bench_error("ran out of memory while allocating") == "oom"
    assert classify_bench_error(
        "exceeds the amount of memory available (need 20g)") == "oom"
    assert classify_bench_error(
        "internal: http 500 remote_compile failed") == "opaque"
    assert classify_bench_error("tpu_compile_helper exit code 1") == "opaque"
    assert classify_bench_error("typeerror: bad argument") == "raise"


def _patched(monkeypatch, behavior):
    """Patch bench.run with a scripted behavior: size -> list of outcomes
    (numbers return, strings raise RuntimeError(str)); each attempt pops."""
    import bench

    calls = []

    def fake_run(cfg, **kw):
        size = cfg
        calls.append(size)
        outcome = behavior[size].pop(0)
        if isinstance(outcome, str):
            raise RuntimeError(outcome)
        return outcome

    monkeypatch.setattr(bench, "run", fake_run)
    return calls


def test_descends_on_oom(monkeypatch):
    calls = _patched(monkeypatch, {
        "big": ["resource_exhausted"], "small": [123.0]})
    cfg, tok_s = run_descending(("big", "small"), lambda s: s, tag="t")
    assert (cfg, tok_s) == ("small", 123.0)
    assert calls == ["big", "small"]


def test_opaque_retries_same_size_once(monkeypatch):
    calls = _patched(monkeypatch, {
        "big": ["remote_compile http 500", 99.0]})
    cfg, tok_s = run_descending(("big", "small"), lambda s: s, tag="t")
    assert (cfg, tok_s) == ("big", 99.0)
    assert calls == ["big", "big"]


def test_opaque_twice_descends(monkeypatch):
    calls = _patched(monkeypatch, {
        "big": ["remote_compile a", "tpu_compile_helper b"], "small": [7.0]})
    cfg, tok_s = run_descending(("big", "small"), lambda s: s, tag="t")
    assert (cfg, tok_s) == ("small", 7.0)
    assert calls == ["big", "big", "small"]


def test_unknown_error_raises(monkeypatch):
    _patched(monkeypatch, {"big": ["some assertion failed"]})
    with pytest.raises(RuntimeError, match="assertion"):
        run_descending(("big", "small"), lambda s: s, tag="t")


def test_all_sizes_fail_exits(monkeypatch):
    _patched(monkeypatch, {"big": ["out of memory"], "small": ["out of memory"]})
    with pytest.raises(SystemExit, match="failed at all sizes"):
        run_descending(("big", "small"), lambda s: s, tag="t")


def test_entry_watchdog_interrupts_wedged_entry(monkeypatch):
    """The 20260731T0316 failure mode: an entry's remote compile wedges in
    an interruptible sleep. The watchdog must fire instead of letting the
    wedge consume the whole budget; a transient wedge (one trip) retries
    the same size and succeeds."""
    import time as _time

    import bench

    monkeypatch.setenv("PICOTRON_BENCH_ENTRY_TIMEOUT", "1")
    calls = []

    def fake_run(cfg, **kw):
        calls.append(cfg)
        if len(calls) == 1:
            _time.sleep(30)  # wedge: only the alarm can end this
        return 42.0

    monkeypatch.setattr(bench, "run", fake_run)
    t0 = _time.monotonic()
    cfg, tok_s = run_descending(("big", "small"), lambda s: s, tag="t")
    assert (cfg, tok_s) == ("big", 42.0)
    assert calls == ["big", "big"]  # one trip, retry same size, success
    assert _time.monotonic() - t0 < 10


def test_second_watchdog_trip_bails_with_infra_code(monkeypatch):
    """A persistently wedged service must not pay the cap on every size:
    the second trip exits EX_INFRA so the orchestrator can retry/fall back
    without misreading it as a code failure."""
    import time as _time

    import bench

    monkeypatch.setenv("PICOTRON_BENCH_ENTRY_TIMEOUT", "1")
    monkeypatch.setattr(bench, "run",
                        lambda cfg, **kw: _time.sleep(30) or 0.0)
    with pytest.raises(SystemExit) as ei:
        run_descending(("big", "small"), lambda s: s, tag="t")
    assert ei.value.code == bench.EX_INFRA


def test_run_inner_guarded_verdicts():
    """The inner converts ITS OWN terminal failure into the exit-code
    verdict: infra-signature exceptions (tunnel died mid-run) and the
    preflight's backend-init-hung SystemExit exit EX_INFRA; genuine code
    failures propagate (rc=1); success passes through."""
    import bench

    def raises(e):
        def f():
            raise e
        return f

    with pytest.raises(SystemExit) as ei:
        bench.run_inner_guarded(
            raises(RuntimeError("UNAVAILABLE: socket closed")))
    assert ei.value.code == bench.EX_INFRA
    with pytest.raises(SystemExit) as ei:
        bench.run_inner_guarded(raises(SystemExit(
            "TPU kernel parity preflight timed out: backend init hung")))
    assert ei.value.code == bench.EX_INFRA
    with pytest.raises(SystemExit) as ei:  # the watchdog's own bail-out
        bench.run_inner_guarded(raises(SystemExit(bench.EX_INFRA)))
    assert ei.value.code == bench.EX_INFRA
    with pytest.raises(ValueError, match="boom"):
        bench.run_inner_guarded(raises(ValueError("boom")))
    with pytest.raises(SystemExit, match="failed at all sizes"):
        bench.run_inner_guarded(raises(SystemExit(
            "bench failed at all sizes: out of memory")))
    bench.run_inner_guarded(lambda: None)


def test_orchestrate_code_failure_null_is_stamped(monkeypatch, capsys):
    """A genuine code crash (no infra signature) publishes a null artifact
    carrying code_failure=true so the watcher can strike it."""
    import json
    import subprocess as sp

    import bench

    t = _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")

    def failing_inner(script, timeout):
        t[0] += 120
        return sp.CompletedProcess(script, 1, "", "ImportError: boom\n")

    monkeypatch.setattr(bench, "_run_inner", failing_inner)
    monkeypatch.setattr(bench, "latest_captured_record",
                        lambda metric: None)
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=900)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None and rec["code_failure"] is True


def test_orchestrate_last_verdict_wins(monkeypatch, capsys):
    """An early rc=1 crash (e.g. an unlisted transport error text) must
    not stick a code verdict onto a run whose LAST attempt was diagnosed
    infra — the stale fallback stays eligible and no code_failure stamp
    is written."""
    import json
    import subprocess as sp

    import bench

    t = _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")
    attempts = []

    def inner(script, timeout):
        t[0] += 120
        attempts.append(1)
        if len(attempts) == 1:
            return sp.CompletedProcess(script, 1, "", "weird crash\n")
        return sp.CompletedProcess(script, bench.EX_INFRA, "", "wedged\n")

    monkeypatch.setattr(bench, "_run_inner", inner)
    monkeypatch.setattr(
        bench, "latest_captured_record",
        lambda metric: ({"metric": metric, "value": 55.3, "unit": "%",
                         "vs_baseline": 2.5}, "/r/docs/chip_runs/X"))
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=900)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 55.3 and "infra sick" in rec["note"]
    assert len(attempts) >= 2


def test_run_inner_guarded_first_line_classification():
    """A deterministic failure whose message EMBEDS a log tail with
    transport noise (the parity preflight's 'FAILED:\\n<tail>' format)
    must stay a code failure — only the first line classifies."""
    import bench
    import pytest as _pytest

    with _pytest.raises(SystemExit) as ei:
        bench.run_inner_guarded(lambda: (_ for _ in ()).throw(SystemExit(
            "TPU kernel parity tests FAILED:\n...UNAVAILABLE: socket "
            "closed...deadline exceeded...")))
    assert ei.value.code != bench.EX_INFRA


def test_orchestrate_infra_bail_publishes_stale_capture(monkeypatch, capsys):
    """An inner EX_INFRA exit (watchdog gave up on a sick compile service)
    keeps the stale-capture fallback eligible, unlike an rc=1 code failure."""
    import json
    import subprocess as sp

    import bench

    t = _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")

    def infra_inner(script, timeout):
        t[0] += 120
        return sp.CompletedProcess(script, bench.EX_INFRA, "", "wedged\n")

    monkeypatch.setattr(bench, "_run_inner", infra_inner)
    monkeypatch.setattr(
        bench, "latest_captured_record",
        lambda metric: ({"metric": metric, "value": 55.3, "unit": "%",
                         "vs_baseline": 2.5}, "/r/docs/chip_runs/X"))
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=900)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 55.3 and "infra sick" in rec["note"]
    assert f"rc={bench.EX_INFRA}" in rec["error"]


def test_entry_watchdog_disabled_and_cleared(monkeypatch):
    """0 disables the watchdog; after a successful entry no alarm is left
    pending to fire mid-publish."""
    import signal

    import bench

    monkeypatch.setenv("PICOTRON_BENCH_ENTRY_TIMEOUT", "0")
    monkeypatch.setattr(bench, "run", lambda cfg, **kw: 5.0)
    assert run_descending(("a",), lambda s: s, tag="t") == ("a", 5.0)

    monkeypatch.setenv("PICOTRON_BENCH_ENTRY_TIMEOUT", "60")
    assert run_descending(("a",), lambda s: s, tag="t") == ("a", 5.0)
    assert signal.getitimer(signal.ITIMER_REAL) == (0.0, 0.0)


def _tiny_cfg():
    from picotron_tpu.config import Config

    return Config.from_dict({
        "distributed": {"use_cpu": True},
        "model": dict(num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, hidden_size=64,
                      intermediate_size=128, vocab_size=256,
                      max_position_embeddings=64, dtype="float32"),
        "training": {"seq_length": 32, "micro_batch_size": 1},
        "dataset": {"name": "synthetic"},
    })


def test_flash_layout_ab_adopts_faster(monkeypatch):
    import bench

    monkeypatch.setattr(
        bench, "run",
        lambda c, **kw: 200.0 if c.model.flash_layout == "bshd" else 100.0)
    cfg, tok_s = bench.try_flash_layout_ab(_tiny_cfg(), 100.0)
    assert tok_s == 200.0 and cfg.model.flash_layout == "bshd"


def test_flash_layout_ab_failure_keeps_folded(monkeypatch):
    import bench

    def boom(c, **kw):
        raise RuntimeError("Mosaic failed to legalize")

    monkeypatch.setattr(bench, "run", boom)
    base = _tiny_cfg()
    cfg, tok_s = bench.try_flash_layout_ab(base, 100.0)
    assert tok_s == 100.0 and cfg is base


def test_flash_layout_ab_slower_keeps_folded(monkeypatch):
    import bench

    monkeypatch.setattr(bench, "run", lambda c, **kw: 80.0)
    base = _tiny_cfg()
    cfg, tok_s = bench.try_flash_layout_ab(base, 100.0)
    assert tok_s == 100.0 and cfg is base


def test_flash_layout_ab_picks_merged_for_lane_aligned_heads(monkeypatch):
    """head_dim % 128 == 0 (the 7B geometry) must A/B the hardware-lowerable
    'merged' layout, not the Mosaic-rejected 'bshd'."""
    import bench

    tried = []

    def fake_run(c, **kw):
        tried.append(c.model.flash_layout)
        return 200.0

    monkeypatch.setattr(bench, "run", fake_run)
    base = _tiny_cfg()
    base.model.hidden_size = 512  # 4 heads -> head_dim 128
    cfg, tok_s = bench.try_flash_layout_ab(base, 100.0)
    assert tried == ["merged"]
    assert tok_s == 200.0 and cfg.model.flash_layout == "merged"


def _fake_clock(monkeypatch):
    """Patch bench's time.time/time.sleep with a virtual clock so the
    orchestrator's backoffs run instantly in tests."""
    import bench

    t = [0.0]
    monkeypatch.setattr(bench.time, "time", lambda: t[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: t.__setitem__(0, t[0] + s))
    return t


def test_orchestrate_dead_tunnel_prints_null_artifact(monkeypatch, capsys):
    """Round-3 failure mode: tunnel dead the whole window. The artifact must
    still be a parseable JSON line (value=null + diagnosis), exit 0."""
    import json

    import bench

    t = _fake_clock(monkeypatch)

    def dead_probe(timeout):
        t[0] += timeout
        return "dead"

    monkeypatch.setattr(bench, "probe_tunnel", dead_probe)
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=900)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "m" and rec["value"] is None
    assert rec["vs_baseline"] is None and "probe" in rec["error"]


def test_orchestrate_passes_through_inner_success(monkeypatch, capsys):
    import json
    import subprocess as sp

    import bench

    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")
    monkeypatch.setattr(
        bench, "_run_inner",
        lambda script, timeout: sp.CompletedProcess(
            script, 0, '{"metric": "m", "value": 55.0}\n',
            "# flash_layout=bshd wins\n"))
    bench.orchestrate("/x/bench.py", metric="m", unit="%")
    out = capsys.readouterr()
    assert json.loads(out.out.strip()) == {"metric": "m", "value": 55.0}
    assert "bshd wins" in out.err  # A/B record survives into driver stderr


def test_orchestrate_retries_inner_failure_then_succeeds(monkeypatch, capsys):
    import json
    import subprocess as sp

    import bench

    _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")
    outcomes = [
        sp.CompletedProcess((), 1, stdout="", stderr="transient flap\n"),
        sp.CompletedProcess((), 0, stdout='{"metric": "m", "value": 42.0}\n',
                            stderr=""),
    ]
    monkeypatch.setattr(bench, "_run_inner",
                        lambda script, timeout: outcomes.pop(0))
    bench.orchestrate("/x/bench.py", metric="m", unit="%")
    assert json.loads(
        capsys.readouterr().out.strip()) == {"metric": "m", "value": 42.0}
    assert not outcomes


def test_orchestrate_cpu_box_runs_inner_once(monkeypatch, capsys):
    """A plain CPU machine (probe finds a working CPU backend, no
    accelerator) must get the fast smoke path — one inner run, no retry
    loop — instead of burning the backoff budget (round-4 review)."""
    import json
    import subprocess as sp

    import bench

    _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "cpu")
    calls = []

    def fake_run(script, timeout):
        calls.append(script)
        return sp.CompletedProcess(
            script, 0, '{"metric": "tokens_per_sec_cpu_smoke", "value": 9.0}\n',
            "")

    monkeypatch.setattr(bench, "_run_inner", fake_run)
    bench.orchestrate("/x/bench.py", metric="m", unit="%")
    assert len(calls) == 1
    assert json.loads(capsys.readouterr().out.strip())["value"] == 9.0


def test_orchestrate_cpu_box_failure_is_final(monkeypatch, capsys):
    import json
    import subprocess as sp

    import bench

    _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "cpu")
    n = [0]

    def fake_run(script, timeout):
        n[0] += 1
        return sp.CompletedProcess(script, 1, "", "boom")

    monkeypatch.setattr(bench, "_run_inner", fake_run)
    bench.orchestrate("/x/bench.py", metric="m", unit="%")
    assert n[0] == 1  # no pointless retries without an accelerator
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None and "rc=1" in rec["error"]


def test_latest_captured_record_picks_newest_real_capture(tmp_path):
    """The stale-capture fallback must pick the NEWEST in-age original
    record for the metric, skipping nulls, other metrics, re-published
    stale records, out-of-age dirs, and unparseable junk."""
    import json

    import bench

    runs = tmp_path / "docs" / "chip_runs"

    def write(stamp, name, lines):
        d = runs / stamp
        d.mkdir(parents=True, exist_ok=True)
        (d / name).write_text("\n".join(lines) + "\n")

    import datetime

    def stamp(hours_ago):
        t = (datetime.datetime.now(datetime.timezone.utc)
             - datetime.timedelta(hours=hours_ago))
        return t.strftime("%Y%m%dT%H%M%SZ")

    old, mid, new = stamp(30), stamp(5), stamp(1)
    write(old, "bench.log",
          [json.dumps({"metric": "m", "value": 99.0})])  # too old
    write(mid, "bench.log",
          ["# noise", "{not json",
           json.dumps({"metric": "m", "value": 54.0, "unit": "%"})])
    write(new, "bench.log",
          [json.dumps({"metric": "m", "value": None}),     # null: skip
           json.dumps({"metric": "other", "value": 77.0}),  # other metric
           json.dumps({"metric": "m", "value": 50.0,
                       "stale_from": "x"})])               # re-publish: skip
    got = bench.latest_captured_record("m", base=str(tmp_path))
    assert got is not None
    rec, run_dir = got
    assert rec["value"] == 54.0 and run_dir.endswith(mid)
    assert bench.latest_captured_record("nope", base=str(tmp_path)) is None


def test_orchestrate_dead_tunnel_publishes_stale_capture(monkeypatch, capsys):
    """Tunnel dead at publish time but a live window earlier in the round
    captured a real number: publish THAT (with provenance + the dead-tunnel
    diagnosis), not a null artifact."""
    import json

    import bench

    t = _fake_clock(monkeypatch)

    def dead_probe(timeout):
        t[0] += timeout
        return "dead"

    monkeypatch.setattr(bench, "probe_tunnel", dead_probe)
    monkeypatch.setattr(
        bench, "latest_captured_record",
        lambda metric: ({"metric": metric, "value": 55.3, "unit": "%",
                         "vs_baseline": 2.5}, "/r/docs/chip_runs/X"))
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=900)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 55.3 and rec["vs_baseline"] == 2.5
    assert rec["stale_from"].endswith("X") and "probe" in rec["error"]


def test_latest_captured_record_excludes_previous_round(tmp_path):
    """Captures stamped before the round boundary (the newest BENCH_r*.json
    commit) are a previous round's code — never republishable."""
    import datetime
    import json
    import time

    import bench

    t = (datetime.datetime.now(datetime.timezone.utc)
         - datetime.timedelta(hours=2))
    d = tmp_path / "docs" / "chip_runs" / t.strftime("%Y%m%dT%H%M%SZ")
    d.mkdir(parents=True)
    (d / "bench.log").write_text(
        json.dumps({"metric": "m", "value": 42.0}) + "\n")
    assert bench.latest_captured_record("m", base=str(tmp_path)) is not None
    assert bench.latest_captured_record(
        "m", base=str(tmp_path), after_epoch=time.time()) is None


def test_orchestrate_live_tunnel_inner_failures_never_publish_stale(
        monkeypatch, capsys):
    """A live tunnel with a persistently failing inner bench is a CODE
    problem; the stale fallback must not mask it with an old number."""
    import json
    import subprocess as sp

    import bench

    t = _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")

    def failing_inner(script, timeout):
        t[0] += 120
        return sp.CompletedProcess(script, 1, "", "boom\n")

    monkeypatch.setattr(bench, "_run_inner", failing_inner)
    monkeypatch.setattr(
        bench, "latest_captured_record",
        lambda metric: ({"metric": metric, "value": 55.3}, "/x"))
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=900)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None and "rc=1" in rec["error"]


def test_orchestrate_half_alive_tunnel_publishes_stale_capture(
        monkeypatch, capsys):
    """Probes succeed but every inner run HANGS (a half-alive tunnel whose
    remote compiles wedge — the 20260731T0103 window's failure mode).
    Unlike an rc!=0 code failure, a hang is infra: a validated in-round
    capture must be published over a null artifact."""
    import json

    import bench

    t = _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")

    def hanging_inner(script, timeout):
        t[0] += timeout  # consumed its whole timeout, returned partial tail
        return "partial stderr"

    monkeypatch.setattr(bench, "_run_inner", hanging_inner)
    monkeypatch.setattr(
        bench, "latest_captured_record",
        lambda metric: ({"metric": metric, "value": 55.3, "unit": "%",
                         "vs_baseline": 2.5}, "/r/docs/chip_runs/X"))
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=900)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 55.3 and rec["stale_from"].endswith("X")
    assert "half-alive" in rec["note"] and "timed out" in rec["error"]


def test_orchestrate_repeated_hangs_publish_null_not_stale(
        monkeypatch, capsys):
    """EVERY inner attempt hanging while probes stay alive is ambiguous —
    a deterministic deadlock in the bench code looks exactly like a wedged
    compile service — so the stale fallback must NOT fire (it would mask a
    code regression behind an old number). The per-attempt cap is what
    makes a second attempt possible inside the budget."""
    import json

    import bench

    t = _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")
    hangs = []

    def hanging_inner(script, timeout):
        hangs.append(timeout)
        t[0] += timeout
        return "partial stderr"

    monkeypatch.setattr(bench, "_run_inner", hanging_inner)
    monkeypatch.setattr(
        bench, "latest_captured_record",
        lambda metric: ({"metric": metric, "value": 55.3}, "/x"))
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=7000)
    assert len(hangs) >= 2  # the cap left room for a second attempt
    assert all(tmo <= 3000.0 for tmo in hangs)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert "ambiguous" in rec["error"]
    # ambiguous, not a code verdict: the watcher must keep it pending
    # (retryable next window) rather than strike it
    assert "code_failure" not in rec


def test_infra_signature_anchoring():
    """The infra substrings are anchored: gRPC status framing and the
    watchdog's exact phrase count; the bare words appearing in a genuine
    code failure's message must not buy it an infra verdict."""
    import bench

    assert bench._infra_signature("UNAVAILABLE: socket closed")
    assert bench._infra_signature("status = StatusCode.UNAVAILABLE")
    assert bench._infra_signature(
        "ladder entry exceeded its 900s watchdog (wedged remote compile?)")
    assert bench._infra_signature("backend init hung somewhere")
    assert not bench._infra_signature(
        "ValueError: dataset 'unavailable' is not a valid split name")
    assert not bench._infra_signature(
        "AssertionError: watchdog thread failed to start")


def test_orchestrate_truncated_second_hang_still_serves_stale(
        monkeypatch, capsys):
    """A second attempt whose budget was truncated below the full
    per-attempt cap can kill a healthy-but-slow run — its hang must NOT
    vote for the ambiguous-deadlock verdict, so the stale fallback still
    fires (pre-cap behavior preserved)."""
    import json

    import bench

    t = _fake_clock(monkeypatch)
    monkeypatch.setattr(bench, "probe_tunnel", lambda timeout: "tpu")

    def hanging_inner(script, timeout):
        t[0] += timeout
        return "partial stderr"

    monkeypatch.setattr(bench, "_run_inner", hanging_inner)
    monkeypatch.setattr(
        bench, "latest_captured_record",
        lambda metric: ({"metric": metric, "value": 55.3, "unit": "%",
                         "vs_baseline": 2.5}, "/r/docs/chip_runs/X"))
    # 5400 budget: attempt 1 hangs at the 3000 cap, attempt 2 gets only
    # ~2370 (truncated) — one full-cap vote, not two
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=5400)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 55.3 and rec["stale_from"].endswith("X")


def test_orchestrate_tunnel_dies_after_hangs_serves_stale(
        monkeypatch, capsys):
    """Two full-cap hangs followed by the tunnel fully dying: the tunnel
    is NOT alive at the last look, so this is the dead-tunnel case where
    a validated in-round capture beats a null artifact."""
    import json

    import bench

    t = _fake_clock(monkeypatch)
    probes = []

    def degrading_probe(timeout):
        probes.append(1)
        if len(probes) <= 2:
            return "tpu"
        t[0] += timeout
        return "dead"

    monkeypatch.setattr(bench, "probe_tunnel", degrading_probe)

    def hanging_inner(script, timeout):
        t[0] += timeout
        return "partial stderr"

    monkeypatch.setattr(bench, "_run_inner", hanging_inner)
    monkeypatch.setattr(
        bench, "latest_captured_record",
        lambda metric: ({"metric": metric, "value": 55.3, "unit": "%",
                         "vs_baseline": 2.5}, "/r/docs/chip_runs/X"))
    bench.orchestrate("/x/bench.py", metric="m", unit="%", max_total=9000)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 55.3 and rec["stale_from"].endswith("X")
    assert "dead at publish time" in rec["note"]
