"""Decode fast path (ISSUE-3): blocked decode, int8 KV cache, chunked
prefill, and the batcher scheduling fix.

Acceptance surface:
- ``decode_block`` with block_len ∈ {1, 4, 8} produces EXACTLY the token
  streams of the per-token ``decode_step`` loop — EOS and budget stops
  mid-block included — on tp=1 and a tp=2 dryrun mesh, with
  ≤ ceil(N/block_len) + O(1) decode dispatches for N tokens;
- int8-cache greedy decode tracks the fp32-cache oracle (pinned max-abs
  logits bound + token-match rate), and the int8 cache (scales included)
  measures ≤ ~55% of the bf16 cache bytes;
- chunked prefill matches the one-shot bucketed prefill (allclose K/V
  blocks, identical last-token argmax) for prompts spanning 1–3 chunks,
  ragged final chunks included;
- a slot freed by a deadline timeout is refilled in the SAME scheduler
  round (expire-before-admit), not the next.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    Request,
)
from picotron_tpu.inference import kv_cache
from picotron_tpu.models import llama

MAX_LEN = 96

# int8 acceptance knobs: bound on the first post-prefill decode step's
# logits error vs the fp32 cache (measured ~2e-3 on the tiny model; 25x
# margin), and the greedy token-match rate over a 24-token stream
INT8_LOGITS_ATOL = 0.05
INT8_TOKEN_MATCH_RATE = 0.9


def _engine(tiny_model_kwargs, tp=1, slots=2, **kw):
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    return cfg, InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN, **kw)


def _params(cfg, engine, seed=0):
    p = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(seed))
    return engine.shard_params(p)


def _per_token_reference(engine, params, prompt, max_new, eos_id=None):
    """The PR-1 per-token serving loop, written out against decode_step:
    one dispatch + one host sync per token, host-side EOS/budget checks.
    The greedy oracle every blocked run must reproduce bit-for-bit."""
    cache = engine.init_cache()
    kv, logits = engine.prefill(params, prompt)
    cache = engine.insert(cache, kv, 0, len(prompt))
    n = engine.slots
    toks = [int(np.argmax(np.asarray(logits)[0]))]
    temp = np.zeros(n, np.float32)
    top_k = np.zeros(n, np.int32)
    top_p = np.ones(n, np.float32)
    key = jax.random.PRNGKey(0)
    budget = min(max_new, engine.max_seq_len - len(prompt))
    while len(toks) < budget and (eos_id is None or toks[-1] != eos_id):
        feed = np.zeros(n, np.int32)
        feed[0] = toks[-1]
        key, sub = jax.random.split(key)
        cache, out, _ = engine.decode_step(params, cache, feed, sub,
                                           temp, top_k, top_p)
        toks.append(int(np.asarray(out)[0]))
    return toks


# --------------------------------------------------------------------------- #
# blocked decode == per-token loop
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("tp", [1, 2])
@pytest.mark.parametrize("block_len", [1, 4, 8])
def test_decode_block_matches_per_token_loop(tiny_model_kwargs, tp,
                                             block_len):
    """Greedy streams through the blocked batcher — budgets that stop
    mid-block (17 and 6 tokens against blocks of 4/8) — must equal the
    explicit per-token decode_step loop token for token."""
    cfg, engine = _engine(tiny_model_kwargs, tp=tp,
                          decode_block_len=block_len)
    params = _params(cfg, engine)
    reqs = [Request("a", [1, 2, 3, 4, 5], max_new_tokens=17),
            Request("b", [9, 8, 7], max_new_tokens=6)]
    got = ContinuousBatcher(engine, params).run(reqs)
    for r in reqs:
        want = _per_token_reference(engine, params, r.prompt,
                                    r.max_new_tokens)
        assert got[r.uid].tokens == want, (r.uid, block_len, tp)
        assert got[r.uid].finish_reason == "length"


@pytest.mark.parametrize("block_len", [4, 8])
def test_decode_block_eos_mid_block(tiny_model_kwargs, block_len):
    """A slot hitting EOS mid-block goes inactive on device: the stream
    ends AT the EOS token (no post-EOS garbage), identical to the
    per-token loop, and the queued request behind it still completes."""
    cfg, engine = _engine(tiny_model_kwargs, slots=1,
                          decode_block_len=block_len)
    params = _params(cfg, engine)
    prompt = [5, 6, 7, 8]
    free = ContinuousBatcher(engine, params).run(
        [Request("f", prompt, max_new_tokens=12)])["f"]
    eos = free.tokens[5]  # forces a stop 6 tokens in — mid-block for both
    assert eos not in free.tokens[:5], "pick a different seed/prompt"
    res = ContinuousBatcher(engine, params).run([
        Request("x", prompt, max_new_tokens=12, eos_id=eos),
        Request("y", [3, 1, 4], max_new_tokens=5),
    ])
    assert res["x"].finish_reason == "eos"
    assert res["x"].tokens == free.tokens[:6]
    assert res["x"].tokens == _per_token_reference(
        engine, params, prompt, 12, eos_id=eos)
    assert res["y"].finish_reason == "length"
    assert len(res["y"].tokens) == 5


def test_decode_block_stochastic_key_chain(tiny_model_kwargs):
    """Sampled (temperature > 0) streams pin the PRNG plumbing the greedy
    tests can't see: the batcher splits one key per in-block step in chain
    order, so block_len ∈ {1, 4} and an explicit decode_step loop driving
    the SAME split chain must all draw identical tokens — including a
    finish mid-block (14 = 1 prefill token + 13 decode steps vs blocks
    of 4)."""
    cfg, eng1 = _engine(tiny_model_kwargs, decode_block_len=1)
    _, eng4 = _engine(tiny_model_kwargs, decode_block_len=4)
    params = _params(cfg, eng1)
    req = Request("r", [2, 4, 6, 8], max_new_tokens=14,
                  temperature=0.8, top_k=5, top_p=0.9)
    got1 = ContinuousBatcher(eng1, params, seed=3).run([req])["r"].tokens
    got4 = ContinuousBatcher(eng4, params, seed=3).run([req])["r"].tokens

    # the batcher's chain, written out against decode_step: one split for
    # the admit-time draw, then one split per decode round
    key = jax.random.PRNGKey(3)
    cache = eng1.init_cache()
    kv, logits = eng1.prefill(params, req.prompt)
    cache = eng1.insert(cache, kv, 0, len(req.prompt))
    n = eng1.slots
    temp = np.zeros(n, np.float32)
    top_k = np.zeros(n, np.int32)
    top_p = np.ones(n, np.float32)
    temp[0], top_k[0], top_p[0] = req.temperature, req.top_k, req.top_p
    key, sub = jax.random.split(key)
    from picotron_tpu.inference import sampling
    want = [int(sampling.sample(logits, sub, temp[:1], top_k[:1],
                                top_p[:1])[0])]
    while len(want) < req.max_new_tokens:
        feed = np.zeros(n, np.int32)
        feed[0] = want[-1]
        key, sub = jax.random.split(key)
        cache, out, _ = eng1.decode_step(params, cache, feed, sub,
                                         temp, top_k, top_p)
        want.append(int(np.asarray(out)[0]))
    assert got1 == want
    assert got4 == want


@pytest.mark.parametrize("block_len", [1, 4, 8])
def test_decode_dispatch_count(tiny_model_kwargs, block_len):
    """N tokens must cost ≤ ceil(N/block_len) + O(1) decode dispatches —
    the host-sync amortization the block exists for."""
    cfg, engine = _engine(tiny_model_kwargs, slots=2,
                          decode_block_len=block_len)
    params = _params(cfg, engine)
    n_new = 24
    b = ContinuousBatcher(engine, params)
    res = b.run([Request("a", [1, 2, 3], max_new_tokens=n_new)])["a"]
    assert len(res.tokens) == n_new
    assert b.generated_tokens == n_new
    assert b.decode_dispatches <= math.ceil(n_new / block_len) + 1
    assert b.prefill_dispatches == 1


# --------------------------------------------------------------------------- #
# int8 KV cache
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("tp", [1, 2])
def test_int8_cache_tracks_fp32_oracle(tiny_model_kwargs, tp):
    """Greedy decode from the int8 cache must track the fp32-cache oracle:
    first-step logits within INT8_LOGITS_ATOL, ≥ INT8_TOKEN_MATCH_RATE of
    24 greedy tokens identical (tp=2 shards the scale tensors' head axis
    alongside K/V)."""
    cfg, eng_f = _engine(tiny_model_kwargs, tp=tp)
    _, eng_q = _engine(tiny_model_kwargs, tp=tp, cache_dtype="int8")
    assert eng_q.quantized
    params = _params(cfg, eng_f)
    prompt = list(range(1, 9))

    # per-step logits bound: same prompt parked in both caches, one step
    kv_f, lg_f = eng_f.prefill(params, prompt)
    kv_q, lg_q = eng_q.prefill(params, prompt)
    np.testing.assert_array_equal(np.asarray(lg_f), np.asarray(lg_q))
    c_f = eng_f.insert(eng_f.init_cache(), kv_f, 0, len(prompt))
    c_q = eng_q.insert(eng_q.init_cache(), kv_q, 0, len(prompt))
    n = eng_f.slots
    feed = np.zeros(n, np.int32)
    feed[0] = int(np.argmax(np.asarray(lg_f)[0]))
    args = (feed, jax.random.PRNGKey(0), np.zeros(n, np.float32),
            np.zeros(n, np.int32), np.ones(n, np.float32))
    _, _, lo_f = eng_f.decode_step(params, c_f, *args)
    _, _, lo_q = eng_q.decode_step(params, c_q, *args)
    err = float(np.max(np.abs(np.asarray(lo_f)[0] - np.asarray(lo_q)[0])))
    assert err < INT8_LOGITS_ATOL, err

    # stream-level token match rate
    req = [Request("r", prompt, max_new_tokens=24)]
    toks_f = ContinuousBatcher(eng_f, params).run(req)["r"].tokens
    toks_q = ContinuousBatcher(eng_q, params).run(req)["r"].tokens
    match = np.mean([a == b for a, b in zip(toks_f, toks_q)])
    assert match >= INT8_TOKEN_MATCH_RATE, (match, toks_f, toks_q)


def test_int8_cache_halves_bytes():
    """int8 cache bytes (scales included) ≤ 55% of the bf16 cache at the
    production head_dim 64 — the ~2x slots-or-context headroom claim."""
    from picotron_tpu.config import ModelConfig

    m = ModelConfig(num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=4, hidden_size=256,
                    vocab_size=128, dtype="bfloat16")
    assert m.head_dim == 64
    bf16 = kv_cache.cache_bytes(kv_cache.init_cache(m, 4, 128))
    int8 = kv_cache.cache_bytes(
        kv_cache.init_cache(m, 4, 128, quantized=True))
    assert int8 <= 0.55 * bf16, (int8, bf16)
    # and the quantizer round-trips within one scale step of exact
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 4, 64), jnp.float32)
    q, s = kv_cache.quantize_kv(x)
    back = kv_cache.dequantize_kv(q, s, jnp.float32)
    step = np.asarray(s)[..., None] / 2 + 1e-7
    assert np.all(np.abs(np.asarray(back) - np.asarray(x)) <= step)


# --------------------------------------------------------------------------- #
# chunked prefill
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("tp", [1, 2])
@pytest.mark.parametrize("n_tokens", [10, 16, 23, 32, 41])
def test_chunked_prefill_matches_one_shot(tiny_model_kwargs, tp, n_tokens):
    """prefill_chunked (chunk width 16; prompts spanning 1–3 chunks, ragged
    finals included) must reproduce the bucketed one-shot prefill: K/V rows
    allclose, lengths equal, last-token logits allclose with identical
    argmax."""
    cfg, engine = _engine(tiny_model_kwargs, tp=tp, prefill_chunk=16)
    params = _params(cfg, engine)
    prompt = [(7 * i + 3) % cfg.model.vocab_size for i in range(n_tokens)]

    kv, lg_ref = engine.prefill(params, prompt)
    ref = engine.insert(engine.init_cache(), kv, 1, n_tokens)
    chk, lg_chk = engine.prefill_chunked(params, engine.init_cache(),
                                         prompt, 1)
    np.testing.assert_array_equal(np.asarray(ref["lengths"]),
                                  np.asarray(chk["lengths"]))
    for name in ("k", "v"):
        a = np.asarray(ref[name])[:, 1, :n_tokens]
        b = np.asarray(chk[name])[:, 1, :n_tokens]
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lg_ref), np.asarray(lg_chk),
                               rtol=1e-4, atol=1e-4)
    assert (np.argmax(np.asarray(lg_ref)[0])
            == np.argmax(np.asarray(lg_chk)[0]))


def test_chunked_prefill_ragged_cache_window(tiny_model_kwargs):
    """max_seq_len NOT a multiple of prefill_chunk: the final chunk's write
    window would overrun the cache and dynamic_update_slice would CLAMP it
    onto earlier prompt rows — the slide-back path must instead reproduce
    the one-shot prefill exactly (regression: silent K/V corruption)."""
    cfg = make_config(tiny_model_kwargs, seq=24)
    engine = InferenceEngine(cfg, slots=2, max_seq_len=24, prefill_chunk=16)
    params = _params(cfg, engine)
    prompt = [(5 * i + 2) % cfg.model.vocab_size for i in range(20)]

    kv, lg_ref = engine.prefill(params, prompt)
    ref = engine.insert(engine.init_cache(), kv, 0, len(prompt))
    chk, lg_chk = engine.prefill_chunked(params, engine.init_cache(),
                                         prompt, 0)
    for name in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(ref[name])[:, 0, :len(prompt)],
            np.asarray(chk[name])[:, 0, :len(prompt)],
            rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lg_ref), np.asarray(lg_chk),
                               rtol=1e-4, atol=1e-4)
    assert (np.argmax(np.asarray(lg_ref)[0])
            == np.argmax(np.asarray(lg_chk)[0]))


def test_cache_dtype_keyword_overrides_config(tiny_model_kwargs):
    """An explicit cache_dtype wins over inference.kv_cache_dtype in BOTH
    directions — int8 on, and back off."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    cfg.inference.kv_cache_dtype = "int8"
    assert InferenceEngine(cfg, max_seq_len=MAX_LEN).quantized
    off = InferenceEngine(cfg, max_seq_len=MAX_LEN, cache_dtype="float32")
    assert not off.quantized and off.cache_dtype == np.dtype(np.float32)


def test_chunked_prefill_through_batcher(tiny_model_kwargs):
    """A prompt above prefill_chunk admits through the chunked path and
    generates the same stream as an engine whose chunk width makes the
    same prompt take the bucketed one-shot path (int8 cache included —
    chunk writes quantize like inserts do)."""
    for extra in ({}, {"cache_dtype": "int8"}):
        cfg, eng_c = _engine(tiny_model_kwargs, prefill_chunk=16, **extra)
        _, eng_b = _engine(tiny_model_kwargs, prefill_chunk=512, **extra)
        params = _params(cfg, eng_c)
        prompt = [(3 * i + 1) % cfg.model.vocab_size for i in range(40)]
        req = [Request("r", prompt, max_new_tokens=8)]
        bc = ContinuousBatcher(eng_c, params)
        chunked = bc.run(req)["r"].tokens
        assert bc.prefill_dispatches == 3  # ceil(40/16)
        bucketed = ContinuousBatcher(eng_b, params).run(req)["r"].tokens
        assert chunked == bucketed, extra


# --------------------------------------------------------------------------- #
# batcher scheduling: expire before admit
# --------------------------------------------------------------------------- #


def test_timeout_slot_refilled_same_round(tiny_model_kwargs):
    """A slot whose request is past deadline at the top of step() must be
    expired AND refilled by the waiting request within that same step —
    the old admit-first order left it idle for a full round."""

    class Clock:
        t = 0.0

        def __call__(self):
            self.t += 1.0
            return self.t

    cfg, engine = _engine(tiny_model_kwargs, slots=1, decode_block_len=2)
    params = _params(cfg, engine)
    b = ContinuousBatcher(engine, params, clock=Clock())
    b.submit(Request("hog", [1, 2, 3], max_new_tokens=64, timeout_s=0.5))
    b.submit(Request("queued", [4, 5, 6], max_new_tokens=4))
    b.step()  # admits hog (deadline already in the past after admit)
    assert b._slots[0] is not None and b._slots[0].req.uid == "hog"
    b.step()  # ONE round: expire hog -> admit queued -> decode queued
    assert "hog" in b._results
    assert b._results["hog"].finish_reason == "timeout"
    s = b._slots[0]
    assert s is not None and s.req.uid == "queued"
    assert len(s.generated) > 0  # queued decoded in the same round
