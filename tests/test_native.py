"""Native (C++) data-loader kernels vs their numpy fallbacks: the two paths
must be bitwise identical (picotron_tpu/native/dataloader.cc contract)."""

import subprocess
import sys

import numpy as np
import pytest

from picotron_tpu import native
from picotron_tpu.data import MicroBatchDataLoader, synthetic_corpus
from tests.conftest import make_config

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)")


@needs_native
def test_affine_chain_matches_python():
    vocab, length, seed = 257, 10_000, 7
    rng = np.random.default_rng(seed)
    a = int(rng.integers(1, vocab))
    b = int(rng.integers(0, vocab))
    toks = np.empty(length, dtype=np.int32)
    toks[0] = rng.integers(0, vocab)
    jumps = rng.random(length) < 0.05
    jump_vals = rng.integers(0, vocab, length)

    ref = toks.copy()
    for i in range(1, length):
        ref[i] = jump_vals[i] if jumps[i] else (a * int(ref[i - 1]) + b) % vocab

    native.affine_chain(toks, jumps.view(np.uint8), jump_vals, a, b, vocab)
    np.testing.assert_array_equal(toks, ref)


@needs_native
def test_gather_batch_matches_numpy():
    rng = np.random.default_rng(0)
    samples = rng.integers(0, 1000, (50, 33), dtype=np.int32)
    idx = rng.permutation(50)[:24].astype(np.int64)
    inp, tgt = native.gather_batch(samples, idx)
    np.testing.assert_array_equal(inp, samples[idx][:, :-1])
    np.testing.assert_array_equal(tgt, samples[idx][:, 1:])


@needs_native
def test_loader_identical_with_and_without_native(tiny_model_kwargs):
    """Full-loader oracle: batches and epoch accounting agree between the
    native path (in-process) and a PICOTRON_DISABLE_NATIVE=1 subprocess."""
    cfg = make_config(tiny_model_kwargs, dp=2, seq=32, mbs=3, acc=2)
    loader = MicroBatchDataLoader(cfg)
    batches = [next(loader) for _ in range(4)]

    code = """
import json, sys
import numpy as np
from tests.conftest import make_config
from picotron_tpu.data import MicroBatchDataLoader
tiny = json.loads(sys.argv[1])
cfg = make_config(tiny, dp=2, seq=32, mbs=3, acc=2)
loader = MicroBatchDataLoader(cfg)
out = [next(loader) for _ in range(4)]
np.save(sys.stdout.buffer, np.stack([np.stack([b["input_ids"], b["target_ids"]]) for b in out]))
"""
    import json
    import os

    env = {**os.environ, "PICOTRON_DISABLE_NATIVE": "1",
           "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-c", code, json.dumps(tiny_model_kwargs)],
        capture_output=True, env=env, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr.decode()
    import io

    ref = np.load(io.BytesIO(proc.stdout))
    for i, b in enumerate(batches):
        np.testing.assert_array_equal(b["input_ids"], ref[i, 0])
        np.testing.assert_array_equal(b["target_ids"], ref[i, 1])


def test_epoch_wrap_accounting(tiny_model_kwargs):
    """Wrapping a small corpus bumps the epoch and keeps yielding batches
    (reference infinite-iterator semantics, data.py:118-137)."""
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=4)
    loader = MicroBatchDataLoader(cfg)
    n_batches_per_epoch = len(loader.samples) / loader.rows_per_step
    for _ in range(int(n_batches_per_epoch) + 1):
        next(loader)
    assert loader._epoch >= 1


def test_synthetic_corpus_deterministic():
    a = synthetic_corpus(128, 5000, seed=3)
    b = synthetic_corpus(128, 5000, seed=3)
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 0 and a.max() < 128
