"""Prefill/decode disaggregation suite (ISSUE 15;
inference/page_transport.py, serve.py roles, router orchestration).

Four layers of pinning:

- **transport**: export -> import roundtrips are BYTE-exact across every
  storage variant (fp32 / bf16 / int8 / the hot_bf16 dual representation)
  and across tp shardings (a tp=1 export lands on a tp=2 pool and vice
  versa — payloads carry gathered global bytes); contiguous engines are
  rejected; spec mismatches and torn payloads (CRC) fail loudly BEFORE
  any pool page exists;
- **refcounts**: a failed import (exhausted pool, device write fault)
  releases every allocated page — the pool is exactly as before — and a
  retry then succeeds; re-importing an already-cached payload allocates
  nothing (idempotent under the dispatch-retry discipline);
- **seating**: a request admitted with a handoff payload seats with ZERO
  prefill dispatches and generates bit-identically to a colocated
  (role=both) run, across decode_block / speculative verify / chunked
  prefill x dense/flash x int8 KV/weights x tp=1/2;
- **fabric**: the same bit-identity through the REAL router over a
  two-role fleet (prefill worker exports, decode worker seats), plus the
  cross-replica prefix lookup: a second replica serving a shared prefix
  imports the affinity owner's pages and performs zero prefill
  dispatches for the covered prefix, asserted via the registry counters.

The chaos rungs (prefill-worker death mid-export, severed page stream)
run in `make router-chaos-smoke`, whose full drill is tier-1 via
tests/test_router.py::test_router_chaos_smoke_acceptance.
"""

import threading
import time

import numpy as np
import pytest

import jax

from conftest import make_config
from picotron_tpu.config import Config, RouterConfig
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    Request,
)
from picotron_tpu.inference import page_transport
from picotron_tpu.inference.page_transport import TransportError
from picotron_tpu.inference.paged_kv import PagePoolExhausted, RadixCache, \
    PagePool
from picotron_tpu.models import llama

MAX_LEN = 64
PAGE = 8

_TINY = dict(
    num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
    hidden_size=64, intermediate_size=128, vocab_size=256,
    max_position_embeddings=MAX_LEN, rope_theta=10000.0, dtype="float32",
    attention_impl="sdpa")

# 18 tokens = 2 full pages + a partial tail at PAGE=8 — exercises the
# partial-leaf adoption path in every roundtrip
PROMPT = list(range(1, 19))


def _build(tp=1, **kw):
    cfg = make_config(dict(_TINY), tp=tp, seq=32)
    kw.setdefault("kv_page_len", PAGE)
    engine = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                             kv_layout="paged", **kw)
    params = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(0))
    if engine.quant_weights:
        params = llama.quantize_params(params)
    return engine, engine.shard_params(params)


def _payload_for(prompt, max_new=1, **kw):
    """Prefill ``prompt`` on a fresh engine and export its pages + first
    token — the prefill worker's half of the handoff."""
    engine, params = _build(**kw)
    b = ContinuousBatcher(engine, params)
    res = b.run([Request("pf", list(prompt), max_new_tokens=max_new)])
    payload = b.export_prefix(list(prompt),
                              first_token=res["pf"].tokens[0])
    return engine, b, payload, res["pf"].tokens


# --------------------------------------------------------------------------- #
# transport: byte-exact roundtrips + loud rejections
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("kw_exp,kw_imp", [
    (dict(), dict()),
    (dict(cache_dtype="bfloat16"), dict(cache_dtype="bfloat16")),
    (dict(cache_dtype="int8"), dict(cache_dtype="int8")),
    (dict(kv_page_policy="hot_bf16"), dict(kv_page_policy="hot_bf16")),
    (dict(tp=2), dict(tp=2)),
    # tp-shard interop: payloads hold gathered global bytes, so a tp=1
    # export lands on a tp=2 pool byte-identically (the reverse
    # direction exercises no further code: tp=2 gathering is the "tp2"
    # case, tp=1 import the identity placement)
    (dict(), dict(tp=2)),
], ids=["fp32", "bf16", "int8", "hot_bf16", "tp2", "tp1_to_tp2"])
def test_transport_roundtrip_byte_exact(kw_exp, kw_imp):
    eng_a, b_a, payload, _ = _payload_for(PROMPT, **kw_exp)
    assert payload["token_ids"] == PROMPT
    assert len(payload["pages"]) == 3 and payload["bytes_total"] > 0
    eng_b, params_b = _build(**kw_imp)
    b_b = ContinuousBatcher(eng_b, params_b)
    info = b_b.import_prefix(payload)
    assert info["tokens"] == 18 and info["pages_imported"] == 3
    # pin both sides' pages and compare every storage leaf byte-for-byte
    pids_a, m_a = eng_a.paged.acquire_prefix(PROMPT)
    pids_b, m_b = eng_b.paged.acquire_prefix(PROMPT)
    assert m_a == m_b == 18
    try:
        for pa, pb in zip(pids_a, pids_b):
            page_a = eng_a._slice_page_jit(b_a._cache, pa)
            page_b = eng_b._slice_page_jit(b_b._cache, pb)
            assert set(page_a) == set(page_b)
            for name in page_a:
                assert (np.asarray(page_a[name]).tobytes()
                        == np.asarray(page_b[name]).tobytes()), name
    finally:
        eng_a.paged.release_pages(pids_a)
        eng_b.paged.release_pages(pids_b)


def test_transport_rejects_contiguous_and_mismatch_and_crc():
    cfg = make_config(dict(_TINY), tp=1, seq=32)
    contiguous = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
    with pytest.raises(TransportError, match="paged"):
        page_transport.transport_spec(contiguous)

    _, _, payload, _ = _payload_for(PROMPT)  # fp32 payload
    eng_i8, params_i8 = _build(cache_dtype="int8")
    b_i8 = ContinuousBatcher(eng_i8, params_i8)
    with pytest.raises(TransportError, match="mismatch"):
        b_i8.import_prefix(payload)

    eng_pl, params_pl = _build(kv_page_len=16)
    b_pl = ContinuousBatcher(eng_pl, params_pl)
    with pytest.raises(TransportError, match="page_len"):
        b_pl.import_prefix(payload)

    # torn page stream: CRC dies before any page is allocated
    eng, params = _build()
    b = ContinuousBatcher(eng, params)
    free0 = eng.paged.pool.free_count
    bad = dict(payload, crc32=payload["crc32"] ^ 1)
    with pytest.raises(TransportError, match="CRC"):
        b.import_prefix(bad)
    assert eng.paged.pool.free_count == free0
    # truncated page list is a count mismatch, not a silent partial
    bad = dict(payload, pages=payload["pages"][:2])
    with pytest.raises(TransportError, match="pages"):
        b.import_prefix(bad)
    assert eng.paged.pool.free_count == free0


# --------------------------------------------------------------------------- #
# refcounts: failed imports leak nothing, retries converge
# --------------------------------------------------------------------------- #


def test_failed_import_releases_every_page_and_retry_succeeds():
    _, _, payload, _ = _payload_for(PROMPT)
    eng, params = _build()
    b = ContinuousBatcher(eng, params)
    free0 = eng.paged.pool.free_count
    orig = eng._write_pages_jit

    def bomb(cache, pages, pids):
        raise RuntimeError("chaos: device write fault")

    eng._write_pages_jit = bomb
    with pytest.raises(RuntimeError, match="write fault"):
        b.import_prefix(payload)
    # all-or-nothing: the pool is exactly as before the import, and the
    # radix grafted nothing (a later match must not see garbage pages)
    assert eng.paged.pool.free_count == free0
    assert eng.paged.radix.match(PROMPT) == ([], 0)
    eng._write_pages_jit = orig
    info = b.import_prefix(payload)
    assert info["pages_imported"] == 3
    assert eng.paged.pool.free_count == free0 - 3
    # idempotent: a re-import (the dispatch-retry shape) allocates nothing
    info = b.import_prefix(payload)
    assert info["pages_imported"] == 0 and info["created"] == 0
    assert eng.paged.pool.free_count == free0 - 3


def test_exhausted_pool_releases_partial_alloc():
    _, _, payload, _ = _payload_for(PROMPT)
    # a pool with room for 2 of the 3 payload pages (num_pages counts the
    # reserved NULL page)
    eng, params = _build(kv_num_pages=3)
    b = ContinuousBatcher(eng, params)
    with pytest.raises(PagePoolExhausted):
        b.import_prefix(payload)
    assert eng.paged.pool.free_count == 2
    assert np.all(eng.paged.pool.refs[1:] == 0)


def test_radix_adopt_plan_and_duplicates():
    pool = PagePool(16)
    radix = RadixCache(4, pool)
    ids = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]  # 2 full chunks + tail of 2
    assert radix.plan_adopt(ids) == [0, 1, 2]
    pids = [pool.alloc() for _ in range(3)]
    created, dups = radix.adopt(ids, dict(zip([0, 1, 2], pids)))
    assert created == 3 and dups == []
    for pid in pids:
        pool.unref(pid)  # drop the importer refs; the cache holds all 3
    assert all(pool.refs[p] == 1 for p in pids)
    # the whole prefix now matches, partial tail included
    assert radix.match(ids)[1] == 10
    # a second adopt of the same ids: every chunk is a duplicate
    assert radix.plan_adopt(ids) == []
    pids2 = [pool.alloc() for _ in range(3)]
    created, dups = radix.adopt(ids, dict(zip([0, 1, 2], pids2)))
    assert created == 0 and sorted(dups) == sorted(pids2)
    for pid in pids2:
        pool.unref(pid)
    assert pool.refs[pids2[0]] == 0  # duplicates freed outright
    # a longer prefix sharing chunk 0 plans only its own suffix
    ids2 = [1, 2, 3, 4, 99, 98, 97, 96]
    assert radix.plan_adopt(ids2) == [1]


# --------------------------------------------------------------------------- #
# seating: handoff == colocated, across the dispatch-family matrix
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("kw", [
    dict(decode_block_len=4),
    # one cell covers flash attend + int8 KV + the speculative verify
    # dispatch family (their interactions, not just each alone)
    dict(attend_impl="flash", cache_dtype="int8", spec_len=2),
    # ...and one covers int8 weights + chunked prefill ON THE PREFILL
    # SIDE (prompt wider than the chunk)
    dict(weight_dtype="int8", prefill_chunk=8, decode_block_len=2),
    dict(kv_page_policy="hot_bf16", tp=2, decode_block_len=2),
], ids=["block_dense", "flash_int8kv_verify", "int8w_chunked",
        "hot_bf16_tp2"])
def test_handoff_seat_bit_identical_and_dispatch_free(kw):
    kw = dict(kw)
    max_new = 8
    eng_p, b_p, payload, _ = _payload_for(PROMPT, **dict(kw))
    # decode worker: seats the payload, generates with ZERO prefill work
    eng_d, params_d = _build(**dict(kw))
    b_d = ContinuousBatcher(eng_d, params_d)
    res_d = b_d.run([Request("d", PROMPT, max_new_tokens=max_new,
                             kv_import=payload)])
    assert b_d.handoff_seated == 1
    assert b_d.prefill_dispatches == 0
    # colocated oracle: a plain admission of the same prompt on the
    # EXPORTER engine (its radix hit is output-invariant — pinned in
    # test_paged_kv — so this is the colocated generation)
    res_c = b_p.run([Request("c", PROMPT, max_new_tokens=max_new)])
    assert res_d["d"].tokens == res_c["c"].tokens
    assert res_d["d"].finish_reason == res_c["c"].finish_reason


def test_remote_prefix_hit_equals_local_prefix_hit():
    """A second request sharing the prompt's prefix generates the same
    tokens whether the prefix came from the LOCAL radix cache (same
    replica) or from a REMOTE import — and the remote replica's prefill
    work covers only the uncovered suffix."""
    shared = PROMPT
    extended = shared + [41, 42, 43]
    # local: one engine serves both requests (radix hit on the second)
    eng_l, params_l = _build()
    b_l = ContinuousBatcher(eng_l, params_l)
    b_l.run([Request("seed", shared, max_new_tokens=1)])
    pf0 = b_l.prefill_dispatches
    res_l = b_l.run([Request("ext", extended, max_new_tokens=8)])
    local_prefills = b_l.prefill_dispatches - pf0
    # remote: a fresh engine imports the exported prefix, then serves
    payload = b_l.export_prefix(shared)
    assert "first_token" not in payload  # a lookup vouches for pages only
    eng_r, params_r = _build()
    b_r = ContinuousBatcher(eng_r, params_r)
    b_r.import_prefix(payload)
    res_r = b_r.run([Request("ext", extended, max_new_tokens=8)])
    assert res_r["ext"].tokens == res_l["ext"].tokens
    # the import covered the shared prefix: the remote replica prefilled
    # exactly what the local radix hit left over (the 3-token suffix +
    # the last-token rule), never the shared pages
    assert b_r.prefill_dispatches == local_prefills
    assert int(b_r._remote_hits_total.value) == 1
    stats = b_r.stats()
    assert stats["prefix_remote_hits"] == 1
    assert stats["prefix_cached_tokens"] >= 16  # page-aligned share


def test_partial_payload_falls_back_to_prefix_hint():
    """A payload that covers only part of the prompt (no first_token for
    the full prompt) cannot seat — the admission imports it as a radix
    hint and prefills the remainder, still bit-identical."""
    eng_p, b_p, payload, _ = _payload_for(PROMPT)
    extended = PROMPT + [51, 52, 53, 54]
    eng_d, params_d = _build()
    b_d = ContinuousBatcher(eng_d, params_d)
    res_d = b_d.run([Request("d", extended, max_new_tokens=6,
                             kv_import=payload)])
    assert b_d.handoff_seated == 0  # hint, not a seat
    assert b_d.prefill_dispatches >= 1
    eng_c, params_c = _build()
    b_c = ContinuousBatcher(eng_c, params_c)
    res_c = b_c.run([Request("c", extended, max_new_tokens=6)])
    assert res_d["d"].tokens == res_c["c"].tokens
    # a CORRUPT payload on the seating path degrades to self-prefill —
    # the request is servable, so it must never finish "error"
    bad = dict(payload, crc32=payload["crc32"] ^ 1)
    res_bad = b_d.run([Request("bad", extended, max_new_tokens=6,
                               kv_import=bad)])
    assert res_bad["bad"].finish_reason == "length"
    assert res_bad["bad"].tokens == res_c["c"].tokens
    assert b_d.handoff_seated == 0


def test_config_role_validation():
    raw = Config.from_dict({"dataset": {"name": "synthetic"}}).to_dict()
    raw["inference"].update(role="prefill", kv_layout="paged")
    Config.from_dict(raw).validate()
    raw["inference"].update(kv_layout="contiguous")
    with pytest.raises(ValueError, match="paged"):
        Config.from_dict(raw).validate()
    raw["inference"].update(role="router")
    with pytest.raises(ValueError, match="unknown inference.role"):
        Config.from_dict(raw).validate()
    cfg = RouterConfig(handoff_timeout_s=0.0)
    with pytest.raises(ValueError, match="handoff_timeout_s"):
        cfg.validate()


# --------------------------------------------------------------------------- #
# fabric: the real router over a two-role fleet
# --------------------------------------------------------------------------- #


def _serve_fleet(roles, **inf_kw):
    """In-process serve.py servers over identical params; paged layout,
    per-token streaming."""
    from picotron_tpu.tools import serve

    servers = []
    for role in roles:
        cfg = make_config(dict(_TINY), tp=inf_kw.get("tp", 1), seq=32)
        cfg.inference.kv_layout = "paged"
        cfg.inference.kv_page_len = PAGE
        cfg.inference.role = role
        cfg.inference.decode_block_len = 1
        for k, v in inf_kw.items():
            if k != "tp":
                setattr(cfg.inference, k, v)
        engine = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
        params = engine.shard_params(jax.jit(
            lambda k, m=cfg.model: llama.init_params(k, m))(
                jax.random.PRNGKey(0)))
        srv = serve.Server(engine, params, port=0,
                           log=lambda *a, **k: None)
        srv.start()
        servers.append(srv)
    return servers


def _wait(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


@pytest.mark.parametrize("inf_kw", [
    # chunked prefill on the prefill worker + speculative verify on the
    # decode worker — the plain dense/block fabric case runs in the
    # router-chaos-smoke disagg rungs (tier-1 via test_router's
    # acceptance test), so this parameterization covers what it doesn't
    dict(prefill_chunk=8, spec_len=2),
], ids=["chunked_spec"])
def test_disagg_fleet_through_router_bit_identical(inf_kw):
    """The acceptance fabric: prefill worker + decode worker behind the
    REAL router. The routed generation must be bit-identical to a
    colocated (role=both) replica's, the decode worker must seat the
    handoff with zero prefill dispatches, and the handoff must be
    accounted on both the router and the replicas."""
    from picotron_tpu.tools import serve
    from picotron_tpu.tools.router import RouterServer, _stream_post

    servers = _serve_fleet(("prefill", "decode", "both"), **inf_kw)
    pre, dec, both = servers
    names = [f"127.0.0.1:{s.port}" for s in (pre, dec)]
    rs = RouterServer(names, RouterConfig(probe_interval_s=0.05,
                                          scrape_stale_s=5.0),
                      log=lambda *a, **k: None)
    rs.start()
    try:
        assert _wait(lambda: len(rs.router._candidates(
            kind="prefill")) == 1 and len(rs.router._eligible()) == 1)
        spec = {"prompt": PROMPT, "max_new_tokens": 10}
        st, body = serve._post(both.port, spec)  # colocated oracle
        assert st == 200
        oracle = body["tokens"]
        st, rows = _stream_post(rs.port, {**spec, "request_id": "dg"})
        toks = [r["token"] for r in rows if r.get("event") == "token"]
        done = [r for r in rows if r.get("event") == "done"][0]
        assert st == 200 and done["tokens"] == toks == oracle
        assert done["finish_reason"] == "length" and done["replays"] == 0
        dstz = serve._get(dec.port, "/statz")[1]
        assert dstz["handoff_seated"] == 1
        assert dstz["prefill_dispatches"] == 0
        assert dstz["role"] == "decode"
        pstz = serve._get(pre.port, "/statz")[1]
        assert pstz["admitted"] == 1 and pstz["role"] == "prefill"
        stats = rs.router.stats()
        assert stats["handoffs"]["served"] == 1
        assert stats["handoff_bytes"] > 0
        assert stats["handoff_s"] is not None
        # replica-side byte accounting reached /metrics
        mtext = serve._get_text(dec.port, "/metrics")[1]
        assert 'picotron_handoff_bytes_total{dir="import"}' in mtext
        assert "picotron_prefix_remote_hits_total" in mtext
    finally:
        rs.stop()
        for s in servers:
            try:
                s.drain_and_join(timeout=60)
            except OSError:
                pass


def test_prefill_role_sheds_generate_and_router_skips_it():
    from picotron_tpu.tools import serve

    servers = _serve_fleet(("prefill",))
    try:
        st, body = serve._post(servers[0].port,
                               {"prompt": [1, 2, 3], "max_new_tokens": 4})
        assert st == 503 and "prefill" in body["error"]
        stz = serve._get(servers[0].port, "/statz")[1]
        assert stz["rejected"]["role"] == 1
        # a router over ONLY a prefill worker has no decode candidates
        from picotron_tpu.tools.router import Router

        r = Router([f"127.0.0.1:{servers[0].port}"],
                   RouterConfig(probe_interval_s=0.05),
                   log=lambda *a, **k: None)
        r.start()
        try:
            assert _wait(lambda: len(r._candidates(kind="prefill")) == 1)
            assert r._eligible() == []
        finally:
            r.stop()
    finally:
        for s in servers:
            try:
                s.drain_and_join(timeout=60)
            except OSError:
                pass


def test_cross_replica_prefix_lookup_through_router():
    """The acceptance counterpart of the ISSUE's last criterion: replica
    B serving a prompt whose prefix the affinity owner A already holds
    performs ZERO prefill dispatches for the shared prefix — the router
    fetches A's pages (GET-shaped /kv/pages lookup + /kv/import) before
    B's generate, and the registry counters prove the import."""
    from picotron_tpu.tools import serve
    from picotron_tpu.tools.router import RouterServer, _stream_post

    servers = _serve_fleet(("both", "both"))
    names = [f"127.0.0.1:{s.port}" for s in servers]
    by_name = dict(zip(names, servers))
    rs = RouterServer(names, RouterConfig(probe_interval_s=0.05,
                                          scrape_stale_s=10.0,
                                          affinity_load_slack=0.0),
                      log=lambda *a, **k: None)
    rs.start()
    try:
        assert rs.router.wait_eligible(2, timeout=30)
        owner = rs.router._affinity_owner(PROMPT)
        other = [n for n in names if n != owner.name][0]
        spec = {"prompt": PROMPT, "max_new_tokens": 8}
        st, rows = _stream_post(rs.port, {**spec, "request_id": "seed"})
        toks = [r["token"] for r in rows if r.get("event") == "token"]
        assert st == 200
        assert serve._get(by_name[owner.name].port,
                          "/statz")[1]["admitted"] == 1
        # force the next placement off the affinity owner
        rep = rs.router.replicas[owner.name]
        with rep._mu:
            rep.inflight += 50
        pre = serve._get(by_name[other].port, "/statz")[1]
        st, rows = _stream_post(rs.port, {**spec, "request_id": "esc"})
        toks2 = [r["token"] for r in rows if r.get("event") == "token"]
        done = [r for r in rows if r.get("event") == "done"][0]
        assert st == 200 and done["replica"] == other and toks2 == toks
        post = serve._get(by_name[other].port, "/statz")[1]
        # the escape imported the owner's pages: one remote hit, the
        # whole page-aligned shared prefix cached, and the only prefill
        # dispatch is the capped last token — zero for the shared prefix
        assert post["prefix_remote_hits"] - pre.get(
            "prefix_remote_hits", 0) == 1
        assert post["prefix_cached_tokens"] - pre.get(
            "prefix_cached_tokens", 0) == len(PROMPT) - 1
        assert post["prefill_dispatches"] - pre.get(
            "prefill_dispatches", 0) == 1
        assert rs.router.stats()["prefix_fetches"]["hit"] == 1
    finally:
        rs.stop()
        for s in servers:
            try:
                s.drain_and_join(timeout=60)
            except OSError:
                pass


def test_unusable_kv_payload_is_dropped_not_400():
    """A mixed/mid-upgrade fleet must degrade to colocated behavior:
    a /generate carrying a payload this replica cannot consume (here a
    mismatched page_len) self-prefills and serves — never a client
    400 — with the drop counted."""
    from picotron_tpu.tools import serve

    _, _, payload, _ = _payload_for(PROMPT)  # PAGE=8 payload
    servers = _serve_fleet(("both",), kv_page_len=16)
    try:
        st, body = serve._post(
            servers[0].port,
            {"prompt": PROMPT, "max_new_tokens": 6, "kv": payload})
        assert st == 200 and len(body["tokens"]) == 6
        mtext = serve._get_text(servers[0].port, "/metrics")[1]
        assert "picotron_handoff_dropped_total 1" in mtext
        stz = serve._get(servers[0].port, "/statz")[1]
        assert stz["handoff_seated"] == 0
    finally:
        for s in servers:
            try:
                s.drain_and_join(timeout=60)
            except OSError:
                pass


def test_kv_pages_get_endpoint_and_import_endpoint():
    """The raw lookup surface: GET /kv/pages?ids=... on the owner, POST
    /kv/import on the peer — the manual (router-less) flavor of the
    cross-replica transfer."""
    import http.client
    import json as _json

    from picotron_tpu.tools import serve

    a, b = _serve_fleet(("both", "both"))
    try:
        st, _ = serve._post(a.port, {"prompt": PROMPT,
                                     "max_new_tokens": 1})
        assert st == 200
        ids = ",".join(str(t) for t in PROMPT)
        st, out = serve._get(a.port, f"/kv/pages?ids={ids}")
        assert st == 200 and out["matched"] == len(PROMPT)
        conn = http.client.HTTPConnection("127.0.0.1", b.port, timeout=60)
        conn.request("POST", "/kv/import", _json.dumps({"kv": out["kv"]}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        info = _json.loads(resp.read())
        conn.close()
        assert resp.status == 200 and info["tokens"] == len(PROMPT)
        assert info["pages_imported"] == 3
        # miss: unknown ids match nothing
        st, out = serve._get(a.port, "/kv/pages?ids=250,251,252")
        assert st == 200 and out["matched"] == 0
    finally:
        for s in (a, b):
            try:
                s.drain_and_join(timeout=60)
            except OSError:
                pass
