"""Per-channel int8 weight quantization: the fused dequant matmul and its
end-to-end wiring (ops/pallas/quant_matmul.py, ``inference.weight_dtype``).

The discipline mirrors the int8 KV cache's (test_decode_kernel.py):

- kernel-level parity: the Pallas kernel (interpret mode — the CPU tier-1
  gate; the same program lowers to Mosaic on a chip) and the XLA fallback
  are both allclose to the fake-quant reference
  ``x @ dequantize_weight(q, s)`` across shapes, dtypes, and non-dividing
  tile sizes;
- the no-materialization proof: ``dequantize_weight`` is monkeypatched to
  raise and full int8-weight generations still run — the serving path
  never builds a dequantized copy of any weight, on either impl;
- engine-level equivalence: an int8 engine's generations are IDENTICAL to
  a bf16 engine fed the fake-quant reference tree (the quantization error
  is in both, so any difference is the fused pipeline itself) across
  decode_block / speculative verify / chunked prefill, dense AND flash
  attends, contiguous AND paged KV layouts, tp=1 and tp=2, greedy pinned
  through the full ContinuousBatcher.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.config import Config
from picotron_tpu.inference import ContinuousBatcher, InferenceEngine, Request
from picotron_tpu.models import llama
from picotron_tpu.ops.pallas import quant_matmul as qm

MAX_LEN = 96


# --------------------------------------------------------------------------- #
# quantization + kernel parity (direct calls)
# --------------------------------------------------------------------------- #


def test_quantize_weight_per_channel_error_bound():
    """Dequantized weights sit within the per-channel absmax grid: error
    at most half a quantization step (scale/2) per element, and an
    all-zero channel round-trips exactly (uneven-pp pad rows)."""
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 40)).astype(np.float32)
    w[:, 7] = 0.0  # a dead channel
    w[:, 11] = w[:, 11] * 1e-12  # denormal-tiny channel: the clamp edge —
    # the STORED scale must be the clamped divisor, or dequantization
    # collapses to zero while claiming a true tiny scale
    qw = qm.quantize_weight(jnp.asarray(w))
    deq = np.asarray(qm.dequantize_weight(qw["q"], qw["s"]))
    step = np.asarray(qw["s"])  # one scale per output channel
    assert np.all(np.abs(deq - w) <= step[None, :] / 2 + 1e-8)
    np.testing.assert_array_equal(deq[:, 7], 0.0)
    # the host (numpy) variant is bit-identical — the checkpoint
    # streaming path quantizes exactly like the in-memory one
    qh = qm.quantize_weight_host(w)
    np.testing.assert_array_equal(np.asarray(qw["q"]), qh["q"])
    np.testing.assert_array_equal(np.asarray(qw["s"]), qh["s"])


@pytest.mark.parametrize("dtype,tol", [("float32", 2e-5), ("bfloat16", 2e-2)])
@pytest.mark.parametrize("M,K,N", [(1, 32, 48), (3, 64, 40), (16, 128, 96),
                                   (5, 96, 256)])
def test_kernel_and_fallback_match_fakequant(M, K, N, dtype, tol):
    """Pallas (interpret) and the XLA fallback against the fake-quant
    reference: odd M (sublane padding), non-pow2 N/K (halve-until-divides
    tiling), fp32 and bf16 activations."""
    rng = np.random.default_rng(1)
    dt = jnp.dtype(dtype)
    x = jnp.asarray(rng.normal(size=(M, K)).astype(np.float32)).astype(dt)
    w = jnp.asarray(rng.normal(size=(K, N)).astype(np.float32))
    qw = qm.quantize_weight(w)
    ref = np.asarray(x.astype(jnp.float32)
                     @ qm.dequantize_weight(qw["q"], qw["s"]), np.float32)
    out_p = qm.quant_matmul(x, qw["q"], qw["s"], interpret=True)
    out_x = qm.quant_matmul(x, qw["q"], qw["s"], impl="xla")
    # the output dtype follows x (the dense path's same-dtype promotion)
    assert out_p.dtype == dt and out_x.dtype == dt
    got_p = np.asarray(out_p, np.float32)
    got_x = np.asarray(out_x, np.float32)
    np.testing.assert_allclose(got_p, ref, rtol=tol, atol=tol)
    np.testing.assert_allclose(got_x, ref, rtol=tol, atol=tol)
    # leading batch dims flatten through
    x3 = x.reshape(1, M, K)
    got3 = np.asarray(qm.quant_matmul(x3, qw["q"], qw["s"], impl="xla"),
                      np.float32)
    np.testing.assert_array_equal(got3[0], got_x)


def test_small_tile_fallback_blocks():
    """Tiny non-dividing dims degrade tile sizes instead of crashing —
    the tiny CPU test models' shapes."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 24)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(24, 24)).astype(np.float32))
    qw = qm.quantize_weight(w)
    ref = np.asarray(x @ qm.dequantize_weight(qw["q"], qw["s"]))
    got = np.asarray(qm.quant_matmul(x, qw["q"], qw["s"], interpret=True))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_quant_matmul_validates():
    x = jnp.zeros((2, 8))
    w = jnp.zeros((8, 8))  # NOT int8
    s = jnp.zeros((8,))
    with pytest.raises(ValueError, match="int8"):
        qm.quant_matmul(x, w, s)
    with pytest.raises(ValueError, match="impl"):
        qm.quant_matmul(x, w.astype(jnp.int8), s, impl="dense")


def test_no_dequantized_weight_materialization(monkeypatch):
    """Both impls must consume int8 bytes + scales directly — routing
    through ``dequantize_weight`` (the tests-only whole-tensor fp32
    materialization) raises. The test_decode_kernel.py discipline."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 64)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(64, 48)).astype(np.float32))
    qw = qm.quantize_weight(w)
    ref = np.asarray(x @ qm.dequantize_weight(qw["q"], qw["s"]))

    def boom(*a, **kw):
        raise AssertionError("quant matmul materialized a dequantized copy")

    monkeypatch.setattr(qm, "dequantize_weight", boom)
    for kw in (dict(interpret=True), dict(impl="xla")):
        got = np.asarray(qm.quant_matmul(x, qw["q"], qw["s"], **kw))
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------------- #
# tree helpers + pspecs
# --------------------------------------------------------------------------- #


def _params(cfg):
    return jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(0))


def test_quantize_params_tree_and_bytes(tiny_model_kwargs):
    """Only the seven projections + lm_head quantize; embeddings/norms
    stay full precision; the pspec tree mirrors the quantized tree's
    structure; int8 bytes come in at <= 55% of the bf16 tree's."""
    cfg = make_config(tiny_model_kwargs, dtype="bfloat16")
    params = _params(cfg)
    qp = llama.quantize_params(params)
    for k in llama.QUANT_WEIGHT_LEAVES:
        leaf = qp["layers"][k]
        assert qm.is_quant_weight(leaf)
        assert leaf["q"].dtype == jnp.int8
        assert leaf["s"].dtype == jnp.float32
        assert leaf["s"].shape == leaf["q"].shape[:-2] + leaf["q"].shape[-1:]
    assert qm.is_quant_weight(qp["lm_head"])
    for k in ("embed", "final_norm"):
        assert qp[k].dtype == params[k].dtype
    for k in ("attn_norm", "mlp_norm"):
        assert not qm.is_quant_weight(qp["layers"][k])
    # the quantized pspec tree has the quantized params' structure
    specs = llama.param_pspecs(cfg.model, weight_dtype="int8")
    assert (jax.tree.structure(qp)
            == jax.tree.structure(specs,
                                  is_leaf=lambda x: not isinstance(x, dict)))
    # the quantized-leaf bytes come in at <= 55% of their bf16 form (the
    # tiny model's full-tree ratio is dominated by the deliberately
    # full-precision embedding; at the 7B geometry — checked below via
    # bench_7b's arithmetic — the whole tree lands at ~51%)
    def mat_bytes(tree):
        leaves = [tree["layers"][k] for k in llama.QUANT_WEIGHT_LEAVES]
        leaves.append(tree["lm_head"])
        return sum(a.size * a.dtype.itemsize
                   for a in jax.tree.leaves(leaves))

    ratio = mat_bytes(qp) / mat_bytes(params)
    assert ratio <= 0.55, ratio
    assert llama.param_bytes(qp) < llama.param_bytes(params)

    from bench_7b import LLAMA2_7B_GEOM, weight_bytes

    geom = dict(LLAMA2_7B_GEOM, num_hidden_layers=32)
    assert weight_bytes(geom, "int8") <= 0.55 * weight_bytes(geom, "bf16")
    # fake-quant round trip restores the dense structure and dtype
    fq = llama.dequantize_params(qp, jnp.bfloat16)
    assert jax.tree.structure(fq) == jax.tree.structure(params)
    assert fq["layers"]["wq"].dtype == jnp.bfloat16


def test_fsdp_rejects_quantized_pspecs(tiny_model_kwargs):
    cfg = make_config(tiny_model_kwargs)
    with pytest.raises(ValueError, match="fsdp"):
        llama.param_pspecs(cfg.model, fsdp=True, weight_dtype="int8")


def test_config_and_engine_validate_weight_dtype(tiny_model_kwargs):
    """Bad weight_dtype strings fail loudly at config load and engine
    build, naming the fix."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    raw = cfg.to_dict()
    raw["inference"]["weight_dtype"] = "fp8"
    with pytest.raises(ValueError, match="weight_dtype"):
        Config.from_dict(raw)
    with pytest.raises(ValueError, match="weight_dtype"):
        InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                        weight_dtype="fp8")


# --------------------------------------------------------------------------- #
# engine-level equivalence: int8 vs the fake-quant bf16 reference
# --------------------------------------------------------------------------- #


def _engines(tiny_model_kwargs, tp=1, **kw):
    """(int8 engine + quantized params, dense engine + fake-quant params)
    — the pair every equivalence test compares. Both trees carry the SAME
    quantization error; only the matmul plumbing differs."""
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    eng_q = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                            weight_dtype="int8", **kw)
    eng_d = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                            weight_dtype="bf16", **kw)
    params = _params(cfg)
    qp = llama.quantize_params(params)
    fq = llama.dequantize_params(qp, jnp.dtype(cfg.model.dtype))
    return ((eng_q, eng_q.shard_params(qp)),
            (eng_d, eng_d.shard_params(fq)))


@pytest.mark.parametrize("attend_impl", ["dense", "flash"])
@pytest.mark.parametrize("kv_layout", ["contiguous", "paged"])
def test_decode_block_matches_fakequant(tiny_model_kwargs, attend_impl,
                                        kv_layout, monkeypatch):
    """The blocked-decode dispatch across attend kernels and KV layouts —
    with ``dequantize_weight`` armed to raise, so the whole int8 decode
    provably never materializes a weight."""
    outs = []
    for i, (eng, params) in enumerate(_engines(
            tiny_model_kwargs, attend_impl=attend_impl,
            kv_layout=kv_layout, decode_block_len=4)):
        if i == 0:  # the int8 engine runs under the no-materialize trap
            monkeypatch.setattr(qm, "dequantize_weight", _boom)
        else:
            monkeypatch.undo()
        cache = eng.init_cache()
        kv, logits = eng.prefill(params, list(range(1, 9)))
        cache = eng.insert(cache, kv, 0, 8)
        toks = np.array([int(np.argmax(np.asarray(logits)[0])), 0], np.int32)
        keys = jnp.stack([jax.random.PRNGKey(7)] * 4)
        cache, blk, counts = eng.decode_block(
            params, cache, toks, keys, np.full(2, -1, np.int32),
            np.array([8, 0], np.int32), np.zeros(2, np.float32),
            np.zeros(2, np.int32), np.ones(2, np.float32))
        outs.append((int(toks[0]), np.asarray(blk), np.asarray(counts)))
    assert outs[0][0] == outs[1][0]  # prefill argmax
    np.testing.assert_array_equal(outs[0][1], outs[1][1])
    np.testing.assert_array_equal(outs[0][2], outs[1][2])


def _boom(*a, **kw):
    raise AssertionError("serving path materialized a dequantized weight")


@pytest.mark.parametrize("attend_impl", ["dense", "flash"])
def test_verify_matches_fakequant(tiny_model_kwargs, attend_impl):
    """The speculative verify dispatch (S>1, B>1): same emitted tokens,
    counts, accepted-draft counts, and length pointers."""
    outs = []
    for eng, params in _engines(tiny_model_kwargs, spec_len=3,
                                attend_impl=attend_impl):
        cache = eng.init_cache()
        for slot in (0, 1):
            kv, _ = eng.prefill(params, list(range(1 + slot, 9 + slot)))
            cache = eng.insert(cache, kv, slot, 8)
        tokens = np.array([[3, 5, 7, 9], [4, 6, 8, 10]], np.int32)
        cache, emitted, counts, accepted = eng.verify(
            params, cache, tokens, jax.random.PRNGKey(3),
            np.full(2, -1, np.int32), np.full(2, 8, np.int32),
            np.zeros(2, np.float32), np.zeros(2, np.int32),
            np.ones(2, np.float32))
        outs.append(tuple(np.asarray(x) for x in
                          (emitted, counts, accepted, cache["lengths"])))
    for a, b in zip(*outs):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("attend_impl", ["dense", "flash"])
def test_chunked_prefill_matches_fakequant(tiny_model_kwargs, attend_impl):
    """The chunked-prefill dispatch (B=1, S=chunk, ragged final chunk):
    final logits agree across the int8 and fake-quant engines AND with
    the int8 one-shot prefill."""
    prompt = [(5 * i + 2) % 199 + 1 for i in range(20)]
    logits = []
    for eng, params in _engines(tiny_model_kwargs, prefill_chunk=8,
                                attend_impl=attend_impl):
        cache, last = eng.prefill_chunked(params, eng.init_cache(),
                                          prompt, slot=1)
        assert int(np.asarray(cache["lengths"])[1]) == len(prompt)
        logits.append(np.asarray(last)[0])
        oneshot = np.asarray(eng.prefill(params, prompt)[1])[0]
        np.testing.assert_allclose(last[0], oneshot, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(logits[0], logits[1], rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("tp", [1, 2])
def test_batcher_generations_match_fakequant(tiny_model_kwargs, tp):
    """Greedy generations pinned through the full ContinuousBatcher on
    tp=1 AND tp=2 — the sharded path, where int8 values and their
    per-channel scales split over 'tp' together. Identical tokens and
    finish reasons for every request."""
    results = []
    for eng, params in _engines(tiny_model_kwargs, tp=tp):
        reqs = [Request(uid=f"r{i}", prompt=list(range(1 + i, 7 + i)),
                        max_new_tokens=10) for i in range(3)]
        results.append(ContinuousBatcher(eng, params, seed=0).run(reqs))
    for uid in results[0]:
        assert results[0][uid].tokens == results[1][uid].tokens, uid
        assert (results[0][uid].finish_reason
                == results[1][uid].finish_reason)


def test_tp2_shards_scales_with_channels(tiny_model_kwargs):
    """A tp=2 engine's placed quantized tree: each wq shard carries the
    GLOBAL quantization's values and scales for its own channel slice —
    per-channel quantization commutes with the column split."""
    cfg = make_config(tiny_model_kwargs, tp=2, seq=MAX_LEN)
    eng = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                          weight_dtype="int8")
    qp = llama.quantize_params(_params(cfg))
    placed = eng.shard_params(qp)
    wq = placed["layers"]["wq"]
    # the scale leaf is sharded over tp on its channel axis
    shard = wq["s"].sharding.shard_shape(wq["s"].shape)
    assert shard[-1] == wq["s"].shape[-1] // 2
    np.testing.assert_array_equal(np.asarray(wq["q"]),
                                  np.asarray(qp["layers"]["wq"]["q"]))
    np.testing.assert_array_equal(np.asarray(wq["s"]),
                                  np.asarray(qp["layers"]["wq"]["s"]))


def test_int8_generations_allclose_bf16_logits(tiny_model_kwargs):
    """Against the TRUE full-precision weights (not the fake-quant
    reference) the contract is allclose logits within the absmax grid:
    prefill logits of the int8 engine sit near the dense engine's, with
    the error bounded by the quantization step — the same tolerance
    discipline as the checkpoint roundtrip test."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    params = _params(cfg)
    eng_d = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
    dense = np.asarray(eng_d.prefill(eng_d.shard_params(params),
                                     list(range(1, 9)))[1])
    eng_q = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                            weight_dtype="int8")
    quant = np.asarray(eng_q.prefill(
        eng_q.shard_params(llama.quantize_params(params)),
        list(range(1, 9)))[1])
    # int8 carries ~0.4% relative error per matmul; across 4 tiny layers
    # the logits stay within a loose-but-meaningful band
    np.testing.assert_allclose(quant, dense, rtol=0.1, atol=0.1)
    assert int(np.argmax(quant[0])) == int(np.argmax(dense[0]))
