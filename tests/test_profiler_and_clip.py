"""Profiler trace window (SURVEY §5.1) and gradient clipping coverage."""

import glob
import os

import numpy as np

from conftest import make_config
from picotron_tpu.train import train


def test_profiler_window_writes_trace(tiny_model_kwargs, tmp_path):
    """logging.profile_start/stop captures a jax.profiler trace exactly once
    into profile_dir (the reference has no profiler; SURVEY §5.1 calls for
    this as the TPU-idiomatic addition)."""
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.training.total_train_steps = 4
    cfg.logging.profile_start = 2
    cfg.logging.profile_stop = 3
    cfg.logging.profile_dir = str(tmp_path / "profiles")
    step, tokens, loss = train(cfg)
    assert step == 4 and np.isfinite(loss)
    traces = glob.glob(os.path.join(cfg.logging.profile_dir, "**", "*.trace*"),
                       recursive=True)
    assert traces, f"no trace files under {cfg.logging.profile_dir}"


def test_grad_clip_changes_step_but_still_learns(tiny_model_kwargs):
    """training.grad_clip wires optax.clip_by_global_norm ahead of adamw
    (the reference passes only lr; clipping is config surface here). A tiny
    clip bound must alter the trajectory while training still learns."""
    from test_parallel import run_losses

    base = run_losses(make_config(tiny_model_kwargs, seq=32, mbs=8), steps=6)
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=8)
    cfg.training.grad_clip = 0.05
    clipped = run_losses(cfg, steps=6)
    assert not np.allclose(clipped, base, atol=1e-4), (
        "grad_clip=0.05 did not change the trajectory")
    assert clipped[-1] < clipped[0], f"clipped run did not learn: {clipped}"
