"""Profiler trace window (SURVEY §5.1) and gradient clipping coverage."""

import pytest

import glob
import os

import numpy as np

from conftest import make_config
from picotron_tpu.train import train

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow


def test_profiler_window_writes_trace(tiny_model_kwargs, tmp_path):
    """logging.profile_start/stop captures a jax.profiler trace exactly once
    into profile_dir (the reference has no profiler; SURVEY §5.1 calls for
    this as the TPU-idiomatic addition)."""
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.training.total_train_steps = 4
    cfg.logging.profile_start = 2
    cfg.logging.profile_stop = 3
    cfg.logging.profile_dir = str(tmp_path / "profiles")
    step, tokens, loss = train(cfg)
    assert step == 4 and np.isfinite(loss)
    traces = glob.glob(os.path.join(cfg.logging.profile_dir, "**", "*.trace*"),
                       recursive=True)
    assert traces, f"no trace files under {cfg.logging.profile_dir}"


def test_grad_clip_changes_step_but_still_learns(tiny_model_kwargs):
    """training.grad_clip applies a global-norm clip ahead of adamw
    (the reference passes only lr; clipping is config surface here). A tiny
    clip bound must alter the trajectory while training still learns."""
    from test_parallel import run_losses

    base = run_losses(make_config(tiny_model_kwargs, seq=32, mbs=8), steps=6)
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=8)
    cfg.training.grad_clip = 0.05
    clipped = run_losses(cfg, steps=6)
    assert not np.allclose(clipped, base, atol=1e-4), (
        "grad_clip=0.05 did not change the trajectory")
    assert clipped[-1] < clipped[0], f"clipped run did not learn: {clipped}"


def test_grad_clip_topology_equivalence(tiny_model_kwargs):
    """The clip norm is the TRUE global norm on any topology: each leaf's
    squared sum is psum'd over exactly the axes sharding it
    (clip_by_global_norm_sharded), so sharded runs clip identically to the
    single-device run — a per-device local norm would desync tp-replicated
    params (norm weights) and diverge from this oracle."""
    from test_parallel import run_losses

    def clipped(**kw):
        cfg = make_config(tiny_model_kwargs, seq=32, **kw)
        cfg.training.grad_clip = 0.05
        return run_losses(cfg, steps=5)

    base = clipped(mbs=8)
    for kw in (dict(tp=4, mbs=8), dict(pp=2, acc=2, mbs=4, engine="1f1b"),
               dict(tp=2, cp=2, mbs=8, sp=True)):
        got = clipped(**kw)
        np.testing.assert_allclose(got, base, rtol=2e-5, atol=2e-5,
                                   err_msg=str(kw))
