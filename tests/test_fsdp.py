"""FSDP / ZeRO-3 for the decoder-layer stack (beyond the reference —
SURVEY.md §2.3 marks ZeRO out of its scope).

Layer params rest dp-sharded on their H-sized axis
(models/llama.py:FSDP_GATHER_AXIS), are all-gathered just in time inside
decoder_layer, and the gather's AD transpose reduce-scatters (dp-sums)
the grads back onto the shards; train_step finishes the mean with /dp +
a cp pmean. The oracle is the usual one: the fp32 loss trajectory must
match single-device training exactly.
"""

import jax
import numpy as np
import pytest

from picotron_tpu import train_step as ts
from picotron_tpu.config import Config
from picotron_tpu.topology import topology_from_config


def test_fsdp_zero1_mutually_exclusive(cfg_factory):
    with pytest.raises(ValueError, match="mutually exclusive"):
        cfg_factory(dp=2, fsdp=True, zero1=True)


def test_fsdp_requires_divisible_hidden(tiny_model_kwargs):
    from conftest import make_config

    with pytest.raises(ValueError, match="divisible"):
        make_config(dict(tiny_model_kwargs, hidden_size=96,
                         intermediate_size=192), dp=5, fsdp=True)


def test_fsdp_params_rest_sharded(cfg_factory):
    """At rest every layer param's addressable shard is 1/dp on its
    H-sized axis; embed/head/final_norm stay replicated."""
    from picotron_tpu.models.llama import FSDP_GATHER_AXIS

    cfg = cfg_factory(dp=2, fsdp=True)
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    for name, ax in FSDP_GATHER_AXIS.items():
        w = params["layers"][name]
        shard = w.addressable_shards[0].data.shape
        # +1: the leading stacked-layer axis
        assert shard[ax + 1] == w.shape[ax + 1] // 2, (name, w.shape, shard)
    emb = params["embed"]
    assert emb.addressable_shards[0].data.shape[1] == emb.shape[1]
    # optimizer moments mirror the param sharding (the FSDP state win):
    # every opt-state leaf with a layer-param shape holds 1/dp per shard
    # (different layer params share shapes — wq vs wo — so check volume)
    wq = params["layers"]["wq"]
    moments = [x for x in jax.tree.leaves(opt_state)
               if getattr(x, "shape", None) == wq.shape]
    assert moments, "no adam moments matching wq's shape found"
    for m in moments:
        assert (np.prod(m.addressable_shards[0].data.shape)
                == np.prod(m.shape) // 2), m.sharding


# ---------------------------------------------------------------- slow matrix

pytestmark_matrix = pytest.mark.slow

FSDP_TOPOLOGIES = [
    dict(dp=2, fsdp=True),
    dict(dp=2, tp=2, sp=True, cp=2, fsdp=True),
    dict(dp=2, pp=2, acc=2, engine="1f1b", fsdp=True),
    dict(dp=2, pp=2, acc=2, engine="afab", fsdp=True),
    dict(dp=2, pp=2, acc=2, engine="1f1b", interleave=2, fsdp=True),
    dict(dp=2, cp=2, zigzag=True, fsdp=True),
]


@pytest.mark.slow
@pytest.mark.parametrize("top", FSDP_TOPOLOGIES,
                         ids=[str(t) for t in FSDP_TOPOLOGIES])
def test_fsdp_matches_single_device(cfg_factory, top):
    from test_parallel import GLOBAL_BATCH, run_losses

    ref = run_losses(cfg_factory(mbs=GLOBAL_BATCH))
    mbs = GLOBAL_BATCH // (top.get("dp", 1) * top.get("acc", 1))
    got = run_losses(cfg_factory(mbs=mbs, **top))
    np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-5)


@pytest.mark.slow
def test_fsdp_uneven_pp_matches_single_device(tiny_model_kwargs):
    """FSDP composes with an UNEVEN pipeline split (5 layers over pp=2 ->
    3+2 with a masked pad row): the pad row's gathered params see zero
    cotangents, so the reduce-scattered grads stay exact."""
    from conftest import make_config
    from test_parallel import GLOBAL_BATCH, run_losses

    model = dict(tiny_model_kwargs, num_hidden_layers=5)
    ref = run_losses(make_config(model, mbs=GLOBAL_BATCH))
    got = run_losses(make_config(model, dp=2, pp=2, acc=2, mbs=2,
                                 engine="1f1b", fsdp=True))
    np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-5)


@pytest.mark.slow
def test_fsdp_grad_clip_matches_single_device(cfg_factory):
    """The pspec-aware global-norm clip psums the dp-sharded layer grads'
    sumsq over dp, reproducing single-device clipping exactly."""
    from test_parallel import GLOBAL_BATCH, run_losses

    ref = run_losses(cfg_factory(mbs=GLOBAL_BATCH, grad_clip=0.5))
    got = run_losses(cfg_factory(dp=2, mbs=GLOBAL_BATCH // 2, fsdp=True,
                                 grad_clip=0.5))
    np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-5)


@pytest.mark.slow
def test_fsdp_checkpoint_roundtrip_to_plain_dp(tmp_path, cfg_factory):
    """Checkpoints save GLOBAL arrays, so an fsdp-dp2 save restores into a
    plain dp2 run (and continues with the identical trajectory)."""
    from picotron_tpu import checkpoint as ckpt_mod
    from picotron_tpu.data import MicroBatchDataLoader

    def train(cfg, steps, params=None, opt_state=None, skip=0):
        topo = topology_from_config(cfg)
        if params is None:
            params, opt_state = ts.init_state(cfg, topo)
        step = ts.build_train_step(cfg, topo)
        loader = MicroBatchDataLoader(cfg)
        for _ in range(skip):
            next(loader)
        losses = []
        for _ in range(steps):
            tokens, targets = ts.shard_batch(next(loader), topo)
            params, opt_state, loss = step(params, opt_state, tokens,
                                           targets)
            losses.append(float(loss))
        return params, opt_state, losses

    fs = cfg_factory(dp=2, mbs=2, fsdp=True)
    p, o, l1 = train(fs, steps=3)
    mgr = ckpt_mod.CheckpointManager(str(tmp_path / "ck"))
    mgr.save(3, p, o, trained_tokens=0)
    mgr.close()

    plain = cfg_factory(dp=2, mbs=2)
    topo2 = topology_from_config(plain)
    p_like, o_like = ts.init_state(plain, topo2)
    mgr2 = ckpt_mod.CheckpointManager(str(tmp_path / "ck"))
    p2, o2, step_no, _ = mgr2.load(p_like, o_like)
    mgr2.close()
    assert step_no == 3
    _, _, l_resumed = train(plain, steps=2, params=p2, opt_state=o2, skip=3)

    # uninterrupted fsdp run over the same 5 steps is the oracle
    _, _, l_full = train(cfg_factory(dp=2, mbs=2, fsdp=True), steps=5)
    np.testing.assert_allclose(l1 + l_resumed, l_full, rtol=3e-5, atol=3e-5)
