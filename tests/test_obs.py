"""Unified telemetry suite (ISSUE 10; docs/OBSERVABILITY.md).

The observability plane's contracts:

- registry correctness under concurrency (N threads hammering counters
  while snapshotters read — totals exact, no lock held across user code);
- histogram bucket-edge semantics (le-inclusive, cumulative rendering,
  +Inf == count) and exact percentiles over the bounded window;
- CounterDict: plain-dict surface, every write mirrored to the registry;
- span tracer: parent links, ring overflow, Chrome-trace JSON validity,
  instant events; trace_dump's validation/chain queries;
- end-to-end: the batcher's /statz numbers == the registry's /metrics
  numbers; one serve request's COMPLETE parented chain in /tracez; a
  timed /profilez capture; train's per-step metrics JSONL ingested by
  extract_metrics without the regex path; obs.enabled: false no-ops.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax

from conftest import make_config
from picotron_tpu import obs as obs_mod
from picotron_tpu.inference import ContinuousBatcher, InferenceEngine, Request
from picotron_tpu.models import llama
from picotron_tpu.obs import (
    GLOBAL_REGISTRY,
    GLOBAL_TRACER,
    MetricsRegistry,
    NullTracer,
    Obs,
    SpanTracer,
)
from picotron_tpu.obs.metrics import (
    CounterDict,
    NullRegistry,
    parse_prometheus,
)
from picotron_tpu.tools import trace_dump

MAX_LEN = 64

_TINY = dict(
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    hidden_size=32, intermediate_size=64, vocab_size=128,
    max_position_embeddings=MAX_LEN, rope_theta=10000.0, dtype="float32",
    attention_impl="sdpa")


def _engine(slots=2, **inf):
    cfg = make_config(dict(_TINY), seq=32)
    for k, v in inf.items():
        setattr(cfg.inference, k, v)
    engine = InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN)
    params = engine.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    return cfg, engine, params


# --------------------------------------------------------------------------- #
# registry
# --------------------------------------------------------------------------- #


def test_counter_concurrency_exact():
    """N threads x M increments with concurrent snapshot/prometheus
    readers: the final value is exactly N*M (no lost updates) and no
    reader ever crashes or deadlocks."""
    reg = MetricsRegistry()
    c = reg.counter("hammer_total", "concurrency test")
    n_threads, m = 8, 500
    stop = threading.Event()
    reader_errs = []

    def reader():
        while not stop.is_set():
            try:
                reg.snapshot()
                reg.prometheus()
            except Exception as e:  # noqa: BLE001 - the assertion payload
                reader_errs.append(e)
                return

    def writer():
        for _ in range(m):
            c.inc()

    readers = [threading.Thread(target=reader) for _ in range(2)]
    writers = [threading.Thread(target=writer) for _ in range(n_threads)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join(30)
    stop.set()
    for t in readers:
        t.join(30)
    assert not reader_errs
    assert c.value == n_threads * m
    assert parse_prometheus(reg.prometheus())["hammer_total"] == n_threads * m


def test_histogram_concurrent_observe_count_exact():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds")
    n_threads, m = 6, 400

    def writer():
        for i in range(m):
            h.observe(1e-4 * (i + 1))

    threads = [threading.Thread(target=writer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    r = h.read()
    assert r["count"] == n_threads * m
    assert sum(r["counts"]) + r["inf"] == r["count"]


def test_histogram_bucket_edges():
    """Prometheus 'le' is INCLUSIVE: a value exactly on a bound lands in
    that bucket; above the last bound lands in +Inf; the cumulative
    rendering ends at _count."""
    reg = MetricsRegistry()
    h = reg.histogram("edges_seconds", buckets=(0.001, 0.01, 0.1))
    for v in (0.001, 0.0005, 0.01, 0.05, 0.1, 99.0):
        h.observe(v)
    r = h.read()
    assert r["counts"] == [2, 1, 2]  # per-bucket, le-inclusive
    assert r["inf"] == 1
    assert r["count"] == 6
    assert r["sum"] == pytest.approx(0.001 + 0.0005 + 0.01 + 0.05 + 0.1 + 99)
    prom = parse_prometheus(reg.prometheus())
    assert prom['edges_seconds_bucket{le="0.001"}'] == 2
    assert prom['edges_seconds_bucket{le="0.01"}'] == 3  # cumulative
    assert prom['edges_seconds_bucket{le="0.1"}'] == 5
    assert prom['edges_seconds_bucket{le="+Inf"}'] == 6
    assert prom["edges_seconds_count"] == 6


def test_histogram_percentiles_window():
    """Exact percentiles over the retained window; the oldest samples
    drop past sample_window (the /statz recent-window semantics)."""
    reg = MetricsRegistry(sample_window=100)
    h = reg.histogram("w_seconds")
    for v in range(1000):  # only the last 100 (900..999) retained
        h.observe(float(v))
    p = h.percentiles()
    assert p["n"] == 100
    assert p["p50"] == pytest.approx(np.percentile(np.arange(900, 1000), 50))
    assert reg.histogram("w_seconds") is h  # get-or-create
    assert reg.histogram("empty_seconds").percentiles() is None


def test_histogram_rejects_bad_buckets():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="strictly increasing"):
        reg.histogram("bad", buckets=(0.1, 0.1))
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("bad")  # name taken by a histogram family


def test_counter_dict_semantics_and_mirror():
    """The exact surface the batcher/serve counters rely on: dict
    equality, dict(), += — with every write mirrored into the labeled
    family (including keys born after construction)."""
    reg = MetricsRegistry()
    d = reg.counter_dict("req_total", ("a", "b"), label="state")
    assert d == {"a": 0, "b": 0}
    d["a"] += 1
    d["a"] += 1
    d["b"] += 1
    d["late"] = 3  # unknown key: plain-dict write + lazy child
    assert dict(d) == {"a": 2, "b": 1, "late": 3}
    prom = parse_prometheus(reg.prometheus())
    assert prom['req_total{state="a"}'] == 2
    assert prom['req_total{state="b"}'] == 1
    assert prom['req_total{state="late"}'] == 3


def test_gauge_and_summary():
    reg = MetricsRegistry()
    reg.gauge("depth").set(7)
    reg.counter("n_total").inc(3)
    reg.histogram("h_seconds").observe(0.5)
    s = reg.summary()
    assert s["depth"] == 7 and s["n_total"] == 3
    assert s["h_seconds"]["count"] == 1
    assert s["h_seconds"]["p50"] == pytest.approx(0.5)


def test_null_registry_and_disabled_obs():
    o = Obs(enabled=False)
    assert isinstance(o.registry, NullRegistry)
    assert isinstance(o.tracer, NullTracer)
    o.registry.counter("x").inc()
    o.registry.histogram("y").observe(1.0)
    with o.tracer.span("s"):
        pass
    assert o.registry.prometheus() == "" and o.registry.snapshot() == {}
    assert o.tracer.spans() == []
    d = CounterDict(o.registry, "z", ("k",))
    d["k"] += 1
    assert d == {"k": 1}  # local dict still authoritative


# --------------------------------------------------------------------------- #
# span tracer + trace_dump
# --------------------------------------------------------------------------- #


def test_span_parent_links_and_chrome_validity():
    tr = SpanTracer(ring=64)
    root = tr.begin("request", uid="r1")
    with tr.span("prefill", parent=root, prompt_tokens=3):
        pass
    tr.record("decode", 1.0, 2.0, parent=root, tokens=4)
    tr.instant("comm/all_reduce", axis="tp")
    tr.end(root, finish_reason="length")
    trace = tr.chrome_trace()
    assert trace_dump.validate(trace) == []
    by_name = {e["name"]: e for e in trace["traceEvents"]}
    rid = by_name["request"]["args"]["id"]
    assert by_name["prefill"]["args"]["parent"] == rid
    assert by_name["decode"]["args"]["parent"] == rid
    assert by_name["decode"]["dur"] == pytest.approx(1e6)
    assert by_name["comm/all_reduce"]["ph"] == "i"
    assert by_name["request"]["ph"] == "X"


def test_span_ring_overflow_keeps_latest():
    tr = SpanTracer(ring=4)
    for i in range(10):
        tr.record(f"s{i}", float(i), float(i) + 0.5)
    names = [s.name for s in tr.spans()]
    assert names == ["s6", "s7", "s8", "s9"]
    tr.resize(8)  # grow-only, retained spans survive
    assert [s.name for s in tr.spans()] == names
    tr.resize(2)  # shrink requests are ignored
    assert len(tr.spans()) == 4


def test_scoped_span_records_exception():
    tr = SpanTracer(ring=8)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    (s,) = tr.spans()
    assert s.args["error"] == "RuntimeError"


def test_trace_dump_validate_catches_defects():
    assert trace_dump.validate({}) == ["top-level 'traceEvents' must be "
                                       "a list"]
    bad = {"traceEvents": [
        {"name": "a", "ph": "X", "ts": 1, "pid": 1, "tid": 1},  # no dur
        {"name": "b", "ph": "i", "ts": 1, "pid": 1, "tid": 1,
         "args": {"id": 2, "parent": 99}},  # dangling parent
    ]}
    errs = trace_dump.validate(bad)
    assert any("dur" in e for e in errs)
    # a dangling parent is a WARNING, never a validation error: a live
    # /tracez snapshot has in-flight requests whose root span isn't in
    # the ring yet (it lands at end()), and ring eviction drops old roots
    assert not any("parent" in e for e in errs)
    warns = trace_dump.dangling_parents(bad)
    assert any("parent 99" in w for w in warns)
    assert trace_dump.dangling_parents(
        {"traceEvents": [{"name": "c", "ph": "i", "ts": 0, "pid": 1,
                          "tid": 1, "args": {"id": 5, "parent": 5}}]}) == []


def test_trace_dump_cli_roundtrip(tmp_path):
    tr = SpanTracer(ring=16)
    root = tr.begin("request", uid="u1")
    tr.record("prefill", 0.0, 0.1, parent=root)
    tr.record("decode", 0.1, 0.2, parent=root)
    tr.record("delivery", 0.2, 0.21, parent=root)
    tr.end(root)
    path = tmp_path / "trace.json"
    tr.dump_chrome(str(path))
    assert trace_dump.main([str(path), "--require-request-chain"]) == 0
    assert trace_dump.main([str(path), "--require-request-chain",
                            "u1"]) == 0
    assert trace_dump.main([str(path), "--require-request-chain",
                            "nope"]) == 1
    # an incomplete chain (no delivery) fails the gate
    tr2 = SpanTracer(ring=16)
    r2 = tr2.begin("request", uid="u2")
    tr2.record("prefill", 0.0, 0.1, parent=r2)
    tr2.end(r2)
    p2 = tmp_path / "t2.json"
    tr2.dump_chrome(str(p2))
    assert trace_dump.main([str(p2)]) == 0  # valid, just partial
    assert trace_dump.main([str(p2), "--require-request-chain"]) == 1


def test_trace_dump_lane_chain_audit(tmp_path):
    """The mixed-dispatch lane gate: ``lane`` spans must parent to a
    request root and tile the prompt — chunk numbers 1..n, each chunk
    starting where the previous ended, the last landing at the lane
    prefill span's prompt_tokens. Gaps, bad numbering, or a short final
    chunk fail ``--require-lane-chain``."""
    tr = SpanTracer(ring=32)
    root = tr.begin("request", uid="u1")
    pf = tr.begin("prefill", parent=root, uid="u1", prompt_tokens=20,
                  lane=True)
    tr.record("lane", 0.0, 0.1, parent=root, chunk=1, start=0, end=8,
              slot=0)
    tr.record("lane", 0.1, 0.2, parent=root, chunk=2, start=8, end=16,
              slot=0)
    tr.record("lane", 0.2, 0.3, parent=root, chunk=3, start=16, end=20,
              slot=0)
    tr.end(pf, dispatches=3, lane=True)
    tr.end(root)
    good = tmp_path / "lane.json"
    tr.dump_chrome(str(good))
    la = trace_dump.lane_chain(trace_dump.load(str(good)))
    assert la == {"lanes": 3, "linked": 3, "errors": []}
    assert trace_dump.main([str(good), "--require-lane-chain"]) == 0

    # a gap between chunks (8 -> 12) and a short final chunk both fail
    tr2 = SpanTracer(ring=32)
    r2 = tr2.begin("request", uid="u2")
    pf2 = tr2.begin("prefill", parent=r2, uid="u2", prompt_tokens=20,
                    lane=True)
    tr2.record("lane", 0.0, 0.1, parent=r2, chunk=1, start=0, end=8,
               slot=0)
    tr2.record("lane", 0.1, 0.2, parent=r2, chunk=2, start=12, end=18,
               slot=0)
    tr2.end(pf2, dispatches=2, lane=True)
    tr2.end(r2)
    bad = tmp_path / "lane_bad.json"
    tr2.dump_chrome(str(bad))
    la2 = trace_dump.lane_chain(trace_dump.load(str(bad)))
    assert any("starts at 12" in e for e in la2["errors"])
    assert any("prompt has 20 tokens" in e for e in la2["errors"])
    assert trace_dump.main([str(bad), "--require-lane-chain"]) == 1
    # no lane spans at all: the gate reports the likely cause
    empty = tmp_path / "none.json"
    tr3 = SpanTracer(ring=4)
    r3 = tr3.begin("request", uid="u3")
    tr3.end(r3)
    tr3.dump_chrome(str(empty))
    assert trace_dump.main([str(empty), "--require-lane-chain"]) == 1


# --------------------------------------------------------------------------- #
# engine/batcher integration
# --------------------------------------------------------------------------- #


def test_batcher_stats_agree_with_registry():
    """/statz and /metrics are two renderings of the SAME instruments:
    the counters, token totals, dispatch counts, and percentile payloads
    must agree exactly."""
    GLOBAL_TRACER.clear()
    cfg, engine, params = _engine(slots=2)
    b = ContinuousBatcher(engine, params)
    b.run([Request(f"q{i}", [3 + i, 7 + i], max_new_tokens=4)
           for i in range(3)])
    s = b.stats()
    prom = parse_prometheus(engine.obs.registry.prometheus())
    assert prom['picotron_requests_total{state="completed"}'] == \
        s["completed"] == 3
    assert prom['picotron_requests_total{state="admitted"}'] == 3
    assert prom["picotron_generated_tokens_total"] == \
        s["generated_tokens"] == 12
    assert prom['picotron_dispatch_total{kind="prefill"}'] == \
        s["prefill_dispatches"]
    assert prom["picotron_queue_wait_seconds_count"] == \
        s["queue_wait_s"]["n"] == 3
    assert prom["picotron_ttft_seconds_count"] == s["ttft_s"]["n"] == 3
    assert prom["picotron_queue_depth"] == 0
    assert prom["picotron_active_slots"] == 0
    # dispatch latency histogram counted one entry per decode dispatch
    assert prom['picotron_dispatch_seconds_count{kind="decode"}'] == \
        b.decode_dispatches
    # the span ring holds each request's prefill + >= 1 decode child
    chains = trace_dump.request_chains(GLOBAL_TRACER.chrome_trace())
    assert set(chains) == {"q0", "q1", "q2"}
    for c in chains.values():
        assert c["queue_wait"] and c["prefill"] and c["dispatches"] >= 1


def test_speculative_round_spans_carry_accept_counts():
    GLOBAL_TRACER.clear()
    cfg, engine, params = _engine(slots=2, spec_len=3)
    b = ContinuousBatcher(engine, params)
    b.run([Request("s0", [5, 6, 7], max_new_tokens=6)])
    prom = parse_prometheus(engine.obs.registry.prometheus())
    assert prom["picotron_draft_proposed_total"] == b.draft_proposed > 0
    assert prom["picotron_draft_accepted_total"] == b.draft_accepted
    verifies = [s for s in GLOBAL_TRACER.spans() if s.name == "verify"]
    assert verifies and all("accepted" in s.args and
                            s.args["draft_len"] == 3 for s in verifies)


def test_obs_disabled_batcher_runs_and_records_nothing():
    cfg = make_config(dict(_TINY), seq=32)
    cfg.obs.enabled = False
    engine = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
    params = engine.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    b = ContinuousBatcher(engine, params)
    res = b.run([Request("q", [3, 4, 5], max_new_tokens=4)])
    assert res["q"].finish_reason == "length" and res["q"].span_id is None
    assert b.counters["completed"] == 1  # the dict view still works
    assert engine.obs.registry.prometheus() == ""
    s = b.stats()
    assert s["queue_wait_s"] is None and s["ttft_s"] is None


def test_obs_disabled_output_identical():
    """The acceptance bit: obs off produces byte-identical generations to
    obs on (the instruments never touch the PRNG chain or the dispatch
    path)."""
    reqs = [Request(f"q{i}", [3 + i, 9 + i], max_new_tokens=6,
                    temperature=0.8) for i in range(3)]
    _, e_on, p_on = _engine(slots=2)
    on = ContinuousBatcher(e_on, p_on, seed=11).run(
        [Request(**vars(r)) for r in reqs])
    cfg = make_config(dict(_TINY), seq=32)
    cfg.obs.enabled = False
    e_off = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN)
    p_off = e_off.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    off = ContinuousBatcher(e_off, p_off, seed=11).run(
        [Request(**vars(r)) for r in reqs])
    for uid in on:
        assert on[uid].tokens == off[uid].tokens
        assert on[uid].finish_reason == off[uid].finish_reason


# --------------------------------------------------------------------------- #
# serve integration: /metrics, /tracez, /profilez
# --------------------------------------------------------------------------- #


def _server(slots=2, **front_kw):
    from picotron_tpu.tools import serve

    cfg, engine, params = _engine(slots=slots)
    front_kw.setdefault("log", lambda *a, **k: None)
    srv = serve.Server(engine, params, port=0, **front_kw)
    srv.start()
    return cfg, srv


def test_serve_metrics_tracez_profilez(tmp_path):
    from picotron_tpu.tools import serve

    GLOBAL_TRACER.clear()
    cfg, srv = _server()
    try:
        port = srv.port
        st, body = serve._post(port, {"prompt": [1, 2, 3],
                                      "max_new_tokens": 5, "uid": "m1"})
        assert st == 200
        st, stats = serve._get(port, "/statz")
        mst, mtext = serve._get_text(port, "/metrics")
        assert mst == 200
        prom = parse_prometheus(mtext)
        assert prom['picotron_requests_total{state="completed"}'] == \
            stats["completed"]
        assert prom['picotron_rejections_total{reason="queue_full"}'] == 0
        # the model-memory gauge (ISSUE 13): /statz and /metrics agree on
        # resident weight bytes — what the router's scrape reads to see
        # per-replica model memory (int8 replicas report ~half bf16)
        assert stats["weight_bytes"] == srv.front.weight_bytes > 0
        assert stats["weight_dtype"] == "bf16"
        assert prom["picotron_weight_bytes"] == stats["weight_bytes"]
        # /tracez: the request's chain is COMPLETE (queue wait ->
        # prefill -> >= 1 dispatch -> delivery), all parented
        tst, trace = serve._get(port, "/tracez")
        assert tst == 200 and trace_dump.validate(trace) == []
        chains = trace_dump.request_chains(trace)
        assert chains["m1"]["complete"], chains
        # /profilez: one timed capture lands real files; a second start
        # while running is 409
        prof = tmp_path / "prof"
        pst, pbody = serve._profilez_post(
            port, {"seconds": 0.8, "dir": str(prof)})
        assert pst == 200 and pbody["ok"]
        pst2, pbody2 = serve._profilez_post(
            port, {"seconds": 0.8, "dir": str(prof)})
        assert pst2 == 409 and "already running" in pbody2["error"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and srv.front.profiler.running:
            time.sleep(0.05)
        assert srv.front.profiler.captures == 1
        assert prof.is_dir() and list(prof.iterdir())
        pst3, pbody3 = serve._profilez_post(port, {"seconds": -1})
        assert pst3 == 400 and "seconds" in pbody3["error"]
    finally:
        srv.drain_and_join(timeout=60)


# --------------------------------------------------------------------------- #
# train integration: metrics JSONL + trace dump
# --------------------------------------------------------------------------- #


def _train_cfg(tmp_path, **obs_kw):
    cfg = make_config(dict(_TINY), seq=32, total_train_steps=4)
    for k, v in obs_kw.items():
        setattr(cfg.obs, k, v)
    return cfg


def test_train_writes_metrics_jsonl_and_trace(tmp_path):
    from picotron_tpu.tools import extract_metrics as em
    from picotron_tpu.train import train

    run = tmp_path / "run_dp1_tp1_mbs2_sl32"
    run.mkdir()
    cfg = _train_cfg(tmp_path,
                     metrics_jsonl=str(run / "metrics.jsonl"),
                     trace_path=str(run / "trace.json"))
    step, tokens, loss = train(cfg)
    assert step == 4
    rows = em.parse_jsonl_file(str(run / "metrics.jsonl"))
    assert [r["step"] for r in rows] == [1, 2, 3, 4]
    assert all(np.isfinite(r["loss"]) for r in rows)
    # the terminal summary row carries the registry snapshot and is NOT
    # a step row
    last = [json.loads(l) for l in
            open(run / "metrics.jsonl") if l.strip()][-1]
    assert last.get("event") == "summary"
    assert "picotron_train_dispatch_seconds" in last["metrics"]
    # extract_metrics ingests the run WITHOUT any log present (and with
    # a decoy log whose regex rows would disagree, the JSONL wins)
    (run / "log.out").write_text(
        "Step: 9 | Loss: 1.0 | Global batch size: 1 | "
        "Tokens/s: 1.00K | Tokens/s/chip: 1.00K | Tokens: 1\n")
    out = em.extract(str(tmp_path))
    assert len(out) == 1
    assert out[0]["num_steps"] == 1  # 4 steps - 3 warmup
    assert out[0]["final_loss"] == pytest.approx(rows[-1]["loss"])
    # the dumped trace is valid Chrome-trace JSON with train spans
    trace = trace_dump.load(str(run / "trace.json"))
    assert trace_dump.validate(trace) == []
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"train/dispatch", "data", "dispatch", "host_sync"} <= names


def test_train_metrics_jsonl_env_override(tmp_path, monkeypatch):
    from picotron_tpu.train import train

    env_path = tmp_path / "env.jsonl"
    monkeypatch.setenv("PICOTRON_METRICS_JSONL", str(env_path))
    cfg = _train_cfg(tmp_path, metrics_jsonl=str(tmp_path / "cfg.jsonl"))
    train(cfg, max_steps_override=2)
    assert env_path.exists()  # the supervisor's export wins
    assert not (tmp_path / "cfg.jsonl").exists()


def test_train_obs_disabled_writes_nothing(tmp_path):
    from picotron_tpu.train import train

    cfg = _train_cfg(tmp_path, enabled=False,
                     metrics_jsonl=str(tmp_path / "m.jsonl"),
                     trace_path=str(tmp_path / "t.json"))
    step, _, loss = train(cfg, max_steps_override=2)
    assert step == 2 and np.isfinite(loss)
    assert not (tmp_path / "m.jsonl").exists()
    assert not (tmp_path / "t.json").exists()


# --------------------------------------------------------------------------- #
# resilience + comm_trace feeds
# --------------------------------------------------------------------------- #


def test_retry_counts_into_global_registry():
    from picotron_tpu.resilience.retry import retry

    before = GLOBAL_REGISTRY.counter(
        "picotron_retries_total", desc="obs-test").value
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("flake")
        return "ok"

    assert retry(flaky, attempts=3, backoff=0, jitter=0,
                 desc="obs-test", sleep=lambda s: None) == "ok"
    after = GLOBAL_REGISTRY.counter(
        "picotron_retries_total", desc="obs-test").value
    assert after - before == 2  # two failed attempts counted


def test_emergency_save_outcomes_counted():
    from picotron_tpu.resilience.preemption import PreemptionGuard

    def val(outcome):
        return GLOBAL_REGISTRY.counter(
            "picotron_emergency_saves_total", outcome=outcome).value

    g = PreemptionGuard()
    c0, f0 = val("completed"), val("failed")
    assert g.emergency_save(lambda: None) is True
    with pytest.raises(RuntimeError):
        g.emergency_save(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert val("completed") == c0 + 1
    assert val("failed") == f0 + 1


def test_comm_trace_records_instant_events(monkeypatch, capsys):
    from picotron_tpu import comm_trace

    GLOBAL_TRACER.clear()
    monkeypatch.setenv("PICOTRON_VERBOSE", "1")
    x = np.ones((2, 4), np.float32)
    out = comm_trace.log("all_reduce", "tp", x)
    assert out is x  # identity on the value, as before
    (s,) = [s for s in GLOBAL_TRACER.spans()
            if s.name == "comm/all_reduce"]
    assert s.args["axis"] == "tp" and s.args["shape"] == "(2, 4)"
    assert "[comm] all_reduce" in capsys.readouterr().err
    # verbose off: no stderr line AND no span
    GLOBAL_TRACER.clear()
    monkeypatch.setenv("PICOTRON_VERBOSE", "0")
    comm_trace.log("all_gather", "tp", x)
    assert GLOBAL_TRACER.spans() == []
