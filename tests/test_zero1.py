"""ZeRO-1 (dp-sharded optimizer state): identical numerics, 1/dp state memory.

Beyond-parity feature (SURVEY.md §2.3 marks ZeRO out of the reference's
scope). The oracle is the same as every other topology: with the same seed,
config and data, the fp32 loss trajectory must equal the unsharded baseline
exactly — reduce-scatter + chunked update + all-gather is a pure
reassociation of all-reduce + replicated update.
"""

import pytest
import jax
import numpy as np

from picotron_tpu import train_step as ts
from picotron_tpu.topology import topology_from_config
from tests.test_parallel import run_losses

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow


def test_zero1_matches_replicated(cfg_factory):
    base = run_losses(cfg_factory(dp=4, seq=32, mbs=2))
    got = run_losses(cfg_factory(dp=4, seq=32, mbs=2, zero1=True))
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=2e-5)


def test_zero1_with_grad_clip(cfg_factory):
    base = run_losses(cfg_factory(dp=2, seq=32, mbs=4, grad_clip=0.5))
    got = run_losses(cfg_factory(dp=2, seq=32, mbs=4, grad_clip=0.5, zero1=True))
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=2e-5)


def test_zero1_4d_topology(cfg_factory):
    base = run_losses(cfg_factory(seq=32, mbs=8))
    got = run_losses(cfg_factory(dp=2, pp=2, tp=2, acc=2, seq=32, mbs=2,
                                 engine="1f1b", zero1=True))
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=2e-5)


def test_zero1_checkpoint_guard(cfg_factory, tmp_path):
    """A ZeRO-1 checkpoint restores under the same (zero1, dp) and refuses a
    mismatched layout with a real error (the chunk shapes are dp-specific)."""
    import pytest

    from picotron_tpu.checkpoint import CheckpointManager

    cfg = cfg_factory(dp=2, seq=32, mbs=4, zero1=True)
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, params, opt_state, trained_tokens=5, layout=(4, 1),
             zero1=(True, 2))
    p2, o2, step, tokens = mgr.load(params, opt_state, layout=(4, 1),
                                    zero1=(True, 2))
    assert step == 1 and tokens == 5
    for a, b in zip(jax.tree.leaves(o2), jax.tree.leaves(opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="dp-specific"):
        mgr.load(params, opt_state, layout=(4, 1), zero1=(True, 4))
    with pytest.raises(ValueError, match="dp-specific"):
        mgr.load(params, opt_state, layout=(4, 1), zero1=(False, 2))
    mgr.close()


def test_zero1_state_is_dp_sharded(cfg_factory):
    """Each device holds 1/dp of every mu/nu leaf (vs the replicated
    baseline), i.e. per-device optimizer state shrinks by dp."""
    cfg = cfg_factory(dp=4, seq=32, mbs=2, zero1=True)
    topo = topology_from_config(cfg)
    _, opt_state = ts.init_state(cfg, topo)
    leaves = [l for l in jax.tree.leaves(opt_state)
              if hasattr(l, "sharding") and l.ndim == 1]
    assert leaves, "expected chunked optimizer-state leaves"
    for leaf in leaves:
        shard = leaf.sharding.shard_shape(leaf.shape)
        assert shard[0] * 4 == leaf.shape[0], (
            f"leaf {leaf.shape} shard {shard} is not 1/dp")


def test_zero1_param_dtype_accum_bf16(cfg_factory):
    """ZeRO-1 with bf16 (param-dtype) grad accumulators — the projected
    'canonical + bf16 grad accum' 7B configuration (docs/PROJECTION.md):
    the bf16 grads must flow through the reduce-scatter + sharded clip +
    chunked-optimizer path and track the replicated-optimizer trajectory
    to bf16 tolerance."""
    kw = dict(dp=2, pp=2, acc=2, engine="1f1b", seq=32, mbs=1,
              dtype="bfloat16", grad_accum_dtype="param", grad_clip=1.0)
    base = run_losses(cfg_factory(**kw), steps=6)
    got = run_losses(cfg_factory(**kw, zero1=True), steps=6)
    np.testing.assert_allclose(got, base, rtol=0.02, atol=0.02)
    assert min(base[-3:]) < base[0], f"did not trend down: {base}"


def test_zero1_with_zigzag_cp(cfg_factory):
    base = run_losses(cfg_factory(dp=2, cp=2, zigzag=True, seq=32, mbs=4))
    got = run_losses(cfg_factory(dp=2, cp=2, zigzag=True, seq=32, mbs=4,
                                 zero1=True))
    np.testing.assert_allclose(got, base, rtol=2e-5, atol=2e-5)
