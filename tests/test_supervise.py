"""Supervisor suite (picotron_tpu/tools/supervise.py).

The watchdog is the outermost resilience layer, so its accounting bugs cost
real runs: a budget that never replenishes kills a weeks-long job over daily
hiccups, a deleted heartbeat silently disables stall detection, a signal
death propagated as a bare negative number confuses every scheduler. Each
of those (the ISSUE 8 satellites) gets a pinned test here, plus the pod
mode the cluster control plane (resilience/cluster.py) relies on: the pod
lives and dies together, restarts are budgeted once per pod, and per-host
supervisors coordinate through the shared restart-epoch file.

Children are real subprocesses; the loops run in-process with tiny
backoffs, so the whole file stays tier-1 fast.
"""

import os
import sys
import textwrap
import threading
import time

import pytest

from picotron_tpu.tools.supervise import (
    EXIT_CLUSTER_FAILED,
    EXIT_PREEMPTED,
    _bump_epoch,
    _heartbeat_age,
    _pod_exit_code,
    _read_epoch,
    _RestartBudget,
    _shell_code,
    main,
    run_pod,
    run_supervised,
)


def _script(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


# --------------------------------------------------------------------------- #
# exit-code plumbing
# --------------------------------------------------------------------------- #


def test_shell_code_signal_convention():
    assert _shell_code(0) == 0
    assert _shell_code(7) == 7
    assert _shell_code(-15) == 143  # SIGTERM
    assert _shell_code(-9) == 137  # SIGKILL


def test_pod_exit_code_ladder():
    # a real crash wins over 75; 75 over a stall kill; clean is clean
    assert _pod_exit_code([0, 7], stalled=False) == 7
    assert _pod_exit_code([-9, EXIT_PREEMPTED], stalled=False) == 137
    assert _pod_exit_code([EXIT_CLUSTER_FAILED, EXIT_PREEMPTED],
                          stalled=False) == EXIT_CLUSTER_FAILED
    assert _pod_exit_code([EXIT_PREEMPTED, 0], stalled=False) == EXIT_PREEMPTED
    assert _pod_exit_code([0, 0], stalled=True) == 1
    assert _pod_exit_code([0, 0], stalled=False) == 0
    # a reaped straggler's SIGTERM (-15) must not mask the root cause:
    # the child's own verdict wins regardless of rank order
    assert _pod_exit_code([-15, EXIT_CLUSTER_FAILED],
                          stalled=False) == EXIT_CLUSTER_FAILED
    assert _pod_exit_code([-15, 76], stalled=False) == 76


def test_signal_death_propagates_shell_code(tmp_path):
    """A child dying to an uncaught signal must surface as 128+sig — the
    convention every scheduler keys on — not a bare negative returncode."""
    script = _script(tmp_path, "die.py", """
        import os, signal
        os.kill(os.getpid(), signal.SIGKILL)
    """)
    rc = run_supervised([sys.executable, script], max_restarts=0,
                        backoff=0.01, poll_interval=0.02)
    assert rc == 137


# --------------------------------------------------------------------------- #
# restart budget: replenishment + spot-quota ladder
# --------------------------------------------------------------------------- #


def test_budget_exhausts_without_replenishment():
    b = _RestartBudget(max_restarts=2, backoff=1.0, backoff_max=60.0,
                       healthy_reset=0.0)  # legacy: attempt only grows
    assert b.record(uptime=1e6) is not None  # even a long run charges
    assert b.record(uptime=1e6) is not None
    assert b.record(uptime=1e6) is None


def test_budget_replenishes_after_healthy_uptime():
    """The ISSUE satellite: a long run that fails once a day must not be
    killed by arithmetic after max_restarts days."""
    b = _RestartBudget(max_restarts=2, backoff=1.0, backoff_max=60.0,
                       healthy_reset=600.0)
    assert b.record(uptime=5.0)[0] == "restart 1/2"
    assert b.record(uptime=5.0)[0] == "restart 2/2"
    # a healthy day of uptime: the counter resets, the ladder restarts
    kind, delay = b.record(uptime=86400.0)
    assert kind == "restart 1/2" and delay == 1.0
    assert b.record(uptime=5.0)[0] == "restart 2/2"
    assert b.record(uptime=5.0) is None


def test_budget_quota_ladder_spares_restart_budget():
    b = _RestartBudget(max_restarts=1, backoff=1.0, backoff_max=60.0,
                       quota_window=10.0, quota_backoff=30.0,
                       quota_backoff_max=100.0, max_launch_retries=3)
    # fast deaths: the long doubling ladder, capped, no budget charge
    assert b.record(uptime=0.5) == ("launch failure 1/3", 30.0)
    assert b.record(uptime=0.5) == ("launch failure 2/3", 60.0)
    assert b.record(uptime=0.5) == ("launch failure 3/3", 100.0)  # capped
    assert b.attempt == 0
    assert b.record(uptime=0.5) is None  # retries bounded too
    # a real run resets the consecutive-failure count
    b2 = _RestartBudget(max_restarts=2, backoff=1.0, backoff_max=60.0,
                        quota_window=10.0, max_launch_retries=3)
    assert b2.record(uptime=0.5)[0].startswith("launch failure 1")
    assert b2.record(uptime=50.0)[0] == "restart 1/2"
    assert b2.launch_failures == 0
    assert b2.record(uptime=0.5)[0].startswith("launch failure 1")


def test_budget_stalled_runs_never_replenish_or_read_as_quota():
    """A stall kill's uptime is mostly DEAD time: with stall_timeout >=
    healthy_reset it must not reset the budget (a permanently wedged
    trainer would relaunch forever), and with stall_timeout < quota_window
    it must not ride the no-charge launch-failure ladder."""
    b = _RestartBudget(max_restarts=1, backoff=1.0, backoff_max=60.0,
                       healthy_reset=10.0, quota_window=100.0)
    assert b.record(uptime=50.0, stalled=True)[0] == "restart 1/1"
    assert b.launch_failures == 0  # held capacity: not a quota failure
    assert b.record(uptime=50.0, stalled=True) is None  # no replenish


def test_stalled_run_exhausts_budget_through_run_supervised(tmp_path):
    """Call-site regression: run_supervised must pass its stall verdict to
    the budget — with healthy_reset below the stall uptime, a dropped
    ``stalled=`` flag replenishes every cycle and relaunches the wedged
    trainer forever (the bug: record() had the logic, no caller used it)."""
    log = tmp_path / "launches"
    script = _script(tmp_path, "hang3.py", f"""
        import sys, time
        with open({str(log)!r}, "a") as f:
            f.write("x")
        if len(open({str(log)!r}).read()) >= 3:
            sys.exit(0)  # regression backstop: never loop forever
        time.sleep(60)
    """)
    rc = run_supervised([sys.executable, script], max_restarts=1,
                        backoff=0.01, heartbeat=str(tmp_path / "hb"),
                        stall_timeout=0.6, term_grace=2.0,
                        poll_interval=0.05, healthy_reset=0.3)
    assert rc == 143
    assert log.read_text() == "xx"  # launch + ONE budgeted restart, done


def test_budget_preempted_fast_death_is_not_quota():
    """A preemption can land seconds after launch, but the run HELD
    capacity and checkpointed: it must take the normal restart path, not
    the half-hour quota ladder."""
    b = _RestartBudget(max_restarts=3, backoff=1.0, backoff_max=60.0,
                       quota_window=10.0)
    kind, delay = b.record(uptime=0.5, preempted=True)
    assert kind == "restart 1/3" and delay == 1.0 and b.launch_failures == 0


# --------------------------------------------------------------------------- #
# heartbeat / stall detection
# --------------------------------------------------------------------------- #


def test_heartbeat_age_counts_missing_file_from_launch(tmp_path):
    """The ISSUE satellite: the old code returned 0.0 ("perfectly fresh")
    on OSError forever, so deleting the heartbeat file mid-run silently
    disabled stall detection."""
    hb = tmp_path / "hb"
    hb.write_text("")
    assert _heartbeat_age(str(hb), time.time() - 100) < 5.0
    os.remove(hb)
    assert _heartbeat_age(str(hb), time.time() - 100) > 95.0


def test_deleted_heartbeat_still_trips_stall_kill(tmp_path):
    script = _script(tmp_path, "rm_hb.py", """
        import os, time
        os.remove(os.environ["PICOTRON_HEARTBEAT"])
        time.sleep(60)
    """)
    rc = run_supervised([sys.executable, script], max_restarts=0,
                        heartbeat=str(tmp_path / "hb"), stall_timeout=1.0,
                        term_grace=2.0, poll_interval=0.05)
    assert rc == 143


def test_stall_kill_counts_as_restart(tmp_path):
    """A stall kill consumes the restart budget like any failure — a
    permanently wedged run must not be relaunched forever. Previously
    untested (the existing test uses max_restarts=0)."""
    log = tmp_path / "launches"
    script = _script(tmp_path, "hang.py", f"""
        import time
        with open({str(log)!r}, "a") as f:
            f.write("x")
        time.sleep(60)
    """)
    rc = run_supervised([sys.executable, script], max_restarts=1,
                        backoff=0.01, heartbeat=str(tmp_path / "hb"),
                        stall_timeout=0.7, term_grace=2.0,
                        poll_interval=0.05)
    assert rc == 143
    assert log.read_text() == "xx"  # launch + exactly one budgeted restart


# --------------------------------------------------------------------------- #
# pod mode: N local ranks, one fate
# --------------------------------------------------------------------------- #

# each rank records "<rank>" per incarnation; reads pod env vars or dies
_POD_OK = """
    import os, sys
    rank = os.environ["PICOTRON_POD_RANK"]
    assert os.environ["JAX_PROCESS_ID"] == rank
    assert os.environ["JAX_NUM_PROCESSES"] == "2"
    with open(sys.argv[1], "a") as f:
        f.write(rank)
"""


def test_pod_clean_exit_and_env(tmp_path):
    log = tmp_path / "log"
    script = _script(tmp_path, "ok.py", _POD_OK)
    rc = run_pod([sys.executable, script, str(log)], num_procs=2,
                 max_restarts=0, poll_interval=0.02)
    assert rc == 0
    assert sorted(log.read_text()) == ["0", "1"]


def test_pod_one_crash_restarts_whole_pod(tmp_path):
    """Rank 1 crashes once; rank 0 would happily sleep on — the supervisor
    must terminate the straggler and relaunch BOTH ranks (a half-restarted
    pod can never re-form its collectives)."""
    log = tmp_path / "log"
    marker = tmp_path / "crashed_once"
    script = _script(tmp_path, "crashy_pod.py", f"""
        import os, sys, time
        rank = os.environ["PICOTRON_POD_RANK"]
        with open({str(log)!r}, "a") as f:
            f.write(rank)
        if rank == "1" and not os.path.exists({str(marker)!r}):
            open({str(marker)!r}, "w").close()
            sys.exit(7)
        if not os.path.exists({str(marker)!r}):
            time.sleep(60)  # healthy rank: would outlive the crash alone
    """)
    rc = run_pod([sys.executable, script, str(log)], num_procs=2,
                 max_restarts=1, backoff=0.01, term_grace=1.0,
                 poll_interval=0.02)
    assert rc == 0
    # both ranks launched twice: crash incarnation + the clean relaunch
    assert sorted(log.read_text()) == ["0", "0", "1", "1"]


def test_pod_preemption_restarts_as_resumable(tmp_path):
    """All ranks exiting 0/75 is a coordinated preemption (the consensus
    path): restart normally — and never misread the fast death as a quota
    failure."""
    log = tmp_path / "log"
    marker = tmp_path / "preempted_once"
    script = _script(tmp_path, "preempt_pod.py", f"""
        import os, sys
        with open({str(log)!r}, "a") as f:
            f.write(os.environ["PICOTRON_POD_RANK"])
        if not os.path.exists({str(marker)!r}):
            if os.environ["PICOTRON_POD_RANK"] == "1":
                open({str(marker)!r}, "w").close()
            sys.exit(75)
    """)
    rc = run_pod([sys.executable, script, str(log)], num_procs=2,
                 max_restarts=1, backoff=0.01, term_grace=1.0,
                 poll_interval=0.02, quota_window=30.0, quota_backoff=60.0)
    assert rc == 0  # a quota misread would still be sleeping its hour out
    assert sorted(log.read_text()) == ["0", "0", "1", "1"]


def test_pod_stall_kills_and_propagates(tmp_path):
    script = _script(tmp_path, "hang.py", "import time; time.sleep(60)")
    rc = run_pod([sys.executable, script], num_procs=2, max_restarts=0,
                 heartbeat=str(tmp_path / "hb"), stall_timeout=0.7,
                 term_grace=1.0, poll_interval=0.05)
    assert rc == 143  # the stall-killed ranks' SIGTERM deaths


def test_pod_stall_exhausts_budget_like_run_supervised(tmp_path):
    """The pod call site must pass its stall verdict to the shared budget
    too — same regression as the single-process path."""
    log = tmp_path / "launches"
    script = _script(tmp_path, "hang4.py", f"""
        import os, sys, time
        with open({str(log)!r}, "a") as f:
            f.write(os.environ["PICOTRON_POD_RANK"])
        if len(open({str(log)!r}).read()) >= 5:
            sys.exit(0)  # regression backstop: never loop forever
        time.sleep(60)
    """)
    rc = run_pod([sys.executable, script], num_procs=2, max_restarts=1,
                 backoff=0.01, heartbeat=str(tmp_path / "hb"),
                 stall_timeout=0.6, term_grace=2.0, poll_interval=0.05,
                 healthy_reset=0.3)
    assert rc == 143
    assert sorted(log.read_text()) == ["0", "0", "1", "1"]


def test_pod_budget_exhaustion_propagates_crash_code(tmp_path):
    script = _script(tmp_path, "die.py", "import sys; sys.exit(9)")
    rc = run_pod([sys.executable, script], num_procs=2, max_restarts=1,
                 backoff=0.01, term_grace=1.0, poll_interval=0.02)
    assert rc == 9


# --------------------------------------------------------------------------- #
# per-host pods: the shared restart-epoch file
# --------------------------------------------------------------------------- #


def test_epoch_file_round_trip(tmp_path):
    path = str(tmp_path / "epoch")
    assert _read_epoch(path) == 0  # missing file is epoch 0
    _bump_epoch(path, 0)
    assert _read_epoch(path) == 1
    _bump_epoch(path, 5)  # bump must advance PAST what the host observed
    assert _read_epoch(path) == 6


def test_local_failure_bumps_epoch_for_peers(tmp_path):
    """A failing host's supervisor must tell the other hosts to restart
    too, even when its own budget is spent."""
    epoch = tmp_path / "epoch"
    script = _script(tmp_path, "die.py", "import sys; sys.exit(7)")
    rc = run_supervised([sys.executable, script], max_restarts=0,
                        backoff=0.01, poll_interval=0.02,
                        epoch_file=str(epoch))
    assert rc == 7
    assert _read_epoch(str(epoch)) == 1


def test_pod_wide_failure_bumps_epoch_exactly_once(tmp_path):
    """When a peer already bumped the epoch for this incarnation (a
    coordinated preemption lands every host's failure within seconds),
    our failure must FOLLOW that restart — on the peer's budget, without
    compounding the bump (each compound would SIGTERM peers' freshly
    resumed trainers)."""
    epoch = tmp_path / "epoch"
    log = tmp_path / "launches"
    # first incarnation: "a peer host" bumps the shared epoch while we are
    # failing too; second incarnation succeeds
    script = _script(tmp_path, "fail_with_peer.py", f"""
        import sys
        with open({str(log)!r}, "a") as f:
            f.write("x")
        if len(open({str(log)!r}).read()) == 1:
            with open({str(epoch)!r}, "w") as f:
                f.write("1")
            sys.exit(75)
    """)
    rc = run_supervised([sys.executable, script], max_restarts=0,
                        backoff=0.01, poll_interval=0.05,
                        epoch_file=str(epoch))
    assert rc == 0
    # max_restarts=0: the relaunch happened on the peer's budget, and the
    # epoch stayed at the peer's bump — we did not advance it again
    assert log.read_text() == "xx"
    assert _read_epoch(str(epoch)) == 1


def test_peer_epoch_bump_restarts_without_budget_charge(tmp_path):
    """A peer-initiated pod restart terminates the local child and
    relaunches — on the PEER's budget: with max_restarts=0 the relaunch
    must still happen."""
    epoch = tmp_path / "epoch"
    log = tmp_path / "launches"
    script = _script(tmp_path, "follow.py", f"""
        import os, time
        with open({str(log)!r}, "a") as f:
            f.write("x")
        if len(open({str(log)!r}).read()) == 1:
            time.sleep(60)  # first incarnation waits to be peer-restarted
    """)
    result = {}

    def drive():
        result["rc"] = run_supervised(
            [sys.executable, script], max_restarts=0, backoff=0.01,
            term_grace=1.0, poll_interval=0.05, epoch_file=str(epoch))

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    deadline = time.time() + 10
    while time.time() < deadline and not log.exists():
        time.sleep(0.05)
    _bump_epoch(str(epoch), 0)  # the "peer host" asks for a pod restart
    t.join(timeout=15)
    assert not t.is_alive()
    assert result["rc"] == 0
    assert log.read_text() == "xx"  # terminated + relaunched, no budget used


# --------------------------------------------------------------------------- #
# CLI plumbing
# --------------------------------------------------------------------------- #


def test_main_runs_single_command():
    assert main(["--max-restarts", "0", "--backoff", "0.01", "--",
                 sys.executable, "-c", "raise SystemExit(0)"]) == 0


def test_main_rejects_conflicting_pod_modes():
    with pytest.raises(SystemExit):
        main(["--num-procs", "2", "--epoch-file", "/tmp/e", "--",
              "true"])
    with pytest.raises(SystemExit):
        main(["--stall-timeout", "5", "--", "true"])  # needs --heartbeat
    with pytest.raises(SystemExit):
        main(["--max-restarts", "0"])  # no command
    # pod mode without a rendezvous address would launch N DUPLICATE
    # single-process trainers racing on one save_dir
    with pytest.raises(SystemExit):
        main(["--num-procs", "2", "--", "true"])


# --------------------------------------------------------------------------- #
# serve mode (--serve): replica-fleet restart semantics
# --------------------------------------------------------------------------- #


def test_serve_mode_clean_drain_relaunches_without_budget_charge(tmp_path):
    """A serving replica's clean drain (exit 0) is a rollout, not a
    crash: serve mode relaunches it WITHOUT charging the restart budget,
    while nonzero exits still walk the bounded ladder. Run sequence:
    exit 0 (free relaunch), exit 7 (charges 1/1), exit 7 (budget
    exhausted -> propagate)."""
    count = tmp_path / "count"
    script = _script(tmp_path, "replica.py", """
        import pathlib, sys
        p = pathlib.Path({count!r})
        n = len(p.read_text()) if p.exists() else 0
        p.write_text("x" * (n + 1))
        sys.exit(0 if n == 0 else 7)
    """.format(count=str(count)))
    rc = run_supervised([sys.executable, script], max_restarts=1,
                        backoff=0.01, backoff_max=0.02, healthy_reset=0,
                        serve_mode=True, sleep=lambda s: None)
    assert rc == 7
    assert count.read_text() == "xxx"  # drained once + two crash runs


def test_serve_mode_off_keeps_exit_zero_final(tmp_path):
    """Without --serve, exit 0 still means done (trainer semantics are
    untouched by the serve-mode addition)."""
    script = _script(tmp_path, "once.py", "raise SystemExit(0)\n")
    rc = run_supervised([sys.executable, script], max_restarts=3,
                        backoff=0.01, sleep=lambda s: None)
    assert rc == 0


def test_serve_mode_sigterm_forwards_to_child_and_ends_supervision(
        tmp_path):
    """The supervisor is the fleet's stop surface: its own SIGTERM
    forwards to the replica (which drains and exits 0) and supervision
    ends with that code instead of relaunching. Runs on the main thread
    (signal handlers are only installable there)."""
    import signal as _signal

    script = _script(tmp_path, "drain.py", """
        import signal, sys, time
        signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
        while True:
            time.sleep(0.05)
    """)
    threading.Timer(
        0.8, lambda: os.kill(os.getpid(), _signal.SIGTERM)).start()
    rc = run_supervised([sys.executable, script], max_restarts=3,
                        backoff=0.01, serve_mode=True)
    assert rc == 0
    # the handler was restored: a later SIGTERM uses the default again
    assert _signal.getsignal(_signal.SIGTERM) == _signal.SIG_DFL


def test_main_rejects_serve_with_pod_mode():
    with pytest.raises(SystemExit):
        main(["--serve", "--num-procs", "2",
              "--coordinator", "localhost:1", "--", "true"])
