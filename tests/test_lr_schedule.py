"""Learning-rate schedules (beyond the reference's constant lr, train.py:209)."""

import numpy as np
import pytest

from conftest import make_config
from picotron_tpu.train_step import lr_schedule


def _tcfg(tiny_model_kwargs, **kw):
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    for k, v in kw.items():
        setattr(cfg.training, k, v)
    cfg.validate()
    return cfg.training


def test_constant_no_warmup_is_plain_float(tiny_model_kwargs):
    t = _tcfg(tiny_model_kwargs)
    assert lr_schedule(t) == t.learning_rate  # float => schedule-free opt state


def test_warmup_ramp_and_plateau(tiny_model_kwargs):
    t = _tcfg(tiny_model_kwargs, lr_warmup_steps=10)
    s = lr_schedule(t)
    assert float(s(0)) == 0.0
    np.testing.assert_allclose(float(s(5)), t.learning_rate / 2, rtol=1e-6)
    np.testing.assert_allclose(float(s(10)), t.learning_rate, rtol=1e-6)
    np.testing.assert_allclose(float(s(1000)), t.learning_rate, rtol=1e-6)


def test_cosine_decays_to_min_ratio(tiny_model_kwargs):
    t = _tcfg(tiny_model_kwargs, lr_schedule="cosine", lr_warmup_steps=4,
              lr_min_ratio=0.1, lr_decay_steps=100,
              total_train_steps=100)
    s = lr_schedule(t)
    np.testing.assert_allclose(float(s(4)), t.learning_rate, rtol=1e-6)
    np.testing.assert_allclose(float(s(100)), 0.1 * t.learning_rate, rtol=1e-5)
    assert float(s(50)) < t.learning_rate


def test_linear_decay_endpoints(tiny_model_kwargs):
    t = _tcfg(tiny_model_kwargs, lr_schedule="linear", lr_warmup_steps=5,
              lr_min_ratio=0.0, total_train_steps=55)
    s = lr_schedule(t)
    np.testing.assert_allclose(float(s(5)), t.learning_rate, rtol=1e-6)
    np.testing.assert_allclose(float(s(30)), t.learning_rate / 2, rtol=1e-5)
    np.testing.assert_allclose(float(s(55)), 0.0, atol=1e-12)


def test_bad_schedule_rejected(tiny_model_kwargs):
    with pytest.raises(ValueError, match="lr_schedule"):
        _tcfg(tiny_model_kwargs, lr_schedule="step")


@pytest.mark.slow
def test_warmup_changes_trajectory_and_topology_agrees(tiny_model_kwargs):
    """A scheduled run trains (and differs from constant lr), and the
    schedule rides the jitted step identically on a sharded topology."""
    from test_parallel import run_losses

    base = run_losses(make_config(tiny_model_kwargs, seq=32, mbs=8), steps=6)
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=8)
    cfg.training.lr_schedule = "cosine"
    cfg.training.lr_warmup_steps = 3
    cfg.training.total_train_steps = 20
    warm = run_losses(cfg, steps=6)
    assert not np.allclose(warm, base, atol=1e-4)
    assert warm[-1] < warm[0]

    cfg2 = make_config(tiny_model_kwargs, dp=2, seq=32, mbs=4, zero1=True)
    cfg2.training.lr_schedule = "cosine"
    cfg2.training.lr_warmup_steps = 3
    cfg2.training.total_train_steps = 20
    got = run_losses(cfg2, steps=6)
    np.testing.assert_allclose(got, warm, rtol=2e-5, atol=2e-5)
