"""Pallas kernels vs their XLA oracles, via the TPU interpreter on CPU.

The reference validates its fast paths against pure-torch formulations
(LlamaRMSNorm vs TritonRMSNorm, SDPA vs flash-attn — model.py:147-157,191);
here the Pallas flash-attention and RMSNorm kernels are checked against
ops.attention.sdpa and ops.rmsnorm.rms_norm in interpret mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "force_tpu_interpret_mode"):
    # environment, not code: the installed jax predates the Mosaic
    # interpret-mode context manager every test here runs under — skip
    # (pass/skip signal) instead of failing on an AttributeError floor
    pytest.skip(
        f"jax {jax.__version__} lacks pltpu.force_tpu_interpret_mode "
        "(the TPU-interpreter-on-CPU API this module needs)",
        allow_module_level=True)

from picotron_tpu.ops.attention import sdpa
from picotron_tpu.ops.pallas.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
)
from picotron_tpu.ops.pallas.rmsnorm import rms_norm_pallas
from picotron_tpu.ops.rmsnorm import rms_norm


def _qkv(b=2, s=256, h=2, d=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


# merged requires head_dim % 128 == 0, so its cases run at d=128
LAYOUT_D = [("folded", 64), ("bshd", 64), ("merged", 128)]


@pytest.mark.parametrize("layout,d", LAYOUT_D)
@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_sdpa(causal, layout, d):
    q, k, v = _qkv(d=d)
    scale = 0.125
    with pltpu.force_tpu_interpret_mode():
        got = flash_attention(q, k, v, scale, causal=causal, block_q=128,
                              block_k=128, layout=layout)
    want = sdpa(q, k, v, scale, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("layout,d", LAYOUT_D)
def test_flash_lse_matches_block_attention(layout, d):
    from picotron_tpu.ops.attention import _causal_mask, block_attention

    q, k, v = _qkv(s=128, d=d)
    scale = 0.125
    with pltpu.force_tpu_interpret_mode():
        out, lse = flash_attention_with_lse(q, k, v, scale, causal=True,
                                            block_q=128, block_k=128,
                                            layout=layout)
    mask = _causal_mask(q.shape[1], k.shape[1], 0)
    want_out, want_lse = block_attention(q, k, v, scale, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want_out),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want_lse),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("layout,d", LAYOUT_D)
def test_flash_grads_match_sdpa(layout, d):
    q, k, v = _qkv(s=128, d=d)
    scale = 0.125

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, scale, causal=True, block_q=64,
                              block_k=64, layout=layout)
        return jnp.sum(out * jnp.cos(out))

    def loss_ref(q, k, v):
        out = sdpa(q, k, v, scale, causal=True)
        return jnp.sum(out * jnp.cos(out))

    with pltpu.force_tpu_interpret_mode():
        g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{name}")


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rmsnorm_matches_reference(dtype):
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 96, 128)).astype(dtype)
    w = jax.random.normal(jax.random.PRNGKey(1), (128,)).astype(dtype)
    with pltpu.force_tpu_interpret_mode():
        got = rms_norm_pallas(x, w, 1e-5)
    want = rms_norm(x, w, 1e-5)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), rtol=1e-2 if
                               dtype == jnp.bfloat16 else 1e-6, atol=1e-2 if
                               dtype == jnp.bfloat16 else 1e-6)


def test_rmsnorm_grads_match_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64, 128))
    w = jax.random.normal(jax.random.PRNGKey(1), (128,)) + 1.0

    def loss_pallas(x, w):
        return jnp.sum(jnp.sin(rms_norm_pallas(x, w, 1e-5)))

    def loss_ref(x, w):
        return jnp.sum(jnp.sin(rms_norm(x, w, 1e-5)))

    with pltpu.force_tpu_interpret_mode():
        gx, gw = jax.grad(loss_pallas, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=5e-5, atol=5e-5)


def test_merged_block_grads_match_einsum():
    """The ring-attention building block in the merged layout: block
    backward fed an external out/lse must match AD through the einsum
    block (full-attend block, the ring's off-diagonal case)."""
    from picotron_tpu.ops.attention import block_attention
    from picotron_tpu.ops.pallas.flash_attention import (
        flash_attention_with_lse, flash_block_grads)

    q, k, v = _qkv(s=128, d=128, seed=7)
    scale = 0.125
    with pltpu.force_tpu_interpret_mode():
        out, lse = flash_attention_with_lse(q, k, v, scale, causal=False,
                                            block_q=64, block_k=64,
                                            layout="merged")
    do = jax.random.normal(jax.random.PRNGKey(8), out.shape)
    with pltpu.force_tpu_interpret_mode():
        dq, dk, dv = flash_block_grads(q, k, v, out, lse, do, scale,
                                       causal=False, block_q=64, block_k=64,
                                       layout="merged")

    def ref_f(q, k, v):
        o, _ = block_attention(q, k, v, scale, mask=None)
        return jnp.sum(o * do)

    rq, rk, rv = jax.grad(ref_f, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip((dq, dk, dv), (rq, rk, rv), "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{name}")


def test_merged_layout_rejects_unaligned_head_dim():
    q, k, v = _qkv(d=64)
    with pytest.raises(ValueError, match="head_dim % 128"):
        flash_attention(q, k, v, 0.125, layout="merged")


def test_flash_blocks_configurable_through_model(tiny_model_kwargs):
    """model.flash_block_q/k and flash_layout reach the kernel through
    _attention: a custom tiling or the bshd layout must not change the
    math."""
    from picotron_tpu.config import Config
    from picotron_tpu.models.llama import _attention

    def cfg_with(bq, bk, layout="folded"):
        return Config.from_dict({
            "distributed": {"use_cpu": True},
            "model": dict(tiny_model_kwargs, attention_impl="flash",
                          flash_block_q=bq, flash_block_k=bk,
                          flash_layout=layout),
            "training": {"seq_length": 128},
            "dataset": {"name": "synthetic"},
        })

    q, k, v = _qkv(b=1, s=128, h=2, d=64, seed=3)
    with pltpu.force_tpu_interpret_mode():
        got = _attention(q, k, v, cfg_with(32, 128))
        ref = _attention(q, k, v, cfg_with(None, None))
        bshd = _attention(q, k, v, cfg_with(None, None, layout="bshd"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(bshd), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
