"""Tooling-layer tests: config generator, sweep scheduler + status triage,
metrics extractor (the reference's L6 surface, SURVEY.md §3.5)."""

import json
import os
import subprocess
import sys

import pytest

from picotron_tpu.config import Config
from picotron_tpu.tools import create_config as cc
from picotron_tpu.tools import extract_metrics as em
from picotron_tpu.tools import submit_jobs as sj


# ---------------------------------------------------------------- create_config


def test_create_config_writes_valid_config(tmp_path):
    path = cc.create_single_config(
        out_dir=str(tmp_path), exp_name="exp1", dp=2, tp=2,
        model_name="HuggingFaceTB/SmolLM-1.7B", seq_len=512, mbs=4,
        grad_acc_steps=8, use_cpu=True)
    cfg = Config.from_json(path)
    assert cfg.distributed.dp_size == 2 and cfg.distributed.tp_size == 2
    assert cfg.model.hidden_size == 2048  # SmolLM-1.7B from the shape table
    assert cfg.training.seq_length == 512
    assert cfg.global_batch_size == 4 * 8 * 2


def test_create_config_shape_overrides_win(tmp_path):
    path = cc.create_single_config(
        out_dir=str(tmp_path), exp_name="exp2",
        model_name="HuggingFaceTB/SmolLM-1.7B", num_hidden_layers=5,
        seq_len=128, use_cpu=True)
    cfg = Config.from_json(path)
    assert cfg.model.num_hidden_layers == 5
    assert cfg.model.hidden_size == 2048


def test_known_model_shapes_all_validate():
    """Every offline shape-table entry builds a valid Config (the table is
    the zero-egress path to each supported model family)."""
    from picotron_tpu.config import Config
    from picotron_tpu.models import llama
    from picotron_tpu.tools.create_config import KNOWN_MODEL_SHAPES

    for name, shape in KNOWN_MODEL_SHAPES.items():
        cfg = Config.from_dict({
            "distributed": {"use_cpu": True},
            "model": dict(shape, name=name, dtype="float32",
                          attention_impl="sdpa"),
            "training": {"seq_length": 32, "micro_batch_size": 1},
            "dataset": {"name": "synthetic"},
        })
        assert llama.num_params(cfg.model) > 1e8, name
        # GQA geometries must divide cleanly
        assert (cfg.model.num_attention_heads
                % cfg.model.num_key_value_heads == 0), name


def test_create_config_rejects_bad_topology(tmp_path):
    with pytest.raises(ValueError):
        cc.create_single_config(
            out_dir=str(tmp_path), exp_name="bad",
            model_name="HuggingFaceTB/SmolLM-1.7B", tp=7, use_cpu=True)


def test_create_config_unknown_model_full_override_offline(tmp_path):
    # An unknown model with a full shape override must not touch the network.
    path = cc.create_single_config(
        out_dir=str(tmp_path), exp_name="custom",
        model_name="mycorp/custom-tiny", num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, hidden_size=32,
        intermediate_size=64, vocab_size=128, seq_len=64, use_cpu=True)
    cfg = Config.from_json(path)
    assert cfg.model.hidden_size == 32 and cfg.model.vocab_size == 128


def test_create_config_overwrite(tmp_path):
    kw = dict(out_dir=str(tmp_path), exp_name="dup",
              model_name="HuggingFaceTB/SmolLM-135M", seq_len=128, use_cpu=True)
    cc.create_single_config(**kw)
    with pytest.raises(FileExistsError):
        cc.create_single_config(**kw)
    cc.create_single_config(**kw, exist_ok=True)


def test_create_config_cli(tmp_path):
    rc = cc.main(["--out_dir", str(tmp_path), "--exp_name", "cli_exp",
                  "--model_name", "HuggingFaceTB/SmolLM-135M",
                  "--dp", "1", "--seq_len", "256", "--use_cpu"])
    assert rc == 0
    cfg = Config.from_json(str(tmp_path / "cli_exp" / "config.json"))
    assert cfg.model.num_hidden_layers == 30


# ---------------------------------------------------------------- status triage


def test_classify_log_patterns():
    assert sj.classify_log("... RESOURCE_EXHAUSTED: out of memory ...", 1) is sj.Status.OOM
    assert sj.classify_log("xx DUE TO TIME LIMIT xx", None) is sj.Status.TIMEOUT
    assert sj.classify_log("Traceback ...", 1) is sj.Status.FAIL
    assert sj.classify_log("done: 2 steps", 0) is sj.Status.COMPLETED
    # exit code wins over benign warning substrings in successful runs
    assert sj.classify_log(
        "W0001 Attempting to reserve 2.1G\ndone: 100 steps", 0) is sj.Status.COMPLETED
    assert sj.classify_log(
        "Timed out waiting for barrier, retrying\ndone", 0) is sj.Status.COMPLETED


def test_job_status_roundtrip(tmp_path):
    job = sj.Job(str(tmp_path))
    assert job.status is sj.Status.INIT  # no status.txt yet
    job.set_status(sj.Status.PENDING)
    assert sj.Job(str(tmp_path)).status is sj.Status.PENDING


def _make_tiny_exp(tmp_path, name, steps=2):
    raw = {
        "distributed": {"use_cpu": True},
        "model": dict(num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, hidden_size=32,
                      intermediate_size=64, vocab_size=128,
                      max_position_embeddings=64, dtype="float32",
                      attention_impl="sdpa"),
        "training": dict(seq_length=32, micro_batch_size=2,
                         total_train_steps=steps, remat="none"),
        "dataset": {"name": "synthetic"},
    }
    d = tmp_path / name
    d.mkdir(parents=True)
    with open(d / "config.json", "w") as f:
        json.dump(raw, f)
    return d


def test_scheduler_local_end_to_end(tmp_path):
    _make_tiny_exp(tmp_path, "run_dp1_tp1_mbs2_sl32")
    sched = sj.Scheduler(str(tmp_path), backend="local")
    assert len(sched.jobs) == 1
    job = sched.jobs[0]
    status = sched.run_local(job, timeout_s=600)
    log = open(job.log_path).read()
    assert status is sj.Status.COMPLETED, log
    assert "Step:" in log
    # resubmit filter: completed jobs are not selected by default
    assert sched.select(None) == []
    assert sched.select("completed") == [job]


def test_scheduler_classifies_failure(tmp_path):
    d = _make_tiny_exp(tmp_path, "broken")
    # corrupt the config so the run fails fast
    with open(d / "config.json", "w") as f:
        f.write("{not json")
    sched = sj.Scheduler(str(tmp_path), backend="local")
    status = sched.run_local(sched.jobs[0], timeout_s=120)
    assert status is sj.Status.FAIL


def test_slurm_render(tmp_path):
    _make_tiny_exp(tmp_path, "slurm_exp")
    sched = sj.Scheduler(str(tmp_path), backend="slurm")
    script = sched.render_slurm(sched.jobs[0])
    text = open(script).read()
    assert "picotron_tpu.train" in text
    assert "status.txt" in text
    assert "{{" not in text  # fully rendered


# ------------------------------------------------------------- extract_metrics


SAMPLE_LOG = """\
model SmolLM: 1.71B params | mesh dp=1 pp=1 cp=1 tp=1 on 1 x TPU v5e
Step: 1     | Loss: 10.8016 | Global batch size: 8.19K | Tokens/s: 1.02K | Tokens/s/chip: 1.02K | Tokens: 8.19K | MFU: 1.00% | Memory usage: 4.10GB
Step: 2     | Loss: 9.5000 | Global batch size: 8.19K | Tokens/s: 30.00K | Tokens/s/chip: 30.00K | Tokens: 16.38K | MFU: 30.00% | Memory usage: 4.10GB
Step: 3     | Loss: 9.0000 | Global batch size: 8.19K | Tokens/s: 31.00K | Tokens/s/chip: 31.00K | Tokens: 24.58K | MFU: 31.00% | Memory usage: 4.10GB
Step: 4     | Loss: 8.5000 | Global batch size: 8.19K | Tokens/s: 40.00K | Tokens/s/chip: 40.00K | Tokens: 32.77K | MFU: 40.00% | Memory usage: 4.10GB
Step: 5     | Loss: 8.0000 | Global batch size: 8.19K | Tokens/s: 42.00K | Tokens/s/chip: 42.00K | Tokens: 40.96K | MFU: 42.00% | Memory usage: 4.10GB
done: 5 steps
"""


def test_parse_log_line():
    row = em.parse_log_line(SAMPLE_LOG.splitlines()[1])
    assert row == {
        "step": 1, "loss": 10.8016, "tokens_per_sec": 1020.0,
        "tokens_per_sec_per_chip": 1020.0, "mfu_pct": 1.0, "memory_gb": 4.10,
    }
    assert em.parse_log_line("model SmolLM: 1.71B params") is None


def test_extract_sweep(tmp_path):
    run = tmp_path / "smollm_dp2_tp4_pp1_cp1_mbs1_ga8_sl2048"
    run.mkdir()
    (run / "log.out").write_text(SAMPLE_LOG)
    rows = em.extract(str(tmp_path))
    assert len(rows) == 1
    r = rows[0]
    # warmup: first 3 steps dropped -> mean of steps 4,5
    assert r["num_steps"] == 2
    assert r["tokens_per_sec_per_chip"] == pytest.approx(41000.0)
    assert r["mfu_pct"] == pytest.approx(41.0)
    assert r["final_loss"] == pytest.approx(8.0)
    assert (r["dp"], r["tp"], r["pp"], r["cp"]) == (2, 4, 1, 1)
    assert (r["micro_batch_size"], r["grad_acc"], r["seq_len"]) == (1, 8, 2048)
    assert (run / "metrics.csv").exists()
    assert (tmp_path / "global_metrics.csv").exists()


def test_from_readable_format():
    assert em.from_readable_format("1.5K") == 1500.0
    assert em.from_readable_format("2M") == 2_000_000.0
    assert em.from_readable_format("7") == 7.0


# ----------------------------------------------- metrics JSONL (obs) source


def _jsonl_row(step, loss, tps):
    import json

    return json.dumps({"step": step, "loss": loss, "tokens_per_sec": tps,
                       "tokens_per_sec_per_chip": tps, "trained_tokens": 1,
                       "mfu_pct": None, "memory_gb": None, "t": 0.0})


def test_parse_jsonl_file_rows_and_junk(tmp_path):
    """Step rows come back in parse_log_file's shape; the summary row,
    corrupt lines, and a truncated tail (killed run) are skipped without
    losing the steps before them."""
    p = tmp_path / "metrics.jsonl"
    p.write_text(
        _jsonl_row(1, 10.5, 1000.0) + "\n"
        + "not json at all\n"
        + _jsonl_row(2, 9.5, 2000.0) + "\n"
        + '{"event": "summary", "metrics": {}}\n'
        + '{"step": 3, "loss": 9.0, "tokens_per_sec": 3000.0'  # truncated
    )
    rows = em.parse_jsonl_file(str(p))
    assert [r["step"] for r in rows] == [1, 2]
    assert rows[0]["loss"] == pytest.approx(10.5)
    assert rows[1]["tokens_per_sec_per_chip"] == pytest.approx(2000.0)
    assert rows[0]["mfu_pct"] is None and rows[0]["memory_gb"] is None


def test_extract_prefers_jsonl_over_log(tmp_path):
    """A run dir with BOTH sources: the structured JSONL wins and the
    disagreeing legacy log is never regex-scraped."""
    run = tmp_path / "smollm_dp2_tp4_pp1_cp1_mbs1_ga8_sl2048"
    run.mkdir()
    (run / "log.out").write_text(SAMPLE_LOG)  # says final_loss 8.0
    (run / em.JSONL_NAME).write_text(
        "\n".join(_jsonl_row(s, 20.0 - s, 5000.0) for s in range(1, 6))
        + "\n")
    rows = em.extract(str(tmp_path))
    assert len(rows) == 1
    assert rows[0]["final_loss"] == pytest.approx(15.0)  # JSONL, not 8.0
    assert rows[0]["tokens_per_sec_per_chip"] == pytest.approx(5000.0)
    assert (rows[0]["dp"], rows[0]["tp"]) == (2, 4)  # folder parse intact


def test_extract_falls_back_to_legacy_log(tmp_path):
    """An empty/corrupt JSONL (or none at all) drops to the regex path —
    pre-obs runs keep extracting exactly as before."""
    run = tmp_path / "smollm_dp1_tp1_pp1_cp1_mbs1_ga1_sl2048"
    run.mkdir()
    (run / "log.out").write_text(SAMPLE_LOG)
    (run / em.JSONL_NAME).write_text("garbage\n{\n")
    rows = em.extract(str(tmp_path))
    assert len(rows) == 1
    assert rows[0]["final_loss"] == pytest.approx(8.0)  # the log's numbers
    assert rows[0]["num_steps"] == 2


# ------------------------------------------------------------------- packaging


def test_root_shims_importable():
    """The repo-root shims must resolve against the package."""
    for shim in ("create_config.py", "submit_jobs.py", "extract_metrics.py"):
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), shim)
        assert os.path.exists(path)
    out = subprocess.run(
        [sys.executable, "-c",
         "from picotron_tpu.tools import create_config, submit_jobs, "
         "extract_metrics; print('ok')"],
        capture_output=True, text=True)
    assert out.stdout.strip() == "ok", out.stderr


def test_parse_folder_name_anchored():
    """Keys must not match inside other tokens (round-1 ADVICE: undelimited
    dp(\\d+) regexes mislabel sweep rows)."""
    from picotron_tpu.tools.extract_metrics import parse_folder_name

    got = parse_folder_name("smollm_dp2_tp4_pp2_cp1_mbs1_ga8_sl2048")
    assert (got["dp"], got["tp"], got["pp"], got["cp"]) == (2, 4, 2, 1)
    assert (got["micro_batch_size"], got["grad_acc"], got["seq_len"]) == (1, 8, 2048)
    # 'warmup3' must not read as pp=3; 'setup2' must not read as tp=2;
    # 'speedup9' must not poison anything
    got = parse_folder_name("warmup3_setup2_speedup9_dp4")
    assert got["dp"] == 4
    assert got["pp"] is None and got["tp"] is None
    # no topology tokens at all
    got = parse_folder_name("baseline_run")
    assert all(v is None for v in got.values())


def test_create_config_round3_flags(tmp_path):
    """cp_zigzag / remat / steps_per_call are reachable from the generator
    CLI surface."""
    from picotron_tpu.tools.create_config import main as cc_main

    rc = cc_main([
        "--out_dir", str(tmp_path), "--exp_name", "zig",
        "--model_name", "HuggingFaceTB/SmolLM-1.7B",
        "--cp", "2", "--cp_zigzag", "--remat", "save_attn",
        "--steps_per_call", "8", "--seq_len", "2048", "--use_cpu", "--dp", "4"])
    assert rc == 0
    cfg = json.load(open(tmp_path / "zig" / "config.json"))
    assert cfg["distributed"]["cp_zigzag"] is True
    assert cfg["training"]["remat"] == "save_attn"
    assert cfg["training"]["steps_per_call"] == 8


def test_create_config_sp_zero1_flags(tmp_path):
    """tp_sequence_parallel / zero1 are reachable from the generator CLI and
    default to the template's values when absent."""
    from picotron_tpu.tools.create_config import main as cc_main

    rc = cc_main([
        "--out_dir", str(tmp_path), "--exp_name", "spz",
        "--model_name", "HuggingFaceTB/SmolLM-1.7B",
        "--tp", "2", "--dp", "2", "--tp_sequence_parallel", "--zero1",
        "--seq_len", "2048", "--use_cpu"])
    assert rc == 0
    cfg = json.load(open(tmp_path / "spz" / "config.json"))
    assert cfg["distributed"]["tp_sequence_parallel"] is True
    assert cfg["distributed"]["zero1"] is True

    rc = cc_main([
        "--out_dir", str(tmp_path), "--exp_name", "plain",
        "--model_name", "HuggingFaceTB/SmolLM-1.7B", "--use_cpu"])
    assert rc == 0
    cfg = json.load(open(tmp_path / "plain" / "config.json"))
    assert cfg["distributed"]["tp_sequence_parallel"] is False
    assert cfg["distributed"]["zero1"] is False


# ---------------------------------------------------------- project_multichip


def test_projection_ladder_sane():
    """The multi-chip projection (docs/PROJECTION.md) must stay internally
    consistent: MFU below the single-chip anchor, every ladder config fitting
    v5e HBM, and the BASELINE north star (>= 40% SmolLM on v5e-16) holding
    under the stated conservative assumptions."""
    from picotron_tpu.tools import project_multichip as pm

    rows = [pm.project(lc) for lc in pm.LADDER]
    for lc, r in zip(pm.LADDER, rows):
        assert 0 < r["mfu"] < 100 * lc.model.eff_1chip
        # configs must fit v5e HBM unless explicitly tagged as over (the
        # canonical config-5 is shown alongside a fitting variant)
        assert r["mem_gb"] < 16.0 or "over HBM" in r["config"], (
            f"{r['config']} does not fit v5e HBM")
        assert r["comm_eff"] <= 100 and r["bubble_eff"] <= 100
    assert any(r["mem_gb"] < 16.0 and "seq8192" in r["config"]
               for r in rows), "no fitting 7B long-context config"
    north_star = next(r for r in rows if "cp2" in r["config"]
                      and "SmolLM" in r["config"])
    assert north_star["mfu"] >= 40.0


def test_projection_param_count_matches_model():
    """The projector's closed-form n_params must agree with the real model's
    count (llama.num_params) for both ladder models."""
    from picotron_tpu.config import SMOLLM_1_7B, ModelConfig
    from picotron_tpu.models import llama
    from picotron_tpu.tools import project_multichip as pm

    mc = ModelConfig(**SMOLLM_1_7B)
    assert pm.SMOLLM.n_params() == llama.num_params(mc)


# --------------------------------------------------------------- chip_agenda


def test_chip_agenda_run_step(tmp_path):
    """The on-chip agenda runner must survive per-step timeouts/failures and
    always leave a log artifact (a tunnel dying mid-window must not lose the
    earlier steps' evidence)."""
    import sys

    from picotron_tpu.tools.chip_agenda import run_step

    ok = run_step("ok", [sys.executable, "-c", "print('x')"], str(tmp_path),
                  timeout=30)
    assert ok["rc"] == 0 and os.path.exists(ok["log"])
    to = run_step("to", [sys.executable, "-c", "import time; time.sleep(9)"],
                  str(tmp_path), timeout=1)
    assert to["rc"] == -9 and "timed out" in open(to["log"]).read()


def test_chip_agenda_profile_triggers_analysis(tmp_path, monkeypatch):
    """A successful profile step is followed by the derived (chip-free)
    profile_analysis step; a failed one is not."""
    from picotron_tpu.tools import chip_agenda as ca

    for profile_rc, expect_analysis in ((0, True), (1, False)):
        calls = []

        def fake_run_step(name, cmd, out_dir, timeout, env=None):
            calls.append(name)
            return {"step": name, "rc": profile_rc if name == "profile"
                    else 0, "log": os.path.join(out_dir, f"{name}.log")}

        monkeypatch.setattr(ca, "run_step", fake_run_step)
        out = tmp_path / f"run{profile_rc}"
        ca.main([str(out), "--only", "profile"])
        assert ("profile_analysis" in calls) == expect_analysis, calls


# ------------------------------------------------------------- analyze_trace


def test_analyze_trace_summarizes_a_real_capture(tmp_path, capsys):
    """Generate a real jax.profiler capture (CPU backend) and check the
    analyzer finds the op events and attributes the matmul-dominated cost
    correctly — the same code path the chip agenda's profile step feeds."""
    import jax
    import jax.numpy as jnp

    from picotron_tpu.tools import analyze_trace as at

    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: jnp.tanh(a @ a) @ a)
    jax.block_until_ready(f(x))  # compile outside the window
    jax.profiler.start_trace(str(tmp_path))
    for _ in range(3):
        jax.block_until_ready(f(x))
    jax.profiler.stop_trace()

    rc = at.main([str(tmp_path)])
    if rc != 0:
        # environment, not code: some sandboxes' profiler captures carry no
        # device op events at all (the analyzer's explicit empty-capture
        # exit) — nothing to summarize, nothing to assert
        pytest.skip("jax.profiler capture contains no device op events in "
                    "this environment")
    out = capsys.readouterr().out
    line = [l for l in out.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["active_ms"] > 0
    assert "matmul" in rec["categories_pct"]
    # two dot_generals vs one tanh: matmuls must dominate
    assert rec["categories_pct"]["matmul"] > 50


def test_analyze_trace_missing_dir_is_a_clear_error(tmp_path):
    from picotron_tpu.tools import analyze_trace as at

    with pytest.raises(FileNotFoundError, match="xplane"):
        at.find_xplane(str(tmp_path))


def test_measure_cond_gating_small(capsys):
    """The cond-gating micro-bench (VERDICT r3 weak #3) runs end-to-end on
    the CPU mesh and reports every field the round record needs. The
    TPU-magnitude claim itself (gated-false ~ free) is only checkable on
    hardware — chip_agenda runs the full-size version there."""
    from picotron_tpu.tools import measure_cond_gating as mcg

    rc = mcg.main(["--small"])
    assert rc == 0
    out = capsys.readouterr().out
    rec = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
    for k in ("loss_owner_ms", "loss_gated_other_ms",
              "loss_maskedboth_other_ms", "embed_owner_ms",
              "embed_gated_other_ms", "embed_maskedboth_other_ms"):
        assert rec[k] > 0


@pytest.mark.slow
def test_measure_offload_bw_small(capsys):
    """The offload-economics probe (remat='offload' bandwidth math,
    docs/BENCH_7B.md) runs end-to-end on CPU and reports link bandwidth +
    both step timings; the decisive PCIe numbers need the chip —
    chip_agenda runs the full-size version there."""
    from picotron_tpu.tools import measure_offload_bw as mob

    rc = mob.main(["--small"])
    assert rc == 0
    out = capsys.readouterr().out
    rec = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
    assert rec["d2h_gbps"] > 0 and rec["h2d_gbps"] > 0
    assert rec["save_attn_ms"] > 0 and rec["offload_ms"] > 0
    assert rec["value"] > 0


def test_chip_agenda_rejects_unknown_step(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "picotron_tpu.tools.chip_agenda",
         str(tmp_path), "--only", "bogus"],
        capture_output=True, text=True)
    assert r.returncode == 2
    assert "unknown step" in r.stderr


def test_tunnel_watch_resumes_and_exits_on_complete(tmp_path, capsys):
    """A watcher whose state file already records every step as passed must
    exit 0 without probing the tunnel (state is how a restarted watcher —
    or a later round — avoids re-burning a live window)."""
    import json

    from picotron_tpu.tools import tunnel_watch as tw

    run = tmp_path / "run"
    run.mkdir()
    summary = []
    for s in tw.ALL_STEPS:
        log = run / f"{s}.log"
        metric = tw.BENCH_STEP_METRICS.get(s)
        if metric:  # bench steps must show REAL evidence to stay passed
            log.write_text(json.dumps(
                {"metric": metric, "value": 55.3, "unit": "%"}) + "\n")
        else:
            log.write_text("ok\n")
        summary.append({"step": s, "rc": 0, "log": str(log)})
    (run / "summary.json").write_text(json.dumps(summary))
    state = tmp_path / "state.json"
    tw.save_state(str(state), {"passed": {s: str(run)
                                          for s in tw.ALL_STEPS}})
    rc = tw.main(["--state", str(state), "--interval", "1",
                  "--budget-hours", "0.001"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "done: passed=" in out and "given_up=[]" in out


def test_tunnel_watch_budget_exhausts(tmp_path, monkeypatch, capsys):
    from picotron_tpu.tools import tunnel_watch as tw

    monkeypatch.setattr(tw, "probe_tunnel", lambda timeout=90.0: "dead")
    monkeypatch.setattr(tw.time, "sleep", lambda s: None)
    rc = tw.main(["--state", str(tmp_path / "s.json"),
                  "--interval", "1", "--budget-hours", "-1"])
    assert rc == 1
    assert "budget exhausted" in capsys.readouterr().out


def test_chip_agenda_term_handler_kills_step_group():
    """tunnel_watch SIGTERMs the agenda on its global cap; the agenda's
    handler must forward a SIGKILL to the in-flight step's process group
    (each step runs in its own session) — an orphaned step would hold the
    TPU for the rest of the live window."""
    import signal

    from picotron_tpu.tools import chip_agenda as ca

    sleeper = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        start_new_session=True)
    old = signal.getsignal(signal.SIGTERM)
    try:
        ca._install_term_handler()
        ca._current_pgid = os.getpgid(sleeper.pid)
        with pytest.raises(SystemExit) as ei:
            os.kill(os.getpid(), signal.SIGTERM)
        assert ei.value.code == 128 + signal.SIGTERM
        assert sleeper.wait(timeout=10) == -signal.SIGKILL
    finally:
        ca._current_pgid = None
        signal.signal(signal.SIGTERM, old)
        if sleeper.poll() is None:
            sleeper.kill()


def test_tunnel_watch_step_captured_semantics(tmp_path):
    """rc!=0 never counts; non-bench steps count on rc==0 alone; bench
    steps additionally need a real, non-stale JSON record in their own
    log — a null artifact or a stale republish must leave the step
    pending so a later window retries it (the 20260731T0316 bench exited
    rc=0 with a null artifact)."""
    import json

    from picotron_tpu.tools import tunnel_watch as tw

    log = tmp_path / "bench.log"
    p = str(log)
    assert not tw.step_captured("kernel_parity", 1, p)
    assert tw.step_captured("kernel_parity", 0, p)  # non-bench: rc alone
    # bench: no log yet -> not captured
    assert not tw.step_captured("bench", 0, p)
    log.write_text(json.dumps(
        {"metric": "smollm_1.7b_mfu_1chip", "value": None,
         "unit": "%", "error": "x"}) + "\n")
    assert not tw.step_captured("bench", 0, p)  # null artifact
    log.write_text(json.dumps(
        {"metric": "smollm_1.7b_mfu_1chip", "value": 55.3,
         "stale_from": "/old"}) + "\n")
    assert not tw.step_captured("bench", 0, p)  # stale republish
    log.write_text(json.dumps(
        {"metric": "tokens_per_sec_cpu_smoke", "value": 990.0}) + "\n")
    assert not tw.step_captured("bench", 0, p)  # CPU smoke, wrong metric
    log.write_text("# noise\n" + json.dumps(
        {"metric": "smollm_1.7b_mfu_1chip", "value": 55.3,
         "unit": "%"}) + "\n")
    assert tw.step_captured("bench", 0, p)
    assert not tw.step_captured("bench_7b", 0, p)  # needs ITS metric


def test_tunnel_watch_state_revalidates_bench_entries(tmp_path, capsys):
    """A resumed state file claiming a bench passed is only honored when
    the recorded out_dir's summary + log actually show a real capture
    (an old watcher marked null-artifact benches passed on rc==0)."""
    import json

    from picotron_tpu.tools import tunnel_watch as tw

    run = tmp_path / "run"
    run.mkdir()
    (run / "bench.log").write_text(json.dumps(
        {"metric": "smollm_1.7b_mfu_1chip", "value": None,
         "error": "x"}) + "\n")
    (run / "summary.json").write_text(json.dumps(
        [{"step": "bench", "rc": 0, "log": str(run / "bench.log")}]))
    state_file = tmp_path / "s.json"
    state_file.write_text(json.dumps(
        {"passed": {"bench": str(run), "kernel_parity": str(run)}}))
    state = tw.load_state(str(state_file))
    # null bench capture dropped; non-bench steps are trusted as-is
    assert "bench" not in state["passed"]
    assert "kernel_parity" in state["passed"]

    (run / "bench.log").write_text(json.dumps(
        {"metric": "smollm_1.7b_mfu_1chip", "value": 55.3,
         "unit": "%"}) + "\n")
    state = tw.load_state(str(state_file))
    assert state["passed"]["bench"] == str(run)  # real capture honored


def test_tunnel_watch_null_artifact_code_blame(tmp_path):
    """A null artifact stamped code_failure by the orchestrator earns a
    strike; infra nulls (hangs, probes, EX_INFRA bail-outs, tunnel-death
    crash tails — never stamped) do not."""
    import json

    from picotron_tpu.tools import tunnel_watch as tw

    log = tmp_path / "bench.log"
    p = str(log)
    assert not tw.null_artifact_blames_code(p)  # no log: no blame
    log.write_text(json.dumps(
        {"metric": "m", "value": None,
         "error": "attempt 1: tunnel probe hung/failed"}) + "\n")
    assert not tw.null_artifact_blames_code(p)
    log.write_text(json.dumps(
        {"metric": "m", "value": None, "code_failure": True,
         "error": "attempt 1: inner bench rc=1; tail: 'ImportError'"}) + "\n")
    assert tw.null_artifact_blames_code(p)
    log.write_text(json.dumps(  # real capture: nothing to blame
        {"metric": "m", "value": 55.3, "unit": "%"}) + "\n")
    assert not tw.null_artifact_blames_code(p)


def test_tunnel_watch_gives_up_on_failed_steps(tmp_path, capsys):
    """--max-step-failures 0 marks every unpassed step given-up at once:
    the watcher exits 1 (not 0) and names them, instead of hammering a
    deterministically failing step for the whole budget."""
    from picotron_tpu.tools import tunnel_watch as tw

    rc = tw.main(["--state", str(tmp_path / "s.json"),
                  "--max-step-failures", "0", "--budget-hours", "1"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "given_up=" in out and "bench" in out


def test_tunnel_watch_state_paths_are_repo_relative(tmp_path, monkeypatch,
                                                    capsys):
    """State persists evidence paths REPO-relative (a checkout on another
    machine must not inherit absolute /root/... pointers), joins them back
    on load, and drops non-bench entries whose evidence dir is gone."""
    import json

    from picotron_tpu.tools import tunnel_watch as tw

    monkeypatch.setattr(tw, "REPO", str(tmp_path))
    run = tmp_path / "docs" / "chip_runs" / "X"
    run.mkdir(parents=True)
    state_file = tmp_path / "s.json"
    tw.save_state(str(state_file), {"passed": {
        "kernel_parity": str(run),            # under REPO -> relative
        "cond_gating": "/elsewhere/run"}})    # outside REPO -> untouched
    on_disk = json.loads(state_file.read_text())
    assert on_disk["passed"]["kernel_parity"] == os.path.join(
        "docs", "chip_runs", "X")
    assert on_disk["passed"]["cond_gating"] == "/elsewhere/run"

    state = tw.load_state(str(state_file))
    # relative joined back to absolute; missing-dir entry dropped
    assert state["passed"]["kernel_parity"] == str(run)
    assert "cond_gating" not in state["passed"]
    assert "does not exist" in capsys.readouterr().out


def test_tunnel_watch_ignores_out_of_set_summary_records(
        tmp_path, monkeypatch, capsys):
    """Summary records for steps outside ALL_STEPS (the derived
    profile_analysis) must neither be marked passed (a name that can never
    be pending) nor strike; a failed analysis is retried chip-free since
    the trace is already on disk."""
    import json
    import types

    from picotron_tpu.tools import tunnel_watch as tw

    monkeypatch.setattr(tw, "REPO", str(tmp_path))
    monkeypatch.setattr(tw, "probe_tunnel", lambda timeout=90.0: "tpu")
    retried = []
    monkeypatch.setattr(
        tw.subprocess, "run",
        lambda cmd, **kw: (retried.append(cmd),
                           types.SimpleNamespace(returncode=1))[1])

    class FakeAgenda:
        def __init__(self, cmd, **kw):
            out_dir = cmd[3]
            os.makedirs(out_dir, exist_ok=True)
            log = os.path.join(out_dir, "profile.log")
            with open(log, "w") as f:
                f.write("ok\n")
            with open(os.path.join(out_dir, "summary.json"), "w") as f:
                json.dump([
                    {"step": "profile", "rc": 0, "log": log},
                    {"step": "profile_analysis", "rc": 1, "log": log},
                ], f)

        def wait(self, timeout=None):
            return 0

    monkeypatch.setattr(tw.subprocess, "Popen", FakeAgenda)
    state_file = tmp_path / "s.json"
    rc = tw.main(["--state", str(state_file), "--steps", "profile",
                  "--interval", "1", "--budget-hours", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "retrying chip-free" in out
    assert retried and "picotron_tpu.tools.analyze_trace" in retried[0]
    state = json.loads(state_file.read_text())
    assert set(state["passed"]) == {"profile"}  # analysis never marked
