"""Pipeline FLOP-cost guardrails (docs/PP_COST.md).

The 1F1B engine must stay a phase-split layer-remat schedule: per
docs/PP_COST.md the per-device flops ratio 1F1B/AFAB at pp=2, M=4 is ~1.29
(theory 1.33); a tick-uniform schedule that executes masked halves in bubble
ticks measures ~1.54 and a whole-stage-forward-rebuild backward ~2.0, so the
assert at 1.45 separates the healthy regime from both regressions with
margin for compiler drift.
"""

import pytest

from conftest import make_config
from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.topology import topology_from_config

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow


def _step_flops(cfg):
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    tokens, targets = ts.shard_batch(next(loader), topo)
    comp = step.lower(params, opt_state, tokens, targets).compile()
    return comp.cost_analysis()["flops"]


def test_1f1b_has_no_stage_forward_rebuild(tiny_model_kwargs):
    kw = dict(pp=2, acc=4, mbs=2, seq=32)
    f_afab = _step_flops(make_config(tiny_model_kwargs, engine="afab", **kw))
    f_1f1b = _step_flops(make_config(tiny_model_kwargs, engine="1f1b", **kw))
    ratio = f_1f1b / f_afab
    assert 1.0 < ratio < 1.45, (
        f"1F1B/AFAB flops ratio {ratio:.2f} outside the phase-split "
        f"layer-remat regime (~1.3); ~1.54 means bubble ticks execute masked "
        f"halves again, ~2.0 means the whole-stage forward rebuild is back")


def test_interleaved_flops_stay_near_plain_1f1b(tiny_model_kwargs):
    """Interleaved 1F1B does the same per-device layer work as plain 1F1B in
    more ticks of 1/v-size units. On this CPU cost model the measured ratio
    is inflated well above the TPU reality: the embed/loss stage gating
    compiles to compute-both where-masks off-TPU (llama._stage_gating), and
    the interleaved schedule runs v*M units + boundary half-ticks instead of
    M stage passes — each paying the masked embed+loss again, which on the
    tiny test model (vocab comparable to hidden) is a large fraction.
    Measured 1.79 at (pp=2, v=2, M=4); a whole-stage-forward-rebuild
    backward regression lands ~2.5+, so 2.1 separates the regimes."""
    kw = dict(pp=2, acc=4, mbs=2, seq=32)
    f_plain = _step_flops(make_config(tiny_model_kwargs, engine="1f1b", **kw))
    f_inter = _step_flops(make_config(tiny_model_kwargs, engine="1f1b",
                                      interleave=2, **kw))
    ratio = f_inter / f_plain
    assert 1.2 < ratio < 2.1, (
        f"interleaved/plain 1F1B flops ratio {ratio:.2f}: above 2.1 the "
        f"interleaved backward is executing more than layer-remat + masked "
        f"boundary half-ticks (whole-stage rebuild regression?); below 1.2 "
        f"it is silently skipping unit work")
