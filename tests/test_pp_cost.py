"""Pipeline FLOP-cost guardrails (docs/PP_COST.md).

The 1F1B backward must stay a layer-remat backward (3x fwd per stage), never
a whole-stage forward rebuild (4x): per docs/PP_COST.md the per-device flops
ratio 1F1B/AFAB at pp=2, M=4 is ~1.54 for the layer-remat backward (theory
1.60) and ~2.0 for a rebuild-based one, so the assert at 1.75 separates the
two regimes with margin for compiler drift.
"""

from conftest import make_config
from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.topology import topology_from_config


def _step_flops(cfg):
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    tokens, targets = ts.shard_batch(next(loader), topo)
    comp = step.lower(params, opt_state, tokens, targets).compile()
    return comp.cost_analysis()["flops"]


def test_1f1b_has_no_stage_forward_rebuild(tiny_model_kwargs):
    kw = dict(pp=2, acc=4, mbs=2, seq=32)
    f_afab = _step_flops(make_config(tiny_model_kwargs, engine="afab", **kw))
    f_1f1b = _step_flops(make_config(tiny_model_kwargs, engine="1f1b", **kw))
    ratio = f_1f1b / f_afab
    assert 1.0 < ratio < 1.75, (
        f"1F1B/AFAB flops ratio {ratio:.2f} outside the layer-remat regime "
        f"(~1.4-1.6); ~2.0 means the whole-stage forward rebuild is back")
