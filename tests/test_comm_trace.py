"""PICOTRON_VERBOSE collective tracing (the analogue of the reference's
VERBOSE=1 send/recv prints, pp_communications.py:28, cp_communications.py:
33-35): level 1 logs each collective once at trace time — under jit the
traced sequence IS the runtime comm schedule."""

import pytest

from conftest import make_config


def _build_step(tiny_model_kwargs, **kw):
    import jax

    from picotron_tpu import train_step as ts
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.topology import topology_from_config

    cfg = make_config(tiny_model_kwargs, **kw)
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    tokens, targets = ts.shard_batch(next(loader), topo)
    jax.block_until_ready(step(params, opt_state, tokens, targets)[2])


@pytest.mark.parametrize(
    "sp", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_verbose_level1_traces_collectives(tiny_model_kwargs, monkeypatch,
                                           capsys, sp):
    monkeypatch.setenv("PICOTRON_VERBOSE", "1")
    _build_step(tiny_model_kwargs, tp=2, pp=2, acc=2, engine="1f1b", sp=sp)
    err = capsys.readouterr().err
    if sp:
        # SP: both halves of each collective pair are in the record
        assert "[comm] all_gather axis=tp" in err
        assert "[comm] reduce_scatter axis=tp" in err
    else:
        # plain TP: the Megatron f/g all-reduces
        assert "[comm] tp_reduce.fwd all_reduce axis=tp" in err
    assert "[comm] pp.1f1b send_recv act down axis=pp" in err
    assert "[comm] pp.1f1b send_recv grad up axis=pp" in err
    assert "[comm] grad all_reduce(mean)" in err
    # shapes are part of the record, like the reference's prints
    assert "shape=(" in err and "dtype=" in err


@pytest.mark.slow
def test_verbose_traces_ring_and_ulysses(tiny_model_kwargs, monkeypatch,
                                         capsys):
    monkeypatch.setenv("PICOTRON_VERBOSE", "1")
    _build_step(tiny_model_kwargs, cp=2, seq=64)
    err = capsys.readouterr().err
    assert "[comm] ring.fwd send_recv kv axis=cp" in err
    assert "[comm] ring.bwd send_recv kv+dkv axis=cp" in err

    _build_step(tiny_model_kwargs, cp=2, seq=64, cp_impl="ulysses")
    err = capsys.readouterr().err
    assert "[comm] ulysses all_to_all seq->heads axis=cp" in err
    assert "[comm] ulysses all_to_all heads->seq axis=cp" in err


def test_verbose_off_is_silent(tiny_model_kwargs, monkeypatch, capsys):
    monkeypatch.delenv("PICOTRON_VERBOSE", raising=False)
    _build_step(tiny_model_kwargs, tp=2)
    assert "[comm]" not in capsys.readouterr().err


def test_bad_verbose_value_is_off(monkeypatch):
    from picotron_tpu import comm_trace

    monkeypatch.setenv("PICOTRON_VERBOSE", "yes")
    assert comm_trace._level() == 0
    monkeypatch.setenv("PICOTRON_VERBOSE", "2")
    assert comm_trace._level() == 2
