"""Multi-host (multi-controller) training: 2 JAX processes on localhost.

The reference actually runs multi-node (train.py:83-94 rendezvous, per-rank
batch slicing data.py:40-45); this is the rebuild's equivalent proof: two
``jax.distributed`` CPU processes (gloo collectives), each owning 4 of the 8
mesh devices, run the identical library code path — and the loss trajectory
must match a single-process run of the same topology exactly, because
``shard_batch`` places the same global batch by addressable shards
(train_step._place_global) and every collective spans the right processes.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_pod(tmp_path, features: str = ""):
    """Run the 2-process worker pod; returns both processes' JSON results."""
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(WORKER))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    outs = [str(tmp_path / f"p{i}.json") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(port), outs[i]]
            + ([features] if features else []),
            env=env, cwd=repo_root,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    try:
        logs = [p.communicate(timeout=540)[0] for p in procs]
    finally:
        for p in procs:  # a rendezvous hang must not leak workers
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"worker {i} failed:\n{logs[i][-3000:]}"
    return [json.load(open(o)) for o in outs]


@pytest.mark.parametrize("features", ["", "zero1", "fsdp"],
                         ids=["plain", "zero1", "fsdp"])
def test_two_process_matches_single_process(tmp_path, cfg_factory, features):
    """With ZeRO-1, dp being the outermost mesh axis means each dp replica
    (and each optimizer-state chunk) lives on its own process — the grad
    reduce-scatter and param all-gather cross hosts — and the trajectory
    must still equal the single-process run. With FSDP the layer params
    themselves rest sharded across the two processes and every layer's
    just-in-time all-gather crosses the boundary."""
    results = _launch_pod(tmp_path, features=features)
    # both processes observe the same (replicated) loss
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6, atol=1e-6)
    # only process 0 is the logging controller
    assert results[0]["is_main"] and not results[1]["is_main"]

    # and the 2-process trajectory equals the single-process one
    from test_parallel import run_losses

    cfg = cfg_factory(dp=2, cp=2, tp=2, seq=32, mbs=4,
                      zero1=features == "zero1", fsdp=features == "fsdp")
    cfg.model.vocab_size = 256
    ref = run_losses(cfg, steps=4)
    np.testing.assert_allclose(results[0]["losses"], ref, rtol=3e-5, atol=3e-5)
