"""Parallel == single-device equivalence, the core oracle (SURVEY.md §4).

Generalizes the reference's test patterns — sliced-reference TP comparison
(tests/test_tensor_parallel.py) and dual-dataloader CP comparison
(tests/test_dataloader.py) — into one property: with the same seed, config and
data, the fp32 loss trajectory must be identical for every 4D topology and
both pipeline engines.
"""

import numpy as np
import pytest

from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.topology import topology_from_config

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow

STEPS = 5


def run_losses(cfg, steps=STEPS):
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    losses = []
    for _ in range(steps):
        tokens, targets = ts.shard_batch(next(loader), topo)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    return np.asarray(losses)


# Every topology trains on the same GLOBAL batch of 8 sequences per step
# (gb = mbs * acc * dp, reference data.py:17): mbs = 8 // (dp * acc).
GLOBAL_BATCH = 8

TOPOLOGIES = [
    dict(dp=2),
    dict(dp=8),
    dict(tp=2),
    dict(tp=4),
    dict(cp=2),
    dict(cp=4),
    dict(acc=2),
    dict(pp=2, acc=2, engine="1f1b"),
    dict(pp=2, acc=2, engine="afab"),
    dict(pp=4, acc=4, engine="1f1b"),
    dict(pp=4, acc=4, engine="afab"),
    dict(dp=2, tp=2, cp=2),
    dict(dp=2, pp=2, cp=2, acc=2, engine="1f1b"),
    dict(dp=2, pp=2, tp=2, acc=2, engine="1f1b"),
    dict(pp=2, cp=2, tp=2, acc=2, engine="1f1b"),
    # zigzag CP: permuted sequence layout must not change the loss (token
    # mean is permutation-invariant; rope/mask follow the true positions)
    dict(cp=2, zigzag=True),
    dict(cp=4, zigzag=True),
    dict(dp=2, cp=2, tp=2, zigzag=True),
    # Megatron sequence parallelism: seq-sharded residual stream between TP
    # blocks must be a pure layout change (beyond-parity; reference TODO
    # utils.py:66)
    dict(tp=2, sp=True),
    dict(tp=4, sp=True),
    dict(tp=2, cp=2, sp=True),
    dict(pp=2, tp=2, acc=2, engine="1f1b", sp=True),
    dict(pp=2, tp=2, acc=2, engine="afab", sp=True),
    dict(dp=2, tp=2, cp=2, sp=True, zigzag=True),
    # Ulysses all-to-all context parallelism: resharding seq<->heads around
    # one full-sequence attention must be a pure layout change (beyond-parity;
    # SURVEY §2.3 marks Ulysses out of the reference's scope)
    dict(cp=2, cp_impl="ulysses"),
    dict(cp=4, cp_impl="ulysses"),
    dict(tp=2, cp=2, cp_impl="ulysses", sp=True),
    dict(dp=2, pp=2, cp=2, acc=2, engine="1f1b", cp_impl="ulysses"),
    # Interleaved 1F1B (virtual pipeline stages, beyond-parity — SURVEY §2.3
    # notes the reference has none): chunked layer placement + the
    # tick-uniform interleaved schedule must reproduce the same trajectories
    dict(pp=2, acc=2, engine="1f1b", interleave=2),
    dict(pp=2, acc=4, engine="1f1b", interleave=2),
    dict(dp=2, pp=2, tp=2, acc=2, engine="1f1b", interleave=2),
    # lax.cond stage gating — the program a real TPU pod runs (the default
    # only cond-gates on TPU; forcing it here runs that exact structure on
    # the CPU mesh, safe because tp=1 gated branches carry no collectives).
    # Both engines: 1f1b exercises the manual stage_bwd conds, afab the AD
    # engine's stage_apply conds.
    dict(pp=2, acc=2, engine="1f1b", stage_gating="cond"),
    dict(pp=4, acc=4, engine="afab", stage_gating="cond"),
    dict(pp=2, acc=4, engine="1f1b", interleave=2, stage_gating="cond"),
    # cond gating x ring CP: the ring ppermutes live outside the gated
    # branches, so tp=1 stays collective-free inside conds even with cp>1
    dict(pp=2, cp=2, acc=2, engine="1f1b", stage_gating="cond"),
]


@pytest.fixture(scope="module")
def baseline(request):
    return {}


@pytest.mark.parametrize("topo_kw", TOPOLOGIES, ids=lambda d: "-".join(
    f"{k}{v}" for k, v in d.items()))
def test_topology_matches_single_device(cfg_factory, baseline, topo_kw):
    if "ref" not in baseline:
        baseline["ref"] = run_losses(cfg_factory(seq=32, mbs=GLOBAL_BATCH))
    kw = dict(topo_kw)
    acc = kw.pop("acc", 1)
    dp = kw.get("dp", 1)
    got = run_losses(cfg_factory(seq=32, mbs=GLOBAL_BATCH // (dp * acc), acc=acc, **kw))
    np.testing.assert_allclose(got, baseline["ref"], rtol=2e-5, atol=2e-5)


def test_vocab_parallel_ce_matches_gathered(cfg_factory, tiny_model_kwargs):
    cfg_g = cfg_factory(tp=4, seq=32, mbs=2)
    cfg_v = cfg_factory(tp=4, seq=32, mbs=2)
    cfg_v.model.gather_logits = False
    np.testing.assert_allclose(run_losses(cfg_g), run_losses(cfg_v), rtol=2e-5, atol=2e-5)
