"""Sweep-ladder integration: configs 4/5 (the Llama-2-7B topologies) scaled
down, end-to-end through the experiment tooling (round-2 VERDICT item 3b/5).

This is the reference's primary integration story — submit_slurm_jobs.py
walking experiment dirs -> train -> extract_metrics.py summarizing logs
(reference submit_slurm_jobs.py:68-113, extract_metrics.py:108-195) — run
for real: the scheduler's local backend launches `python -m
picotron_tpu.train` subprocesses on the 8-virtual-device CPU mesh with the
ladder's exact parallel topology (config 4's dp is halved, 16 devices -> 8),
a tiny model standing in for the 7B geometry, and the metrics extractor
parses the produced logs into the sweep CSV.
"""

import pytest

import csv
import json
import os

from picotron_tpu.tools.extract_metrics import extract
from picotron_tpu.tools.submit_jobs import Scheduler, Status

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_7B_STANDIN = dict(
    num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=8,
    hidden_size=64, intermediate_size=176,  # 11008/4096 ratio ~ 2.69
    vocab_size=256, max_position_embeddings=8192,
)


def _scaled_ladder_cfg(src_path: str, run_name: str, seq: int) -> dict:
    with open(src_path) as f:
        raw = json.load(f)
    raw["distributed"]["dp_size"] = min(raw["distributed"]["dp_size"], 8 // (
        raw["distributed"]["tp_size"] * raw["distributed"]["pp_size"]
        * raw["distributed"]["cp_size"]))
    raw["distributed"]["use_cpu"] = True
    raw["model"].update(TINY_7B_STANDIN, dtype="float32",
                        attention_impl="sdpa")
    raw["training"].update(seq_length=seq, total_train_steps=12,
                           learning_rate=3e-3)
    raw["logging"]["run_name"] = run_name
    return raw


def test_ladder_configs_through_sweep_tooling(tmp_path):
    sweep = tmp_path / "sweep"
    specs = [
        # (source ladder config, run dir name, scaled seq)
        ("configs/4_llama2_7b_dp4_tp2_pp2_sl1024/config.json",
         "l4_dp2_tp2_pp2_cp1_mbs1_ga8_sl64", 64),
        ("configs/5_llama2_7b_4d_sl8192/config.json",
         "l5_dp1_tp2_pp2_cp2_mbs1_ga4_sl64", 64),
    ]
    for src, name, seq in specs:
        d = sweep / name
        d.mkdir(parents=True)
        with open(d / "config.json", "w") as f:
            json.dump(_scaled_ladder_cfg(os.path.join(REPO, src), name, seq), f)

    # Run both experiments via the scheduler's local backend. The subprocesses
    # must not inherit this test process's 8-device CPU pinning — the configs
    # carry use_cpu and the trainer pins its own device count.
    env_backup = {k: os.environ.pop(k, None)
                  for k in ("XLA_FLAGS", "JAX_PLATFORMS")}
    try:
        sched = Scheduler(str(sweep), backend="local")
        assert len(sched.jobs) == 2
        sched.submit(timeout_s=500)
    finally:
        for k, v in env_backup.items():
            if v is not None:
                os.environ[k] = v

    for job in sched.jobs:
        log = open(job.log_path, errors="replace").read()
        assert job.status is Status.COMPLETED, f"{job.name}:\n{log[-2000:]}"

    # extract_metrics over the sweep -> per-run metrics.csv + global CSV with
    # parsed topology columns and a decreasing loss
    rows = extract(str(sweep))
    assert len(rows) == 2
    by_run = {r["run"]: r for r in rows}
    r4 = by_run["l4_dp2_tp2_pp2_cp1_mbs1_ga8_sl64"]
    assert (r4["dp"], r4["tp"], r4["pp"], r4["cp"]) == (2, 2, 2, 1)
    r5 = by_run["l5_dp1_tp2_pp2_cp2_mbs1_ga4_sl64"]  # dp 2->1: 16 devices -> 8
    assert (r5["dp"], r5["tp"], r5["pp"], r5["cp"]) == (1, 2, 2, 2)
    for r in rows:
        # clearly below ln(256)=5.55 (random-init level): it actually learned
        assert r["final_loss"] < 4.9, r
        assert r["tokens_per_sec"] and r["tokens_per_sec"] > 0
    # and the per-run metrics.csv shows a decreasing per-step loss
    for job in sched.jobs:
        with open(os.path.join(job.root, "metrics.csv")) as f:
            steps = list(csv.DictReader(f))
        assert len(steps) == 12
        assert float(steps[-1]["loss"]) < float(steps[0]["loss"]) - 0.3, steps
    assert os.path.exists(sweep / "global_metrics.csv")
    with open(sweep / "global_metrics.csv") as f:
        assert len(list(csv.DictReader(f))) == 2
