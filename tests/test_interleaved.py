"""Interleaved 1F1B (virtual pipeline stages) — layout + schedule specifics.

Trajectory equivalence against single-device lives in the topology matrix
(tests/test_parallel.py); here: the chunk-permuted layer layout round-trips
through checkpoints across layouts, and the unit-order/layout helpers are
self-consistent.
"""

import pytest
import numpy as np

from conftest import make_config
from picotron_tpu import train_step as ts
from picotron_tpu.checkpoint import CheckpointManager
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.models.llama import pp_layer_layout
from picotron_tpu.topology import topology_from_config
from picotron_tpu.utils import shard_map as shard_map_compat

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow


def test_interleaved_layout_is_permutation():
    """Every global layer gets exactly one stacked row; device s's contiguous
    K-row shard holds chunks {s, pp+s, ...} chunk-major."""
    L, pp, v = 8, 2, 2
    K, counts, positions = pp_layer_layout(L, pp, v)
    assert K == 4 and counts == [4, 4]
    assert sorted(positions) == list(range(L))
    # layer -> (device, local row): chunk c*pp+s holds layers [(c*pp+s)*Kv..)
    # device 0: chunks 0,2 = layers [0,1] + [4,5] at rows 0-3
    assert positions[0:2] == [0, 1]   # chunk 0 -> device 0 rows 0,1
    assert positions[2:4] == [4, 5]   # chunk 1 -> device 1 rows 4,5
    assert positions[4:6] == [2, 3]   # chunk 2 -> device 0 rows 2,3
    assert positions[6:8] == [6, 7]   # chunk 3 -> device 1 rows 6,7


def _run(cfg, steps, params=None, opt_state=None, skip=0):
    topo = topology_from_config(cfg)
    if params is None:
        params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    loader.skip_steps(skip)
    losses = []
    for _ in range(steps):
        tokens, targets = ts.shard_batch(next(loader), topo)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    return params, opt_state, losses


def test_interleaved_hf_roundtrip(tiny_model_kwargs, tmp_path):
    """HF export from plain params -> import into the interleaved layout
    permutes the layer rows correctly (the identity fast path must not fire:
    the interleaved layout has rows == L with non-identity positions)."""
    import jax

    from picotron_tpu.checkpoint import load_hf_safetensors, save_hf_safetensors
    from picotron_tpu.models import llama

    cfg = make_config(tiny_model_kwargs, pp=2, acc=2, engine="1f1b",
                      interleave=2)
    topo = topology_from_config(cfg)
    plain = llama.init_params(jax.random.PRNGKey(3), cfg.model)
    path = str(tmp_path / "m.safetensors")
    save_hf_safetensors(plain, path, (4, 1))

    inter = load_hf_safetensors(path, cfg.model, topo, interleave=2)
    K, _, positions = pp_layer_layout(4, 2, 2)
    for name in ("wq", "w_down", "attn_norm"):
        got = np.asarray(inter["layers"][name])
        want = np.asarray(plain["layers"][name])
        for g, pos in enumerate(positions):
            np.testing.assert_array_equal(got[pos], want[g], err_msg=f"{name}[{g}]")


def test_forward_logits_remaps_interleaved_layout(tiny_model_kwargs):
    """The eval path scans stacked rows in order; interleaved-trained params
    are remapped to contiguous global order on the fly (remap_layout), so
    their logits match the plain-layout model's exactly — no checkpoint
    save/load round-trip."""
    import jax

    from picotron_tpu.models import llama

    from jax.sharding import PartitionSpec as P

    from picotron_tpu.topology import topology_from_config

    cfg = make_config(tiny_model_kwargs, pp=2, acc=2, engine="1f1b",
                      interleave=2)
    plain = llama.init_params(jax.random.PRNGKey(0), cfg.model)
    inter = llama.init_params(jax.random.PRNGKey(0), cfg.model, pp_size=2,
                              interleave=2)
    tokens = np.random.default_rng(0).integers(
        0, cfg.model.vocab_size, (1, 32), dtype=np.int32)

    def eval_logits(cfg_x, params):
        # eval contract: full (replicated) param stack, every device runs
        # the whole model — forward_logits un-permutes the rows itself
        topo = topology_from_config(cfg_x)
        fwd = jax.jit(shard_map_compat(
            lambda p, t: llama.forward_logits(p, t, cfg_x),
            mesh=topo.mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False))
        return np.asarray(fwd(params, tokens))

    want = eval_logits(make_config(tiny_model_kwargs), plain)
    got = eval_logits(cfg, inter)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_remap_layout_roundtrip(tiny_model_kwargs):
    """remap_layout moves global layer g between any two layouts; an
    interleaved -> contiguous -> interleaved round trip is the identity."""
    import jax

    from picotron_tpu.models import llama

    cfg = make_config(tiny_model_kwargs, pp=2, acc=2, engine="1f1b",
                      interleave=2)
    L = cfg.model.num_hidden_layers
    inter = llama.init_params(jax.random.PRNGKey(1), cfg.model, pp_size=2,
                              interleave=2)
    plain = llama.remap_layout(inter, L, (2, 2), (1, 1))
    want = llama.init_params(jax.random.PRNGKey(1), cfg.model)
    for k in plain["layers"]:
        np.testing.assert_array_equal(np.asarray(plain["layers"][k]),
                                      np.asarray(want["layers"][k]), k)
    back = llama.remap_layout(plain, L, (1, 1), (2, 2))
    for k in back["layers"]:
        np.testing.assert_array_equal(np.asarray(back["layers"][k]),
                                      np.asarray(inter["layers"][k]), k)


def test_interleaved_checkpoint_cross_layout(tiny_model_kwargs, tmp_path):
    """A checkpoint saved from an interleaved pp=2/v=2 run restores into the
    single-device (contiguous) layout and continues the exact trajectory —
    the stacked-row remap covers the chunk permutation."""
    kw = dict(seq=32, mbs=4, acc=2)
    cfg_i = make_config(tiny_model_kwargs, pp=2, engine="1f1b", interleave=2, **kw)
    cfg_s = make_config(tiny_model_kwargs, **dict(kw, mbs=8, acc=1))

    _, _, full = _run(make_config(
        tiny_model_kwargs, pp=2, engine="1f1b", interleave=2, **kw), 5)

    p, o, first3 = _run(cfg_i, 3)
    np.testing.assert_allclose(first3, full[:3], rtol=2e-5, atol=2e-5)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(3, p, o, trained_tokens=3, layout=(4, 2, 2))

    topo_s = topology_from_config(cfg_s)
    p_s, o_s = ts.init_state(cfg_s, topo_s)
    p2, o2, step_no, _ = mgr.load(p_s, o_s, layout=(4, 1, 1))
    mgr.close()
    assert step_no == 3
    _, _, cont = _run(cfg_s, 2, params=p2, opt_state=o2, skip=3)
    np.testing.assert_allclose(cont, full[3:5], rtol=2e-5, atol=2e-5)
