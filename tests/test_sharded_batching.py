"""dp-sharded continuous batching (ISSUE 18): ONE logical engine whose
slot axis spans the dp mesh axis.

Acceptance surface:
- dp=2 greedy generations are BIT-IDENTICAL to dp=1 across the program
  families (blocked decode, speculative verify, chunked prefill), attend
  kernels (dense/flash), KV layouts (contiguous/paged), int8 KV cache and
  int8 weights, and mixed-tenant batches — on tp=1 and a tp=2 mesh
  (dp x tp devices out of the forced 8-device CPU host platform);
- a forced cross-shard slot migration (engine.migrate_slot: one batched
  page gather + one donating write through the page-transport device
  path) resumes decode bit-identically, with page refcounts conserved;
- the planner's edge cases hold: a migration attempted after a
  speculative verify exports only ACCEPTED rows (draft garbage past the
  length pointer never travels), destination-pool exhaustion aborts the
  plan with the source slot untouched and refcounts conserved, and a
  dead dp peer discovered mid-migration exits through the ClusterMonitor
  lease path (EXIT_CLUSTER_FAILED) without leaking a single page;
- dp=1 stays the byte-identical default: every construction below also
  runs the dp=1 engine, and the dp=2 run must reproduce it exactly.
"""

import os
import time

import numpy as np
import pytest

import jax

from conftest import make_config
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    Request,
)
from picotron_tpu.inference import paged_kv
from picotron_tpu.models import llama
from picotron_tpu.resilience.cluster import (
    EXIT_CLUSTER_FAILED,
    ClusterMonitor,
)

MAX_LEN = 96


def _engine(tiny_model_kwargs, dp, tp=1, slots=4, **kw):
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    cfg.inference.dp_size = dp
    kw.setdefault("decode_block_len", 4)
    eng = InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN, **kw)
    return cfg, eng


def _params(cfg, engine, seed=0):
    p = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(seed))
    if engine.quant_weights:
        p = llama.quantize_params(p)
    return engine.shard_params(p)


def _skewed_reqs(program):
    """2 long + 2 short greedy requests: shard 0's slots keep decoding
    after shard 1's retire, so a dp=2 batcher sees occupancy skew (and,
    on the paged layout, a rebalance migration) mid-run. ``verify`` uses
    repetitive prompts (the regime prompt-lookup drafting accepts on);
    ``chunked`` uses prompts spanning 2-3 prefill chunks."""
    if program == "verify":
        return [Request("l0", [5, 9, 5, 9, 5, 9], max_new_tokens=20),
                Request("l1", [7, 3, 7, 3, 7, 3, 7], max_new_tokens=20),
                Request("s0", [11, 12, 11, 12], max_new_tokens=4),
                Request("s1", [13, 14, 13, 14], max_new_tokens=4)]
    if program == "chunked":
        long_a = [(5 * i + 2) % 199 + 1 for i in range(20)]
        long_b = [(3 * i + 7) % 199 + 1 for i in range(17)]
        return [Request("l0", long_a, max_new_tokens=16),
                Request("l1", long_b, max_new_tokens=16),
                Request("s0", [11, 12] * 5, max_new_tokens=4),
                Request("s1", [13, 14] * 6, max_new_tokens=4)]
    return [Request("l0", [1, 2, 3, 4, 5], max_new_tokens=24),
            Request("l1", [9, 8, 7, 6], max_new_tokens=24),
            Request("s0", [11, 12], max_new_tokens=4),
            Request("s1", [13, 14, 15], max_new_tokens=4)]


def _run(tiny_model_kwargs, dp, program, **kw):
    if program == "verify":
        kw.setdefault("spec_len", 3)
    if program == "chunked":
        kw.setdefault("prefill_chunk", 8)
    cfg, eng = _engine(tiny_model_kwargs, dp, **kw)
    b = ContinuousBatcher(eng, _params(cfg, eng))
    res = b.run(_skewed_reqs(program))
    return {uid: (r.tokens, r.finish_reason) for uid, r in res.items()}, b


# --------------------------------------------------------------------------- #
# dp=2 == dp=1, across the program/kernel/layout/quantization matrix
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("program,attend,layout,quant,tp", [
    ("block",   "dense", "contiguous", None,     1),
    ("block",   "dense", "paged",      None,     2),
    ("block",   "flash", "paged",      None,     1),
    ("block",   "flash", "contiguous", "int8kv", 2),
    ("block",   "dense", "paged",      "int8w",  1),
    ("verify",  "dense", "contiguous", None,     1),
    ("verify",  "dense", "paged",      "int8kv", 2),
    ("chunked", "dense", "paged",      None,     2),
    ("chunked", "flash", "contiguous", None,     1),
])
def test_dp2_greedy_matches_dp1(tiny_model_kwargs, program, attend,
                                layout, quant, tp):
    """The tentpole gate: the SAME skewed workload through a dp=2 engine
    (slot axis sharded over dp, params replicated across it) produces
    token streams bit-identical to the dp=1 engine — each program family
    crossed with a representative kernel/layout/quantization corner, on
    tp=1 and tp=2. The paged dp=2 runs retire shard 1's short requests
    early, so the rebalance planner is live inside the measured run."""
    kw = dict(attend_impl=attend, kv_layout=layout)
    if quant == "int8kv":
        kw["cache_dtype"] = "int8"
    elif quant == "int8w":
        kw["weight_dtype"] = "int8"
    base, _ = _run(tiny_model_kwargs, 1, program, tp=tp, **kw)
    got, b2 = _run(tiny_model_kwargs, 2, program, tp=tp, **kw)
    assert got == base, (program, attend, layout, quant, tp)
    st = b2.stats()
    assert st["dp_size"] == 2
    assert st["slots_total"] == 2 * b2.engine.slots_per_shard


def test_dp2_mixed_tenants_match_dp1(tiny_model_kwargs):
    """Mixed-tenant batches (2 LoRA tenants + anonymous base rows in ONE
    continuous batch, per-tenant radix salts) survive the dp split: the
    dp=2 paged engine's per-tenant streams equal the dp=1 engine's."""
    from picotron_tpu.inference import tenancy

    def build(dp):
        c = make_config(tiny_model_kwargs, tp=1, seq=MAX_LEN)
        c.inference.dp_size = dp
        pack = tenancy.AdapterPack(c.model, slots=3, rank=2)
        for t in (1, 2):
            pack.set_slot(t, pack.random_leaves(2, seed=t, scale=0.5))
        eng = InferenceEngine(c, adapters=pack, slots=4,
                              max_seq_len=MAX_LEN, decode_block_len=4,
                              kv_layout="paged")
        return c, eng

    def run(dp):
        c, eng = build(dp)
        b = ContinuousBatcher(eng, _params(c, eng))
        reqs = [Request("a", [1, 2, 3, 4], max_new_tokens=20,
                        tenant="acme", adapter_slot=1),
                Request("b", [9, 8, 7], max_new_tokens=20,
                        tenant="beta", adapter_slot=2),
                Request("c", [11, 12], max_new_tokens=4),
                Request("d", [13, 14, 15], max_new_tokens=4)]
        res = b.run(reqs)
        return {u: r.tokens for u, r in res.items()}

    assert run(2) == run(1)


# --------------------------------------------------------------------------- #
# cross-shard migration: exactness + refcount conservation
# --------------------------------------------------------------------------- #


def _refs_snapshot(p):
    """np copies of every shard pool's refcount array (dp=1: the one
    pool) — the conservation ledger migration tests diff."""
    shards = getattr(p, "shards", None)
    if shards is None:
        return [np.asarray(p.pool.refs).copy()]
    return [np.asarray(sh.pool.refs).copy() for sh in shards]


def _seat(eng, params, cache, slot, prompt):
    kv, logits = eng.prefill(params, prompt)
    cache = eng.insert(cache, kv, slot, len(prompt))
    return cache, int(np.argmax(np.asarray(logits)[0]))


def _decode_rounds(eng, params, cache, last_by_slot, rounds=2):
    """Greedy blocked decode for the occupied slots; returns the per-slot
    token streams. Free slots carry budget 0."""
    n = eng.slots
    streams = {s: [] for s in last_by_slot}
    temp = np.zeros(n, np.float32)
    top_k = np.zeros(n, np.int32)
    top_p = np.ones(n, np.float32)
    eos = np.full(n, -1, np.int32)
    key = jax.random.PRNGKey(0)
    for _ in range(rounds):
        feed = np.zeros(n, np.int32)
        budget = np.zeros(n, np.int32)
        for s, t in last_by_slot.items():
            feed[s], budget[s] = t, eng.decode_block_len
        key, *subs = jax.random.split(key, eng.decode_block_len + 1)
        cache, toks, counts = eng.decode_block(
            params, cache, feed, np.asarray(subs), eos, budget,
            temp, top_k, top_p)
        toks = np.asarray(toks)
        for s in list(last_by_slot):
            got = [int(t) for t in toks[s, :int(np.asarray(counts)[s])]]
            streams[s].extend(got)
            last_by_slot[s] = got[-1]
    return cache, streams


def test_migration_resumes_bit_identical_and_conserves_refs(
        tiny_model_kwargs):
    """Seat a slot on shard 0 of a dp=2 paged engine, decode, migrate it
    to shard 1 through migrate_slot, keep decoding: the full stream must
    equal the never-migrated twin's, the freed source references must
    return to shard 0's pool, and the destination pages must be owed to
    exactly the migrated slot (refcount 1 each)."""
    prompt = [1, 2, 3, 4, 5, 6, 7]

    def run(migrate):
        cfg, eng = _engine(tiny_model_kwargs, 2, kv_layout="paged")
        params = _params(cfg, eng)
        cache = eng.init_cache()
        cache, first = _seat(eng, params, cache, 0, prompt)
        cache, pre = _decode_rounds(eng, params, cache, {0: first},
                                    rounds=1)
        last = pre[0][-1]
        slot = 0
        moved = 0
        if migrate:
            p = eng.paged
            live_before = sum(int(np.sum(r[1:] > 0))
                              for r in _refs_snapshot(p))
            cache, moved = eng.migrate_slot(cache, 0, 2,
                                            prompt_ids=prompt)
            slot = 2
            assert moved > 0
            # shard 1 now owes the slot its pages at refcount 1; the
            # radix re-graft may hold extra references on the prompt's
            # whole pages, so the slot's rows read >= 1
            refs = _refs_snapshot(p)
            npages = p.pages_for(int(p.host_len[2]))
            local = np.asarray(p.shards[1].tables)[p.local_slot(2),
                                                   :npages]
            assert all(refs[1][q] >= 1 for q in local)
            assert int(p.host_len[0]) == 0
            # page count is conserved: the move shifts live pages from
            # shard 0 to shard 1, it never mints or leaks them
            live_after = sum(int(np.sum(r[1:] > 0)) for r in refs)
            assert live_after == live_before
        cache, post = _decode_rounds(eng, params, cache, {slot: last},
                                     rounds=2)
        return pre[0] + post[slot]

    assert run(migrate=True) == run(migrate=False)


def test_migration_after_speculative_verify_exports_accepted_only(
        tiny_model_kwargs):
    """A verify round writes spec_len + 1 rows optimistically; rejected
    drafts strand past the length pointer. Migrating the slot right
    after must export ONLY the accepted prefix — the migrated stream
    equals the unmigrated twin's, drafts rolled back by construction."""
    prompt = [5, 9, 5, 9, 5, 9]

    def run(migrate):
        cfg, eng = _engine(tiny_model_kwargs, 2, kv_layout="paged",
                           spec_len=2)
        params = _params(cfg, eng)
        cache = eng.init_cache()
        cache, first = _seat(eng, params, cache, 0, prompt)
        n = eng.slots
        # one verify round with deliberately-poor drafts (repeat the last
        # token): some columns reject, leaving garbage rows in the pages
        toks = np.zeros((n, eng.spec_len + 1), np.int32)
        toks[0] = [first, first, first]
        budget = np.zeros(n, np.int32)
        budget[0] = 8
        cache, emitted, counts, _acc = eng.verify(
            params, cache, toks, jax.random.PRNGKey(1),
            np.full(n, -1, np.int32), budget, np.zeros(n, np.float32),
            np.zeros(n, np.int32), np.ones(n, np.float32))
        got = [int(t) for t in
               np.asarray(emitted)[0, :int(np.asarray(counts)[0])]]
        slot = 0
        if migrate:
            cache, _ = eng.migrate_slot(cache, 0, 3, prompt_ids=prompt)
            slot = 3
        cache, post = _decode_rounds(eng, params, cache,
                                     {slot: got[-1]}, rounds=2)
        return got + post[slot]

    assert run(migrate=True) == run(migrate=False)


def test_migration_dest_pool_exhaustion_aborts_cleanly(tiny_model_kwargs):
    """Destination shard out of pages: the all-or-nothing allocation
    raises BEFORE anything moves — source slot untouched (length, table
    row), every shard's refcounts byte-identical to the pre-attempt
    snapshot."""
    cfg, eng = _engine(tiny_model_kwargs, 2, kv_layout="paged",
                       kv_page_len=8, kv_num_pages=12)  # 6/shard, 5 usable
    params = _params(cfg, eng)
    cache = eng.init_cache()
    p = eng.paged
    # shard 0: the would-be migrant (3 pages at page_len 8)
    cache, _ = _seat(eng, params, cache, 0, [1 + (i % 9) for i in range(17)])
    # shard 1: slot 2 pins 4 of the 5 usable pages
    cache, _ = _seat(eng, params, cache, 2,
                     [(2 * i) % 11 + 1 for i in range(25)])
    refs_before = _refs_snapshot(p)
    len_before = int(p.host_len[0])
    row_before = np.asarray(p.tables)[0].copy()
    with pytest.raises(paged_kv.PagePoolExhausted):
        eng.migrate_slot(cache, 0, 3)
    for got, want in zip(_refs_snapshot(p), refs_before):
        np.testing.assert_array_equal(got, want)
    assert int(p.host_len[0]) == len_before
    np.testing.assert_array_equal(np.asarray(p.tables)[0], row_before)


def test_migration_dead_peer_exits_77_without_page_leak(
        tiny_model_kwargs, tmp_path):
    """A dp peer whose ClusterMonitor lease went silent is discovered by
    the liveness check BETWEEN the page gather and the donating write:
    the migration exits through the monitor's exit path (the injected
    exit_fn stands in for os._exit(EXIT_CLUSTER_FAILED)) and the except
    arm releases every destination page — a restart finds both pools
    exactly as before the attempt."""
    cfg, eng = _engine(tiny_model_kwargs, 2, kv_layout="paged")
    params = _params(cfg, eng)
    cache = eng.init_cache()
    cache, _ = _seat(eng, params, cache, 0, [1, 2, 3, 4, 5, 6, 7, 8, 9])

    def exit_fn(peer, age):
        raise SystemExit(EXIT_CLUSTER_FAILED)

    m = ClusterMonitor(str(tmp_path), 0, 2, peer_timeout_s=5.0,
                       exit_fn=exit_fn)
    os.makedirs(m.dir, exist_ok=True)
    m._births = {1: time.time() - 60.0}
    with open(m.lease_path(1), "w") as f:
        f.write("3")
    old = time.time() - 30.0
    os.utime(m.lease_path(1), (old, old))
    assert m.check_peers() is not None  # the lease IS stale
    eng.attach_monitor(m)
    refs_before = _refs_snapshot(eng.paged)
    len_before = int(eng.paged.host_len[0])
    with pytest.raises(SystemExit) as ei:
        eng.migrate_slot(cache, 0, 2)
    assert ei.value.code == EXIT_CLUSTER_FAILED
    for got, want in zip(_refs_snapshot(eng.paged), refs_before):
        np.testing.assert_array_equal(got, want)
    assert int(eng.paged.host_len[0]) == len_before
    assert int(eng.paged.host_len[2]) == 0


# --------------------------------------------------------------------------- #
# batcher-level planner: the skewed workload migrates, streams stay exact
# --------------------------------------------------------------------------- #


def test_batcher_rebalance_fires_and_streams_stay_exact(tiny_model_kwargs):
    """The end-to-end planner path ``make dp-smoke`` gates, pinned in
    tier-1: long streams land on shard 0, shard 1's short streams retire,
    the watermark trips, ONE slot migrates cross-shard mid-run — and
    every stream still equals the dp=1 baseline. The migration counters
    and per-shard occupancy gauges land in stats()/the registry."""
    base, _ = _run(tiny_model_kwargs, 1, "block", kv_layout="paged",
                   kv_page_len=8)
    got, b = _run(tiny_model_kwargs, 2, "block", kv_layout="paged",
                  kv_page_len=8)
    assert got == base
    st = b.stats()
    assert st["rebalance_count"] >= 1
    assert st["rebalance_bytes"] > 0
    assert st["slots_total"] == 4 and st["dp_size"] == 2
    assert len(st["shard_occupancy"]) == 2
    b.refresh_gauges()
    prom = b.obs.registry.prometheus()
    assert "picotron_dp_size 2" in prom
    assert 'picotron_shard_occupancy{shard="0"}' in prom
    assert 'picotron_shard_occupancy{shard="1"}' in prom
    assert ('picotron_slot_migrations_total{outcome="ok"}' in prom)


def test_dp1_default_unchanged(tiny_model_kwargs):
    """inference.dp_size defaults to 1 and the dp=1 engine reports the
    degenerate topology — one shard holding every slot, planner inert —
    while stats()/gauges still carry the (trivial) dp fields so scrapers
    see one schema."""
    cfg, eng = _engine(tiny_model_kwargs, 1, kv_layout="paged")
    assert cfg.inference.dp_size == 1
    assert eng.slots_per_shard == eng.slots
    b = ContinuousBatcher(eng, _params(cfg, eng))
    res = b.run([Request("r", [1, 2, 3], max_new_tokens=6)])
    assert res["r"].finish_reason == "length"
    st = b.stats()
    assert st["dp_size"] == 1
    assert st["shard_occupancy"] == [0]
    assert st["rebalance_count"] == 0
