"""Multi-step (on-device lax.scan training loop) vs single-step equivalence:
K fused steps must produce the same per-step losses and the same final state
as K separate dispatches (picotron_tpu/train_step.py build_train_step)."""

import pytest

import jax
import numpy as np

from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.topology import topology_from_config

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("fsdp", [False, True], ids=["plain", "fsdp"])
def test_multi_step_matches_single(cfg_factory, fsdp):
    cfg = cfg_factory(dp=2, seq=32, mbs=2, fsdp=fsdp)
    topo = topology_from_config(cfg)
    K, rounds = 3, 2

    p1, o1 = ts.init_state(cfg, topo)
    step1 = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    losses1 = []
    for _ in range(K * rounds):
        tok, tgt = ts.shard_batch(next(loader), topo)
        p1, o1, l = step1(p1, o1, tok, tgt)
        losses1.append(float(l))

    p2, o2 = ts.init_state(cfg, topo)
    stepK = ts.build_train_step(cfg, topo, multi_step=K)
    loader = MicroBatchDataLoader(cfg)
    losses2 = []
    for _ in range(rounds):
        tok, tgt = ts.shard_batch_stack([next(loader) for _ in range(K)], topo)
        p2, o2, ls = stepK(p2, o2, tok, tgt)
        losses2.extend(float(x) for x in ls)

    np.testing.assert_allclose(losses2, losses1, rtol=2e-5, atol=1e-6)
    flat1 = jax.tree.leaves(p1)
    flat2 = jax.tree.leaves(p2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-5, atol=1e-6)


def test_train_max_tokens_caps_group(cfg_factory):
    """A token budget smaller than one spc-group must not overtrain: the
    trainer falls back to single steps near the budget."""
    from picotron_tpu.train import train

    cfg = cfg_factory(seq=32, mbs=2, total_train_steps=100)
    cfg.training.steps_per_call = 4
    cfg.training.max_tokens = 2 * cfg.tokens_per_step
    step, tokens, _ = train(cfg)
    assert step == 2
    assert tokens == cfg.training.max_tokens


def test_train_saves_once_with_steps_per_call(cfg_factory, tmp_path):
    """steps_per_call=4 with save_frequency=5 and 8 steps: the boundary save
    at step 8 must not be duplicated by the end-of-run save."""
    from picotron_tpu.checkpoint import CheckpointManager
    from picotron_tpu.train import train

    cfg = cfg_factory(seq=32, mbs=2, total_train_steps=8)
    cfg.training.steps_per_call = 4
    cfg.checkpoint.save_dir = str(tmp_path / "ck")
    cfg.checkpoint.save_frequency = 5
    step, _, _ = train(cfg)
    assert step == 8
    mgr = CheckpointManager(cfg.checkpoint.save_dir)
    assert mgr.latest_step() == 8
    mgr.close()


def test_train_cli_steps_per_call(cfg_factory, tmp_path, capsys):
    """The trainer with steps_per_call=2 logs every step and trains to the
    same token count; a non-multiple total exercises the single-step tail."""
    from picotron_tpu.train import train

    cfg = cfg_factory(seq=32, mbs=2, total_train_steps=5)
    cfg.training.steps_per_call = 2
    step, tokens, loss = train(cfg)
    assert step == 5
    assert tokens == 5 * cfg.tokens_per_step
    out = capsys.readouterr().out
    for s in range(1, 6):
        assert f"Step: {s}" in out
    assert np.isfinite(loss)
