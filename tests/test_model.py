"""Single-device model + train-step basics: shapes, determinism, learning."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.models import llama
from picotron_tpu.topology import topology_from_config
from picotron_tpu.utils import shard_map as shard_map_compat


def test_forward_shapes(cfg_factory):
    cfg = cfg_factory()
    topo = topology_from_config(cfg)
    params, _ = ts.init_state(cfg, topo)
    tokens = jnp.zeros((2, cfg.training.seq_length), jnp.int32)
    fwd = jax.jit(
        shard_map_compat(
            lambda p, t: llama.forward_logits(p, t, cfg),
            mesh=topo.mesh,
            in_specs=(llama.param_pspecs(cfg.model), jax.sharding.PartitionSpec()),
            out_specs=jax.sharding.PartitionSpec(),
            check_vma=False,
        )
    )
    logits = fwd(params, tokens)
    assert logits.shape == (2, cfg.training.seq_length, cfg.model.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_init_deterministic(cfg_factory):
    cfg = cfg_factory()
    topo = topology_from_config(cfg)
    p1, _ = ts.init_state(cfg, topo)
    p2, _ = ts.init_state(cfg, topo)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_loss_decreases_single_device(cfg_factory):
    cfg = cfg_factory(seq=64, mbs=4)
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    losses = []
    for _ in range(30):
        batch = next(loader)
        tokens, targets = ts.shard_batch(batch, topo)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    # synthetic affine-bigram corpus: model must learn transitions fast
    assert losses[-1] < losses[0] - 0.5, losses


@pytest.mark.slow
def test_grad_accumulation_matches_large_batch(cfg_factory):
    """acc=4 x mbs=1 must equal acc=1 x mbs=4 grads-wise: compare one step's
    loss trajectory (same data, same total batch)."""
    cfg_a = cfg_factory(seq=32, mbs=4, acc=1)
    cfg_b = cfg_factory(seq=32, mbs=1, acc=4)
    topo = topology_from_config(cfg_a)
    pa, oa = ts.init_state(cfg_a, topo)
    pb, ob = ts.init_state(cfg_b, topo)
    step_a = ts.build_train_step(cfg_a, topo)
    step_b = ts.build_train_step(cfg_b, topo)
    rows = np.random.default_rng(0).integers(
        0, cfg_a.model.vocab_size, (4, 33), dtype=np.int32)
    batch_a = {"input_ids": rows[None, :, :-1], "target_ids": rows[None, :, 1:]}
    batch_b = {"input_ids": rows[:, None, :-1], "target_ids": rows[:, None, 1:]}
    ta, tga = ts.shard_batch(batch_a, topo)
    tb, tgb = ts.shard_batch(batch_b, topo)
    pa, oa, loss_a = step_a(pa, oa, ta, tga)
    pb, ob, loss_b = step_b(pb, ob, tb, tgb)
    assert abs(float(loss_a) - float(loss_b)) < 1e-5
    for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_forward_logits_zigzag_layout_roundtrip(cfg_factory):
    """The documented zigzag contract: zigzag-permuted tokens through a
    cp_zigzag forward, logits un-permuted with zigzag_inverse_perm, must
    match the plain single-device forward on the original tokens."""
    from jax.sharding import PartitionSpec as P

    from picotron_tpu.parallel.cp import zigzag_inverse_perm, zigzag_perm

    seq = 32
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, seq)), jnp.int32)

    def logits_for(cfg, toks, **fwd_kw):
        topo = topology_from_config(cfg)
        params, _ = ts.init_state(cfg, topo)
        fwd = jax.jit(shard_map_compat(
            lambda p, t: llama.forward_logits(p, t, cfg, **fwd_kw),
            mesh=topo.mesh,
            in_specs=(llama.param_pspecs(cfg.model), P(None, "cp")),
            out_specs=P(None, "cp"),
            check_vma=False))
        return np.asarray(fwd(params, toks))

    ref = logits_for(cfg_factory(seq=seq, mbs=2), tokens)

    cfg_z = cfg_factory(cp=2, zigzag=True, seq=seq, mbs=2)
    perm = zigzag_perm(seq, 2)
    inv = zigzag_inverse_perm(seq, 2)
    zig = logits_for(cfg_z, tokens[:, perm], seq_layout="zigzag")
    np.testing.assert_allclose(zig[:, inv], ref, rtol=2e-5, atol=2e-5)

    # the contract is LOUD: a zigzag config without the acknowledgement
    # raises instead of silently computing with wrong positions/masks,
    # and claiming zigzag on a non-zigzag config is equally an error
    with pytest.raises(ValueError, match="zigzag"):
        logits_for(cfg_z, tokens[:, perm])
    with pytest.raises(ValueError, match="zigzag"):
        logits_for(cfg_factory(seq=seq, mbs=2), tokens,
                   seq_layout="zigzag")


@pytest.mark.slow
def test_remat_modes_do_not_change_math(cfg_factory):
    """remat trades memory for recompute (or, for "offload", host-link
    bandwidth); all four modes must produce the identical loss trajectory
    (fp32, sdpa path: save_attn's checkpoint names simply match nothing
    and degrade to full; offload parks the decoder_layer-tagged residuals
    in pinned host memory — a real memory-space move even on the CPU
    backend)."""
    from test_parallel import run_losses

    ref = None
    for remat in ("none", "full", "save_attn", "offload"):
        cfg = cfg_factory(seq=32, mbs=4)
        cfg.training.remat = remat
        got = run_losses(cfg, steps=4)
        if ref is None:
            ref = got
        else:
            np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6,
                                       err_msg=f"remat={remat}")


@pytest.mark.slow
def test_offload_remat_on_sharded_topology(cfg_factory):
    """remat='offload' (pinned-host residuals) composes with a 3D mesh +
    sequence parallelism: same loss trajectory as single-device remat=none
    (the offload is a memory-space move, not a math change)."""
    from test_parallel import run_losses

    ref = run_losses(cfg_factory(seq=32, mbs=4), steps=4)
    cfg = cfg_factory(dp=2, cp=2, tp=2, sp=True, seq=32, mbs=2,
                      remat="offload")
    got = run_losses(cfg, steps=4)
    np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-5)
