"""bf16 + grad-accumulation: fp32 accumulation across microbatches.

The reference accumulates microbatch grads in a fp32 main_grad regardless of
the compute dtype (data_parallel.py:66,81). Both pipeline engines here do the
same — 1F1B by construction (fp32 gacc in parallel/pp.py), AFAB via the
fp32-master-params cast trick (pipeline_afab) — so with bf16 compute and a
deep accumulation (acc=8) the two engines' loss trajectories must agree to
bf16 compute noise, and training must still learn.
"""

import pytest

import numpy as np

from conftest import make_config
from test_parallel import run_losses

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow


def test_afab_matches_1f1b_bf16_acc8(tiny_model_kwargs):
    kw = dict(pp=2, acc=8, mbs=1, seq=32, dtype="bfloat16")

    def cfg_for(engine):
        cfg = make_config(tiny_model_kwargs, engine=engine, **kw)
        cfg.training.learning_rate = 3e-3
        return cfg

    l_afab = run_losses(cfg_for("afab"), steps=8)
    l_1f1b = run_losses(cfg_for("1f1b"), steps=8)
    # bf16 compute: the engines order matmuls/reductions differently, so the
    # tolerance is bf16-epsilon-scale, far tighter than bf16 accumulation
    # drift over 8 microbatches would allow
    np.testing.assert_allclose(l_afab, l_1f1b, rtol=0.02, atol=0.02)
    assert l_afab[-1] < l_afab[0] - 0.4, f"bf16 training did not learn: {l_afab}"


def test_param_dtype_accum_with_pipelines(tiny_model_kwargs):
    """grad_accum_dtype='param' (bf16 accumulators — the opt-in that halves
    grad memory and lets 7B fit v5e HBM, docs/PROJECTION.md) now works with
    every pipeline engine: all three must track the pp=1 param-accum
    trajectory to bf16 tolerance and still learn."""
    kw = dict(acc=4, mbs=1, seq=32, dtype="bfloat16",
              grad_accum_dtype="param")

    def cfg_for(pp, engine="1f1b", interleave=1, **over):
        cfg = make_config(tiny_model_kwargs, pp=pp, engine=engine,
                          interleave=interleave, **dict(kw, **over))
        cfg.training.learning_rate = 3e-3
        return cfg

    base = run_losses(cfg_for(pp=1), steps=8)
    for variant, cfg in [
        ("1f1b", cfg_for(pp=2)),
        ("afab", cfg_for(pp=2, engine="afab")),
        ("interleaved", cfg_for(pp=2, interleave=2)),
    ]:
        got = run_losses(cfg, steps=8)
        np.testing.assert_allclose(got, base, rtol=0.02, atol=0.02,
                                   err_msg=variant)
    # bf16 accumulators at acc=4 are noisy on the tiny model; demand a clear
    # downward trend, not the fp32 test's drop
    assert min(base[-3:]) < base[0] - 0.15, (
        f"param-accum training did not learn: {base}")
