"""Uneven pipeline stage splits (reference pipeline_parallel.py:33-36).

The reference hands remainder layers to the earliest stages; the SPMD
pipeline realises the same distribution with a masked padded layer stack
(models/llama.py::pp_layer_layout). The oracle is the same as
test_parallel: an uneven split must reproduce the single-device loss
trajectory exactly (same real-layer weights by construction of init_params).
"""

import numpy as np
import pytest

from conftest import make_config
from picotron_tpu.models.llama import pp_layer_layout
from test_parallel import run_losses

# multi-minute equivalence/e2e matrices: excluded from `make test`
pytestmark = pytest.mark.slow


def test_layout_matches_reference_rule():
    # 32 layers / pp=5: reference gives 7,7,6,6,6 (remainder to earliest)
    K, counts, positions = pp_layer_layout(32, 5)
    assert counts == [7, 7, 6, 6, 6]
    assert K == 7 and len(positions) == 32 and len(set(positions)) == 32
    # stage 1's first real layer is global layer 7, at padded row K*1
    assert positions[7] == 7
    # stage 2 starts at global layer 14 -> padded row 2*K
    assert positions[14] == 2 * K


@pytest.mark.parametrize("engine", ["1f1b", "afab"])
def test_uneven_pp_matches_single_device(tiny_model_kwargs, engine):
    model = dict(tiny_model_kwargs, num_hidden_layers=5)
    ref = run_losses(make_config(model, seq=32, mbs=4))
    got = run_losses(make_config(model, pp=2, acc=2, mbs=2, seq=32,
                                 engine=engine))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_uneven_pp4(tiny_model_kwargs):
    # 5 layers on 4 stages: counts 2,1,1,1 -> 3 pad rows
    model = dict(tiny_model_kwargs, num_hidden_layers=5)
    ref = run_losses(make_config(model, seq=32, mbs=4))
    got = run_losses(make_config(model, pp=4, acc=4, mbs=1, seq=32))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
