"""Flash-decode kernel parity: Pallas fused KV-cache attention vs dense.

The flash path (``ops/pallas/decode_attention.py``, selected by
``inference.attend_impl: "flash"``) must be allclose to the dense
whole-window reference (``kv_cache.decode_attention``) everywhere the
engine can reach it — S = 1 blocked decode, S > 1 speculative verify,
B = 1 chunked prefill — for bf16/fp32 AND int8 caches, across ragged
lengths, stale rows beyond the length mask, GQA head groupings down to
nkv = 1, and cache windows that are not a multiple of the KV block. The
kernel runs in Pallas interpret mode here (the CPU tier-1 gate;
``make kernel-smoke`` runs just this file); the same program lowers to
Mosaic on a chip.

Unit tests drive the kernel directly; the engine tests run the full jitted
dispatch (shard_map + layer scan) under both impls and pin identical
generations — the wiring proof that ``attend_impl`` reaches all three call
sites.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.inference import InferenceEngine, kv_cache
from picotron_tpu.inference.kv_cache import (
    decode_attention,
    dequantize_kv,
    quantize_kv,
)
from picotron_tpu.models import llama
from picotron_tpu.ops.pallas.decode_attention import (
    _pick_block_t,
    flash_decode_attention,
)

MAX_LEN = 96


# --------------------------------------------------------------------------- #
# kernel-level parity (direct calls, interpret mode)
# --------------------------------------------------------------------------- #


def _blocks(rng, B, T, nh, nkv, D, S, dtype, quantized):
    """Random q + cache blocks (+ scales when quantized) and the dense
    reference inputs (the dequantized fp32 view for int8)."""
    q = jnp.asarray(rng.normal(size=(B, S, nh, D)).astype(np.float32))
    k = rng.normal(size=(B, T, nkv, D)).astype(np.float32)
    v = rng.normal(size=(B, T, nkv, D)).astype(np.float32)
    if quantized:
        qk, ks = quantize_kv(jnp.asarray(k))
        qv, vs = quantize_kv(jnp.asarray(v))
        dense_k = dequantize_kv(qk, ks, jnp.float32)
        dense_v = dequantize_kv(qv, vs, jnp.float32)
        return q, (qk, qv, ks, vs), (dense_k, dense_v)
    dt = jnp.dtype(dtype)
    kj, vj = jnp.asarray(k, dt), jnp.asarray(v, dt)
    return q.astype(dt), (kj, vj, None, None), (kj, vj)


def _assert_parity(q, stored, dense_kv, lengths, block_t, tol):
    k, v, ks, vs = stored
    scale = q.shape[-1] ** -0.5
    want = np.asarray(
        decode_attention(q, dense_kv[0], dense_kv[1], lengths, scale),
        np.float32)
    got = np.asarray(
        flash_decode_attention(q, k, v, lengths, scale, k_scale=ks,
                               v_scale=vs, block_t=block_t, interpret=True),
        np.float32)
    live = np.asarray(lengths) > 0
    np.testing.assert_allclose(got[live], want[live], rtol=tol, atol=tol)
    # fully-masked rows are DEFINED as zeros on the flash path (the dense
    # kernel emits an equally-unconsumed uniform average there)
    assert np.all(got[~live] == 0.0)
    return got


@pytest.mark.parametrize("cache_dtype,tol", [
    ("float32", 1e-5), ("bfloat16", 2e-2), ("int8", 1e-5)])
@pytest.mark.parametrize("S", [1, 4])
def test_flash_matches_dense_decode_and_verify(cache_dtype, S, tol):
    """S=1 decode and S=4 (spec_len+1) verify shapes: ragged lengths
    including a fresh slot (0), an S-length slot, and a full window, on
    the GQA 8q/4kv grouping, for all three cache dtypes."""
    rng = np.random.default_rng(0)
    B, T, nh, nkv, D = 4, 64, 8, 4, 16
    q, stored, dense_kv = _blocks(rng, B, T, nh, nkv, D, S,
                                  cache_dtype, cache_dtype == "int8")
    if cache_dtype == "bfloat16":
        q = q.astype(jnp.bfloat16)
    lengths = jnp.asarray([0, S, 29, T], jnp.int32)
    _assert_parity(q, stored, dense_kv, lengths, 16, tol)


@pytest.mark.parametrize("quantized", [False, True])
def test_flash_matches_dense_chunked_prefill(quantized):
    """The B=1, S=chunk call shape: queries attend over the cache prefix
    plus their own freshly-written block (lengths = start + chunk)."""
    rng = np.random.default_rng(1)
    B, T, nh, nkv, D, S = 1, MAX_LEN, 8, 4, 16, 16
    q, stored, dense_kv = _blocks(rng, B, T, nh, nkv, D, S,
                                  "float32", quantized)
    for length in (S, 40, MAX_LEN):  # first chunk, mid-prompt, full window
        _assert_parity(q, stored, dense_kv,
                       jnp.asarray([length], jnp.int32), 32, 1e-5)


def test_gqa_single_kv_head():
    """nkv=1 (every q head in one group) — the widest grouping the fold
    must handle."""
    rng = np.random.default_rng(2)
    q, stored, dense_kv = _blocks(rng, 2, 32, 4, 1, 8, 1, "float32", True)
    _assert_parity(q, stored, dense_kv, jnp.asarray([5, 32], jnp.int32),
                   8, 1e-5)


def test_window_not_multiple_of_block():
    """T=40 with a requested block of 16 halves to 8 (the static DMA slice
    must tile the window); ragged lengths hit the partial-live block."""
    assert _pick_block_t(40, 16) == 8
    # wide chunked-prefill query groups trade KV-block depth for rows so
    # the fp32 score tile stays inside the VMEM budget
    assert _pick_block_t(4096, 256, rows=4096) == 64
    rng = np.random.default_rng(3)
    q, stored, dense_kv = _blocks(rng, 3, 40, 8, 4, 16, 1, "float32", False)
    _assert_parity(q, stored, dense_kv, jnp.asarray([1, 23, 40], jnp.int32),
                   16, 1e-5)


def test_lengths_past_window_clamped():
    """At the cache-window edge the engine's write-then-attend convention
    can pass lengths = pos + S > T (the scatter dropped the OOB rows); the
    block walk must clamp to the window instead of DMA'ing past it, and
    still match dense (whose mask absorbs the same case)."""
    rng = np.random.default_rng(6)
    q, stored, dense_kv = _blocks(rng, 2, 32, 8, 4, 16, 2, "float32", False)
    _assert_parity(q, stored, dense_kv, jnp.asarray([33, 34], jnp.int32),
                   8, 1e-5)


def test_stale_rows_beyond_mask_invisible():
    """Rows past ``lengths`` (a speculative rollback's rejected drafts, a
    freed slot's leftovers) are poisoned with huge values; the flash output
    must not move — the mask, not luck, keeps them out."""
    rng = np.random.default_rng(4)
    B, T, nh, nkv, D = 2, 48, 8, 4, 16
    q, (k, v, _, _), _ = _blocks(rng, B, T, nh, nkv, D, 1, "float32", False)
    lengths = jnp.asarray([7, 31], jnp.int32)
    scale = D ** -0.5
    clean = flash_decode_attention(q, k, v, lengths, scale, block_t=16,
                                   interpret=True)
    rows = np.arange(T)[None, :, None, None] >= np.asarray(lengths)[
        :, None, None, None]
    poison = jnp.where(rows, 1e4, 0.0).astype(k.dtype)
    dirty = flash_decode_attention(q, k + poison, v + poison, lengths,
                                   scale, block_t=16, interpret=True)
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(dirty))


def test_flash_path_never_materializes_dequantized_cache(monkeypatch):
    """The int8 flash attend must read int8 bytes + scales inside the
    kernel — if it ever routed through ``dequantize_kv`` (the dense path's
    whole-block fp32 materialization) this raises."""
    rng = np.random.default_rng(5)
    q, (k, v, ks, vs), (dk, dv) = _blocks(rng, 2, 32, 8, 4, 16, 1,
                                          "float32", True)
    cache = {"k": k, "v": v, "k_scale": ks, "v_scale": vs}
    lengths = jnp.asarray([9, 20], jnp.int32)
    want = np.asarray(kv_cache.attend(q, cache, lengths, 0.25, impl="dense"))

    def boom(*a, **kw):
        raise AssertionError("flash attend materialized a dequantized copy")

    monkeypatch.setattr(kv_cache, "dequantize_kv", boom)
    got = np.asarray(kv_cache.attend(q, cache, lengths, 0.25, impl="flash"))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------- #
# double-buffered DMA: pipelined fetches are bitwise the serial kernel
# --------------------------------------------------------------------------- #


def _paged_blocks(rng, B, maxp, plen, nkv, D, S, nh, quantized):
    """A page pool + shuffled block tables + the dense gathered window."""
    P = 2 + B * maxp
    q = jnp.asarray(rng.normal(size=(B, S, nh, D)).astype(np.float32))
    pk = rng.normal(size=(P, plen, nkv, D)).astype(np.float32)
    pv = rng.normal(size=(P, plen, nkv, D)).astype(np.float32)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, P))[: B * maxp].reshape(B, maxp),
        jnp.int32)
    if quantized:
        qk, ks = quantize_kv(jnp.asarray(pk))
        qv, vs = quantize_kv(jnp.asarray(pv))
        dk = dequantize_kv(qk, ks, jnp.float32)
        dv = dequantize_kv(qv, vs, jnp.float32)
        stored = (qk, qv, ks, vs)
    else:
        stored = (jnp.asarray(pk), jnp.asarray(pv), None, None)
        dk, dv = stored[0], stored[1]
    gather = lambda pool: pool[tables].reshape(B, maxp * plen, *pool.shape[2:])
    return q, stored, (gather(dk), gather(dv)), tables


@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("T,block_t,lengths", [
    (16, 16, [16, 7]),        # single block: the whole window is one DMA
    (48, 16, [48, 33]),       # odd block count (3)
    (40, 16, [1, 23]),        # T % block_t != 0 (block halves to 8)
    (32, 8, [0, 0]),          # nothing live: zero iterations, zeros out
    (32, 8, [0, 29]),         # fresh slot riding next to a live one
])
def test_double_buffer_matches_serial_and_dense_contiguous(
        T, block_t, lengths, quantized):
    """The pipelined (two-buffer, prefetch-j+1) walk must be BITWISE the
    serial walk — same blocks, same order, same fp32 math — and allclose
    to dense, across the nasty window shapes and int8 scales."""
    rng = np.random.default_rng(10)
    B, nh, nkv, D, S = 2, 8, 4, 16, 1
    q, stored, dense_kv = _blocks(rng, B, T, nh, nkv, D, S,
                                  "float32", quantized)
    lengths = jnp.asarray(lengths, jnp.int32)
    piped = _assert_parity(q, stored, dense_kv, lengths, block_t, 1e-5)
    k, v, ks, vs = stored
    serial = np.asarray(flash_decode_attention(
        q, k, v, lengths, q.shape[-1] ** -0.5, k_scale=ks, v_scale=vs,
        block_t=block_t, pipeline=False, interpret=True))
    np.testing.assert_array_equal(piped, serial)


@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("maxp,plen,lengths", [
    (1, 16, [16, 5]),         # single page per slot
    (3, 8, [24, 17]),         # odd page count
    (4, 8, [0, 31]),          # fresh slot + nearly-full slot
])
def test_double_buffer_matches_serial_and_dense_paged(
        maxp, plen, lengths, quantized):
    """The paged walk (one DMA per pool page through the block table)
    under the same discipline: pipelined == serial bitwise, both allclose
    to the dense gathered-window reference, fp32 and int8 pools."""
    rng = np.random.default_rng(11)
    B, nh, nkv, D, S = 2, 8, 4, 16, 1
    q, stored, dense_kv, tables = _paged_blocks(
        rng, B, maxp, plen, nkv, D, S, nh, quantized)
    lengths = jnp.asarray(lengths, jnp.int32)
    scale = q.shape[-1] ** -0.5
    k, v, ks, vs = stored
    want = np.asarray(
        decode_attention(q, dense_kv[0], dense_kv[1], lengths, scale))
    outs = {}
    for pipeline in (True, False):
        outs[pipeline] = np.asarray(flash_decode_attention(
            q, k, v, lengths, scale, k_scale=ks, v_scale=vs,
            block_tables=tables, pipeline=pipeline, interpret=True))
    np.testing.assert_array_equal(outs[True], outs[False])
    live = np.asarray(lengths) > 0
    np.testing.assert_allclose(outs[True][live], want[live],
                               rtol=1e-5, atol=1e-5)
    assert np.all(outs[True][~live] == 0.0)


def test_double_buffer_verify_shape():
    """The S>1 verify shape under pipelining: ragged lengths including a
    row with lengths < S (leading fully-masked query rows)."""
    rng = np.random.default_rng(12)
    q, stored, dense_kv = _blocks(rng, 3, 48, 8, 4, 16, 4, "float32", True)
    lengths = jnp.asarray([4, 30, 48], jnp.int32)
    piped = _assert_parity(q, stored, dense_kv, lengths, 16, 1e-5)
    k, v, ks, vs = stored
    serial = np.asarray(flash_decode_attention(
        q, k, v, lengths, q.shape[-1] ** -0.5, k_scale=ks, v_scale=vs,
        block_t=16, pipeline=False, interpret=True))
    np.testing.assert_array_equal(piped, serial)


# --------------------------------------------------------------------------- #
# flash chunked prefill: the q-blocked grid (flash_attention machinery)
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("quantized", [False, True])
def test_chunk_q_blocking_matches_dense(quantized):
    """B=1 chunk windows wide enough to split over the q grid axis
    (block_q below S*g forces multiple q-tiles): every tile walks only
    its causal band's KV blocks and the assembled output is allclose to
    dense — first chunk, mid-prompt resume, ragged final, full window."""
    rng = np.random.default_rng(13)
    B, T, nh, nkv, D, S = 1, MAX_LEN, 8, 4, 16, 24
    q, stored, dense_kv = _blocks(rng, B, T, nh, nkv, D, S,
                                  "float32", quantized)
    k, v, ks, vs = stored
    scale = D ** -0.5
    for length in (S, 40, 61, MAX_LEN):
        lengths = jnp.asarray([length], jnp.int32)
        want = np.asarray(
            decode_attention(q, dense_kv[0], dense_kv[1], lengths, scale))
        got = np.asarray(flash_decode_attention(
            q, k, v, lengths, scale, k_scale=ks, v_scale=vs,
            block_t=16, block_q=16, interpret=True))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        # q-blocked == single-tile (the pre-blocking layout)
        one = np.asarray(flash_decode_attention(
            q, k, v, lengths, scale, k_scale=ks, v_scale=vs,
            block_t=16, interpret=True))
        np.testing.assert_allclose(got, one, rtol=1e-6, atol=1e-6)


def test_chunk_q_blocking_paged():
    """The paged chunk shape (prefix-sharing resume attends over pages
    the chunk never wrote) with q-tiles narrower than the window."""
    rng = np.random.default_rng(14)
    B, nh, nkv, D, S = 1, 8, 4, 16, 16
    q, stored, dense_kv, tables = _paged_blocks(
        rng, B, 6, 8, nkv, D, S, nh, False)
    k, v, _, _ = stored
    scale = D ** -0.5
    for length in (S, 37, 48):
        lengths = jnp.asarray([length], jnp.int32)
        want = np.asarray(
            decode_attention(q, dense_kv[0], dense_kv[1], lengths, scale))
        got = np.asarray(flash_decode_attention(
            q, k, v, lengths, scale, block_tables=tables, block_q=16,
            interpret=True))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------- #
# engine-level wiring: attend_impl reaches all three jitted call sites
# --------------------------------------------------------------------------- #


def _engine(tiny_model_kwargs, impl, **kw):
    cfg = make_config(tiny_model_kwargs, tp=1, seq=MAX_LEN)
    return cfg, InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                                attend_impl=impl, **kw)


def _params(cfg, engine):
    p = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(0))
    return engine.shard_params(p)


@pytest.mark.parametrize("cache_dtype", [None, "int8"])
def test_engine_flash_decode_block_matches_dense(tiny_model_kwargs,
                                                 cache_dtype):
    """The blocked decode dispatch (S=1 site) generates the same greedy
    tokens under both impls, fp32 and int8 caches."""
    outs = {}
    for impl in ("dense", "flash"):
        cfg, eng = _engine(tiny_model_kwargs, impl, decode_block_len=4,
                           cache_dtype=cache_dtype)
        params = _params(cfg, eng)
        cache = eng.init_cache()
        kv, logits = eng.prefill(params, list(range(1, 9)))
        cache = eng.insert(cache, kv, 0, 8)
        toks = np.array([int(np.argmax(np.asarray(logits)[0])), 0], np.int32)
        keys = jnp.stack([jax.random.PRNGKey(7)] * 4)
        cache, blk, counts = eng.decode_block(
            params, cache, toks, keys, np.full(2, -1, np.int32),
            np.array([8, 0], np.int32), np.zeros(2, np.float32),
            np.zeros(2, np.int32), np.ones(2, np.float32))
        outs[impl] = (np.asarray(blk), np.asarray(counts),
                      np.asarray(cache["lengths"]))
    for a, b in zip(outs["dense"], outs["flash"]):
        np.testing.assert_array_equal(a, b)
    assert outs["flash"][1].tolist() == [4, 0]  # free slot stayed inert


def test_engine_flash_verify_matches_dense(tiny_model_kwargs):
    """The speculative verify dispatch (S>1, B>1 site): same emitted
    tokens, counts, accepted-draft counts, and length pointers."""
    outs = {}
    for impl in ("dense", "flash"):
        cfg, eng = _engine(tiny_model_kwargs, impl, spec_len=3)
        params = _params(cfg, eng)
        cache = eng.init_cache()
        for slot in (0, 1):
            kv, logits = eng.prefill(params, list(range(1 + slot, 9 + slot)))
            cache = eng.insert(cache, kv, slot, 8)
        tokens = np.array([[3, 5, 7, 9], [4, 6, 8, 10]], np.int32)
        cache, emitted, counts, accepted = eng.verify(
            params, cache, tokens, jax.random.PRNGKey(3),
            np.full(2, -1, np.int32), np.full(2, 8, np.int32),
            np.zeros(2, np.float32), np.zeros(2, np.int32),
            np.ones(2, np.float32))
        outs[impl] = tuple(np.asarray(x) for x in
                           (emitted, counts, accepted, cache["lengths"]))
    for a, b in zip(outs["dense"], outs["flash"]):
        np.testing.assert_array_equal(a, b)


def test_engine_flash_chunked_prefill_matches_dense(tiny_model_kwargs):
    """The chunked-prefill dispatch (B=1, S=chunk site): final-chunk logits
    agree across impls AND with the one-shot prefill oracle (ragged final
    chunk included: 20 tokens over width-8 chunks)."""
    prompt = [(5 * i + 2) % 199 + 1 for i in range(20)]
    logits = {}
    for impl in ("dense", "flash"):
        cfg, eng = _engine(tiny_model_kwargs, impl, prefill_chunk=8)
        params = _params(cfg, eng)
        cache, last = eng.prefill_chunked(params, eng.init_cache(),
                                          prompt, slot=1)
        assert int(np.asarray(cache["lengths"])[1]) == len(prompt)
        logits[impl] = np.asarray(last)[0]
        if impl == "dense":
            _, oneshot = eng.prefill(params, prompt)
    np.testing.assert_allclose(logits["flash"], logits["dense"],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(logits["dense"], np.asarray(oneshot)[0],
                               rtol=1e-4, atol=1e-4)


def test_engine_flash_matches_dense_tp2(tiny_model_kwargs):
    """On a tp=2 dryrun mesh the cache's kv-head axis is sharded, so each
    shard's kernel instance sees the LOCAL head count — greedy decode must
    still match dense exactly."""
    tokens = {}
    for impl in ("dense", "flash"):
        cfg = make_config(dict(tiny_model_kwargs, num_hidden_layers=2),
                          tp=2, seq=MAX_LEN)
        eng = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                              attend_impl=impl)
        params = _params(cfg, eng)
        cache = eng.init_cache()
        kv, logits = eng.prefill(params, list(range(1, 9)))
        cache = eng.insert(cache, kv, 0, 8)
        toks = np.array([int(np.argmax(np.asarray(logits)[0])), 0],
                        np.int32)
        got, key = [], jax.random.PRNGKey(1)
        for _ in range(4):
            key, sub = jax.random.split(key)
            cache, toks, _ = eng.decode_step(
                params, cache, toks, sub, np.zeros(2, np.float32),
                np.zeros(2, np.int32), np.ones(2, np.float32))
            toks = np.asarray(toks)
            got.append(int(toks[0]))
        tokens[impl] = got
    assert tokens["dense"] == tokens["flash"]


def test_attend_impl_validated(tiny_model_kwargs):
    """Bad impl strings fail loudly at engine build and config load."""
    cfg = make_config(tiny_model_kwargs, tp=1, seq=MAX_LEN)
    with pytest.raises(ValueError, match="attend_impl"):
        InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                        attend_impl="paged")
    raw = cfg.to_dict()
    raw["inference"]["attend_impl"] = "paged"
    from picotron_tpu.config import Config

    with pytest.raises(ValueError, match="attend_impl"):
        Config.from_dict(raw)
    # the attend helper itself must not silently fall through to dense
    q = jnp.zeros((1, 1, 2, 4))
    cache = {"k": jnp.zeros((1, 8, 2, 4)), "v": jnp.zeros((1, 8, 2, 4))}
    with pytest.raises(ValueError, match="attend impl"):
        kv_cache.attend(q, cache, jnp.ones(1, jnp.int32), 0.5, impl="Flash")
