"""Force an 8-virtual-device CPU platform before JAX initializes a backend.

This is the TPU rebuild's equivalent of the reference's CPU/Gloo fake-cluster
path (reference README.md:40-47, train.py:83): every parallelism test runs as
a real multi-device program on one host. SURVEY.md §4 calls for exactly this.
"""

import os

if os.environ.get("PICOTRON_TEST_TPU") == "1":
    # real-TPU kernel runs (tests/test_tpu_kernels.py, invoked by bench.py's
    # parity pre-flight): leave the platform alone so the TPU backend loads
    import jax
else:
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=8 "
        + os.environ.get("XLA_FLAGS", "")
    )
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from picotron_tpu.config import Config  # noqa: E402


@pytest.fixture
def tiny_model_kwargs():
    """A tiny Llama config: GQA (8 q-heads, 4 kv-heads), small but
    tp/cp/pp-divisible everywhere."""
    return dict(
        num_hidden_layers=4,
        num_attention_heads=8,
        num_key_value_heads=4,
        hidden_size=64,
        intermediate_size=128,
        vocab_size=256,
        max_position_embeddings=128,
        rope_theta=10000.0,
        dtype="float32",
        attention_impl="sdpa",
    )


def make_config(tiny_model_kwargs, dp=1, pp=1, cp=1, tp=1, seq=32, mbs=2, acc=1,
                engine="1f1b", dtype=None, zigzag=False, sp=False, zero1=False,
                cp_impl="ring", interleave=1, fsdp=False, stage_gating="auto",
                check_vma=False, **overrides) -> Config:
    raw = {
        "distributed": {"dp_size": dp, "pp_size": pp, "cp_size": cp, "tp_size": tp,
                        "pp_engine": engine, "use_cpu": True,
                        "cp_zigzag": zigzag, "tp_sequence_parallel": sp,
                        "zero1": zero1, "cp_impl": cp_impl,
                        "pp_interleave": interleave, "fsdp": fsdp,
                        "stage_gating": stage_gating, "check_vma": check_vma},
        "model": dict(tiny_model_kwargs, **({"dtype": dtype} if dtype else {})),
        "training": {**dict(seq_length=seq, micro_batch_size=mbs,
                            gradient_accumulation_steps=acc,
                            learning_rate=1e-3, remat="none"),
                     **overrides},
        "dataset": {"name": "synthetic"},
    }
    return Config.from_dict(raw)


@pytest.fixture
def cfg_factory(tiny_model_kwargs):
    def factory(**kw):
        return make_config(tiny_model_kwargs, **kw)

    return factory


@pytest.fixture(autouse=True)
def _clear_jax_caches():
    """XLA's CPU runtime aborts after ~17 live multi-device executables with
    collectives accumulate in-process; dropping compiled programs between
    tests keeps the suite stable (and bounds memory)."""
    yield
    jax.clear_caches()
