"""Property-based invariants for the layout/permutation machinery.

These structures (stacked-layer layouts, zigzag sequence permutations, the
GQA expand/fold pair) are where a silent indexing bug would corrupt training
while every shape still checks out — so their algebraic invariants get
hypothesis coverage across the whole small-parameter space, not just the
handful of geometries the equivalence matrices use.
"""

import numpy as np
import pytest

# environment, not code: hypothesis is an optional dev dependency — absent,
# the whole module skips at collection instead of erroring
pytest.importorskip("hypothesis")
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from picotron_tpu.models.llama import pp_layer_layout
from picotron_tpu.parallel.cp import (
    chunk_positions,
    zigzag_inverse_perm,
    zigzag_perm,
)


@settings(deadline=None, max_examples=60)
@given(st.integers(1, 6), st.integers(1, 6), st.integers(1, 4),
       st.integers(0, 20))
def test_pp_layer_layout_is_an_injection_with_early_remainder(pp, v, kfac,
                                                              extra):
    """Every real layer occupies exactly one stacked row (injectivity); with
    interleave the layer count must divide pp*v, and uneven remainders go to
    the EARLIEST stages (the reference's distribute_layers rule,
    pipeline_parallel.py:33-36)."""
    if v > 1:
        L = pp * v * kfac
    else:
        L = pp + extra  # any L >= pp
    K, counts, positions = pp_layer_layout(L, pp, v)
    assert len(positions) == L
    assert len(set(positions)) == L, "two layers share a stacked row"
    assert all(0 <= p < K * pp for p in positions)
    assert sum(counts) == L and len(counts) == pp
    # remainder layers land on the earliest stages: counts non-increasing
    assert all(a >= b for a, b in zip(counts, counts[1:]))
    assert max(counts) <= K


@settings(deadline=None, max_examples=40)
@given(st.integers(1, 8), st.integers(1, 8))
def test_zigzag_perm_roundtrip_and_ownership(n, h):
    """zigzag_perm/inverse are true inverses, and contiguous shard r of the
    permuted sequence owns exactly original chunks (r, 2n-1-r) — the
    property chunk_positions encodes for the ring's causal masks."""
    S = 2 * n * h
    perm = zigzag_perm(S, n)
    inv = zigzag_inverse_perm(S, n)
    assert sorted(perm) == list(range(S))
    np.testing.assert_array_equal(perm[inv], np.arange(S))
    np.testing.assert_array_equal(inv[perm], np.arange(S))
    s_local = S // n
    for r in range(n):
        shard = perm[r * s_local:(r + 1) * s_local]
        np.testing.assert_array_equal(
            shard, np.asarray(chunk_positions(r, s_local, n, True)))


@settings(deadline=None, max_examples=40)
@given(st.integers(1, 4), st.integers(1, 4), st.integers(1, 3),
       st.integers(1, 3), st.integers(0, 2 ** 31 - 1))
def test_gqa_expand_fold_are_transposes(hkv, g, s, d, seed):
    """<expand(x), y> == <x, fold(y)> — fold is the exact transpose of
    expand (what autodiff needs for the compact-GQA grads), and
    fold(expand(x)) == g * x."""
    import jax.numpy as jnp

    from picotron_tpu.parallel.cp import _gqa_expand, _gqa_fold

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((1, s, hkv, d)).astype(np.float32)
    y = rng.standard_normal((1, s, hkv * g, d)).astype(np.float32)
    ex = np.asarray(_gqa_expand(jnp.asarray(x), g))
    fy = np.asarray(_gqa_fold(jnp.asarray(y), g))
    # fp32 sum reassociation between the two reductions; atol guards the
    # near-zero dot products small random draws produce
    np.testing.assert_allclose(np.sum(ex * y), np.sum(x * fy), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(_gqa_fold(jnp.asarray(ex), g)), g * x, rtol=1e-6)
