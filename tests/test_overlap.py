"""Zero-bubble overlapped scheduling (inference.overlap,
docs/INFERENCE.md "Overlapped scheduling").

The tentpole gate is BIT-IDENTITY: with the per-slot key schedule, the
two-stage pipeline (issue round N+1 before syncing round N) must emit
exactly the streams the serial scheduler emits — greedy AND seeded
stochastic — across the engine matrix (decode_block/verify/chunked x
dense/flash x contiguous/paged x int8 x tp x dp). Around it:

- the key-schedule invariant itself: a slot-keyed stream depends only on
  (base key, position), so it is independent of round structure — block
  length, speculative grouping — and, for greedy, of the schedule;
- late-stop rollback: a round issued against stale budgets/EOS state
  overshoots on device, and the sync stage's masked delivery plus the
  length-pointer discipline emit every token exactly once;
- composition: slot-isolation re-dispatch, ServingChaos faults, and the
  dp=2 rebalance planner all run UNDER the pipeline with the same
  accounting and exactness contracts they have without it;
- drain: `busy` covers the in-flight lookahead round, so a drain loop
  flushes it instead of stranding its tokens.

`make overlap-smoke` (bench_decode --overlap ab) is the throughput half:
gap p50 <= 0.5x serial and tokens/s >= 1.3x with host work ~= device.
"""

from __future__ import annotations

import threading

import jax
import numpy as np
import pytest

from conftest import make_config
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    Request,
)
from picotron_tpu.models import llama
from picotron_tpu.resilience.chaos import ServingChaos

MAX_LEN = 96


def _engine(tiny_model_kwargs, overlap, tp=1, dp=1, slots=4,
            key_schedule="slot", hooks=None, **kw):
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    cfg.inference.dp_size = dp
    kw.setdefault("decode_block_len", 4)
    eng = InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN,
                          overlap=overlap, key_schedule=key_schedule,
                          hooks=hooks, **kw)
    return cfg, eng


def _params(cfg, engine, seed=0):
    p = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(seed))
    if engine.quant_weights:
        p = llama.quantize_params(p)
    return engine.shard_params(p)


def _reqs(program, temp=0.0):
    """Mixed-length batch; ``verify`` uses repetitive prompts (the regime
    prompt-lookup drafting accepts on), ``chunked`` prompts span several
    prefill chunks. Lengths deliberately retire at different rounds so
    the pipeline crosses admissions, finishes, and partial occupancy."""
    k = dict(temperature=temp, top_k=0 if temp == 0 else 40, top_p=0.95)
    if program == "verify":
        return [Request("a", [5, 9, 5, 9, 5, 9], max_new_tokens=18, **k),
                Request("b", [7, 3, 7, 3, 7], max_new_tokens=11, **k),
                Request("c", [11, 12, 11, 12], max_new_tokens=4, **k)]
    if program == "chunked":
        long_a = [(5 * i + 2) % 199 + 1 for i in range(20)]
        long_b = [(3 * i + 7) % 199 + 1 for i in range(17)]
        return [Request("a", long_a, max_new_tokens=14, **k),
                Request("b", long_b, max_new_tokens=10, **k),
                Request("c", [11, 12] * 5, max_new_tokens=4, **k)]
    return [Request("a", [5, 9, 5, 9, 5, 9], max_new_tokens=19, **k),
            Request("b", [7, 3, 7, 3, 7], max_new_tokens=13, **k),
            Request("c", [11, 12, 11, 12], max_new_tokens=4, **k)]


def _run(tiny_model_kwargs, overlap, program="block", temp=0.0, seed=7,
         **kw):
    if program == "verify":
        kw.setdefault("spec_len", 3)
    if program == "chunked":
        kw.setdefault("prefill_chunk", 8)
    cfg, eng = _engine(tiny_model_kwargs, overlap, **kw)
    b = ContinuousBatcher(eng, _params(cfg, eng), seed=seed)
    res = b.run(_reqs(program, temp))
    return {u: (r.tokens, r.finish_reason) for u, r in res.items()}, b


# --------------------------------------------------------------------------- #
# the tentpole: overlap-on == overlap-off across the engine matrix
# --------------------------------------------------------------------------- #


# The full matrix is the gate; the un-marked legs are the tier-1 core and
# the rest ride the `slow` lane (same budget discipline as the sharded
# and speculative matrices).
_slow = pytest.mark.slow
@pytest.mark.parametrize("program,layout,attend,quant,tp,dp,temp", [
    ("block",   "contiguous", "dense", None,     1, 1, 0.0),
    ("block",   "contiguous", "dense", None,     1, 1, 0.9),
    pytest.param("block", "paged", "dense", None,     1, 1, 0.9, marks=_slow),
    pytest.param("block", "paged", "flash", None,     1, 1, 0.0, marks=_slow),
    pytest.param("block", "contiguous", "dense", "int8kv", 1, 1, 0.9,
                 marks=_slow),
    pytest.param("block", "paged", "dense", "int8w",  1, 1, 0.0, marks=_slow),
    pytest.param("block", "contiguous", "dense", None, 2, 1, 0.9,
                 marks=_slow),
    pytest.param("block", "paged", "dense", None,     1, 2, 0.9, marks=_slow),
    pytest.param("verify", "contiguous", "dense", None, 1, 1, 0.0,
                 marks=_slow),
    ("verify",  "contiguous", "dense", None,     1, 1, 0.9),
    pytest.param("verify", "paged", "dense", None,    1, 2, 0.0, marks=_slow),
    ("chunked", "paged",      "dense", None,     1, 1, 0.0),
])
def test_overlap_identity_matrix(tiny_model_kwargs, program, layout,
                                 attend, quant, tp, dp, temp):
    """Overlap-on emits streams BIT-IDENTICAL to overlap-off — same seed,
    same per-slot key schedule — for every program family crossed with
    representative kernel/layout/quantization corners, greedy and seeded
    stochastic, on tp=2 and dp=2. This is the whole correctness story:
    the pipeline may overshoot on device and deliver a round late, but
    nothing observable moves."""
    kw = dict(kv_layout=layout, attend_impl=attend, tp=tp, dp=dp)
    if quant == "int8kv":
        kw["cache_dtype"] = "int8"
    elif quant == "int8w":
        kw["weight_dtype"] = "int8"
    off, _ = _run(tiny_model_kwargs, False, program, temp, **kw)
    on, b = _run(tiny_model_kwargs, True, program, temp, **kw)
    assert on == off, (program, layout, attend, quant, tp, dp, temp)
    st = b.stats()
    assert st["overlap"]["enabled"]
    assert b._inflight is None  # drained, nothing stranded


@pytest.mark.slow
def test_slot_schedule_greedy_matches_round_schedule(tiny_model_kwargs):
    """Greedy decode is key-independent, so the slot schedule (overlap's
    prerequisite) changes nothing against the legacy round schedule —
    the default-off path and the overlap path share one greedy oracle."""
    legacy, _ = _run(tiny_model_kwargs, False, key_schedule="round")
    slot, _ = _run(tiny_model_kwargs, False, key_schedule="slot")
    assert legacy == slot


@pytest.mark.slow
def test_slot_stream_independent_of_round_structure(tiny_model_kwargs):
    """The key-schedule invariant: token at position p is keyed
    fold_in(base, p - 1) no matter how rounds chunk the stream — so a
    seeded-stochastic stream is identical across decode block lengths
    AND under speculative grouping (sample-and-match draws the same
    chain), which is exactly why one-round-stale drafts and overshot
    rounds cannot perturb emitted tokens."""
    b2, _ = _run(tiny_model_kwargs, False, temp=0.9, decode_block_len=2)
    b4, _ = _run(tiny_model_kwargs, False, temp=0.9, decode_block_len=4)
    spec, _ = _run(tiny_model_kwargs, False, temp=0.9, spec_len=3,
                   decode_block_len=1)
    assert b2 == b4
    assert spec == b4


def test_overlap_rejects_round_key_schedule(tiny_model_kwargs):
    """overlap + key_schedule='round' is an invalid combination (a
    round-shared key makes streams depend on stale round membership):
    config.validate and the engine both refuse it."""
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    cfg.inference.overlap = True
    cfg.inference.key_schedule = "round"
    with pytest.raises(ValueError, match="key schedule"):
        cfg.validate()
    cfg2 = make_config(tiny_model_kwargs, seq=MAX_LEN)
    with pytest.raises(ValueError, match="key schedule"):
        InferenceEngine(cfg2, slots=2, max_seq_len=MAX_LEN,
                        overlap=True, key_schedule="round")


# --------------------------------------------------------------------------- #
# late-stop rollback: overshot rounds deliver exactly once
# --------------------------------------------------------------------------- #


def test_late_stop_budget_rollback_exactly_once(tiny_model_kwargs):
    """max_new_tokens that end mid-round: the lookahead round was issued
    against a stale budget and the device overshoots, but the sync
    stage's host walk truncates at the request's own limit — stream
    lengths are exact, nothing duplicated, nothing dropped."""
    for temp in (0.0, 0.9):
        on, b = _run(tiny_model_kwargs, True, temp=temp)
        want = {"a": 19, "b": 13, "c": 4}  # none a multiple of block 4
        for uid, n in want.items():
            toks, reason = on[uid]
            assert len(toks) == n, (uid, temp)
            assert reason == "length"
        assert b.counters["completed"] == 3


def test_late_eos_rollback_exactly_once(tiny_model_kwargs):
    """An EOS that lands mid-round while the NEXT round is already in
    flight: the on-device stop state masks the late-finished slot in the
    overshot round (counts merge), the host walk cuts at EOS, and the
    stream equals the serial scheduler's to the last token."""
    base, _ = _run(tiny_model_kwargs, False)
    # pick an eos the greedy stream actually emits mid-round for "a"
    eos = base["a"][0][5]
    reqs_kw = dict(eos_id=eos, max_new_tokens=19)

    def run(overlap):
        cfg, eng = _engine(tiny_model_kwargs, overlap)
        b = ContinuousBatcher(eng, _params(cfg, eng), seed=7)
        res = b.run([Request("a", [5, 9, 5, 9, 5, 9], **reqs_kw),
                     Request("b", [7, 3, 7, 3, 7], max_new_tokens=13),
                     Request("c", [11, 12, 11, 12], max_new_tokens=4)])
        return {u: (r.tokens, r.finish_reason) for u, r in res.items()}

    off, on = run(False), run(True)
    assert on == off
    assert on["a"][1] == "eos"
    assert on["a"][0][-1] == eos
    assert eos not in on["a"][0][:-1]  # exactly once, nothing replayed


# --------------------------------------------------------------------------- #
# composition: isolation re-dispatch, chaos, dp rebalance, drain
# --------------------------------------------------------------------------- #


@pytest.mark.slow
def test_overlap_slot_isolation_redispatch(tiny_model_kwargs):
    """A persistently failing slot under the pipeline: the fallback
    serial round isolates it (finishes "error"), SURVIVORS' streams are
    bit-identical to the fault-free overlap run — greedy and sampled
    rows — and no slot, queue entry, or in-flight record leaks."""
    clean, _ = _run(tiny_model_kwargs, True, temp=0.9)
    chaos = ServingChaos(_chaos_res(tiny_model_kwargs,
                                    chaos_dispatch_fail_slot=1))
    on, b = _run(tiny_model_kwargs, True, temp=0.9, hooks=chaos)
    # "b" was admitted into the faulted slot: errors with only its
    # prefill-time first token (identical to the clean run's)
    assert on["b"][1] == "error"
    assert on["b"][0] == clean["b"][0][:1]
    for uid in ("a", "c"):
        assert on[uid] == clean[uid]
    assert all(s is None for s in b._slots)
    assert b._inflight is None
    assert b.queue_depth == 0
    assert b.counters["errored"] == 1
    assert b.counters["completed"] == 2


def _chaos_res(tiny_model_kwargs, **kw):
    cfg = make_config(tiny_model_kwargs, seq=MAX_LEN)
    for k, v in kw.items():
        setattr(cfg.resilience, k, v)
    cfg.validate()
    return cfg.resilience


@pytest.mark.slow
def test_overlap_chaos_faults_account_everything(tiny_model_kwargs):
    """Transient dispatch exception + latency spike + poisoned logits,
    all inside the pipeline: no hang, every request terminates with an
    accounted finish_reason, emitted tokens stay defined, and the
    transient fault is absorbed bit-identically (the fallback replays
    the SAME slot-keyed draws, so retries cannot fork a stream)."""
    clean, _ = _run(tiny_model_kwargs, True, temp=0.9)
    chaos = ServingChaos(_chaos_res(
        tiny_model_kwargs, chaos_dispatch_raise_round=2,
        chaos_latency_round=3, chaos_latency_s=0.05,
        chaos_poison_logits_round=4))
    on, b = _run(tiny_model_kwargs, True, temp=0.9, hooks=chaos)
    assert chaos._fired >= {"raise", "latency", "poison"}
    vocab = 256
    for uid, (toks, reason) in on.items():
        assert reason in ("length", "eos")
        assert all(0 <= t < vocab for t in toks)
    # the raise round is absorbed by the serial fallback; the poison
    # round changes sampled VALUES (that is its job) but never counts
    assert b.counters["errored"] == 0
    assert b.counters["completed"] == 3
    assert {u: len(t) for u, (t, _) in on.items()} == \
        {u: len(t) for u, (t, _) in clean.items()}


@pytest.mark.slow
def test_overlap_dp2_rebalance_streams_exact(tiny_model_kwargs):
    """The dp=2 paged skewed workload under the pipeline: short streams
    retire early, the occupancy watermark trips, and the planner drains
    the in-flight round before migrating (migrate_slot reads host
    lengths the lookahead round would otherwise leave stale) — streams
    still equal the dp=1 overlap run and the migration counters moved."""
    reqs = [Request("l0", [1, 2, 3, 4, 5], max_new_tokens=24),
            Request("l1", [9, 8, 7, 6], max_new_tokens=24),
            Request("s0", [11, 12], max_new_tokens=4),
            Request("s1", [13, 14, 15], max_new_tokens=4)]

    def run(dp):
        cfg, eng = _engine(tiny_model_kwargs, True, dp=dp,
                           kv_layout="paged")
        b = ContinuousBatcher(eng, _params(cfg, eng), seed=7)
        res = b.run([Request(**vars(r)) for r in reqs])
        return {u: (r.tokens, r.finish_reason) for u, r in res.items()}, b

    base, _ = run(1)
    got, b2 = run(2)
    assert got == base
    st = b2.stats()
    assert st["rebalance_count"] >= 1
    assert st["rebalance_bytes"] > 0


def test_drain_flushes_inflight_lookahead_round(tiny_model_kwargs):
    """`busy` covers the in-flight record, so serve.py's drain loop
    (`while busy: step()`) flushes the lookahead round instead of
    stranding its tokens: stepping manually, the batcher stays busy
    while ONLY the in-flight round remains, and the flushed streams are
    complete to the exact token count."""
    cfg, eng = _engine(tiny_model_kwargs, True)
    b = ContinuousBatcher(eng, _params(cfg, eng), seed=7)
    for r in _reqs("block"):
        b.submit(r)
    saw_inflight_only = False
    steps = 0
    while b.busy:
        b.step()
        steps += 1
        if b._inflight is not None and b.queue_depth == 0:
            saw_inflight_only = True
        assert steps < 200, "drain loop did not terminate"
    assert saw_inflight_only  # the pipeline actually ran a lookahead
    assert b._inflight is None
    res = b.take_results()
    assert {u: len(r.tokens) for u, r in res.items()} == \
        {"a": 19, "b": 13, "c": 4}


def test_stats_overlap_payload_and_threaded_scrape(tiny_model_kwargs):
    """stats() exposes the overlap A/B payload and takes its scratch
    snapshots (last_host_sync_s, last_prefill) under the leaf lock — a
    scrape hammering from another thread mid-run sees consistent values
    and never trips the pipeline (the C003/C004 fixture in
    tests/test_analysis.py pins the lock discipline statically)."""
    cfg, eng = _engine(tiny_model_kwargs, True)
    b = ContinuousBatcher(eng, _params(cfg, eng), seed=7)
    stop = threading.Event()
    seen = []

    def scrape():
        while not stop.is_set():
            st = b.stats()
            assert st["overlap"]["enabled"] is True
            seen.append(st.get("last_host_sync_s"))

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    try:
        b.run(_reqs("block"))
    finally:
        stop.set()
        t.join(timeout=5)
    st = b.stats()
    assert "last_prefill" in st and "last_host_sync_s" in st
    ov = st["overlap"]
    assert ov["enabled"] is True
    assert ov["dispatch_gap_s"] is None or "p50" in ov["dispatch_gap_s"]
    assert 0.0 <= ov.get("overlap_efficiency", 0.0) <= 1.0
