"""Inference subsystem tests (picotron_tpu/inference/).

Covers the ISSUE-1 acceptance surface: (a) prefill + KV-cache decode_step
greedy generation exactly matches the full-sequence ``forward_logits``
argmax, on tp=1 AND a tp=2 dryrun mesh; (b) the samplers are
distribution-correct under fixed keys; (c) the continuous batcher recycles
slots across mixed-length requests without cross-request interference;
(d) a training checkpoint (including an uneven-pp padded layer stack)
round-trips through ``CheckpointManager.load_params`` into the engine.
"""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from conftest import make_config
from picotron_tpu import checkpoint as ckpt
from picotron_tpu import train_step as ts
from picotron_tpu.inference import (
    ContinuousBatcher,
    InferenceEngine,
    Request,
    sampling,
)
from picotron_tpu.models import llama
from picotron_tpu.topology import named_shardings, topology_from_config
from picotron_tpu.utils import shard_map as shard_map_compat

MAX_LEN = 96


def _engine(tiny_model_kwargs, tp=1, slots=2):
    cfg = make_config(tiny_model_kwargs, tp=tp, seq=MAX_LEN)
    return cfg, InferenceEngine(cfg, slots=slots, max_seq_len=MAX_LEN)


def _params(cfg, engine, seed=0):
    p = jax.jit(lambda k: llama.init_params(k, cfg.model))(
        jax.random.PRNGKey(seed))
    return engine.shard_params(p)


def _oracle_logits(cfg, engine, params, seq):
    """Full-sequence logits [S, V] from forward_logits — the training-side
    oracle the KV-cache path must reproduce."""
    fwd = jax.jit(shard_map_compat(
        lambda p, t: llama.forward_logits(p, t, cfg),
        engine.topo.mesh,
        in_specs=(llama.param_pspecs(cfg.model), P()),
        out_specs=P()))
    toks = jnp.asarray(np.asarray(seq, np.int32)[None, :])
    return np.asarray(fwd(params, toks))[0]


# --------------------------------------------------------------------------- #
# (a) prefill + decode == full forward
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("tp", [1, 2])
def test_greedy_decode_matches_full_forward(tiny_model_kwargs, tp):
    """32 greedy tokens from prefill + decode_step must equal the
    full-sequence argmax chain, exactly, on tp=1 and a tp=2 dryrun mesh
    (the tiny model is GQA: 8 q-heads over 4 kv-heads)."""
    cfg, engine = _engine(tiny_model_kwargs, tp=tp)
    params = _params(cfg, engine)
    prompt = list(range(1, 9))
    n_new = 32
    res = ContinuousBatcher(engine, params).run(
        [Request("r", prompt, max_new_tokens=n_new)])["r"]
    assert len(res.tokens) == n_new
    # one oracle pass over the final sequence verifies every step: greedy
    # means seq[i+1] must be argmax of the full-forward logits at i
    seq = prompt + res.tokens
    pred = np.argmax(_oracle_logits(cfg, engine, params, seq), axis=-1)
    for i in range(len(prompt) - 1, len(seq) - 1):
        assert pred[i] == seq[i + 1], (i, pred[i], seq[i + 1])


def test_prefill_logits_match_full_forward(tiny_model_kwargs):
    """The prefill's last-token logits are the full forward's, to fp32
    tolerance, for several prompt lengths (bucket padding must be inert)."""
    cfg, engine = _engine(tiny_model_kwargs)
    params = _params(cfg, engine)
    for n in (1, 5, 16):
        prompt = [(7 * i + 3) % cfg.model.vocab_size for i in range(n)]
        _, last = engine.prefill(params, prompt)
        want = _oracle_logits(cfg, engine, params, prompt)[n - 1]
        np.testing.assert_allclose(np.asarray(last)[0], want,
                                   rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------- #
# (b) samplers
# --------------------------------------------------------------------------- #


def test_sample_zero_temperature_is_greedy():
    logits = jnp.asarray(
        np.random.default_rng(0).normal(size=(3, 17)).astype(np.float32))
    want = np.argmax(np.asarray(logits), axis=-1)
    for seed in range(4):
        got = sampling.sample(
            logits, jax.random.PRNGKey(seed), jnp.zeros(3),
            jnp.zeros(3, jnp.int32), jnp.ones(3))
        np.testing.assert_array_equal(np.asarray(got), want)


def test_top_k_filter_keeps_k_highest():
    logits = jnp.asarray(
        np.random.default_rng(1).normal(size=(2, 32)).astype(np.float32))
    out = np.asarray(sampling.apply_top_k(logits, jnp.asarray([3, 0])))
    kept0 = np.flatnonzero(out[0] > -1e29)
    assert set(kept0) == set(np.argsort(np.asarray(logits)[0])[-3:])
    np.testing.assert_array_equal(out[1], np.asarray(logits)[1])  # k<=0: off


def test_top_p_filter_keeps_minimal_nucleus():
    # probs 0.5, 0.3, 0.1, 0.1: p=0.7 keeps {0, 1} (exclusive prefix mass
    # 0.0 and 0.5 < 0.7; token 2's 0.8 is out); p>=1 keeps everything
    probs = np.array([[0.5, 0.3, 0.1, 0.1]], np.float32)
    logits = jnp.asarray(np.log(probs))
    out = np.asarray(sampling.apply_top_p(logits, jnp.asarray([0.7])))
    assert set(np.flatnonzero(out[0] > -1e29)) == {0, 1}
    out_off = np.asarray(sampling.apply_top_p(logits, jnp.asarray([1.0])))
    np.testing.assert_array_equal(out_off, np.asarray(logits))


def test_fused_filter_matches_sequential_application():
    """filter_top_k_top_p (one sort, what ``sample`` runs) must keep
    exactly the token set of the sequential apply_top_k -> apply_top_p
    application — randomized logits WITH exact ties (quantized values make
    threshold collisions common), across k/p combinations including the
    disabled sentinels."""
    rng = np.random.default_rng(7)
    V = 24
    # quantize to force exact ties at top-k thresholds and nucleus cutoffs
    logits = np.round(rng.normal(size=(64, V)) * 4) / 4
    logits = jnp.asarray(logits.astype(np.float32))
    for k in (0, 1, 3, V, V + 5):
        for p in (0.05, 0.3, 0.7, 0.95, 1.0):
            ks = jnp.full(logits.shape[0], k, jnp.int32)
            ps = jnp.full(logits.shape[0], p, jnp.float32)
            fused = np.asarray(sampling.filter_top_k_top_p(logits, ks, ps))
            seq = np.asarray(
                sampling.apply_top_p(sampling.apply_top_k(logits, ks), ps))
            np.testing.assert_array_equal(fused > -1e29, seq > -1e29,
                                          err_msg=f"k={k} p={p}")
            # surviving logits pass through unchanged
            np.testing.assert_array_equal(
                np.where(fused > -1e29, fused, 0),
                np.where(seq > -1e29, np.asarray(logits), 0))


def test_top_p_zero_pins_top1():
    """p <= 0 would mask every column (exclusive prefix mass 0 < 0 is
    False); both filters must pin the top-1 token instead of degenerating
    into a constant token-0 emitter."""
    logits = jnp.asarray(
        np.random.default_rng(3).normal(size=(4, 16)).astype(np.float32))
    best = np.argmax(np.asarray(logits), axis=-1)
    for p in (0.0, -1.0):
        ps = jnp.full(4, p, jnp.float32)
        for out in (sampling.apply_top_p(logits, ps),
                    sampling.filter_top_k_top_p(
                        logits, jnp.zeros(4, jnp.int32), ps)):
            kept = np.asarray(out) > -1e29
            np.testing.assert_array_equal(np.sum(kept, axis=-1),
                                          np.ones(4))
            assert all(kept[i, best[i]] for i in range(4))
        # and sampling at any temperature draws exactly the argmax
        got = sampling.sample(logits, jax.random.PRNGKey(0),
                              jnp.ones(4), jnp.zeros(4, jnp.int32), ps)
        np.testing.assert_array_equal(np.asarray(got), best)


def test_sample_distribution_matches_softmax():
    """Temperature-1 sampling frequencies converge to softmax; with top_k
    the support restricts to the k best and renormalizes."""
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(1, 8)).astype(np.float32))
    n = 4000
    keys = jax.random.split(jax.random.PRNGKey(0), n)

    def draw(top_k):
        toks = jax.vmap(lambda k: sampling.sample(
            logits, k, jnp.ones(1), jnp.asarray([top_k]), jnp.ones(1))[0]
        )(keys)
        return np.bincount(np.asarray(toks), minlength=8) / n

    probs = np.asarray(jax.nn.softmax(logits[0]))
    np.testing.assert_allclose(draw(0), probs, atol=0.04)

    top3 = set(np.argsort(probs)[-3:])
    freq = draw(3)
    assert set(np.flatnonzero(freq)) <= top3
    renorm = np.where(np.isin(np.arange(8), list(top3)), probs, 0)
    np.testing.assert_allclose(freq, renorm / renorm.sum(), atol=0.04)


# --------------------------------------------------------------------------- #
# (c) continuous batching / slot recycling
# --------------------------------------------------------------------------- #


def test_batcher_recycles_slots_mixed_lengths(tiny_model_kwargs):
    """5 mixed-length requests through 2 slots: every request finishes with
    its full budget, and a request's tokens are identical to running it
    alone — slot sharing and recycling must not leak across sequences."""
    cfg, engine = _engine(tiny_model_kwargs, slots=2)
    params = _params(cfg, engine)
    reqs = [
        Request(f"r{i}", [(3 * i + j) % 50 + 1 for j in range(3 + 2 * i)],
                max_new_tokens=5 + 3 * i)
        for i in range(5)
    ]
    batched = ContinuousBatcher(engine, params).run(reqs)
    assert set(batched) == {r.uid for r in reqs}
    for r in reqs:
        res = batched[r.uid]
        assert res.finish_reason == "length"
        assert len(res.tokens) == r.max_new_tokens, r.uid
    for r in (reqs[0], reqs[4]):  # shortest and longest
        solo = ContinuousBatcher(engine, params).run(
            [Request("solo", r.prompt, max_new_tokens=r.max_new_tokens)])
        assert solo["solo"].tokens == batched[r.uid].tokens, r.uid


def test_batcher_request_timeout_frees_slot(tiny_model_kwargs):
    """A request past its wall-clock deadline finishes with reason "timeout"
    and releases its slot, so a queued request behind it still completes —
    driven by an injected clock (1s per scheduler tick) for determinism."""

    class Clock:
        t = 0.0

        def __call__(self):
            self.t += 1.0
            return self.t

    cfg, engine = _engine(tiny_model_kwargs, slots=1)
    params = _params(cfg, engine)
    b = ContinuousBatcher(engine, params, clock=Clock())
    res = b.run([
        Request("hog", [1, 2, 3], max_new_tokens=64, timeout_s=3.0),
        Request("queued", [4, 5, 6], max_new_tokens=4),
    ])
    assert res["hog"].finish_reason == "timeout"
    assert 0 < len(res["hog"].tokens) < 64  # partial output is returned
    assert res["queued"].finish_reason == "length"
    assert len(res["queued"].tokens) == 4
    # no deadline => never times out, identical to the pre-deadline behavior
    free = ContinuousBatcher(engine, params, clock=Clock()).run(
        [Request("a", [1, 2, 3], max_new_tokens=8)])["a"]
    assert free.finish_reason == "length" and len(free.tokens) == 8


def test_batcher_eos_terminates_early(tiny_model_kwargs):
    cfg, engine = _engine(tiny_model_kwargs)
    params = _params(cfg, engine)
    prompt = [5, 6, 7, 8]
    free = ContinuousBatcher(engine, params).run(
        [Request("a", prompt, max_new_tokens=10)])["a"]
    eos = free.tokens[2]
    assert eos not in free.tokens[:2], "pick a different seed/prompt"
    res = ContinuousBatcher(engine, params).run(
        [Request("a", prompt, max_new_tokens=10, eos_id=eos)])["a"]
    assert res.finish_reason == "eos"
    assert res.tokens == free.tokens[:3]


# --------------------------------------------------------------------------- #
# (d) checkpoint -> engine round trip
# --------------------------------------------------------------------------- #


def test_checkpoint_roundtrip_into_engine(tiny_model_kwargs, tmp_path):
    """Save from an UNEVEN pp=3 training topology (padded stacked layer
    rows), params-only restore into a pp=1 engine with layout remap, and
    decode: the loaded weights must equal the plain-layout init bit-for-bit
    and generate identically to using them directly."""
    cfg3 = make_config(tiny_model_kwargs, pp=3, seq=32)
    topo3 = topology_from_config(cfg3)
    params3, opt3 = ts.init_state(cfg3, topo3)
    L = cfg3.model.num_hidden_layers
    mgr = ckpt.CheckpointManager(str(tmp_path / "c"))
    mgr.save(7, params3, opt3, trained_tokens=1234, layout=(L, 3))
    mgr.close()

    icfg, engine = _engine(tiny_model_kwargs)
    like = jax.eval_shape(partial(llama.init_params, m=icfg.model),
                          jax.random.PRNGKey(0))
    shardings = named_shardings(engine.topo,
                                llama.param_pspecs(icfg.model))
    like = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        like, shardings)
    loaded, step, tokens = ckpt.CheckpointManager(
        str(tmp_path / "c")).load_params(like, layout=(L, 1))
    assert (step, tokens) == (7, 1234)

    # same seed in the plain pp=1 layout == the remapped restore
    direct = _params(icfg, engine, seed=cfg3.training.seed)
    for a, b in zip(jax.tree.leaves(loaded), jax.tree.leaves(direct)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    req = [Request("g", [9, 8, 7], max_new_tokens=8)]
    got = ContinuousBatcher(engine, loaded).run(req)["g"].tokens
    want = ContinuousBatcher(engine, direct).run(req)["g"].tokens
    assert got == want


def test_generate_cli_end_to_end_from_checkpoint(tiny_model_kwargs, tmp_path,
                                                 capsys):
    """The acceptance-criteria path verbatim: save with checkpoint.py, run
    ``tools/generate.py --load-path`` in-process, get tokens out."""
    from picotron_tpu.tools import generate

    cfg = make_config(tiny_model_kwargs, seq=32)
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    mgr = ckpt.CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(3, params, opt_state, trained_tokens=99,
             layout=(cfg.model.num_hidden_layers, 1))
    mgr.close()
    cfg_path = str(tmp_path / "cfg.json")
    cfg.to_json(cfg_path)

    rc = generate.main([
        "--config", cfg_path, "--load-path", str(tmp_path / "ckpt"),
        "--prompt-ids", "4,5,6", "--prompt-ids", "7,8",
        "--max-new-tokens", "6", "--max-seq-len", "64", "--slots", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "loaded step 3" in out
    assert "[req0]" in out and "[req1]" in out
