"""Worker for the multi-host suites: one JAX process of an N-process CPU
"pod" running real ``jax.distributed`` + gloo collectives on localhost.

Two modes:

**Lockstep mode** (tests/test_multihost.py) — 4 virtual devices each, 8
global: runs the library path (global mesh, shard_batch's multi-process
placement, the jitted 4D train step) step by step and writes its loss
trajectory (and which process printed) to a JSON file::

    python multihost_worker.py <process_id> <port> <out_json> [features]

``features`` is a comma-separated flag list; "zero1" turns on dp-sharded
optimizer state, whose reduce-scatter/all-gather then cross the process
boundary (dp is the outermost axis); "fsdp" rests the layer params
dp-sharded, so every layer's just-in-time param all-gather (and its grad
reduce-scatter transpose) crosses the boundary instead.

**Train mode** (tests/test_cluster_pod.py, ``make chaos-pod-smoke``) — runs
the REAL ``train()`` loop from a config JSON, with checkpoints, preemption
consensus, the cluster monitor, and rank-targeted chaos all live::

    python multihost_worker.py train <config.json> <port> <out_prefix>

The rank comes from ``$JAX_PROCESS_ID`` and the pod size from
``$JAX_NUM_PROCESSES`` (both exported by ``tools/supervise.py --num-procs``,
so the SAME command line serves every rank and every restart). Each run
APPENDS one JSON line — ``{"rank", "hist": [[step, loss], ...], "rc"}`` —
to ``<out_prefix>.p<rank>.jsonl``, so a supervised sequence of runs leaves
the full stitched trajectory behind, and exits with the code
``train.main`` would: 0 done, 75 preempted-with-checkpoint, 76 anomaly
abort (a chaos SIGKILL obviously writes nothing — the missing record IS
the evidence of the dead incarnation).
"""

import json
import os
import sys

# runnable as a bare script from any cwd (the pod supervisor relaunches it
# with the original argv): the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _init_jax(process_id: int, port: str, num_processes: int,
              local_devices: int):
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    # without gloo, any jitted program spanning processes fails with
    # "Multiprocess computations aren't implemented on the CPU backend"
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=num_processes, process_id=process_id)
    return jax


def main_lockstep():
    pid, port, out = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    feats = sys.argv[4].split(",") if len(sys.argv) > 4 else []
    jax = _init_jax(pid, port, num_processes=2, local_devices=4)
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    from picotron_tpu import train_step as ts
    from picotron_tpu import utils
    from picotron_tpu.config import Config
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.topology import topology_from_config

    cfg = Config.from_dict({
        # dp is the outermost mesh axis, so dp=0 lives on process 0 and dp=1
        # on process 1 — the grad pmean crosses the process boundary, like dp
        # over DCN on a real pod
        "distributed": {"dp_size": 2, "cp_size": 2, "tp_size": 2,
                        "use_cpu": True, "zero1": "zero1" in feats,
                        "fsdp": "fsdp" in feats},
        "model": dict(num_hidden_layers=4, num_attention_heads=8,
                      num_key_value_heads=4, hidden_size=64,
                      intermediate_size=128, vocab_size=256,
                      max_position_embeddings=128, dtype="float32",
                      attention_impl="sdpa"),
        "training": {"seq_length": 32, "micro_batch_size": 4,
                     "gradient_accumulation_steps": 1, "learning_rate": 1e-3,
                     "remat": "none"},
        "dataset": {"name": "synthetic"},
    })
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    losses = []
    for _ in range(4):
        tokens, targets = ts.shard_batch(next(loader), topo)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        # the replicated loss spans both processes; read the local copy
        losses.append(float(utils.host_values(loss)))

    with open(out, "w") as f:
        json.dump({"process": pid, "losses": losses,
                   "is_main": utils.is_main_process()}, f)


def main_train():
    cfg_path, port, out_prefix = sys.argv[2], sys.argv[3], sys.argv[4]
    pid = int(os.environ.get("JAX_PROCESS_ID", "0"))
    nproc = int(os.environ.get("JAX_NUM_PROCESSES", "2"))
    with open(cfg_path) as f:
        raw = json.load(f)
    d = raw.get("distributed", {})
    world = (d.get("dp_size", 1) * d.get("pp_size", 1)
             * d.get("cp_size", 1) * d.get("tp_size", 1))
    assert world % nproc == 0, (world, nproc)
    _init_jax(pid, port, num_processes=nproc, local_devices=world // nproc)

    from picotron_tpu import resilience
    from picotron_tpu.config import Config
    from picotron_tpu.resilience.anomaly import AnomalyAbort
    from picotron_tpu.train import train

    cfg = Config.from_dict(raw)
    hist: list = []
    rc = 0
    try:
        train(cfg, loss_history=hist)
    except AnomalyAbort:
        rc = resilience.EXIT_ANOMALY
    if resilience.was_preempted():
        rc = resilience.EXIT_PREEMPTED
    with open(f"{out_prefix}.p{pid}.jsonl", "a") as f:
        f.write(json.dumps({"rank": pid, "hist": hist, "rc": rc}) + "\n")
    sys.exit(rc)


if __name__ == "__main__":
    if sys.argv[1] == "train":
        main_train()
    else:
        main_lockstep()
