"""Worker for tests/test_multihost.py: one JAX process of a 2-process CPU
"pod" (4 virtual devices each, 8 global). Runs the real library path —
jax.distributed.initialize, global mesh over all 8 devices, shard_batch's
multi-process placement, the jitted 4D train step — and writes its loss
trajectory (and which processes printed) to a JSON file.

Usage: python multihost_worker.py <process_id> <port> <out_json> [features]
``features`` is a comma-separated flag list; "zero1" turns on dp-sharded
optimizer state, whose reduce-scatter/all-gather then cross the process
boundary (dp is the outermost axis); "fsdp" rests the layer params
dp-sharded, so every layer's just-in-time param all-gather (and its
grad reduce-scatter transpose) crosses the boundary instead.
"""

import json
import os
import sys


def main():
    pid, port, out = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    feats = sys.argv[4].split(",") if len(sys.argv) > 4 else []
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}", num_processes=2,
        process_id=pid)
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

    from picotron_tpu import train_step as ts
    from picotron_tpu import utils
    from picotron_tpu.config import Config
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.topology import topology_from_config

    cfg = Config.from_dict({
        # dp is the outermost mesh axis, so dp=0 lives on process 0 and dp=1
        # on process 1 — the grad pmean crosses the process boundary, like dp
        # over DCN on a real pod
        "distributed": {"dp_size": 2, "cp_size": 2, "tp_size": 2,
                        "use_cpu": True, "zero1": "zero1" in feats,
                        "fsdp": "fsdp" in feats},
        "model": dict(num_hidden_layers=4, num_attention_heads=8,
                      num_key_value_heads=4, hidden_size=64,
                      intermediate_size=128, vocab_size=256,
                      max_position_embeddings=128, dtype="float32",
                      attention_impl="sdpa"),
        "training": {"seq_length": 32, "micro_batch_size": 4,
                     "gradient_accumulation_steps": 1, "learning_rate": 1e-3,
                     "remat": "none"},
        "dataset": {"name": "synthetic"},
    })
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    losses = []
    for _ in range(4):
        tokens, targets = ts.shard_batch(next(loader), topo)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(jax.block_until_ready(loss)))

    with open(out, "w") as f:
        json.dump({"process": pid, "losses": losses,
                   "is_main": utils.is_main_process()}, f)


if __name__ == "__main__":
    main()
