"""HF-datasets data path without network: local text/json files + an
injected tokenizer exercise the tokenize -> pack -> batch pipeline
(reference data.py:57-100 semantics)."""

import json

import numpy as np
import pytest

from picotron_tpu.data import MicroBatchDataLoader
from tests.conftest import make_config


class ToyTokenizer:
    """Whitespace 'tokenizer' with a fixed small vocab (hash-bucketed)."""

    def __init__(self, vocab_size):
        self.vocab_size = vocab_size

    def __call__(self, texts):
        ids = [[hash(w) % self.vocab_size for w in t.split()] for t in texts]
        return {"input_ids": ids}


@pytest.fixture
def json_corpus(tmp_path):
    rng = np.random.default_rng(0)
    rows = [{"text": " ".join(f"w{int(x)}" for x in rng.integers(0, 50, 64))}
            for _ in range(200)]
    path = tmp_path / "corpus.json"
    path.write_text(json.dumps(rows))
    return str(path)


def test_local_json_dataset_loads_and_packs(tiny_model_kwargs, json_corpus):
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.dataset.name = json_corpus
    tok = ToyTokenizer(cfg.model.vocab_size)
    loader = MicroBatchDataLoader(cfg, tokenizer=tok)
    batch = next(loader)
    assert batch["input_ids"].shape == (1, 2, 32)
    assert batch["input_ids"].dtype == np.int32
    assert batch["input_ids"].max() < cfg.model.vocab_size
    # shifted-view contract: target[t] == input[t+1] within a packed sample
    np.testing.assert_array_equal(batch["input_ids"][0, :, 1:],
                                  batch["target_ids"][0, :, :-1])


def test_local_json_dataset_trains(tiny_model_kwargs, json_corpus):
    from picotron_tpu.train import train

    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2, total_train_steps=2)
    cfg.dataset.name = json_corpus

    # the trainer builds the loader itself; inject the toy tokenizer by
    # patching AutoTokenizer resolution is overkill — instead run the loader
    # path directly through train_step
    from picotron_tpu import train_step as ts
    from picotron_tpu.topology import topology_from_config

    topo = topology_from_config(cfg)
    loader = MicroBatchDataLoader(cfg, tokenizer=ToyTokenizer(cfg.model.vocab_size))
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    for _ in range(2):
        tok_b, tgt = ts.shard_batch(next(loader), topo)
        params, opt_state, loss = step(params, opt_state, tok_b, tgt)
    assert np.isfinite(float(loss))
