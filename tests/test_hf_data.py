"""HF-datasets data path without network: local text/json files + an
injected tokenizer exercise the tokenize -> pack -> batch pipeline
(reference data.py:57-100 semantics)."""

import json

import numpy as np
import pytest

from picotron_tpu.data import MicroBatchDataLoader
from tests.conftest import make_config


class ToyTokenizer:
    """Whitespace 'tokenizer' with a fixed small vocab (hash-bucketed)."""

    def __init__(self, vocab_size):
        self.vocab_size = vocab_size

    def __call__(self, texts):
        ids = [[hash(w) % self.vocab_size for w in t.split()] for t in texts]
        return {"input_ids": ids}


@pytest.fixture
def json_corpus(tmp_path):
    rng = np.random.default_rng(0)
    rows = [{"text": " ".join(f"w{int(x)}" for x in rng.integers(0, 50, 64))}
            for _ in range(200)]
    path = tmp_path / "corpus.json"
    path.write_text(json.dumps(rows))
    return str(path)


def test_local_json_dataset_loads_and_packs(tiny_model_kwargs, json_corpus):
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.dataset.name = json_corpus
    tok = ToyTokenizer(cfg.model.vocab_size)
    loader = MicroBatchDataLoader(cfg, tokenizer=tok)
    batch = next(loader)
    assert batch["input_ids"].shape == (1, 2, 32)
    assert batch["input_ids"].dtype == np.int32
    assert batch["input_ids"].max() < cfg.model.vocab_size
    # shifted-view contract: target[t] == input[t+1] within a packed sample
    np.testing.assert_array_equal(batch["input_ids"][0, :, 1:],
                                  batch["target_ids"][0, :, :-1])


def test_local_json_dataset_trains(tiny_model_kwargs, json_corpus):
    from picotron_tpu.train import train

    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2, total_train_steps=2)
    cfg.dataset.name = json_corpus

    # the trainer builds the loader itself; inject the toy tokenizer by
    # patching AutoTokenizer resolution is overkill — instead run the loader
    # path directly through train_step
    from picotron_tpu import train_step as ts
    from picotron_tpu.topology import topology_from_config

    topo = topology_from_config(cfg)
    loader = MicroBatchDataLoader(cfg, tokenizer=ToyTokenizer(cfg.model.vocab_size))
    params, opt_state = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    for _ in range(2):
        tok_b, tgt = ts.shard_batch(next(loader), topo)
        params, opt_state, loss = step(params, opt_state, tok_b, tgt)
    assert np.isfinite(float(loss))


def test_num_samples_subsets_raw_documents(tiny_model_kwargs, json_corpus):
    """training.num_samples selects the first N raw documents before
    tokenization (reference data.py:34-35) — fewer packed rows result, and
    a cap above the dataset size is a no-op."""
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.dataset.name = json_corpus
    tok = ToyTokenizer(cfg.model.vocab_size)
    full = MicroBatchDataLoader(cfg, tokenizer=tok)
    cfg.training.num_samples = 10
    sub = MicroBatchDataLoader(cfg, tokenizer=tok)
    # 10 docs x 64 tokens = 640 -> 640 // 33 = 19 packed rows
    assert len(sub.samples) == (10 * 64) // 33
    assert len(sub.samples) < len(full.samples)
    cfg.training.num_samples = 10_000  # above len(dataset): min() applies
    assert len(MicroBatchDataLoader(cfg, tokenizer=tok).samples) \
        == len(full.samples)


def test_num_samples_caps_synthetic_samples(tiny_model_kwargs):
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.training.num_samples = 7
    loader = MicroBatchDataLoader(cfg)
    assert len(loader.samples) == 7


def test_num_samples_validation():
    from tests.conftest import make_config as mk
    import pytest as _pytest

    cfg = mk({"num_hidden_layers": 1, "num_attention_heads": 2,
              "num_key_value_heads": 2, "hidden_size": 16,
              "intermediate_size": 32, "vocab_size": 64,
              "max_position_embeddings": 64}, seq=32, mbs=1)
    cfg.training.num_samples = 0
    with _pytest.raises(ValueError, match="num_samples"):
        cfg.validate()


def test_corpus_above_memory_cap_stays_arrow_backed(
        tiny_model_kwargs, json_corpus):
    """A corpus above dataset.max_in_memory_tokens is served from the
    arrow cache (disk-mapped), not one host array — and the batches it
    yields are bitwise identical to the in-memory path's."""
    from picotron_tpu.data import _ArrowSamples

    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.dataset.name = json_corpus
    tok = ToyTokenizer(cfg.model.vocab_size)
    mem = MicroBatchDataLoader(cfg, tokenizer=tok)
    assert isinstance(mem.samples, np.ndarray)

    cfg.dataset.max_in_memory_tokens = 100  # force the arrow path
    arrow = MicroBatchDataLoader(cfg, tokenizer=tok)
    assert isinstance(arrow.samples, _ArrowSamples)
    assert len(arrow.samples) == len(mem.samples)
    for _ in range(3):  # spans a wrap if the corpus is small enough
        a, b = next(mem), next(arrow)
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
        np.testing.assert_array_equal(a["target_ids"], b["target_ids"])
    assert arrow._epoch == mem._epoch


def test_arrow_gather_batched_take_bitwise_equals_per_row(
        tiny_model_kwargs, json_corpus):
    """_ArrowSamples.gather is one batched arrow `take`; it must return
    bit-for-bit what the per-row fetch loop returns — same dtype, same
    shape, same values — including repeated and unsorted indices (the
    wrap-around batch pattern the loader actually produces)."""
    from picotron_tpu.data import _ArrowSamples

    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.dataset.name = json_corpus
    cfg.dataset.max_in_memory_tokens = 100  # force the arrow path
    loader = MicroBatchDataLoader(cfg, tokenizer=ToyTokenizer(
        cfg.model.vocab_size))
    samples = loader.samples
    assert isinstance(samples, _ArrowSamples)
    n = len(samples)
    rng = np.random.default_rng(3)
    for idx in (np.arange(min(8, n)),
                np.asarray([n - 1, 0, n // 2, 0]),  # unsorted + repeated
                rng.integers(0, n, 16)):
        got = samples.gather(np.asarray(idx))
        ref = samples._gather_per_row(np.asarray(idx))
        assert got.dtype == ref.dtype == np.int32
        assert got.shape == ref.shape
        np.testing.assert_array_equal(got, ref)


def test_arrow_loader_skip_steps_matches_memory(tiny_model_kwargs,
                                                json_corpus):
    """Resume support on the arrow-backed path: skip_steps must land the
    cursor (and epoch) exactly where the in-memory loader lands it, and
    the post-skip batches must be bitwise identical."""
    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.dataset.name = json_corpus
    tok = ToyTokenizer(cfg.model.vocab_size)
    mem = MicroBatchDataLoader(cfg, tokenizer=tok)
    cfg.dataset.max_in_memory_tokens = 100
    arrow = MicroBatchDataLoader(cfg, tokenizer=tok)
    mem.skip_steps(7)
    arrow.skip_steps(7)
    assert arrow._cursor == mem._cursor and arrow._epoch == mem._epoch
    a, b = next(mem), next(arrow)
    np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
    np.testing.assert_array_equal(a["target_ids"], b["target_ids"])
