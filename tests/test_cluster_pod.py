"""Pod-scale chaos drills: cluster fault tolerance on a REAL 2-process pod.

Two ``jax.distributed`` CPU processes (gloo collectives, 2 local devices
each) run the real ``train()`` loop — checkpoints, preemption consensus,
cluster monitor, rank-targeted chaos all live — under the real pod
supervisor (``tools/supervise.run_pod``). The drills pin ISSUE 8's
acceptance criteria end to end:

- **preemption**: SIGTERM ONE rank mid-run; the consensus all-reduce turns
  it into the SAME coordinated emergency save on both ranks (both exit 75,
  nobody wedges in a torn collective), the supervised relaunch auto-resumes,
  and the stitched loss trajectory is bit-for-bit the unfaulted pod run's;
- **dead host**: SIGKILL one rank; its peer detects the silent lease within
  ``peer_timeout_s`` and exits ``EXIT_CLUSTER_FAILED`` (77) instead of
  hanging forever inside gloo, the pod restarts together, the chaos marker
  keeps the replayed step from re-tripping the kill, and the run completes
  on the baseline trajectory.

``make chaos-pod-smoke`` runs exactly this file.
"""

import json
import os
import re
import socket
import sys

import pytest

from picotron_tpu.tools.supervise import run_pod

from conftest import make_config

# multi-minute 2-process e2e: excluded from `make test`, like test_multihost
pytestmark = pytest.mark.slow

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")

_TINY = dict(
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    hidden_size=32, intermediate_size=64, vocab_size=128,
    max_position_embeddings=64, rope_theta=10000.0, dtype="float32",
    attention_impl="sdpa")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _write_cfg(tmp_path, name, **res) -> str:
    """A dp=2,tp=2 6-step run (2 devices per process): periodic saves every
    2 steps, consensus every boundary, plus the drill's resilience fields."""
    cfg = make_config(_TINY, dp=2, tp=2, seq=32, mbs=2, total_train_steps=6)
    cfg.checkpoint.save_dir = str(tmp_path / f"{name}_ckpt")
    cfg.checkpoint.save_frequency = 2
    cfg.resilience.consensus_interval = 1
    for k, v in res.items():
        setattr(cfg.resilience, k, v)
    cfg.validate()
    path = tmp_path / f"{name}.json"
    with open(path, "w") as f:
        json.dump(cfg.to_dict(), f)
    return str(path)


def _run_pod(tmp_path, name, cfg_path, **kw):
    """Supervise the 2-rank worker pod; returns (pod_rc, per-rank record
    lists) — each worker incarnation appends one {"rank","hist","rc"} line
    (a SIGKILLed or os._exit'd incarnation appends nothing)."""
    out = str(tmp_path / f"{name}_out")
    rc = run_pod(
        [sys.executable, WORKER, "train", cfg_path, str(_free_port()), out],
        num_procs=2, backoff=0.1, poll_interval=0.1, term_grace=60.0, **kw)
    recs = []
    for p in range(2):
        try:
            with open(f"{out}.p{p}.jsonl") as f:
                recs.append([json.loads(l) for l in f if l.strip()])
        except OSError:
            recs.append([])
    return rc, recs


def _stitch(records):
    """Last-write-wins step->loss map across a rank's incarnations (a
    resume replays steps after its checkpoint)."""
    out = {}
    for rec in records:
        out.update({int(s): l for s, l in rec["hist"]})
    return out


@pytest.fixture(scope="module")
def baseline(tmp_path_factory):
    """The unfaulted 2-process pod run: the bit-for-bit oracle."""
    tmp = tmp_path_factory.mktemp("pod_base")
    rc, recs = _run_pod(tmp, "base", _write_cfg(tmp, "base"), max_restarts=0)
    assert rc == 0
    assert [r["rc"] for r in recs[0]] == [0]
    traj = _stitch(recs[0])
    assert sorted(traj) == [1, 2, 3, 4, 5, 6]
    assert _stitch(recs[1]) == traj  # the loss is replicated across ranks
    return traj


def test_preempting_one_rank_coordinates_save_and_resumes(tmp_path, baseline):
    """SIGTERM rank 1 after step 3: consensus makes rank 0 break at the
    same boundary (signame PEER-PREEMPT), both take the collective
    emergency save and exit 75 — no torn save, no hung peer — and the
    supervised relaunch resumes to a bit-for-bit identical trajectory."""
    cfg = _write_cfg(tmp_path, "pre", chaos_preempt_rank_at_step="1:3")
    rc, recs = _run_pod(tmp_path, "pre", cfg, max_restarts=2)
    assert rc == 0
    # both ranks: one preempted incarnation, then the clean resume
    assert [r["rc"] for r in recs[0]] == [75, 0]
    assert [r["rc"] for r in recs[1]] == [75, 0]
    # the emergency save landed at the break step: the resume replays
    # nothing before step 4 (steps 1-3 exist ONLY in the 75 incarnation)
    assert max(s for s, _ in recs[0][0]["hist"]) == 3
    assert min(s for s, _ in recs[0][1]["hist"]) == 4
    for p in range(2):
        assert _stitch(recs[p]) == baseline


def test_killed_rank_detected_by_peer_and_pod_restarts(tmp_path, baseline,
                                                       capsys):
    """SIGKILL rank 1 after step 3 (newest checkpoint: step 2). Rank 0's
    next dispatch is a collective with a dead peer — instead of wedging, its
    monitor flags the silent lease within peer_timeout_s and exits 77. The
    pod restarts together, the fired marker keeps the replayed step 3 from
    re-killing, and the run completes on the baseline trajectory."""
    cfg = _write_cfg(tmp_path, "kill", chaos_kill_rank_at_step="1:3",
                     peer_timeout_s=4.0, lease_interval_s=0.5)
    rc, recs = _run_pod(tmp_path, "kill", cfg, max_restarts=2)
    out = capsys.readouterr().out
    assert rc == 0
    # first incarnation: rank 1 died to SIGKILL (-9), rank 0 self-evicted
    # with EXIT_CLUSTER_FAILED — visible in the supervisor's verdict line
    assert re.search(r"pod exit codes \[77, -9\]", out), out[-3000:]
    # neither first incarnation wrote a record (SIGKILL / os._exit); the
    # relaunch alone finishes the run from the step-2 checkpoint
    assert [r["rc"] for r in recs[0]] == [0]
    assert [r["rc"] for r in recs[1]] == [0]
    for p in range(2):
        traj = _stitch(recs[p])
        assert sorted(traj) == [3, 4, 5, 6]  # replayed from the step-2 save
        assert traj == {s: baseline[s] for s in traj}
