"""Fused on-device sampling epilogue: seeded-identical to the host path.

``inference.sample_on_device`` moves the prefill / chunked-prefill /
decode_step sampling INSIDE the jitted dispatch (temperature -> top-k ->
top-p -> categorical over the same fused filter, ``sanitize_logits``
first), so only token ids cross to the host. The contract this file pins:

- the epilogue is the SAME function over the SAME key the host sampler
  would have run — a full batcher run (prefill first-token draws, blocked
  decode, speculative verify rows, stochastic and greedy slots mixed)
  emits bit-identical streams with the epilogue on and off;
- the engine API is honest about where sampling happens: a
  ``sample_on_device`` engine refuses a prefill without sampling params,
  a host-sampling engine refuses one with them, and ``decode_step``'s
  logits slot is None when they never left the device;
- the config key validates (bad JSON types rejected with the fix named).
"""

import numpy as np
import pytest

import jax

from conftest import make_config
from picotron_tpu.config import Config
from picotron_tpu.inference import InferenceEngine
from picotron_tpu.inference.batcher import ContinuousBatcher, Request
from picotron_tpu.models import llama

MAX_LEN = 96


def _engine(tiny_model_kwargs, sod, **kw):
    cfg = make_config(tiny_model_kwargs, tp=1, seq=MAX_LEN)
    eng = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                          sample_on_device=sod, **kw)
    params = eng.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    return eng, params


_REQS = [
    # stochastic, greedy, and filtered slots in one batch — the epilogue
    # must reproduce every per-slot parameter combination
    dict(uid="greedy", prompt=[1, 2, 3, 4, 5], max_new_tokens=6),
    dict(uid="hot", prompt=list(range(1, 20)), max_new_tokens=5,
         temperature=0.9, top_k=7, top_p=0.9),
    dict(uid="warm", prompt=[9, 8, 7], max_new_tokens=4, temperature=0.5,
         top_p=0.8),
]


def _run_batch(tiny_model_kwargs, sod, **kw):
    eng, params = _engine(tiny_model_kwargs, sod, **kw)
    b = ContinuousBatcher(eng, params, seed=11)
    out = b.run([Request(**r) for r in _REQS])
    assert all(r.finish_reason == "length" for r in out.values())
    return {u: r.tokens for u, r in out.items()}


@pytest.mark.parametrize("kw", [
    {},                          # one-shot prefill + blocked decode
    {"prefill_chunk": 8},        # chunked prefill epilogue (final chunk)
    {"kv_layout": "paged"},      # prefix-sharing admission path
    {"spec_len": 3},             # draft-verify rounds (verify rows)
    {"cache_dtype": "int8"},     # quantized cache under the epilogue
])
def test_batcher_streams_identical_on_and_off(tiny_model_kwargs, kw):
    """The whole serving loop, epilogue on vs off, same seed: bit-equal
    token streams — the on-device draw is the host draw, relocated."""
    host = _run_batch(tiny_model_kwargs, False, **kw)
    dev = _run_batch(tiny_model_kwargs, True, **kw)
    assert host == dev


def test_prefill_epilogue_equals_host_sample(tiny_model_kwargs):
    """Direct engine call: the token the epilogue returns is exactly
    sampling.sample over the logits the host path returns, same key —
    stochastic params included."""
    from picotron_tpu.inference import sampling

    host_eng, params = _engine(tiny_model_kwargs, False)
    dev_eng, _ = _engine(tiny_model_kwargs, True)
    prompt = list(range(1, 12))
    key = jax.random.PRNGKey(42)
    _, logits = host_eng.prefill(params, prompt)
    for temp, tk, tp in ((0.0, 0, 1.0), (0.8, 5, 0.9), (1.3, 0, 0.7)):
        want = int(sampling.sample(
            logits, key, np.float32([temp]), np.int32([tk]),
            np.float32([tp]))[0])
        _, tok = dev_eng.prefill(params, prompt,
                                 sample=(key, temp, tk, tp))
        assert int(np.asarray(tok)[0]) == want


def test_decode_step_drops_logits(tiny_model_kwargs):
    """decode_step on an epilogue engine returns (cache, tokens, None) —
    and the tokens match the host-sampling engine's draw."""
    host_eng, params = _engine(tiny_model_kwargs, False)
    dev_eng, _ = _engine(tiny_model_kwargs, True)
    outs = {}
    for eng in (host_eng, dev_eng):
        cache = eng.init_cache()
        kv, first = eng.prefill(
            params, [1, 2, 3, 4],
            sample=((jax.random.PRNGKey(5), 0.0, 0, 1.0)
                    if eng.sample_on_device else None))
        cache = eng.insert(cache, kv, 0, 4)
        toks = np.array([int(np.asarray(first).reshape(-1)[0])
                         if eng.sample_on_device
                         else int(np.argmax(np.asarray(first)[0])), 0],
                        np.int32)
        cache, nxt, logits = eng.decode_step(
            params, cache, toks, jax.random.PRNGKey(6),
            np.float32([0.7, 0.0]), np.zeros(2, np.int32),
            np.ones(2, np.float32))
        outs[eng.sample_on_device] = np.asarray(nxt)
        if eng.sample_on_device:
            assert logits is None
        else:
            assert np.asarray(logits).shape[1] > 1
    np.testing.assert_array_equal(outs[True], outs[False])


def test_sample_argument_contract(tiny_model_kwargs):
    """Mode mismatches fail loudly instead of returning the wrong kind
    of array."""
    host_eng, params = _engine(tiny_model_kwargs, False)
    dev_eng, _ = _engine(tiny_model_kwargs, True)
    with pytest.raises(ValueError, match="sample_on_device"):
        dev_eng.prefill(params, [1, 2, 3])  # epilogue engine needs params
    with pytest.raises(ValueError, match="sample_on_device"):
        host_eng.prefill(params, [1, 2, 3],
                         sample=(jax.random.PRNGKey(0), 0.0, 0, 1.0))


def test_config_key_validated(tiny_model_kwargs):
    """JSON-level validation names the fix for a mistyped boolean."""
    cfg = make_config(tiny_model_kwargs, tp=1, seq=MAX_LEN)
    raw = cfg.to_dict()
    raw["inference"]["sample_on_device"] = "true"
    with pytest.raises(ValueError, match="sample_on_device"):
        Config.from_dict(raw)
