"""Per-page KV quantization policy (``inference.kv_page_policy: hot_bf16``).

The paged pool keeps TWO representations of every written row — full
precision and int8 + per-row scales — and a per-page flag, recomputed from
the host allocator's refcounts before each dispatch, selects which one
the attend READS: pages with more than one holder (radix-shared prefixes,
forked slots) stay full precision, exclusively-held pages (cold unique
tails) read as int8. This file pins the contract:

- **dense ≡ flash**: both read paths consume the same flags and bytes, so
  paged generations are bit-identical across impls (mirroring the int8
  discipline in tests/test_decode_kernel.py);
- **hot pages really are hot**: under a shared prefix, the shared pages'
  flags read full-precision while exclusive tail pages read int8;
- **allclose vs uniform** at int8-level tolerance with strictly fewer
  accounted cache bytes per attend walk;
- **validation** rejects the policy off the paged layout (and over a
  uniformly int8 cache) with the fix named, at both the config and the
  engine-kwarg layer.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import make_config
from picotron_tpu.config import Config
from picotron_tpu.inference import InferenceEngine
from picotron_tpu.inference.batcher import ContinuousBatcher, Request
from picotron_tpu.models import llama

MAX_LEN = 96
# two prompts sharing a 14-token prefix (page_len 8 -> one full shared
# page + a shared partial) plus a radix re-hit of the first prompt
PROMPTS = [
    list(range(1, 19)),
    list(range(1, 15)) + [41, 42],
    list(range(1, 19)),
]


def _engine(tiny_model_kwargs, **kw):
    cfg = make_config(tiny_model_kwargs, tp=1, seq=MAX_LEN)
    eng = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                          kv_layout="paged", kv_page_len=8,
                          decode_block_len=2, **kw)
    params = eng.shard_params(jax.jit(
        lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
    return eng, params


def _generate(tiny_model_kwargs, **kw):
    eng, params = _engine(tiny_model_kwargs, **kw)
    b = ContinuousBatcher(eng, params, seed=3)
    reqs = [Request(f"r{i}", p, max_new_tokens=8)
            for i, p in enumerate(PROMPTS)]
    out = b.run(reqs)
    assert all(r.finish_reason == "length" for r in out.values())
    return {u: r.tokens for u, r in out.items()}, eng


def test_policy_dense_equals_flash(tiny_model_kwargs):
    """Both read paths consume the same per-page flags, so generations
    are bit-identical — the wiring proof that the mixed read reaches the
    dense gather AND the flash DMA walk."""
    dense, _ = _generate(tiny_model_kwargs, kv_page_policy="hot_bf16",
                         attend_impl="dense")
    flash, _ = _generate(tiny_model_kwargs, kv_page_policy="hot_bf16",
                         attend_impl="flash")
    assert dense == flash


def test_policy_allclose_uniform_with_fewer_bytes(tiny_model_kwargs):
    """hot_bf16 generations stay within int8 tolerance of the uniform
    full-precision cache (here: token-identical on the tiny model), and
    the accounted bytes per attend walk strictly shrink."""
    from bench_decode import kv_bytes_per_token

    uni, ue = _generate(tiny_model_kwargs, kv_page_policy="uniform",
                        attend_impl="flash")
    hot, he = _generate(tiny_model_kwargs, kv_page_policy="hot_bf16",
                        attend_impl="flash")
    assert uni == hot  # int8 tails don't move the tiny model's argmax
    lengths = np.full(2, 32)
    assert (kv_bytes_per_token(he, lengths)
            < kv_bytes_per_token(ue, lengths))
    stats = he.paged.stats()
    assert stats["kv_pages_quant"] >= 1  # cold tails exist and are int8


def test_shared_prefix_pages_read_full_precision(tiny_model_kwargs):
    """Mid-run flag check: admit two prefix-sharing requests, then look
    at the flags the next dispatch would ship — shared prefix pages hot
    (flag 0), exclusively-held pages cold (flag 1)."""
    eng, params = _engine(tiny_model_kwargs, kv_page_policy="hot_bf16")
    cache = eng.init_cache()
    cache, _, _, cached0 = eng.prefill_paged(params, cache, PROMPTS[0], 0)
    cache, _, _, cached1 = eng.prefill_paged(params, cache, PROMPTS[1], 1)
    assert cached0 == 0 and cached1 > 0  # the second request shared pages
    # the decode pre-write COWs each slot's tail page off the radix-shared
    # prefix — from here the pool holds BOTH shared prefix pages and
    # exclusively-owned tails, the mix the policy exists for
    cache = eng._pre_write(cache, 2, budget=np.array([2, 2]))
    flags = eng.paged.quant_flags()
    refs = eng.paged.pool.refs
    # every multi-holder page reads full precision, every exclusive live
    # page reads int8 — the flag IS the refcount rule
    assert np.all(flags[refs > 1] == 0)
    live_exclusive = (refs == 1)
    live_exclusive[0] = False  # NULL page is metadata, never read
    shared = int(np.sum(refs[1:] > 1))
    assert shared >= 1 and int(np.sum(flags[live_exclusive])) >= 1
    # the attend consumes exactly these flags (shipped by _sync_tables)
    np.testing.assert_array_equal(np.asarray(cache["page_quant"]), flags)


def test_policy_dual_write_keeps_representations_consistent(
        tiny_model_kwargs):
    """Every written page carries BOTH representations: the int8 leaves
    dequantize back to the full-precision leaves within quantization
    error, for every live page (so a flag flip mid-stream can never read
    stale bytes)."""
    from picotron_tpu.inference import kv_cache

    eng, params = _engine(tiny_model_kwargs, kv_page_policy="hot_bf16")
    b = ContinuousBatcher(eng, params, seed=3)
    b.run([Request("a", PROMPTS[0], max_new_tokens=6)])
    cache = b._cache
    refs = eng.paged.pool.refs
    live = np.flatnonzero(refs[1:] > 0) + 1
    k = np.asarray(cache["k"])[:, live].astype(np.float32)
    kq = np.asarray(kv_cache.dequantize_kv(
        jnp.asarray(np.asarray(cache["k_q"])[:, live]),
        jnp.asarray(np.asarray(cache["k_scale"])[:, live]), jnp.float32))
    np.testing.assert_allclose(kq, k, atol=2e-2, rtol=2e-2)


def test_all_rungs_on_tp2(tiny_model_kwargs):
    """The whole PR-11 ladder at once on a tp=2 dryrun mesh — pipelined
    flash DMA over mixed-precision pages with the sampling epilogue —
    emits the same streams as the host-sampling run (the kv-head axis of
    BOTH pool representations shards over 'tp'; the epilogue draws from
    replicated gathered logits, so every shard agrees)."""
    cfg = make_config(dict(tiny_model_kwargs, num_hidden_layers=2),
                      tp=2, seq=MAX_LEN)
    outs = {}
    for sod in (False, True):
        eng = InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                              kv_layout="paged", kv_page_policy="hot_bf16",
                              attend_impl="flash", sample_on_device=sod,
                              decode_block_len=2)
        params = eng.shard_params(jax.jit(
            lambda k: llama.init_params(k, cfg.model))(jax.random.PRNGKey(0)))
        b = ContinuousBatcher(eng, params, seed=5)
        out = b.run([Request("a", PROMPTS[0], max_new_tokens=6,
                             temperature=0.7, top_k=9),
                     Request("b", PROMPTS[1], max_new_tokens=5)])
        outs[sod] = {u: r.tokens for u, r in out.items()}
    assert outs[False] == outs[True]


def test_policy_validation_names_the_fix(tiny_model_kwargs):
    """Config- and engine-level rejections: wrong layout, int8 conflict,
    unknown policy — each naming the corrective setting."""
    cfg = make_config(tiny_model_kwargs, tp=1, seq=MAX_LEN)
    raw = cfg.to_dict()
    raw["inference"]["kv_page_policy"] = "hot_bf16"
    with pytest.raises(ValueError, match="kv_layout.*paged|paged"):
        Config.from_dict(raw)
    raw["inference"]["kv_layout"] = "paged"
    Config.from_dict(raw)  # the named fix works
    raw["inference"]["kv_cache_dtype"] = "int8"
    with pytest.raises(ValueError, match="int8"):
        Config.from_dict(raw)
    raw["inference"]["kv_cache_dtype"] = "auto"
    raw["inference"]["kv_page_policy"] = "hot_fp64"
    with pytest.raises(ValueError, match="uniform|hot_bf16"):
        Config.from_dict(raw)
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                        kv_page_policy="hot_bf16")
    with pytest.raises(ValueError, match="int8"):
        InferenceEngine(cfg, slots=2, max_seq_len=MAX_LEN,
                        kv_layout="paged", kv_page_policy="hot_bf16",
                        cache_dtype="int8")
