"""Pallas flash blocks as ring-attention building blocks.

The Mosaic *interpreter* deadlocks when pallas calls run inside a
multi-device CPU shard_map (its cross-grid barrier collides with the
threaded device executor), so the flash-block math is validated here by
decomposing a 2-chunk causal attention by hand on ONE device — exactly the
per-step computation the ring performs (picotron_tpu/parallel/cp.py) minus
the ppermute. The ring's collective schedule itself is covered by the
einsum-path topology-equivalence tests in test_parallel.py — and by the
GQA ring test at the bottom of this file, which CAN run the full ring in a
2-device shard_map because it uses the einsum path (use_flash=False), not
Pallas. Einsum and flash paths share the merge/backward glue tested here.
"""

from contextlib import nullcontext

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.pallas import tpu as pltpu

from picotron_tpu.ops.attention import sdpa
from picotron_tpu.ops.pallas.flash_attention import (
    flash_attention_with_lse,
    flash_block_grads,
)
from picotron_tpu.parallel.cp import (
    _block_bwd_einsum,
    _block_bwd_flash,
    _block_fwd,
    chunk_positions,
    zigzag_perm,
)
from picotron_tpu.utils import shard_map as shard_map_compat

B, S, H, D = 2, 256, 2, 64  # two 128-token chunks
SCALE = 0.125

# environment, not code: the flash-block tests run the Pallas kernels under
# the Mosaic TPU interpreter, whose context manager older jax lacks — skip
# (pass/skip signal), the einsum-path tests below still run
needs_interpret = pytest.mark.skipif(
    not hasattr(pltpu, "force_tpu_interpret_mode"),
    reason=f"jax {jax.__version__} lacks pltpu.force_tpu_interpret_mode")


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), jnp.float32) for k in ks)


def _merge(o0, l0, o1, l1):
    """The ring's LSE merge (reference context_parallel.py:170-171)."""
    w = jax.nn.sigmoid(l1 - l0)[..., None]
    return o0 - w * (o0 - o1), jnp.logaddexp(l0, l1)


@needs_interpret
def test_two_chunk_flash_decomposition_matches_full():
    """Chunk-1 queries: merge(full-attend chunk-0 block, causal diagonal
    chunk-1 block) must equal rows [C:] of full causal attention, and the
    flash block-backwards fed the merged out/lse must reproduce the full
    attention's gradients."""
    q, k, v = _qkv()
    C = S // 2
    q1 = q[:, C:]
    k0, v0 = k[:, :C], v[:, :C]
    k1, v1 = k[:, C:], v[:, C:]

    with pltpu.force_tpu_interpret_mode():
        o_full, l_full = flash_attention_with_lse(q1, k0, v0, SCALE, causal=False)
        o_diag, l_diag = flash_attention_with_lse(q1, k1, v1, SCALE, causal=True)
    out1, lse1 = _merge(o_full.astype(jnp.float32), l_full,
                        o_diag.astype(jnp.float32), l_diag)

    ref_full = sdpa(q, k, v, SCALE, causal=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(ref_full[:, C:]),
                               rtol=3e-5, atol=3e-5)

    # gradients of sum(out**2) wrt q, k, v — reference via autodiff through sdpa
    def loss(q, k, v):
        return jnp.sum(sdpa(q, k, v, SCALE, causal=True)[:, C:] ** 2)

    ref_dq, ref_dk, ref_dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    dout1 = 2.0 * out1.astype(jnp.float32)
    with pltpu.force_tpu_interpret_mode():
        dq_a, dk0_g, dv0_g = flash_block_grads(
            q1, k0, v0, out1.astype(q.dtype), lse1, dout1.astype(q.dtype),
            SCALE, causal=False)
        dq_b, dk1_g, dv1_g = flash_block_grads(
            q1, k1, v1, out1.astype(q.dtype), lse1, dout1.astype(q.dtype),
            SCALE, causal=True)
    dq1 = dq_a.astype(jnp.float32) + dq_b.astype(jnp.float32)

    np.testing.assert_allclose(np.asarray(dq1), np.asarray(ref_dq[:, C:]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dk0_g), np.asarray(ref_dk[:, :C]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dk1_g), np.asarray(ref_dk[:, C:]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dv0_g), np.asarray(ref_dv[:, :C]),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dv1_g), np.asarray(ref_dv[:, C:]),
                               rtol=1e-4, atol=1e-4)


# ------------------------------- zigzag layout ------------------------------- #

N = 2  # cp ranks; S=256 -> 4 chunks of 64, rank r owns chunks (r, 2N-1-r).
# _block_fwd/_block_bwd_* take src/rank as plain values and use no collective,
# so the whole zigzag ring schedule can be simulated on one device.


def _zig_local(x, r):
    pos = np.asarray(chunk_positions(r, S // N, N, True))
    return x[:, pos]


def _simulate_rank_fwd(r, q, k, v, use_flash):
    ql = _zig_local(q, r)
    out = jnp.zeros(ql.shape, jnp.float32)
    lse = jnp.full(ql.shape[:3], -1e30, jnp.float32)
    for t in range(N):
        src = (r - t) % N
        kl, vl = _zig_local(k, src), _zig_local(v, src)
        bo, bl = _block_fwd(ql, kl, vl, SCALE, jnp.int32(src), jnp.int32(r),
                            True, use_flash, N, True)
        w = jax.nn.sigmoid(bl - lse)[..., None]
        out = out - w * (out - bo)
        lse = jnp.logaddexp(lse, bl)
    return out, lse


@pytest.mark.parametrize(
    "use_flash", [False, pytest.param(True, marks=needs_interpret)])
def test_zigzag_blocks_match_full_attention(use_flash):
    q, k, v = _qkv()
    ref = np.asarray(sdpa(q, k, v, SCALE, causal=True))
    ctx = pltpu.force_tpu_interpret_mode() if use_flash else nullcontext()
    with ctx:
        for r in range(N):
            out, _ = _simulate_rank_fwd(r, q, k, v, use_flash)
            pos = np.asarray(chunk_positions(r, S // N, N, True))
            np.testing.assert_allclose(np.asarray(out), ref[:, pos],
                                       rtol=3e-5, atol=3e-5)


@pytest.mark.slow
@needs_interpret
def test_zigzag_flash_bwd_matches_einsum_bwd():
    q, k, v = _qkv()
    with pltpu.force_tpu_interpret_mode():
        for r in range(N):
            ql = _zig_local(q, r)
            out, lse = _simulate_rank_fwd(r, q, k, v, False)
            dout = (2.0 * out).astype(q.dtype)
            D = jnp.sum(dout.astype(jnp.float32) * out, axis=-1)
            for src in range(N):
                kl, vl = _zig_local(k, src), _zig_local(v, src)
                fe = _block_bwd_einsum(ql, kl, vl, dout, out, lse, D, SCALE,
                                       jnp.int32(src), jnp.int32(r), True, N,
                                       True)
                ff = _block_bwd_flash(ql, kl, vl, dout,
                                      out.astype(q.dtype), lse, SCALE,
                                      jnp.int32(src), jnp.int32(r), True, True)
                for a, b in zip(ff, fe):
                    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                               rtol=1e-4, atol=1e-4)


def test_zigzag_perm_inverse():
    perm = zigzag_perm(S, N)
    assert sorted(perm.tolist()) == list(range(S))
    # contiguous shard r of the permuted sequence = positions chunk_positions(r)
    for r in range(N):
        sl = S // N
        np.testing.assert_array_equal(
            perm[r * sl:(r + 1) * sl],
            np.asarray(chunk_positions(r, sl, N, True)))


@needs_interpret
def test_block_fwd_custom_tiles_match_default():
    """flash_block_q/k plumb through the ring's _block_fwd: a custom tiling
    must not change the block math (single device, interpret mode)."""
    q, k, v = _qkv(3)
    C = S // 2
    with pltpu.force_tpu_interpret_mode():
        o_def, l_def = _block_fwd(q[:, :C], k[:, :C], v[:, :C], SCALE,
                                  src=0, rank=0, causal=True, use_flash=True,
                                  n=2, zigzag=False)
        o_cus, l_cus = _block_fwd(q[:, :C], k[:, :C], v[:, :C], SCALE,
                                  src=0, rank=0, causal=True, use_flash=True,
                                  n=2, zigzag=False, block_q=32, block_k=64)
    np.testing.assert_allclose(np.asarray(o_cus), np.asarray(o_def),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(l_cus), np.asarray(l_def),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_gqa_cp_matches_full_attention_and_grads(mode):
    """GQA-aware context parallelism (compact Hkv-head K/V on the wire, both
    algorithms): forward, loss, and (dq, dk, dv) must match full causal
    attention over pre-repeated K/V, with dk/dv group-summed back to the
    compact heads — the transpose of the repeat the reference performs
    before its ring (model.py:141-142)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from picotron_tpu.parallel.cp import ring_attention, ulysses_attention

    n = 2
    hq, hkv = 4, 2
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, S, hq, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, hkv, D), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, S, hq, D), jnp.float32)

    if mode == "ring":
        attn = lambda q, k, v: ring_attention(q, k, v, SCALE, "cp", n, True,
                                              False)
    else:
        attn = lambda q, k, v: ulysses_attention(q, k, v, SCALE, "cp", n,
                                                 True, False)

    mesh = Mesh(np.array(jax.devices()[:n]), ("cp",))
    spec = P(None, "cp")

    def shard_fn(q, k, v, wl):
        def loss_fn(q, k, v):
            out = attn(q, k, v)
            return jnp.sum(out * wl), out

        (loss, out), grads = jax.value_and_grad(
            loss_fn, argnums=(0, 1, 2), has_aux=True)(q, k, v)
        return out, grads, jax.lax.psum(loss, "cp")

    out, (dq, dk, dv), loss = jax.jit(shard_map_compat(
        shard_fn, mesh=mesh, in_specs=(spec, spec, spec, spec),
        out_specs=((spec, (spec, spec, spec), P())), check_vma=False,
    ))(q, k, v, w)

    # reference: plain causal attention over pre-repeated K/V
    g = hq // hkv
    kr, vr = jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)

    def ref_loss(q, k, v):
        o = sdpa(q, k, v, SCALE, causal=True)
        return jnp.sum(o * w), o

    (rl, ro), (rdq, rdkr, rdvr) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2), has_aux=True)(q, kr, vr)
    # fold the reference's repeated-head grads to the compact layout
    rdk = rdkr.reshape(B, S, hkv, g, D).sum(axis=3)
    rdv = rdvr.reshape(B, S, hkv, g, D).sum(axis=3)

    np.testing.assert_allclose(float(loss), float(rl), rtol=2e-5)
    for got, want in ((out, ro), (dq, rdq), (dk, rdk), (dv, rdv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_gqa_ulysses_rejects_indivisible_compact_heads():
    """Compact kv heads that do not split over cp must be a clear error at
    the API boundary, not a shape crash inside the all-to-all."""
    from picotron_tpu.parallel.cp import ulysses_attention

    q = jnp.zeros((1, 8, 6, 4), jnp.float32)
    k = v = jnp.zeros((1, 8, 3, 4), jnp.float32)
    with pytest.raises(ValueError, match="divisible by cp"):
        ulysses_attention(q, k, v, 1.0, "cp", 2, True, False)
