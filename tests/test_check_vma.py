"""Static replication protection: build + run the training step under
shard_map's varying-manual-axes checker (distributed.check_vma).

Round-4 VERDICT weak #2: with the checker off everywhere, replication
correctness rested entirely on the dynamic equivalence tests and "any new
code path inherits zero static protection". These tests ARE that static
protection: a new code path that mishandles replicated-vs-varying typing
(a scan carry entering replicated where the body makes it varying, cond
branches disagreeing in vma, a vjp cotangent not matching its primal)
fails here at trace time, named by the checker, before any trajectory
drifts.

Why check_vma is not the production default (and the afab / cond-gating
combinations are rejected at validation): the checker auto-inserts pvary
casts whose AD transposes are REAL psums, which resequences reductions —
measured trajectory drift vs the unchecked build ranges from fp32 noise
(most topologies) to ~1e-2 over 5 steps on zero1/fsdp — and a psum landed
inside a lax.cond stage branch deadlocks every backend. Diagnostic mode.
"""

import numpy as np
import pytest

from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.topology import topology_from_config
from tests.conftest import make_config

# (topology kwargs, drift) — drift is the measured scale of the checker's
# reduction-resequencing on a 5-step fp32 trajectory; "tight" topologies
# additionally assert trajectory equivalence with the unchecked build.
CHECKED_TOPOLOGIES = [
    (dict(), "tight"),
    (dict(tp=2, cp=2, sp=True), "tight"),
    (dict(cp=2, zigzag=True), "tight"),
    (dict(cp=2, cp_impl="ulysses"), "tight"),
    (dict(dp=2, pp=2, cp=2, acc=2, engine="1f1b"), "loose"),
    (dict(pp=2, tp=2, acc=2, engine="1f1b", sp=True), "loose"),
    (dict(pp=2, acc=2, engine="1f1b", interleave=2), "loose"),
    (dict(dp=2, tp=2, zero1=True, engine="1f1b"), "loose"),
    (dict(dp=2, tp=2, fsdp=True), "loose"),
    (dict(dp=2, acc=2, grad_clip=0.5), "loose"),
]


def _losses(cfg, steps=5):
    topo = topology_from_config(cfg)
    params, opt = ts.init_state(cfg, topo)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    out = []
    for _ in range(steps):
        tok, tgt = ts.shard_batch(next(loader), topo)
        params, opt, loss = step(params, opt, tok, tgt)
        out.append(float(loss))
    return np.asarray(out)


@pytest.mark.slow
@pytest.mark.parametrize("topo_kw,drift", CHECKED_TOPOLOGIES,
                         ids=lambda v: v if isinstance(v, str) else
                         "-".join(f"{k}{x}" for k, x in v.items()) or "single")
def test_step_builds_and_trains_under_vma_checker(tiny_model_kwargs,
                                                  topo_kw, drift):
    cfg = make_config(tiny_model_kwargs, check_vma=True, **topo_kw)
    checked = _losses(cfg)
    assert np.isfinite(checked).all(), checked
    # the oracle is the UNCHECKED build of the same topology: tight
    # topologies match to fp32 noise; the drift-prone ones (pipelines,
    # zero1/fsdp — the checker resequences their reductions) stay within
    # the measured drift envelope rather than asserting a noisy
    # 5-step decrease
    tol = 3e-5 if drift == "tight" else 3e-2
    cfg_off = make_config(tiny_model_kwargs, **topo_kw)
    np.testing.assert_allclose(checked, _losses(cfg_off), rtol=tol, atol=tol)


def test_check_vma_rejects_unsound_combinations(tiny_model_kwargs):
    # afab: jax's scan transpose does not type vma (upstream limitation)
    with pytest.raises(ValueError, match="afab"):
        make_config(tiny_model_kwargs, pp=2, acc=2, engine="afab",
                    check_vma=True)
    # cond stage gating: checker-inserted psums inside single-stage
    # branches deadlock
    with pytest.raises(ValueError, match="cond"):
        make_config(tiny_model_kwargs, pp=2, acc=2, engine="1f1b",
                    stage_gating="cond", check_vma=True)
    # pp=1 has no stage gating at all: fine on any backend default
    make_config(tiny_model_kwargs, check_vma=True)
