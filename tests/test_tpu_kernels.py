"""Real-TPU Pallas kernel parity vs the XLA oracles (round-2 VERDICT item 4).

The rest of the suite validates the kernels in Mosaic interpret mode on CPU;
here the compiled kernels run on an actual TPU chip. Skipped unless the
backend is TPU — run with ``PICOTRON_TEST_TPU=1 python -m pytest
tests/test_tpu_kernels.py`` (conftest then leaves the platform alone), which
is what ``bench.py`` invokes as its pre-flight parity gate so the driver's
bench environment executes these on hardware.

bf16 inputs (the production dtype), fp32 tolerances sized to bf16 resolution:
the oracle computes the same math through XLA einsums with fp32 softmax
statistics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# NOTE: deliberately NOT gated on the interpret-mode API the CPU-side
# pallas suite (test_pallas_kernels.py) needs — these are compiled runs
# that never touch the interpreter, and skipping them by proxy on a real
# TPU host would hide genuine kernel regressions
pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="needs a real TPU backend")

B, S, H, D = 2, 1024, 4, 64
SCALE = 0.125

# Measured on a v5e chip 2026-07-30 (docs/chip_runs/20260730T221221Z):
# Mosaic's lowering requires the last two block dims be (8k, 128m) or whole;
# in [B, S, H, D] the head axis is second-to-last, so the bshd layout's
# squeezed (size-1) head block can never lower on hardware — structural,
# not a tolerance issue. The layout stays interpret-verified; production
# keeps "folded". strict=False so a future Mosaic that lifts the
# restriction doesn't turn this record into a bench-preflight failure.
BSHD = pytest.param(
    "bshd", 64,
    marks=pytest.mark.xfail(
        reason="Mosaic rejects a squeezed head axis as the second-to-last "
               "block dim (needs 8k/128m or whole-axis blocks)",
        strict=False))

# merged requires head_dim % 128 == 0 (Llama-2-7B geometry), so it runs
# at D=128; folded covers the D=64 SmolLM geometry
LAYOUT_D = [("folded", 64), BSHD, ("merged", 128)]


def _qkv(dtype, seed=0, d=D):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, d), jnp.float32).astype(dtype)
                 for k in ks)


@pytest.mark.parametrize("layout,d", LAYOUT_D)
def test_flash_forward_matches_sdpa_on_tpu(layout, d):
    from picotron_tpu.ops.attention import sdpa
    from picotron_tpu.ops.pallas.flash_attention import flash_attention

    q, k, v = _qkv(jnp.bfloat16, d=d)
    out = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, SCALE, layout=layout))(q, k, v)
    ref = jax.jit(lambda q, k, v: sdpa(q, k, v, SCALE, causal=True))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("layout,d", LAYOUT_D)
def test_flash_grads_match_sdpa_on_tpu(layout, d):
    from picotron_tpu.ops.attention import sdpa
    from picotron_tpu.ops.pallas.flash_attention import flash_attention

    q, k, v = _qkv(jnp.bfloat16, seed=1, d=d)

    def loss(attn):
        def f(q, k, v):
            o = attn(q, k, v)
            return (o.astype(jnp.float32) ** 2).mean()
        return f

    g_flash = jax.jit(jax.grad(loss(
        lambda q, k, v: flash_attention(q, k, v, SCALE, layout=layout)),
        argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss(
        lambda q, k, v: sdpa(q, k, v, SCALE, causal=True)), argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=3e-2, atol=3e-2, err_msg=f"d{name}")


def test_flash_block_grads_match_einsum_on_tpu():
    """The ring-attention building block: block backward fed out/lse must
    match AD through the einsum block on the chip (full-attend block, the
    ring's off-diagonal case)."""
    from picotron_tpu.ops.attention import block_attention
    from picotron_tpu.ops.pallas.flash_attention import (
        flash_attention_with_lse, flash_block_grads)

    q, k, v = _qkv(jnp.bfloat16, seed=2)
    out, lse = jax.jit(lambda q, k, v: flash_attention_with_lse(
        q, k, v, SCALE, causal=False))(q, k, v)
    do = jax.random.normal(jax.random.PRNGKey(3), out.shape,
                           jnp.float32).astype(out.dtype)
    dq, dk, dv = jax.jit(lambda q, k, v, o, l, do: flash_block_grads(
        q, k, v, o, l, do, SCALE, causal=False))(q, k, v, out, lse, do)

    def ref_f(q, k, v):
        o, _ = block_attention(q, k, v, SCALE, mask=None)  # full-attend block
        return (o.astype(jnp.float32) * do.astype(jnp.float32)).sum()

    rq, rk, rv = jax.jit(jax.grad(ref_f, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip((dq, dk, dv), (rq, rk, rv), "qkv"):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=3e-2, atol=3e-2, err_msg=f"d{name}")


def test_rmsnorm_matches_oracle_on_tpu():
    from picotron_tpu.ops.rmsnorm import rms_norm
    from picotron_tpu.ops.pallas.rmsnorm import rms_norm_pallas

    x = jax.random.normal(jax.random.PRNGKey(4), (4, 512, 2048),
                          jnp.float32).astype(jnp.bfloat16)
    w = (1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(5), (2048,),
                                       jnp.float32)).astype(jnp.bfloat16)
    y = jax.jit(lambda x, w: rms_norm_pallas(x, w, 1e-5))(x, w)
    ref = jax.jit(lambda x, w: rms_norm(x, w, 1e-5))(x, w)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)

    def f(norm):
        return lambda x, w: (norm(x, w, 1e-5).astype(jnp.float32) ** 2).mean()

    gx, gw = jax.jit(jax.grad(f(rms_norm_pallas), argnums=(0, 1)))(x, w)
    rx, rw = jax.jit(jax.grad(f(rms_norm), argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(rx, np.float32), rtol=3e-2, atol=3e-2)
    np.testing.assert_allclose(np.asarray(gw, np.float32),
                               np.asarray(rw, np.float32), rtol=3e-2, atol=3e-2)
