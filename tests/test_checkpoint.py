"""Checkpoint subsystem tests (SURVEY.md §5.4).

Covers what the reference never unit-tested: training-checkpoint save/resume
(reference checkpoint.py:242-278) including resume-under-a-different-topology
(unsupported in the reference — "Assume the topology is the same",
checkpoint.py:263 — but free with global sharded arrays), and the HF
safetensors name-map round trip (checkpoint.py:213-230).
"""

import numpy as np
import pytest

import jax

from picotron_tpu import checkpoint as ckpt
from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.models import llama
from picotron_tpu.topology import topology_from_config

from conftest import make_config

# multi-minute equivalence/e2e matrices: excluded from `make test`
# Only the multi-minute resume/equivalence matrices are excluded from the
# fast gate; the save->wait->load behavior and both HF bootstrap modes STAY
# in `make test` so regressions in the async-checkpoint path surface there.


def _train(cfg, topo, params, opt_state, loader, steps):
    step = ts.build_train_step(cfg, topo)
    loss = None
    for _ in range(steps):
        tokens, targets = ts.shard_batch(next(loader), topo)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    return params, opt_state, loss


def test_save_resume_bitwise(tiny_model_kwargs, tmp_path):
    """Train 2 steps, checkpoint, train 3 more; vs. resume-from-checkpoint
    and train the same 3: identical final loss."""
    cfg = make_config(tiny_model_kwargs, dp=2, tp=2, acc=1)
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    params, opt_state, _ = _train(cfg, topo, params, opt_state, loader, 2)

    mgr = ckpt.CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(2, params, opt_state, trained_tokens=2 * cfg.tokens_per_step)

    # continue original
    batches = [next(loader) for _ in range(3)]
    step = ts.build_train_step(cfg, topo)
    p1, o1 = params, opt_state
    for b in batches:
        tok, tgt = ts.shard_batch(b, topo)
        p1, o1, loss_orig = step(p1, o1, tok, tgt)

    # resume path: fresh state objects, restore, replay same batches
    p2, o2 = ts.init_state(cfg, topo, seed=123)  # different seed: must be overwritten
    p2, o2, got_step, got_tokens = mgr.load(p2, o2)
    assert got_step == 2
    assert got_tokens == 2 * cfg.tokens_per_step
    for b in batches:
        tok, tgt = ts.shard_batch(b, topo)
        p2, o2, loss_res = step(p2, o2, tok, tgt)

    assert float(loss_orig) == float(loss_res)
    mgr.close()


@pytest.mark.slow
def test_resume_under_different_topology(tiny_model_kwargs, tmp_path):
    """Save under dp=8, restore under tp=2/cp=2/dp=2 — the topology-change
    resharding the reference cannot do (checkpoint.py:263)."""
    cfg_a = make_config(tiny_model_kwargs, dp=8, mbs=1)
    topo_a = topology_from_config(cfg_a)
    params_a, opt_a = ts.init_state(cfg_a, topo_a)
    mgr = ckpt.CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, params_a, opt_a, trained_tokens=17)

    cfg_b = make_config(tiny_model_kwargs, dp=2, tp=2, cp=2, mbs=4)
    topo_b = topology_from_config(cfg_b)
    params_b, opt_b = ts.init_state(cfg_b, topo_b, seed=999)
    params_b, opt_b, step_no, tokens = mgr.load(params_b, opt_b)
    assert (step_no, tokens) == (1, 17)

    # values equal regardless of layout
    for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored arrays carry topology-B shardings, ready for the B train step
    loader = MicroBatchDataLoader(cfg_b)
    tok, tgt = ts.shard_batch(next(loader), topo_b)
    step = ts.build_train_step(cfg_b, topo_b)
    _, _, loss = step(params_b, opt_b, tok, tgt)
    assert np.isfinite(float(loss))
    mgr.close()


def test_corrupt_latest_falls_back_to_previous_step(tiny_model_kwargs, tmp_path):
    """Truncate the latest orbax step's largest file: ``load()`` must warn
    and restore the previous step; with every step corrupt it must raise a
    clean FileNotFoundError, not an orbax stack trace mid-restore."""
    from picotron_tpu.resilience.chaos import truncate_latest_checkpoint

    cfg = make_config(tiny_model_kwargs, dp=2, tp=2, acc=1)
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    loader = MicroBatchDataLoader(cfg)

    d = str(tmp_path / "ckpt")
    mgr = ckpt.CheckpointManager(d, io_attempts=1)
    mgr.save(1, params, opt_state, trained_tokens=10)
    params, opt_state, _ = _train(cfg, topo, params, opt_state, loader, 1)
    mgr.save(2, params, opt_state, trained_tokens=20)
    mgr.wait_until_finished()

    truncate_latest_checkpoint(d)  # step 2 is now partially written
    with pytest.warns(RuntimeWarning, match="corrupt or partially written"):
        p2, o2, step_no, tokens = mgr.load(params, opt_state)
    assert (step_no, tokens) == (1, 10)
    assert mgr.last_restored_step == 1
    assert np.isfinite(float(_train(cfg, topo, p2, o2, loader, 1)[2]))

    # corrupt the survivor too: a clean, typed failure
    import shutil

    shutil.rmtree(str(tmp_path / "ckpt" / "2"))
    truncate_latest_checkpoint(d)  # now step 1
    with pytest.warns(RuntimeWarning):
        with pytest.raises(FileNotFoundError, match="no readable checkpoint"):
            mgr.load(params, opt_state)
    mgr.close()


def test_mirror_replication_and_fallback(tiny_model_kwargs, tmp_path):
    """resilience.ckpt_mirror_dir: every committed save is replicated to
    the mirror tier; when EVERY primary step is corrupt, load()/
    load_params() fall back to the mirror and restore the same state —
    and with the mirror also gone, the failure is still a clean typed
    error."""
    import os

    cfg = make_config(tiny_model_kwargs, dp=2, tp=2, acc=1)
    topo = topology_from_config(cfg)
    params, opt_state = ts.init_state(cfg, topo)
    loader = MicroBatchDataLoader(cfg)

    d, m = str(tmp_path / "ckpt"), str(tmp_path / "mirror")
    mgr = ckpt.CheckpointManager(d, io_attempts=1, mirror_dir=m)
    mgr.save(1, params, opt_state, trained_tokens=10)
    params, opt_state, _ = _train(cfg, topo, params, opt_state, loader, 1)
    mgr.save(2, params, opt_state, trained_tokens=20)
    mgr.wait_until_finished()
    # replication is per committed step, atomic-rename committed
    assert sorted(os.listdir(m)) == ["1", "2"]
    assert not any(n.startswith(".tmp") for n in os.listdir(m))

    # corrupt BOTH primary steps: the primary-internal fallback is
    # exhausted and the restore must come from the mirror. Truncation is
    # targeted at the step's params item so the params-only serving
    # restore breaks too (the generic helper may hit an opt_state file).
    victim, size = None, -1
    for root, _, files in os.walk(os.path.join(d, "2", "params")):
        for f in files:
            p = os.path.join(root, f)
            if os.path.getsize(p) > size:
                victim, size = p, os.path.getsize(p)
    with open(victim, "r+b") as f:
        f.truncate(max(1, size // 2))
    import shutil

    shutil.rmtree(os.path.join(d, "1"))
    with pytest.warns(RuntimeWarning, match="falling back to the mirror"):
        p2, o2, step_no, tokens = mgr.load(params, opt_state)
    assert (step_no, tokens) == (2, 20)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # params-only restore (the serving path) takes the same fallback
    with pytest.warns(RuntimeWarning, match="falling back to the mirror"):
        p3, step_no, _ = mgr.load_params(params)
    assert step_no == 2
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p3)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # mirror gone too: a clean FileNotFoundError, not an orbax stack trace
    shutil.rmtree(m)
    mgr._mirror_mgr = None
    with pytest.warns(RuntimeWarning):
        with pytest.raises(FileNotFoundError, match="no readable"):
            mgr.load(params, opt_state)
    mgr.close()


def test_mirror_worker_survives_warnings_as_errors(tmp_path):
    """A warning raised INSIDE the mirror worker (e.g. the lag warning
    under ``-W error``) must not kill the worker thread: queued entries
    still get ``task_done`` and readers' ``_mirror_q.join()`` returns
    instead of deadlocking shutdown/restore, with the failure recorded."""
    import threading
    import warnings as w

    mgr = ckpt.CheckpointManager(str(tmp_path / "c"), io_attempts=1,
                                 mirror_dir=str(tmp_path / "m"))
    with w.catch_warnings():
        w.simplefilter("error")      # promote the worker's warnings
        mgr._spawn_mirror(99)        # no step 99 dir: the lag-skip path
        done = threading.Event()
        t = threading.Thread(
            target=lambda: (mgr._mirror_q.join(), done.set()), daemon=True)
        t.start()
        t.join(30)
    assert done.is_set()             # no deadlock: the batch completed
    assert mgr._mirror_errs          # ...and the failure was recorded
    with pytest.warns(RuntimeWarning, match="mirror"):
        mgr.close()                  # the join re-surfaces it to readers


def test_mirror_error_list_is_lock_guarded(tmp_path):
    """``_mirror_errs`` is appended by the worker thread and swapped out
    by ``_join_mirror`` from reader/emergency-save threads (picolint
    PICO-C004: there was no ordering between the two at all). Both sides
    must go through ``_mirror_mu``: an instrumented lock proves the
    record and the swap each take it, the retention cap holds, and a
    join surfaces every recorded error exactly once."""
    import queue
    import threading

    mgr = ckpt.CheckpointManager(str(tmp_path / "c"), io_attempts=1,
                                 mirror_dir=str(tmp_path / "m"))
    real = mgr._mirror_mu
    acquisitions = []

    class _Spy:
        def __enter__(self):
            acquisitions.append(threading.current_thread().name)
            return real.__enter__()

        def __exit__(self, *a):
            return real.__exit__(*a)

    mgr._mirror_mu = _Spy()
    for i in range(10):
        mgr._record_mirror_err(RuntimeError(f"boom{i}"))
    assert len(acquisitions) == 10
    assert len(mgr._mirror_errs) == 8      # bounded retention
    mgr._mirror_q = queue.Queue()          # join path, no live worker
    with pytest.warns(RuntimeWarning, match="boom0"):
        mgr._join_mirror()
    assert len(acquisitions) == 11         # the swap held the lock too
    assert mgr._mirror_errs == []          # drained exactly once
    mgr._join_mirror()                     # nothing left to re-surface
    mgr.close()


def test_mirror_through_train_entry(tiny_model_kwargs, tmp_path):
    """The config key wires through train(): a run with ckpt_mirror_dir
    replicates every periodic save, and a resume whose primary is fully
    corrupt completes from the mirror on the same trajectory."""
    import os
    import shutil

    from picotron_tpu.resilience.chaos import truncate_latest_checkpoint
    from picotron_tpu.train import train

    d, m = str(tmp_path / "ckpt"), str(tmp_path / "mirror")

    def cfg_with_mirror():
        cfg = make_config(tiny_model_kwargs, dp=2, tp=2, mbs=2, seq=32)
        cfg.training.total_train_steps = 4
        cfg.checkpoint.save_dir = d
        cfg.checkpoint.save_frequency = 2
        cfg.resilience.ckpt_mirror_dir = m
        cfg.resilience.io_attempts = 1
        return cfg

    hist_a = []
    steps, _, _ = train(cfg_with_mirror(), loss_history=hist_a)
    assert steps == 4
    assert {"2", "4"} <= set(os.listdir(m))

    # wipe one primary step, truncate the other: resume must come from
    # the mirror and replay the same losses
    shutil.rmtree(os.path.join(d, "2"))
    truncate_latest_checkpoint(d)
    cfg2 = cfg_with_mirror()
    cfg2.training.total_train_steps = 6
    hist_b = []
    with pytest.warns(RuntimeWarning, match="falling back to the mirror"):
        steps, _, _ = train(cfg2, loss_history=hist_b)
    assert steps == 6
    assert [s for s, _ in hist_b] == [5, 6]  # resumed at the mirrored step 4


def test_hf_safetensors_roundtrip(tiny_model_kwargs, tmp_path):
    """Export to HF naming, re-import, require exact tree equality and an
    identical forward — validates both directions of the name map
    (reference checkpoint.py:213-230) and the (out,in)↔(in,out) transpose."""
    cfg = make_config(tiny_model_kwargs, tp=1)
    params = llama.init_params(jax.random.PRNGKey(0), cfg.model)
    sft = str(tmp_path / "model.safetensors")
    ckpt.save_hf_safetensors(params, sft, cfg)

    topo = topology_from_config(cfg)
    loaded = ckpt.load_hf_safetensors(sft, cfg.model, topo)
    assert jax.tree.structure(params) == jax.tree.structure(loaded)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_hf_import_sharded_and_tied(tiny_model_kwargs, tmp_path):
    """Sharded index layout + tied-embeddings fallback: a checkpoint without
    lm_head.weight gets the embedding transpose as an untied head
    (reference always creates a fresh untied head, checkpoint.py:88-91)."""
    import json

    from safetensors.numpy import save_file

    cfg = make_config(tiny_model_kwargs)
    params = llama.init_params(jax.random.PRNGKey(1), cfg.model)
    full = {}
    ckpt.save_hf_safetensors(params, str(tmp_path / "tmp.safetensors"), cfg)
    from safetensors import safe_open

    with safe_open(str(tmp_path / "tmp.safetensors"), framework="np") as f:
        for k in f.keys():
            full[k] = f.get_tensor(k)
    del full["lm_head.weight"]  # tie

    # split across two shard files with an index
    names = sorted(full)
    half = len(names) // 2
    shards = {"model-00001.safetensors": names[:half],
              "model-00002.safetensors": names[half:]}
    d = tmp_path / "sharded"
    d.mkdir()
    weight_map = {}
    for fname, ks in shards.items():
        save_file({k: full[k] for k in ks}, str(d / fname))
        weight_map.update({k: fname for k in ks})
    with open(d / "model.safetensors.index.json", "w") as f:
        json.dump({"weight_map": weight_map}, f)

    loaded = ckpt.load_hf_safetensors(str(d), cfg.model)
    np.testing.assert_array_equal(
        np.asarray(loaded["lm_head"]), np.asarray(params["embed"]).T)
    np.testing.assert_array_equal(
        np.asarray(loaded["layers"]["wq"]), np.asarray(params["layers"]["wq"]))


def test_hf_int8_load_matches_bf16_within_scale(tiny_model_kwargs, tmp_path):
    """HF safetensors -> ``load_hf_safetensors(weight_dtype="int8")``:
    every matmul weight lands as a per-channel (int8, scales) pair whose
    dequantization matches the full-precision load within half a
    quantization step per channel; the streamed quantization is
    bit-identical to quantizing the loaded tree; embeddings/norms are
    untouched; TP sharding places scales with their channels."""
    from picotron_tpu.ops.pallas import quant_matmul as qm

    cfg = make_config(tiny_model_kwargs, tp=2)
    params = llama.init_params(jax.random.PRNGKey(3), cfg.model)
    sft = str(tmp_path / "model.safetensors")
    ckpt.save_hf_safetensors(params, sft, cfg)

    topo = topology_from_config(cfg)
    dense = ckpt.load_hf_safetensors(sft, cfg.model, topo)
    quant = ckpt.load_hf_safetensors(sft, cfg.model, topo,
                                     weight_dtype="int8")
    # streamed per-layer quantization == quantizing the whole loaded tree
    want = llama.quantize_params(dense)
    for k in llama.QUANT_WEIGHT_LEAVES:
        np.testing.assert_array_equal(
            np.asarray(quant["layers"][k]["q"]),
            np.asarray(want["layers"][k]["q"]))
        np.testing.assert_array_equal(
            np.asarray(quant["layers"][k]["s"]),
            np.asarray(want["layers"][k]["s"]))
        # dequant sits inside the per-channel absmax grid of the source
        deq = np.asarray(qm.dequantize_weight(
            quant["layers"][k]["q"], quant["layers"][k]["s"]))
        src = np.asarray(dense["layers"][k], np.float32)
        step = np.asarray(quant["layers"][k]["s"])
        assert np.all(np.abs(deq - src) <= step[:, None, :] / 2 + 1e-8), k
    np.testing.assert_array_equal(np.asarray(quant["embed"]),
                                  np.asarray(dense["embed"]))
    # scales shard over tp with their output channels (wq: column split)
    s = quant["layers"]["wq"]["s"]
    assert s.sharding.shard_shape(s.shape)[-1] == s.shape[-1] // 2

    # quantized params cannot round-trip back to HF (lossy serving format)
    with pytest.raises(ValueError, match="cannot be exported"):
        ckpt.save_hf_safetensors(quant, str(tmp_path / "no.safetensors"),
                                 cfg)


def test_hf_int8_quantizes_after_model_dtype_cast(tiny_model_kwargs,
                                                  tmp_path):
    """A file whose storage dtype differs from the model dtype (fp32
    export served under a bf16 config) must quantize the CAST weights —
    exactly what the dense path serves and what the fake-quant parity
    oracle (quantize-after-cast) reproduces — not the file's raw
    values."""
    cfg32 = make_config(tiny_model_kwargs)
    params = llama.init_params(jax.random.PRNGKey(9), cfg32.model)
    sft = str(tmp_path / "fp32.safetensors")
    ckpt.save_hf_safetensors(params, sft, cfg32)

    cfg16 = make_config(dict(tiny_model_kwargs, dtype="bfloat16"))
    dense = ckpt.load_hf_safetensors(sft, cfg16.model)  # casts to bf16
    quant = ckpt.load_hf_safetensors(sft, cfg16.model, weight_dtype="int8")
    want = llama.quantize_params(dense)
    for k in llama.QUANT_WEIGHT_LEAVES:
        np.testing.assert_array_equal(
            np.asarray(quant["layers"][k]["q"]),
            np.asarray(want["layers"][k]["q"]), err_msg=k)
        np.testing.assert_array_equal(
            np.asarray(quant["layers"][k]["s"]),
            np.asarray(want["layers"][k]["s"]), err_msg=k)


def test_load_params_int8_with_layout_remap(tiny_model_kwargs, tmp_path):
    """Orbax params-only restore with ``weight_dtype="int8"``: an
    uneven-pp-trained stack remaps to the contiguous pp=1 layout FIRST,
    then quantizes — the served tree equals quantizing a full-precision
    load, layer for layer (pad rows vanish before any scale exists)."""
    model = dict(tiny_model_kwargs, num_hidden_layers=5)
    cfg = make_config(model, pp=2, acc=2, mbs=2)
    params = llama.init_params(jax.random.PRNGKey(5), cfg.model, pp_size=2)
    mgr = ckpt.CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, params, {"dummy": jax.numpy.zeros(())}, trained_tokens=3,
             layout=(5, 2))
    mgr.wait_until_finished()

    like = jax.eval_shape(
        lambda k: llama.init_params(k, cfg.model), jax.random.PRNGKey(0))
    dense, step, _ = mgr.load_params(like, layout=(5, 1))
    quant, step_q, _ = mgr.load_params(like, layout=(5, 1),
                                       weight_dtype="int8")
    assert (step, step_q) == (1, 1)
    want = llama.quantize_params(dense)
    np.testing.assert_array_equal(np.asarray(quant["layers"]["wq"]["q"]),
                                  np.asarray(want["layers"]["wq"]["q"]))
    np.testing.assert_array_equal(np.asarray(quant["layers"]["wq"]["s"]),
                                  np.asarray(want["layers"]["wq"]["s"]))
    assert quant["layers"]["wq"]["q"].shape[0] == 5  # contiguous stack
    with pytest.raises(ValueError, match="weight_dtype"):
        mgr.load_params(like, weight_dtype="fp8")
    mgr.close()


def test_model_config_from_hf(tmp_path):
    import json

    hf = dict(num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
              hidden_size=32, intermediate_size=64, vocab_size=128,
              rms_norm_eps=1e-6, rope_theta=5000.0, max_position_embeddings=64,
              architectures=["LlamaForCausalLM"])
    p = tmp_path / "config.json"
    p.write_text(json.dumps(hf))
    got = ckpt.model_config_from_hf(str(p))
    assert got["hidden_size"] == 32 and got["rope_theta"] == 5000.0
    assert "architectures" not in got


@pytest.mark.slow
def test_resume_across_uneven_pp_layouts(tiny_model_kwargs, tmp_path):
    """Save under an uneven pp=2 split (5 layers -> padded [6] stack), restore
    under pp=1 ([5] stack) and under uneven pp=4 ([8] stack): real layer rows
    must land in the right padded positions and training must continue."""
    model = dict(tiny_model_kwargs, num_hidden_layers=5)
    cfg_a = make_config(model, pp=2, acc=2, mbs=2)
    topo_a = topology_from_config(cfg_a)
    params_a, opt_a = ts.init_state(cfg_a, topo_a)
    loader = MicroBatchDataLoader(cfg_a)
    params_a, opt_a, _ = _train(cfg_a, topo_a, params_a, opt_a, loader, 2)
    mgr = ckpt.CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(2, params_a, opt_a, trained_tokens=7, layout=(5, 2))

    from picotron_tpu.models.llama import pp_layer_layout
    K_a, _, pos_a = pp_layer_layout(5, 2)

    for pp_b, acc_b, mbs_b in ((1, 1, 4), (4, 4, 1)):
        cfg_b = make_config(model, pp=pp_b, acc=acc_b, mbs=mbs_b)
        topo_b = topology_from_config(cfg_b)
        params_b, opt_b = ts.init_state(cfg_b, topo_b, seed=999)
        params_b, opt_b, step_no, tokens = mgr.load(
            params_b, opt_b, layout=(5, pp_b))
        assert (step_no, tokens) == (2, 7)

        if pp_b == 1:
            pos_b = list(range(5))
        else:
            _, _, pos_b = pp_layer_layout(5, pp_b)
        wq_a = np.asarray(params_a["layers"]["wq"])
        wq_b = np.asarray(params_b["layers"]["wq"])
        np.testing.assert_array_equal(wq_b[pos_b], wq_a[pos_a])

        step = ts.build_train_step(cfg_b, topo_b)
        loader_b = MicroBatchDataLoader(cfg_b)
        tok, tgt = ts.shard_batch(next(loader_b), topo_b)
        _, _, loss = step(params_b, opt_b, tok, tgt)
        assert np.isfinite(float(loss))
    mgr.close()


@pytest.mark.slow
def test_train_entry_hf_bootstrap(tiny_model_kwargs, tmp_path):
    """checkpoint.hf_bootstrap_path through the real train() entry: exported
    weights must be what training starts from (the reference's bootstrap
    path, checkpoint.py:50-102)."""
    from picotron_tpu.train import train

    cfg0 = make_config(tiny_model_kwargs, seq=32, mbs=2)
    params = llama.init_params(jax.random.PRNGKey(7), cfg0.model)
    sft = str(tmp_path / "boot.safetensors")
    ckpt.save_hf_safetensors(params, sft, cfg0)

    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.training.total_train_steps = 2
    cfg.checkpoint.hf_bootstrap_path = sft
    # seed 42 init would differ from key-7 params; identical first-step loss
    # to a manual run from the exported params proves the bootstrap loaded
    from picotron_tpu import train_step as ts2
    from picotron_tpu.data import MicroBatchDataLoader as Loader

    topo = topology_from_config(cfg0)
    opt0 = ts2.build_optimizer(cfg0).init(params)
    step = ts2.build_train_step(cfg0, topo)
    loader = Loader(cfg0)
    tok, tgt = ts2.shard_batch(next(loader), topo)
    _, _, want_first_loss = step(params, opt0, tok, tgt)

    _, _, last_loss = train(cfg)
    assert np.isfinite(last_loss)
    # compare first-step losses by re-running train for 1 step
    cfg1 = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg1.training.total_train_steps = 1
    cfg1.checkpoint.hf_bootstrap_path = sft
    _, _, got_first_loss = train(cfg1)
    np.testing.assert_allclose(got_first_loss, float(want_first_loss),
                               rtol=1e-6, atol=1e-6)


def test_hf_bootstrap_reinit_keeps_random_init(tiny_model_kwargs, tmp_path):
    """checkpoint.hf_bootstrap_reinit reproduces the reference's re-randomize
    semantics (reference checkpoint.py:99-100): the safetensors file is
    validated as a shape template, but training starts from the seed-derived
    init — the first-step loss matches a no-bootstrap run, not the file."""
    from picotron_tpu.train import train

    cfg0 = make_config(tiny_model_kwargs, seq=32, mbs=2)
    params = llama.init_params(jax.random.PRNGKey(7), cfg0.model)
    sft = str(tmp_path / "boot.safetensors")
    ckpt.save_hf_safetensors(params, sft, cfg0)

    def one_step(**ckpt_kw):
        cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
        cfg.training.total_train_steps = 1
        for k, v in ckpt_kw.items():
            setattr(cfg.checkpoint, k, v)
        return train(cfg)[2]

    plain = one_step()
    reinit = one_step(hf_bootstrap_path=sft, hf_bootstrap_reinit=True)
    loaded = one_step(hf_bootstrap_path=sft)

    np.testing.assert_allclose(reinit, plain, rtol=1e-6, atol=1e-6)
    assert abs(loaded - plain) > 1e-6  # the file's values really differ


def test_hf_bootstrap_rejects_shape_mismatch(tiny_model_kwargs, tmp_path):
    """A template whose shapes disagree with the model config is an error in
    both bootstrap modes, not a silent mis-load."""
    from picotron_tpu.train import train

    other = dict(tiny_model_kwargs, hidden_size=tiny_model_kwargs["hidden_size"] * 2)
    cfg0 = make_config(other, seq=32, mbs=2)
    params = llama.init_params(jax.random.PRNGKey(7), cfg0.model)
    sft = str(tmp_path / "boot.safetensors")
    ckpt.save_hf_safetensors(params, sft, cfg0)

    cfg = make_config(tiny_model_kwargs, seq=32, mbs=2)
    cfg.training.total_train_steps = 1
    cfg.checkpoint.hf_bootstrap_path = sft
    cfg.checkpoint.hf_bootstrap_reinit = True
    with pytest.raises(ValueError, match="does not match the model config"):
        train(cfg)
