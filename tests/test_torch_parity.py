"""Step-for-step loss parity against an independent torch oracle.

SURVEY.md §7 flags "step-for-step loss parity with torch" as a hard
requirement of the rebuild. torch (CPU) is available here, so this test
implements the reference architecture *independently in torch* from its spec
(reference picotron/model.py: RMSNorm fp32 variance :66-85, HF rotate-half
RoPE :14-30, GQA repeat_interleave :141-142, SwiGLU :163-185, untied head
:226-271; torch AdamW defaults train.py:209), loads the JAX model's initial
weights into it, feeds both the same batches, and requires the two loss
trajectories to agree step for step in fp32.
"""

import jax
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from picotron_tpu import train_step as ts
from picotron_tpu.data import MicroBatchDataLoader
from picotron_tpu.topology import topology_from_config

STEPS = 6
LR, WD, B1, B2, EPS = 1e-3, 0.01, 0.9, 0.999, 1e-8


def _torch_rope(seq, head_dim, base):
    # reference get_cos_sin (model.py:21-30): fp32 on CPU, .repeat(1, 2)
    theta = 1.0 / (base ** (torch.arange(0, head_dim, 2, dtype=torch.int64)
                            .float() / head_dim))
    pos = torch.arange(seq).unsqueeze(1).float()
    ang = pos * theta
    return torch.cos(ang).repeat(1, 2), torch.sin(ang).repeat(1, 2)


def _rotate_half(x):
    h = x.shape[-1] // 2
    return torch.cat([-x[..., h:], x[..., :h]], dim=-1)


def _torch_forward(p, tokens, mcfg, cos, sin):
    """tokens: [B, S] long. Weights use the same (in, out) layout as the JAX
    pytree (x @ w == nn.Linear with transposed weight)."""
    nh, nkv, D = (mcfg["num_attention_heads"], mcfg["num_key_value_heads"],
                  mcfg["hidden_size"] // mcfg["num_attention_heads"])
    eps = mcfg.get("rms_norm_eps", 1e-5)

    def rms(x, w):
        var = x.float().pow(2).mean(-1, keepdim=True)
        return (x.float() * torch.rsqrt(var + eps)).to(x.dtype) * w

    h = p["embed"][tokens]
    B, S, H = h.shape
    L = p["layers"]["wq"].shape[0]
    for i in range(L):
        lp = {k: v[i] for k, v in p["layers"].items()}
        x = rms(h, lp["attn_norm"])
        q = (x @ lp["wq"]).view(B, S, nh, D).transpose(1, 2)
        k = (x @ lp["wk"]).view(B, S, nkv, D).transpose(1, 2)
        v = (x @ lp["wv"]).view(B, S, nkv, D).transpose(1, 2)
        q = q * cos[None, None] + _rotate_half(q) * sin[None, None]
        k = k * cos[None, None] + _rotate_half(k) * sin[None, None]
        k = k.repeat_interleave(nh // nkv, dim=1)
        v = v.repeat_interleave(nh // nkv, dim=1)
        o = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        o = o.transpose(1, 2).reshape(B, S, nh * D)
        h = h + o @ lp["wo"]
        x = rms(h, lp["mlp_norm"])
        h = h + (F.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
    x = rms(h, p["final_norm"])
    return x @ p["lm_head"]


def _to_torch_params(params):
    def conv(x):
        return torch.nn.Parameter(torch.from_numpy(np.array(x)).float())

    return {
        "embed": conv(params["embed"]),
        "layers": {k: conv(v) for k, v in params["layers"].items()},
        "final_norm": conv(params["final_norm"]),
        "lm_head": conv(params["lm_head"]),
    }


@pytest.mark.parametrize("gqa", [True, False])
def test_loss_trajectory_matches_torch_oracle(tiny_model_kwargs, gqa):
    from tests.conftest import make_config

    mk = dict(tiny_model_kwargs)
    if not gqa:
        mk["num_key_value_heads"] = mk["num_attention_heads"]
    cfg = make_config(mk, seq=32, mbs=2)  # conftest sets learning_rate=1e-3 == LR
    topo = topology_from_config(cfg)

    # ---- JAX side ----
    params, opt_state = ts.init_state(cfg, topo)
    init_np = jax.tree.map(lambda x: np.asarray(x), params)
    step = ts.build_train_step(cfg, topo)
    loader = MicroBatchDataLoader(cfg)
    batches = [next(loader) for _ in range(STEPS)]
    jax_losses = []
    for b in batches:
        tok, tgt = ts.shard_batch(b, topo)
        params, opt_state, loss = step(params, opt_state, tok, tgt)
        jax_losses.append(float(loss))

    # ---- torch oracle ----
    tp = _to_torch_params(init_np)
    flat = [tp["embed"], *tp["layers"].values(), tp["final_norm"], tp["lm_head"]]
    opt = torch.optim.AdamW(flat, lr=LR, betas=(B1, B2), eps=EPS,
                            weight_decay=WD)
    m = cfg.model
    cos, sin = _torch_rope(cfg.training.seq_length, m.head_dim, m.rope_theta)
    mcfg = dict(num_attention_heads=m.num_attention_heads,
                num_key_value_heads=m.num_key_value_heads,
                hidden_size=m.hidden_size, rms_norm_eps=m.rms_norm_eps)
    torch_losses = []
    for b in batches:
        tokens = torch.from_numpy(b["input_ids"][0]).long()
        targets = torch.from_numpy(b["target_ids"][0]).long()
        logits = _torch_forward(tp, tokens, mcfg, cos, sin)
        loss = F.cross_entropy(logits.view(-1, logits.shape[-1]),
                               targets.reshape(-1))
        opt.zero_grad()
        loss.backward()
        opt.step()
        torch_losses.append(float(loss.detach()))

    np.testing.assert_allclose(jax_losses, torch_losses, rtol=2e-4, atol=2e-5)
