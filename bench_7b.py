"""Llama-2-7B-geometry proxy benchmark on the available chip(s).

The reference's second headline is 38% MFU training Llama-2-7B on 8xH100
(reference README.md:7; BASELINE ladder configs 4-5). A full 7B with
optimizer state does not fit one 16 GB v5e chip, so this benches a *proxy*
with the exact 7B layer geometry (hidden 4096, intermediate 11008, 32 heads,
vocab 32000, seq 4096, remat=full, fused linear+CE) at the best-throughput
(layers, micro-batch) point that fits — larger batches beat more layers for
MFU. Per-layer math, kernel shapes, and memory behavior match the real
model; MFU is computed against the proxy's own parameter count, which
*understates* the full-model MFU (the LM head is amortized over fewer
layers than the real model's 32).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} with
vs_baseline = mfu / 38. Executed results are committed in docs/BENCH_7B.md.
"""

from __future__ import annotations

import json
import sys

from picotron_tpu.bench_record import BENCH_METRICS

LLAMA2_7B_GEOM = dict(
    name="meta-llama/Llama-2-7b (proxy geometry)",
    num_attention_heads=32, num_key_value_heads=32, hidden_size=4096,
    intermediate_size=11008, vocab_size=32000, max_position_embeddings=4096,
    dtype="bfloat16", attention_impl="auto",
)


def proxy_cfg(layers: int, mbs: int, seq: int, on_tpu: bool):
    from picotron_tpu.config import Config

    model = dict(LLAMA2_7B_GEOM, num_hidden_layers=layers)
    if not on_tpu:  # CPU smoke: shrink everything
        model.update(num_hidden_layers=2, hidden_size=256,
                     intermediate_size=688, vocab_size=1024,
                     num_attention_heads=4, num_key_value_heads=4,
                     dtype="float32", attention_impl="sdpa",
                     max_position_embeddings=512)
        seq, mbs = 128, 2
    return Config.from_dict({
        "distributed": {"dp_size": 1, "pp_size": 1, "cp_size": 1, "tp_size": 1},
        "model": model,
        "training": {"seq_length": seq, "micro_batch_size": mbs,
                     "gradient_accumulation_steps": 1, "remat": "full",
                     "grad_accum_dtype": "param", "learning_rate": 3e-4},
        "dataset": {"name": "synthetic"},
    })


def weight_bytes(m, weight_dtype: str = "bf16") -> int:
    """Serving-weight bytes of a model config: every matmul weight at the
    storage format (bf16 = 2 bytes/element; int8 = 1 byte + one fp32
    scale per output channel — ops/pallas/quant_matmul.py), embeddings
    and norms always full precision. Pure arithmetic, mirroring
    llama.param_bytes over the tree checkpoint.load_* builds."""
    get = (m.__getitem__ if isinstance(m, dict)
           else lambda k: getattr(m, k))  # dict geometry or ModelConfig
    H, I, V, L = (get("hidden_size"), get("intermediate_size"),
                  get("vocab_size"), get("num_hidden_layers"))
    D = H // get("num_attention_heads")
    Hq = get("num_attention_heads") * D
    Hkv = get("num_key_value_heads") * D
    # (in, out) shapes of the quantizable matmuls, per layer + the head
    mats = [(H, Hq), (H, Hkv), (H, Hkv), (Hq, H),
            (H, I), (H, I), (I, H)]
    per_layer_mat = sum(i * o for i, o in mats)
    per_layer_scales = sum(o for _, o in mats)
    fp = 2  # bf16 bytes/element
    full = (V * H + H) * fp + L * 2 * H * fp  # embed + final norm + norms
    head = (H * V, V)
    if weight_dtype == "int8":
        return (full + L * (per_layer_mat + 4 * per_layer_scales)
                + head[0] + 4 * head[1])
    return full + fp * (L * per_layer_mat + head[0])


def serve_fit_report(hbm_bytes: int = 16 << 30, seq: int = 4096) -> dict:
    """The memory-headroom story int8 weights exist for: the deepest
    (layers, micro_batch) serving point — layers of the Llama-2-7B
    geometry, micro_batch = concurrent bf16-KV decode slots at the bench
    seq length — that fits one chip's HBM, per weight format. ESTIMATED
    from arithmetic (weights + per-slot KV bytes vs HBM), not measured —
    the field the TPU A/B validates once the tunnel returns. At the full
    32-layer depth, bf16 weights eat ~13.5 GB of a 16 GB v5e and strand
    a single slot; int8 (~6.8 GB) serves the SAME checkpoint with ~4x
    the decode batch — the whole point of the feature."""
    out = {}
    for wd in ("bf16", "int8"):
        for layers in (32, 24, 16, 8):
            m = dict(LLAMA2_7B_GEOM, num_hidden_layers=layers)
            D = m["hidden_size"] // m["num_attention_heads"]
            kv_slot = (2 * layers * seq
                       * m["num_key_value_heads"] * D * 2)  # bf16 K+V
            wb = weight_bytes(m, wd)
            mb = (hbm_bytes - wb) // kv_slot
            if mb >= 1:
                out[wd] = {"layers": layers, "micro_batch": int(mb),
                           "weight_bytes_total": wb}
                break
    return out


def main():
    import os

    from bench import (_cpu_pinned, _honor_cpu_env, orchestrate,
                       run_inner_guarded)

    _honor_cpu_env()
    if not _cpu_pinned() and "--inner" not in sys.argv:
        orchestrate(os.path.abspath(__file__),
                    metric=BENCH_METRICS["bench_7b"], unit="%")
        return
    run_inner_guarded(inner_main)


def inner_main():
    from bench import kernel_parity_preflight, run_descending

    parity = kernel_parity_preflight()  # before the parent holds the chip
    from picotron_tpu.models import llama
    from picotron_tpu.utils import get_mfu, on_tpu, peak_flops_per_chip

    tpu = on_tpu()
    if tpu:
        if "passed" not in parity or "skipped" in parity:
            raise SystemExit(
                f"parent backend is TPU but the kernel parity preflight did "
                f"not run on TPU: {parity!r}")
        print(f"# TPU kernel parity: {parity}", file=sys.stderr)
    # (layers, mbs) candidates: larger batches beat more layers for MFU
    # (measured on the v5e: 6 layers @ mbs4 = 66.7% vs 8 @ mbs2 = 62.6%),
    # and fewer layers *understate* full-model MFU (the LM head amortizes
    # over fewer layers), so preferring the batch is the conservative
    # choice. Ordered best-expected-MFU first; memory-infeasible entries
    # fall through via run_descending.
    run_kw = dict(calls=4, warmup=1, steps_per_call=8)
    cfg, tok_s = run_descending(
        ((8, 4), (6, 4), (8, 2), (6, 2), (8, 1), (6, 1), (4, 1))
        if tpu else ((2, 2),),
        lambda lm: proxy_cfg(lm[0], lm[1], 4096, tpu),
        tag="bench_7b", **run_kw)
    if tpu:
        from bench import try_flash_layout_ab

        # identical timing kwargs keep the layout A/B apples-to-apples
        cfg, tok_s = try_flash_layout_ab(cfg, tok_s, **run_kw)

    m = cfg.model
    n_params = llama.num_params(m)
    peak = peak_flops_per_chip()
    # the memory-headroom fields int8 weights exist for (ROADMAP item 3):
    # the measured geometry's weight bytes in both storage formats, and
    # the estimated deepest (layers, micro_batch) serving point per
    # format — int8 must come in at <= 55% of bf16 (tests/test_bench.py)
    weights = {"weight_dtype": "bf16",
               "weight_bytes_total": weight_bytes(m, "bf16"),
               "weight_bytes_total_int8": weight_bytes(m, "int8"),
               "serve_fit": serve_fit_report()}
    if peak is None:
        print(json.dumps({"metric": "llama2_7b_proxy_tokens_per_sec_cpu_smoke",
                          "value": round(tok_s, 1), "unit": "tokens/s",
                          "vs_baseline": 0.0, **weights}))
        return
    mfu = get_mfu(tok_s, n_params, m.num_hidden_layers, m.hidden_size,
                  cfg.training.seq_length, peak)
    print(json.dumps({"metric": BENCH_METRICS["bench_7b"],
                      "value": round(mfu, 2), "unit": "%",
                      "vs_baseline": round(mfu / 38.0, 3), **weights}))
    print(f"# layers={m.num_hidden_layers} mbs={cfg.training.micro_batch_size} "
          f"seq={cfg.training.seq_length} flash={m.flash_layout} "
          f"tokens/s/chip={tok_s:.0f} "
          f"params={n_params/1e9:.2f}B peak={peak/1e12:.0f}TF",
          file=sys.stderr)


if __name__ == "__main__":
    main()
