#!/usr/bin/env python
"""Repo-root shim matching the reference UX: ``python extract_metrics.py <sweep_dir>``."""

from picotron_tpu.tools.extract_metrics import main

if __name__ == "__main__":
    raise SystemExit(main())
