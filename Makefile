# picotron_tpu build/test entry points.
NATIVE_SO := picotron_tpu/native/_build/libpicotron_data.so
NATIVE_SRC := picotron_tpu/native/dataloader.cc

.PHONY: native test bench clean

native: $(NATIVE_SO)

$(NATIVE_SO): $(NATIVE_SRC)
	mkdir -p $(dir $@)
	g++ -O3 -shared -fPIC -std=c++17 $< -o $@

test: native
	python -m pytest tests/ -x -q

bench: native
	python bench.py

clean:
	rm -rf picotron_tpu/native/_build
