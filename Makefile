# picotron_tpu build/test entry points.
NATIVE_SO := picotron_tpu/native/_build/libpicotron_data.so
NATIVE_SRC := picotron_tpu/native/dataloader.cc

.PHONY: native test test-all test-isolated bench lint decode-smoke spec-smoke kernel-smoke quant-smoke paged-smoke chaos-smoke chaos-pod-smoke serve-smoke serve-chaos-smoke router-chaos-smoke disagg-smoke dp-smoke tenant-smoke fleet-chaos-smoke fleet-bench obs-smoke overlap-smoke mixed-smoke clean

native: $(NATIVE_SO)

$(NATIVE_SO): $(NATIVE_SRC)
	mkdir -p $(dir $@)
	g++ -O3 -shared -fPIC -std=c++17 $< -o $@

# Fast gate: picolint first (pure-AST, ~1s — a lock-discipline or
# hot-path regression fails before any test imports jax), then the
# not-slow test matrix — ~6 min on one core. `make test-all` runs
# everything.
test: native lint
	python -m pytest tests/ -x -q -m "not slow"

test-all: native lint
	python -m pytest tests/ -x -q
	$(MAKE) obs-smoke
	$(MAKE) quant-smoke
	$(MAKE) router-chaos-smoke
	$(MAKE) disagg-smoke
	$(MAKE) dp-smoke
	$(MAKE) tenant-smoke
	$(MAKE) fleet-chaos-smoke
	$(MAKE) overlap-smoke
	$(MAKE) mixed-smoke

# picolint static analysis (picotron_tpu/analysis/, docs/ANALYSIS.md):
# JAX hot-path rules (host syncs on traced values, trace-time
# nondeterminism, program_id-in-loop-body, jit-in-loop recompiles) +
# concurrency rules (lock-order inversions, blocking under a lock,
# unguarded shared mutation) over the whole package. Exit 1 on any
# finding not in analysis/baseline.json. `--json` variant for trends:
#   python -m picotron_tpu.tools.lint --json > lint.json
lint:
	python -m picotron_tpu.tools.lint --fail-on-new

# One pytest process per test file: the XLA CPU runtime's in-process
# collective rendezvous can abort the interpreter on rare races, and process
# isolation keeps one crash from taking down the rest of the suite.
test-isolated: native
	@fail=0; for f in tests/test_*.py; do \
	  echo "== $$f"; \
	  python -m pytest "$$f" -q || fail=1; \
	done; exit $$fail

bench: native
	python bench.py

# Serving-path smoke: tiny-model CPU generate through the full
# prefill/KV-cache/batcher/CLI stack (picotron_tpu/inference) — seconds,
# no checkpoint or network needed. Runs the blocked decode fast path
# (on-device stop state, one host sync per block) and the int8 KV cache,
# then the blocked-decode bench so dispatches-per-token shows up in logs.
decode-smoke:
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke \
	  --kv-cache-dtype int8 --decode-block-len 4
	JAX_PLATFORMS=cpu python bench_decode.py --block-len 8

# Speculative-decoding smoke: draft-verify generation (prompt-lookup
# drafter, one verify dispatch per accepted run) through the CLI, then
# the spec bench on repetitive prompts — dispatches-per-token under the
# spec-off baseline of 1 with a nonzero accept rate in the JSON line —
# and the CONTROLLER run: a mixed repetitive/random-prompt workload
# through the real batcher with inference.spec_controller enabled, so
# spec_len_effective / accept_rate_by_drafter / controller-decision
# counts land in the JSON trajectory (docs/INFERENCE.md "Self-tuning
# speculation").
spec-smoke:
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke \
	  --spec-len 4
	JAX_PLATFORMS=cpu python bench_decode.py --spec-len 4
	JAX_PLATFORMS=cpu python bench_decode.py --spec-len 4 --spec-auto

# Flash-decode kernel parity (ops/pallas/decode_attention.py) in Pallas
# interpret mode on CPU: flash vs dense allclose across S=1 decode,
# speculative verify, chunked prefill; bf16/fp32 AND int8 caches; ragged
# lengths, stale rows, GQA down to nkv=1, non-dividing KV blocks;
# double-buffered DMA pinned bitwise against the serial fetch — plus the
# engine-level wiring proof for inference.attend_impl and the on-device
# sampling epilogue's seeded host-equivalence. Closes with the
# mixed-rung bench: every PR-11 ladder rung ON in one run (pipelined
# flash DMA over paged pages, hot_bf16 per-page policy, fused sampling
# epilogue), so the JSON line carries the full A/B field set
# (kv_bytes_per_token, logits_bytes_to_host_per_token,
# dispatch_latency_s) the TPU A/B matrix diffs. The serving default
# stays dense, so decode-smoke/spec-smoke GENERATION output is
# unchanged.
kernel-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_decode_kernel.py \
	  tests/test_sampling_epilogue.py -q
	JAX_PLATFORMS=cpu python bench_decode.py --attend-impl flash \
	  --kv-layout paged --kv-page-policy hot_bf16 --sample-on-device \
	  --block-len 8

# Quantized-weights smoke (ops/pallas/quant_matmul.py, docs/INFERENCE.md
# "Quantized weights"): per-channel int8 weights through the full
# generate CLI with --check-weight-parity — greedy generations must be
# IDENTICAL to a bf16 engine fed the fake-quant reference (the
# quantization error is in both; any difference is the fused dequant
# pipeline itself), on tp=1 here and tp=1/2 in tier-1
# (tests/test_quant_weights.py). Closes with the int8 bench so
# weight_bytes_total/weight_bytes_per_token land in the JSON trajectory
# next to the bf16 default's. The serving default stays bf16, so
# decode/spec/paged-smoke output is unchanged.
quant-smoke:
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke \
	  --weight-dtype int8 --check-weight-parity
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke \
	  --weight-dtype int8 --check-weight-parity --kv-cache-dtype int8 \
	  --decode-block-len 4
	JAX_PLATFORMS=cpu python bench_decode.py --weight-dtype int8 \
	  --block-len 8

# Paged-KV smoke (inference/paged_kv.py): a shared-prefix batch through
# the page-pool layout (block-table indirection, radix prefix sharing,
# copy-on-write) with --check-layout-parity asserting every request's
# tokens are IDENTICAL to the contiguous layout — fp32 and int8 caches —
# then the paged bench so kv_pages_*/pool utilization land in the JSON
# trajectory. tests/test_paged_kv.py is the full tier-1 matrix.
paged-smoke:
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke \
	  --kv-layout paged --check-layout-parity \
	  --prompt-ids "1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,17,18" \
	  --prompt-ids "1,2,3,4,5,6,7,8,9,10,11,12,13,14,21,22" \
	  --prompt-ids "1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,31"
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke \
	  --kv-layout paged --check-layout-parity --kv-cache-dtype int8 \
	  --decode-block-len 4 \
	  --prompt-ids "1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,17,18" \
	  --prompt-ids "1,2,3,4,5,6,7,8,9,10,11,12,13,14,21,22"
	JAX_PLATFORMS=cpu python bench_decode.py --kv-layout paged --block-len 8

# Fault-injection suite on a CPU mesh (picotron_tpu/resilience/): chaos
# SIGTERM/crash/NaN/truncation at fixed steps, kill->resume bit-for-bit
# equivalence, corrupt-checkpoint fallback, supervisor restart bounds.
chaos-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q

# Pod-scale chaos drills (resilience/cluster.py, docs/MULTIHOST.md): a
# REAL 2-process jax.distributed CPU pod under tools/supervise.py
# --num-procs. Chaos-preempt one rank -> preemption consensus takes the
# same coordinated emergency save on both ranks (75/75, no hang) and the
# relaunch resumes bit-for-bit; chaos-SIGKILL one rank -> the peer's
# cluster monitor exits 77 within peer_timeout_s instead of wedging in
# gloo, and the pod restarts together. A few minutes (pytest.mark.slow;
# the fast consensus/monitor units are tier-1 in tests/test_cluster.py).
chaos-pod-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_cluster_pod.py -q

# HTTP serving front end smoke (tools/serve.py, docs/SERVING.md): start
# the server on an ephemeral port with the tiny CPU model, check
# /healthz //readyz, POST one request, stream a second, then SIGTERM —
# the in-flight request finishes, the drain is clean, and every counter
# accounts. Exits nonzero on any malfunction.
serve-smoke:
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.serve --smoke

# Observability smoke (picotron_tpu/obs, docs/OBSERVABILITY.md): the
# serve smoke drive with its telemetry checks — /metrics agreeing with
# /statz, a timed /profilez capture — saving the drive's /tracez JSON,
# then tools/trace_dump.py re-validates the saved trace from scratch and
# requires a COMPLETE parented request chain (queue_wait -> prefill ->
# every dispatch -> delivery). Runs inside `make test-all`.
OBS_SMOKE_DIR := /tmp/picotron-obs-smoke
obs-smoke:
	rm -rf $(OBS_SMOKE_DIR) $(OBS_SMOKE_DIR)-overlap
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.serve --smoke \
	  --obs-dump $(OBS_SMOKE_DIR)
	python -m picotron_tpu.tools.trace_dump $(OBS_SMOKE_DIR)/trace.json \
	  --require-request-chain
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.serve --smoke \
	  --overlap --obs-dump $(OBS_SMOKE_DIR)-overlap
	python -m picotron_tpu.tools.trace_dump \
	  $(OBS_SMOKE_DIR)-overlap/trace.json \
	  --require-request-chain --require-overlap-chain

# Zero-bubble overlapped-scheduling smoke (inference.overlap,
# docs/INFERENCE.md "Overlapped scheduling"): the bench_decode
# --overlap ab protocol — the SAME batcher workload with the pipeline
# off then on, synthetic device windows + injected per-token host work.
# Gates bit-identical token streams, overlap-on dispatch-gap p50
# <= 0.5x overlap-off, and tokens/s >= 1.3x with host work and device
# time comparable. Runs inside `make test-all`; the serving default
# stays overlap OFF, so decode/spec-smoke output is unchanged.
overlap-smoke:
	JAX_PLATFORMS=cpu python bench_decode.py --overlap ab

# Mixed prefill-decode dispatch smoke (inference.mixed_dispatch,
# docs/INFERENCE.md "Mixed prefill-decode dispatch"): the bench_decode
# --mixed ab protocol — long prompts arriving mid-decode with the fused
# lane off then on, plus a decoders-only TPOT floor leg. Gates
# bit-identical token streams, decode TPOT p95 under concurrent prefill
# <= 3x the no-prefill floor, TTFT p95 <= 3x the serial+gate baseline
# (a CPU-proxy allowance: a solo B=1 chunk dispatch here is ~3x cheaper
# than a fused round), and prompt tokens actually moved through the lane
# (picotron_prefill_lane_tokens_total). Runs inside `make test-all`;
# the serving default stays mixed_dispatch OFF, so every other smoke's
# output is unchanged.
mixed-smoke:
	JAX_PLATFORMS=cpu python bench_decode.py --mixed ab

# Multi-replica router chaos drill (tools/router.py, docs/SERVING.md
# "Multi-replica fabric"): 3 in-process serve.py replicas behind the
# prefix-affinity router; kill one mid-stream (the spliced client stream
# must be BIT-IDENTICAL to an unfaulted greedy run, replays=1, no token
# duplicated or dropped), flap/stall a second through the circuit
# breaker's open -> half-open -> closed walk with zero client-visible
# errors, inject scrape failures (candidate drop without a breaker
# trip), drain a third gracefully — with every request accounted in the
# router's own /metrics and a route -> attempt[n] -> replay span chain
# in /tracez. The same drill runs in tier-1 (tests/test_router.py).
router-chaos-smoke:
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.router --smoke

# Prefill/decode disaggregation interference bench (ISSUE 15,
# docs/SERVING.md "Disaggregated prefill/decode"): decode-stream TPOT
# with long shared-prefix prompts arriving mid-stream, measured three
# ways — no interference (baseline), colocated (the long prefills run
# inside the decode batcher's own loop and stall every stream), and a
# disaggregated prefill+decode two-role fleet behind the router (the
# prefills land on the prefill worker, finished KV pages stream to the
# decode worker, its batcher never spends a dispatch on them). Greedy
# streams asserted bit-identical across all three phases; the JSON
# records tpot_p95_{baseline,colocated,disagg}, handoff bytes/latency,
# and the cluster-wide prefix hit rate. Exit nonzero unless the
# colocated configuration measurably degrades past the disaggregated
# one. CPU proxy (subprocess replicas = one interpreter per role).
disagg-smoke:
	JAX_PLATFORMS=cpu python bench_decode.py --disagg

# dp-sharded continuous batching smoke (ISSUE 18, inference/engine.py,
# docs/INFERENCE.md "dp-sharded batching"): a REAL dp=2 batcher on the
# forced multi-device CPU mesh vs the dp=1 baseline — gates bit-identical
# greedy streams, slots_total = dp x slots_per_shard, a comm_trace-verified
# collective-free decode hot path, and at least one cross-shard slot
# migration driven by the occupancy-rebalance planner.
dp-smoke:
	JAX_PLATFORMS=cpu python bench_decode.py --dp 2

# Multi-tenant serving smoke (ISSUE 16, inference/tenancy.py,
# docs/SERVING.md "Multi-tenant serving"): the adapter-parity gate —
# greedy generations through the segmented multi-LoRA matmul must be
# IDENTICAL to an adapter-less engine fed the merged-weight (W + BA)
# reference — on the int8 base (the fake-quant error is in both; any
# difference is the segmented adapter path itself), then the
# mixed-tenant bench: 3 adapters + base-only rows in ONE continuous
# batch, per-tenant tokens/dpt/TTFT and adapter_bytes_per_token in the
# JSON trajectory. The serving default stays adapter-less, so every
# other smoke's output is unchanged.
tenant-smoke:
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke \
	  --weight-dtype int8 --adapter 4 --check-adapter-parity
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.generate --smoke \
	  --adapter 4:7:0.5 --check-adapter-parity --kv-layout paged \
	  --spec-len 3
	JAX_PLATFORMS=cpu python bench_decode.py --tenants 3 --adapter-rank 4 \
	  --weight-dtype int8

# Elastic fleet chaos drill (ISSUE 17, tools/fleet.py, docs/SERVING.md
# "Elastic fleet"): the controller bootstraps a 3-worker fleet against
# an EMPTY router through the dynamic replica-set admin API, then the
# acceptance drill under live traffic — SIGKILL a worker holding an
# in-flight stream (the fleet replaces it within the restart-budget
# ladder while the router replays the stream exactly-once, greedy
# bit-identical), stall the controller's scrape plane (stale must never
# read as dead: no replacement storm), inject an admission spike (a grow
# decision within the cooloff window, zero requests shed), then the
# scale-down drain back to min_workers (zero in-flight lost, hot radix
# prefixes relocated to a survivor, replica deregistered) — with every
# decision accounted in picotron_fleet_* counters. Exits nonzero on any
# malfunction.
fleet-chaos-smoke:
	JAX_PLATFORMS=cpu python -m picotron_tpu.tools.fleet --smoke

# Elasticity latency bench (ISSUE 17): a real 3-worker SUBPROCESS fleet
# (serve.py under supervise --serve; a SIGKILL is a real process-group
# death) behind the router under the controller — the JSON records
# scale_up_latency_s, replace_latency_s, ttft_p95_during_spike vs
# ttft_p95_steady. Minutes on CPU (three cold jax startups are part of
# what it measures), so it rides outside test-all.
fleet-bench:
	JAX_PLATFORMS=cpu python bench_decode.py --fleet

# Serving chaos suite (tests/test_serving.py): dispatch-exception,
# latency-spike, and poisoned-logits faults through the engine hooks —
# no hangs, every submitted request terminates with an accounted
# finish_reason (eos|length|timeout|shed|error), unaffected requests are
# bit-identical to a chaos-off run; plus slot-failure isolation, the
# flash->dense degradation ladder, admission control, and drain.
serve-chaos-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q

clean:
	rm -rf picotron_tpu/native/_build
