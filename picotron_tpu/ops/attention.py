"""Attention: XLA scaled-dot-product reference path + LSE-returning block form.

The reference has a 3-way backend switch in Attention.forward
(picotron/model.py:147-157): ring attention (CP), flash-attn CUDA kernel, or
torch SDPA. Here:

- ``sdpa`` is the XLA path (and CPU test oracle): fp32 softmax, causal mask.
- ``block_attention`` additionally returns the log-sum-exp per query row; it is
  the building block that the ring-attention loop merges across K/V blocks
  (LSE-merge numerics spec: reference context_parallel.py:112-128, 157-187).
- the Pallas TPU flash-attention kernel lives in ops/pallas/flash_attention.py.

All functions take q/k/v with the SAME number of heads — GQA repetition
(reference model.py:141-142 repeat_interleave) happens in the model, so its
gradient (sum over repeated heads) falls out of autodiff.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: keeps softmax rows with no
# visible keys finite (they appear in ring attention's skipped blocks)


def _causal_mask(s_q: int, s_k: int, q_offset) -> jnp.ndarray:
    """[s_q, s_k] boolean, True = attend. Query i (global position q_offset+i)
    may see key j (global position given by the caller's block layout)."""
    qi = jnp.arange(s_q)[:, None] + q_offset
    kj = jnp.arange(s_k)[None, :]
    return qi >= kj


def block_attention(
    q: jnp.ndarray,  # [B, Sq, H, D]
    k: jnp.ndarray,  # [B, Sk, H, D]
    v: jnp.ndarray,  # [B, Sk, H, D]
    scale: float,
    mask: Optional[jnp.ndarray] = None,  # [Sq, Sk] or broadcastable, True=attend
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (out float32 [B, Sq, H, D], lse float32 [B, Sq, H])."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    # a fully-masked row has m = NEG_INF, p = 1, lse ~ NEG_INF + log(s_k):
    # finite garbage whose tiny LSE makes ring attention's merge discard it
    p = jnp.exp(scores - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    lse = (m + jnp.log(denom))[..., 0]  # [B, H, Sq]
    out = jnp.einsum("bhqk,bkhd->bqhd", p / denom, v.astype(jnp.float32))
    return out, lse.transpose(0, 2, 1)  # lse -> [B, Sq, H]


def sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float,
    causal: bool = True,
) -> jnp.ndarray:
    """Plain attention, fp32 softmax, output cast back to q.dtype."""
    mask = _causal_mask(q.shape[1], k.shape[1], 0) if causal else None
    out, _ = block_attention(q, k, v, scale, mask)
    return out.astype(q.dtype)
