"""Cross-entropy over (possibly vocab-sharded) logits.

The reference gathers tensor-parallel logits before the loss — the final
projection is ColumnParallel with gather_output=True
(tensor_parallel.py:48-50, all-gather at tp_communications.py:51-72) and the
loss is plain F.cross_entropy (train.py:46-49). ``cross_entropy_gathered``
reproduces that. ``cross_entropy_vocab_parallel`` is the TPU-native fast path:
it never materializes the gathered [B,S,V] tensor, computing the global
log-sum-exp and target logit with a pmax/psum pair over 'tp' instead
(selected by model.gather_logits=False).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_gathered(logits_local, targets, tp_axis: str = "tp"):
    """logits_local: [B, S, V/tp] shard; targets: [B, S] global token ids.
    Returns mean loss (float32 scalar)."""
    logits = jax.lax.all_gather(logits_local, tp_axis, axis=-1, tiled=True)
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - target_logit)


def cross_entropy_vocab_parallel(logits_local, targets, tp_axis: str = "tp"):
    """Same value as cross_entropy_gathered without materializing full logits."""
    logits32 = logits_local.astype(jnp.float32)
    v_local = logits32.shape[-1]
    shard = jax.lax.axis_index(tp_axis)
    vocab_start = shard * v_local

    local_max = jnp.max(logits32, axis=-1)
    # stop_gradient (inside, so pmax never sees a tangent — it has no
    # differentiation rule) is exact: the max shift cancels analytically in
    # logz - target_logit.
    global_max = jax.lax.pmax(jax.lax.stop_gradient(local_max), tp_axis)
    sumexp = jnp.sum(jnp.exp(logits32 - global_max[..., None]), axis=-1)
    global_sumexp = jax.lax.psum(sumexp, tp_axis)
    logz = global_max + jnp.log(global_sumexp)

    local_ids = targets - vocab_start
    in_range = (local_ids >= 0) & (local_ids < v_local)
    safe_ids = jnp.clip(local_ids, 0, v_local - 1)
    picked = jnp.take_along_axis(logits32, safe_ids[..., None], axis=-1)[..., 0]
    target_logit = jax.lax.psum(jnp.where(in_range, picked, 0.0), tp_axis)
    return jnp.mean(logz - target_logit)
