"""Cross-entropy over (possibly vocab-sharded) logits.

The reference gathers tensor-parallel logits before the loss — the final
projection is ColumnParallel with gather_output=True
(tensor_parallel.py:48-50, all-gather at tp_communications.py:51-72) and the
loss is plain F.cross_entropy (train.py:46-49). ``cross_entropy_gathered``
reproduces that. ``cross_entropy_vocab_parallel`` is the TPU-native fast path:
it never materializes the gathered [B,S,V] tensor, computing the global
log-sum-exp and target logit with a pmax/psum pair over 'tp' instead
(selected by model.gather_logits=False).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from picotron_tpu.utils import pvary_like


def cross_entropy_gathered(logits_local, targets, tp_axis: str = "tp"):
    """logits_local: [B, S, V/tp] shard; targets: [B, S] global token ids.
    Returns mean loss (float32 scalar)."""
    # invariant-typed under the vma checker (keeps the loss and its h
    # cotangent tp-invariant), the plain public gather otherwise — see
    # parallel.tp.all_gather_dim_invariant
    from picotron_tpu.parallel.tp import all_gather_dim_invariant

    logits = all_gather_dim_invariant(logits_local, tp_axis, -1)
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - target_logit)


def cross_entropy_vocab_parallel(logits_local, targets, tp_axis: str = "tp"):
    """Same value as cross_entropy_gathered without materializing full logits."""
    logits32 = logits_local.astype(jnp.float32)
    v_local = logits32.shape[-1]
    shard = jax.lax.axis_index(tp_axis)
    vocab_start = shard * v_local

    local_max = jnp.max(logits32, axis=-1)
    # stop_gradient (inside, so pmax never sees a tangent — it has no
    # differentiation rule) is exact: the max shift cancels analytically in
    # logz - target_logit.
    global_max = jax.lax.pmax(jax.lax.stop_gradient(local_max), tp_axis)
    sumexp = jnp.sum(jnp.exp(logits32 - global_max[..., None]), axis=-1)
    global_sumexp = jax.lax.psum(sumexp, tp_axis)
    logz = global_max + jnp.log(global_sumexp)

    local_ids = targets - vocab_start
    in_range = (local_ids >= 0) & (local_ids < v_local)
    safe_ids = jnp.clip(local_ids, 0, v_local - 1)
    picked = jnp.take_along_axis(logits32, safe_ids[..., None], axis=-1)[..., 0]
    target_logit = jax.lax.psum(jnp.where(in_range, picked, 0.0), tp_axis)
    return jnp.mean(logz - target_logit)


# --------------------------------------------------------------------------- #
# fused linear + cross-entropy (row-chunked, logits never fully materialized)
# --------------------------------------------------------------------------- #


def _chunk_logz(x_c, w, t_c, tp_axis):
    """Per-chunk fp32 (logz [tc], target_logit [tc]); collectives over the
    vocab-sharded axis as in cross_entropy_vocab_parallel."""
    logits = (x_c @ w).astype(jnp.float32)  # [tc, Vl]
    v_local = logits.shape[-1]
    vocab_start = lax.axis_index(tp_axis) * v_local
    local_max = jnp.max(logits, axis=-1)
    global_max = lax.pmax(lax.stop_gradient(local_max), tp_axis)
    sumexp = jnp.sum(jnp.exp(logits - global_max[:, None]), axis=-1)
    logz = global_max + jnp.log(lax.psum(sumexp, tp_axis))
    local_ids = t_c - vocab_start
    in_range = (local_ids >= 0) & (local_ids < v_local)
    safe_ids = jnp.clip(local_ids, 0, v_local - 1)
    picked = jnp.take_along_axis(logits, safe_ids[:, None], axis=-1)[:, 0]
    target_logit = lax.psum(jnp.where(in_range, picked, 0.0), tp_axis)
    return logz, target_logit


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def cross_entropy_fused(x, w, targets, tp_axis: str = "tp",
                        chunk_rows: int = 1024):
    """Mean CE of ``x @ w`` without materializing the [T, V] logits.

    x: [B, S, H] (already tp-copied), w: [H, V/tp] vocab-sharded LM head,
    targets: [B, S] global ids. Rows are processed in chunks of
    ``chunk_rows``; the backward recomputes each chunk's logits (one extra
    head matmul, ~2·T·H·V FLOPs — a few % of a training step) instead of
    keeping fp32 logits + softmax + dlogits alive, which at Llama vocab
    sizes is multiple GB of HBM. The TPU analogue of fused CE losses used
    on GPU (the reference just calls F.cross_entropy on gathered logits,
    train.py:46-49 — same value, very different memory).

    Gradient note: the returned dx is this shard's partial (local vocab
    columns only); the surrounding ``tp_copy``'s backward psum completes it,
    exactly as for a column-parallel linear."""
    loss, _ = _fused_fwd_impl(x, w, targets, tp_axis, chunk_rows)
    return loss


def _chunks(x2, t, chunk_rows):
    """Split rows into ceil(T/chunk) chunks, zero-padding the tail; the
    returned fp32 mask marks real rows (padding must contribute neither loss
    nor gradient). Without padding a non-divisible T would silently fall back
    to one full-size chunk — the exact fp32-logits blowup this path avoids."""
    T = x2.shape[0]
    tc = min(chunk_rows, T)
    n = -(-T // tc)
    pad = n * tc - T
    if pad:
        x2 = jnp.concatenate([x2, jnp.zeros((pad, x2.shape[1]), x2.dtype)])
        t = jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
    mask = (jnp.arange(n * tc) < T).astype(jnp.float32)
    return x2.reshape(n, tc, -1), t.reshape(n, tc), mask.reshape(n, tc), n


def _fused_fwd_impl(x, w, targets, tp_axis, chunk_rows):
    H = x.shape[-1]
    x2, t = x.reshape(-1, H), targets.reshape(-1)
    T = x2.shape[0]
    xc, tc, mc, _ = _chunks(x2, t, chunk_rows)

    def body(acc, inp):
        x_c, t_c, m_c = inp
        logz, tl = _chunk_logz(x_c, w, t_c, tp_axis)
        return acc + jnp.sum((logz - tl) * m_c), logz

    total, logz_all = lax.scan(
        body, pvary_like(jnp.zeros((), jnp.float32), x, w, targets),
        (xc, tc, mc))
    return total / T, logz_all.reshape(-1)


def _fused_fwd(x, w, targets, tp_axis, chunk_rows):
    loss, logz = _fused_fwd_impl(x, w, targets, tp_axis, chunk_rows)
    return loss, (x, w, targets, logz)


def _fused_bwd(tp_axis, chunk_rows, res, g):
    x, w, targets, logz = res
    H = x.shape[-1]
    x2, t = x.reshape(-1, H), targets.reshape(-1)
    T = x2.shape[0]
    xc, tc, mc, n = _chunks(x2, t, chunk_rows)
    lzc = logz.reshape(n, -1)
    v_local = w.shape[-1]
    scale = (g / T).astype(jnp.float32)

    def body(dw_acc, inp):
        x_c, t_c, m_c, logz_c = inp
        logits = (x_c @ w).astype(jnp.float32)
        p = jnp.exp(logits - logz_c[:, None])
        vocab_start = lax.axis_index(tp_axis) * v_local
        local_ids = t_c - vocab_start
        in_range = (local_ids >= 0) & (local_ids < v_local)
        onehot = (jax.nn.one_hot(jnp.clip(local_ids, 0, v_local - 1),
                                 v_local, dtype=jnp.float32)
                  * in_range[:, None].astype(jnp.float32))
        dlog = ((p - onehot) * (scale * m_c)[:, None]).astype(w.dtype)
        dx_c = lax.dot_general(dlog, w, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
        dw_acc = dw_acc + lax.dot_general(
            x_c, dlog, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dw_acc, dx_c.astype(x.dtype)

    dw, dxc = lax.scan(
        body, pvary_like(jnp.zeros(w.shape, jnp.float32), x, w, targets, g),
        (xc, tc, mc, lzc))
    dx = dxc.reshape(-1, H)[:T].reshape(x.shape)
    dt = np.zeros(targets.shape, jax.dtypes.float0)
    return dx, dw.astype(w.dtype), dt


cross_entropy_fused.defvjp(_fused_fwd, _fused_bwd)
