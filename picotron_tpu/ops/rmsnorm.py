"""RMSNorm.

Numerics spec from the reference's pure-torch LlamaRMSNorm
(picotron/model.py:66-85): variance in float32, ``x * rsqrt(var + eps)`` cast
back to the input dtype, then scaled by the (learned) weight. The reference's
fast path is a Triton kernel (TritonRMSNorm, model.py:38-64); the TPU-native
fast path is the Pallas kernel in picotron_tpu/ops/pallas/rmsnorm.py — this
module is the XLA-fused formulation used on CPU and as the oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = (x32 * jax.lax.rsqrt(var + eps)).astype(dtype)
    return normed * weight
