"""Rotary position embeddings (GPT-NeoX / HF-Llama rotate_half convention).

Numerics spec from the reference (picotron/model.py:15-30): inverse frequencies
computed in float32, angle table cos/sin(pos * theta) tiled to head_dim
(torch ``.repeat(1, 2)`` = concatenation), cast to compute dtype once; applied
as ``x * cos + rotate_half(x) * sin`` with rotate_half = [-x2, x1]. The
reference fuses this with a CUDA kernel when FLASH_ATTEN=1 (model.py:130-136);
on TPU the mul/add chain fuses into the surrounding matmuls under XLA, so no
Pallas kernel is needed for parity.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def precompute_rope(seq_length: int, head_dim: int, base: float, dtype) -> tuple:
    """Return (cos, sin), each [seq_length, head_dim], computed in float64/32
    on host for stable numerics (reference computes on CPU fp32, model.py:23)."""
    assert head_dim % 2 == 0
    inv_freq = 1.0 / (base ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    pos = np.arange(seq_length, dtype=np.float64)[:, None]  # [S, 1]
    angles = pos * inv_freq[None, :]  # [S, head_dim/2]
    cos = np.concatenate([np.cos(angles), np.cos(angles)], axis=-1)
    sin = np.concatenate([np.sin(angles), np.sin(angles)], axis=-1)
    return jnp.asarray(cos, dtype=dtype), jnp.asarray(sin, dtype=dtype)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [batch, seq, heads, head_dim]; cos/sin: [seq, head_dim] shared
    across the batch (training), or [batch, seq, head_dim] per-sequence
    tables (KV-cache decode, where each slot sits at its own position —
    see ``rope_at_positions``)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    if cos.ndim == 3:
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]
    else:
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
    return x * c + rotated * s


def rope_at_positions(cos: jnp.ndarray, sin: jnp.ndarray,
                      pos: jnp.ndarray) -> tuple:
    """Gather per-sequence angle rows for decode-at-offset: ``pos`` is [B]
    (one new token per sequence) or [B, S]; returns [B, S, head_dim] tables
    that ``apply_rope`` broadcasts over heads. Out-of-table positions clamp
    to the last row (callers bound generation by max_seq_len)."""
    if pos.ndim == 1:
        pos = pos[:, None]
    pos = jnp.clip(pos, 0, cos.shape[0] - 1)
    return jnp.take(cos, pos, axis=0), jnp.take(sin, pos, axis=0)
