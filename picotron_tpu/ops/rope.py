"""Rotary position embeddings (GPT-NeoX / HF-Llama rotate_half convention).

Numerics spec from the reference (picotron/model.py:15-30): inverse frequencies
computed in float32, angle table cos/sin(pos * theta) tiled to head_dim
(torch ``.repeat(1, 2)`` = concatenation), cast to compute dtype once; applied
as ``x * cos + rotate_half(x) * sin`` with rotate_half = [-x2, x1]. The
reference fuses this with a CUDA kernel when FLASH_ATTEN=1 (model.py:130-136);
on TPU the mul/add chain fuses into the surrounding matmuls under XLA, so no
Pallas kernel is needed for parity.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def precompute_rope(seq_length: int, head_dim: int, base: float, dtype) -> tuple:
    """Return (cos, sin), each [seq_length, head_dim], computed in float64/32
    on host for stable numerics (reference computes on CPU fp32, model.py:23)."""
    assert head_dim % 2 == 0
    inv_freq = 1.0 / (base ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    pos = np.arange(seq_length, dtype=np.float64)[:, None]  # [S, 1]
    angles = pos * inv_freq[None, :]  # [S, head_dim/2]
    cos = np.concatenate([np.cos(angles), np.cos(angles)], axis=-1)
    sin = np.concatenate([np.sin(angles), np.sin(angles)], axis=-1)
    return jnp.asarray(cos, dtype=dtype), jnp.asarray(sin, dtype=dtype)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [batch, seq, heads, head_dim]; cos/sin: [seq, head_dim]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return x * c + rotated * s
