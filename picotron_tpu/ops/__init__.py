from picotron_tpu.ops.rope import precompute_rope, apply_rope  # noqa: F401
from picotron_tpu.ops.rmsnorm import rms_norm  # noqa: F401
from picotron_tpu.ops.attention import sdpa, block_attention  # noqa: F401
