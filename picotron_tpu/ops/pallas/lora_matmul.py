"""Segmented multi-LoRA matmul: one dispatch, many adapters.

The weight-side half of multi-tenant serving (inference/tenancy.py):
every tenant's low-rank adapter pair lives stacked in one
``[T, in, r]`` / ``[T, r, out]`` pack, and a per-row adapter-id vector
``ids [B]`` selects which pair each batch row runs — so ONE
decode/verify/prefill dispatch mixes tenants (S-LoRA / Punica's
segmented-gather matmul, adapted to our leaf-form dispatch seam). The
base matmul — dense bf16 or the PR 13 fused int8 dequant — is untouched:
the adapter contributes an ADDITIVE fp32 residual

    residual[b] = (x[b] @ a[ids[b]]) @ b[ids[b]]

added onto the base output at the ``models/llama.py::matmul`` seam.

Slot 0 of every pack is the reserved NULL adapter (A = B = 0), so
base-only rows ride the same dispatch and their residual is exactly
zero — adding it never changes a base value beyond the sign of a zero,
which no comparison downstream observes. An engine with no adapter pack
configured never builds adapter leaves at all, so default serving traces
byte-identical programs to the pre-tenancy build.

Two implementations behind one entry point, ``lora_matmul(x, a, b,
ids)``:

- **Pallas kernel** (TPU, or ``interpret=True`` for the CPU parity
  suite): a ``(B,)`` grid with ``ids`` as a scalar-prefetch operand
  (``pltpu.PrefetchScalarGridSpec``) — the BlockSpec index maps read
  ``ids_ref[b]`` so each grid instance's A/B blocks are DMA'd straight
  from the chosen adapter's pack rows; no gathered copy of the adapter
  ever materializes in HBM. Per instance: two tiny MXU contractions
  ([S, K] @ [K, r] then [S, r] @ [r, out]) with fp32 accumulation.
- **XLA fallback** (off-TPU serving / any platform): ``a[ids]`` /
  ``b[ids]`` gathers plus two batched einsums with the same fp32
  accumulation — identical math, XLA's gather instead of prefetched
  index maps.

The rank axis r is tiny (8-64) next to the lane quantum; the kernel
trades a sliver of lane utilization for zero gather traffic, which is
the right trade at decode batch sizes. Shapes with huge S (long prefill
chunks) stay bounded because S rides inside one grid instance's block —
the chunked prefill's C is already the VMEM-sized unit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from picotron_tpu.utils import on_tpu

# Adapter packs store fp32: the residual accumulates in fp32 end to end,
# and adapter bytes are negligible next to the base weights they modify.
ADAPTER_DTYPE = jnp.float32

# The reserved null adapter every pack carries in slot 0 (A = B = 0):
# base-only rows point here and their residual is exactly zero.
NULL_ADAPTER = 0


def is_lora_weight(leaf) -> bool:
    """Whether a parameter leaf is an adapter-wrapped weight — the dict
    form ``{"w": base_leaf, "a": [T, in, r], "b": [T, r, out],
    "ids": [B]}`` the model's matmul sites dispatch on
    (models/llama.py::matmul). ``w`` may itself be the quantized
    ``{"q", "s"}`` pair — the base dispatch recurses."""
    return isinstance(leaf, dict) and set(leaf) == {"w", "a", "b", "ids"}


# --------------------------------------------------------------------------- #
# the kernel
# --------------------------------------------------------------------------- #


def _lora_kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
    """One batch row's adapter residual. The grid instance's A/B blocks
    were already steered to ``ids[b]``'s pack rows by the scalar-prefetch
    index maps — the kernel body never sees the id, only its adapter.
    Both contractions accumulate in fp32 (rank is tiny; precision is
    free)."""
    del ids_ref  # consumed by the BlockSpec index maps, not the body
    xb = x_ref[0].astype(jnp.float32)  # [S, K]
    t = lax.dot_general(xb, a_ref[0], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)  # [S, r]
    o_ref[0] = lax.dot_general(t, b_ref[0], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def lora_matmul_pallas(x, a, b, ids, *, interpret: bool = False):
    """The Pallas path: x [B, S, K], a [T, K, r], b [T, r, N], ids [B]
    int32 -> [B, S, N] fp32. Grid is one instance per batch row; ``ids``
    rides as the scalar-prefetch operand so each instance's a/b
    BlockSpecs index straight into its adapter's pack rows."""
    B, S, K = x.shape
    T, _, r = a.shape
    N = b.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, S, K), lambda bi, ids_ref: (bi, 0, 0)),
            pl.BlockSpec((1, K, r), lambda bi, ids_ref: (ids_ref[bi], 0, 0)),
            pl.BlockSpec((1, r, N), lambda bi, ids_ref: (ids_ref[bi], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, S, N), lambda bi, ids_ref: (bi, 0, 0)),
    )
    return pl.pallas_call(
        _lora_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, N), jnp.float32),
        interpret=interpret,
    )(ids, x, a, b)


def lora_matmul_xla(x, a, b, ids):
    """The XLA fallback (off-TPU serving and any non-Pallas platform):
    gather each row's adapter pair, then the same two fp32-accumulated
    contractions as the kernel — batched einsums instead of a grid."""
    ag = a[ids]  # [B, K, r]
    bg = b[ids]  # [B, r, N]
    t = jnp.einsum("bsk,bkr->bsr", x.astype(jnp.float32), ag,
                   preferred_element_type=jnp.float32)
    return jnp.einsum("bsr,brn->bsn", t, bg,
                      preferred_element_type=jnp.float32)


def lora_matmul(x, a, b, ids, *, impl: str | None = None,
                interpret: bool = False):
    """Per-row adapter residual ``(x[b] @ a[ids[b]]) @ b[ids[b]]``.

    x: [B, S, in] activations (any float dtype); a: [T, in, r] fp32
    stacked adapter down-projections; b: [T, r, out] fp32 stacked
    up-projections; ids: [B] int32 adapter slots (0 = the null adapter —
    exact zero residual). Returns [B, S, out] fp32.

    ``impl``: "pallas" | "xla" | None (auto: the Pallas kernel on TPU,
    the XLA gather-einsum elsewhere — quant_matmul's dispatch rule).
    ``interpret`` forces the Pallas interpreter (the CPU parity suite).
    """
    if x.ndim != 3:
        raise ValueError(f"lora_matmul expects x [B, S, in]; got {x.shape}")
    if a.ndim != 3 or b.ndim != 3 or a.shape[2] != b.shape[1] \
            or a.shape[0] != b.shape[0]:
        raise ValueError(
            f"adapter pack shapes disagree: a {a.shape} (want [T, in, r]) "
            f"vs b {b.shape} (want [T, r, out])")
    if impl is None:
        impl = "pallas" if (on_tpu() or interpret) else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown lora_matmul impl {impl!r} (pallas|xla)")
    ids = jnp.asarray(ids, jnp.int32)
    if impl == "pallas":
        return lora_matmul_pallas(x, a, b, ids, interpret=interpret)
    return lora_matmul_xla(x, a, b, ids)
