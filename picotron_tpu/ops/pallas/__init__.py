"""Pallas TPU kernels: the native fast path.

The reference's performance rests on external CUDA/Triton kernels —
flash-attn (model.py:32-36,151-153) and TritonRMSNorm (model.py:38-64).
These are their TPU-native equivalents, written against Mosaic via Pallas.
"""
