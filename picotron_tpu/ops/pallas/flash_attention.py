"""Flash attention as a Pallas TPU kernel (fwd + custom-VJP bwd).

TPU-native equivalent of the reference's flash-attn CUDA dependency
(reference picotron/model.py:7,32-36,151-153; pinned flash-attn==2.5.0).
Same asymptotics as FlashAttention-2: O(S) memory (never materializes the
[S, S] score matrix in HBM), online softmax in fp32, log-sum-exp saved for
the backward, which re-derives P per block.

Three data layouts share the same kernel bodies (``model.flash_layout``):

- "folded" (default, battle-tested): the model's [B, S, H, D] is folded to
  [B*H, S, D] around the pallas_call; the grid walks (batch*head, q-block).
  The fold is a host-side transpose+reshape copy of every operand per call.
- "bshd" (interpret-mode only — REJECTED on hardware): the kernels consume
  [B, S, H, D] directly — grid (batch, head, q-block), the head dimension
  squeezed out by a size-None BlockSpec entry — avoiding the fold's
  transpose copies. Measured on a v5e chip 2026-07-30
  (docs/chip_runs/20260730T221221Z/kernel_parity.log): Mosaic refuses to
  lower it — the last two block dims must be (8k, 128m) or span the whole
  axis, and in [B, S, H, D] the head axis is second-to-last, so a
  squeezed head block is structurally un-lowerable regardless of D. The
  only hardware paths are (a) this folded layout or (b) the "merged"
  layout below. "folded" stays the production default; bshd remains as
  the interpret-mode record of the experiment.
- "merged" (head_dim % 128 == 0 geometries, e.g. Llama-2-7B's D=128): the
  [B, S, H, D] operands are viewed as [B, S, H*D] — a free reshape, minor
  dims merge — and the head grid axis selects a D-wide LANE-aligned slice
  of the last dim, which Mosaic accepts. Same zero-transpose-copy win the
  bshd experiment wanted, within the tiling rules.

K/V for one head live whole in VMEM (S*D*2B ~ 1 MB at S=8192, D=64)
while scores exist only as a [block_q, block_k] VMEM tile — the MXU sees
(block_q x D) @ (D x block_k) and (block_q x block_k) @ (block_k x D)
matmuls, all 128-aligned. The per-row LSE is materialized with a broadcast
128-lane minor dim ([BH, S, 128] / [B, S, H, 128]) — Mosaic requires the
last two block dims be (8k, 128m), so a lane-less layout can't be tiled
per-q-block (the in-tree TPU flash kernel uses the same trick).

Causality is handled at two levels: whole key-blocks strictly above the
diagonal are skipped (the fori_loop upper bound), the diagonal block gets an
iota mask. The softmax-backward row term delta = rowsum(dO * O) is computed
in-kernel from the O/dO blocks. GQA repetition happens in the model before
the call (as the reference repeats before its kernel, model.py:141-142).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl

NEG_INF = -1e30
LANE = 128  # minor-dim width for the broadcast LSE layout

# 512 measured ~1.6x faster than 256 on v5e at S=2048, D=64 (the QK^T and
# PV matmuls are contraction/width-limited by D=64, so bigger tiles amortize
# better); VMEM still fits the fp32 [bq, bk] score tile comfortably.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _pick_block(seq: int, want: int) -> int:
    b = min(want, seq)
    while seq % b:
        b //= 2
    return max(b, 1)


def causal_kv_blocks(nk, q_hi, block_k):
    """Leading ``block_k``-row KV blocks that intersect key positions
    ``<= q_hi`` — the causal block-skip bound. Shared machinery: the
    training flash forward/backward kernels bound their key walk with it
    (``q_hi`` = the q-tile's last row position), and the decode/chunked-
    prefill kernel (ops/pallas/decode_attention.py) reuses it with
    ``q_hi`` additionally clipped to the slot's live length, so early
    prefill chunks and short sequences alike skip whole blocks instead of
    masking them."""
    return jnp.minimum(nk, (q_hi + block_k) // block_k)


def _causal_band(s, q0, k0, bq, bk):
    qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_q,
                block_k, causal, blk_axis=1):
    # Matmul inputs stay in their native dtype (bf16 in training) with fp32
    # accumulation via preferred_element_type — fp32 MXU issue rate is 1/8
    # of bf16 on TPU, so casting q/k/v up would throttle the whole kernel.
    # Softmax state (m, l, acc) is fp32. blk_axis: which grid axis walks the
    # q-blocks (1 = folded (BH, nq) grid, 2 = bshd (B, H, nq) grid).
    qi = pl.program_id(blk_axis)
    q = q_ref[0]  # [bq, D]
    seq_k = k_ref.shape[1]
    nk = seq_k // block_k
    if causal:
        # key blocks that intersect rows <= this q block's last row
        nk = causal_kv_blocks(nk, (qi + 1) * block_q - 1, block_k)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_band(s, qi * block_q, j * block_k, block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    bq, d = q.shape
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = lax.fori_loop(0, nk, body, (acc0, m0, l0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), (bq, LANE))


def _fwd(q, k, v, scale, causal, block_q, block_k, layout="folded"):
    """folded: q [BH,Sq,D] -> (out [BH,Sq,D], lse [BH,Sq,LANE]).
    bshd/merged: q [B,Sq,H,D] -> (out [B,Sq,H,D], lse [B,Sq,H,LANE]).
    LSE is the broadcast-lane fp32 layout. Sq and Sk may differ
    (ring-attention half blocks); causal requires Sq == Sk (aligned
    positions)."""
    sq, sk = q.shape[1], k.shape[1]
    d = q.shape[-1]
    assert not causal or sq == sk
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    post = lambda out, lse: (out, lse)
    if layout == "merged":
        # [B, S, H, D] viewed as [B, S, H*D] (free: minor dims merge), the
        # head index a grid axis selecting a D-wide lane slice — needs
        # D % 128 == 0 to satisfy Mosaic's lane tiling, and in exchange the
        # kernels consume the model layout with ZERO transpose copies.
        b, h = q.shape[0], q.shape[2]
        q, k, v = (x.reshape(x.shape[0], x.shape[1], h * d)
                   for x in (q, k, v))
        grid = (b, h, sq // bq)
        blk_axis = 2
        in_specs = [
            pl.BlockSpec((1, bq, d), lambda b_, hh, i: (b_, i, hh)),
            pl.BlockSpec((1, sk, d), lambda b_, hh, i: (b_, 0, hh)),
            pl.BlockSpec((1, sk, d), lambda b_, hh, i: (b_, 0, hh)),
        ]
        out_specs = [
            pl.BlockSpec((1, bq, d), lambda b_, hh, i: (b_, i, hh)),
            pl.BlockSpec((1, bq, LANE), lambda b_, hh, i: (b_, i, hh)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((b, sq, h * d), q.dtype),
            jax.ShapeDtypeStruct((b, sq, h * LANE), jnp.float32),
        ]
        post = lambda out, lse: (out.reshape(b, sq, h, d),
                                 lse.reshape(b, sq, h, LANE))
    elif layout == "folded":
        bh = q.shape[0]
        grid = (bh, sq // bq)
        blk_axis = 1
        in_specs = [
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ]
        out_specs = [
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, LANE), lambda b, i: (b, i, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, LANE), jnp.float32),
        ]
    else:
        b, h = q.shape[0], q.shape[2]
        grid = (b, h, sq // bq)
        blk_axis = 2
        in_specs = [
            pl.BlockSpec((1, bq, None, d), lambda b, hh, i: (b, i, hh, 0)),
            pl.BlockSpec((1, sk, None, d), lambda b, hh, i: (b, 0, hh, 0)),
            pl.BlockSpec((1, sk, None, d), lambda b, hh, i: (b, 0, hh, 0)),
        ]
        out_specs = [
            pl.BlockSpec((1, bq, None, d), lambda b, hh, i: (b, i, hh, 0)),
            pl.BlockSpec((1, bq, None, LANE), lambda b, hh, i: (b, i, hh, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((b, sq, h, d), q.dtype),
            jax.ShapeDtypeStruct((b, sq, h, LANE), jnp.float32),
        ]
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_q=bq, block_k=bk,
                          causal=causal, blk_axis=blk_axis),
        grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape,
    )(q, k, v)
    return post(out, lse)


# --------------------------------------------------------------------------- #
# backward
# --------------------------------------------------------------------------- #


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *,
                   scale, block_q, block_k, causal, blk_axis=1):
    qi = pl.program_id(blk_axis)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, 0:1]
    delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                    axis=1, keepdims=True)
    seq_k = k_ref.shape[1]
    nk = seq_k // block_k
    if causal:
        nk = causal_kv_blocks(nk, (qi + 1) * block_q - 1, block_k)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_band(s, qi * block_q, j * block_k, block_q, block_k)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, nk, body, jnp.zeros(q.shape, jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, *, scale, block_q, block_k, causal,
                    blk_axis=1):
    kj = pl.program_id(blk_axis)
    k = k_ref[0]  # [bk, D]
    v = v_ref[0]
    seq_q = q_ref.shape[1]
    nq = seq_q // block_q
    # first q block that can see this k block
    j0 = (kj * block_k) // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        o = o_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), 0:1]
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=1, keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_band(s, i * block_q, kj * block_k, block_q, block_k)
        p = jnp.exp(s - lse)
        pt = p.astype(do.dtype)
        dv = dv + jax.lax.dot_general(pt, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros(k.shape, jnp.float32)
    dk, dv = lax.fori_loop(j0, nq, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, layout, res, dout):
    q, k, v, out, lse_c = res
    sq, sk = q.shape[1], k.shape[1]
    d = q.shape[-1]
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    # Residuals carry the compact (lane-less) LSE (the broadcast LANE layout
    # is 128x larger, which matters when a remat policy saves it);
    # re-broadcast to the Mosaic-tileable layout here, transiently.
    lse = jnp.broadcast_to(lse_c[..., None], lse_c.shape + (LANE,))

    if layout == "folded":
        bh = q.shape[0]
        dq_grid, dkv_grid, blk_axis = (bh, sq // bq), (bh, sk // bk), 1

        def spec(n, lane=False):  # block of n rows (or whole axis), d/LANE wide
            w = LANE if lane else d
            if n is None:  # whole seq axis
                return pl.BlockSpec((1, sq, w), lambda b, i: (b, 0, 0))
            return pl.BlockSpec((1, n, w), lambda b, i: (b, i, 0))

        def kspec(n):
            if n is None:
                return pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0))
            return pl.BlockSpec((1, n, d), lambda b, i: (b, i, 0))

        dq_shape = jax.ShapeDtypeStruct((bh, sq, d), q.dtype)
        dkv_shape = [jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                     jax.ShapeDtypeStruct((bh, sk, d), v.dtype)]
    elif layout == "merged":
        b, h = q.shape[0], q.shape[2]
        hd = h * d
        q, out, dout = (x.reshape(b, sq, hd) for x in (q, out, dout))
        k, v = (x.reshape(b, sk, hd) for x in (k, v))
        lse = lse.reshape(b, sq, h * LANE)
        dq_grid, dkv_grid, blk_axis = (b, h, sq // bq), (b, h, sk // bk), 2

        def spec(n, lane=False):
            w = LANE if lane else d
            if n is None:
                return pl.BlockSpec((1, sq, w), lambda b_, hh, i: (b_, 0, hh))
            return pl.BlockSpec((1, n, w), lambda b_, hh, i: (b_, i, hh))

        def kspec(n):
            if n is None:
                return pl.BlockSpec((1, sk, d), lambda b_, hh, i: (b_, 0, hh))
            return pl.BlockSpec((1, n, d), lambda b_, hh, i: (b_, i, hh))

        dq_shape = jax.ShapeDtypeStruct((b, sq, hd), q.dtype)
        dkv_shape = [jax.ShapeDtypeStruct((b, sk, hd), k.dtype),
                     jax.ShapeDtypeStruct((b, sk, hd), v.dtype)]
    else:
        b, h = q.shape[0], q.shape[2]
        dq_grid, dkv_grid, blk_axis = (b, h, sq // bq), (b, h, sk // bk), 2

        def spec(n, lane=False):
            w = LANE if lane else d
            if n is None:
                return pl.BlockSpec((1, sq, None, w),
                                    lambda b, hh, i: (b, 0, hh, 0))
            return pl.BlockSpec((1, n, None, w),
                                lambda b, hh, i: (b, i, hh, 0))

        def kspec(n):
            if n is None:
                return pl.BlockSpec((1, sk, None, d),
                                    lambda b, hh, i: (b, 0, hh, 0))
            return pl.BlockSpec((1, n, None, d),
                                lambda b, hh, i: (b, i, hh, 0))

        dq_shape = jax.ShapeDtypeStruct((b, sq, h, d), q.dtype)
        dkv_shape = [jax.ShapeDtypeStruct((b, sk, h, d), k.dtype),
                     jax.ShapeDtypeStruct((b, sk, h, d), v.dtype)]

    # operand order is layout-independent; only spec/kspec/grids/shapes vary
    dq_in = [spec(bq), kspec(None), kspec(None), spec(bq), spec(bq),
             spec(bq, lane=True)]
    dq_out = spec(bq)
    dkv_in = [spec(None), kspec(bk), kspec(bk), spec(None), spec(None),
              spec(None, lane=True)]
    dkv_out = [kspec(bk), kspec(bk)]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=bq, block_k=bk,
                          causal=causal, blk_axis=blk_axis),
        grid=dq_grid, in_specs=dq_in, out_specs=dq_out, out_shape=dq_shape,
    )(q, k, v, out, dout, lse)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=bq,
                          block_k=bk, causal=causal, blk_axis=blk_axis),
        grid=dkv_grid, in_specs=dkv_in, out_specs=dkv_out,
        out_shape=dkv_shape,
    )(q, k, v, out, dout, lse)
    if layout == "merged":  # back to the [B, S, H, D] primal shape (free)
        b, h = dq.shape[0], dq.shape[-1] // d
        dq = dq.reshape(b, sq, h, d)
        dk = dk.reshape(b, sk, h, d)
        dv = dv.reshape(b, sk, h, d)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# public API
# --------------------------------------------------------------------------- #


def _check_layout(layout: str, d: int | None = None) -> None:
    if layout not in ("folded", "bshd", "merged"):
        raise ValueError(
            f"unknown flash layout {layout!r} (folded|bshd|merged)")
    if layout == "merged" and d is not None and d % LANE:
        raise ValueError(
            f"flash layout 'merged' needs head_dim % {LANE} == 0 (the head "
            f"slice must be a whole lane tile); got head_dim={d}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, scale, causal, block_q, block_k, layout):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k, layout)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k, layout):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, layout)
    # checkpoint_name lets a selective remat policy (llama.layers_forward,
    # remat="save_attn") keep out+lse across the backward, so rematerialized
    # backward passes skip the flash forward kernel entirely.
    out = checkpoint_name(out, "flash_out")
    lse_c = checkpoint_name(lse[..., 0], "flash_lse")
    return out, (q, k, v, out, lse_c)


_flash_core.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(q, k, v, scale: float | None = None, causal: bool = True,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    layout: str = "folded"):
    """q, k, v: [B, S, H, D] with equal head counts. Returns [B, S, H, D].
    layout="merged" (head_dim % 128 == 0 only) and layout="bshd"
    (interpret-mode only; Mosaic rejects it on hardware) run the kernels on
    the model layout directly with no fold copies; "folded" is the
    always-available default."""
    b, s, h, d = q.shape
    _check_layout(layout, d)
    block_q = block_q or DEFAULT_BLOCK_Q
    block_k = block_k or DEFAULT_BLOCK_K
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if layout in ("bshd", "merged"):
        return _flash_core(q, k, v, float(scale), causal, block_q, block_k,
                           layout)
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out = _flash_core(fold(q), fold(k), fold(v), float(scale), causal,
                      block_q, block_k, "folded")
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def flash_block_grads(q, k, v, out, lse, dout, scale: float,
                      causal: bool = True,
                      block_q: int | None = None,
                      block_k: int | None = None,
                      layout: str = "folded"):
    """Gradients of one attention block given an externally-merged (global)
    out/lse — the ring-attention backward building block (the ring re-derives
    each block's true share of the global softmax as exp(s - lse_global),
    reference context_parallel.py:112-155). q/out/dout are [B, Sq, H, D],
    k/v are [B, Sk, H, D] (Sq != Sk allowed for ring half-blocks, non-causal
    only); lse is [B, Sq, H] fp32. Returns (dq, dk, dv)."""
    b, sq, h, d = q.shape
    _check_layout(layout, d)
    block_q = block_q or DEFAULT_BLOCK_Q
    block_k = block_k or DEFAULT_BLOCK_K
    if layout in ("bshd", "merged"):
        return _bwd(scale, causal, block_q, block_k, layout,
                    (q, k, v, out, lse), dout)
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    lse_c = lse.transpose(0, 2, 1).reshape(b * h, sq)
    dq, dk, dv = _bwd(scale, causal, block_q, block_k, "folded",
                      (fold(q), fold(k), fold(v), fold(out), lse_c),
                      fold(dout))
    unfold = lambda x: x.reshape(b, h, x.shape[1], d).transpose(0, 2, 1, 3)
    return unfold(dq), unfold(dk), unfold(dv)


def flash_attention_with_lse(q, k, v, scale: float | None = None,
                             causal: bool = True,
                             block_q: int | None = None,
                             block_k: int | None = None,
                             layout: str = "folded"):
    """Forward-only variant returning (out [B,Sq,H,D], lse [B,Sq,H]) — the
    building block for ring attention's LSE merge. Sq != Sk allowed
    (non-causal only)."""
    b, s, h, d = q.shape
    _check_layout(layout, d)
    block_q = block_q or DEFAULT_BLOCK_Q
    block_k = block_k or DEFAULT_BLOCK_K
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if layout in ("bshd", "merged"):
        out, lse = _fwd(q, k, v, float(scale), causal, block_q, block_k,
                        layout)
        return out, lse[..., 0]
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    out, lse = _fwd(fold(q), fold(k), fold(v), float(scale), causal,
                    block_q, block_k, "folded")
    return (out.reshape(b, h, s, d).transpose(0, 2, 1, 3),
            lse[:, :, 0].reshape(b, h, s).transpose(0, 2, 1))
