"""RMSNorm as a Pallas TPU kernel (fwd + custom-VJP bwd).

TPU-native equivalent of the reference's TritonRMSNorm (picotron/model.py:38-64,
layer_norm_fn from the flash-attn package). Numerics match the pure formulation
in ops/rmsnorm.py (the reference's LlamaRMSNorm, model.py:66-85): variance in
float32, ``x * rsqrt(var + eps)`` cast to the input dtype, scaled by weight.

Rows (B*S flattened) stream through a 1-D grid; the weight gradient
accumulates across grid steps into a single [1, H] output block (TPU grid
iterations over the same output block run sequentially, so the accumulation
is race-free).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_block(rows: int, h: int, itemsize: int) -> int:
    """Row-block sized so one block is ~512 KB: with Pallas double-buffering
    and the kernel's fp32 temporaries this keeps VMEM well under the 16 MB
    budget at any hidden size."""
    want = max(8, (512 * 1024) // max(h * itemsize, 1))
    b = min(want, rows)
    while rows % b:
        b //= 2
    return max(b, 1)


def _fwd_kernel(x_ref, w_ref, y_ref, *, eps):
    x32 = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = (x32 * jax.lax.rsqrt(var + eps)).astype(y_ref.dtype)
    y_ref[:] = normed * w_ref[0][None, :].astype(y_ref.dtype)


def _bwd_kernel(x_ref, w_ref, dy_ref, dx_ref, dw_ref, *, eps):
    @pl.when(pl.program_id(0) == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    x32 = x_ref[:].astype(jnp.float32)
    dy32 = dy_ref[:].astype(jnp.float32)
    w32 = w_ref[0][None, :].astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + eps)
    xhat = x32 * r
    dxhat = dy32 * w32
    dx = r * (dxhat - xhat * jnp.mean(dxhat * xhat, axis=-1, keepdims=True))
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dw_ref[:] = dw_ref[:] + jnp.sum(dy32 * xhat, axis=0, keepdims=True)


def _run_fwd(x2d, w, eps):
    rows, h = x2d.shape
    br = _pick_block(rows, h, x2d.dtype.itemsize)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), x2d.dtype),
    )(x2d, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_2d(x2d, w, eps):
    return _run_fwd(x2d, w, eps)


def _fwd_rule(x2d, w, eps):
    return _run_fwd(x2d, w, eps), (x2d, w)


def _bwd_rule(eps, res, dy):
    x2d, w = res
    rows, h = x2d.shape
    br = _pick_block(rows, h, x2d.dtype.itemsize)
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, h), x2d.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
    )(x2d, w, dy)
    return dx, dw[0].astype(w.dtype)


_rms_norm_2d.defvjp(_fwd_rule, _bwd_rule)


def rms_norm_pallas(x, weight, eps: float = 1e-5):
    """x: [..., H]; weight: [H]. Same numerics as ops.rmsnorm.rms_norm."""
    shape = x.shape
    h = shape[-1]
    out = _rms_norm_2d(x.reshape(-1, h), weight.reshape(1, h), float(eps))
    return out.reshape(shape)
