"""Flash decode: fused KV-cache attention as a Pallas TPU kernel.

The serving counterpart of ``flash_attention.py``. The dense decode path
(``inference/kv_cache.py::decode_attention``) attends every fresh query
against the **whole** ``[B, max_seq_len, Hkv, D]`` cache block — an einsum
whose HBM traffic is O(max_seq_len) per decoded token no matter how short
the live sequences are, and whose int8 mode first materializes a
dequantized fp32 copy of the entire block (4x the bytes the cache stores).
This kernel removes both costs:

- **Length-aware**: the grid is ``(slots, kv_heads)`` and each instance
  walks KV blocks with a ``fori_loop`` bounded by
  ``ceil(lengths[b] / block_t)`` — its OWN slot's live token count, an
  even tighter bound than ``max(lengths)`` — so HBM reads track parked
  tokens, not the cache window. Keys inside the last partial block are
  masked per query row against the slot's ``lengths`` (the stale rows a
  speculative rollback or a freed slot leaves beyond the length pointer
  are never visible). Nothing beyond ``ceil(lengths[b]/block_t)*block_t``
  rows is ever DMA'd.
- **int8 dequant in registers**: K/V stay int8 on the wire — each block is
  DMA'd from HBM in its storage dtype together with its per-row fp32
  scales (``[block_t]`` vectors) and dequantized in VMEM right before the
  matmul, so the quantized cache's ~2x byte saving reaches the attend
  itself, not just storage.
- **GQA native**: queries fold to ``[B, Hkv, S*g, D]`` (``g = Hq/Hkv``
  grouped rows per compact kv head — the same trick the training flash
  kernel's folded layout uses) and each grid instance serves one kv head's
  whole query group; the cache stays compact, nothing is repeated.
- **S >= 1 queries per slot**: query row ``r`` sits at global position
  ``pos_q = lengths[b] - S + r // g`` (key ``t`` visible iff
  ``t <= pos_q``) — the exact masking convention of the dense kernel — so
  ONE kernel serves all three call sites: blocked decode (S = 1),
  speculative verify (S = spec_len + 1, B = slots), and chunked prefill
  (B = 1, S = chunk width).

Softmax is the standard online (flash) recurrence in fp32: running max
``m``, normalizer ``l``, and accumulator ``acc`` per query row, masked
probabilities zeroed exactly so a fully-masked row (``lengths == 0`` — a
fresh slot attended directly) comes out as **zeros**, a defined value,
where the dense kernel emits an (equally unconsumed) uniform average.
Every other row is allclose to the dense path for bf16/fp32 AND int8
caches (tests/test_decode_kernel.py pins all three call shapes in
interpret mode).

Hardware notes: K/V (+ scales) are handed to the kernel in ``pl.ANY``
memory space (they stay in HBM) and each block is pulled with
``pltpu.make_async_copy`` into VMEM scratch; query rows pad to a multiple
of 8 sublanes. Blocks are fetched serially (no double buffering yet —
decode is a bandwidth-bound dot per block, and the DMA engine overlaps
across grid instances); on CPU the kernel runs in Pallas interpret mode
(``interpret=True``), which is how the parity suite and the tier-1 gate
exercise it. Dense remains the serving default (``inference.attend_impl``)
until the kernel is A/B'd on a chip, the same staging discipline the
``bshd`` flash layout went through.

**The program_id trap (picolint rule PICO-J003).** ``pl.program_id`` must
be read ONCE, outside the ``fori_loop`` body: the jax 0.4.37 Pallas
interpreter cannot resolve grid ids inside a loop body's sub-jaxpr, so a
kernel that reads ``pl.program_id`` under ``fori_loop``/``while_loop``
traces fine on TPU but fails (or silently misindexes) on the interpret
path every CPU test runs. This kernel hit exactly that during PR 5 — the
fix is the ``b``/``h`` reads at the top of ``_flash_decode_kernel``,
before ``body`` closes over them. The hazard is now enforced
mechanically: ``python -m picotron_tpu.tools.lint`` flags any
``program_id`` read inside a loop-body closure as PICO-J003
(picotron_tpu/analysis/jax_rules.py; catalog: docs/ANALYSIS.md#pico-j003).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from picotron_tpu.ops.attention import NEG_INF
from picotron_tpu.ops.pallas.flash_attention import _pick_block

# KV rows fetched per DMA; halved automatically until the block divides the
# cache window AND the [S*g, block_t] fp32 score tile stays under
# _MAX_SCORE_TILE elements (see _pick_block_t).
DEFAULT_BLOCK_T = 256
# score-tile budget: 256K fp32 elements = 1 MB, the same tile scale the
# training flash kernel's 512x512 default occupies — decode shapes
# (S*g <= 8 rows) keep the full DEFAULT_BLOCK_T, wide chunked-prefill query
# groups (S*g in the thousands) trade KV-block depth for row count so VMEM
# never blows up with the chunk width
_MAX_SCORE_TILE = 256 * 1024
_SUBLANE = 8  # fp32 sublane quantum the padded query-row count respects


def _pick_block_t(seq: int, want: int, rows: int = _SUBLANE) -> int:
    """KV block size: at or under ``want``, shrunk (a) so the
    ``[rows, block]`` fp32 score tile fits the VMEM budget and (b) by
    halving until it divides ``seq`` (flash_attention._pick_block — the
    DMA slice size must be static, so the block must tile the cache window
    exactly; this is what keeps windows that are NOT a multiple of the
    preferred block correct instead of reading past the buffer)."""
    while want > _SUBLANE and rows * want > _MAX_SCORE_TILE:
        want //= 2
    return _pick_block(seq, want)


def _flash_decode_kernel(*refs, scale, block_t, S, g, quantized, paged):
    """One (slot, kv head) grid instance: all S*g query rows of slot ``b``
    under kv head ``h`` against the slot's live KV blocks. ``paged``
    mode walks the slot's block-table row instead of contiguous blocks:
    iteration ``j`` DMAs pool page ``bt[b, j]`` (K/V are the global
    ``[num_pages, page_len, Hkv, D]`` pool, ``block_t == page_len``) —
    the indirection lives entirely in the DMA source address, the
    online-softmax math is unchanged."""
    refs = list(refs)
    len_ref = refs.pop(0)
    bt_ref = refs.pop(0) if paged else None
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         kbuf, vbuf, ksbuf, vsbuf, sems) = refs
    else:
        (q_ref, k_ref, v_ref, o_ref, kbuf, vbuf, sems) = refs
        ks_ref = vs_ref = ksbuf = vsbuf = None
    # program ids are read ONCE here: the 0.4.37 interpreter cannot resolve
    # pl.program_id inside the fori_loop body's sub-jaxpr (enforced as
    # picolint PICO-J003 — see the module docstring)
    b = pl.program_id(0)
    h = pl.program_id(1)
    L = len_ref[0]  # this slot's live token count
    q = q_ref[0, 0].astype(jnp.float32)  # [Sgp, D]
    sgp = q.shape[0]
    # query row r = s*g + g_idx sits at global position L - S + s
    pos_q = (L - S
             + lax.broadcasted_iota(jnp.int32, (sgp, block_t), 0) // g)
    kiota = lax.broadcasted_iota(jnp.int32, (sgp, block_t), 1)

    def body(j, carry):
        acc, m, l = carry
        if paged:
            # the page walk: block j's DMA source is pool page bt[b, j]
            pid = bt_ref[0, j]
            ksrc, vsrc = k_ref.at[pid, :, h, :], v_ref.at[pid, :, h, :]
            kssrc = None if not quantized else ks_ref.at[pid, :, h]
            vssrc = None if not quantized else vs_ref.at[pid, :, h]
        else:
            rows = pl.ds(j * block_t, block_t)
            ksrc, vsrc = k_ref.at[b, rows, h, :], v_ref.at[b, rows, h, :]
            kssrc = None if not quantized else ks_ref.at[b, rows, h]
            vssrc = None if not quantized else vs_ref.at[b, rows, h]
        kdma = pltpu.make_async_copy(ksrc, kbuf, sems.at[0])
        vdma = pltpu.make_async_copy(vsrc, vbuf, sems.at[1])
        kdma.start()
        vdma.start()
        if quantized:
            ksdma = pltpu.make_async_copy(kssrc, ksbuf, sems.at[2])
            vsdma = pltpu.make_async_copy(vssrc, vsbuf, sems.at[3])
            ksdma.start()
            vsdma.start()
        kdma.wait()
        kb = kbuf[...].astype(jnp.float32)  # [bt, D]
        if quantized:
            ksdma.wait()
            kb = kb * ksbuf[...][:, None]  # dequant in registers
        s = lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        mask = (j * block_t + kiota) <= pos_q
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        # zero masked probabilities EXACTLY (not just exp(-inf)): a row
        # whose every key so far is masked keeps l == 0 and lands on the
        # defined all-zeros output below instead of a uniform average
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        vdma.wait()
        vb = vbuf[...].astype(jnp.float32)
        if quantized:
            vsdma.wait()
            vb = vb * vsbuf[...][:, None]
        acc = acc * alpha + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    d = q.shape[1]
    acc0 = jnp.zeros((sgp, d), jnp.float32)
    m0 = jnp.full((sgp, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((sgp, 1), jnp.float32)
    # the whole point: the block walk is bounded by THIS slot's live
    # length, never by max_seq_len — a fresh slot (L == 0) runs no
    # iterations and costs no HBM reads at all. Clamped to the window's
    # block count: at the window edge the engine's write-then-attend
    # convention can pass lengths = pos + S > T (the scatter dropped the
    # out-of-bounds rows), and the walk must not DMA past the cache
    # (the dense kernel's mask absorbs the same case for free). Paged
    # mode clamps to the block-table width instead.
    max_nb = bt_ref.shape[1] if paged else k_ref.shape[1] // block_t
    nb = jnp.minimum(lax.div(L + block_t - 1, block_t), max_nb)
    acc, _, l = lax.fori_loop(0, nb, body, (acc0, m0, l0))
    out = acc / jnp.where(l > 0, l, 1.0)
    o_ref[0, 0] = jnp.where(l > 0, out, 0.0).astype(o_ref.dtype)


def flash_decode_attention(q, k, v, lengths, scale, *,
                           k_scale=None, v_scale=None,
                           block_t: int | None = None,
                           block_tables=None,
                           interpret: bool = False):
    """Fused masked attention of S fresh queries per slot against a KV
    cache block, reading only live rows.

    q: [B, S, n_heads, D] — the new tokens, the LAST of which sits at
    global position ``lengths[b] - 1``; k/v: [B, T, n_kv_heads, D] cache
    blocks, int8 when ``k_scale``/``v_scale`` ([B, T, n_kv_heads] fp32
    per-row scales) are given; lengths: [B] int32 valid-key counts.
    Returns [B, S, n_heads, D] in q.dtype — allclose to
    ``kv_cache.decode_attention`` on every query row with at least one
    visible key (``pos_q = lengths[b] - S + s >= 0``; inside the engine
    that is every row of every occupied slot). Fully-masked rows —
    ``lengths == 0``, or the leading rows of a direct call with
    ``lengths < S`` — return ZEROS, where the dense kernel emits an
    equally-unconsumed uniform average over the whole window.
    ``interpret=True`` runs the Pallas interpreter (the CPU path).

    ``block_tables`` ([B, max_pages] int32) switches to the PAGED cache
    layout (inference/paged_kv.py): k/v (and scales) are then the global
    page pool — ``[num_pages, page_len, n_kv_heads, D]`` — and slot
    ``b``'s walk reads pool page ``block_tables[b, j]`` at iteration
    ``j`` instead of its contiguous block ``j``. The KV block size is
    the page length; everything else (masking, online softmax, GQA fold,
    in-register dequant) is the identical code path."""
    B, S, nh, D = q.shape
    paged = block_tables is not None
    if paged:
        if block_tables.shape[0] != B:
            raise ValueError(
                f"block_tables rows {block_tables.shape[0]} != batch {B}")
        T = block_tables.shape[1] * k.shape[1]  # max_pages * page_len
        nkv = k.shape[2]
    else:
        T, nkv = k.shape[1], k.shape[2]
    if nh % nkv:
        raise ValueError(f"n_heads {nh} not a multiple of n_kv_heads {nkv}")
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")
    if (k.dtype == jnp.int8) != quantized:
        raise ValueError(
            f"int8 cache blocks need per-row scales (and vice versa); got "
            f"k.dtype={k.dtype} with scales={'set' if quantized else 'unset'}")
    g = nh // nkv
    sg = S * g
    sgp = -(-sg // _SUBLANE) * _SUBLANE  # pad query rows to the sublane tile
    # paged: the DMA unit is a whole pool page, so the block size IS the
    # page length (the allocator's granularity, already VMEM-sized)
    bt = (k.shape[1] if paged
          else _pick_block_t(T, block_t or DEFAULT_BLOCK_T, rows=sgp))
    # fold [B, S, nkv, g, D] -> [B, nkv, S*g, D]: one kv head's whole query
    # group per grid instance (tiny copy — S is 1..chunk, never the cache)
    qf = q.reshape(B, S, nkv, g, D).swapaxes(1, 2).reshape(B, nkv, sg, D)
    if sgp != sg:
        qf = jnp.pad(qf, ((0, 0), (0, 0), (0, sgp - sg), (0, 0)))

    kernel = functools.partial(
        _flash_decode_kernel, scale=float(scale), block_t=bt, S=S, g=g,
        quantized=quantized, paged=paged)
    in_specs = [
        pl.BlockSpec((1,), lambda b, h: (b,), memory_space=pltpu.SMEM),
    ]
    operands = [lengths.astype(jnp.int32)]
    if paged:
        maxp = block_tables.shape[1]
        in_specs.append(pl.BlockSpec((1, maxp), lambda b, h: (b, 0),
                                     memory_space=pltpu.SMEM))
        operands.append(block_tables.astype(jnp.int32))
    in_specs += [
        pl.BlockSpec((1, 1, sgp, D), lambda b, h: (b, h, 0, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),  # K stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),  # V stays in HBM
    ]
    operands += [qf, k, v]
    scratch = [pltpu.VMEM((bt, D), k.dtype), pltpu.VMEM((bt, D), v.dtype)]
    if quantized:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        operands += [k_scale, v_scale]
        scratch += [pltpu.VMEM((bt,), jnp.float32),
                    pltpu.VMEM((bt,), jnp.float32)]
    scratch.append(pltpu.SemaphoreType.DMA((4,)))

    out = pl.pallas_call(
        kernel,
        grid=(B, nkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, sgp, D), lambda b, h: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, nkv, sgp, D), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)
    return (out[:, :, :sg]
            .reshape(B, nkv, S, g, D).swapaxes(1, 2)
            .reshape(B, S, nh, D))
