"""Flash decode: fused KV-cache attention as a Pallas TPU kernel.

The serving counterpart of ``flash_attention.py``. The dense decode path
(``inference/kv_cache.py::decode_attention``) attends every fresh query
against the **whole** ``[B, max_seq_len, Hkv, D]`` cache block — an einsum
whose HBM traffic is O(max_seq_len) per decoded token no matter how short
the live sequences are, and whose int8 mode first materializes a
dequantized fp32 copy of the entire block (4x the bytes the cache stores).
This kernel removes both costs:

- **Length-aware**: the grid is ``(slots, kv_heads, q_blocks)`` and each
  instance walks KV blocks with a ``fori_loop`` bounded by
  ``ceil(visible / block_t)`` — its OWN slot's live token count clipped to
  the highest key its query rows can see (the same causal block-skip the
  training flash kernel uses, shared via
  ``flash_attention.causal_kv_blocks``) — so HBM reads track parked
  tokens, not the cache window. Keys inside the last partial block are
  masked per query row against the slot's ``lengths`` (the stale rows a
  speculative rollback or a freed slot leaves beyond the length pointer
  are never visible). Nothing beyond ``ceil(lengths[b]/block_t)*block_t``
  rows is ever DMA'd.
- **int8 dequant in registers**: K/V stay int8 on the wire — each block is
  DMA'd from HBM in its storage dtype together with its per-row fp32
  scales (``[block_t]`` vectors) and dequantized in VMEM right before the
  matmul, so the quantized cache's ~2x byte saving reaches the attend
  itself, not just storage.
- **GQA native**: queries fold to ``[B, Hkv, S*g, D]`` (``g = Hq/Hkv``
  grouped rows per compact kv head — the same trick the training flash
  kernel's folded layout uses) and each grid instance serves one kv head's
  whole query group; the cache stays compact, nothing is repeated.
- **S >= 1 queries per slot**: query row ``r`` sits at global position
  ``pos_q = lengths[b] - S + r // g`` (key ``t`` visible iff
  ``t <= pos_q``) — the exact masking convention of the dense kernel — so
  ONE kernel serves all three call sites: blocked decode (S = 1),
  speculative verify (S = spec_len + 1, B = slots), and chunked prefill
  (B = 1, S = chunk width).
- **Blocked queries for chunked prefill**: wide query groups (S*g beyond
  ``block_q`` folded rows — the chunked-prefill shape) split over the
  third grid axis instead of shrinking the KV block to fit one giant
  score tile: each q-block keeps a deep ``block_t``, walks only the KV
  blocks its own causal band can see, and the q-blocks parallelize
  across the grid — ``flash_attention.py``'s block machinery applied to
  the cache-prefix+chunk window. Decode/verify shapes (a handful of
  rows) fold to a single q-block, exactly the old layout.

Softmax is the standard online (flash) recurrence in fp32: running max
``m``, normalizer ``l``, and accumulator ``acc`` per query row, masked
probabilities zeroed exactly so a fully-masked row (``lengths == 0`` — a
fresh slot attended directly) comes out as **zeros**, a defined value,
where the dense kernel emits an (equally unconsumed) uniform average.
Every other row is allclose to the dense path for bf16/fp32 AND int8
caches (tests/test_decode_kernel.py pins all three call shapes in
interpret mode).

Hardware notes: K/V (+ scales) are handed to the kernel in ``pl.ANY``
memory space (they stay in HBM) and each block is pulled with
``pltpu.make_async_copy`` into VMEM scratch; query rows pad to a multiple
of 8 sublanes. Block fetches are **double-buffered** (``pipeline=True``,
the default): two VMEM scratch buffers per operand and iteration ``j``
prefetches block ``j+1`` into the idle buffer before waiting on its own,
so the next block's DMA commits while the current block's dots run — the
async-send/compute overlap the reference survey credits for its MFU
(SURVEY §5.7). ``pipeline=False`` keeps the serial fetch (one buffer,
start-wait-compute per block) as the bitwise-identical reference the
parity suite pins the pipelined path against. On CPU the kernel runs in
Pallas interpret mode (``interpret=True``), which is how the parity suite
and the tier-1 gate exercise it. Dense remains the serving default
(``inference.attend_impl``) until the kernel is A/B'd on a chip, the same
staging discipline the ``bshd`` flash layout went through.

``block_tables`` switches to the PAGED layout (one DMA per pool page);
``block_quant`` additionally enables the **mixed-precision page read**
(``inference.kv_page_policy: "hot_bf16"`` — inference/paged_kv.py): each
page carries a per-page flag choosing which of the two pool
representations to DMA — the full-precision leaves for hot (radix-shared)
prefix pages, the int8+scales leaves for cold unique tails — so shared
prefixes keep full precision while the long tail moves ~half the bytes.

**The program_id trap (picolint rule PICO-J003).** ``pl.program_id`` must
be read ONCE, outside the ``fori_loop`` body: the jax 0.4.37 Pallas
interpreter cannot resolve grid ids inside a loop body's sub-jaxpr, so a
kernel that reads ``pl.program_id`` under ``fori_loop``/``while_loop``
traces fine on TPU but fails (or silently misindexes) on the interpret
path every CPU test runs. This kernel hit exactly that during PR 5 — the
fix is the ``b``/``h``/``qi`` reads at the top of
``_flash_decode_kernel``, before ``body`` closes over them.

**The two-buffer semaphore discipline (picolint rule PICO-J005).** With
double buffering, iteration ``j`` owns buffer slot ``j % 2`` and its
semaphore column ``sems[j % 2, :]``; the prefetch of block ``j+1``
targets the OTHER slot, so the only write-after-read hazard (re-filling a
buffer the current iteration still reads) is structurally impossible —
the body runs sequentially and the j+2 prefetch happens one full
iteration after slot ``j % 2``'s compute finished. Every ``start()`` has
a matching ``wait()`` built from the same (source, destination,
semaphore) triple — in the mixed-page mode both live under the SAME
``pl.when`` predicate, so a wait can never block on a copy that was
never started. A ``make_async_copy`` whose wait is missing (or sits off
some fori_loop path its start runs on) is now flagged mechanically as
PICO-J005 (picotron_tpu/analysis/jax_rules.py; catalog:
docs/ANALYSIS.md#pico-j005), like the program_id trap before it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from picotron_tpu.ops.attention import NEG_INF
from picotron_tpu.ops.pallas.flash_attention import (
    _pick_block,
    causal_kv_blocks,
)

# KV rows fetched per DMA; halved automatically until the block divides the
# cache window AND the [block_q, block_t] fp32 score tile stays under
# _MAX_SCORE_TILE elements (see _pick_block_t).
DEFAULT_BLOCK_T = 256
# Folded query rows (S*g) per grid instance. Decode/verify shapes (S*g <= 8)
# fold into one block; chunked-prefill windows wider than this split over
# the q grid axis instead of shrinking block_t — the flash_attention.py
# blocking applied to the decode kernel.
DEFAULT_BLOCK_Q = 256
# score-tile budget: 256K fp32 elements = 1 MB, the same tile scale the
# training flash kernel's 512x512 default occupies — decode shapes
# (S*g <= 8 rows) keep the full DEFAULT_BLOCK_T, wide chunked-prefill query
# groups (S*g in the thousands) first split over the q grid axis and only
# then trade KV-block depth for row count, so VMEM never blows up with the
# chunk width
_MAX_SCORE_TILE = 256 * 1024
_SUBLANE = 8  # fp32 sublane quantum the padded query-row count respects


def _pick_block_t(seq: int, want: int, rows: int = _SUBLANE) -> int:
    """KV block size: at or under ``want``, shrunk (a) so the
    ``[rows, block]`` fp32 score tile fits the VMEM budget and (b) by
    halving until it divides ``seq`` (flash_attention._pick_block — the
    DMA slice size must be static, so the block must tile the cache window
    exactly; this is what keeps windows that are NOT a multiple of the
    preferred block correct instead of reading past the buffer)."""
    while want > _SUBLANE and rows * want > _MAX_SCORE_TILE:
        want //= 2
    return _pick_block(seq, want)


def _pick_block_q(sgp: int, want: int, block_t: int) -> int:
    """Folded query rows per grid instance: at or under ``want``, dividing
    the padded row count, shrunk until the [rows, block_t] fp32 score tile
    fits the VMEM budget (the paged layout fixes block_t at the page
    length, so rows are the only tunable there)."""
    rq = _pick_block(sgp, want)
    while rq > _SUBLANE and rq * block_t > _MAX_SCORE_TILE:
        rq = _pick_block(sgp, rq // 2)
    return rq


def _flash_decode_kernel(*refs, scale, block_t, S, g, rq, quantized, mixed,
                         paged, pipeline):
    """One (slot, kv head, q-block) grid instance: ``rq`` folded query
    rows of slot ``b`` under kv head ``h`` against the slot's visible KV
    blocks. ``paged`` mode walks the slot's block-table row instead of
    contiguous blocks: iteration ``j`` DMAs pool page ``bt[b, j]`` (K/V
    are the global ``[num_pages, page_len, Hkv, D]`` pool,
    ``block_t == page_len``) — the indirection lives entirely in the DMA
    source address, the online-softmax math is unchanged. ``mixed`` adds
    the per-page dtype flag (``qt[b, j]``) choosing which pool
    representation iteration ``j`` fetches. ``pipeline`` double-buffers
    the fetches (see the module docstring's semaphore discipline)."""
    refs = list(refs)
    len_ref = refs.pop(0)
    bt_ref = refs.pop(0) if paged else None
    qt_ref = refs.pop(0) if mixed else None
    q_ref = refs.pop(0)
    k_ref = refs.pop(0)
    v_ref = refs.pop(0)
    kq_ref = refs.pop(0) if mixed else None
    vq_ref = refs.pop(0) if mixed else None
    scaled = quantized or mixed
    ks_ref = refs.pop(0) if scaled else None
    vs_ref = refs.pop(0) if scaled else None
    o_ref = refs.pop(0)
    kbuf, vbuf = refs.pop(0), refs.pop(0)
    kqbuf = refs.pop(0) if mixed else None
    vqbuf = refs.pop(0) if mixed else None
    ksbuf = refs.pop(0) if scaled else None
    vsbuf = refs.pop(0) if scaled else None
    sems = refs.pop(0)
    # program ids are read ONCE here: the 0.4.37 interpreter cannot resolve
    # pl.program_id inside the fori_loop body's sub-jaxpr (enforced as
    # picolint PICO-J003 — see the module docstring)
    b = pl.program_id(0)
    h = pl.program_id(1)
    qi = pl.program_id(2)
    L = len_ref[0]  # this slot's live token count
    q = q_ref[0, 0].astype(jnp.float32)  # [rq, D]
    r0 = qi * rq  # first folded query row of this tile
    # query row r = s*g + g_idx sits at global position L - S + s
    pos_q = (L - S
             + (r0 + lax.broadcasted_iota(jnp.int32, (rq, block_t), 0)) // g)
    kiota = lax.broadcasted_iota(jnp.int32, (rq, block_t), 1)

    def _srcs(j):
        """Iteration j's DMA source slices (K, V, and the scale rows)."""
        if paged:
            pid = bt_ref[0, j]
            return (lambda ref: ref.at[pid, :, h, :],
                    lambda ref: ref.at[pid, :, h])
        rows = pl.ds(j * block_t, block_t)
        return (lambda ref: ref.at[b, rows, h, :],
                lambda ref: ref.at[b, rows, h])

    # start/wait pairs are built from the SAME (src, dst, sem) triples, so
    # a wait always matches the copy its iteration/slot started — the
    # PICO-J005 discipline. sems column layout: 0=K(+q), 1=V(+q),
    # 2=k_scale, 3=v_scale.
    if mixed:
        def _flag(j):
            return qt_ref[0, j] != 0

        def start(j, slot):
            path, spath = _srcs(j)
            isq = _flag(j)

            @pl.when(isq)
            def _():  # cold page: int8 bytes + per-row scales
                pltpu.make_async_copy(path(kq_ref), kqbuf.at[slot],
                                      sems.at[slot, 0]).start()
                pltpu.make_async_copy(path(vq_ref), vqbuf.at[slot],
                                      sems.at[slot, 1]).start()
                pltpu.make_async_copy(spath(ks_ref), ksbuf.at[slot],
                                      sems.at[slot, 2]).start()
                pltpu.make_async_copy(spath(vs_ref), vsbuf.at[slot],
                                      sems.at[slot, 3]).start()

            @pl.when(~isq)
            def _():  # hot page: the full-precision leaves
                pltpu.make_async_copy(path(k_ref), kbuf.at[slot],
                                      sems.at[slot, 0]).start()
                pltpu.make_async_copy(path(v_ref), vbuf.at[slot],
                                      sems.at[slot, 1]).start()

        def wait_k(j, slot):
            path, spath = _srcs(j)
            isq = _flag(j)

            @pl.when(isq)
            def _():
                pltpu.make_async_copy(path(kq_ref), kqbuf.at[slot],
                                      sems.at[slot, 0]).wait()
                pltpu.make_async_copy(spath(ks_ref), ksbuf.at[slot],
                                      sems.at[slot, 2]).wait()

            @pl.when(~isq)
            def _():
                pltpu.make_async_copy(path(k_ref), kbuf.at[slot],
                                      sems.at[slot, 0]).wait()
            deq = kqbuf[slot].astype(jnp.float32) * ksbuf[slot][:, None]
            return jnp.where(isq, deq, kbuf[slot].astype(jnp.float32))

        def wait_v(j, slot):
            path, spath = _srcs(j)
            isq = _flag(j)

            @pl.when(isq)
            def _():
                pltpu.make_async_copy(path(vq_ref), vqbuf.at[slot],
                                      sems.at[slot, 1]).wait()
                pltpu.make_async_copy(spath(vs_ref), vsbuf.at[slot],
                                      sems.at[slot, 3]).wait()

            @pl.when(~isq)
            def _():
                pltpu.make_async_copy(path(v_ref), vbuf.at[slot],
                                      sems.at[slot, 1]).wait()
            deq = vqbuf[slot].astype(jnp.float32) * vsbuf[slot][:, None]
            return jnp.where(isq, deq, vbuf[slot].astype(jnp.float32))
    else:
        def start(j, slot):
            path, spath = _srcs(j)
            pltpu.make_async_copy(path(k_ref), kbuf.at[slot],
                                  sems.at[slot, 0]).start()
            pltpu.make_async_copy(path(v_ref), vbuf.at[slot],
                                  sems.at[slot, 1]).start()
            if quantized:
                pltpu.make_async_copy(spath(ks_ref), ksbuf.at[slot],
                                      sems.at[slot, 2]).start()
                pltpu.make_async_copy(spath(vs_ref), vsbuf.at[slot],
                                      sems.at[slot, 3]).start()

        def wait_k(j, slot):
            path, spath = _srcs(j)
            pltpu.make_async_copy(path(k_ref), kbuf.at[slot],
                                  sems.at[slot, 0]).wait()
            kb = kbuf[slot].astype(jnp.float32)
            if quantized:
                pltpu.make_async_copy(spath(ks_ref), ksbuf.at[slot],
                                      sems.at[slot, 2]).wait()
                kb = kb * ksbuf[slot][:, None]  # dequant in registers
            return kb

        def wait_v(j, slot):
            path, spath = _srcs(j)
            pltpu.make_async_copy(path(v_ref), vbuf.at[slot],
                                  sems.at[slot, 1]).wait()
            vb = vbuf[slot].astype(jnp.float32)
            if quantized:
                pltpu.make_async_copy(spath(vs_ref), vsbuf.at[slot],
                                      sems.at[slot, 3]).wait()
                vb = vb * vsbuf[slot][:, None]
            return vb

    # the whole point: the block walk is bounded by THIS slot's live
    # length, never by max_seq_len — a fresh slot (L == 0) runs no
    # iterations and costs no HBM reads at all. Clipped twice: (a) to the
    # highest key this q-tile's causal band can see (the flash_attention
    # block-skip — early chunked-prefill q-blocks never walk the whole
    # window), and (b) to the window's block count: at the window edge the
    # engine's write-then-attend convention can pass
    # lengths = pos + S > T (the scatter dropped the out-of-bounds rows),
    # and the walk must not DMA past the cache (the dense kernel's mask
    # absorbs the same case for free). Paged mode clamps to the
    # block-table width instead.
    max_nb = bt_ref.shape[1] if paged else k_ref.shape[1] // block_t
    hi = jnp.clip(L - S + (r0 + rq - 1) // g, -1, L - 1)  # last visible key
    nb = jnp.maximum(causal_kv_blocks(max_nb, hi, block_t), 0)

    def body(j, carry):
        acc, m, l = carry
        if pipeline:
            slot = lax.rem(j, 2)

            @pl.when(j + 1 < nb)
            def _():  # commit block j+1 into the idle buffer NOW; the
                # dots below overlap with its DMA (SURVEY §5.7's overlap)
                start(j + 1, 1 - slot)
        else:
            slot = 0
            start(j, slot)
        kb = wait_k(j, slot)  # [bt, D] fp32
        s = lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        mask = (j * block_t + kiota) <= pos_q
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        # zero masked probabilities EXACTLY (not just exp(-inf)): a row
        # whose every key so far is masked keeps l == 0 and lands on the
        # defined all-zeros output below instead of a uniform average
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        vb = wait_v(j, slot)
        acc = acc * alpha + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l

    if pipeline:
        @pl.when(nb > 0)
        def _():  # warm-up: block 0's DMA is in flight before the loop
            start(0, 0)

    d = q.shape[1]
    acc0 = jnp.zeros((rq, d), jnp.float32)
    m0 = jnp.full((rq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rq, 1), jnp.float32)
    acc, _, l = lax.fori_loop(0, nb, body, (acc0, m0, l0))
    out = acc / jnp.where(l > 0, l, 1.0)
    o_ref[0, 0] = jnp.where(l > 0, out, 0.0).astype(o_ref.dtype)


def flash_decode_attention(q, k, v, lengths, scale, *,
                           k_scale=None, v_scale=None,
                           k_quant=None, v_quant=None,
                           block_quant=None,
                           block_t: int | None = None,
                           block_q: int | None = None,
                           block_tables=None,
                           pipeline: bool = True,
                           interpret: bool = False):
    """Fused masked attention of S fresh queries per slot against a KV
    cache block, reading only live rows.

    q: [B, S, n_heads, D] — the new tokens, the LAST of which sits at
    global position ``lengths[b] - 1``; k/v: [B, T, n_kv_heads, D] cache
    blocks, int8 when ``k_scale``/``v_scale`` ([B, T, n_kv_heads] fp32
    per-row scales) are given; lengths: [B] int32 valid-key counts.
    Returns [B, S, n_heads, D] in q.dtype — allclose to
    ``kv_cache.decode_attention`` on every query row with at least one
    visible key (``pos_q = lengths[b] - S + s >= 0``; inside the engine
    that is every row of every occupied slot). Fully-masked rows —
    ``lengths == 0``, or the leading rows of a direct call with
    ``lengths < S`` — return ZEROS, where the dense kernel emits an
    equally-unconsumed uniform average over the whole window.
    ``interpret=True`` runs the Pallas interpreter (the CPU path).

    ``block_tables`` ([B, max_pages] int32) switches to the PAGED cache
    layout (inference/paged_kv.py): k/v (and scales) are then the global
    page pool — ``[num_pages, page_len, n_kv_heads, D]`` — and slot
    ``b``'s walk reads pool page ``block_tables[b, j]`` at iteration
    ``j`` instead of its contiguous block ``j``. The KV block size is
    the page length; everything else (masking, online softmax, GQA fold,
    in-register dequant) is the identical code path.

    ``k_quant``/``v_quant`` + ``block_quant`` ([B, max_pages] int32, paged
    only) enable the MIXED-precision page read (``kv_page_policy:
    "hot_bf16"``): k/v stay the full-precision pool, k_quant/v_quant are
    the parallel int8 pool with ``k_scale``/``v_scale`` per-row scales,
    and page ``j`` of slot ``b`` is fetched from whichever representation
    ``block_quant[b, j]`` selects (0 = full precision, nonzero = int8).

    ``pipeline=True`` (default) double-buffers the block DMA — page
    ``j+1``'s copy commits while page ``j``'s dots run; ``False`` keeps
    the serial fetch the pipelined path is pinned bitwise-identical to.
    ``block_q`` caps the folded query rows per grid instance (chunked
    prefill splits wide windows over the q grid axis)."""
    B, S, nh, D = q.shape
    paged = block_tables is not None
    mixed = k_quant is not None
    if mixed != (v_quant is not None):
        raise ValueError("k_quant and v_quant must be given together")
    if mixed and not paged:
        raise ValueError(
            "mixed-precision pages (k_quant/v_quant) require the paged "
            "layout (block_tables)")
    if mixed and block_quant is None:
        raise ValueError(
            "mixed-precision pages need block_quant per-page flags")
    if paged:
        if block_tables.shape[0] != B:
            raise ValueError(
                f"block_tables rows {block_tables.shape[0]} != batch {B}")
        T = block_tables.shape[1] * k.shape[1]  # max_pages * page_len
        nkv = k.shape[2]
    else:
        T, nkv = k.shape[1], k.shape[2]
    if nh % nkv:
        raise ValueError(f"n_heads {nh} not a multiple of n_kv_heads {nkv}")
    quantized = (k_scale is not None) and not mixed
    if (k_scale is not None) != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")
    if mixed and k_scale is None:
        raise ValueError("mixed-precision pages need k_scale/v_scale for "
                         "the int8 representation")
    if (k.dtype == jnp.int8) != quantized:
        raise ValueError(
            f"int8 cache blocks need per-row scales (and vice versa); got "
            f"k.dtype={k.dtype} with scales="
            f"{'set' if k_scale is not None else 'unset'}")
    g = nh // nkv
    sg = S * g
    sgp = -(-sg // _SUBLANE) * _SUBLANE  # pad query rows to the sublane tile
    # paged: the DMA unit is a whole pool page, so the block size IS the
    # page length (the allocator's granularity, already VMEM-sized) and
    # the q-block count is the only VMEM-budget tunable
    if paged:
        bt = k.shape[1]
        rq = _pick_block_q(sgp, block_q or DEFAULT_BLOCK_Q, bt)
    else:
        rq = _pick_block(sgp, block_q or DEFAULT_BLOCK_Q)
        bt = _pick_block_t(T, block_t or DEFAULT_BLOCK_T, rows=rq)
    # fold [B, S, nkv, g, D] -> [B, nkv, S*g, D]: one kv head's whole query
    # group per grid instance (tiny copy — S is 1..chunk, never the cache)
    qf = q.reshape(B, S, nkv, g, D).swapaxes(1, 2).reshape(B, nkv, sg, D)
    if sgp != sg:
        qf = jnp.pad(qf, ((0, 0), (0, 0), (0, sgp - sg), (0, 0)))

    kernel = functools.partial(
        _flash_decode_kernel, scale=float(scale), block_t=bt, S=S, g=g,
        rq=rq, quantized=quantized, mixed=mixed, paged=paged,
        pipeline=pipeline)
    in_specs = [
        pl.BlockSpec((1,), lambda b, h, i: (b,), memory_space=pltpu.SMEM),
    ]
    operands = [lengths.astype(jnp.int32)]
    if paged:
        maxp = block_tables.shape[1]
        in_specs.append(pl.BlockSpec((1, maxp), lambda b, h, i: (b, 0),
                                     memory_space=pltpu.SMEM))
        operands.append(block_tables.astype(jnp.int32))
    if mixed:
        maxp = block_tables.shape[1]
        in_specs.append(pl.BlockSpec((1, maxp), lambda b, h, i: (b, 0),
                                     memory_space=pltpu.SMEM))
        operands.append(block_quant.astype(jnp.int32))
    in_specs += [
        pl.BlockSpec((1, 1, rq, D), lambda b, h, i: (b, h, i, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),  # K stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),  # V stays in HBM
    ]
    operands += [qf, k, v]
    nbuf = 2 if pipeline else 1
    scratch = [pltpu.VMEM((nbuf, bt, D), k.dtype),
               pltpu.VMEM((nbuf, bt, D), v.dtype)]
    if mixed:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        operands += [k_quant, v_quant]
        scratch += [pltpu.VMEM((nbuf, bt, D), jnp.int8),
                    pltpu.VMEM((nbuf, bt, D), jnp.int8)]
    if quantized or mixed:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        operands += [k_scale, v_scale]
        scratch += [pltpu.VMEM((nbuf, bt), jnp.float32),
                    pltpu.VMEM((nbuf, bt), jnp.float32)]
    scratch.append(pltpu.SemaphoreType.DMA((nbuf, 4)))

    out = pl.pallas_call(
        kernel,
        grid=(B, nkv, sgp // rq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rq, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, nkv, sgp, D), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)
    return (out[:, :, :sg]
            .reshape(B, nkv, S, g, D).swapaxes(1, 2)
            .reshape(B, S, nh, D))
