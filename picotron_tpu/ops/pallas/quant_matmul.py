"""Fused dequant matmul over per-channel int8 weights.

The weight-side counterpart of the int8 KV cache (inference/kv_cache.py):
weights are stored as int8 values with ONE fp32 scale per output channel
(``quantize_weight`` — absmax over the contraction axis, so quantization
error never crosses a channel), and the matmul consumes that storage
directly. A Llama-2-7B checkpoint's matmul weights land on device at
~half the bf16 bytes (1 byte/element + 4/in_features for the scales ≈
50.1% of bf16), which is what opens the 7B-class serving scenario on a
small slice (ROADMAP item 3).

Two implementations behind one entry point, ``quant_matmul(x, q, s)``:

- **Pallas kernel** (TPU, or ``interpret=True`` for the CPU parity
  suite): a ``(M//bm, N//bn)`` grid; each instance walks the contraction
  in ``block_k`` tiles pulled from the int8 VMEM block, casts the tile to
  the activation dtype IN REGISTERS (int8 values are at most ±127 —
  exactly representable in bf16, so the cast is lossless and the MXU
  runs at full bf16 rate), accumulates in fp32 via
  ``preferred_element_type``, and applies the per-output-channel scale
  ONCE to the fp32 accumulator in the epilogue. Per-channel scales
  commute with the contraction (``x @ (q * s[None, :]) ==
  (x @ q) * s[None, :]`` exactly, in real arithmetic), so scaling the
  epilogue IS the per-channel dequant — fused past the matmul, touching
  [bm, bn] accumulator elements instead of [K, N] weight elements. At no
  point does a dequantized copy of the weight exist anywhere: not in
  HBM, not in VMEM — the widest dequant-adjacent object is the one
  [block_k, bn] int8->bf16 register tile feeding the MXU.
- **XLA fallback** (off-TPU serving / any platform): the same
  scale-after-accumulate ordering as one ``jnp.einsum`` over the int8
  values (cast to the activation dtype) with the scale broadcast applied
  to the fp32 result. Bit-for-bit it differs from the kernel only in
  contraction order; both are allclose to the fake-quant reference
  ``x @ dequantize_weight(q, s)`` (tests/test_quant_weights.py).

``dequantize_weight`` exists for tests and offline tooling ONLY. The
serving path must never call it — tests/test_quant_weights.py enforces
that the same way test_decode_kernel.py pins the KV path: the helper is
monkeypatched to raise and full int8-weight generations still run.

Tiling notes: block sizes follow ``flash_attention._pick_block``
(halve-until-divides, so any K/N tiles exactly — the tiny CPU test
shapes degrade to small blocks, real model dims keep the 512/256
defaults). The M axis (tokens x folded batch) pads to the fp32 sublane
quantum. int8's native (32, 128) VMEM tile means very small K slices
underutilize lanes on real hardware; the shapes this kernel serves
(H >= 2048) never hit that.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from picotron_tpu.ops.pallas.flash_attention import _pick_block
from picotron_tpu.utils import on_tpu

# int8 symmetric range; scales are fp32 so the epilogue multiply never
# double-rounds — the same convention as the int8 KV cache
# (inference/kv_cache.py::INT8_MAX / SCALE_DTYPE).
INT8_MAX = 127.0
SCALE_DTYPE = jnp.float32

DEFAULT_BLOCK_M = 256  # token rows per grid instance (decode: B*S, tiny)
DEFAULT_BLOCK_N = 256  # output channels per grid instance
DEFAULT_BLOCK_K = 512  # contraction tile dequantized in registers per step
_SUBLANE = 8  # fp32 sublane quantum the padded M respects


def is_quant_weight(leaf) -> bool:
    """Whether a parameter leaf is a quantized ``(int8, scales)`` pair —
    the dict form ``{"q": int8 [..., in, out], "s": fp32 [..., out]}`` the
    model's matmul sites dispatch on (models/llama.py::matmul)."""
    return isinstance(leaf, dict) and set(leaf) == {"q", "s"}


def quantize_weight(w) -> dict:
    """Per-output-channel absmax int8 quantization of a matmul weight.

    ``w`` is [..., in_features, out_features] (our (in, out) storage
    layout, optionally layer-stacked); the scale reduces over the
    CONTRACTION axis (-2), one fp32 scale per output channel — so a
    TP-sharded column split carries exactly the global quantization's
    values and scales for its channels (scales shard WITH their
    channels). The STORED scale is the exact divisor the values were
    rounded against (the raw absmax/127 clamped away from zero), so the
    |Δw| <= scale/2 per-element bound holds for every channel including
    denormal-tiny ones; an all-zero channel quantizes to zeros with
    scale 0 — dequantization is exact there (pad rows of uneven-pp
    stacks stay exactly zero)."""
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2)
    div = jnp.maximum(amax / INT8_MAX, 1e-12)
    q = jnp.round(wf / div[..., None, :])
    return {"q": jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8),
            "s": jnp.where(amax > 0, div, 0.0).astype(SCALE_DTYPE)}


def quantize_weight_host(w: np.ndarray) -> dict:
    """``quantize_weight`` on host numpy — the checkpoint streaming path
    (one layer's fp weight in RAM at a time, int8 out; checkpoint.py)."""
    wf = np.asarray(w, np.float32)
    amax = np.max(np.abs(wf), axis=-2)
    div = np.maximum(amax / INT8_MAX, np.float32(1e-12))
    q = np.round(wf / div[..., None, :])
    return {"q": np.clip(q, -INT8_MAX, INT8_MAX).astype(np.int8),
            "s": np.where(amax > 0, div, 0.0).astype(np.float32)}


def dequantize_weight(q, s, dtype=jnp.float32):
    """Inverse of ``quantize_weight`` — TESTS AND OFFLINE TOOLING ONLY.
    The serving path never materializes this (enforced by monkeypatching
    this helper to raise in tests/test_quant_weights.py, the
    test_decode_kernel.py discipline)."""
    return (jnp.asarray(q).astype(jnp.float32)
            * jnp.asarray(s)[..., None, :]).astype(dtype)


# --------------------------------------------------------------------------- #
# the kernel
# --------------------------------------------------------------------------- #


def _quant_matmul_kernel(x_ref, q_ref, s_ref, o_ref, *, block_k):
    """One (m, n) grid instance: [bm, K] activations against the [K, bn]
    int8 weight block. The contraction walks ``block_k`` tiles: each int8
    tile casts to the activation dtype in registers (lossless — int8
    values are exact in bf16) and feeds the MXU with fp32 accumulation;
    the per-output-channel fp32 scale lands once on the accumulator in
    the epilogue (per-channel scales commute with the contraction, so
    this IS the dequant, fused). No dequantized weight tensor ever
    exists."""
    nk = x_ref.shape[1] // block_k

    def body(j, acc):
        xb = x_ref[:, pl.ds(j * block_k, block_k)]
        wb = q_ref[pl.ds(j * block_k, block_k), :].astype(xb.dtype)
        return acc + lax.dot_general(
            xb, wb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc0 = jnp.zeros((x_ref.shape[0], q_ref.shape[1]), jnp.float32)
    acc = lax.fori_loop(0, nk, body, acc0)
    o_ref[:] = (acc * s_ref[0, :][None, :]).astype(o_ref.dtype)


def quant_matmul_pallas(x2, q, s, *, block_m=None, block_n=None,
                        block_k=None, out_dtype=None,
                        interpret: bool = False):
    """The Pallas path: x2 [M, K] @ q [K, N] int8 with s [N] fp32 scales
    -> [M, N] in ``out_dtype`` (default: x2.dtype). M pads to the sublane
    quantum; N/K tile by halve-until-divides blocks."""
    M, K = x2.shape
    N = q.shape[1]
    dt = jnp.dtype(out_dtype or x2.dtype)
    Mp = -(-max(M, 1) // _SUBLANE) * _SUBLANE
    if Mp != M:
        x2 = jnp.pad(x2, ((0, Mp - M), (0, 0)))
    bm = _pick_block(Mp, block_m or DEFAULT_BLOCK_M)
    bn = _pick_block(N, block_n or DEFAULT_BLOCK_N)
    bk = _pick_block(K, block_k or DEFAULT_BLOCK_K)
    kernel = functools.partial(_quant_matmul_kernel, block_k=bk)
    out = pl.pallas_call(
        kernel,
        grid=(Mp // bm, N // bn),
        in_specs=[
            pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, N), dt),
        interpret=interpret,
    )(x2, q, s.reshape(1, N))
    return out[:M]


def quant_matmul_xla(x2, q, s, *, out_dtype=None):
    """The XLA fallback (off-TPU serving and any non-Pallas platform):
    one einsum over the int8 values cast to the activation dtype, fp32
    accumulation, the per-channel scale broadcast onto the fp32 result —
    the kernel's exact ordering minus the K-blocking. Never materializes
    a dequantized weight either: the cast int8 operand IS the matmul
    input."""
    dt = jnp.dtype(out_dtype or x2.dtype)
    acc = jnp.einsum("mk,kn->mn", x2, q.astype(x2.dtype),
                     preferred_element_type=jnp.float32)
    return (acc * s[None, :].astype(jnp.float32)).astype(dt)


def quant_matmul(x, q, s, *, out_dtype=None, impl: str | None = None,
                 interpret: bool = False, block_m=None, block_n=None,
                 block_k=None):
    """``x @ W`` from int8 weights + per-output-channel fp32 scales.

    x: [..., in_features] activations (any leading shape — the model's
    [B, S, H] sites flatten through); q: [in_features, out_features]
    int8; s: [out_features] fp32. Returns [..., out_features] in
    ``out_dtype`` (default: x.dtype).

    ``impl``: "pallas" | "xla" | None (auto: the Pallas kernel on TPU,
    the XLA fallback elsewhere — the same dispatch rule as
    ``inference.attend_impl``'s interpret-mode guard). ``interpret``
    forces the Pallas interpreter (the CPU parity suite)."""
    if q.dtype != jnp.int8:
        raise ValueError(f"quant_matmul weights must be int8, got {q.dtype}")
    if impl is None:
        impl = "pallas" if (on_tpu() or interpret) else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown quant_matmul impl {impl!r} (pallas|xla)")
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    if impl == "pallas":
        out = quant_matmul_pallas(x2, q, s, block_m=block_m,
                                  block_n=block_n, block_k=block_k,
                                  out_dtype=out_dtype, interpret=interpret)
    else:
        out = quant_matmul_xla(x2, q, s, out_dtype=out_dtype)
    return out.reshape(*lead, q.shape[1])
