"""Logging, formatting, MFU accounting.

Ports of the reference's picotron/utils.py, TPU-ified: the analytic MFU
formula is kept (utils.py:42-48) but the hardcoded H100 989.5 TFLOPs
denominator becomes a per-chip-generation table; the fcntl-locked multi-process
print (utils.py:12-20) is unnecessary under a single controller.
"""

from __future__ import annotations

import jax
import numpy as np

# RNG values must not depend on how a computation is sharded: newer JAX
# defaults jax_threefry_partitionable=True; 0.4.x does not, and there
# jit(init_params, out_shardings=...) draws DIFFERENT weights per topology
# (vocab-sharded embed under tp, stacked layers under pp) — which breaks
# the cross-topology loss-trajectory oracle the whole test suite leans on.
# Pin the partitionable generator on every version.
if not jax.config.jax_threefry_partitionable:
    jax.config.update("jax_threefry_partitionable", True)

# dense bf16 peak FLOPs per chip
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}
H100_PEAK_FLOPS = 989.5e12  # the reference's denominator (utils.py:42)


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across JAX releases. Newer releases expose it as a
    top-level API with the varying-manual-axes checker (``check_vma``);
    older ones (<= 0.4.x) only have ``jax.experimental.shard_map.shard_map``
    with the predecessor ``check_rep`` flag, whose replication checker
    rejects valid custom_vjp collectives — there ``check_vma=False`` maps to
    ``check_rep=False`` and ``check_vma=True`` raises (the vma checker does
    not exist to run). Single home for the version split; every shard_map in
    the repo goes through here."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    if check_vma:
        raise NotImplementedError(
            "distributed.check_vma=True needs jax.shard_map's varying-"
            f"manual-axes checker (jax >= 0.6); this is jax {jax.__version__}")
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def typeof_vma(x) -> frozenset:
    """The varying-manual-axes set of ``x``'s type, or the empty set on JAX
    releases whose avals are not vma-typed (``jax.typeof`` absent). Every
    vma-driven cast in the repo keys off this, so on an old JAX they all
    collapse to provable no-ops instead of AttributeErrors."""
    if hasattr(jax, "typeof"):
        return frozenset(jax.typeof(x).vma)
    return frozenset()


def is_main_process() -> bool:
    """True on the controller process that should own logging/wandb/metadata
    (the reference gates prints on global rank 0 via an fcntl lock,
    utils.py:12-20, and wandb on wandb_rank, train.py:101). Collective-side
    work (orbax saves, the train step itself) must NOT be gated — every
    process participates there."""
    return jax.process_index() == 0


def log0(*args, **kwargs) -> None:
    """print() on process 0 only — the multi-host log gate."""
    if is_main_process():
        print(*args, **kwargs)


def host_values(x) -> np.ndarray:
    """Fetch a replicated device array to the host, multi-process safe.

    On a multi-controller pod a replicated output (the loss, the consensus
    verdict) spans every host's devices, and jax refuses whole-array reads
    of non-addressable shards — but each host holds a full copy, so the
    first addressable shard IS the value. Single-process arrays take the
    plain path untouched."""
    x = jax.block_until_ready(x)
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    return np.asarray(x.addressable_data(0))


def on_tpu() -> bool:
    """Trace-time backend check gating the Pallas (Mosaic) fast paths: only
    an actual TPU backend qualifies — GPU must not be routed into kernels
    lowered for Mosaic."""
    return jax.default_backend() == "tpu"


def cpu_pinned() -> bool:
    """The caller pinned the CPU platform via JAX_PLATFORMS."""
    import os

    return os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() == "cpu"


def honor_cpu_env_pin() -> None:
    """Make JAX_PLATFORMS=cpu win over a site-pinned accelerator platform
    BEFORE any backend initializes. On this site the TPU sits behind a
    tunnel whose client blocks forever inside backend init when the tunnel
    is dead — CPU-only work must never touch it. Call before the first
    jax.devices()/computation; no-op without the env pin."""
    if cpu_pinned():
        jax.config.update("jax_platforms", "cpu")


def peak_flops_per_chip(device=None) -> float | None:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in TPU_PEAK_FLOPS.items():
        if key in kind:
            return val
    return None  # CPU or unknown: MFU not reported


def flops_per_token(num_params: int, num_layers: int, hidden: int, seq_len: int) -> float:
    """6N + 12*layers*hidden*seq (reference utils.py:42-48: param FLOPs +
    attention quadratic term)."""
    return 6 * num_params + 12 * num_layers * hidden * seq_len


def get_mfu(tokens_per_sec_per_chip: float, num_params: int, num_layers: int,
            hidden: int, seq_len: int, peak: float | None) -> float | None:
    if peak is None:
        return None
    fpt = flops_per_token(num_params, num_layers, hidden, seq_len)
    return 100.0 * fpt * tokens_per_sec_per_chip / peak


def to_readable_format(num: float, precision: int = 2) -> str:
    """1234567 -> '1.23M' (reference utils.py:27-37)."""
    for bound, suffix in ((1e12, "T"), (1e9, "B"), (1e6, "M"), (1e3, "K")):
        if abs(num) >= bound:
            return f"{num / bound:.{precision}f}{suffix}"
    return f"{num:.{precision}f}"


def set_all_seed(seed: int) -> None:
    np.random.seed(seed)


def device_memory_gb(device=None) -> float | None:
    """Peak live bytes across this process's devices (the reference logs
    torch.cuda.memory_reserved of the local rank, train.py:257). Max, not
    device 0: pp/tp shards can differ in footprint and the max is what OOMs."""
    devices = [device] if device is not None else jax.local_devices()
    best = None
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if stats:
            b = stats.get("peak_bytes_in_use",
                          stats.get("bytes_in_use", 0)) / 1e9
            best = b if best is None else max(best, b)
    return best


def pvary_like(x, *refs):
    """Cast every leaf of ``x`` to be VARYING over the union of the
    ``refs``' varying mesh axes, for shard_map's varying-manual-axes
    checker (``check_vma=True``). A pure type cast, numerically the
    identity, and a no-op where the leaf already varies. Needed where a
    replicated literal (a ``jnp.zeros`` scan carry, a masked fill) meets
    axis-varying values: the checker would otherwise reject the scan
    carry as replicated-in/varying-out."""
    import jax
    from jax import lax

    target = frozenset().union(
        *[typeof_vma(r) for r in jax.tree.leaves(refs)])

    def cast(v):
        need = tuple(sorted(target - typeof_vma(v)))
        return lax.pcast(v, need, to="varying") if need else v

    return jax.tree.map(cast, x)


def vma_checking(axis: str) -> bool:
    """Whether shard_map's varying-manual-axes checker is typing the
    current trace: a fresh ``axis_index`` is vma-typed iff it is. Used to
    skip the checker-only eval_shape passes (scan-carry fixpoints) on the
    production (``check_vma=False``) build, where every vma is empty and
    the casts are provable no-ops."""
    from jax import lax

    return bool(typeof_vma(lax.axis_index(axis)))


def scan_carry_fixpoint(body, carry, x_example):
    """Cast a ``lax.scan`` carry to the varying-manual-axes fix-point of
    ``body(carry, x) -> (carry, y)`` under shard_map's ``check_vma``: a
    replicated init meeting axis-varying values inside the body must enter
    the scan already typed with the body's output vma. Numerically the
    identity; converges in a few ``eval_shape`` passes (vma only grows);
    a no-op when the checker is off (every vma is empty). Casting to the
    fix-point (rather than some outer upper bound) matters: over-casting
    leaks spurious varying axes into downstream cotangents."""
    import jax

    # vma growth can propagate between carry leaves one pass at a time, so
    # the cap scales with the carry's size; non-convergence fails HERE with
    # a named error instead of as the checker's opaque
    # replicated-in/varying-out complaint at the scan itself
    for _ in range(max(4, len(jax.tree.leaves(carry)) + 1)):
        out = jax.eval_shape(lambda c: body(c, x_example)[0], carry)
        new = jax.tree.map(pvary_like, carry, out)
        if [typeof_vma(a) for a in jax.tree.leaves(new)] == \
           [typeof_vma(a) for a in jax.tree.leaves(carry)]:
            return new
        carry = new
    raise ValueError(
        "scan_carry_fixpoint did not converge: the scan body keeps adding "
        "varying axes to its carry across passes — check the body for a "
        "vma-oscillating construct")


def collective_scan_unroll():
    """Workaround for an XLA CPU runtime race: InProcessCommunicator's
    rendezvous for collective-permutes inside While loops can admit
    participants from adjacent loop iterations (observed:
    "Check failed: id < num_threads (8 vs. 8) ... collective permute
    RendezvousKey"), aborting the process. Fully unrolling ppermute-bearing
    scans gives every permute a distinct op id, which sidesteps the
    collision. TPU runtimes are unaffected, and the hot loops stay rolled
    there for compile time."""
    import jax

    return True if jax.default_backend() == "cpu" else 1
